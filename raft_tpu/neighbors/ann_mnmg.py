"""Sharded multi-device ANN search: IVF-Flat, IVF-PQ and brute force as
ONE ``shard_map`` program per query batch.

Counterpart of the reference ecosystem's MNMG ANN layer (cuML's
distributed brute-force/ANN driven through raft comms, the
``neighbors/brute_force.cuh:76`` part-merge design): the index is
partitioned across the communicator's devices, every device scans its
shard with the SAME fused single-device kernels (the PR-1 fused scan, the
PR-3 hoisted-ADC pipeline), and per-shard top-k candidates merge on
device.  Design (docs/sharded_ann.md):

* **Partitioning** — inverted lists are assigned round-robin
  (``list l → shard l % world``) at ``shard()`` time: coarse centroids /
  rotation / codebooks / ``list_adc`` are REPLICATED (they are read by
  every query against every probe), while the packed list blocks
  (vectors/codes, indices, per-chunk sizes, ADC csums) are gathered into
  per-shard blocks stacked along a leading ``world`` axis and laid out on
  the mesh with ``P(axis)`` — inside the program each device sees only
  its own block.  Brute force shards rows contiguously (the OPG split
  ``knn_mnmg`` uses), so global ids are ``rank·rows_per + local``.

* **Probe intersection** — search runs the replicated coarse GEMM +
  top-``n_probes`` on every shard (identical, collective-free), then
  intersects the GLOBAL probe set with the local lists through the
  shard-LOCAL chunk table: probes owned elsewhere expand to the local
  dummy row and compact to the back of the scan (``expand_probes``),
  so each shard pays only for its own lists.  The continuation-chunk
  budget cannot be derived from the local table shape (it spans all
  logical lists but holds only local rows) — ``shard()`` computes the
  true per-shard worst case and threads it through as the static
  ``probe_extra``.

* **Merge** — per-shard (nq, k) results pack distances and bitcast ids
  into ONE payload, ONE ``comms.allgather`` moves them, and
  ``matrix.select_k.merge_sorted_parts`` folds the (world, nq, k) parts
  on device — no host round-trips anywhere in the search path (the
  hot-path-host-transfer rule bans unmarked host transfers module-wide;
  sanctioned table fetches carry the unified exemption marker).  The
  L2Sqrt root is DEFERRED past the merge, so merging squared distances
  in shard order reproduces the single-device scan's stable tie order
  bit for bit.

* **Caching/serving** — the whole batch is one
  ``core.aot.MeshAotFunction`` executable keyed on (bucket, dtype,
  leaf shardings) and cached per (communicator, statics), so
  ``serve.ServeEngine``'s sharded backend warms every signature up front
  and steady-state dispatch never retraces; ``Comms.collective_calls``
  (count AND payload bytes) pins exactly one allgather per search batch.

The query-sharded large-batch brute-force mode (split queries instead of
the index when nq dominates — zero collectives, disjoint results gathered
by the output sharding alone) lives in ``knn_mnmg(partition=...)`` and
shares this module's program-cache plumbing.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.analysis.registry import hlo_program
from raft_tpu.comms.comms import (Comms, ReplicaLayout, as_comms,
                                  shard_map_compat)
from raft_tpu.core.aot import MeshAotFunction, _bucket_dim
from raft_tpu.core.error import expects
from raft_tpu.core.logger import traced
from raft_tpu.cluster.kmeans_mnmg import _cached_program
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.matrix.select_k import merge_sorted_parts
from raft_tpu.neighbors import brute_force, ivf_flat, ivf_pq
from raft_tpu.neighbors._common import empty_result


def _host(x) -> np.ndarray:
    """Device→host fetch for BUILD/SERIALIZE-time table construction only.
    The search path must never fetch (the ci/lint.py ann_mnmg rule bans
    unmarked host transfers in this module)."""
    # exempt(hot-path-host-transfer): build/serialize-time assembly
    return np.asarray(x)


def _full_axis_comms(comms) -> Comms:
    comms = as_comms(comms)
    # A split communicator's get_size() is group-local while P(axis) shards
    # over the FULL mesh axis — the partition arithmetic would silently
    # corrupt: require the full-axis communicator (knn_mnmg's rule).
    expects(getattr(comms, "groups", None) is None,
            "sharded ANN needs a full (non-split) communicator")
    return comms


# ---------------------------------------------------------------------------
# the sharded index container


@dataclasses.dataclass
class ShardedIndex:
    """A list- (or row-) partitioned ANN index resident across the devices
    of one communicator.

    ``replicated`` holds the global tables every shard reads (coarse
    centroids; for IVF-PQ also rotation/codebooks/list_adc), laid out
    replicated on the mesh; ``stacked`` holds the per-shard blocks with a
    leading ``world`` axis sharded along the communicator's mesh axis.
    ``aux`` carries the static search configuration (metric, dims, the
    per-shard ``probe_extra`` budget, ...).  Build with ``Index.shard``
    (:func:`shard_ivf_flat` / :func:`shard_ivf_pq`) or
    :func:`shard_brute_force`; search with :func:`search` or through
    ``serve.ServeEngine``.  The partition is immutable: after
    ``extend()``-ing the base index, re-``shard()`` it (partitioning is
    one host-side table pass + device gathers, cheap next to a build).
    """

    kind: str                    # "ivf_flat" | "ivf_pq" | "brute_force"
    comms: Comms
    replicated: Tuple[Any, ...]  # kind-specific global tables
    stacked: Tuple[Any, ...]     # kind-specific (world, ...) shard blocks
    aux: Dict[str, Any]          # static search metadata (JSON-safe)

    @property
    def world(self) -> int:
        return int(self.aux["world"])

    @property
    def dim(self) -> int:
        return int(self.aux["dim"])

    @property
    def metric(self) -> DistanceType:
        return DistanceType(self.aux["metric"])

    def search(self, queries, k: int, params=None, **kw):
        return search(self, queries, k, params, **kw)

    def searcher(self, k: int, params=None) -> "ShardedSearcher":
        return ShardedSearcher(self, k, params)


# ---------------------------------------------------------------------------
# partitioning (build/shard time; host-side table arithmetic + device gathers)


def _partition(chunk_table_h: np.ndarray, n_rows: int, world: int):
    """Round-robin partition of a chunked-list layout; *n_rows* is the
    global physical block's leading dim (n_phys + 1).

    Returns ``(gather, local_tables, probe_extra, local_rows)`` where
    ``gather`` (world, local_rows+1) maps each shard's local physical
    slot to a GLOBAL physical row (padding slots and the local dummy map
    to the global dummy, whose size is 0 — they never score),
    ``local_tables`` (world, n_lists, max_chunks) int32 is each shard's
    logical→local chunk table (non-local lists → local dummy), and
    ``probe_extra`` is the max over shards of local continuation chunks —
    the static scan budget every shard's ``expand_probes`` must use (one
    SPMD program).
    """
    n_lists, max_chunks = chunk_table_h.shape
    dummy = n_rows - 1
    lists = np.arange(n_lists)
    shard_of = lists % world
    real = chunk_table_h != dummy                    # (n_lists, max_chunks)
    counts = real.sum(axis=1)                        # real chunks per list
    # exempt(hot-path-host-transfer): build-time (world,) table
    n_local = np.array([int(counts[shard_of == s].sum())
                        for s in range(world)], np.int64)
    local_rows = int(n_local.max()) if world else 0
    gather = np.full((world, local_rows + 1), dummy, np.int64)
    local_tables = np.full((world, n_lists, max_chunks), local_rows,
                           np.int32)                 # default: local dummy
    for s in range(world):
        ls = lists[shard_of == s]
        rs, cs = np.nonzero(real[ls])                # (list-major, chunk asc)
        glob = chunk_table_h[ls[rs], cs]
        gather[s, :glob.size] = glob
        local_tables[s, ls[rs], cs] = np.arange(glob.size, dtype=np.int32)
    probe_extra = int(max(
        (int((counts[shard_of == s] - 1).clip(min=0).sum())
         for s in range(world)), default=0))
    return gather, local_tables, probe_extra, local_rows


def _stack_shards(comms: Comms, leaf, gather: np.ndarray):
    """Gather one global physical block into the (world, local_rows+1, …)
    stacked layout and lay it out shard-per-device on the mesh.

    The gather runs HOST-side: a device gather would materialize the
    whole padded stack on the default device (~2× the index) before
    distribution, defeating the capacity win sharding exists for — the
    host copy routes through ``device_put``-to-NamedSharding, which
    transfers each shard straight to its own device."""
    from jax.sharding import PartitionSpec as P

    stacked = _host(leaf)[gather]
    return comms.globalize(stacked, P(comms.axis_name))


def _replicate(comms: Comms, leaf):
    from jax.sharding import PartitionSpec as P

    return comms.globalize(jnp.asarray(leaf), P())


def _ivf_flat_aux(world: int, dim: int, metric: int, n_lists: int,
                  probe_extra: int) -> Dict[str, Any]:
    """Static search aux for an IVF-Flat ShardedIndex — ONE builder shared
    by :func:`shard_ivf_flat` and ``ivf_flat.build_sharded`` so the two
    construction paths are identical by construction (program-cache keys
    derive from these values)."""
    return {"world": world, "dim": dim, "metric": metric,
            "n_lists": n_lists, "probe_extra": probe_extra}


def _ivf_pq_aux(world: int, dim: int, metric: int, n_lists: int,
                probe_extra: int, pq_bits: int, codebook_kind: int,
                dataset_dtype: str, pq_dim: int,
                max_chunks: int) -> Dict[str, Any]:
    """Static search aux for an IVF-PQ ShardedIndex — ONE builder shared by
    :func:`shard_ivf_pq` and ``ivf_pq.build_sharded`` (see
    :func:`_ivf_flat_aux`)."""
    return {"world": world, "dim": dim, "metric": metric,
            "n_lists": n_lists, "probe_extra": probe_extra,
            "pq_bits": pq_bits, "codebook_kind": codebook_kind,
            "dataset_dtype": dataset_dtype, "pq_dim": pq_dim,
            # per-shard transient-cap inputs (ivf_pq.hoisted_batch_cap_dims
            # derives its scan budget as n_probes + (n_phys − n_lists), and
            # the sharded program's true budget is n_probes + probe_extra —
            # feeding the LOCAL block shape would undercount it and void
            # the ~128 MiB bound)
            "cap_n_phys": int(n_lists + probe_extra),
            "cap_max_chunks": int(max_chunks)}


@traced("raft_tpu.neighbors.ann_mnmg.shard_ivf_flat")
def shard_ivf_flat(index: ivf_flat.Index, comms) -> ShardedIndex:
    """Partition an IVF-Flat index's lists round-robin across *comms*'
    devices (``list l → shard l % world``); centroids replicate."""
    comms = _full_axis_comms(comms)
    world = comms.get_size()
    table_h = _host(index.chunk_table)
    gather, local_tables, probe_extra, _ = _partition(
        table_h, index.list_data.shape[0], world)
    stacked = (
        _stack_shards(comms, index.list_data, gather),
        _stack_shards(comms, index.list_indices, gather),
        _stack_shards(comms, index.phys_sizes, gather),
        _replicate_stacked_tables(comms, local_tables),
    )
    replicated = (_replicate(comms, index.centers),)
    aux = _ivf_flat_aux(world, index.dim, int(index.metric), index.n_lists,
                        probe_extra)
    return ShardedIndex("ivf_flat", comms, replicated, stacked, aux)


def _replicate_stacked_tables(comms: Comms, tables_h: np.ndarray):
    """Per-shard chunk tables are host-built (world, n_lists, max_chunks)
    numpy — shard them along the world axis like the data blocks."""
    from jax.sharding import PartitionSpec as P

    return comms.globalize(jnp.asarray(tables_h), P(comms.axis_name))


@traced("raft_tpu.neighbors.ann_mnmg.shard_ivf_pq")
def shard_ivf_pq(index: ivf_pq.Index, comms) -> ShardedIndex:
    """Partition an IVF-PQ index's lists round-robin across *comms*'
    devices; the trained model (centers/rotation/codebooks) and the
    list-side ADC table replicate — probe ids stay GLOBAL list ids, so the
    hoisted per-batch LUT stage runs unchanged against the full tables
    while the scan touches only local rows."""
    comms = _full_axis_comms(comms)
    world = comms.get_size()
    table_h = _host(index.chunk_table)
    gather, local_tables, probe_extra, _ = _partition(
        table_h, index.list_codes.shape[0], world)
    stacked = (
        _stack_shards(comms, index.list_codes, gather),
        _stack_shards(comms, index.list_indices, gather),
        _stack_shards(comms, index.phys_sizes, gather),
        _replicate_stacked_tables(comms, local_tables),
        _stack_shards(comms, index.owner, gather),   # local row → GLOBAL list
        _stack_shards(comms, index.list_csum, gather),
    )
    replicated = (_replicate(comms, index.centers),
                  _replicate(comms, index.rotation),
                  _replicate(comms, index.codebooks),
                  _replicate(comms, index.list_adc))
    aux = _ivf_pq_aux(world, index.dim, int(index.metric), index.n_lists,
                      probe_extra, int(index.pq_bits),
                      int(index.codebook_kind), index.dataset_dtype,
                      int(index.pq_dim), int(index.chunk_table.shape[1]))
    return ShardedIndex("ivf_pq", comms, replicated, stacked, aux)


@traced("raft_tpu.neighbors.ann_mnmg.shard_brute_force")
def shard_brute_force(dataset, comms, metric=DistanceType.L2SqrtExpanded,
                      metric_arg: float = 2.0,
                      batch_size_index: int = 16384) -> ShardedIndex:
    """Shard a dense (n, dim) matrix row-contiguously (the OPG split of
    ``knn_mnmg``) for serving: global ids are ``rank·rows_per + local``.
    Ragged row counts pad with huge-magnitude sentinel rows (L2 metrics
    only): their distances rank WORST — as +inf, or as NaN for extreme
    queries whose sentinel dot overflows, which the NaN-robust
    select/merge also rank worst — so they surface only when k exceeds
    the real row count."""
    comms = _full_axis_comms(comms)
    world = comms.get_size()
    x = jnp.asarray(dataset)
    expects(x.ndim == 2, "brute-force index must be (n, dim)")
    n = x.shape[0]
    metric = brute_force._resolve_metric(metric)
    rows_per = -(-n // world)
    if rows_per * world != n:
        # Sentinel rows exist only for the L2 metrics: a huge-magnitude
        # row's squared distance beats (loses to) every real row, so it
        # can only surface when k exceeds the REAL row count.  No finite
        # vector is guaranteed to lose under InnerProduct (dot grows WITH
        # magnitude for aligned queries) or Cosine (scale-invariant — a
        # sentinel's direction can genuinely rank), and integer dtypes
        # overflow the filler — require an even split for all of those.
        expects(metric in (DistanceType.L2Expanded,
                           DistanceType.L2SqrtExpanded)
                and jnp.issubdtype(x.dtype, jnp.floating),
                f"n ({n}) not divisible by world ({world}): sentinel row "
                f"padding is only sound for float L2 metrics, not "
                f"{DistanceType(metric).name}/{x.dtype} — pad the dataset "
                "to a multiple of world first")
        pad_rows = rows_per * world - n
        filler = jnp.full((pad_rows, x.shape[1]),
                          jnp.asarray(1e30, jnp.float32).astype(x.dtype))
        x = jnp.concatenate([x, filler], axis=0)
    from jax.sharding import PartitionSpec as P

    xs = comms.globalize(x.reshape(world, rows_per, x.shape[1]),
                         P(comms.axis_name))
    aux = {"world": world, "dim": int(x.shape[1]), "metric": int(metric),
           "metric_arg": float(metric_arg), "rows_per": int(rows_per),
           "n_rows": int(n),
           "tile": int(min(batch_size_index, rows_per))}
    return ShardedIndex("brute_force", comms, (), (xs,), aux)


# ---------------------------------------------------------------------------
# replica groups: the 2D (shard × replica) layout


@dataclasses.dataclass(frozen=True)
class ReplicaSet:
    """R full :class:`ShardedIndex` copies laid out on a 2D (shard ×
    replica) carve of one communicator's devices
    (docs/sharded_ann.md §replica groups).

    Each replica group holds a COMPLETE copy of the index — the model
    tables replicated within the group, the packed list blocks
    round-robin-sharded across the group's own devices — built with the
    group's full-axis sub-mesh communicator from
    :meth:`raft_tpu.comms.comms.Comms.replica_split`.  A query batch
    dispatches to exactly ONE group (occupying only that group's
    devices), so R groups serve R batches concurrently and throughput
    scales past a single model copy; the one-allgather-per-batch
    discipline holds per group and is byte/count-accounted on each
    group communicator's own ``collective_calls`` rows.

    Route through ``serve.ServeEngine`` (its replica backend picks the
    least-loaded live group per super-batch and drains faulted groups),
    or search a single group directly via ``replicas[r].search(...)``.
    """

    kind: str
    layout: ReplicaLayout
    replicas: Tuple[ShardedIndex, ...]

    @property
    def n_replicas(self) -> int:
        return len(self.replicas)

    @property
    def dim(self) -> int:
        return self.replicas[0].dim

    @property
    def metric(self) -> DistanceType:
        return self.replicas[0].metric

    @property
    def aux(self) -> Dict[str, Any]:
        return self.replicas[0].aux


@traced("raft_tpu.neighbors.ann_mnmg.replicate")
def replicate(index, comms_or_layout, n_replicas: int = None, *,
              metric=DistanceType.L2SqrtExpanded, metric_arg: float = 2.0,
              batch_size_index: int = 16384) -> ReplicaSet:
    """Build a :class:`ReplicaSet`: carve *comms_or_layout* into replica
    groups (:meth:`Comms.replica_split`, unless a pre-built
    :class:`ReplicaLayout` is passed) and shard one full copy of *index*
    into each group.

    *index* selects the kind exactly like ``ServeEngine``/``shard()``: an
    ``ivf_flat.Index``, an ``ivf_pq.Index``, or a dense (n, dim) matrix
    (brute force; ``metric``/``metric_arg``/``batch_size_index`` apply).
    Every replica runs the SAME partition arithmetic over congruent
    groups, so per-group search results are identical across replicas —
    routing is free to pick any live group (the serve engine's router
    asserts nothing about WHICH group served a batch)."""
    if isinstance(comms_or_layout, ReplicaLayout):
        expects(n_replicas is None
                or int(n_replicas) == comms_or_layout.n_replicas,
                "replicate: n_replicas disagrees with the provided layout")
        layout = comms_or_layout
    else:
        expects(n_replicas is not None,
                "replicate: pass n_replicas (or a prebuilt ReplicaLayout)")
        layout = as_comms(comms_or_layout).replica_split(int(n_replicas))
    if isinstance(index, ivf_flat.Index):
        kind = "ivf_flat"
        replicas = tuple(shard_ivf_flat(index, g) for g in layout.groups)
    elif isinstance(index, ivf_pq.Index):
        kind = "ivf_pq"
        replicas = tuple(shard_ivf_pq(index, g) for g in layout.groups)
    else:
        kind = "brute_force"
        replicas = tuple(
            shard_brute_force(index, g, metric, metric_arg,
                              batch_size_index)
            for g in layout.groups)
    return ReplicaSet(kind, layout, replicas)


# ---------------------------------------------------------------------------
# the one-allgather cross-shard merge


def _merge_one_allgather(comms: Comms, d, i, k: int, select_min: bool):
    """Merge per-shard (nq, k) top-k runs with EXACTLY ONE collective.

    Distances and ids pack into one (nq, 2k) payload — int32 ids bitcast
    into the f32 lane (or widened exactly into the f64 lane under x64) —
    so the whole exchange is a single ``comms.allgather`` launch; the
    (world, nq, k) parts then fold on device via ``merge_sorted_parts``
    (earlier shards win ties, reproducing the single-device scan order).
    ``Comms.collective_calls`` records the launch and its payload bytes;
    tests and the bench assert both."""
    i = i.astype(jnp.int32)
    if d.dtype == jnp.float64:                # x64-only branch
        ids_lane = i.astype(jnp.float64)      # x64: exact for |id| < 2^53
        parts = comms.allgather(jnp.concatenate([d, ids_lane], axis=1))
        pd = parts[..., :k]
        pi = parts[..., k:].astype(jnp.int32)
    else:
        d = d.astype(jnp.float32)
        ids_lane = jax.lax.bitcast_convert_type(i, jnp.float32)
        parts = comms.allgather(jnp.concatenate([d, ids_lane], axis=1))
        pd = parts[..., :k]
        pi = jax.lax.bitcast_convert_type(parts[..., k:], jnp.int32)
    return merge_sorted_parts(pd, pi, k=k, select_min=select_min)


# ---------------------------------------------------------------------------
# per-kind shard programs (cached per (comms, statics))


def _ivf_flat_program(comms: Comms, metric_val: int, k: int, n_probes: int,
                      probe_extra: int, engine: str = "xla",
                      masked: bool = False):
    sqrt = metric_val == int(DistanceType.L2SqrtExpanded)
    is_ip = metric_val == int(DistanceType.InnerProduct)
    # defer the L2Sqrt root PAST the merge: shards merge squared distances
    # in shard order, reproducing the single-device scan's stable tie
    # order; the root is applied once on the merged (nq, k)
    scan_metric = (int(DistanceType.L2Expanded) if sqrt else metric_val)

    # ``masked`` grows ONE trailing replicated input — the mutable-index
    # tombstone bitmap (neighbors.mutable) — threaded into the per-shard
    # scan, where _common.scan_probe_lists folds it into the pad-row mask.
    # A separate program variant (not a runtime branch): the unmasked
    # serving ladder's lowered HLO stays byte-identical.
    def program(q, centers, data, idx, psz, ctab, *tomb):
        local = (centers, data[0], idx[0], psz[0], ctab[0])
        d, i = ivf_flat._search_batch_impl(q, local, scan_metric, k,
                                           n_probes, False, probe_extra,
                                           engine,
                                           tomb[0] if masked else None)
        d, i = _merge_one_allgather(comms, d, i, k, select_min=not is_ip)
        if sqrt:
            d = jnp.sqrt(jnp.maximum(d, 0))
        return d, i

    return program


def _ivf_pq_program(comms: Comms, metric_val: int, k: int, n_probes: int,
                    per_cluster: bool, lut_dtype: str, int_dtype: str,
                    pq_bits: int, hoisted: bool, probe_extra: int,
                    engine: str = "xla", masked: bool = False):
    sqrt = metric_val == int(DistanceType.L2SqrtExpanded)
    is_ip = metric_val == int(DistanceType.InnerProduct)
    scan_metric = (int(DistanceType.L2Expanded) if sqrt else metric_val)

    def program(q, centers, rotation, codebooks, list_adc,
                codes, idx, psz, ctab, owner, csum, *tomb):
        leaves = (centers, rotation, codebooks, codes[0], idx[0], psz[0],
                  ctab[0], owner[0], list_adc, csum[0])
        d, i = ivf_pq._full_search_impl(q, leaves, scan_metric, k, n_probes,
                                        per_cluster, lut_dtype, int_dtype,
                                        pq_bits, hoisted, probe_extra,
                                        engine,
                                        tomb[0] if masked else None)
        d, i = _merge_one_allgather(comms, d, i, k, select_min=not is_ip)
        if sqrt:
            d = jnp.sqrt(jnp.maximum(d, 0))
        return d, i

    return program


def _brute_force_program(comms: Comms, metric_val: int, metric_arg: float,
                         k: int, tile: int, rows_per: int):
    metric = DistanceType(metric_val)
    select_min = metric != DistanceType.InnerProduct
    defer = metric == DistanceType.L2SqrtExpanded
    scan_metric = DistanceType.L2Expanded if defer else metric

    def program(q, xs):
        # chunked: keeps knn()'s bounded (4096, tile) per-step transient
        d, i = brute_force._knn_scan_chunked(xs[0], q, k, scan_metric,
                                             metric_arg, tile, select_min)
        rank = jax.lax.axis_index(comms.axis_name)
        i = i + (rank * rows_per).astype(i.dtype)
        d, i = _merge_one_allgather(comms, d, i, k, select_min)
        if defer:
            d = jnp.sqrt(d)   # knn's deferred-root epilogue, post-merge
        return d, i

    return program


def _searcher_fn(sharded: ShardedIndex, key, builder,
                 extra_replicated: int = 0) -> MeshAotFunction:
    """One MeshAotFunction per (communicator, program statics): program
    identity (and with it the jit/AOT caches) is stable across repeated
    searcher constructions — the kmeans_mnmg._cached_program pattern.

    *extra_replicated*: trailing replicated inputs AFTER the stacked
    shard blocks (the masked program variants' tombstone bitmap)."""
    from jax.sharding import PartitionSpec as P

    comms = sharded.comms

    def build():
        program = builder()
        n_rep = len(sharded.replicated)
        in_specs = ((P(),) + (P(),) * n_rep
                    + (P(comms.axis_name),) * len(sharded.stacked)
                    + (P(),) * extra_replicated)
        mapped = shard_map_compat(program, comms.mesh, in_specs,
                                  (P(), P()), check_vma=False)
        return MeshAotFunction(mapped)

    return _cached_program(comms, ("ann_mnmg",) + tuple(key), build)


class ShardedSearcher:
    """Warm-able zero-retrace dispatcher for one (sharded index, k, params)
    serving key — the object ``serve.ServeEngine``'s sharded backend warms
    and dispatches.  ``warm(bucket, dtype)`` pre-lowers the (bucket,
    dtype, world) signature through the MeshAot cache;
    ``dispatch(qb)`` runs one pre-bucketed query batch and returns
    replicated (d, i).

    ``masked=True`` selects the tombstone-masked program variant
    (``neighbors.mutable``): warm/dispatch then take ONE trailing
    replicated uint32 bitmap argument.  A distinct program-cache key, so
    masked and unmasked ladders never cross-pollute."""

    def __init__(self, sharded: ShardedIndex, k: int, params=None, *,
                 masked: bool = False):
        expects(k >= 1, "k must be >= 1")
        self.sharded = sharded
        self.k = int(k)
        self.masked = bool(masked)
        aux = sharded.aux
        if sharded.kind == "ivf_flat":
            p = params or ivf_flat.SearchParams()
            self.n_probes = int(min(p.n_probes, aux["n_lists"]))
            # kernel engine resolved at searcher construction, OUTSIDE the
            # program cache, and keyed into it (kernels.engine policy) —
            # the sharded merge is engine-agnostic because both select_k
            # engines emit identical sorted runs (multichip battery case
            # select_k_sharded_matches_local pins this)
            from raft_tpu.kernels.engine import resolve_engine

            engine = resolve_engine("select_k", dtype=jnp.float32)
            key = ("ivf_flat", aux["metric"], self.k, self.n_probes,
                   aux["probe_extra"], engine, self.masked)
            builder = lambda: _ivf_flat_program(  # noqa: E731
                sharded.comms, aux["metric"], self.k, self.n_probes,
                aux["probe_extra"], engine, masked=self.masked)
        elif sharded.kind == "ivf_pq":
            p = params or ivf_pq.SearchParams()
            expects(p.lut_dtype in ivf_pq._LUT_DTYPES,
                    f"lut_dtype must be one of {list(ivf_pq._LUT_DTYPES)}")
            self.n_probes = int(min(p.n_probes, aux["n_lists"]))
            hoisted = (ivf_pq.hoisted_lut_enabled() if p.hoisted_lut is None
                       else bool(p.hoisted_lut))
            per_cluster = (aux["codebook_kind"]
                           == int(ivf_pq.CodebookKind.PER_CLUSTER))
            engine = ivf_pq._resolve_scan_engine(aux["pq_dim"],
                                                 aux["pq_bits"])
            statics = (aux["metric"], self.k, self.n_probes, per_cluster,
                       p.lut_dtype, p.internal_distance_dtype,
                       aux["pq_bits"], hoisted, aux["probe_extra"], engine)
            key = ("ivf_pq",) + statics + (self.masked,)
            builder = lambda: _ivf_pq_program(  # noqa: E731
                sharded.comms, *statics, masked=self.masked)
            self.hoisted = hoisted
            self.lut_dtype = p.lut_dtype
        else:
            expects(sharded.kind == "brute_force",
                    f"unknown sharded kind {sharded.kind!r}")
            expects(not self.masked, "tombstone masking needs an IVF kind "
                    "(brute_force has no id-carrying probe scan)")
            expects(params is None, "brute_force sharded search takes no "
                    "SearchParams (metric rides the ShardedIndex)")
            expects(self.k <= aux["n_rows"],
                    f"k={k} must be <= n_index={aux['n_rows']}")
            key = ("brute_force", aux["metric"], aux["metric_arg"], self.k,
                   aux["tile"], aux["rows_per"])
            builder = lambda: _brute_force_program(  # noqa: E731
                sharded.comms, aux["metric"], aux["metric_arg"], self.k,
                aux["tile"], aux["rows_per"])
        self.fn = _searcher_fn(sharded, key, builder,
                               extra_replicated=1 if self.masked else 0)
        self._tail = tuple(sharded.replicated) + tuple(sharded.stacked)

    @property
    def dim(self) -> int:
        return self.sharded.dim

    def _rep_spec(self, shape, dtype):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.ShapeDtypeStruct(
            tuple(int(s) for s in shape), jnp.dtype(dtype),
            sharding=NamedSharding(self.sharded.comms.mesh, P()))

    def _q_spec(self, bucket: int, dtype):
        return self._rep_spec((int(bucket), self.dim), dtype)

    def warm(self, bucket: int, dtype, *extra) -> None:
        """Pre-lower+compile the (bucket, dtype, world) signature.
        ``masked`` searchers pass the tombstone-bitmap word count as one
        extra int (the bitmap shape is part of the signature)."""
        extra = tuple(self._rep_spec((int(n),), jnp.uint32) for n in extra)
        self.fn.compiled(self._q_spec(bucket, dtype), *self._tail, *extra)

    def dispatch(self, qb, *extra):
        """Run one PRE-BUCKETED (bucket, dim) batch; returns replicated
        (d (bucket, k), i (bucket, k)).  ``masked`` searchers pass the
        replicated tombstone bitmap (already globalized — the mutable
        writer replicates it ONCE per mutation, not per dispatch)."""
        from jax.sharding import PartitionSpec as P

        q = self.sharded.comms.globalize(jnp.asarray(qb), P())
        return self.fn(q, *self._tail, *extra)


# ---------------------------------------------------------------------------
# the public search entry point


def _ingest(sharded: ShardedIndex, queries):
    """Per-kind compute-form prologue — MUST match the single-device
    search's own ingest so sharded results stay comparable bit-for-bit."""
    if sharded.kind == "ivf_pq":
        q, q_dtype = ivf_pq._ingest_dataset(queries)
        expects(q_dtype in (sharded.aux["dataset_dtype"], "float32"),
                f"query dtype {q_dtype} != index dataset dtype "
                f"{sharded.aux['dataset_dtype']}")
        return q
    q = jnp.asarray(queries)
    if sharded.kind == "ivf_flat":
        q = q.astype(ivf_flat._compute_dtype(q))
        if sharded.metric == DistanceType.CosineExpanded:
            q = ivf_flat._normalize_rows(q)
    return q


@traced("raft_tpu.neighbors.ann_mnmg.search")
def search(sharded: ShardedIndex, queries, k: int, params=None, *,
           batch_size_query: int = 1024
           ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Search a :class:`ShardedIndex` across all of its devices.

    One ``shard_map`` program per (bucketed) query batch: replicated
    coarse ranking → per-shard fused probe scan → ONE packed allgather →
    on-device part merge.  Returns replicated ``(distances (nq, k),
    indices (nq, k))`` — top-k IDENTICAL (f32) to the single-device
    search of the unsharded index (ties at exactly-equal distances may
    resolve by shard order instead of scan order).
    """
    q = _ingest(sharded, queries)
    expects(q.ndim == 2 and q.shape[1] == sharded.dim, "query dim mismatch")
    if q.shape[0] == 0:
        # distance dtype must match the solo path's empty result: the
        # accumulation dtype of the ingested queries (f32 for ivf_pq,
        # whose ingest already lands on f32)
        from raft_tpu.distance.pairwise import accum_dtype

        return empty_result(0, int(k), accum_dtype(q.dtype))
    s = sharded.searcher(int(k), params)
    bs = int(batch_size_query)
    if sharded.kind == "ivf_pq" and getattr(s, "hoisted", False):
        cap = ivf_pq.hoisted_batch_cap_dims(
            sharded.metric, sharded.aux["codebook_kind"]
            == int(ivf_pq.CodebookKind.PER_CLUSTER),
            sharded.aux["cap_n_phys"], sharded.aux["cap_max_chunks"],
            sharded.aux["n_lists"], sharded.aux["pq_dim"],
            sharded.aux["pq_bits"], s.n_probes, s.lut_dtype, s.hoisted)
        if cap is not None:
            bs = min(bs, cap)
    out_d, out_i = [], []
    for q0 in range(0, q.shape[0], bs):
        q1 = min(q0 + bs, q.shape[0])
        qb = q[q0:q1]
        n_valid = qb.shape[0]
        bucket = min(_bucket_dim(n_valid), bs)
        if bucket != n_valid:
            qb = jnp.pad(qb, ((0, bucket - n_valid), (0, 0)))
        d, i = s.dispatch(qb)
        if n_valid != qb.shape[0]:
            d, i = d[:n_valid], i[:n_valid]
        out_d.append(d)
        out_i.append(i)
    d = out_d[0] if len(out_d) == 1 else jnp.concatenate(out_d, axis=0)
    i = out_i[0] if len(out_i) == 1 else jnp.concatenate(out_i, axis=0)
    return d, i


# ---------------------------------------------------------------------------
# HLO audit declarations (raft_tpu.analysis.hlo_audit): budgets for the
# sharded search programs live HERE, next to the programs they bound.
# Both entries pin the ONE-collective-per-batch contract STATICALLY — the
# runtime Comms.collective_calls asserts count launches while serving;
# the auditor counts them in the optimized module before any bench runs.


def _audit_sharded(kind: str):
    """Tiny sharded searcher on the full-device mesh; returns the warmed
    executable for a (64, dim) f32 query bucket, k=8."""
    rng = np.random.default_rng(0)
    comms = Comms()
    x = rng.standard_normal((1024, 16)).astype(np.float32)
    if kind == "ivf_flat":
        sharded = shard_ivf_flat(
            ivf_flat.build(ivf_flat.IndexParams(n_lists=8), x), comms)
    elif kind == "ivf_pq":
        sharded = shard_ivf_pq(
            ivf_pq.build(ivf_pq.IndexParams(n_lists=8, pq_dim=4), x),
            comms)
    else:
        sharded = shard_brute_force(x, comms)
    s = ShardedSearcher(sharded, 8)
    return dict(compiled=s.fn.compiled(
        s._q_spec(64, jnp.float32), *s._tail))


#: one allgather of the packed (nq, 2k) f32 merge payload, stacked over
#: the world: 8 shards × 64 queries × 16 lanes × 4 B
_SHARDED_AUDIT_BYTES = 8 * 64 * 2 * 8 * 4


@hlo_program(
    "ann_mnmg.ivf_flat_sharded",
    collectives=1, collective_bytes=_SHARDED_AUDIT_BYTES,
    requires_devices=8, fast=False,
    notes="whole sharded ivf_flat batch search as ONE shard_map program: "
          "replicated coarse + per-shard probe scan + ONE allgather merge "
          "(docs/sharded_ann.md)")
def _audit_sharded_ivf_flat():
    return _audit_sharded("ivf_flat")


@hlo_program(
    "ann_mnmg.brute_force_sharded",
    collectives=1, collective_bytes=_SHARDED_AUDIT_BYTES,
    requires_devices=8, fast=False,
    notes="row-sharded brute-force kNN: per-shard fused scan + ONE "
          "allgather merge (docs/sharded_ann.md)")
def _audit_sharded_brute_force():
    return _audit_sharded("brute_force")


@hlo_program(
    "ann_mnmg.ivf_pq_sharded",
    collectives=1, collective_bytes=_SHARDED_AUDIT_BYTES,
    requires_devices=8, fast=False,
    notes="whole sharded ivf_pq batch search (hoisted-LUT pipeline) as "
          "ONE shard_map program: replicated coarse + per-shard ADC probe "
          "scan + ONE allgather merge — completes the three serve "
          "backends in sharded form (docs/sharded_ann.md)")
def _audit_sharded_ivf_pq():
    return _audit_sharded("ivf_pq")


#: ONE allgather per batch PER REPLICA GROUP: a group's program spans only
#: its own sub-mesh, so the payload stacks over the GROUP world (8/2 = 4
#: shards) — the ×R total collective budget of a replica-routed fleet is
#: R groups × this per-group bound (docs/sharded_ann.md §replica groups)
_REPLICA_GROUP_AUDIT_BYTES = (8 // 2) * 64 * 2 * 8 * 4


@hlo_program(
    "ann_mnmg.ivf_flat_replica_group",
    collectives=1, collective_bytes=_REPLICA_GROUP_AUDIT_BYTES,
    requires_devices=8, fast=False,
    notes="one replica group's batch search on the 2D (shard × replica) "
          "carve (R=2 over the 8-device mesh): the SAME one-shard_map-"
          "program discipline as the full-mesh entries, lowered on the "
          "group's own 4-device sub-mesh — exactly ONE allgather of the "
          "group-world-stacked merge payload, so the fleet-total budget "
          "is R × this bound (docs/sharded_ann.md §replica groups)")
def _audit_replica_group():
    rng = np.random.default_rng(0)
    layout = Comms().replica_split(2)
    x = rng.standard_normal((1024, 16)).astype(np.float32)
    rep = replicate(ivf_flat.build(ivf_flat.IndexParams(n_lists=8), x),
                    layout)
    s = ShardedSearcher(rep.replicas[0], 8)
    return dict(compiled=s.fn.compiled(
        s._q_spec(64, jnp.float32), *s._tail))
