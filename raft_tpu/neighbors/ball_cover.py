"""Random ball cover: landmark-based exact kNN.

Counterpart of reference ``neighbors/ball_cover.cuh:63-336``
(``build_index`` / ``all_knn_query`` / ``knn_query`` / ``eps_nn``; impl
``spatial/knn/detail/ball_cover.cuh:70,122`` — Cayton's random ball cover):
sample landmarks, group points by nearest landmark, prune scans with the
triangle inequality ``d(q, x) ≥ d(q, L) − radius(L)``.

TPU-first redesign: the reference's register-tuned 2D/3D pass kernels
(detail/ball_cover/registers.cuh) become the same padded-list scan used by
IVF-Flat, and the *dynamic* per-query pruning becomes a two-pass scheme
with a **certificate of exactness** that keeps all shapes static:

1. probe the P nearest landmarks per query (static P), keeping running
   top-k;
2. check per query that every unprobed landmark's lower bound
   ``d(q, L) − radius(L)`` exceeds the current k-th distance;
3. if any query fails the certificate, double P and rerun (host loop —
   each attempt is one compiled computation).

Step 3 terminates at P = n_landmarks, where the scan is exhaustive, so the
result is always exact — same guarantee as the reference, with the
data-dependent work expressed as shape-bucketed retries instead of
divergent warps.

Supported metrics: L2 (sqrt/squared) and Haversine, as in the reference.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.error import expects
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.distance.pairwise import distance as _pairwise
from raft_tpu.neighbors._common import (
    empty_result,
    pack_lists,
    scan_probe_lists,
)

_SUPPORTED = (DistanceType.L2SqrtExpanded, DistanceType.L2SqrtUnexpanded,
              DistanceType.Haversine)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class BallCoverIndex:
    """Reference ``BallCoverIndex`` (neighbors/ball_cover_types.hpp):
    landmarks + per-landmark padded point blocks + radii."""

    landmarks: jnp.ndarray      # (n_landmarks, dim)
    radii: jnp.ndarray          # (n_landmarks,) max dist to members
    list_data: jnp.ndarray      # (n_landmarks, capacity, dim)
    list_indices: jnp.ndarray   # (n_landmarks, capacity) int32, -1 pad
    list_sizes: jnp.ndarray     # (n_landmarks,) int32
    metric: DistanceType

    @property
    def n_landmarks(self) -> int:
        return self.landmarks.shape[0]

    @property
    def dim(self) -> int:
        return self.landmarks.shape[1]

    @property
    def capacity(self) -> int:
        return self.list_data.shape[1]

    def tree_flatten(self):
        return ((self.landmarks, self.radii, self.list_data,
                 self.list_indices, self.list_sizes), (self.metric,))

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, metric=aux[0])


def _tile_distance(q, data, metric: DistanceType):
    """Distances from queries (nq, dim) to gathered tiles (nq, cap, dim).

    Half-precision inputs are upcast so scores accumulate in f32
    (pairwise.accum_dtype policy, same as brute_force/ivf_flat — r4
    advisor finding: the nq==0 path already returned accum_dtype, and the
    certificate's exactness promise needs full-precision scores anyway).

    The L2 branch is the DIRECT Σ(q−x)² form, not the expanded
    ||q||²+||x||²−2⟨q,x⟩ trick the other scans use: a (q, c) tile pair is
    a batched matvec (no shared MXU matmul to exploit), the flop cost is
    the same, and the expanded form's cancellation noise (~1e-7 squared,
    ≈5e-4 after sqrt) is NOT exactly 0 on self-pairs unless XLA happens
    to fuse the norms into the epilogue — an accident this module's
    exactness certificate must not depend on (measured:
    test_ball_cover_all_knn broke when a consumer change shifted fusion)."""
    from raft_tpu.distance.pairwise import accum_dtype

    acc = accum_dtype(q.dtype)
    q = q.astype(acc)
    data = data.astype(acc)
    if metric == DistanceType.Haversine:
        dlat = q[:, None, 0] - data[:, :, 0]
        dlon = q[:, None, 1] - data[:, :, 1]
        h = (jnp.sin(dlat / 2) ** 2 +
             jnp.cos(q[:, None, 0]) * jnp.cos(data[:, :, 0]) *
             jnp.sin(dlon / 2) ** 2)
        return 2.0 * jnp.arcsin(jnp.sqrt(jnp.clip(h, 0.0, 1.0)))
    diff = q[:, None, :] - data
    return jnp.sqrt(jnp.sum(diff * diff, axis=-1))


def build_index(x, metric: DistanceType = DistanceType.L2SqrtExpanded,
                n_landmarks: Optional[int] = None, seed: int = 0
                ) -> BallCoverIndex:
    """Sample ≈√n landmarks, group points by nearest landmark, record
    per-landmark radii (reference ``build_index``, ball_cover.cuh:63;
    ``sample_landmarks`` + ``construct_landmark_1nn``,
    detail/ball_cover.cuh:70,122)."""
    x = jnp.asarray(x)
    expects(x.ndim == 2, "x must be (n, dim)")
    metric = DistanceType(metric)
    expects(metric in _SUPPORTED, f"ball_cover: unsupported metric {metric}")
    if metric == DistanceType.Haversine:
        expects(x.shape[1] == 2, "haversine needs (lat, lon) columns")
    n = x.shape[0]
    if n_landmarks is None:
        n_landmarks = max(1, int(math.isqrt(n)))
    n_landmarks = min(n_landmarks, n)
    sel = np.sort(np.random.default_rng(seed).choice(
        n, size=n_landmarks, replace=False))
    landmarks = x[jnp.asarray(sel)]
    # 1-NN of every point among landmarks
    d = _pairwise(x, landmarks, metric, 2.0)
    labels = jnp.argmin(d, axis=1).astype(jnp.int32)
    dist = jnp.min(d, axis=1)
    radii = jax.ops.segment_max(dist, labels, num_segments=n_landmarks)

    data, idx, counts, _ = pack_lists(x, jnp.arange(n, dtype=jnp.int32),
                                      labels, n_landmarks)
    return BallCoverIndex(landmarks=landmarks, radii=radii, list_data=data,
                          list_indices=idx, list_sizes=counts, metric=metric)


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _probe_pass(index_leaves, queries, k: int, n_probe: int, metric_val: int):
    """Scan each query's n_probe nearest landmarks; return top-k plus the
    exactness certificate (no unprobed landmark can beat the k-th dist)."""
    landmarks, radii, list_data, list_indices, list_sizes = index_leaves
    metric = DistanceType(int(metric_val))
    nq = queries.shape[0]
    nl = landmarks.shape[0]

    ql = _pairwise(queries, landmarks, metric, 2.0)        # (nq, nl)
    _, probe_order = jax.lax.top_k(-ql, n_probe)           # nearest first

    from raft_tpu.distance.pairwise import accum_dtype

    # NB: unlike brute_force/ivf_flat, nothing is hoisted here —
    # _tile_distance scores with the direct Σ(q−x)² form, which has no
    # per-row statistics to hoist and keeps self-pair distances exactly 0
    # (the expanded-form alternative measurably broke the exactness
    # promise; see _tile_distance's docstring).
    def score_tile(lists):
        return _tile_distance(queries, list_data[lists], metric)

    best_d, best_i = scan_probe_lists(probe_order.astype(jnp.int32),
                                      score_tile, list_indices, list_sizes,
                                      k, select_min=True,
                                      dtype=accum_dtype(queries.dtype))
    # certificate: lower bound of every unprobed landmark vs k-th distance
    probed = jnp.zeros((nq, nl), bool).at[
        jnp.arange(nq)[:, None], probe_order].set(True)
    lb = jnp.maximum(ql - radii[None, :], 0.0)
    kth = best_d[:, -1]
    exact = jnp.all(probed | (lb > kth[:, None]), axis=1)
    return best_d, best_i, exact


def knn_query(index: BallCoverIndex, queries, k: int,
              *, initial_probes: Optional[int] = None,
              batch_size_query: int = 4096
              ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact kNN against the indexed points (reference ``knn_query``,
    ball_cover.cuh:225).  Returns (distances [nq, k], indices [nq, k])."""
    q = jnp.asarray(queries)
    expects(q.ndim == 2 and q.shape[1] == index.dim, "query dim mismatch")
    expects(k >= 1, "k must be >= 1")
    if q.shape[0] == 0:
        from raft_tpu.distance.pairwise import accum_dtype

        return empty_result(0, int(k), accum_dtype(q.dtype))
    nl = index.n_landmarks
    leaves = (index.landmarks, index.radii, index.list_data,
              index.list_indices, index.list_sizes)
    out_d, out_i = [], []
    for q0 in range(0, q.shape[0], batch_size_query):
        q1 = min(q0 + batch_size_query, q.shape[0])
        qb = q[q0:q1]
        p = min(nl, initial_probes) if initial_probes else \
            min(nl, max(4, int(math.isqrt(nl)) * 2))
        while True:
            d, i, exact = _probe_pass(leaves, qb, int(k), int(p),
                                      int(index.metric))
            if bool(jnp.all(exact)) or p >= nl:
                break
            p = min(nl, p * 2)
        out_d.append(d)
        out_i.append(i)
    d = out_d[0] if len(out_d) == 1 else jnp.concatenate(out_d, axis=0)
    i = out_i[0] if len(out_i) == 1 else jnp.concatenate(out_i, axis=0)
    return d, i


def all_knn_query(index: BallCoverIndex, k: int, **kw
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """kNN of the indexed points among themselves (reference
    ``all_knn_query``, ball_cover.cuh:112): self-query over the packed
    lists in source order."""
    live = index.list_indices.reshape(-1) >= 0
    flat = index.list_data.reshape(-1, index.dim)[live]
    ids = index.list_indices.reshape(-1)[live]
    order = jnp.argsort(ids)
    return knn_query(index, flat[order], k, **kw)


def eps_nn(index: BallCoverIndex, queries, eps: float,
           *, batch_size_query: int = 4096
           ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """All neighbors within *eps* (reference ``eps_nn``,
    ball_cover.cuh:291): boolean adjacency (nq, n_indexed) in source-id
    order + per-query degree.  The reference's landmark pruning
    ``d(q, L) − radius(L) > eps`` is subsumed here: pruned lists cannot
    contain hits, and on TPU the dense masked scan is the fast path."""
    q = jnp.asarray(queries)
    expects(q.ndim == 2 and q.shape[1] == index.dim, "query dim mismatch")
    n_total = int(jnp.sum(index.list_sizes))
    leaves = (index.landmarks, index.radii, index.list_data,
              index.list_indices, index.list_sizes)
    out = []
    for q0 in range(0, q.shape[0], batch_size_query):
        q1 = min(q0 + batch_size_query, q.shape[0])
        out.append(_eps_pass(leaves, q[q0:q1], float(eps),
                             int(index.metric), n_total))
    adj = out[0] if len(out) == 1 else jnp.concatenate(out, axis=0)
    return adj, jnp.sum(adj, axis=1, dtype=jnp.int32)


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _eps_pass(index_leaves, queries, eps: float, metric_val: int,
              n_total: int):
    landmarks, radii, list_data, list_indices, list_sizes = index_leaves
    metric = DistanceType(metric_val)
    nq = queries.shape[0]
    nl, cap, dim = list_data.shape

    adj = jnp.zeros((nq, n_total), bool)

    def step(li, adj):
        data = list_data[li]
        ids = list_indices[li]
        d = _pairwise(queries, data, metric, 2.0)          # (nq, cap)
        live = (jnp.arange(cap) < list_sizes[li])[None, :]
        hit = (d <= eps) & live
        return adj.at[:, jnp.where(ids >= 0, ids, n_total)].max(
            hit, mode="drop")

    return jax.lax.fori_loop(0, nl, step, adj)
