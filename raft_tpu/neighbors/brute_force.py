"""Tiled brute-force k-nearest-neighbors.

Counterpart of reference ``neighbors/brute_force.cuh:76,144``
(``knn_merge_parts`` + ``knn``) and ``spatial/knn/detail/``:

- The reference delegates most metrics to FAISS ``bfKnn``
  (knn_brute_force_faiss.cuh:220) and keeps a hand-fused L2 path
  (fused_l2_knn.cuh) that never materializes the full distance matrix.
- TPU-first both collapse into ONE design: a `lax.scan` over index tiles
  where each step computes a (bq × bi) distance tile (MXU matmul for
  expanded metrics) and folds it into a running top-k — the
  distance-epilogue fusion XLA performs plays the role of the reference's
  hand-fused kernel, and HBM traffic stays O(tiles) not O(m·n).

The scan is a FUSED PIPELINE (the three costs the reference's hand-fused
kernel avoids, avoided here too):

1. invariant per-row statistics (row norms etc.) are HOISTED out of the
   loop — query stats once per batch, index stats once per scan, threaded
   through the scan as xs (``distance.pairwise.metric_stats``) instead of
   recomputed by every step's pairwise call;
2. each step folds its tile via partial top-k + a SORTED-RUN MERGE of
   O(k²) vectorized comparisons (``matrix.select_k.merge_sorted_runs``)
   instead of re-sorting (k + tile) concatenated candidates, and tile
   ids stay a broadcast off the step base (no (nq, tile) id
   materialization);
3. ragged query batches are PADDED to the bucketed batch shape
   (``core.aot._bucket_dim``) and sliced after, so the scan executable
   compiles once per bucket signature, not once per remainder shape.

Indices returned are int32; ``global_id_offset`` past the int32 range
promotes them to int64 (requires ``jax_enable_x64``).  The index is never
padded (the ragged tail is its own scan-free step) — only query batches
pad, and their extra rows are sliced off before returning.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from raft_tpu.analysis.registry import hlo_program
from raft_tpu.core.aot import _bucket_dim, aot, aot_dispatchable
from raft_tpu.core.error import expects
from raft_tpu.core.handle import auto_sync_handle
from raft_tpu.distance.distance_types import DISTANCE_TYPES, DistanceType
from raft_tpu.matrix.select_k import (merge_sorted_parts, merge_sorted_runs,
                                      select_k)

_INT32_MAX = 2**31 - 1


def _resolve_metric(metric) -> DistanceType:
    if isinstance(metric, str):
        m = DISTANCE_TYPES.get(metric.lower())
        expects(m is not None, f"unknown metric {metric!r}")
        return m
    return DistanceType(metric)


def _knn_scan_impl(index, queries, k: int, metric: DistanceType,
                   metric_arg: float, tile: int, select_min: bool):
    """Running top-k over index tiles: never materializes (m, n)."""
    from raft_tpu.distance.pairwise import (accum_dtype, distance_with_stats,
                                            metric_stats)

    # sqrt is monotone: scan + select on SQUARED L2, root only the final
    # (nq, k) — the per-tile (nq, tile) sqrt pass disappears.  Returned
    # distances are bit-identical to the root-then-select reference path;
    # ties are resolved on the squared values, which distinguish pairs
    # f32 sqrt would collapse (strictly sharper tie-breaking, but an
    # exact-index comparison against a rooted-path selection can differ
    # on such near-ties).
    defer_sqrt = metric == DistanceType.L2SqrtExpanded
    scan_metric = DistanceType.L2Expanded if defer_sqrt else metric

    n, dim = index.shape
    # No index padding and no per-step validity mask: the scan covers the
    # full tiles and the ragged tail folds in as one extra unrolled step.
    # A masking `where` between the epilogue and the tile select measurably
    # blocks XLA from fusing the select's block-extremum reduce into the
    # distance epilogue (~50% per-step cost on CPU); keeping every scanned
    # tile all-real sidesteps the mask entirely.
    n_full = n // tile
    rem = n - n_full * tile

    # hoisted invariant statistics: query stats once per batch, index
    # stats once per scan; the scan body consumes the per-tile slice as xs
    q_stats = metric_stats(queries, scan_metric)
    i_stats = metric_stats(index, scan_metric)

    nq = queries.shape[0]
    # running top-k carry must match the distance dtype: f32 for
    # half-precision inputs (pairwise accumulates them in f32)
    val_dtype = accum_dtype(queries.dtype)
    sentinel = jnp.asarray(jnp.inf if select_min else -jnp.inf, val_dtype)

    def fold(carry, tile_x, tile_stats, base, width):
        best_d, best_i = carry
        d = distance_with_stats(queries, tile_x, scan_metric, metric_arg,
                                q_stats, tile_stats).astype(val_dtype)
        # partial top-k of this tile (block-extremum candidate filter),
        # positions broadcast off the base — then an O(k²)-comparison
        # merge of two sorted runs; the carry (earlier tiles = lower ids)
        # wins ties, reproducing a stable full sort exactly
        tile_d, pos = select_k(d, min(k, width), select_min=select_min)
        tile_i = base + pos.astype(jnp.int32)
        return merge_sorted_runs(best_d, best_i, tile_d, tile_i, k=k,
                                 select_min=select_min)

    carry = (jnp.full((nq, k), sentinel, val_dtype),
             jnp.full((nq, k), -1, jnp.int32))
    if n_full:
        tiles = index[:n_full * tile].reshape(n_full, tile, dim)
        t_stats = i_stats[:n_full * tile].reshape(n_full, tile, -1)
        bases = (jnp.arange(n_full) * tile).astype(jnp.int32)

        def step(carry, xs):
            tile_x, tile_stats, base = xs
            return fold(carry, tile_x, tile_stats, base, tile), None

        carry, _ = jax.lax.scan(step, carry, (tiles, t_stats, bases))
    if rem:
        carry = fold(carry, index[n_full * tile:], i_stats[n_full * tile:],
                     jnp.int32(n_full * tile), rem)
    best_d, best_i = carry
    if defer_sqrt:
        best_d = jnp.sqrt(best_d)
    return best_d, best_i


def _knn_scan_chunked(index, queries, k: int, metric: DistanceType,
                      metric_arg: float, tile: int, select_min: bool,
                      batch_size_query: int = 4096):
    """Traced-context query chunking around :func:`_knn_scan_impl`.

    ``knn()`` bounds the per-scan-step (bq, tile) distance transient with
    its eager query loop; shard_map programs (knn_mnmg, ann_mnmg) call
    the scan impl directly inside a trace and would otherwise materialize
    a (nq, tile) tile per step — 4 GB at nq=64k, tile=16k.  This restores
    the same bound inside the trace (nq is static there, so the chunk
    loop unrolls into independent scan segments)."""
    nq = queries.shape[0]
    if nq <= batch_size_query:
        return _knn_scan_impl(index, queries, k, metric, metric_arg, tile,
                              select_min)
    outs = [_knn_scan_impl(index, queries[q0:min(q0 + batch_size_query, nq)],
                           k, metric, metric_arg, tile, select_min)
            for q0 in range(0, nq, batch_size_query)]
    return (jnp.concatenate([d for d, _ in outs], axis=0),
            jnp.concatenate([i for _, i in outs], axis=0))


# Eager calls dispatch the AOT executable cache (the precompiled
# libraft-nn role, SURVEY.md §2.14) so steady-state serving skips the
# per-call trace check; jit kept for traced callers and off-default-device
# inputs.  serve.ServeEngine warms and dispatches _knn_scan_aot directly.
_KNN_STATICS = (2, 3, 4, 5, 6)
_knn_scan = functools.partial(jax.jit, static_argnums=_KNN_STATICS)(
    _knn_scan_impl)
_knn_scan_aot = aot(_knn_scan_impl, static_argnums=_KNN_STATICS)


@hlo_program(
    "brute_force.knn_scan",
    collectives=0, collective_bytes=0,
    # per-step transient: the (nq, tile) distance tile + select scratch —
    # NOT the (m, n) matrix the scan exists to avoid (64×4096 f32 ≈ 1 MB
    # with fusion headroom; a full-matrix regression would be ≥ 4 MB here)
    transient_bytes=2 << 20,
    notes="the ServeEngine brute-force backend program (one dispatch per "
          "super-batch; docs/serving.md)")
def _audit_knn_scan():
    q = jax.ShapeDtypeStruct((64, 32), jnp.float32)
    xs = jax.ShapeDtypeStruct((4096, 32), jnp.float32)
    return dict(fn=_knn_scan_impl,
                args=(xs, q, 8, DistanceType.L2SqrtExpanded, 2.0, 1024,
                      True),
                static_argnums=_KNN_STATICS)


@auto_sync_handle
def knn(index, queries, k: int,
        metric: Union[str, DistanceType] = DistanceType.L2SqrtExpanded,
        metric_arg: float = 2.0, *,
        batch_size_index: int = 16384,
        batch_size_query: int = 4096,
        global_id_offset: int = 0,
        handle=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact k-nearest-neighbors of *queries* among rows of *index*.

    Reference ``brute_force::knn`` (neighbors/brute_force.cuh:144; impl
    spatial/knn/detail/knn_brute_force_faiss.cuh:332-353) with the same
    ``translations``-style *global_id_offset* for sharded indexes.

    Returns (distances [nq, k], indices [nq, k] int32 — int64 when
    *global_id_offset* pushes ids past int32, which requires
    ``jax_enable_x64``).
    """
    index = jnp.asarray(index)
    queries = jnp.asarray(queries)
    metric = _resolve_metric(metric)
    expects(index.ndim == 2 and queries.ndim == 2, "inputs must be 2-d")
    expects(index.shape[1] == queries.shape[1], "feature dim mismatch")
    expects(1 <= k <= index.shape[0],
            f"k={k} must be in [1, n_index={index.shape[0]}]")
    if queries.shape[0] == 0:
        from raft_tpu.distance.pairwise import accum_dtype
        from raft_tpu.neighbors._common import empty_result

        return empty_result(0, int(k), accum_dtype(queries.dtype))
    tile = min(batch_size_index, index.shape[0])
    # InnerProduct is a similarity: kNN selects the LARGEST values
    # (reference knn_brute_force_faiss.cuh: IP uses a max-selection heap).
    select_min = metric != DistanceType.InnerProduct
    bs = int(batch_size_query)
    out_d, out_i = [], []
    for q0 in range(0, queries.shape[0], bs):
        q1 = min(q0 + bs, queries.shape[0])
        qb = queries[q0:q1]
        n_valid = q1 - q0
        # Bucket the ragged tail batch (pad + slice, same policy as
        # ivf_flat/ivf_pq.search): one compiled scan per bucket signature
        # instead of one per remainder shape.
        bucket = min(_bucket_dim(n_valid), bs)
        if bucket != n_valid:
            qb = jnp.pad(qb, ((0, bucket - n_valid), (0, 0)))
        scan_fn = (_knn_scan_aot if aot_dispatchable(index, qb)
                   else _knn_scan)
        d, i = scan_fn(index, qb, int(k), metric, float(metric_arg),
                       int(tile), select_min)
        if bucket != n_valid:
            d, i = d[:n_valid], i[:n_valid]
        out_d.append(d)
        out_i.append(i)
    d = out_d[0] if len(out_d) == 1 else jnp.concatenate(out_d, axis=0)
    i = out_i[0] if len(out_i) == 1 else jnp.concatenate(out_i, axis=0)
    if global_id_offset:
        expects(global_id_offset >= 0, "global_id_offset must be >= 0")
        if int(global_id_offset) + index.shape[0] - 1 > _INT32_MAX:
            # int64-safe sharded-id handling: ids past 2^31 must not
            # silently wrap (knn_mnmg shards past 2^31 rows land here)
            expects(bool(jax.config.jax_enable_x64),
                    f"global_id_offset={global_id_offset} pushes ids past "
                    "int32; enable jax_enable_x64 for int64 ids")
            i = i.astype(jnp.int64) + jnp.asarray(global_id_offset, jnp.int64)
        else:
            i = i + jnp.int32(global_id_offset)
    return d, i


def brute_force_knn(index, queries, k: int, **kw):
    """Alias with the reference's legacy name (spatial/knn/knn.cuh)."""
    return knn(index, queries, k, **kw)


def fused_l2_knn(index, queries, k: int, sqrt: bool = True,
                 **kw) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """L2 kNN without materializing the distance matrix (reference
    ``spatial/knn/detail/fused_l2_knn.cuh``).  On TPU the generic tiled
    scan already is the fused form; this surface pins the metric."""
    metric = (DistanceType.L2SqrtExpanded if sqrt
              else DistanceType.L2Expanded)
    return knn(index, queries, k, metric, **kw)


def knn_merge_parts(part_distances, part_indices, k: Optional[int] = None,
                    translations: Optional[Sequence[int]] = None,
                    metric: Union[str, DistanceType] = DistanceType.L2SqrtExpanded
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Merge per-part top-k results into a global top-k.

    Reference ``knn_merge_parts`` (neighbors/brute_force.cuh:76; FAISS
    block-select merge in knn_brute_force_faiss.cuh:66-139): parts are
    (n_parts, n_queries, k) stacked results from sharded indexes;
    *translations* offsets each part's local ids into the global id space.
    *metric* must match the per-part searches: InnerProduct results are
    similarities and merge with max-selection.

    Part rows must be SORTED best-first — the contract every ``knn``/
    ``select_k`` output satisfies, and the same precondition the
    reference's block-select merge has.  The merge is a fold of
    ``matrix.select_k.merge_sorted_runs`` over parts: O(n_parts·k²)
    vectorized comparisons instead of re-sorting n_parts·k candidates.
    When *k* exceeds the per-part width, candidates whose distance equals
    the sentinel (±inf) may come back with id -1 in the padded slots;
    within the per-part width every real candidate keeps its id.
    """
    select_min = _resolve_metric(metric) != DistanceType.InnerProduct
    d = jnp.asarray(part_distances)
    i = jnp.asarray(part_indices)
    expects(d.ndim == 3 and i.shape == d.shape,
            "expected (n_parts, n_queries, k) distances+indices")
    n_parts, nq, in_k = d.shape
    if k is None:
        k = in_k
    k = int(k)
    expects(k <= n_parts * in_k, "k larger than total candidates")
    if translations is not None:
        expects(len(translations) == n_parts,
                "need one translation per part")
        t = jnp.asarray(translations, i.dtype).reshape(n_parts, 1, 1)
        i = i + t
    # The fold itself (part-0 seed, earlier-part-wins ties) is the shared
    # matrix.select_k.merge_sorted_parts primitive — ONE implementation
    # under this surface and the sharded-ANN cross-shard merge.
    return merge_sorted_parts(d, i, k=k, select_min=select_min)
