"""Tiled brute-force k-nearest-neighbors.

Counterpart of reference ``neighbors/brute_force.cuh:76,144``
(``knn_merge_parts`` + ``knn``) and ``spatial/knn/detail/``:

- The reference delegates most metrics to FAISS ``bfKnn``
  (knn_brute_force_faiss.cuh:220) and keeps a hand-fused L2 path
  (fused_l2_knn.cuh) that never materializes the full distance matrix.
- TPU-first both collapse into ONE design: a `lax.scan` over index tiles
  where each step computes a (bq × bi) distance tile (MXU matmul for
  expanded metrics) and folds it into a running top-k — the
  distance-epilogue fusion XLA performs plays the role of the reference's
  hand-fused kernel, and HBM traffic stays O(tiles) not O(m·n).

Indices returned are int32 (padded index rows get ``inf`` distance and are
never selected while n ≥ k live rows exist).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.handle import auto_sync_handle
from raft_tpu.distance.distance_types import DISTANCE_TYPES, DistanceType
from raft_tpu.distance.pairwise import distance as _pairwise
from raft_tpu.matrix.select_k import select_k


def _resolve_metric(metric) -> DistanceType:
    if isinstance(metric, str):
        m = DISTANCE_TYPES.get(metric.lower())
        expects(m is not None, f"unknown metric {metric!r}")
        return m
    return DistanceType(metric)


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5, 6))
def _knn_scan(index, queries, k: int, metric: DistanceType,
              metric_arg: float, tile: int, select_min: bool):
    """Running top-k over index tiles: never materializes (m, n)."""
    n = index.shape[0]
    n_tiles = max(1, -(-n // tile))
    pad = n_tiles * tile - n
    padded = jnp.pad(index, ((0, pad), (0, 0)))
    valid = jnp.arange(n_tiles * tile) < n
    tiles = padded.reshape(n_tiles, tile, index.shape[1])
    vtiles = valid.reshape(n_tiles, tile)
    bases = (jnp.arange(n_tiles) * tile).astype(jnp.int32)

    nq = queries.shape[0]
    # running top-k carry must match the distance dtype: f32 for
    # half-precision inputs (pairwise accumulates them in f32)
    from raft_tpu.distance.pairwise import accum_dtype

    val_dtype = accum_dtype(queries.dtype)
    sentinel = jnp.asarray(jnp.inf if select_min else -jnp.inf, val_dtype)

    def step(carry, xs):
        best_d, best_i = carry
        tile_x, tile_valid, base = xs
        d = _pairwise(queries, tile_x, metric, metric_arg)
        d = jnp.where(tile_valid[None, :], d, sentinel)
        ids = (base + jnp.arange(tile, dtype=jnp.int32))[None, :].repeat(nq, 0)
        merged_d = jnp.concatenate([best_d, d], axis=1)
        merged_i = jnp.concatenate([best_i, ids], axis=1)
        best_d, best_i = select_k(merged_d, k, select_min=select_min,
                                  indices=merged_i)
        return (best_d, best_i), None

    init = (jnp.full((nq, k), sentinel, val_dtype),
            jnp.full((nq, k), -1, jnp.int32))
    (best_d, best_i), _ = jax.lax.scan(step, init, (tiles, vtiles, bases))
    return best_d, best_i


@auto_sync_handle
def knn(index, queries, k: int,
        metric: Union[str, DistanceType] = DistanceType.L2SqrtExpanded,
        metric_arg: float = 2.0, *,
        batch_size_index: int = 8192,
        batch_size_query: int = 4096,
        global_id_offset: int = 0,
        handle=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact k-nearest-neighbors of *queries* among rows of *index*.

    Reference ``brute_force::knn`` (neighbors/brute_force.cuh:144; impl
    spatial/knn/detail/knn_brute_force_faiss.cuh:332-353) with the same
    ``translations``-style *global_id_offset* for sharded indexes.

    Returns (distances [nq, k], indices [nq, k] int32).
    """
    index = jnp.asarray(index)
    queries = jnp.asarray(queries)
    metric = _resolve_metric(metric)
    expects(index.ndim == 2 and queries.ndim == 2, "inputs must be 2-d")
    expects(index.shape[1] == queries.shape[1], "feature dim mismatch")
    expects(1 <= k <= index.shape[0],
            f"k={k} must be in [1, n_index={index.shape[0]}]")
    if queries.shape[0] == 0:
        from raft_tpu.distance.pairwise import accum_dtype
        from raft_tpu.neighbors._common import empty_result

        return empty_result(0, int(k), accum_dtype(queries.dtype))
    tile = min(batch_size_index, index.shape[0])
    # InnerProduct is a similarity: kNN selects the LARGEST values
    # (reference knn_brute_force_faiss.cuh: IP uses a max-selection heap).
    select_min = metric != DistanceType.InnerProduct
    out_d, out_i = [], []
    for q0 in range(0, queries.shape[0], batch_size_query):
        q1 = min(q0 + batch_size_query, queries.shape[0])
        d, i = _knn_scan(index, queries[q0:q1], int(k), metric,
                         float(metric_arg), int(tile), select_min)
        out_d.append(d)
        out_i.append(i)
    d = out_d[0] if len(out_d) == 1 else jnp.concatenate(out_d, axis=0)
    i = out_i[0] if len(out_i) == 1 else jnp.concatenate(out_i, axis=0)
    if global_id_offset:
        i = i + jnp.int32(global_id_offset)
    return d, i


def brute_force_knn(index, queries, k: int, **kw):
    """Alias with the reference's legacy name (spatial/knn/knn.cuh)."""
    return knn(index, queries, k, **kw)


def fused_l2_knn(index, queries, k: int, sqrt: bool = True,
                 **kw) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """L2 kNN without materializing the distance matrix (reference
    ``spatial/knn/detail/fused_l2_knn.cuh``).  On TPU the generic tiled
    scan already is the fused form; this surface pins the metric."""
    metric = (DistanceType.L2SqrtExpanded if sqrt
              else DistanceType.L2Expanded)
    return knn(index, queries, k, metric, **kw)


def knn_merge_parts(part_distances, part_indices, k: Optional[int] = None,
                    translations: Optional[Sequence[int]] = None,
                    metric: Union[str, DistanceType] = DistanceType.L2SqrtExpanded
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Merge per-part top-k results into a global top-k.

    Reference ``knn_merge_parts`` (neighbors/brute_force.cuh:76; FAISS
    block-select merge in knn_brute_force_faiss.cuh:66-139): parts are
    (n_parts, n_queries, k) stacked results from sharded indexes;
    *translations* offsets each part's local ids into the global id space.
    *metric* must match the per-part searches: InnerProduct results are
    similarities and merge with max-selection.
    """
    select_min = _resolve_metric(metric) != DistanceType.InnerProduct
    d = jnp.asarray(part_distances)
    i = jnp.asarray(part_indices)
    expects(d.ndim == 3 and i.shape == d.shape,
            "expected (n_parts, n_queries, k) distances+indices")
    n_parts, nq, in_k = d.shape
    if k is None:
        k = in_k
    expects(k <= n_parts * in_k, "k larger than total candidates")
    if translations is not None:
        expects(len(translations) == n_parts,
                "need one translation per part")
        t = jnp.asarray(translations, i.dtype).reshape(n_parts, 1, 1)
        i = i + t
    merged_d = jnp.moveaxis(d, 0, 1).reshape(nq, n_parts * in_k)
    merged_i = jnp.moveaxis(i, 0, 1).reshape(nq, n_parts * in_k)
    return select_k(merged_d, int(k), select_min=select_min, indices=merged_i)
