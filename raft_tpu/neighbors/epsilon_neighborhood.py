"""Epsilon neighborhood: boolean adjacency within a radius.

Counterpart of reference ``neighbors/epsilon_neighborhood.cuh:48``
(``epsUnexpL2SqNeighborhood``): for each (x_i, y_j) pair, adjacency
``‖x_i − y_j‖² ≤ eps`` plus per-row vertex degrees — the DBSCAN building
block.  The reference fuses the unexpanded L2 into the tiled contraction
kernel; on TPU the expanded form rides the MXU and XLA fuses the
threshold + popcount epilogue.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.distance.pairwise import distance as _pairwise


@functools.partial(jax.jit, static_argnums=())
def _eps_tile(x, y, eps):
    d = _pairwise(x, y, DistanceType.L2Expanded, 2.0)
    adj = d <= eps
    return adj, jnp.sum(adj, axis=1, dtype=jnp.int32)


def eps_neighbors_l2sq(x, y, eps: float, *, batch_size: int = 8192
                       ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Adjacency of squared-L2 balls: ``adj[i, j] = ‖x_i − y_j‖² ≤ eps``.

    Returns (adj [m, n] bool, vd [m] int32 row degrees).  *eps* is the
    squared radius, as in the reference.
    """
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    expects(x.ndim == 2 and y.ndim == 2, "inputs must be 2-d")
    expects(x.shape[1] == y.shape[1], "feature dim mismatch")
    eps = jnp.asarray(eps, x.dtype)
    adj_rows, vd_rows = [], []
    for i0 in range(0, x.shape[0], batch_size):
        i1 = min(i0 + batch_size, x.shape[0])
        adj, vd = _eps_tile(x[i0:i1], y, eps)
        adj_rows.append(adj)
        vd_rows.append(vd)
    adj = adj_rows[0] if len(adj_rows) == 1 else jnp.concatenate(adj_rows, 0)
    vd = vd_rows[0] if len(vd_rows) == 1 else jnp.concatenate(vd_rows, 0)
    return adj, vd


def eps_neighbors(x, y, eps: float, **kw):
    """Radius (not squared) convenience wrapper."""
    return eps_neighbors_l2sq(x, y, float(eps) ** 2, **kw)
