"""Haversine (great-circle) k-nearest-neighbors.

Counterpart of reference ``spatial/knn/detail/haversine_distance.cuh``
(``haversine_knn``): brute-force kNN under the haversine metric over
(latitude, longitude) pairs in radians.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.neighbors.brute_force import knn


def haversine_knn(index, queries, k: int, **kw
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """kNN in great-circle distance; rows are (lat, lon) in radians.

    Returns (distances [nq, k], indices [nq, k]).
    """
    index = jnp.asarray(index)
    queries = jnp.asarray(queries)
    expects(index.shape[1] == 2 and queries.shape[1] == 2,
            "haversine inputs must be (n, 2) lat/lon radians")
    return knn(index, queries, k, DistanceType.Haversine, **kw)
