"""IVF-Flat approximate nearest-neighbor index.

Counterpart of reference ``neighbors/ivf_flat.cuh`` +
``spatial/knn/detail/ivf_flat_{build,search}.cuh`` (SURVEY.md §2.8):
coarse k-means quantizer (balanced hierarchical, ann_kmeans_balanced.cuh:942)
→ inverted lists of raw vectors → search = coarse GEMM + top-n_probes +
masked list scan + final top-k.

TPU-first redesign of the storage layout: the reference packs each list in
interleaved groups of ``kIndexGroupSize = 32·veclen`` rows tuned for warp
coalescing (ivf_flat_types.hpp:58-109) — a CUDA-ism.  Here every list is a
row-block of one dense (n_lists, list_capacity, dim) array padded to a
lane-friendly capacity (multiple of 8): each (query, probe) scan step is a
(capacity × dim)·(dim) contraction the MXU tiles natively, and padding is
masked with +inf distances.  Ragged lists become static shapes — the XLA
requirement SURVEY.md §7 calls out — at the cost of measured padding waste
(`Index.padding_fraction`).

Supported dtypes mirror the reference (f32 + int8/uint8 storage with f32
compute); supported metrics: L2Expanded/L2SqrtExpanded/InnerProduct/
CosineExpanded (cosine = IP on normalized vectors, as in the reference
search prologue ivf_flat_search.cuh:1120).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.analysis.registry import hlo_program
from raft_tpu.core.aot import _bucket_dim, aot, aot_dispatchable
from raft_tpu.core.error import expects
from raft_tpu.core.handle import auto_sync_handle
from raft_tpu.core.logger import traced
from raft_tpu.cluster import build_hierarchical, min_cluster_and_distance
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.distance.pairwise import (_l2_expanded, _mxu_dot, _row_norms,
                                        accum_dtype)
from raft_tpu.matrix.select_k import select_k
from raft_tpu.neighbors import _build
from raft_tpu.neighbors._common import (
    chunk_layout,
    device_counts,
    empty_result,
    expand_probes,
    extend_lists_chunked,
    pack_lists_chunked,
    scan_probe_lists,
    subsample_trainset,
    validate_new_ids,
)
from raft_tpu.random.rng import RngState

_SUPPORTED = (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
              DistanceType.InnerProduct, DistanceType.CosineExpanded)


@dataclasses.dataclass
class IndexParams:
    """Reference ``ivf_flat::index_params`` (ivf_flat_types.hpp:30)."""

    n_lists: int = 1024
    metric: DistanceType = DistanceType.L2Expanded
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    adaptive_centers: bool = False
    add_data_on_build: bool = True
    seed: int = 1234


@dataclasses.dataclass
class SearchParams:
    """Reference ``ivf_flat::search_params`` (ivf_flat_types.hpp:118)."""

    n_probes: int = 20
    # Exact re-rank ratio for TIERED serving (neighbors.tiering): search
    # with k·ratio candidates, then re-score the survivors against the
    # original host-tier vectors with exact distance.  None/1 disables.
    # Honored by the tiered backend only — the fully-resident flat scan
    # already scores exact distances, so there is nothing to refine.
    refine_ratio: Optional[int] = None


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Index:
    """IVF-Flat index: CHUNKED padded inverted lists.

    A logical list of size s occupies ceil(s / cap) fixed-capacity physical
    rows (bounded padding waste on skewed cluster sizes — the reference
    allocates per list, ivf_list.hpp; flat max-capacity padding would be
    quadratic-ish there).  The last physical row is a reserved empty dummy.

    ``list_data``    (n_phys+1, cap, dim) — stored vectors (storage dtype)
    ``list_indices`` (n_phys+1, cap) int32 — source ids, -1 at padding
    ``phys_sizes``   (n_phys+1,) int32 — live rows per physical chunk
    ``chunk_table``  (n_lists, max_chunks) int32 — logical → physical rows
    ``list_sizes``   (n_lists,) int32 — logical list sizes
    ``centers``      (n_lists, dim) f32 coarse centroids
    """

    centers: jnp.ndarray
    list_data: jnp.ndarray
    list_indices: jnp.ndarray
    list_sizes: jnp.ndarray
    phys_sizes: jnp.ndarray
    chunk_table: jnp.ndarray
    metric: DistanceType
    adaptive_centers: bool = False

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def capacity(self) -> int:
        """Per-chunk capacity."""
        return self.list_data.shape[1]

    @property
    def size(self) -> int:
        return int(jnp.sum(self.list_sizes))

    @property
    def padding_fraction(self) -> float:
        """Fraction of allocated list slots that are padding — the metric
        SURVEY.md §7 says to measure for the padded-list design (bounded
        by construction under chunking)."""
        total = self.list_data.shape[0] * self.capacity
        return 1.0 - self.size / max(total, 1)

    def shard(self, comms):
        """Partition this index's lists round-robin across *comms*' devices
        for multi-device search — returns a
        :class:`raft_tpu.neighbors.ann_mnmg.ShardedIndex` whose
        ``search``/serving run as ONE shard_map program per batch
        (docs/sharded_ann.md)."""
        from raft_tpu.neighbors import ann_mnmg

        return ann_mnmg.shard_ivf_flat(self, comms)

    def tree_flatten(self):
        leaves = (self.centers, self.list_data, self.list_indices,
                  self.list_sizes, self.phys_sizes, self.chunk_table)
        return leaves, (self.metric, self.adaptive_centers)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, metric=aux[0], adaptive_centers=aux[1])


def _normalize_rows(x):
    n = jnp.maximum(jnp.linalg.norm(x, axis=-1, keepdims=True), 1e-30)
    return x / n


def _compute_dtype(x):
    return jnp.float32 if x.dtype in (jnp.int8, jnp.uint8) else x.dtype


def _assign_lists(q, centers, metric: DistanceType) -> jnp.ndarray:
    """Assign vectors to lists consistently with how search ranks probes:
    max-dot for InnerProduct/Cosine (q pre-normalized for cosine), else
    min-L2 via the fused path."""
    if metric in (DistanceType.InnerProduct, DistanceType.CosineExpanded):
        c = _normalize_rows(centers) if metric == DistanceType.CosineExpanded \
            else centers
        return jnp.argmax(q @ c.T.astype(q.dtype), axis=1).astype(jnp.int32)
    return min_cluster_and_distance(q, centers).key.astype(jnp.int32)


def _train_centers(params: IndexParams, x, n_lists: int):
    """Coarse-quantizer training (shared VERBATIM by :func:`build` and
    :func:`build_sharded` so both paths train the bit-identical model)."""
    xf = x.astype(_compute_dtype(x))
    train = subsample_trainset(xf, params.kmeans_trainset_fraction, n_lists,
                               params.seed)
    cx = _normalize_rows(train) if params.metric == DistanceType.CosineExpanded else train
    centers = build_hierarchical(RngState(params.seed), cx, n_lists,
                                 params.kmeans_n_iters)
    return centers, xf


@traced("raft_tpu.neighbors.ivf_flat.build")
@auto_sync_handle
def build(params: IndexParams, dataset, ids=None, *,
          tiled: Optional[bool] = None, handle=None) -> Index:
    """Train + populate an IVF-Flat index (reference ``ivf_flat::build``,
    neighbors/ivf_flat.cuh:64 → ivf_flat_build.cuh:228).

    The populate is device-resident by default (docs/index_build.md): the
    assignment already runs at O(tile) transients through the fused-L2-NN
    scan, and the pack routes through the cached device-side slot/scatter
    programs (``_build.pack_device``) — no per-row host work.
    ``tiled=False`` / ``RAFT_TPU_TILED_BUILD=0`` restores the pre-PR
    host-bookkept pack (bit-identical results, the A/B baseline)."""
    x = jnp.asarray(dataset)
    expects(x.ndim == 2, "dataset must be (n, dim)")
    expects(params.metric in _SUPPORTED,
            f"ivf_flat: unsupported metric {params.metric}")
    n = x.shape[0]
    n_lists = min(params.n_lists, n)
    centers, _ = _train_centers(params, x, n_lists)
    index = Index(centers=centers,
                  list_data=jnp.zeros((1, 8, x.shape[1]), x.dtype),
                  list_indices=jnp.full((1, 8), -1, jnp.int32),
                  list_sizes=jnp.zeros((n_lists,), jnp.int32),
                  phys_sizes=jnp.zeros((1,), jnp.int32),
                  chunk_table=jnp.zeros((n_lists, 1), jnp.int32),
                  metric=params.metric,
                  adaptive_centers=params.adaptive_centers)
    if params.add_data_on_build:
        index = extend(index, x, ids, tiled=tiled)
    else:
        expects(ids is None,
                "ids were passed but add_data_on_build=False stores no "
                "rows — pass them to extend() instead")
    return index


@traced("raft_tpu.neighbors.ivf_flat.build_sharded")
def build_sharded(params: IndexParams, dataset, comms, ids=None, *,
                  tile_rows: Optional[int] = None):
    """Train once (replicated) + populate DIRECT-TO-SHARD: each device of
    *comms*' mesh packs ONLY its round-robin list shard's rows, producing
    a :class:`raft_tpu.neighbors.ann_mnmg.ShardedIndex` bit-identical to
    ``build(params, dataset).shard(comms)`` without the full padded index
    ever materializing on one device (docs/index_build.md §sharded) —
    for IVF-Flat the padded list blocks ARE the dataset-sized state, so
    this is the capacity win sharding exists for.  *tile_rows* bounds the
    per-step row transfer to the shards (``RAFT_TPU_BUILD_TILE``)."""
    from raft_tpu.neighbors import ann_mnmg

    comms = ann_mnmg._full_axis_comms(comms)
    x = jnp.asarray(dataset)
    expects(x.ndim == 2, "dataset must be (n, dim)")
    expects(params.metric in _SUPPORTED,
            f"ivf_flat: unsupported metric {params.metric}")
    expects(params.add_data_on_build,
            "build_sharded populates by construction — use "
            "build(add_data_on_build=False) + extend + shard() for "
            "deferred ingest")
    n = x.shape[0]
    n_lists = min(params.n_lists, n)
    centers, xf = _train_centers(params, x, n_lists)
    q = _normalize_rows(xf) if params.metric == DistanceType.CosineExpanded else xf
    labels = _assign_lists(q, centers, params.metric)
    if ids is None:
        ids = jnp.arange(n, dtype=jnp.int32)
    else:
        ids = jnp.asarray(ids, jnp.int32)

    lay = chunk_layout(device_counts(labels, n_lists))
    key = ("ivf_flat", n_lists, str(x.dtype))
    (stacked_pay, stacked_idx, stacked_phys, stacked_tables, _,
     probe_extra, _) = _build.populate_sharded(
        comms, x, labels, ids, lay, tile_fn=None, n_payloads=1, key=key,
        tile_rows=tile_rows)
    stacked = (stacked_pay[0], stacked_idx, stacked_phys, stacked_tables)
    replicated = (ann_mnmg._replicate(comms, centers),)
    aux = ann_mnmg._ivf_flat_aux(comms.get_size(), int(x.shape[1]),
                                 int(params.metric), n_lists, probe_extra)
    return ann_mnmg.ShardedIndex("ivf_flat", comms, replicated, stacked,
                                 aux)


def extend(index: Index, new_vectors, new_ids=None, *,
           tiled: Optional[bool] = None, in_place: bool = False) -> Index:
    """Add vectors to an existing index (reference ``ivf_flat::extend``,
    ivf_flat_build.cuh:108).  Functional: returns a new Index.  INCREMENTAL
    (r5): new rows append into each list's free tail slots, only
    overflowing lists grow a chunk — the reference appends to the affected
    lists the same way; the r4 path unpacked and re-sorted the whole index
    per extend.  DEVICE-RESIDENT (r7, default): the append runs through
    the cached slot/scatter programs (``_build.extend_device``), and
    ``in_place=True`` DONATES the old blocks when no list overflows —
    O(n_new) append, no O(index) copy, the input index is consumed.
    ``tiled=False`` / ``RAFT_TPU_TILED_BUILD=0`` restores the pre-PR path
    (bit-identical results).

    .. note::
       Caller-supplied *new_ids* are validated for uniqueness — within
       the batch AND against every id already live in the index — and a
       collision raises ``ValueError`` loudly: a duplicate id would
       silently yield two live rows answering for one key.  Replace
       semantics (tombstone the old row, append the new) live in
       :meth:`raft_tpu.neighbors.mutable.MutableIndex.upsert`.
    """
    xa = jnp.asarray(new_vectors)
    expects(xa.ndim == 2 and xa.shape[1] == index.dim, "dim mismatch")
    n_new = xa.shape[0]
    base = index.size
    if new_ids is None:
        new_ids = jnp.arange(base, base + n_new, dtype=jnp.int32)
    else:
        new_ids = jnp.asarray(new_ids, jnp.int32)
        expects(new_ids.shape == (n_new,), "ids must be (n_new,)")
        validate_new_ids(new_ids, index.list_indices, index.phys_sizes)

    xf = xa.astype(_compute_dtype(xa))
    q = _normalize_rows(xf) if index.metric == DistanceType.CosineExpanded else xf
    labels = _assign_lists(q, index.centers, index.metric)

    use_tiled = _build.resolve_tiled(tiled)
    if base:
        ext = _build.extend_device if use_tiled else extend_lists_chunked
        kw = {"in_place": in_place} if use_tiled else {}
        (data, idx, phys_sizes, sizes, chunk_table, _, _) = \
            ext(index.list_data, index.list_indices,
                index.list_sizes, index.chunk_table,
                xa, new_ids, labels, **kw)
    else:
        pack = _build.pack_device if use_tiled else pack_lists_chunked
        data, idx, phys_sizes, sizes, chunk_table, _, _ = pack(
            xa, new_ids, labels, index.n_lists)
    centers = index.centers
    if index.adaptive_centers:
        # drift centers toward the member mean (reference ivf_flat_build.cuh
        # extend with adaptive_centers=true updates centers from accumulated
        # sums): new = (old·n_old + Σ new members) / n_total — incremental,
        # no pass over the stored rows
        from raft_tpu.linalg.reduce import reduce_rows_by_key

        sums = reduce_rows_by_key(xa.astype(centers.dtype), labels,
                                  index.n_lists)
        n_old = index.list_sizes.astype(centers.dtype)[:, None]
        n_tot = jnp.maximum(sizes.astype(centers.dtype), 1)[:, None]
        centers = jnp.where(sizes[:, None] > 0,
                            (centers * n_old + sums) / n_tot, centers)
    return Index(centers=centers, list_data=data, list_indices=idx,
                 list_sizes=sizes, phys_sizes=phys_sizes,
                 chunk_table=chunk_table, metric=index.metric,
                 adaptive_centers=index.adaptive_centers)


def _owner_of(chunk_table, n_phys_rows: int):
    """Inverse of the chunk table: physical row → logical list (dummy and
    unreferenced rows map to 0; their sizes are 0 so they never score)."""
    n_lists, max_chunks = chunk_table.shape
    owners = jnp.repeat(jnp.arange(n_lists, dtype=jnp.int32), max_chunks)
    return jnp.zeros((n_phys_rows,), jnp.int32).at[
        chunk_table.reshape(-1)].set(owners, mode="drop")


def _search_batch_impl(queries, index_leaves, metric_val: int, k: int,
                       n_probes: int, sqrt: bool, probe_extra: int = -1,
                       engine: str = "xla", tombstones=None):
    """ONE program for a query batch: coarse ranking → top-n_probes →
    probe-list scan → top-k (reference ivf_flat_search.cuh:1057 pipeline).

    ``probe_extra`` (static): continuation-chunk budget override for
    ``expand_probes`` (−1 derives it from the table shape).  Shard-local
    index blocks (``ann_mnmg``) must pass their true per-shard worst case
    — the local table shape undercounts it (see expand_probes).

    ``engine`` (static, resolved by the caller via
    ``raft_tpu.kernels.resolve_engine``): the select-k engine for the
    coarse top-n_probes and the per-tile probe-scan top-k — "xla"
    (``lax.top_k``) or "pallas" (blockwise bitonic kernel, BIT-IDENTICAL
    output, so the whole search is bit-identical across engines).

    One `lax.scan` step per (probe rank, chunk): logical probes expand
    through the chunk table into physical rows, each step gathers one
    (nq, cap, dim) tile and contracts it against the queries — the TPU
    analogue of the reference's per-(query, probe) interleaved scan blocks
    (ivf_flat_search.cuh:658-782), with the running top-k merge playing
    the role of the in-kernel warp-sort queues.

    Lives behind BOTH a jit wrapper (traced / off-device callers) and an
    ``aot()`` cache (eager serving dispatch — the whole per-batch search is
    one cached executable, so ``serve.ServeEngine`` can pin its signatures
    at warmup and never retrace; previously the coarse GEMM + select and
    the probe scan were separate dispatches).
    """
    (centers, list_data, list_indices, phys_sizes, chunk_table) = index_leaves
    metric = DistanceType(metric_val)

    # coarse ranking against centroids (reference :1120 linalg::gemm)
    cd = _coarse_distances(queries, centers, metric)
    _, probe_sel = select_k(cd, n_probes, select_min=True, engine=engine)
    probe_ids = probe_sel.astype(jnp.int32)
    return _probe_search_impl(queries, probe_ids, index_leaves[1:],
                              metric_val, k, sqrt, probe_extra, engine,
                              tombstones)


def _probe_search_impl(queries, probe_ids, scan_leaves, metric_val: int,
                       k: int, sqrt: bool, probe_extra: int = -1,
                       engine: str = "xla", tombstones=None):
    """The probe-scoring stage of :func:`_search_batch_impl` with the probe
    set supplied EXPLICITLY: ``scan_leaves`` is the index leaves minus the
    centroids — (list_data, list_indices, phys_sizes, chunk_table).

    Split out so the tiered residency layer (``neighbors.tiering``) can run
    the IDENTICAL scoring program over a doctored physical block (the
    device-resident hot rows, or one staged cold tile) while computing the
    probe selection once per batch: per-candidate distances here are a pure
    function of (queries, gathered rows), so any residency split that
    preserves row content scores bit-identically to the fully-resident
    scan."""
    (list_data, list_indices, phys_sizes, chunk_table) = scan_leaves
    is_ip = metric_val == int(DistanceType.InnerProduct)
    is_cos = metric_val == int(DistanceType.CosineExpanded)

    # Half-precision datasets (bf16/f16 — TPU-native) keep half-width MXU
    # inputs but accumulate scores in f32 (same contract as
    # distance.pairwise._mxu_dot): on near-tie candidate sets, bf16 score
    # rounding measurably costs recall (~0.04 at 2k×32 uniform).
    acc_t = accum_dtype(queries.dtype)
    # hoisted invariant statistic: query sq-norms once per batch — a scan
    # constant, not recomputed by every probe step's score_tile; ONE
    # norm/upcast policy (_row_norms accumulates half inputs in f32,
    # matching acc_t)
    q_sq = _row_norms(queries)[:, None].astype(acc_t)

    def score_tile(rows):
        data = list_data[rows].astype(queries.dtype)        # (nq, cap, dim)
        # the tile-SCORING GEMM against the gathered rows — O(tile)
        # work by construction, not per-batch LUT recompute (the
        # regression class of the probe-scan-closure rule)
        # exempt(probe-scan-closure): O(tile) scoring over gathered rows
        dots = jnp.einsum("qd,qcd->qc", queries, data,
                          preferred_element_type=acc_t)
        if is_ip:
            return dots
        if is_cos:
            # queries are pre-normalized; normalize stored vectors here
            xn = jnp.sqrt(jnp.maximum(
                jnp.sum(data.astype(acc_t) ** 2, axis=-1), 1e-30))
            return 1.0 - dots / xn
        xn = jnp.sum(data.astype(acc_t) ** 2, axis=-1)
        return q_sq + xn - 2.0 * dots

    phys_probes = expand_probes(probe_ids, chunk_table, list_data.shape[0],
                                extra=None if probe_extra < 0 else probe_extra)
    best_d, best_i = scan_probe_lists(phys_probes, score_tile, list_indices,
                                      phys_sizes, k, select_min=not is_ip,
                                      dtype=acc_t, engine=engine,
                                      tombstones=tombstones)
    if sqrt:
        best_d = jnp.sqrt(jnp.maximum(best_d, 0))
    return best_d, best_i


# Eager searches dispatch the AOT executable cache (reference precompiled
# ivf-flat kernel instantiations, SURVEY.md §2.14); jit kept for traced
# callers and inputs off the default device — the ivf_pq._search_batch
# pattern, now covering the WHOLE batch program (coarse + select + scan).
_SEARCH_STATICS = (2, 3, 4, 5, 6, 7)
_search_batch = functools.partial(jax.jit, static_argnums=_SEARCH_STATICS)(
    _search_batch_impl)
_search_batch_aot = aot(_search_batch_impl, static_argnums=_SEARCH_STATICS)

# Explicit-probe scoring stage (probe_ids is arg 1, so statics shift by
# one vs _SEARCH_STATICS minus the n_probes slot) — the tiered hot/cold
# phase programs dispatch this cache (neighbors.tiering).
_PROBE_SEARCH_STATICS = (3, 4, 5, 6, 7)
_probe_search = functools.partial(
    jax.jit, static_argnums=_PROBE_SEARCH_STATICS)(_probe_search_impl)
_probe_search_aot = aot(_probe_search_impl,
                        static_argnums=_PROBE_SEARCH_STATICS)


@hlo_program(
    "ivf_flat.search_batch",
    collectives=0, collective_bytes=0,
    # per-probe-step transient: one gathered (nq, cap, dim) tile + its
    # score epilogue, NOT an (nq, n_rows) matrix — 64×cap×32 f32 with
    # select scratch stays well under this at the audit shape
    transient_bytes=4 << 20,
    notes="the whole per-batch ivf_flat search as ONE program (coarse "
          "GEMM + top-n_probes + probe scan) — the ServeEngine backend")
def _audit_search_batch():
    # build a REAL tiny index so leaf dtypes/layout track the shipped
    # build path; audit-time only (the registry builder is lazy)
    import numpy as np

    x = np.random.default_rng(0).standard_normal((2048, 32)
                                                 ).astype(np.float32)
    idx = build(IndexParams(n_lists=16), x)
    leaves = (idx.centers, idx.list_data, idx.list_indices,
              idx.phys_sizes, idx.chunk_table)
    q = jax.ShapeDtypeStruct((64, 32), jnp.float32)
    return dict(fn=_search_batch_impl,
                args=(q, leaves, int(DistanceType.L2SqrtExpanded), 8, 4,
                      True, -1, "xla"),
                static_argnums=_SEARCH_STATICS)


@traced("raft_tpu.neighbors.ivf_flat.search")
@auto_sync_handle
def search(params: SearchParams, index: Index, queries, k: int,
           *, batch_size_query: int = 1024, handle=None
           ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Search the index (reference ``ivf_flat::search``,
    neighbors/ivf_flat.cuh:325 → ivf_flat_search.cuh:1057):
    coarse GEMM → top-n_probes lists → masked list scans → final top-k.

    Returns (distances [nq, k], indices [nq, k]).
    """
    q = jnp.asarray(queries)
    expects(q.ndim == 2 and q.shape[1] == index.dim, "query dim mismatch")
    n_probes = min(params.n_probes, index.n_lists)
    expects(k >= 1, "k must be >= 1")
    qf = q.astype(_compute_dtype(q))
    if qf.shape[0] == 0:
        # distance dtype matches the non-empty path: f32 for half queries
        return empty_result(0, int(k), accum_dtype(qf.dtype))
    if index.metric == DistanceType.CosineExpanded:
        qf = _normalize_rows(qf)
    sqrt = index.metric == DistanceType.L2SqrtExpanded
    leaves = (index.centers, index.list_data, index.list_indices,
              index.phys_sizes, index.chunk_table)
    # select-k engine: env default resolved HERE, outside the jit/aot
    # caches, and threaded as a static (kernels.engine policy)
    from raft_tpu.kernels.engine import resolve_engine

    engine = resolve_engine("select_k", dtype=qf.dtype)
    out_d, out_i = [], []
    for q0 in range(0, qf.shape[0], batch_size_query):
        q1 = min(q0 + batch_size_query, qf.shape[0])
        qb = qf[q0:q1]
        # Bucket the ragged tail batch (pad + slice, see ivf_pq.search):
        # varying query counts must not compile per distinct residue.
        n_valid = qb.shape[0]
        bucket = min(_bucket_dim(n_valid), batch_size_query)
        if bucket != n_valid:
            qb = jnp.pad(qb, ((0, bucket - n_valid), (0, 0)))
        batch_fn = (_search_batch_aot if aot_dispatchable(qb, leaves)
                    else _search_batch)
        d, i = batch_fn(qb, leaves, int(index.metric), int(k),
                        int(n_probes), sqrt, -1, engine)
        if n_valid != qb.shape[0]:
            d, i = d[:n_valid], i[:n_valid]
        out_d.append(d)
        out_i.append(i)
    d = out_d[0] if len(out_d) == 1 else jnp.concatenate(out_d, axis=0)
    i = out_i[0] if len(out_i) == 1 else jnp.concatenate(out_i, axis=0)
    return d, i


@jax.jit
def _coarse_l2(q, centers):
    # half inputs: f32 norms + f32-accumulated dot (probe selection
    # misranks near-tie centroids otherwise — same contract as the fine
    # scan's acc_t); f32 inputs keep the default-precision matmul.
    # ONE L2 epilogue implementation: distance.pairwise._l2_expanded.
    return _l2_expanded(q, centers, sqrt=False, precision=None)


def _coarse_distances(q, centers, metric: DistanceType):
    centers = centers.astype(q.dtype)
    if metric == DistanceType.CosineExpanded:
        centers = _normalize_rows(centers)
        return -_mxu_dot(q, centers, None)
    if metric == DistanceType.InnerProduct:
        return -_mxu_dot(q, centers, None)
    return _coarse_l2(q, centers)


def build_and_search(dataset, queries, k: int,
                     index_params: Optional[IndexParams] = None,
                     search_params: Optional[SearchParams] = None):
    """Convenience one-shot (used by tests/benchmarks)."""
    ip = index_params or IndexParams()
    sp = search_params or SearchParams()
    idx = build(ip, dataset)
    return search(sp, idx, queries, k)
