"""IVF-PQ approximate nearest-neighbor index.

Counterpart of reference ``neighbors/ivf_pq.cuh`` +
``spatial/knn/detail/ivf_pq_{build,search}.cuh`` (SURVEY.md §2.8):
two-level quantization ``y ≈ Q1(y) + Q2(y − Q1(y))`` — coarse k-means
centers + product-quantized residuals — with search-time per-query lookup
tables.

Parameter surface mirrors ``ivf_pq_types.hpp:30-120``: ``pq_bits`` 4–8,
``pq_dim`` (0 → heuristic), ``codebook_kind`` PER_SUBSPACE/PER_CLUSTER,
``force_random_rotation``; search: ``n_probes``, ``lut_dtype``
(f32/bf16/f16), ``internal_distance_dtype``.

TPU-first redesign:
- The reference stores codes in a bit-packed interleaved layout and scores
  them with 15 precompiled CUDA kernel variants holding the LUT in shared
  memory (ivf_pq_search.cuh:594-738).  Here codes live **bit-packed** in
  padded dense (n_lists, capacity, ⌈pq_dim·pq_bits/8⌉) uint8 blocks
  (reference packing contract ivf_pq_types.hpp:56-65 — a pq_bits=4 index
  costs half the bytes of pq_bits=8); search unpacks each gathered probe
  tile with VPU shift/mask ops.
- HOISTED ADC pipeline (default; docs/ivf_pq_adc.md): the classic ADC
  decomposition ``‖r − c‖² = ‖r‖² − 2·rot_q·c + 2·ctr_rot·c + ‖c‖²``
  splits the LUT into a list-side part that is constant at BUILD time
  (``Index.list_adc`` = ‖c‖² + 2·ctr_rot·c, (n_lists, pq_dim, 2^bits))
  and a query-side part computed ONCE per query batch (−2·rot_q·c, one
  einsum for the whole batch).  The combined per-(query, probe) LUT is
  quantized with a SINGLE per-(query, probe-set) affine and threaded
  through the probe scan as ``lax.scan`` xs, so the scan body is only
  bit-unpack + ``Σ_m LUT[q, m·2^bits + code[q, c, m]]`` — one flattened
  take_along_axis on CPU / one one-hot MXU contraction on TPU, instead of
  re-deriving the codebook einsums + norm epilogues + re-quantization per
  physical chunk tile.  ``RAFT_TPU_HOISTED_LUT=0`` (or
  ``SearchParams.hoisted_lut=False``) restores the pre-PR in-scan path.
- Codebook training is Lloyd k-means ``vmap``-ed over subspaces (or over
  clusters for PER_CLUSTER) — all codebooks train simultaneously on the
  MXU instead of the reference's sequential per-subspace loop, on a
  residual sample capped at ``IndexParams.pq_trainset_cap`` rows (the
  reference likewise trains on a trainset fraction, ivf_pq_build.cuh).
- TILED, device-resident populate (default; docs/index_build.md): the
  per-row pipeline (residual → encode → bit-pack, plus the standalone
  csum stage) runs as fused fixed-shape programs through the AOT cache —
  peak transients are O(tile), repeated builds/extends dispatch warm
  executables, packing is device-side, and ``build_sharded`` runs the
  same kernels as a shard_map program that packs each round-robin list
  shard directly on its own device (bit-identical to
  ``build().shard(comms)``).  This mirrors the reference's batched
  ``ivf_pq::build`` ingest (ivf_pq_build.cuh caps its batch sizes);
  ``RAFT_TPU_TILED_BUILD=0`` / ``build(..., tiled=False)`` restores the
  monolithic populate (bit-identical indexes, the A/B structure
  baseline).
- The random rotation is a QR-orthonormalized Gaussian (dim, rot_dim)
  matrix, applied as one GEMM (the reference multiplies by the same kind
  of matrix in ivf_pq_build).

Supported dataset dtypes mirror the reference's T ∈ {float, int8_t,
uint8_t} (neighbors/ivf_pq.cuh:62): integer datasets train/encode/search
in f32 (the reference likewise converts T→float on ingest), and the index
carries a ``dataset_dtype`` tag enforcing extend/search consistency.
"""

from __future__ import annotations

import dataclasses
import enum
import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.aot import _bucket_dim, aot, aot_dispatchable
from raft_tpu.core.error import expects
from raft_tpu.core.handle import auto_sync_handle
from raft_tpu.core.logger import traced
from raft_tpu import telemetry
from raft_tpu.cluster import build_hierarchical, min_cluster_and_distance
from raft_tpu.analysis.registry import hlo_program
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.distance.pairwise import _l2_expanded, _row_norms
from raft_tpu.matrix.select_k import select_k
from raft_tpu.neighbors import _build
from raft_tpu.neighbors._build import build_trace_counters
from raft_tpu.neighbors._common import (
    chunk_layout,
    device_counts,
    empty_result,
    expand_probes,
    extend_lists_chunked,
    pack_lists_chunked,
    scan_probe_lists,
    subsample_trainset,
    validate_new_ids,
)
from raft_tpu.random.rng import RngState

_SUPPORTED = (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
              DistanceType.InnerProduct)

_LUT_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
               "float16": jnp.float16, "float8_e4m3": jnp.float8_e4m3fn}
# fp8 e4m3 max finite is 448; quantize LUTs to a per-query [0, 440] range
# (reference lut_dtype CUDA_R_8U plays the same compressed-LUT role,
# ivf_pq_types.hpp:94-100).
_FP8_PEAK = 440.0

#: Trace-time counters (the ``Comms.collective_calls`` pattern): bumped
#: while the search program is being TRACED, so tests can assert where the
#: LUT gets built — ``in_scan_lut_builds`` increments once per trace of the
#: legacy per-tile recompute path, ``hoisted_lut_builds`` once per trace of
#: the per-batch hoisted build.  A hoisted-path trace bumping the in-scan
#: counter would mean codebook einsums crept back into the scan body.
#: Registry-backed (telemetry PR): same read surface, atomic increments,
#: exported as ``raft_tpu_ivf_pq_lut_trace{key}``.
lut_trace_counters: telemetry.LegacyCounterView = telemetry.legacy_counter(
    "raft_tpu_ivf_pq_lut_trace",
    "IVF-PQ LUT build sites observed at search-program trace time")


def hoisted_lut_enabled() -> bool:
    """``RAFT_TPU_HOISTED_LUT`` env gate (default ON).
    ``RAFT_TPU_HOISTED_LUT=0`` restores the pre-PR in-scan LUT recompute
    for A/B measurement, mirroring ``RAFT_TPU_FUSED_EM``."""
    return os.environ.get("RAFT_TPU_HOISTED_LUT", "1") != "0"


def _resolve_scan_engine(pq_dim: int, pq_bits: int,
                         engine: Optional[str] = None) -> str:
    """ONE resolution of the ivf_pq scan's kernel engine (kernels.engine
    policy; consumed by :func:`search`, the serve backend and the sharded
    searcher).  The single static knob enables BOTH Pallas kernels inside
    the scan program — the LUT-in-VMEM scorer and the blockwise select_k —
    so the env default is pallas when EITHER kind opts in; unsupported
    LUT widths keep the XLA lookup (``_scan_hoisted`` guards per kernel)."""
    from raft_tpu.kernels.engine import resolve_engine

    if engine is not None:
        return resolve_engine("pq_lut", engine=engine)
    if (resolve_engine("pq_lut") == "pallas"
            or resolve_engine("select_k") == "pallas"):
        return "pallas"
    return "xla"


class CodebookKind(enum.IntEnum):
    """Reference ``codebook_gen`` (ivf_pq_types.hpp:31)."""

    PER_SUBSPACE = 0
    PER_CLUSTER = 1


@dataclasses.dataclass
class IndexParams:
    """Reference ``ivf_pq::index_params`` (ivf_pq_types.hpp:36)."""

    n_lists: int = 1024
    metric: DistanceType = DistanceType.L2Expanded
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    pq_bits: int = 8
    pq_dim: int = 0          # 0 → heuristic (ivf_pq_build calc_pq_dim)
    codebook_kind: CodebookKind = CodebookKind.PER_SUBSPACE
    force_random_rotation: bool = False
    # Train the model on *dataset* but store no rows (reference
    # ``ann::index_params::add_data_on_build``, ann_common.h — rows are
    # then added by extend()); ivf_flat.IndexParams has the same knob.
    add_data_on_build: bool = True
    # "auto" (the default): "pca_balanced" whenever pq_dim | dim, else
    # "default".  "default" = identity, or random when forced /
    # rot_dim != dim.  "pca_balanced" = parametric OPQ-style rotation —
    # residual PCA basis with eigenvalue allocation balancing variance
    # products across the pq_dim subspaces (Ge et al. 2013).  BEYOND the
    # reference (it only has force_random_rotation): same search cost,
    # much higher recall on correlated data (measured on the low-rank
    # SIFT-like model at 10k×128 pq8 nprobes=50: 0.95 vs 0.78; at 64-dim
    # pq4: 0.78 vs 0.45 — hence the default).  Requires rot_dim == dim.
    rotation_kind: str = "auto"
    # Row cap on the residual sample the PQ codebooks train on (the
    # reference trains codebooks on its trainset fraction, not the whole
    # dataset — ivf_pq_build.cuh).  Datasets at or under the cap train on
    # EVERY row (bit-identical to the pre-cap behavior); above it, a
    # seeded uniform sample bounds the (n_train, rot_dim) training
    # residual matrix — the populate pipeline itself never materializes
    # dataset-sized residuals at all (tiled build, docs/index_build.md).
    pq_trainset_cap: int = 262144
    seed: int = 1234


@dataclasses.dataclass
class SearchParams:
    """Reference ``ivf_pq::search_params`` (ivf_pq_types.hpp:88)."""

    n_probes: int = 20
    # float32 | bfloat16 | float16 | float8_e4m3 (reference lut_dtype incl.
    # CUDA_R_8U, ivf_pq_types.hpp:94-100)
    lut_dtype: str = "float32"
    internal_distance_dtype: str = "float32"  # float32 | float16
    # None → RAFT_TPU_HOISTED_LUT env gate (default on).  False forces the
    # pre-PR in-scan LUT recompute (the A/B baseline).
    hoisted_lut: Optional[bool] = None
    # Exact re-rank ratio for TIERED serving (neighbors.tiering, the
    # reference refine() recipe): the ADC scan returns k·ratio candidates,
    # whose ORIGINAL vectors are gathered from the host tier and re-scored
    # with exact distance — the recall safety net for compressed list
    # storage (PR-3 triage: ADC ceiling 0.62 at this shape).  None/1
    # disables; honored by the tiered backend.
    refine_ratio: Optional[int] = None


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Index:
    """IVF-PQ index.

    ``centers``   (n_lists, dim) f32 coarse centroids (original space)
    ``rotation``  (dim, rot_dim) orthonormal transform
    ``codebooks`` PER_SUBSPACE: (pq_dim, 2^bits, ds); PER_CLUSTER:
                  (n_lists, 2^bits, ds) — ds = rot_dim // pq_dim
    Lists are CHUNKED (bounded padding on skewed cluster sizes; the last
    physical row is a reserved empty dummy — see
    ``_common.pack_lists_chunked``):

    ``list_codes``   (n_phys+1, cap, ⌈pq_dim·pq_bits/8⌉) uint8,
                     bit-packed (LSB-first bitstream of pq_bits codes)
    ``list_indices`` (n_phys+1, cap) int32, -1 padding
    ``phys_sizes``   (n_phys+1,) int32 live rows per physical chunk
    ``chunk_table``  (n_lists, max_chunks) int32 logical → physical rows
    ``owner``        (n_phys+1,) int32 logical list of each physical row
    ``list_sizes``   (n_lists,) int32 logical sizes
    ``list_adc``     (n_lists, pq_dim, 2^bits) f32 — BUILD-TIME list-side
                     ADC table ‖c‖² + 2·ctr_rot·c (codebook sq-norms folded
                     with the center-cross term; :func:`_build_list_adc`).
                     Constant per trained model.  Exact f32 regardless of
                     the search-time ``lut_dtype``.
    ``list_csum``    (n_phys+1, cap) f32 — the list-side table CONTRACTED
                     per stored candidate at encode time:
                     ``Σ_m list_adc[owner, m, code_m]`` (=‖decoded‖²
                     + 2·ctr_rot·decoded, :func:`_csum_for_codes`), packed
                     alongside ``list_codes``.  The lookup is linear in the
                     LUT, so the hoisted search adds this scalar instead of
                     gathering/combining per-(query, probe) list tables.
    """

    centers: jnp.ndarray
    rotation: jnp.ndarray
    codebooks: jnp.ndarray
    list_codes: jnp.ndarray
    list_indices: jnp.ndarray
    list_sizes: jnp.ndarray
    phys_sizes: jnp.ndarray
    chunk_table: jnp.ndarray
    owner: jnp.ndarray
    list_adc: jnp.ndarray
    list_csum: jnp.ndarray
    metric: DistanceType
    codebook_kind: CodebookKind
    pq_bits: int
    # Dataset dtype the index was built from — "float32" | "int8" | "uint8"
    # (reference ivf_pq::index is templated on T ∈ {float, int8_t, uint8_t},
    # neighbors/ivf_pq.cuh:62).  Codes/codebooks are dtype-independent (all
    # training happens in f32, as the reference converts T→float on ingest);
    # the tag enforces that extend()/search() inputs stay consistent.
    dataset_dtype: str = "float32"

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def rot_dim(self) -> int:
        return self.rotation.shape[1]

    @property
    def pq_dim(self) -> int:
        if self.codebook_kind == CodebookKind.PER_CLUSTER:
            return self.rot_dim // self.codebooks.shape[2]
        return self.codebooks.shape[0]

    @property
    def pq_len(self) -> int:
        return self.codebooks.shape[2]

    @property
    def capacity(self) -> int:
        return self.list_codes.shape[1]

    @property
    def size(self) -> int:
        return int(jnp.sum(self.list_sizes))

    def shard(self, comms):
        """Partition this index's lists round-robin across *comms*' devices
        for multi-device search — returns a
        :class:`raft_tpu.neighbors.ann_mnmg.ShardedIndex` whose
        ``search``/serving run as ONE shard_map program per batch
        (docs/sharded_ann.md)."""
        from raft_tpu.neighbors import ann_mnmg

        return ann_mnmg.shard_ivf_pq(self, comms)

    def tree_flatten(self):
        leaves = (self.centers, self.rotation, self.codebooks,
                  self.list_codes, self.list_indices, self.list_sizes,
                  self.phys_sizes, self.chunk_table, self.owner,
                  self.list_adc, self.list_csum)
        return leaves, (self.metric, self.codebook_kind, self.pq_bits,
                        self.dataset_dtype)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, metric=aux[0], codebook_kind=aux[1],
                   pq_bits=aux[2], dataset_dtype=aux[3])


def _ingest_dataset(data) -> Tuple[jnp.ndarray, str]:
    """Convert a dataset/query matrix to f32 compute form, returning
    (f32 array, dtype tag).  int8/uint8 are cast directly (same affine
    handling as ivf_flat: nearest-neighbor ranking is scale-invariant, so
    no kDivisor rescale is needed); everything else computes in f32, as the
    reference converts T→float on ingest (ivf_pq_build.cuh trainset copy)."""
    x = jnp.asarray(data)
    if x.dtype in (jnp.int8, jnp.uint8):
        return x.astype(jnp.float32), str(x.dtype)
    expects(jnp.issubdtype(x.dtype, jnp.floating),
            f"ivf_pq: unsupported dataset dtype {x.dtype}; the reference "
            "supports T in {float, int8_t, uint8_t} "
            "(neighbors/ivf_pq.cuh:62)")
    return x.astype(jnp.float32), "float32"


def _code_bytes(pq_dim: int, pq_bits: int) -> int:
    return -(-pq_dim * pq_bits // 8)


def _pack_codes(codes, pq_bits: int) -> jnp.ndarray:
    """Bit-pack (n, pq_dim) sub-quantizer indices into (n, ⌈pq_dim·bits/8⌉)
    uint8 — LSB-first bitstream (reference packed-codes contract,
    ivf_pq_types.hpp:56-65).  pq_bits=8 is the identity."""
    if pq_bits == 8:
        return codes.astype(jnp.uint8)
    n, pq_dim = codes.shape
    total = pq_dim * pq_bits
    nbytes = _code_bytes(pq_dim, pq_bits)
    bits = (codes.astype(jnp.int32)[:, :, None]
            >> jnp.arange(pq_bits)) & 1                 # (n, pq_dim, bits)
    bits = bits.reshape(n, total)
    if nbytes * 8 != total:
        bits = jnp.pad(bits, ((0, 0), (0, nbytes * 8 - total)))
    byte = jnp.sum(bits.reshape(n, nbytes, 8) << jnp.arange(8), axis=-1)
    return byte.astype(jnp.uint8)


def _unpack_codes(packed, pq_dim: int, pq_bits: int) -> jnp.ndarray:
    """Inverse of :func:`_pack_codes`: (..., nbytes) uint8 → (..., pq_dim)
    int32.  VPU shift/mask ops only — runs per gathered probe tile at
    search time so the unpacked form never exists index-wide."""
    if pq_bits == 8:
        return packed.astype(jnp.int32)
    lead = packed.shape[:-1]
    bits = (packed.astype(jnp.int32)[..., :, None] >> jnp.arange(8)) & 1
    bits = bits.reshape(lead + (packed.shape[-1] * 8,))[..., :pq_dim * pq_bits]
    bits = bits.reshape(lead + (pq_dim, pq_bits))
    return jnp.sum(bits << jnp.arange(pq_bits), axis=-1)


def _calc_pq_dim(dim: int) -> int:
    """Heuristic for pq_dim when 0 (reference ivf_pq_build ``calc_pq_dim``:
    roughly dim/2 rounded to a power-of-two-friendly multiple of 8)."""
    d = max(1, dim // 2)
    if d >= 8:
        d = -(-d // 8) * 8
    return d


def _make_rotation(key, dim: int, rot_dim: int, random: bool) -> jnp.ndarray:
    if not random and dim == rot_dim:
        return jnp.eye(dim, dtype=jnp.float32)
    g = jax.random.normal(key, (max(dim, rot_dim), max(dim, rot_dim)),
                          jnp.float32)
    q, _ = jnp.linalg.qr(g)
    return q[:dim, :rot_dim]


def _pca_balanced_rotation(resid_sample: np.ndarray, pq_dim: int
                           ) -> np.ndarray:
    """Parametric OPQ rotation: eigen-basis of the residual covariance,
    with eigen-directions allocated to the pq_dim subspaces so the
    variance PRODUCTS balance (greedy eigenvalue allocation, Ge et al.
    2013 §4's parametric solution for gaussian data).  Orthogonal
    (dim, dim); columns grouped so subspace m takes output dims
    [m·ds, (m+1)·ds)."""
    dim = resid_sample.shape[1]
    ds = dim // pq_dim
    # exempt(dtype-drift): host-side numpy PCA training; np.cov is f64
    cov = np.cov(resid_sample.T).astype(np.float64)
    w, v = np.linalg.eigh(cov)                       # ascending
    w, v = w[::-1], v[:, ::-1]                       # descending variance
    buckets: list = [[] for _ in range(pq_dim)]
    logvar = np.zeros(pq_dim)
    for i in range(dim):
        open_b = [b for b in range(pq_dim) if len(buckets[b]) < ds]
        b = min(open_b, key=lambda bb: logvar[bb])
        buckets[b].append(i)
        logvar[b] += np.log(max(float(w[i]), 1e-12))
    order = [i for b in buckets for i in b]
    return np.ascontiguousarray(v[:, order], dtype=np.float32)


def _lloyd_kmeans(key, data, k: int, iters: int):
    """Plain Lloyd k-means for codebook training (vmappable).

    data: (n, d) → centers (k, d).  The reference trains PQ codebooks with
    the same balanced-kmeans machinery; plain Lloyd on residual subvectors
    converges equally well here and vmaps cleanly over codebooks.  E/M ride
    the shared cluster primitives: the M-step goes through
    ``kmeans.update_centroids`` → ``_weighted_cluster_sums``, which picks
    the MXU one-hot engine on accelerators (~5× over the raw segment-sum
    this previously lowered to — see that docstring) and the scatter on
    CPU; the E-step shares the hoisted-epilogue ``_l2_expanded``.
    """
    from raft_tpu.cluster.kmeans import update_centroids
    from raft_tpu.distance.pairwise import _l2_expanded

    n = data.shape[0]
    sel = jax.random.choice(key, n, (k,), replace=n < k)
    centers = data[sel]

    def step(centers, _):
        d = _l2_expanded(data, centers, sqrt=False, precision="high")
        labels = jnp.argmin(d, axis=1).astype(jnp.int32)
        new, _ = update_centroids(data, labels, k, old_centroids=centers)
        return new, None

    centers, _ = jax.lax.scan(step, centers, None, length=iters)
    return centers


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _train_codebooks_subspace(key, residuals, pq_dim: int, k: int,
                              iters: int):
    """PER_SUBSPACE: one codebook per subspace (pq_dim, k, ds)."""
    n, rot_dim = residuals.shape
    ds = rot_dim // pq_dim
    sub = residuals.reshape(n, pq_dim, ds).swapaxes(0, 1)  # (pq_dim, n, ds)
    keys = jax.random.split(key, pq_dim)
    return jax.vmap(lambda kk, d: _lloyd_kmeans(kk, d, k, iters))(keys, sub)


def _cluster_sample_take(counts: np.ndarray, cap: int,
                         rng_fill: np.random.Generator) -> np.ndarray:
    """Per-(cluster, slot) pool position BEFORE the modulo-pool wrap.

    Slot j < count keeps ``j`` — the j-th entry of the cluster's permuted
    segment, so EVERY pool member enters the training sample exactly once
    (full coverage, sampling without replacement; pools >= cap are
    entirely this case, bit-identical to the r5 behavior).  Only the
    EXCESS slots of sub-cap pools (j >= count) fill from the INDEPENDENT
    ``rng_fill`` stream (r7): the r5 code tiled the permutation
    cyclically there, so a tiny cluster's sample over-represented the
    same few subvectors in a fixed deterministic pattern."""
    n_lists = counts.shape[0]
    j = np.arange(cap)
    take = np.broadcast_to(j[None, :], (n_lists, cap)).copy()
    excess = j[None, :] >= counts[:, None]              # sub-cap fill slots
    if excess.any():
        take[excess] = rng_fill.integers(0, 1 << 62,
                                         size=int(excess.sum()))
    return take


def _train_codebooks_cluster_host(key, residuals_np, labels_np,
                                  n_lists: int, pq_dim: int, k: int,
                                  iters: int):
    """PER_CLUSTER training driven from host: groups are ragged, so build
    fixed-size per-cluster sample matrices host-side, then one vmapped
    Lloyd over clusters on device.

    The sample assembly is ONE segment-shuffle + gather (r5): subvectors
    are randomly permuted within their cluster segment via a single
    lexsort, and each cluster takes its first ``cap`` permuted entries —
    sampling without replacement for pools >= cap.  Sub-cap pools draw
    their cap indices modulo the pool from an INDEPENDENT random stream
    (r7): the r5/r6 code tiled one permutation cyclically
    (``arange(cap) % count``), so a tiny cluster's sample was the same few
    subvectors repeated in a deterministic pattern — the fill draw is now
    random per (cluster, slot), seeded from the build key (seed-stable).
    Pools >= cap are bit-identical to the r5 behavior.  The r4 version
    looped ``rng.choice`` over n_lists clusters host-side — O(n_lists)
    Python iterations, measurable at 8k lists.
    """
    n, rot_dim = residuals_np.shape
    ds = rot_dim // pq_dim
    cap = max(k * 4, 256)
    seed0 = int(jax.random.randint(key, (), 0, 2**31 - 1))
    rng = np.random.default_rng(seed0)
    # independent stream for the sub-cap fill draws — offset-seeded rather
    # than drawn from ``rng`` so the permutation stream (and with it every
    # pool >= cap) stays bit-identical to the r5 behavior
    rng_fill = np.random.default_rng(seed0 + 0x9E3779B9)
    # every row contributes its pq_dim subvectors to its cluster's pool
    sub = residuals_np.reshape(n * pq_dim, ds)
    lab = np.repeat(labels_np, pq_dim)
    shuf = np.lexsort((rng.random(lab.shape[0]), lab))
    counts = np.bincount(lab, minlength=n_lists).astype(np.int64)
    starts = np.zeros(n_lists + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    take = _cluster_sample_take(counts, cap, rng_fill)
    gather = starts[:n_lists, None] + take % np.maximum(counts, 1)[:, None]
    # compose the index chains (shuf ∘ gather) — materializing sub[shuf]
    # first would copy the whole (n·pq_dim, ds) pool to read n_lists·cap rows
    batches = sub[shuf[np.minimum(gather, max(lab.shape[0] - 1, 0))]
                  ].astype(np.float32)
    batches[counts == 0] = 0.0
    keys = jax.random.split(key, n_lists)
    return jax.jit(jax.vmap(
        lambda kk, d: _lloyd_kmeans(kk, d, k, iters)))(keys,
                                                       jnp.asarray(batches))


@functools.partial(jax.jit, static_argnums=(3,))
def _encode(residuals, codebooks, labels, per_cluster: bool):
    """PQ-encode rotated residuals → (n, pq_dim) uint8.

    The cross term is a broadcast multiply-reduce over the subspace dim,
    NOT a batched dot (r7): PQ subvectors are tiny (ds = rot_dim/pq_dim,
    typically 2–16), so the ``nmd,mkd->nmk`` einsum lowers to rank-ds
    batched GEMMs with no operand reuse — on XLA:CPU that materializes the
    (n, pq_dim, 2^bits) tensor at DRAM bandwidth and measures ~3× slower
    than the elementwise form, which fuses straight into the argmin so the
    distance tensor never hits memory (bench.py ``ivf_build``; the tiled
    build's O(tile) transient bound leans on this fusion).  EVERY shipped
    populate path — tiled, monolithic (``tiled=False``) and sharded —
    shares THIS one kernel, so tiled-vs-monolithic and sharded-vs-local
    bit-identity hold by construction: the two lowerings differ in FMA
    rounding of the ds-term accumulation, and degenerate sub-cap
    PER_CLUSTER codebooks contain exact-duplicate codewords whose argmin
    tie-break genuinely flips between lowerings (observed), so mixing
    lowerings across pipelines is NOT sound.  The pre-PR einsum form
    survives only as :func:`_encode_legacy`, the frozen baseline the
    ``ivf_build`` bench A/B measures against."""
    n, rot_dim = residuals.shape
    if per_cluster:
        ds = codebooks.shape[2]
        pq_dim = rot_dim // ds
        sub = residuals.reshape(n, pq_dim, ds)
        cb = codebooks[labels]                          # (n, k, ds)
        d = (jnp.sum(sub ** 2, -1)[:, :, None]
             + jnp.sum(cb ** 2, -1)[:, None, :]
             - 2.0 * jnp.sum(sub[:, :, None, :] * cb[:, None, :, :], -1))
        return jnp.argmin(d, axis=-1).astype(jnp.uint8)
    pq_dim, k, ds = codebooks.shape
    sub = residuals.reshape(n, pq_dim, ds)
    d = (jnp.sum(sub ** 2, -1)[:, :, None]
         + jnp.sum(codebooks ** 2, -1)[None, :, :]
         - 2.0 * jnp.sum(sub[:, :, None, :]
                         * codebooks[None, :, :, :], -1))
    return jnp.argmin(d, axis=-1).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnums=(3,))
def _encode_legacy(residuals, codebooks, labels, per_cluster: bool):
    """The pre-r7 einsum-lowered encode, frozen VERBATIM as the
    ``bench.py ivf_build`` A/B baseline kernel (see :func:`_encode` for
    why the default moved off the batched dot, and why no SHIPPED populate
    path may use this: exact-duplicate codewords tie-break differently
    across lowerings, so a mixed-lowering index pair is not
    bit-comparable)."""
    n, rot_dim = residuals.shape
    if per_cluster:
        ds = codebooks.shape[2]
        pq_dim = rot_dim // ds
        sub = residuals.reshape(n, pq_dim, ds)
        cb = codebooks[labels]                          # (n, k, ds)
        d = (jnp.sum(sub ** 2, -1)[:, :, None]
             + jnp.sum(cb ** 2, -1)[:, None, :]
             - 2.0 * jnp.einsum("nmd,nkd->nmk", sub, cb))
        return jnp.argmin(d, axis=-1).astype(jnp.uint8)
    pq_dim, k, ds = codebooks.shape
    sub = residuals.reshape(n, pq_dim, ds)
    d = (jnp.sum(sub ** 2, -1)[:, :, None]
         + jnp.sum(codebooks ** 2, -1)[None, :, :]
         - 2.0 * jnp.einsum("nmd,mkd->nmk", sub, codebooks))
    return jnp.argmin(d, axis=-1).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnums=(3,))
def _build_list_adc(centers, rotation, codebooks, per_cluster: bool):
    """BUILD-TIME list-side ADC table (n_lists, pq_dim, 2^bits) f32:

        list_adc[l, m, k] = ‖cb‖² + 2·ctr_rot[l, m]·cb

    where ``ctr_rot[l, m]`` is subspace m of the rotated coarse center and
    ``cb`` is codebook entry k of subspace m (PER_SUBSPACE) / of list l's
    codebook (PER_CLUSTER — the per-list gather folds into the same
    (n_lists, pq_dim, 2^bits) layout).  These are the two query-independent
    terms of the ADC decomposition ``‖r − c‖² = ‖r‖² − 2·rot_q·c
    + 2·ctr_rot·c + ‖c‖²``; computed exactly in f32 once per trained model
    instead of re-derived per probe tile at search time."""
    rot_centers = centers @ rotation                     # (L, rot_dim)
    if per_cluster:
        ds = codebooks.shape[2]
        pq_dim = rot_centers.shape[1] // ds
        ctr = rot_centers.reshape(-1, pq_dim, ds)
        cb_sq = jnp.sum(codebooks ** 2, -1)              # (L, kcb)
        cross = jnp.einsum("lmd,lkd->lmk", ctr, codebooks)
        return cb_sq[:, None, :] + 2.0 * cross
    pq_dim, _, ds = codebooks.shape
    ctr = rot_centers.reshape(-1, pq_dim, ds)
    cb_sq = jnp.sum(codebooks ** 2, -1)                  # (pq_dim, kcb)
    cross = jnp.einsum("lmd,mkd->lmk", ctr, codebooks)
    return cb_sq[None, :, :] + 2.0 * cross               # (L, pq_dim, kcb)


@functools.partial(jax.jit, static_argnums=(5,))
def _csum_for_codes(codes, labels, centers, rotation, codebooks,
                    per_cluster: bool):
    """Per-candidate contraction of the list-side ADC table:

        csum[i] = Σ_m (‖cb_code‖² + 2·ctr_rot·cb_code)
                = ‖decoded[i]‖² + 2·ctr_rot[label_i]·decoded[i]

    where ``decoded`` is the candidate's reconstructed rotated residual.
    The ADC lookup is LINEAR in the LUT, so the entire list-side half of
    the decomposition collapses to this (n,) f32 scalar at ENCODE time —
    the hoisted search adds it per gathered candidate instead of
    materializing per-(query, probe) combined tables (which costs more
    gather traffic than it saves; see docs/ivf_pq_adc.md).  Computed via
    the decoded form: O(n·rot_dim), no (n, pq_dim, 2^bits) gather."""
    n = codes.shape[0]
    rot_centers = centers @ rotation
    if per_cluster:
        cbl = codebooks[labels]                          # (n, kcb, ds)
        dec = jnp.take_along_axis(cbl, codes[:, :, None].astype(jnp.int32),
                                  axis=1)                # (n, pq_dim, ds)
        pq_dim = dec.shape[1]
    else:
        pq_dim = codebooks.shape[0]
        dec = codebooks[jnp.arange(pq_dim)[None, :],
                        codes.astype(jnp.int32)]         # (n, pq_dim, ds)
    dec = dec.reshape(n, -1)                             # (n, rot_dim)
    ctr = rot_centers[labels]
    return jnp.sum(dec ** 2, -1) + 2.0 * jnp.sum(ctr * dec, -1)


def _csum_for_packed(list_codes, owner, centers, rotation, codebooks,
                     per_cluster: bool, pq_bits: int,
                     tile_phys: int = 1024):
    """``list_csum`` for an ALREADY-PACKED code block (legacy v1 archive
    load): unpack every slot, contract, repack in place.  Padding slots get
    garbage values — harmless, their scores are masked by ``phys_sizes``.
    TILED over physical rows (r7): the unpacked (rows·cap, pq_dim) codes
    and their decode transients exist only ``tile_phys`` chunk-rows at a
    time, matching the tiled build's O(tile) memory contract on the compat
    path too (each per-slot contraction is row-local, so chunking is
    exact)."""
    rows, cap = list_codes.shape[0], list_codes.shape[1]
    if per_cluster:
        ds = codebooks.shape[2]
        pq_dim = rotation.shape[1] // ds
    else:
        pq_dim = codebooks.shape[0]
    owner_d = jnp.asarray(owner)
    out = []
    for r0 in range(0, rows, tile_phys):
        r1 = min(r0 + tile_phys, rows)
        codes = _unpack_codes(list_codes[r0:r1].reshape((r1 - r0) * cap, -1),
                              pq_dim, pq_bits)
        labels = jnp.repeat(owner_d[r0:r1], cap)
        out.append(_csum_for_codes(codes, labels, centers, rotation,
                                   codebooks, per_cluster
                                   ).reshape(r1 - r0, cap))
    return out[0] if len(out) == 1 else jnp.concatenate(out, axis=0)


def _validate_build(params: IndexParams, x) -> None:
    expects(x.ndim == 2, "dataset must be (n, dim)")
    expects(params.metric in _SUPPORTED,
            f"ivf_pq: unsupported metric {params.metric}")
    expects(4 <= params.pq_bits <= 8,
            "pq_bits must be in [4, 8] (ivf_pq_types.hpp:52)")
    expects(params.rotation_kind in ("auto", "default", "pca_balanced"),
            f"unknown rotation_kind {params.rotation_kind!r}")


def _train_model(params: IndexParams, x):
    """Steps 1–4 of ``build`` (reference ivf_pq_build.cuh): coarse
    quantizer, assignment, rotation, codebooks — ONE implementation shared
    by :func:`build` (both populate modes) and :func:`build_sharded`, so
    every pipeline trains the bit-identical model.

    The assignment runs through the fused-L2-NN scan (O(tile) transients
    already); the codebooks train on a residual sample capped at
    ``params.pq_trainset_cap`` rows (all rows at or under the cap — the
    pre-PR behavior — else a seeded uniform sample), so no stage here
    materializes a dataset-sized residual matrix beyond the cap.  Returns
    (centers, labels, rotation, codebooks, n_lists, pq_dim, per_cluster).
    """
    n, dim = x.shape
    n_lists = min(params.n_lists, n)
    pq_dim = params.pq_dim or _calc_pq_dim(dim)
    rot_dim = -(-dim // pq_dim) * pq_dim
    rotation_kind = params.rotation_kind
    if rotation_kind == "auto":
        rotation_kind = "pca_balanced" if rot_dim == dim else "default"
    expects(rotation_kind != "pca_balanced" or rot_dim == dim,
            "rotation_kind='pca_balanced' needs pq_dim | dim")
    k = 1 << params.pq_bits
    key = jax.random.PRNGKey(params.seed)
    k_rot, k_cb = jax.random.split(key)

    # 1) coarse quantizer
    train = subsample_trainset(x, params.kmeans_trainset_fraction, n_lists,
                               params.seed)
    centers = build_hierarchical(RngState(params.seed), train, n_lists,
                                 params.kmeans_n_iters)

    # 2) assignment.  Must agree with how search ranks probe lists:
    # max-dot for InnerProduct, else min-L2.
    if params.metric == DistanceType.InnerProduct:
        labels = jnp.argmax(x @ centers.T, axis=1).astype(jnp.int32)
    else:
        labels = min_cluster_and_distance(x, centers).key.astype(jnp.int32)

    # 3) rotation
    if rotation_kind == "pca_balanced":
        # residual-covariance sample; seed offset decorrelates it from the
        # trainset subsample (which uses params.seed)
        sel = jnp.asarray(np.sort(np.random.default_rng(
            params.seed + 7).choice(n, size=min(n, 50_000), replace=False)))
        resid_sample = np.asarray(x[sel] - centers[labels[sel]])
        rotation = jnp.asarray(_pca_balanced_rotation(resid_sample, pq_dim))
    else:
        rotation = _make_rotation(k_rot, dim, rot_dim,
                                  params.force_random_rotation
                                  or rot_dim != dim)

    # 4) codebooks, on the (capped) residual sample
    cap_t = max(int(params.pq_trainset_cap), k)
    if n > cap_t:
        sel_t = jnp.asarray(np.sort(np.random.default_rng(
            params.seed + 13).choice(n, size=cap_t, replace=False)))
        x_t, lab_t = x[sel_t], labels[sel_t]
    else:
        x_t, lab_t = x, labels
    resid_t = (x_t - centers[lab_t]) @ rotation      # (n_train, rot_dim)
    if params.codebook_kind == CodebookKind.PER_CLUSTER:
        codebooks = _train_codebooks_cluster_host(
            k_cb, np.asarray(resid_t), np.asarray(lab_t), n_lists, pq_dim,
            k, params.kmeans_n_iters)
    else:
        codebooks = _train_codebooks_subspace(k_cb, resid_t, pq_dim, k,
                                              params.kmeans_n_iters)
    per_cluster = params.codebook_kind == CodebookKind.PER_CLUSTER
    return centers, labels, rotation, codebooks, n_lists, pq_dim, per_cluster


def _encode_tile_impl(x_t, labels_t, centers, rotation, codebooks,
                      per_cluster: bool, pq_bits: int):
    """The per-tile encode kernel: residual → PQ encode → bit-pack, FUSED
    into one executable — the (tile, rot_dim) residual, the
    (tile, pq_dim, 2^bits) encode-distance transient and the
    (tile, pq_dim, pq_bits) bit tensor exist only at tile size
    (docs/index_build.md; the monolithic path materializes all three at
    dataset size).  Also returns the raw (tile, pq_dim) codes for the
    csum stage.  Row-local math only: the same kernel runs per shard
    inside ``build_sharded``'s shard_map populate."""
    build_trace_counters.inc("pq_encode_tile")
    resid = (x_t - centers[labels_t]) @ rotation
    codes = _encode(resid, codebooks, labels_t, per_cluster)
    packed = _pack_codes(codes, pq_bits)
    return packed, codes


def _csum_tile_impl(codes_t, labels_t, centers, rotation, codebooks,
                    per_cluster: bool):
    """Per-tile list-side ADC csum — its OWN program, NOT fused into the
    encode tile: XLA reassociates the decode-contraction's reductions when
    the encode is fused alongside, which perturbs the csum's last ulp vs
    the monolithic ``_csum_for_codes`` dispatch (observed on PER_CLUSTER)
    and would break the tiled ≡ monolithic bit-identity contract.  As a
    standalone trace it is the monolithic program at tile shapes, and the
    contraction is row-local, so row tiling is exact."""
    build_trace_counters.inc("pq_csum_tile")
    return (_csum_for_codes(codes_t, labels_t, centers, rotation, codebooks,
                            per_cluster),)


_ENC_TILE_STATICS = (5, 6)
_encode_tile = functools.partial(jax.jit, static_argnums=_ENC_TILE_STATICS)(
    _encode_tile_impl)
_encode_tile_aot = aot(_encode_tile_impl, static_argnums=_ENC_TILE_STATICS)
_CSUM_TILE_STATICS = (5,)
_csum_tile = functools.partial(jax.jit, static_argnums=_CSUM_TILE_STATICS)(
    _csum_tile_impl)
_csum_tile_aot = aot(_csum_tile_impl, static_argnums=_CSUM_TILE_STATICS)


def _audit_tile_model():
    """Audit-time model SPECS at the PR-7 bench shape (tile 8192, dim 64,
    pq_dim 16, 512 lists, 8-bit PER_SUBSPACE) — shapes only, no data."""
    x_t = jax.ShapeDtypeStruct((8192, 64), jnp.float32)
    labels = jax.ShapeDtypeStruct((8192,), jnp.int32)
    centers = jax.ShapeDtypeStruct((512, 64), jnp.float32)
    rotation = jax.ShapeDtypeStruct((64, 64), jnp.float32)
    codebooks = jax.ShapeDtypeStruct((16, 256, 4), jnp.float32)
    return x_t, labels, centers, rotation, codebooks


@hlo_program(
    "ivf_pq.encode_tile",
    collectives=0, collective_bytes=0,
    # Graduates the PR-7 in-bench O(tile)-transient gate into CI: the
    # residual→encode→bit-pack fusion measured 4.2 MB/tile at exactly this
    # shape (vs 1.66 GB monolithic, BENCH_TPU.md PR-7); the ceiling gives
    # fusion-variance headroom while still catching any (tile, pq_dim,
    # 2^bits) encode-distance materialization (8192·16·256·4 = 128 MB)
    transient_bytes=8 << 20,
    # static compute budget at the audit shape: the mul-reduce encode is
    # tile·pq_dim·2^bits·(3·ds) ≈ 0.8 GFLOP — a lowering regression that
    # re-materializes per-codeword distances (or re-encodes per chunk)
    # multiplies this; ~1.5x headroom for fusion variance
    flops_budget=1_200_000_000,
    notes="per-tile residual→PQ-encode→bit-pack populate kernel "
          "(docs/index_build.md)")
def _audit_encode_tile():
    x_t, labels, centers, rotation, codebooks = _audit_tile_model()
    return dict(fn=_encode_tile_impl,
                args=(x_t, labels, centers, rotation, codebooks, False, 8),
                static_argnums=_ENC_TILE_STATICS)


@hlo_program(
    "ivf_pq.csum_tile",
    collectives=0, collective_bytes=0,
    # the decode-contraction transient at tile size: (tile, pq_dim, 2^bits)
    # one-hot or gather scratch — bounded by the same O(tile) contract
    transient_bytes=8 << 20,
    notes="per-tile list-side ADC csum kernel (its own program for "
          "bit-identity, docs/index_build.md)")
def _audit_csum_tile():
    _, labels, centers, rotation, codebooks = _audit_tile_model()
    codes_t = jax.ShapeDtypeStruct((8192, 16), jnp.int32)
    return dict(fn=_csum_tile_impl,
                args=(codes_t, labels, centers, rotation, codebooks, False),
                static_argnums=_CSUM_TILE_STATICS)


def _encode_rows(model, x, labels, pq_bits: int, per_cluster: bool,
                 tiled: bool, tile_rows: Optional[int]):
    """(packed, csum) for *x*'s rows: the tiled AOT loop (default) or the
    monolithic dispatch chain (``tiled=False``) — same kernels, so the
    results are bit-identical; only transient sizes and executable reuse
    differ."""
    centers, rotation, codebooks = model
    if tiled and x.shape[0]:
        packed, codes = _build.run_tiles(
            _encode_tile, _encode_tile_aot, x, labels,
            (centers, rotation, codebooks), (per_cluster, pq_bits),
            tile_rows)
        (csum,) = _build.run_tiles(
            _csum_tile, _csum_tile_aot, codes, labels,
            (centers, rotation, codebooks), (per_cluster,), tile_rows)
        return packed, csum
    resid = (x - centers[labels]) @ rotation          # (n, rot_dim)
    codes = _encode(resid, codebooks, labels, per_cluster)
    packed = _pack_codes(codes, pq_bits)
    csum = _csum_for_codes(codes, labels, centers, rotation, codebooks,
                           per_cluster)
    return packed, csum


@traced("raft_tpu.neighbors.ivf_pq.build")
@auto_sync_handle
def build(params: IndexParams, dataset, ids=None, *,
          tiled: Optional[bool] = None, tile_rows: Optional[int] = None,
          handle=None) -> Index:
    """Train + populate (reference ``ivf_pq::build``, ivf_pq_build.cuh).

    *dataset* may be float32, int8 or uint8 (reference build is templated
    on T ∈ {float, int8_t, uint8_t}, neighbors/ivf_pq.cuh:62); integer
    datasets train/encode in f32 and the index remembers the dtype so
    extend()/search() stay consistent.

    The populate runs TILED by default (docs/index_build.md): one fused
    per-tile program (residual → encode → bit-pack → csum) through the AOT
    executable cache plus a device-side pack, so peak transient memory is
    O(tile) and repeated builds hit warm executables.  ``tiled=False`` (or
    ``RAFT_TPU_TILED_BUILD=0``) restores the pre-PR monolithic populate —
    the A/B baseline; both produce bit-identical indexes.  *tile_rows*
    overrides the per-tile row count (``RAFT_TPU_BUILD_TILE``, default
    8192)."""
    x, dataset_dtype = _ingest_dataset(dataset)
    _validate_build(params, x)
    n = x.shape[0]
    (centers, labels, rotation, codebooks, n_lists, pq_dim,
     per_cluster) = _train_model(params, x)
    use_tiled = _build.resolve_tiled(tiled)

    # 5) encode + bit-pack + scatter into lists (skipped entirely with
    # add_data_on_build=False: the trained model is kept, rows come later
    # via extend — reference ann::index_params::add_data_on_build)
    if params.add_data_on_build:
        if ids is None:
            ids = jnp.arange(n, dtype=jnp.int32)
        else:
            ids = jnp.asarray(ids, jnp.int32)
        packed, csum = _encode_rows((centers, rotation, codebooks), x,
                                    labels, params.pq_bits, per_cluster,
                                    use_tiled, tile_rows)
    else:
        expects(ids is None,
                "ids were passed but add_data_on_build=False stores no "
                "rows — pass them to extend() instead")
        packed = jnp.zeros((0, _code_bytes(pq_dim, params.pq_bits)),
                           jnp.uint8)
        csum = jnp.zeros((0,), jnp.float32)
        ids = jnp.zeros((0,), jnp.int32)
        labels = jnp.zeros((0,), jnp.int32)
    pack = _build.pack_device if use_tiled else pack_lists_chunked
    ((list_codes, list_csum), list_indices, phys_sizes, list_sizes,
     chunk_table, owner, _) = pack((packed, csum), ids, labels, n_lists)
    list_adc = _build_list_adc(centers, rotation, codebooks, per_cluster)
    return Index(centers=centers, rotation=rotation, codebooks=codebooks,
                 list_codes=list_codes, list_indices=list_indices,
                 list_sizes=list_sizes, phys_sizes=phys_sizes,
                 chunk_table=chunk_table, owner=owner, list_adc=list_adc,
                 list_csum=list_csum, metric=params.metric,
                 codebook_kind=params.codebook_kind, pq_bits=params.pq_bits,
                 dataset_dtype=dataset_dtype)


@traced("raft_tpu.neighbors.ivf_pq.build_sharded")
def build_sharded(params: IndexParams, dataset, comms, ids=None, *,
                  tile_rows: Optional[int] = None):
    """Train once (replicated) + populate DIRECT-TO-SHARD: the tiled
    per-tile encode kernel runs as a ``shard_map`` program over *comms*'
    mesh, each device encoding and packing ONLY its round-robin list
    shard's rows — producing a
    :class:`raft_tpu.neighbors.ann_mnmg.ShardedIndex` bit-identical to
    ``build(params, dataset).shard(comms)`` without the full packed index
    ever materializing on one device (docs/index_build.md §sharded).  The
    populate path moves no dataset-sized data to host (ci/lint.py
    enforced) and repeated builds of the same shapes dispatch only warm
    executables (``aot_compile_counters``-assertable)."""
    from raft_tpu.neighbors import ann_mnmg

    comms = ann_mnmg._full_axis_comms(comms)
    x, dataset_dtype = _ingest_dataset(dataset)
    _validate_build(params, x)
    expects(params.add_data_on_build,
            "build_sharded populates by construction — use "
            "build(add_data_on_build=False) + extend + shard() for "
            "deferred ingest")
    n = x.shape[0]
    (centers, labels, rotation, codebooks, n_lists, pq_dim,
     per_cluster) = _train_model(params, x)
    if ids is None:
        ids = jnp.arange(n, dtype=jnp.int32)
    else:
        ids = jnp.asarray(ids, jnp.int32)

    lay = chunk_layout(device_counts(labels, n_lists))
    pq_bits = int(params.pq_bits)
    key = ("ivf_pq", n_lists, pq_dim, pq_bits, per_cluster)
    # two shard_map stages per tile, mirroring the single-device split:
    # encode/pack fused, csum standalone (its rounding must match the
    # monolithic trace — _csum_tile_impl docstring)
    enc_prog = _build.shard_tile_program(
        comms, key + ("enc",),
        lambda xt, lt, c, r, cb: _encode_tile_impl(xt, lt, c, r, cb,
                                                   per_cluster, pq_bits),
        n_margs=3, n_out=2)
    csum_prog = _build.shard_tile_program(
        comms, key + ("csum",),
        lambda ct, lt, c, r, cb: _csum_tile_impl(ct, lt, c, r, cb,
                                                 per_cluster),
        n_margs=3, n_out=1)
    from jax.sharding import PartitionSpec as P

    margs = tuple(comms.globalize(a, P())
                  for a in (centers, rotation, codebooks))

    def tile_fn(xt_g, lt_g):
        packed, codes = enc_prog(xt_g, lt_g, *margs)
        (csum,) = csum_prog(codes, lt_g, *margs)
        return packed, csum

    (stacked_pay, stacked_idx, stacked_phys, stacked_tables, stacked_owner,
     probe_extra, _) = _build.populate_sharded(
        comms, x, labels, ids, lay, tile_fn, n_payloads=2, key=key,
        tile_rows=tile_rows)
    list_adc = _build_list_adc(centers, rotation, codebooks, per_cluster)
    stacked = (stacked_pay[0], stacked_idx, stacked_phys, stacked_tables,
               stacked_owner, stacked_pay[1])
    replicated = (ann_mnmg._replicate(comms, centers),
                  ann_mnmg._replicate(comms, rotation),
                  ann_mnmg._replicate(comms, codebooks),
                  ann_mnmg._replicate(comms, list_adc))
    aux = ann_mnmg._ivf_pq_aux(
        world=comms.get_size(), dim=x.shape[1], metric=int(params.metric),
        n_lists=n_lists, probe_extra=probe_extra, pq_bits=pq_bits,
        codebook_kind=int(params.codebook_kind),
        dataset_dtype=dataset_dtype, pq_dim=pq_dim,
        max_chunks=lay.max_chunks)
    return ann_mnmg.ShardedIndex("ivf_pq", comms, replicated, stacked, aux)


def extend(index: Index, new_vectors, new_ids=None, *,
           tiled: Optional[bool] = None, tile_rows: Optional[int] = None,
           in_place: bool = False) -> Index:
    """Add vectors to an existing index (reference ``ivf_pq::extend``,
    neighbors/ivf_pq.cuh:103,128).  Functional: encodes the new vectors
    with the trained centers/rotation/codebooks (no retraining, as in the
    reference).  INCREMENTAL (r5): new codes append into each list's free
    tail slots and only overflowing lists grow a chunk; the r4 path
    unpacked ALL live codes and re-sorted the whole index per extend.

    TILED (r7, default; docs/index_build.md): the new rows encode through
    the same warm per-tile AOT program as :func:`build` and append through
    the device-side scatter (``_build.extend_device``) — no per-row host
    work, O(tile) transients, O(n_new) scatter.  ``in_place=True``
    additionally DONATES the old index's list blocks to the append when no
    list overflows, making the append truly in place (O(n_new) total, no
    O(index) copy) — the input *index* is consumed and must not be used
    afterwards.  ``tiled=False`` (or ``RAFT_TPU_TILED_BUILD=0``) restores
    the pre-PR monolithic encode + grow-by-concat path (the A/B baseline,
    bit-identical results).

    .. note::
       Caller-supplied *new_ids* are validated for uniqueness — within
       the batch AND against every id already live in the index — and a
       collision raises ``ValueError`` loudly: a duplicate id would
       silently yield two live rows answering for one key.  Replace
       semantics (tombstone the old row, append the new) live in
       :meth:`raft_tpu.neighbors.mutable.MutableIndex.upsert`.
    """
    x, new_dtype = _ingest_dataset(new_vectors)
    expects(new_dtype == index.dataset_dtype,
            f"extend dtype {new_dtype} != index dataset dtype "
            f"{index.dataset_dtype} (reference extend is templated on the "
            "build T, neighbors/ivf_pq.cuh:103)")
    expects(x.ndim == 2 and x.shape[1] == index.dim, "dim mismatch")
    n_new = x.shape[0]
    base = index.size
    if new_ids is None:
        new_ids = jnp.arange(base, base + n_new, dtype=jnp.int32)
    else:
        new_ids = jnp.asarray(new_ids, jnp.int32)
        expects(new_ids.shape == (n_new,), "ids must be (n_new,)")
        validate_new_ids(new_ids, index.list_indices, index.phys_sizes)

    use_tiled = _build.resolve_tiled(tiled)
    per_cluster = index.codebook_kind == CodebookKind.PER_CLUSTER
    if index.metric == DistanceType.InnerProduct:
        labels = jnp.argmax(x @ index.centers.T, axis=1).astype(jnp.int32)
    else:
        labels = min_cluster_and_distance(x, index.centers).key.astype(jnp.int32)
    packed, csum = _encode_rows(
        (index.centers, index.rotation, index.codebooks), x, labels,
        index.pq_bits, per_cluster, use_tiled, tile_rows)

    if base:
        ext = _build.extend_device if use_tiled else extend_lists_chunked
        kw = {"in_place": in_place} if use_tiled else {}
        ((list_codes, list_csum), list_indices, phys_sizes, list_sizes,
         chunk_table, owner, _) = ext(
            (index.list_codes, index.list_csum), index.list_indices,
            index.list_sizes, index.chunk_table, (packed, csum), new_ids,
            labels, **kw)
    else:
        pack = _build.pack_device if use_tiled else pack_lists_chunked
        ((list_codes, list_csum), list_indices, phys_sizes, list_sizes,
         chunk_table, owner, _) = pack(
            (packed, csum), new_ids, labels, index.n_lists)
    # the trained model (centers/rotation/codebooks) is untouched by extend,
    # so the build-time list-side ADC table carries over unchanged
    return Index(centers=index.centers, rotation=index.rotation,
                 codebooks=index.codebooks, list_codes=list_codes,
                 list_indices=list_indices, list_sizes=list_sizes,
                 phys_sizes=phys_sizes, chunk_table=chunk_table, owner=owner,
                 list_adc=index.list_adc, list_csum=list_csum,
                 metric=index.metric, codebook_kind=index.codebook_kind,
                 pq_bits=index.pq_bits, dataset_dtype=index.dataset_dtype)


def _scan_hoisted(q, probe_ids, rot_q, rot_centers, centers, codebooks,
                  list_adc, list_csum, list_codes, list_indices, phys_sizes,
                  chunk_table, nq: int, pq_dim: int, kcb: int, ds: int,
                  k: int, is_ip: bool, per_cluster: bool,
                  lut_dtype_name: str, acc_dtype, pq_bits: int,
                  probe_extra: int = -1, engine: str = "xla",
                  tombstones=None):
    """Hoisted-ADC probe scan: per-batch LUT stage + lookup-only scan body.

    Stage 2 of the pipeline (stage 1 is the build-time ``list_adc`` /
    ``list_csum``): for the whole query batch, compute the query-cross LUT
    (−2·rot_q·codebooks for L2; rot_q·codebooks for IP — PER_SUBSPACE is
    ONE einsum for the batch; PER_CLUSTER gathers the probed lists'
    codebooks), quantize ONCE with a single per-(query, probe-set) affine
    (:func:`_quantize_lut`), and thread the per-probe parts through the
    probe scan as ``lax.scan`` xs via the expanded slots' probe ordinals.

    The list-side half of the decomposition enters in one of two ways:

    * ``lut_dtype=float32`` (no LUT compression): it does NOT enter the
      LUT at all — the lookup is linear in the LUT, so the list-side
      contribution is the per-candidate ``list_csum`` scalar precomputed
      at encode time, added after the lookup.  For PER_SUBSPACE this makes
      the LUT probe-INVARIANT (closed over by the scan body as a
      constant): no per-(query, probe) combined-table materialization,
      which measures SLOWER than the in-scan recompute on CPU — XLA:CPU
      gathers are effectively single-threaded and combined tables cost
      ~4× the legacy path's gathered bytes.
    * compressed LUTs (bf16/f16/fp8): the stored ``list_adc`` is gathered
      per probe and combined with the query-cross term BEFORE
      quantization, exactly the reference's combined-LUT shape.  The
      combined entries are small (the large ‖r‖²-free cross terms cancel
      against the center-cross + sq-norm terms), so quantization error
      stays relative to the quantity actually ranked — quantizing the raw
      query-cross alone loses ~half the top-k to cancellation noise
      (measured; docs/ivf_pq_adc.md).  ‖r‖² still rides the exact-f32
      per-probe base, shrinking the fp8 dynamic range vs the legacy path.

    Stage 3 is the scan body: bit-unpack + ONE flattened lookup — codes
    offset by m·2^bits index a (nq, pq_dim·2^bits) LUT row, one
    ``take_along_axis`` on CPU / one one-hot MXU einsum on TPU — replacing
    the pq_dim sequential one-hot scan steps of the legacy path, plus the
    csum gather and the threaded base add.  Per-probe work drops from
    O(pq_dim·2^bits·ds) einsum flops + epilogues to a pure table lookup.

    ``engine="pallas"`` routes the lookup through the LUT-in-VMEM Pallas
    kernel (``raft_tpu.kernels.ivf_pq_lut``): the LUT block stays RESIDENT
    in VMEM across a probe tile's candidate blocks and the packed codes
    unpack + one-hot + dot tile-at-a-time in VMEM (int8/fp8 MXU dot paths
    for the compressed LUT dtypes) — bounded-error vs this XLA lookup
    (association order; docs/pallas_kernels.md §error bounds).  The same
    knob selects the blockwise select_k inside the probe scan."""
    lut_trace_counters.inc("hoisted_lut_builds")
    q_sub = rot_q.reshape(nq, pq_dim, ds)
    # combined list+query LUT for compressed dtypes (quantization needs the
    # small-dynamic-range combined entries); csum path for exact f32
    combine = (not is_ip) and lut_dtype_name != "float32"
    per_probe_lut = per_cluster or combine
    if per_cluster:
        cbp = codebooks[probe_ids]                      # (nq, P, kcb, ds)
        qlut = jnp.einsum("qmd,qpkd->qpmk", q_sub, cbp)
    else:
        # ONE einsum for the whole batch — no per-tile owner gather; the
        # size-1 probe axis keeps _quantize_lut single-shape
        qlut = jnp.einsum("qmd,mkd->qmk", q_sub, codebooks)[:, None]
    if is_ip:
        # score = q·c + Σ_m rot_q·cb — no list-side term
        base = jnp.einsum("qd,qpd->qp", q, centers[probe_ids])
        lut = qlut
    else:
        lut = -2.0 * qlut
        if combine:
            lut = list_adc[probe_ids] + lut             # (nq, P, pq_dim, kcb)
        # ‖r‖² — constant across a list's candidates, so it lives in the
        # per-(query, probe) base, not the LUT (shrinks fp8 dynamic range)
        rc = rot_centers[probe_ids]                     # (nq, P, rot_dim)
        base = jnp.sum((rot_q[:, None, :] - rc) ** 2, axis=-1)
    lut_q, base, scale = _quantize_lut(lut, base, lut_dtype_name)
    lut_q = lut_q.reshape(nq, lut_q.shape[1], pq_dim * kcb)

    phys_probes, probe_ord = expand_probes(
        probe_ids, chunk_table, list_codes.shape[0], return_ord=True,
        extra=None if probe_extra < 0 else probe_extra)
    # per-scan-step xs: gather each physical slot's (probe ordinal) slice
    # of the per-batch tables — (budget, nq, …) with the scan axis leading
    base_xs = jnp.swapaxes(
        jnp.take_along_axis(base, probe_ord, axis=1), 0, 1)
    if per_probe_lut:
        lut_xs = jnp.swapaxes(jnp.take_along_axis(
            lut_q, probe_ord[:, :, None], axis=1), 0, 1)
        xs = (lut_xs, base_xs)
    else:
        lut_flat = lut_q[:, 0]                          # (nq, pq_dim·kcb)
        xs = (base_xs,)
    offsets = jnp.arange(pq_dim, dtype=jnp.int32) * kcb
    use_pallas_lut = False
    if engine == "pallas":
        from raft_tpu.kernels import ivf_pq_lut as pallas_lut

        use_pallas_lut = pallas_lut.supports(pq_dim, kcb)

    def _lookup(rows, lut_t):
        """out[q, c] = Σ_m lut_t[q, m·kcb + code[q, c, m]] — the allowlisted
        ADC lookup contraction; no LUT is built here."""
        if use_pallas_lut:
            # LUT-in-VMEM kernel: packed codes go in AS-PACKED — the
            # unpacked (nq, cap, pq_dim) tensor and the one-hot exist only
            # tile-at-a-time in VMEM (docs/pallas_kernels.md)
            return pallas_lut.lut_score(list_codes[rows], lut_t,
                                        pq_dim, pq_bits, kcb)
        codes = _unpack_codes(list_codes[rows], pq_dim, pq_bits)
        cap = codes.shape[1]
        if jax.default_backend() == "cpu":
            # CPU gathers are cheap (see the legacy path's measurement
            # notes): ONE flattened take_along_axis for all subspaces
            flat = (codes + offsets).reshape(nq, cap * pq_dim)
            got = jnp.take_along_axis(lut_t, flat, axis=1)
            return jnp.sum(got.astype(acc_dtype).reshape(nq, cap, pq_dim),
                           axis=-1)
        # TPU: the m-offset segments make the per-subspace one-hots one
        # block-diagonal (cap, pq_dim·kcb) multi-hot — ONE MXU contraction
        # instead of pq_dim sequential scan steps
        oh = (codes[:, :, :, None] ==
              jnp.arange(kcb, dtype=codes.dtype)).astype(lut_t.dtype)
        return jnp.einsum("qck,qk->qc", oh.reshape(nq, cap, pq_dim * kcb),
                          lut_t, preferred_element_type=acc_dtype)

    add_csum = (not is_ip) and not combine

    def _finish(rows, acc, base_t):
        s = (acc.astype(jnp.float32) / scale[:, None]) + base_t[:, None]
        # f32 path: list-side ADC contribution, contracted per candidate
        # at encode time (combined-LUT path already carries it via
        # list_adc; IP has no list-side term)
        return s + list_csum[rows] if add_csum else s

    if per_probe_lut:
        def score_tile_hoisted(rows, lut_t, base_t):
            return _finish(rows, _lookup(rows, lut_t), base_t)
    else:
        def score_tile_hoisted(rows, base_t):
            return _finish(rows, _lookup(rows, lut_flat), base_t)

    return scan_probe_lists(phys_probes, score_tile_hoisted, list_indices,
                            phys_sizes, k, select_min=not is_ip,
                            dtype=jnp.float32, xs=xs, engine=engine,
                            tombstones=tombstones)


def _quantize_lut(lut, base, lut_dtype_name: str):
    """Quantize the per-batch query-side LUT (nq, P, pq_dim, kcb) f32 for
    the scan (P = n_probes for PER_CLUSTER, 1 when probe-invariant),
    returning (lut_q, base', scale).

    fp8 contract (docs/ivf_pq_adc.md): each (query, probe, subspace) row is
    shifted to 0 (the shift re-enters exactly via *base'*, f32), then ONE
    scale per QUERY — computed over the query's ENTIRE probe set — maps the
    peak to ``_FP8_PEAK``.  A single per-(query, probe-set) affine is what
    makes the dequantized scores of candidates from different probe tiles
    mutually comparable; the pre-hoist per-tile recompute re-derived
    ``scale``/``lo`` from per-tile extrema, silently quantizing one query
    with different affines across the tiles of one search (the latent fp8
    bug this hoist fixes).  Positive affine maps preserve per-query
    ranking; the scan inverts the map in f32 after lookup."""
    nq = lut.shape[0]
    if lut_dtype_name != "float8_e4m3":
        return (lut.astype(_LUT_DTYPES[lut_dtype_name]), base,
                jnp.ones((nq,), jnp.float32))
    lo = jnp.min(lut, axis=-1, keepdims=True)       # (nq, P, pq_dim, 1)
    lut0 = lut - lo
    scale = _FP8_PEAK / jnp.maximum(
        jnp.max(lut0, axis=(1, 2, 3)), 1e-30)       # (nq,) — ONE per query
    lut_q = (lut0 * scale[:, None, None, None]).astype(jnp.float8_e4m3fn)
    return lut_q, base + jnp.sum(lo[..., 0], axis=-1), scale


def _search_batch_impl(q, probe_ids, leaves, metric_val: int, k: int,
                       per_cluster: bool, lut_dtype_name: str,
                       int_dtype_name: str, pq_bits: int, hoisted: bool,
                       probe_extra: int = -1, engine: str = "xla",
                       tombstones=None):
    """Score probed lists via per-query LUTs (reference similarity kernels
    ivf_pq_search.cuh:594-738) with a running top-k merge.

    *hoisted* (default path) builds the combined ADC LUT ONCE per (query
    batch, probe set) — build-time ``list_adc`` + per-batch query-cross
    einsum — quantizes it with a single per-query affine, and threads it
    through the probe scan as xs; the scan body is pure bit-unpack +
    flattened table lookup.  ``hoisted=False`` is the pre-PR per-tile
    recompute, kept as the ``RAFT_TPU_HOISTED_LUT=0`` A/B baseline.

    ``engine`` (static, caller-resolved via ``kernels.resolve_engine``):
    "pallas" selects the LUT-in-VMEM scoring kernel + the blockwise
    select_k inside the hoisted scan (see ``_scan_hoisted``)."""
    (centers, rotation, codebooks, list_codes, list_indices,
     phys_sizes, chunk_table, owner, list_adc, list_csum) = leaves
    nq = q.shape[0]
    is_ip = metric_val == int(DistanceType.InnerProduct)
    is_fp8 = lut_dtype_name == "float8_e4m3"
    lut_dtype = _LUT_DTYPES[lut_dtype_name]
    acc_dtype = (jnp.float32 if is_fp8
                 else _LUT_DTYPES.get(int_dtype_name, jnp.float32))

    rot_q = q @ rotation                                  # (nq, rot_dim)
    rot_centers = centers @ rotation                      # (n_lists, rot_dim)
    if per_cluster:
        kcb, ds = codebooks.shape[1], codebooks.shape[2]
        pq_dim = rot_q.shape[1] // ds
    else:
        pq_dim, kcb, ds = codebooks.shape

    if hoisted:
        best_d, best_i = _scan_hoisted(
            q, probe_ids, rot_q, rot_centers, centers, codebooks,
            list_adc, list_csum, list_codes, list_indices, phys_sizes,
            chunk_table,
            nq, pq_dim, kcb, ds, k, is_ip, per_cluster, lut_dtype_name,
            acc_dtype, pq_bits, probe_extra, engine, tombstones)
        if metric_val == int(DistanceType.L2SqrtExpanded):
            best_d = jnp.sqrt(jnp.maximum(best_d, 0))
        return best_d, best_i

    lut_trace_counters.inc("in_scan_lut_builds")

    def score_tile(rows):
        lists = owner[rows]                                # logical list ids
        c_rot = rot_centers[lists]                         # (nq, rot_dim)
        r = (rot_q - c_rot).reshape(nq, pq_dim, ds)        # query residual
        cb = (codebooks[lists] if per_cluster else codebooks)
        # The in-scan codebook einsums below are the SANCTIONED legacy
        # baseline (ci/lint.py forbids new ones in probe-scan callbacks —
        # per-batch-invariant LUT work belongs in _scan_hoisted's batch
        # stage); hence the exemption markers.
        if is_ip:
            # score = q·(c + code) = q·c + Σ_m q_m·cb  → LUT of dots
            if per_cluster:
                lut = jnp.einsum(  # exempt(probe-scan-closure): =0 LUT baseline
                    "qmd,qkd->qmk", rot_q.reshape(nq, pq_dim, ds), cb)
            else:
                lut = jnp.einsum(  # exempt(probe-scan-closure): =0 LUT baseline
                    "qmd,mkd->qmk", rot_q.reshape(nq, pq_dim, ds), cb)
            base = jnp.sum(q * centers[lists], axis=-1)    # (nq,)
        else:
            # score = ||r − code||² summed over subspaces
            if per_cluster:
                lut = (jnp.sum(r ** 2, -1)[:, :, None]
                       + jnp.sum(cb ** 2, -1)[:, None, :]
                       - 2.0 * jnp.einsum(  # exempt(probe-scan-closure): =0 base
                           "qmd,qkd->qmk", r, cb))
            else:
                lut = (jnp.sum(r ** 2, -1)[:, :, None]
                       + jnp.sum(cb ** 2, -1)[None, :, :]
                       - 2.0 * jnp.einsum(  # exempt(probe-scan-closure): =0 base
                           "qmd,mkd->qmk", r, cb))
            base = jnp.zeros((nq,), jnp.float32)
        if is_fp8:
            # fp8 e4m3's dynamic range can't hold raw squared distances:
            # shift each (query, subspace) row to 0 and scale per query so
            # the peak lands at _FP8_PEAK.  Positive per-query affine maps
            # preserve the top-k ranking; the inverse map below restores
            # approximate distances (the reference's fp8 LUT path likewise
            # dequantizes with a scale, ivf_pq_search.cuh:469-494).
            lo = jnp.min(lut, axis=2, keepdims=True)       # (nq, pq_dim, 1)
            lut0 = lut - lo
            scale = _FP8_PEAK / jnp.maximum(
                jnp.max(lut0, axis=(1, 2)), 1e-30)         # (nq,)
            lut = lut0 * scale[:, None, None]
            base = base + jnp.sum(lo[:, :, 0], axis=1)     # re-added after
        else:
            scale = jnp.ones((nq,), jnp.float32)
        lut = lut.astype(lut_dtype)                        # (nq, pq_dim, kcb)
        codes = _unpack_codes(list_codes[rows], pq_dim, pq_bits)
        # codes: (nq, cap, pq_dim) int32
        # LUT lookup, out[q,c] = Σ_m lut[q,m,code]:
        # * TPU: one-hot contraction.  No hardware gather —
        #   take_along_axis serializes on the scalar unit (measured 6×
        #   slower on v5e), while the iota-compare one-hot einsum rides the
        #   MXU/VPU and XLA fuses the one-hot materialization into the
        #   contraction, one subspace per scan step.
        # * CPU (CI/fallback): the one-hot costs kcb× the flops of a
        #   gather and CPU gathers are cheap — take_along_axis directly
        #   (measured ~40× faster at the smoke bench size).
        if jax.default_backend() == "cpu":
            def lut_step(acc, args):
                lut_m, codes_m = args                      # (nq,kcb),(nq,cap)
                got = jnp.take_along_axis(lut_m, codes_m, axis=1)
                return acc + got.astype(acc.dtype), None
        else:
            def lut_step(acc, args):
                lut_m, codes_m = args                      # (nq,kcb),(nq,cap)
                oh = (codes_m[:, :, None] ==
                      jnp.arange(kcb, dtype=codes_m.dtype)).astype(lut.dtype)
                return acc + jnp.einsum(  # exempt(probe-scan-closure): =0 lookup
                    "qck,qk->qc", oh, lut_m,
                    preferred_element_type=acc.dtype), None

        acc, _ = jax.lax.scan(
            lut_step, jnp.zeros((nq, codes.shape[1]), acc_dtype),
            (jnp.moveaxis(lut, 1, 0), jnp.moveaxis(codes, 2, 0)))
        # fp8: invert the per-query affine quantization (scale is 1 else)
        return (acc.astype(jnp.float32) / scale[:, None]) + base[:, None]

    phys_probes = expand_probes(probe_ids, chunk_table, list_codes.shape[0],
                                extra=None if probe_extra < 0 else probe_extra)
    best_d, best_i = scan_probe_lists(phys_probes, score_tile, list_indices,
                                      phys_sizes, k, select_min=not is_ip,
                                      dtype=jnp.float32,
                                      tombstones=tombstones)
    if metric_val == int(DistanceType.L2SqrtExpanded):
        best_d = jnp.sqrt(jnp.maximum(best_d, 0))
    return best_d, best_i


# Eager searches dispatch the AOT executable cache (reference precompiled
# ivfpq similarity-kernel variants, CMakeLists.txt:357-371); jit kept for
# traced callers.  ``hoisted`` is a STATIC arg, so the two pipeline shapes
# compile (and AOT-cache) as distinct executables — flipping
# RAFT_TPU_HOISTED_LUT mid-process can never hit the other path's program.
_SEARCH_STATICS = (3, 4, 5, 6, 7, 8, 9, 10, 11)
_search_batch = functools.partial(jax.jit, static_argnums=_SEARCH_STATICS)(
    _search_batch_impl)
_search_batch_aot = aot(_search_batch_impl, static_argnums=_SEARCH_STATICS)


def _full_search_impl(queries, leaves, metric_val: int, k: int,
                      n_probes: int, per_cluster: bool, lut_dtype_name: str,
                      int_dtype_name: str, pq_bits: int, hoisted: bool,
                      probe_extra: int = -1, engine: str = "xla",
                      tombstones=None):
    """Coarse ranking + top-n_probes + probe scoring as ONE program — the
    serving entry point (``serve.ServeEngine``): the whole query-batch →
    (d, i) computation is one AOT-cacheable executable whose signatures can
    be pinned at engine warmup, so steady-state dispatch never pays the
    separate coarse/select/scan dispatch trace checks.  ``search()`` keeps its
    two-stage path (it hoists the center sq-norms ACROSS batches of one
    call — a win the single-batch serving shape cannot use)."""
    centers = leaves[0]
    if metric_val == int(DistanceType.InnerProduct):
        coarse = -(queries @ centers.T)
    else:
        coarse = _l2_expanded(queries, centers, sqrt=False, precision=None)
    _, probes = select_k(coarse, n_probes, select_min=True, engine=engine)
    return _search_batch_impl(queries, probes.astype(jnp.int32), leaves,
                              metric_val, k, per_cluster, lut_dtype_name,
                              int_dtype_name, pq_bits, hoisted, probe_extra,
                              engine, tombstones)


_FULL_SEARCH_STATICS = (2, 3, 4, 5, 6, 7, 8, 9, 10, 11)
_full_search = functools.partial(
    jax.jit, static_argnums=_FULL_SEARCH_STATICS)(_full_search_impl)
_full_search_aot = aot(_full_search_impl,
                       static_argnums=_FULL_SEARCH_STATICS)


@hlo_program(
    "ivf_pq.full_search",
    collectives=0, collective_bytes=0,
    # hoisted-pipeline per-batch transient: the (nq, pq_dim·2^bits)
    # combined LUT + one probe tile — the hoisted_batch_cap arithmetic
    # bounds the big configs; this audit shape sits far below the cap
    transient_bytes=4 << 20,
    notes="coarse + top-n_probes + hoisted-ADC probe scan as ONE program "
          "— the ServeEngine ivf_pq backend (docs/ivf_pq_adc.md)")
def _audit_full_search():
    import numpy as np

    x = np.random.default_rng(0).standard_normal((2048, 32)
                                                 ).astype(np.float32)
    idx = build(IndexParams(n_lists=16, pq_dim=8, pq_bits=8), x)
    leaves = (idx.centers, idx.rotation, idx.codebooks, idx.list_codes,
              idx.list_indices, idx.phys_sizes, idx.chunk_table, idx.owner,
              idx.list_adc, idx.list_csum)
    q = jax.ShapeDtypeStruct((64, 32), jnp.float32)
    return dict(fn=_full_search_impl,
                args=(q, leaves, int(DistanceType.L2SqrtExpanded), 8, 4,
                      False, "float32", "float32", 8, True, -1, "xla"),
                static_argnums=_FULL_SEARCH_STATICS)


def hoisted_batch_cap_dims(metric, per_cluster: bool, n_phys: int,
                           max_chunks: int, n_lists: int, pq_dim: int,
                           pq_bits: int, n_probes: int, lut_dtype: str,
                           hoisted: bool) -> Optional[int]:
    """Dims-form core of :func:`hoisted_batch_cap` — callers without an
    ``Index`` in hand (the sharded layer sizes by its PER-SHARD physical
    block, ``neighbors.ann_mnmg``) pass the layout numbers directly; the
    formula itself stays in ONE place."""
    is_ip = DistanceType(metric) == DistanceType.InnerProduct
    if not (hoisted and (per_cluster or (not is_ip
                                         and lut_dtype != "float32"))):
        return None
    budget = min(n_probes * max_chunks,
                 n_probes + max(0, n_phys - n_lists))
    cell = pq_dim * (1 << pq_bits)
    lut_bytes = jnp.dtype(_LUT_DTYPES[lut_dtype]).itemsize
    per_q = cell * (3 * n_probes * 4 + budget * lut_bytes)
    # power of two keeps the shape-bucketed executable set small
    return 1 << max(5, ((128 << 20) // max(per_q, 1)).bit_length() - 1)


def hoisted_batch_cap(index: Index, n_probes: int, lut_dtype: str,
                      hoisted: bool) -> Optional[int]:
    """Query-batch cap (power of two) bounding the hoisted pipeline's
    per-batch transients to ~128 MiB, or None when the config builds no
    per-(query, probe) combined tables (in-scan path, exact-f32
    PER_SUBSPACE, IP).  The hoisted compressed-LUT / PER_CLUSTER configs
    materialize several concurrent per-batch copies: ~3 f32 transients
    with an n_probes probe axis (the list_adc gather, the combined LUT,
    the shifted/quantizing copy) plus the xs gather whose probe axis is
    the EXPANDED physical budget (> n_probes when lists span multiple
    chunks) in the quantized dtype.  ONE formula
    (:func:`hoisted_batch_cap_dims`) shared by :func:`search`'s query
    batching, the serving engine's super-batch clamp
    (serve.engine._IvfPqBackend) and the sharded layer — a tuning there
    reaches all three."""
    return hoisted_batch_cap_dims(
        index.metric, index.codebook_kind == CodebookKind.PER_CLUSTER,
        index.list_codes.shape[0] - 1, index.chunk_table.shape[1],
        index.n_lists, index.pq_dim, index.pq_bits, n_probes, lut_dtype,
        hoisted)


@traced("raft_tpu.neighbors.ivf_pq.search")
@auto_sync_handle
def search(params: SearchParams, index: Index, queries, k: int,
           *, batch_size_query: int = 1024, handle=None
           ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Search (reference ``ivf_pq::search``, ivf_pq_search.cuh:780):
    coarse top-n_probes → per-probe LUT scoring → top-k.

    Returns (distances [nq, k], indices [nq, k]).  Distances are
    PQ-approximate, as in the reference.

    Query dtype must match the index's build dtype (reference search is
    templated on the same T); f32 queries are additionally accepted against
    integer-built indexes since all scoring happens in f32 anyway.
    """
    q, q_dtype = _ingest_dataset(queries)
    expects(q_dtype in (index.dataset_dtype, "float32"),
            f"query dtype {q_dtype} != index dataset dtype "
            f"{index.dataset_dtype}")
    expects(q.ndim == 2 and q.shape[1] == index.dim, "query dim mismatch")
    expects(params.lut_dtype in _LUT_DTYPES,
            f"lut_dtype must be one of {list(_LUT_DTYPES)}")
    if q.shape[0] == 0:
        return empty_result(0, int(k), jnp.float32)
    n_probes = min(params.n_probes, index.n_lists)
    is_ip = index.metric == DistanceType.InnerProduct
    hoisted = (hoisted_lut_enabled() if params.hoisted_lut is None
               else bool(params.hoisted_lut))
    # list_adc feeds the compressed-LUT combine stage; the exact-f32 path
    # consumes its per-candidate contraction list_csum (docs/ivf_pq_adc.md)
    leaves = (index.centers, index.rotation, index.codebooks,
              index.list_codes, index.list_indices, index.phys_sizes,
              index.chunk_table, index.owner, index.list_adc,
              index.list_csum)
    # Bound the hoisted pipeline's per-batch combined-table transients to
    # ~128 MiB by shrinking the query batch (hoisted_batch_cap docstring
    # has the arithmetic); the legacy in-scan path only ever held one
    # (nq, pq_dim, 2^bits) tile and needs no cap.
    cap = hoisted_batch_cap(index, n_probes, params.lut_dtype, hoisted)
    if cap is not None:
        batch_size_query = min(batch_size_query, cap)
    # hoisted invariant statistic: coarse-center sq-norms once per search,
    # not once per query batch (distance.pairwise.metric_stats contract)
    center_sq = None if is_ip else _row_norms(index.centers)
    # kernel engine: env default resolved HERE, outside the jit/aot caches,
    # threaded as a static — "pallas" enables the LUT-in-VMEM scoring
    # kernel AND the blockwise select_k in the probe scan
    engine = _resolve_scan_engine(index.pq_dim, index.pq_bits)
    out_d, out_i = [], []
    # Batched dispatch over query blocks: each AOT/jit dispatch is ASYNC, so
    # successive batches overlap dispatch with execution — the TPU analogue
    # of the reference's stream-pool-batched kernel launches
    # (handle.hpp:88-130).  Each batch's in-flight outputs are recorded on
    # the next pool stream when the caller's handle carries one, so
    # ``sync_stream_pool``/``get_next_usable_stream`` own real work.
    pool = (handle is not None and handle.is_stream_pool_initialized())
    for bi, q0 in enumerate(range(0, q.shape[0], batch_size_query)):
        q1 = min(q0 + batch_size_query, q.shape[0])
        qb = q[q0:q1]
        # Shape-bucket the ragged tail batch (pad queries up to the next
        # power of two, slice results): serving workloads with varying
        # query counts would otherwise lower+compile one executable per
        # distinct residue — 20-40 s each on TPU.  Padding costs at most
        # 2× compute on the tail batch only.
        n_valid = qb.shape[0]
        bucket = min(_bucket_dim(n_valid), batch_size_query)
        if bucket != n_valid:
            qb = jnp.pad(qb, ((0, bucket - n_valid), (0, 0)))
        if is_ip:
            coarse = -(qb @ index.centers.T)
        else:
            # shared hoisted-stats L2 epilogue (default-precision matmul,
            # as before — coarse ranking tolerates it)
            coarse = _l2_expanded(qb, index.centers, sqrt=False,
                                  precision=None, yn=center_sq)
        _, probes = select_k(coarse, n_probes, select_min=True,
                             engine=engine)
        batch_fn = (_search_batch_aot if aot_dispatchable(qb, probes, leaves)
                    else _search_batch)
        d, i = batch_fn(qb, probes.astype(jnp.int32), leaves,
                        int(index.metric), int(k),
                        index.codebook_kind == CodebookKind.PER_CLUSTER,
                        params.lut_dtype,
                        params.internal_distance_dtype,
                        index.pq_bits, hoisted, -1, engine)
        if n_valid != qb.shape[0]:
            d, i = d[:n_valid], i[:n_valid]
        if pool:
            handle.get_next_usable_stream(bi).record((d, i))
        out_d.append(d)
        out_i.append(i)
    d = out_d[0] if len(out_d) == 1 else jnp.concatenate(out_d, axis=0)
    i = out_i[0] if len(out_i) == 1 else jnp.concatenate(out_i, axis=0)
    return d, i
