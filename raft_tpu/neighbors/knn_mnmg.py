"""Distributed (OPG) brute-force kNN over a sharded index.

The reference ecosystem's MNMG brute-force pattern (cuML's distributed
``brute_force_knn`` driven through raft comms): each rank holds a shard of
index rows, queries are replicated, every rank computes a local top-k,
and per-rank candidate sets are allgathered and merged with
``knn_merge_parts`` (reference neighbors/brute_force.cuh:76,144).  One
shard_map program: local scan + allgather over ICI + on-device merge.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.logger import traced
from raft_tpu.comms.comms import as_comms
from raft_tpu.cluster.kmeans_mnmg import _cached_program
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.neighbors.brute_force import knn, knn_merge_parts


def _search_program(comms, k: int, metric, metric_arg: float, rows_per: int):
    """Per-shard search body, cached per (comms, statics) so repeated
    searches reuse comms.run's identity-keyed jit cache instead of
    retracing per call (see kmeans_mnmg._fit_program's measurement)."""

    def local(xs, qs):
        d, i = knn(xs, qs, k, metric, metric_arg)
        rank = jax.lax.axis_index(comms.axis_name)
        i = i + (rank * rows_per).astype(i.dtype)   # local → global ids
        dd = comms.allgather(d)                     # (world, nq, k)
        ii = comms.allgather(i)
        return knn_merge_parts(dd, ii, k, metric=metric)

    return _cached_program(comms, ("knn", k, metric, metric_arg, rows_per),
                           lambda: local)


@traced("raft_tpu.neighbors.knn_mnmg")
def knn_mnmg(comms, index, queries, k: int,
             metric=DistanceType.L2SqrtExpanded, metric_arg: float = 2.0):
    """Exact kNN of *queries* among the rows of *index*, index sharded
    row-wise over the communicator's mesh (queries replicated).

    *comms* may be a Comms or a Handle carrying one.  Returns
    (distances [nq, k], global indices [nq, k]) — identical (up to ties)
    to single-device ``knn(index, queries, k)``.
    """
    from jax.sharding import PartitionSpec as P

    comms = as_comms(comms)
    # A split communicator's get_size()/get_rank() are group-local while
    # P(axis_name) shards over the FULL mesh axis — the id arithmetic
    # below would silently corrupt: require the full-axis communicator.
    expects(getattr(comms, "groups", None) is None,
            "knn_mnmg needs a full (non-split) communicator")
    x = jnp.asarray(index)
    q = jnp.asarray(queries)
    nranks = comms.get_size()
    n = x.shape[0]
    expects(n % nranks == 0,
            f"n ({n}) must be divisible by the number of ranks ({nranks}) — "
            "pad the index shard (OPG assumes equal parts)")
    rows_per = n // nranks
    expects(k <= rows_per,
            "k must not exceed rows per shard (each rank contributes k "
            "candidates)")
    # global ids are rank·rows_per + local in int32 inside the shard
    # program: bound the id space so a sharded index past 2^31 rows fails
    # loudly instead of silently wrapping (the single-device knn's
    # global_id_offset path promotes to int64; a shard_map program cannot
    # without x64, so enforce the bound here)
    expects(n - 1 <= 2**31 - 1,
            f"global id space ({n} rows) exceeds int32 — shard the index "
            "across more hosts or search parts explicitly via knn with "
            "global_id_offset (int64 ids under jax_enable_x64)")

    local = _search_program(comms, int(k), metric, float(metric_arg),
                            rows_per)
    x_sharded = comms.globalize(x, P(comms.axis_name, None))
    return comms.run(local, x_sharded, q,
                     in_specs=(P(comms.axis_name, None), P(None, None)),
                     out_specs=(P(None, None), P(None, None)))
