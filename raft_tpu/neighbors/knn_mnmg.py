"""Distributed (OPG) brute-force kNN over a sharded index.

The reference ecosystem's MNMG brute-force pattern (cuML's distributed
``brute_force_knn`` driven through raft comms): each rank holds a shard of
index rows, queries are replicated, every rank computes a local top-k,
and per-rank candidate sets are allgathered and merged with
``knn_merge_parts`` (reference neighbors/brute_force.cuh:76,144).  One
shard_map program: local scan + allgather over ICI + on-device merge.

Two collective topologies (docs/sharded_ann.md):

* ``partition="index"`` (default) — rows sharded, queries replicated.
  Distances and ids pack into ONE allgather payload (ann_mnmg's merge:
  int32 ids bitcast into the f32 lane) and merge on device with the
  L2Sqrt root DEFERRED past the merge — half the collective launches of
  the r1 two-allgather program and bit-identical top-k to single-device
  ``knn`` (the merge in shard order reproduces the sequential scan's
  stable tie order on squared distances).
* ``partition="queries"`` — the large-batch mode: queries shard, the
  index replicates, and each rank searches only its query slice.  Results
  are DISJOINT per rank, so the gather is the output sharding itself —
  ZERO collective launches inside the program (counter-assertable).  The
  right topology when nq dominates: same FLOPs, no (world, nq, k)
  exchange, at the cost of a replicated index (must fit one device).
  ``partition="auto"`` picks it when nq >= the index row count.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.logger import traced
from raft_tpu.comms.comms import as_comms
from raft_tpu.cluster.kmeans_mnmg import _cached_program
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.neighbors.brute_force import _knn_scan_chunked, _resolve_metric


def _search_program(comms, k: int, metric, metric_arg: float, rows_per: int,
                    tile: int):
    """Per-shard search body, cached per (comms, statics) so repeated
    searches reuse comms.run's identity-keyed jit cache instead of
    retracing per call (see kmeans_mnmg._fit_program's measurement)."""
    from raft_tpu.neighbors.ann_mnmg import _merge_one_allgather

    select_min = metric != DistanceType.InnerProduct
    defer = metric == DistanceType.L2SqrtExpanded
    scan_metric = DistanceType.L2Expanded if defer else metric

    def local(xs, qs):
        # chunked: keeps knn()'s bounded (4096, tile) per-step transient
        # inside the trace
        d, i = _knn_scan_chunked(xs, qs, k, scan_metric, metric_arg, tile,
                                 select_min)
        rank = jax.lax.axis_index(comms.axis_name)
        i = i + (rank * rows_per).astype(i.dtype)   # local → global ids
        d, i = _merge_one_allgather(comms, d, i, k, select_min)
        if defer:
            d = jnp.sqrt(d)  # knn's deferred-root epilogue, post-merge
        return d, i

    return _cached_program(comms, ("knn", k, metric, metric_arg, rows_per,
                                   tile), lambda: local)


def _query_sharded_program(comms, k: int, metric, metric_arg: float,
                           tile: int):
    """Query-sharded body: each rank runs the UNMODIFIED single-device
    scan (internal deferred root and all) on its query slice against the
    full index — no rank arithmetic, no collective."""
    select_min = metric != DistanceType.InnerProduct

    def local(xs, qs):
        return _knn_scan_chunked(xs, qs, k, metric, metric_arg, tile,
                                 select_min)

    return _cached_program(comms, ("knn_qs", k, metric, metric_arg, tile),
                           lambda: local)


@traced("raft_tpu.neighbors.knn_mnmg")
def knn_mnmg(comms, index, queries, k: int,
             metric=DistanceType.L2SqrtExpanded, metric_arg: float = 2.0,
             partition: str = "index"):
    """Exact kNN of *queries* among the rows of *index* across the
    communicator's mesh.

    *partition* selects the sharding topology: ``"index"`` (rows sharded,
    queries replicated — the OPG default, one allgather), ``"queries"``
    (queries sharded, index replicated — zero collectives, for
    nq-dominated batches), or ``"auto"`` (queries when nq >= n).

    *comms* may be a Comms or a Handle carrying one.  Returns
    (distances [nq, k], global indices [nq, k]) — identical (up to ties)
    to single-device ``knn(index, queries, k)``.
    """
    from jax.sharding import PartitionSpec as P

    comms = as_comms(comms)
    # A split communicator's get_size()/get_rank() are group-local while
    # P(axis_name) shards over the FULL mesh axis — the id arithmetic
    # below would silently corrupt: require the full-axis communicator.
    expects(getattr(comms, "groups", None) is None,
            "knn_mnmg needs a full (non-split) communicator")
    metric = _resolve_metric(metric)
    x = jnp.asarray(index)
    q = jnp.asarray(queries)
    # the shard programs call the scan impl directly, so the validation
    # knn() used to provide must happen here (clean errors at the caller,
    # not shape failures deep inside shard_map)
    expects(x.ndim == 2 and q.ndim == 2, "inputs must be 2-d")
    expects(x.shape[1] == q.shape[1], "feature dim mismatch")
    nranks = comms.get_size()
    n = x.shape[0]
    nq = q.shape[0]
    expects(partition in ("index", "queries", "auto"),
            f"unknown partition {partition!r}")
    if partition == "auto":
        # nq-dominated batches: the (world, nq, k) exchange outgrows the
        # per-shard capacity win — split the queries instead
        partition = "queries" if nq >= n else "index"

    if partition == "queries":
        expects(1 <= k <= n, f"k={k} must be in [1, n_index={n}]")
        # pad the query axis so every rank gets an equal bucketed slice
        # (one executable per per-rank bucket, not per nq residue)
        from raft_tpu.core.aot import _bucket_dim

        per = _bucket_dim(-(-nq // nranks))
        n_pad = per * nranks
        qp = jnp.pad(q, ((0, n_pad - nq), (0, 0))) if n_pad != nq else q
        local = _query_sharded_program(comms, int(k), metric,
                                       float(metric_arg),
                                       int(min(16384, n)))
        d, i = comms.run(local, x, qp,
                         in_specs=(P(None, None), P(comms.axis_name, None)),
                         out_specs=(P(comms.axis_name, None),
                                    P(comms.axis_name, None)))
        return d[:nq], i[:nq]

    expects(n % nranks == 0,
            f"n ({n}) must be divisible by the number of ranks ({nranks}) — "
            "pad the index shard (OPG assumes equal parts), or use "
            "ann_mnmg.shard_brute_force which pads with sentinel rows")
    rows_per = n // nranks
    expects(k <= rows_per,
            "k must not exceed rows per shard (each rank contributes k "
            "candidates)")
    # global ids are rank·rows_per + local in int32 inside the shard
    # program: bound the id space so a sharded index past 2^31 rows fails
    # loudly instead of silently wrapping (the single-device knn's
    # global_id_offset path promotes to int64; a shard_map program cannot
    # without x64, so enforce the bound here)
    expects(n - 1 <= 2**31 - 1,
            f"global id space ({n} rows) exceeds int32 — shard the index "
            "across more hosts or search parts explicitly via knn with "
            "global_id_offset (int64 ids under jax_enable_x64)")

    local = _search_program(comms, int(k), metric, float(metric_arg),
                            rows_per, int(min(16384, rows_per)))
    x_sharded = comms.globalize(x, P(comms.axis_name, None))
    return comms.run(local, x_sharded, q,
                     in_specs=(P(comms.axis_name, None), P(None, None)),
                     out_specs=(P(None, None), P(None, None)))
