"""Mutable fleet index: delta segment + tombstones + background compaction.

The serving spine (warmed zero-retrace dispatch, admission/supervision,
autotuning) serves an APPEND-ONLY index; a production corpus churns.
:class:`MutableIndex` wraps the triple (main index, delta segment,
tombstone set) and absorbs writes at O(delta) cost while the main —
single-device family Index or the sharded fleet — keeps serving reads
through the UNCHANGED warmed executables:

* **Deletes** set bits in a fixed-capacity device bitmap keyed by row id
  (``_common.tombstone_hit``), grown in power-of-two word buckets
  (:func:`_tomb_words` — the serve signature ladder stays closed).  The
  mask is applied INSIDE the fixed-shape probe-scan tile program
  (``_common.scan_probe_lists``): dead rows score the sentinel exactly
  like padding slots, so a mutation changes bitmap VALUES, never the
  lowered HLO.
* **Upserts** tombstone the old row and append into a small
  single-device delta index that shares the main's trained model
  (centers / rotation / codebooks — one label space), built with the
  existing tiled ``_build`` machinery via ``extend(in_place=True)`` —
  O(n_new) per batch, zero compiles on the warm read path.
* **Reads** search main ∪ delta: both scanned through the family's
  unchanged fixed-shape programs (tombstones masked in-scan), folded
  with the on-device ``merge_sorted_parts`` merge — main is part 0, so
  main wins ties (the ONE documented tie-order divergence vs a
  from-scratch rebuild of the same live rows; at full probe coverage
  every returned distance is bit-identical).
* **Compaction** (:class:`Compactor`, a supervise.py-style seeded
  daemon) rebuilds main ∪ delta minus tombstones through the family
  ``build`` / ``build_sharded`` OFF the request path past a
  delta-fraction or tombstone-fraction threshold, chases the write
  journal, pre-warms every recorded serve signature at the new shapes,
  swaps the core atomically, and promotes through
  ``ServeEngine.refresh`` — zero-compile post-swap steady state, zero
  failed requests during the swap (both counter-asserted by
  tests/bench).

Consistency model: a search dispatch snapshots (main, delta, tombstones)
under the write lock, so every read sees a single write-ordered state;
in-flight reads during a compaction swap finish against the OLD core
(still warm, still consistent) and the next dispatch sees the new one.
Writes briefly serialize with reads (the lock also makes the donated
in-place delta append safe against a racing dispatch).

State-mutation discipline (the ``mutation-discipline`` analysis rule):
tombstone bitmaps and delta blocks are mutated ONLY through
:class:`MutableIndex` methods — raw writes elsewhere are findings.

docs/mutable_index.md has the full design note.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import telemetry
from raft_tpu.analysis.registry import hlo_program
from raft_tpu.core.aot import _bucket_dim, aot, dispatch_device
from raft_tpu.core.error import expects
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.matrix.select_k import (_merge_aot, merge_sorted_parts,
                                      merge_sorted_runs)
from raft_tpu.neighbors import ivf_flat, ivf_pq

#: mutable-index lifecycle events (upsert/delete batches and rows,
#: delta rebuilds, signature rewarms, compaction errors)
mutable_counters = telemetry.legacy_counter(
    "raft_tpu_mutable_events_total",
    "Mutable-index lifecycle events (upsert/delete batches + rows, delta "
    "dedup rebuilds, write-path signature rewarms, compaction errors)")

#: the four headline metrics the ISSUE names
_delta_rows_gauge = telemetry.gauge(
    "raft_tpu_mutable_delta_rows",
    "Rows currently live in the write-optimized delta segment")
_tombstones_gauge = telemetry.gauge(
    "raft_tpu_mutable_tombstones",
    "Row ids currently tombstoned (main + delta)")
_compactions_counter = telemetry.counter(
    "raft_tpu_mutable_compactions",
    "Background compactions completed (delta + tombstones folded back "
    "into a freshly built main)")
compaction_seconds = telemetry.histogram(
    "raft_tpu_mutable_compaction_seconds",
    "Wall seconds per compaction (rebuild + journal chase + rewarm + "
    "swap)")


def _tomb_words(max_id: int) -> int:
    """Tombstone-bitmap word capacity for ids up to *max_id*: the
    power-of-two bucket ladder (``_bucket_dim``), so bitmap growth mints
    at most O(log max_id) distinct serve signatures over an index's whole
    life — the delta/tombstone analogue of the query-bucket ladder."""
    need = (int(max_id) + 32) // 32
    return _bucket_dim(max(need, 1))


# ---------------------------------------------------------------------------
# the delta-merged search program


def _family_scan(q, leaves, kind: str, scan_metric: int, k: int,
                 n_probes: int, per_cluster: bool, lut_dtype_name: str,
                 int_dtype_name: str, pq_bits: int, hoisted: bool,
                 engine: str, tombstones):
    """One segment (main or delta) through the family's UNCHANGED search
    program, tombstone mask threaded into the scan."""
    if kind == "ivf_flat":
        return ivf_flat._search_batch_impl(q, leaves, scan_metric, k,
                                           n_probes, False, -1, engine,
                                           tombstones)
    return ivf_pq._full_search_impl(q, leaves, scan_metric, k, n_probes,
                                    per_cluster, lut_dtype_name,
                                    int_dtype_name, pq_bits, hoisted, -1,
                                    engine, tombstones)


def _merged_search_impl(q, main_leaves, delta_leaves, tomb_main, tomb_delta,
                        kind: str, metric_val: int, k: int, n_probes: int,
                        per_cluster: bool, lut_dtype_name: str,
                        int_dtype_name: str, pq_bits: int, hoisted: bool,
                        engine: str):
    """main ∪ delta as ONE program: two fixed-shape family scans (each
    masked by its segment's tombstone bitmap) folded by the on-device
    ``merge_sorted_parts`` — main is part 0, so main wins duplicated
    distances (the documented tie order).  The L2Sqrt root is deferred
    PAST the merge (the ann_mnmg cross-shard discipline), keeping the
    fold's tie comparisons in the exact squared domain.

    ``delta_leaves=None`` (with ``tomb_delta=None``) is the delta-free
    variant — a DISTINCT AOT signature (None flattens to zero leaves),
    same function, so the delete-only serving state stays on this one
    executable cache too.
    """
    sqrt = metric_val == int(DistanceType.L2SqrtExpanded)
    is_ip = metric_val == int(DistanceType.InnerProduct)
    scan_metric = (int(DistanceType.L2Expanded) if sqrt else metric_val)
    d, i = _family_scan(q, main_leaves, kind, scan_metric, k, n_probes,
                        per_cluster, lut_dtype_name, int_dtype_name,
                        pq_bits, hoisted, engine, tomb_main)
    if delta_leaves is not None:
        dd, di = _family_scan(q, delta_leaves, kind, scan_metric, k,
                              n_probes, per_cluster, lut_dtype_name,
                              int_dtype_name, pq_bits, hoisted, engine,
                              tomb_delta)
        d, i = merge_sorted_parts(jnp.stack([d, dd]), jnp.stack([i, di]),
                                  k=k, select_min=not is_ip)
    if sqrt:
        d = jnp.sqrt(jnp.maximum(d, 0))
    return d, i


_MERGED_STATICS = (5, 6, 7, 8, 9, 10, 11, 12, 13, 14)
_merged_jit = jax.jit(_merged_search_impl, static_argnums=_MERGED_STATICS)
_merged_aot = aot(_merged_search_impl, static_argnums=_MERGED_STATICS)


# ---------------------------------------------------------------------------
# core state (swapped wholesale by compaction)


class _Core:
    """One consistent (main, delta, tombstones) snapshot.  Compaction
    builds a NEW core off the request path and swaps the reference; the
    old core keeps serving in-flight reads unchanged."""

    __slots__ = (
        "kind", "sharded", "main", "delta", "tomb_main_bits",
        "tomb_delta_bits", "tomb_main_mesh", "words_main", "words_delta",
        "n_words", "main_ids", "main_dead", "delta_live", "delta_dead",
        "store", "searcher_cache")

    def __init__(self, kind, sharded, main, main_ids, store, n_words):
        self.kind = kind
        self.sharded = sharded
        self.main = main
        self.delta = None                       # family Index, lazily built
        self.n_words = int(n_words)
        self.words_main = np.zeros((self.n_words,), np.uint32)
        self.words_delta = np.zeros((self.n_words,), np.uint32)
        self.tomb_main_bits = None              # device mirrors, see _push
        self.tomb_delta_bits = None
        self.tomb_main_mesh = None              # replicated copy (sharded)
        self.main_ids = np.unique(np.asarray(main_ids, np.int64))
        self.main_dead = set()                  # ids tombstoned in main
        self.delta_live = {}                    # id -> True (insert order)
        self.delta_dead = set()                 # ids dead but still packed
        self.store = store                      # id -> host row (np 1-D)
        self.searcher_cache = {}                # (k, params) -> main searcher

    @property
    def live_count(self) -> int:
        return (self.main_ids.size - len(self.main_dead)
                + len(self.delta_live))

    @property
    def delta_rows(self) -> int:
        return len(self.delta_live)

    @property
    def tombstones(self) -> int:
        return len(self.main_dead) + len(self.delta_dead)


def _main_leaves(core: _Core):
    m = core.main
    if core.kind == "ivf_flat":
        return (m.centers, m.list_data, m.list_indices, m.phys_sizes,
                m.chunk_table)
    return (m.centers, m.rotation, m.codebooks, m.list_codes,
            m.list_indices, m.phys_sizes, m.chunk_table, m.owner,
            m.list_adc, m.list_csum)


def _delta_leaves(core: _Core):
    d = core.delta
    if d is None:
        return None
    if core.kind == "ivf_flat":
        return (d.centers, d.list_data, d.list_indices, d.phys_sizes,
                d.chunk_table)
    return (d.centers, d.rotation, d.codebooks, d.list_codes,
            d.list_indices, d.phys_sizes, d.chunk_table, d.owner,
            d.list_adc, d.list_csum)


def _leaf_shapes(core: _Core):
    """The signature-relevant shape tuple: a write that leaves this
    unchanged cannot mint a new executable."""
    dl = _delta_leaves(core)
    return (core.n_words,
            None if dl is None else tuple(a.shape for a in dl))


# ---------------------------------------------------------------------------
# the mutable container


class MutableIndex:
    """(main index, delta segment, tombstone set) with zero-stall serving.

    *main* is a built family Index (``ivf_flat`` / ``ivf_pq``) or an
    ``ann_mnmg.ShardedIndex`` of one of those kinds; *dataset* / *ids*
    are the rows it was built from — retained host-side (the tiering
    refine-store precedent) so compaction (and, for the lossy PQ codes,
    ANY rebuild) can re-encode live rows exactly.  *build_params* is the
    family IndexParams compaction rebuilds with.

    All state mutation goes through :meth:`upsert` / :meth:`delete` /
    :meth:`compact` (the ``mutation-discipline`` analysis rule enforces
    this repo-wide).  Reads go through :func:`search` or a
    :meth:`searcher` — the object ``serve.ServeEngine``'s
    ``_MutableBackend`` warms and dispatches.
    """

    def __init__(self, main, dataset, ids=None, *, build_params=None,
                 comms=None):
        from raft_tpu.neighbors import ann_mnmg

        if isinstance(main, ann_mnmg.ShardedIndex):
            kind, sharded = main.kind, True
            expects(kind in ("ivf_flat", "ivf_pq"),
                    "MutableIndex needs an IVF kind (brute_force has no "
                    "id-carrying probe scan to mask)")
            self._comms = main.comms
        else:
            sharded = False
            if isinstance(main, ivf_flat.Index):
                kind = "ivf_flat"
            else:
                expects(isinstance(main, ivf_pq.Index),
                        f"unsupported main index type {type(main)!r}")
                kind = "ivf_pq"
            self._comms = comms
        x = np.asarray(dataset)
        expects(x.ndim == 2, "dataset must be (n, dim)")
        if ids is None:
            ids = np.arange(x.shape[0], dtype=np.int32)
        ids = np.asarray(ids, np.int64)
        expects(ids.shape == (x.shape[0],), "ids must be (n,)")
        expects(ids.size == np.unique(ids).size, "ids must be unique")
        expects(ids.size == 0 or int(ids.min()) >= 0,
                "ids must be non-negative")
        store = {int(j): x[r] for r, j in enumerate(ids)}
        max_id = int(ids.max()) if ids.size else 0
        self._mut_core = _Core(kind, sharded, main, ids, store,
                               _tomb_words(max_id))
        self.build_params = build_params
        self._lock = threading.RLock()
        self._compact_lock = threading.Lock()
        self._journal = None
        self._searchers = {}
        self._push_tombstones(self._mut_core)

    # -- read-side surface -------------------------------------------------

    @property
    def kind(self) -> str:
        return self._mut_core.kind

    @property
    def dim(self) -> int:
        core = self._mut_core
        return int(core.main.dim)

    @property
    def metric(self) -> DistanceType:
        core = self._mut_core
        if core.sharded:
            return DistanceType(core.main.aux["metric"])
        return core.main.metric

    @property
    def size(self) -> int:
        """LIVE row count (main + delta minus tombstones)."""
        return self._mut_core.live_count

    @property
    def delta_rows(self) -> int:
        return self._mut_core.delta_rows

    @property
    def tombstone_count(self) -> int:
        return self._mut_core.tombstones

    def delta_fraction(self) -> float:
        core = self._mut_core
        return core.delta_rows / max(core.live_count, 1)

    def tombstone_fraction(self) -> float:
        core = self._mut_core
        denom = core.main_ids.size + len(core.delta_live) \
            + len(core.delta_dead)
        return core.tombstones / max(denom, 1)

    def live_rows(self) -> Tuple[np.ndarray, np.ndarray]:
        """(vectors, ids) of every live row, main order then delta
        insertion order — the rebuild-oracle input."""
        with self._lock:
            return self._live_rows_locked(self._mut_core)

    def to_index(self):
        """From-scratch rebuild of the live rows with *build_params* —
        the oracle tests/bench compare against (retrains the coarse
        model, so probe sets differ below full probe coverage)."""
        expects(self.build_params is not None,
                "to_index()/compact() need build_params")
        x, ids = self.live_rows()
        family = ivf_flat if self.kind == "ivf_flat" else ivf_pq
        if self._mut_core.sharded:
            return family.build_sharded(self.build_params, x, self._comms,
                                        ids=jnp.asarray(ids, jnp.int32))
        return family.build(self.build_params, x,
                            ids=jnp.asarray(ids, jnp.int32))

    def searcher(self, k: int, params=None) -> "MutableSearcher":
        """Get-or-create the warmed serving searcher for (k, params)."""
        key = (int(k), repr(params))
        with self._lock:
            s = self._searchers.get(key)
            if s is None:
                s = MutableSearcher(self, int(k), params)
                self._searchers[key] = s
            return s

    # -- write-side surface ------------------------------------------------

    def delete(self, ids) -> int:
        """Tombstone *ids*.  Unknown / already-dead ids are a no-op.
        Returns the number of rows newly tombstoned.  O(batch) host
        bookkeeping + one O(n_words) bitmap upload — never a recompile
        (bitmap capacity already covers every live id)."""
        ids = np.atleast_1d(np.asarray(ids, np.int64))
        with self._lock:
            if self._journal is not None:
                self._journal.append(("delete", ids.copy()))
            n = self._delete_core(self._mut_core, ids)
            self._record_state(self._mut_core)
            return n

    def upsert(self, x, ids) -> None:
        """Insert-or-replace rows: tombstone any old row with these ids
        (in main OR delta) and append the new rows into the delta via the
        family's tiled ``extend(in_place=True)`` — O(n_new) per batch.
        Re-upserting an id still physically packed in the delta triggers
        an O(delta) delta dedup rebuild first (rare; the delta stays
        small by construction).  When a batch changes the delta/bitmap
        SHAPES, the write path re-warms every recorded serve signature
        before returning — reads stay zero-compile always."""
        x = np.asarray(x)
        expects(x.ndim == 2 and x.shape[1] == self.dim,
                "upsert rows must be (n, dim)")
        ids = np.asarray(ids, np.int64)
        expects(ids.shape == (x.shape[0],), "ids must be (n,)")
        expects(ids.size == np.unique(ids).size,
                "upsert ids must be unique within the batch")
        expects(ids.size == 0 or int(ids.min()) >= 0,
                "ids must be non-negative")
        with self._lock:
            if self._journal is not None:
                self._journal.append(("upsert", x.copy(), ids.copy()))
            before = _leaf_shapes(self._mut_core)
            self._upsert_core(self._mut_core, x, ids)
            if _leaf_shapes(self._mut_core) != before:
                self._rewarm_locked()
            self._record_state(self._mut_core)

    # -- internal write ops (operate on an EXPLICIT core: the public
    # methods pass the live one, compaction's journal replay the new one)

    def _delete_core(self, core: _Core, ids) -> int:
        n = 0
        main_member = np.isin(ids, core.main_ids)
        for j, in_main in zip(ids.tolist(), main_member.tolist()):
            if j in core.delta_live:
                del core.delta_live[j]
                core.delta_dead.add(j)
                core.words_delta[j >> 5] |= np.uint32(1 << (j & 31))
                n += 1
            elif in_main and j not in core.main_dead:
                core.main_dead.add(j)
                core.words_main[j >> 5] |= np.uint32(1 << (j & 31))
                n += 1
        if n:
            self._push_tombstones(core)
        mutable_counters.inc("deletes")
        mutable_counters.inc("delete_rows", n)
        return n

    def _upsert_core(self, core: _Core, x, ids) -> None:
        max_id = int(ids.max()) if ids.size else 0
        words = _tomb_words(max(max_id, core.n_words * 32 - 1))
        if words != core.n_words:
            self._grow_tombstones(core, words)
        stale = [j for j in ids.tolist()
                 if j in core.delta_live or j in core.delta_dead]
        if stale:
            self._rebuild_delta(core, exclude=set(stale))
        # supersede main rows
        main_hits = ids[np.isin(ids, core.main_ids)]
        dirty = False
        for j in main_hits.tolist():
            if j not in core.main_dead:
                core.main_dead.add(j)
                core.words_main[j >> 5] |= np.uint32(1 << (j & 31))
                dirty = True
        if dirty:
            self._push_tombstones(core)
        self._delta_append(core, x, ids)
        for r, j in enumerate(ids.tolist()):
            core.store[j] = x[r]
            core.delta_live[j] = True
        mutable_counters.inc("upserts")
        mutable_counters.inc("upsert_rows", int(ids.size))

    def _delta_append(self, core: _Core, x, ids) -> None:
        family = ivf_flat if core.kind == "ivf_flat" else ivf_pq
        if core.delta is None:
            core.delta = self._empty_delta(core)
        core.delta = family.extend(core.delta, x,
                                   jnp.asarray(ids, jnp.int32),
                                   in_place=True)

    def _rebuild_delta(self, core: _Core, exclude=()) -> None:
        """Repack the delta from its LIVE rows minus *exclude* — the
        O(delta) slow path a duplicate-id upsert takes (an append-only
        segment cannot mask one of two same-id rows by id alone).  Clears
        the delta tombstone bitmap: dead rows are physically gone."""
        keep = [j for j in core.delta_live if j not in exclude]
        core.words_delta[:] = 0
        core.delta_dead.clear()
        core.delta = None
        old_live = core.delta_live
        core.delta_live = {}
        if keep:
            x = np.stack([core.store[j] for j in keep])
            self._delta_append(core, x, np.asarray(keep, np.int64))
            for j in keep:
                core.delta_live[j] = True
        else:
            del old_live
        self._push_tombstones(core)
        mutable_counters.inc("delta_rebuilds")

    def _grow_tombstones(self, core: _Core, n_words: int) -> None:
        grown = np.zeros((n_words,), np.uint32)
        grown[:core.n_words] = core.words_main
        core.words_main = grown
        grown_d = np.zeros((n_words,), np.uint32)
        grown_d[:core.n_words] = core.words_delta
        core.words_delta = grown_d
        core.n_words = int(n_words)
        self._push_tombstones(core)

    def _push_tombstones(self, core: _Core) -> None:
        """Publish the host bitmaps to the device(s): one O(n_words)
        upload per write batch (words, not rows).  Same shapes → same
        warmed signatures; only the values change."""
        dev = dispatch_device()
        core.tomb_main_bits = jax.device_put(core.words_main, dev)
        core.tomb_delta_bits = jax.device_put(core.words_delta, dev)
        if core.sharded:
            from jax.sharding import PartitionSpec as P

            core.tomb_main_mesh = self._comms.globalize(
                jnp.asarray(core.words_main), P())

    def _empty_delta(self, core: _Core):
        """A zero-row family Index sharing the main's trained model (one
        label space — delta rows land in the same inverted lists a full
        rebuild would put them in).  ``extend`` from here takes its
        fresh-pack path, so the whole delta lifecycle rides the tiled
        ``_build`` machinery."""
        if core.sharded:
            rep = core.main.replicated
            dev = dispatch_device()
            model = tuple(jax.device_put(np.asarray(a), dev) for a in rep)
            aux = core.main.aux
            n_lists = int(aux["n_lists"])
            metric = DistanceType(aux["metric"])
            dim = int(core.main.dim)
            if core.kind == "ivf_flat":
                data_dtype = core.main.stacked[0].dtype
                return ivf_flat.Index(
                    centers=model[0],
                    list_data=jnp.zeros((1, 1, dim), data_dtype),
                    list_indices=jnp.full((1, 1), -1, jnp.int32),
                    list_sizes=jnp.zeros((n_lists,), jnp.int32),
                    phys_sizes=jnp.zeros((1,), jnp.int32),
                    chunk_table=jnp.zeros((n_lists, 1), jnp.int32),
                    metric=metric, adaptive_centers=False)
            codes_w = int(core.main.stacked[0].shape[-1])
            return ivf_pq.Index(
                centers=model[0], rotation=model[1], codebooks=model[2],
                list_codes=jnp.zeros((1, 1, codes_w),
                                     core.main.stacked[0].dtype),
                list_indices=jnp.full((1, 1), -1, jnp.int32),
                list_sizes=jnp.zeros((n_lists,), jnp.int32),
                phys_sizes=jnp.zeros((1,), jnp.int32),
                chunk_table=jnp.zeros((n_lists, 1), jnp.int32),
                owner=jnp.zeros((1,), jnp.int32),
                list_adc=model[3],
                list_csum=jnp.zeros((1, 1),
                                    core.main.stacked[5].dtype),
                metric=metric,
                codebook_kind=ivf_pq.CodebookKind(aux["codebook_kind"]),
                pq_bits=int(aux["pq_bits"]),
                dataset_dtype=aux["dataset_dtype"])
        m = core.main
        if core.kind == "ivf_flat":
            return ivf_flat.Index(
                centers=m.centers,
                list_data=jnp.zeros((1, 1, m.dim), m.list_data.dtype),
                list_indices=jnp.full((1, 1), -1, jnp.int32),
                list_sizes=jnp.zeros((m.n_lists,), jnp.int32),
                phys_sizes=jnp.zeros((1,), jnp.int32),
                chunk_table=jnp.zeros((m.n_lists, 1), jnp.int32),
                metric=m.metric, adaptive_centers=False)
        return ivf_pq.Index(
            centers=m.centers, rotation=m.rotation, codebooks=m.codebooks,
            list_codes=jnp.zeros((1, 1, m.list_codes.shape[-1]),
                                 m.list_codes.dtype),
            list_indices=jnp.full((1, 1), -1, jnp.int32),
            list_sizes=jnp.zeros((m.n_lists,), jnp.int32),
            phys_sizes=jnp.zeros((1,), jnp.int32),
            chunk_table=jnp.zeros((m.n_lists, 1), jnp.int32),
            owner=jnp.zeros((1,), jnp.int32),
            list_adc=m.list_adc,
            list_csum=jnp.zeros((1, 1), m.list_csum.dtype),
            metric=m.metric, codebook_kind=m.codebook_kind,
            pq_bits=m.pq_bits, dataset_dtype=m.dataset_dtype)

    def _live_rows_locked(self, core: _Core):
        ids = [int(j) for j in core.main_ids.tolist()
               if j not in core.main_dead]
        ids.extend(core.delta_live)
        if not ids:
            return (np.zeros((0, self.dim), np.float32),
                    np.zeros((0,), np.int64))
        return np.stack([core.store[j] for j in ids]), \
            np.asarray(ids, np.int64)

    def _rewarm_locked(self) -> None:
        """A write changed the delta/bitmap shapes: re-lower every
        recorded serve signature at the new shapes BEFORE the write
        returns — compiles ride the write path, reads stay zero-compile.
        Amortized: shapes change only on delta chunk growth / bitmap
        bucket growth, both power-of-two-laddered."""
        for s in self._searchers.values():
            s._rewarm()
        mutable_counters.inc("rewarms")

    def _record_state(self, core: _Core) -> None:
        _delta_rows_gauge.set(core.delta_rows)
        _tombstones_gauge.set(core.tombstones)

    # -- compaction --------------------------------------------------------

    def compact_due(self, delta_fraction: float = 0.10,
                    tomb_fraction: float = 0.10) -> bool:
        return (self.delta_fraction() >= delta_fraction
                or self.tombstone_fraction() >= tomb_fraction)

    def compact(self, engine=None) -> None:
        """Rebuild main ∪ delta minus tombstones OFF the request path and
        swap it in: snapshot live rows under the lock, family
        ``build`` / ``build_sharded`` off-lock (old core keeps serving),
        chase the write journal, pre-warm every recorded serve signature
        at the new shapes, swap the core atomically, and — when *engine*
        is given — promote through ``ServeEngine.refresh`` (the ONE
        sanctioned backend-swap door; never a raw backend write)."""
        expects(self.build_params is not None,
                "compact() needs build_params")
        family = ivf_flat if self.kind == "ivf_flat" else ivf_pq
        with self._compact_lock:
            t0 = time.perf_counter()
            with self._lock:
                self._journal = []
                core = self._mut_core
                x, ids = self._live_rows_locked(core)
            try:
                if core.sharded:
                    main = family.build_sharded(
                        self.build_params, x, self._comms,
                        ids=jnp.asarray(ids, jnp.int32))
                else:
                    main = family.build(self.build_params, x,
                                        ids=jnp.asarray(ids, jnp.int32))
                store = {int(j): x[r] for r, j in enumerate(ids)}
                max_id = int(ids.max()) if ids.size else 0
                new_core = _Core(core.kind, core.sharded, main, ids, store,
                                 _tomb_words(max_id))
                self._push_tombstones(new_core)
                # chase the journal off-lock until the tail is short
                applied = 0
                while True:
                    with self._lock:
                        pending = list(self._journal[applied:])
                    if len(pending) <= 4:
                        break
                    for op in pending:
                        self._apply_op(new_core, op)
                    applied += len(pending)
                # pre-warm the new shapes off the read path (old core
                # still serving; warming only grows the AOT caches)
                self._warm_for_core(new_core)
                with self._lock:
                    for op in self._journal[applied:]:
                        self._apply_op(new_core, op)
                    self._journal = None
                    self._mut_core = new_core
                    # tail replay rarely changes shapes; cache hits if not
                    self._warm_for_core(new_core)
                    self._record_state(new_core)
            except BaseException:
                with self._lock:
                    self._journal = None
                raise
            _compactions_counter.inc(1)
            compaction_seconds.observe(time.perf_counter() - t0)
        if engine is not None:
            engine.refresh(self)

    def _apply_op(self, core: _Core, op) -> None:
        if op[0] == "delete":
            self._delete_core(core, op[1])
        else:
            self._upsert_core(core, op[1], op[2])

    def _warm_for_core(self, core: _Core) -> None:
        for s in list(self._searchers.values()):
            s._warm_core(core)


# ---------------------------------------------------------------------------
# the serving searcher


class MutableSearcher:
    """Zero-retrace dispatcher for one (MutableIndex, k, params) serving
    key — the ``_MutableBackend`` delegate.  Single-device mains dispatch
    the ONE delta-merged program (:func:`_merged_search_impl`); sharded
    mains dispatch the masked ``ann_mnmg.ShardedSearcher`` variant for
    main, the same merged program (delta-only signature) for the delta,
    and fold the two warmed sorted runs with ``merge_sorted_runs`` (main
    is run *a* — main wins ties, matching the single-device fold)."""

    def __init__(self, mutable: MutableIndex, k: int, params=None):
        expects(k >= 1, "k must be >= 1")
        self.mutable = mutable
        core = mutable._mut_core
        self.kind = core.kind
        self.k = int(k)
        self.name = f"mutable_{self.kind}"
        self.metric = mutable.metric
        self.dim = int(mutable.dim)
        if core.sharded:
            aux = core.main.aux
            n_lists = int(aux["n_lists"])
        else:
            n_lists = int(core.main.n_lists)
        if self.kind == "ivf_flat":
            self.params = params or ivf_flat.SearchParams()
            self.per_cluster = False
            self.lut_dtype = "float32"
            self.int_dtype = "float32"
            self.pq_bits = 0
            self.hoisted = False
            from raft_tpu.kernels.engine import resolve_engine

            self.engine = resolve_engine("select_k", dtype=jnp.float32)
        else:
            self.params = params or ivf_pq.SearchParams()
            expects(self.params.lut_dtype in ivf_pq._LUT_DTYPES,
                    f"lut_dtype must be one of {list(ivf_pq._LUT_DTYPES)}")
            if core.sharded:
                ck = int(core.main.aux["codebook_kind"])
                self.pq_bits = int(core.main.aux["pq_bits"])
                pq_dim = int(core.main.aux["pq_dim"])
            else:
                ck = int(core.main.codebook_kind)
                self.pq_bits = int(core.main.pq_bits)
                pq_dim = int(core.main.pq_dim)
            self.per_cluster = ck == int(ivf_pq.CodebookKind.PER_CLUSTER)
            self.lut_dtype = self.params.lut_dtype
            self.int_dtype = self.params.internal_distance_dtype
            self.hoisted = (ivf_pq.hoisted_lut_enabled()
                            if self.params.hoisted_lut is None
                            else bool(self.params.hoisted_lut))
            self.engine = ivf_pq._resolve_scan_engine(pq_dim, self.pq_bits)
        self.n_probes = int(min(self.params.n_probes, n_lists))
        self.select_min = self.metric != DistanceType.InnerProduct
        if core.sharded:
            self._main_for(core)
        # _backend_fn cost attribution reads the dispatched fn here
        self.fn = _merged_aot
        self._warmed = set()

    def _main_for(self, core: _Core):
        """The masked ``ShardedSearcher`` over *core*'s main — its warmed
        ``fn`` captures the shard blocks, so it's cached ON the core
        (compaction's new core gets its own, warmed off the read path
        before the swap; the old core keeps serving through its own)."""
        from raft_tpu.neighbors import ann_mnmg

        key = (self.k, repr(self.params))
        s = core.searcher_cache.get(key)
        if s is None:
            s = ann_mnmg.ShardedSearcher(core.main, self.k, self.params,
                                         masked=True)
            core.searcher_cache[key] = s
        return s

    def _statics(self):
        return (self.kind, int(self.metric), self.k, self.n_probes,
                self.per_cluster, self.lut_dtype, self.int_dtype,
                self.pq_bits, self.hoisted, self.engine)

    # -- warmup ------------------------------------------------------------

    def warm(self, bucket: int, dtype) -> None:
        """Pre-lower BOTH serving variants (delta-free and delta-merged)
        at the current core shapes, and record (bucket, dtype): any write
        that changes the delta/bitmap shapes re-lowers every recorded
        signature (``MutableIndex._rewarm_locked``) before the write
        returns — the zero-compile read contract under mutation."""
        self._warmed.add((int(bucket), jnp.dtype(dtype).name))
        self._warm_one(int(bucket), jnp.dtype(dtype).name,
                       self.mutable._mut_core)

    def _rewarm(self) -> None:
        self._warm_core(self.mutable._mut_core)

    def _warm_core(self, core: _Core) -> None:
        for bucket, dtype in self._warmed:
            self._warm_one(bucket, dtype, core)

    def _warm_one(self, bucket: int, dtype: str, core: _Core) -> None:
        spec = lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)  # noqa: E731
        qspec = jax.ShapeDtypeStruct((bucket, self.dim), jnp.dtype(dtype))
        tm = spec(core.tomb_main_bits)
        dl = _delta_leaves(core)
        dspecs = None if dl is None else jax.tree_util.tree_map(spec, dl)
        if core.sharded:
            self._main_for(core).warm(bucket, jnp.dtype(dtype),
                                      core.n_words)
            if dl is not None:
                _merged_aot.compiled(qspec, dspecs, None,
                                     spec(core.tomb_delta_bits), None,
                                     *self._statics())
                rspec = jax.ShapeDtypeStruct((bucket, self.k), jnp.float32)
                ispec = jax.ShapeDtypeStruct((bucket, self.k), jnp.int32)
                _merge_aot.compiled(rspec, ispec, rspec, ispec, self.k,
                                    self.select_min)
            return
        mspecs = jax.tree_util.tree_map(spec, _main_leaves(core))
        _merged_aot.compiled(qspec, mspecs, None, tm, None,
                             *self._statics())
        if dl is not None:
            _merged_aot.compiled(qspec, mspecs, dspecs, tm,
                                 spec(core.tomb_delta_bits),
                                 *self._statics())

    # -- serving -----------------------------------------------------------

    def batch_cap(self) -> Optional[int]:
        """The hoisted compressed-LUT transient clamp (ivf_pq only),
        sized by the MAIN layout — conservative for the small delta."""
        if self.kind != "ivf_pq":
            return None
        core = self.mutable._mut_core
        if core.sharded:
            aux = core.main.aux
            n_phys = int(aux["cap_n_phys"])
            max_chunks = int(aux["cap_max_chunks"])
            n_lists, pq_dim = int(aux["n_lists"]), int(aux["pq_dim"])
        else:
            m = core.main
            n_phys = int(m.list_codes.shape[0])
            max_chunks = int(m.chunk_table.shape[1])
            n_lists, pq_dim = int(m.n_lists), int(m.pq_dim)
        return ivf_pq.hoisted_batch_cap_dims(
            self.metric, self.per_cluster, n_phys, max_chunks, n_lists,
            pq_dim, self.pq_bits, self.n_probes, self.lut_dtype,
            self.hoisted)

    def ingest(self, q):
        """HOST-side compute-form conversion, mirroring the family
        backends bit for bit (the tiering ingest contract)."""
        q = np.asarray(q)
        expects(q.ndim == 2 and q.shape[1] == self.dim,
                "query dim mismatch")
        if self.kind == "ivf_pq":
            core = self.mutable._mut_core
            ds_dtype = (core.main.aux["dataset_dtype"] if core.sharded
                        else core.main.dataset_dtype)
            if q.dtype in (np.int8, np.uint8):
                q_dtype = str(q.dtype)
            else:
                expects(jnp.issubdtype(q.dtype, jnp.floating),
                        f"ivf_pq: unsupported query dtype {q.dtype}")
                q_dtype = "float32"
            expects(q_dtype in (ds_dtype, "float32"),
                    f"query dtype {q_dtype} != index dataset dtype "
                    f"{ds_dtype}")
            return q.astype(np.float32)
        if q.dtype in (np.int8, np.uint8):
            q = q.astype(np.float32)  # exact widening: matches device cast
        if self.metric == DistanceType.CosineExpanded:
            return np.asarray(ivf_flat._normalize_rows(jnp.asarray(q)))
        return q

    def dispatch(self, qb):
        """One PRE-BUCKETED batch against a consistent core snapshot.
        The lock makes the read atomic against writes (and makes the
        donated in-place delta append safe against this dispatch); every
        executable touched is warmed — zero compiles steady-state."""
        m = self.mutable
        with m._lock:
            core = m._mut_core
            if not core.sharded:
                return _merged_aot(jnp.asarray(qb), _main_leaves(core),
                                   _delta_leaves(core),
                                   core.tomb_main_bits,
                                   (None if core.delta is None
                                    else core.tomb_delta_bits),
                                   *self._statics())
            d, i = self._main_for(core).dispatch(qb, core.tomb_main_mesh)
            if core.delta is None:
                return d, i
            dd, di = _merged_aot(jnp.asarray(qb), _delta_leaves(core),
                                 None, core.tomb_delta_bits, None,
                                 *self._statics())
            dev = dispatch_device()
            d = jax.device_put(d, dev)
            i = jax.device_put(i, dev)
            return merge_sorted_runs(d, i, dd, di, k=self.k,
                                     select_min=self.select_min)

    def solo(self, q):
        """Uncoalesced fallback (compiles allowed — off the warmed
        path)."""
        return search(self.mutable, q, self.k, params=self.params)


# ---------------------------------------------------------------------------
# eager search


def search(mutable: MutableIndex, queries, k: int, params=None
           ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Eager search over main ∪ delta minus tombstones.  Queries bucket
    through the power-of-two ladder (pad + slice) exactly like the family
    ``search`` entry points; compiles are allowed here (first call per
    signature) — serving goes through a warmed :class:`MutableSearcher`.
    """
    s = mutable.searcher(int(k), params)
    q = s.ingest(queries)
    nq = q.shape[0]
    if nq == 0:
        from raft_tpu.neighbors._common import empty_result

        return empty_result(0, int(k), jnp.float32)
    bucket = _bucket_dim(nq)
    if bucket != nq:
        q = np.pad(q, ((0, bucket - nq), (0, 0)))
    d, i = s.dispatch(jnp.asarray(q))
    return d[:nq], i[:nq]


# ---------------------------------------------------------------------------
# background compaction


class Compactor:
    """supervise.py-style background compaction driver: a seeded daemon
    thread that, past a delta-fraction or tombstone-fraction threshold,
    runs :meth:`MutableIndex.compact` (rebuild off the request path,
    journal chase, warmed atomic swap, promotion via
    ``ServeEngine.refresh``).  Deterministic under test: ``auto=False``
    (the default) never starts a thread — drive :meth:`tick` manually;
    the thread's sleep jitter is seeded."""

    def __init__(self, mutable: MutableIndex, engine=None, *,
                 delta_fraction: float = 0.10, tomb_fraction: float = 0.10,
                 interval_s: float = 1.0, seed: int = 0):
        self.mutable = mutable
        self.engine = engine
        self.delta_fraction = float(delta_fraction)
        self.tomb_fraction = float(tomb_fraction)
        self.interval_s = float(interval_s)
        self._rng = np.random.default_rng(seed)
        self._stop = threading.Event()
        self._thread = None
        self.compactions = 0
        self.errors = 0

    def due(self) -> bool:
        return self.mutable.compact_due(self.delta_fraction,
                                        self.tomb_fraction)

    def tick(self) -> bool:
        """One deterministic check-and-compact step.  Compaction errors
        (including injected fault-plane refresh failures) are contained:
        the old core keeps serving, the error is counted, the next tick
        retries."""
        if not self.due():
            return False
        try:
            self.mutable.compact(self.engine)
        except Exception:
            self.errors += 1
            mutable_counters.inc("compaction_errors")
            return False
        self.compactions += 1
        return True

    def start(self) -> "Compactor":
        expects(self._thread is None, "compactor already started")
        self._stop.clear()

        def run():
            while not self._stop.is_set():
                self.tick()
                # seeded jitter: desynchronizes fleet members without
                # nondeterminism under a fixed seed
                pause = self.interval_s * (0.5 + self._rng.random())
                self._stop.wait(pause)

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="raft-tpu-compactor")
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None


# ---------------------------------------------------------------------------
# lowering-contract audit entry (analysis registry)


@hlo_program(
    "mutable.delta_merged_search",
    collectives=0, collective_bytes=0,
    # two family probe scans' tile transients + the (2, nq, k) part fold
    transient_bytes=4 << 20,
    notes="main ∪ delta with in-scan tombstone masks folded by "
          "merge_sorted_parts as ONE program — the _MutableBackend "
          "single-device serving executable (docs/mutable_index.md)")
def _audit_merged_search():
    import numpy as np

    rng = np.random.default_rng(0)
    x = rng.standard_normal((2048, 32)).astype(np.float32)
    m = MutableIndex(ivf_flat.build(ivf_flat.IndexParams(n_lists=16), x),
                     x, build_params=ivf_flat.IndexParams(n_lists=16))
    m.upsert(rng.standard_normal((128, 32)).astype(np.float32),
             np.arange(2048, 2176, dtype=np.int64))
    m.delete(np.arange(64, dtype=np.int64))
    core = m._mut_core
    q = jax.ShapeDtypeStruct((64, 32), jnp.float32)
    args = (q, _main_leaves(core), _delta_leaves(core),
            core.tomb_main_bits, core.tomb_delta_bits, "ivf_flat",
            int(DistanceType.L2SqrtExpanded), 8, 4, False, "float32",
            "float32", 0, False, "xla")
    return dict(fn=_merged_search_impl, args=args,
                static_argnums=_MERGED_STATICS)
