"""ANN index serialization (checkpoint/resume).

The 22.12 reference keeps ANN indexes in-memory only (no ``serialize``
symbols in ivf_flat_types.hpp/ivf_pq_types.hpp — SURVEY.md §5); later RAFT
versions added ``serialize``/``deserialize`` per index type.  Provided here
because TPU pods make rebuild-on-every-process expensive: build once, save,
and each process loads the artifact.

Format: a single ``.npz`` (numpy archive) holding every array leaf plus a
JSON-encoded aux header (metric, codebook kind, pq_bits, versioning).
Arrays come back as numpy; jax consumes them zero-copy on first use.

Durability contract (docs/serving.md §failure model): every save writes
to a temp file in the destination directory, fsyncs, and atomically
renames into place — a crash mid-save can never leave a truncated
archive under the real name for ``load`` to half-parse.  The header
additionally carries a per-array CRC32 manifest verified at load; any
corruption (bit flip, truncation, zip damage) raises a typed
:class:`raft_tpu.core.error.CorruptionError` instead of returning
garbage.  Pre-manifest archives still load (verification skipped).
"""

from __future__ import annotations

import dataclasses
import enum
import json
import os
import zipfile
import zlib

import jax.numpy as jnp
import numpy as np

from raft_tpu.core.error import CorruptionError, LogicError, expects
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.neighbors import ivf_flat, ivf_pq

_MAGIC = "raft-tpu-index"
# Versions are PER KIND so a format change to one index type doesn't
# spuriously break older readers of the others (archives are written once
# and loaded across processes/releases).  v1: original leaf set.
# ivf_pq v2 (hoisted-ADC PR): archives additionally carry the build-time
# list-side ADC tables ``list_adc``/``list_csum``; v1 archives still load —
# the tables are recomputed from centers/rotation/codebooks + stored codes,
# which is exact (pure functions of the trained model).
# tiered v1: the underlying family leaves (to_index reassembly) + the
# residency policy (hot_lists mask, tile_phys) + the optional host refine
# store — the residency SPLIT itself is recomputed at load (pure function
# of mask + chunk table), never stored.
_VERSIONS = {"ivf_flat": 1, "ivf_pq": 2, "sharded": 1, "tiered": 1,
             "mutable": 1}
# Readable versions are per kind too: accepting another kind's version at
# the gate would defer the failure to an obscure Index(**arrays) TypeError
# instead of the clean unsupported-version error this check exists to give.
_READABLE_VERSIONS = {"ivf_flat": (1,), "ivf_pq": (1, 2), "sharded": (1,),
                      "tiered": (1,), "mutable": (1,)}


def _checksums(arrays: dict) -> dict:
    """Per-array CRC32 manifest (name → checksum over the raw bytes)."""
    return {name: int(zlib.crc32(np.ascontiguousarray(a).tobytes())
                      & 0xFFFFFFFF)
            for name, a in arrays.items()}


def _finish(kind: str, arrays: dict, aux: dict) -> dict:
    """Attach the JSON header (versioning + aux + checksum manifest)."""
    header = {"magic": _MAGIC, "version": _VERSIONS[kind], "kind": kind,
              "aux": aux, "checksums": _checksums(arrays)}
    arrays["__header__"] = np.frombuffer(
        json.dumps(header).encode(), dtype=np.uint8)
    return arrays


def _pack(kind: str, index, aux: dict) -> dict:
    arrays = {f.name: np.asarray(getattr(index, f.name))
              for f in dataclasses.fields(index)
              if f.name not in aux}
    return _finish(kind, arrays, aux)


def _normalize(path) -> str:
    """np.savez silently appends '.npz' to suffix-less names — normalize up
    front so save and load agree on the on-disk path."""
    path = str(path)
    return path if path.endswith(".npz") else path + ".npz"


def _atomic_savez(path, arrays: dict) -> None:
    """Write the archive via temp file + fsync + atomic rename: readers
    see either the previous complete archive or the new complete archive,
    never a truncation (the rename is atomic within one filesystem; the
    temp lives beside the destination for exactly that reason)."""
    path = _normalize(path)
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):  # failed save: never leave droppings
            os.unlink(tmp)


def _unpack(path, kind: str):
    path = _normalize(path)
    try:
        with np.load(path) as z:
            expects("__header__" in z.files,
                    f"{path}: not a raft-tpu index file (no header)")
            header = json.loads(bytes(z["__header__"]).decode())
            expects(header.get("magic") == _MAGIC,
                    f"{path}: not a raft-tpu index file")
            if header["kind"] != kind:
                raise LogicError(
                    f"{path} holds a {header['kind']} index, not {kind}")
            expects(header.get("version") in _READABLE_VERSIONS[kind],
                    f"{path}: unsupported {kind} index version "
                    f"{header.get('version')}")
            arrays = {k: z[k] for k in z.files if k != "__header__"}
    except (zipfile.BadZipFile, zlib.error, EOFError, ValueError,
            json.JSONDecodeError, UnicodeDecodeError, KeyError, OSError) as e:
        # zip-level damage (numpy/zipfile verify entry CRCs on read) or a
        # mangled header — surface ONE typed error, never a half-parse
        raise CorruptionError(
            f"{path}: corrupt or truncated index archive ({e})") from e
    manifest = header.get("checksums")
    if manifest is not None:  # pre-manifest archives: nothing to verify
        stored = _checksums(arrays)
        bad = sorted(name for name, crc in stored.items()
                     if manifest.get(name) != crc)
        missing = sorted(set(manifest) - set(stored))
        if bad or missing:
            raise CorruptionError(
                f"{path}: checksum manifest mismatch "
                f"(corrupt: {bad or '-'}, missing: {missing or '-'}) — "
                "the archive is damaged; rebuild or restore it")
    return header["aux"], arrays


def save_ivf_flat(path, index: ivf_flat.Index) -> None:
    """Write an IVF-Flat index to *path* (``.npz``; atomic + checksummed
    — module docstring)."""
    aux = {"metric": int(index.metric),
           "adaptive_centers": bool(index.adaptive_centers)}
    _atomic_savez(path, _pack("ivf_flat", index, aux))


def load_ivf_flat(path) -> ivf_flat.Index:
    aux, a = _unpack(path, "ivf_flat")
    return ivf_flat.Index(
        **{k: jnp.asarray(v) for k, v in a.items()},
        metric=DistanceType(aux["metric"]),
        adaptive_centers=aux["adaptive_centers"])


def save_ivf_pq(path, index: ivf_pq.Index) -> None:
    """Write an IVF-PQ index to *path* (``.npz``; atomic + checksummed —
    module docstring)."""
    aux = {"metric": int(index.metric),
           "codebook_kind": int(index.codebook_kind),
           "pq_bits": int(index.pq_bits),
           "dataset_dtype": index.dataset_dtype}
    _atomic_savez(path, _pack("ivf_pq", index, aux))


def save_sharded(path, sharded) -> None:
    """Write an :class:`raft_tpu.neighbors.ann_mnmg.ShardedIndex` to
    *path* (``.npz``): the replicated tables, the per-shard stacked
    blocks, and the static aux (incl. world) — so a serving fleet shards
    once and every process loads the finished partition.

    Requires the stacked leaves to be host-fetchable (single-process mesh
    or fully-replicated layout); a multi-process OPG fleet saves from the
    process that built the partition before distribution.

    A :class:`raft_tpu.neighbors.mutable.MutableIndex` wrapping a sharded
    main routes to :func:`save_mutable` — the fleet-consistent snapshot
    of the (main, delta, tombstone) triple."""
    from raft_tpu.neighbors import mutable as _mutable

    if isinstance(sharded, _mutable.MutableIndex):
        return save_mutable(path, sharded)
    for leaf in tuple(sharded.replicated) + tuple(sharded.stacked):
        expects(getattr(leaf, "is_fully_addressable", True)
                or getattr(leaf, "is_fully_replicated", False),
                "save_sharded: leaves span non-addressable devices — save "
                "from the building process before distribution")
    aux = {"kind": sharded.kind, "aux": dict(sharded.aux)}
    arrays = {f"rep{j}": np.asarray(leaf)
              for j, leaf in enumerate(sharded.replicated)}
    arrays.update({f"st{j}": np.asarray(leaf)
                   for j, leaf in enumerate(sharded.stacked)})
    _atomic_savez(path, _finish("sharded", arrays, aux))


def load_sharded(path, comms):
    """Load a sharded index back onto *comms*' mesh: stacked blocks land
    shard-per-device (``P(axis)``), replicated tables replicate.  The
    archive's world must match the communicator's size — a partition is
    laid out for one world; re-shard from the base index to change it."""
    from jax.sharding import PartitionSpec as P

    from raft_tpu.comms.comms import as_comms
    from raft_tpu.neighbors import ann_mnmg

    if _peek_kind(path) == "mutable":
        return load_mutable(path, comms)
    comms = as_comms(comms)
    aux, a = _unpack(path, "sharded")
    world = int(aux["aux"]["world"])
    expects(world == comms.get_size(),
            f"archive was sharded for world={world}, communicator has "
            f"{comms.get_size()} — re-shard the base index instead")
    n_rep = sum(1 for k in a if k.startswith("rep"))
    n_st = sum(1 for k in a if k.startswith("st"))
    replicated = tuple(comms.globalize(jnp.asarray(a[f"rep{j}"]), P())
                       for j in range(n_rep))
    stacked = tuple(
        comms.globalize(jnp.asarray(a[f"st{j}"]), P(comms.axis_name))
        for j in range(n_st))
    return ann_mnmg.ShardedIndex(aux["kind"], comms, replicated, stacked,
                                 dict(aux["aux"]))


def _peek_kind(path) -> str:
    """Header-only kind probe — lets the sharded entry points accept a
    mutable archive (and vice versa) without guessing from the caller."""
    path = _normalize(path)
    try:
        with np.load(path) as z:
            expects("__header__" in z.files,
                    f"{path}: not a raft-tpu index file (no header)")
            header = json.loads(bytes(z["__header__"]).decode())
    except (zipfile.BadZipFile, zlib.error, EOFError, ValueError,
            json.JSONDecodeError, UnicodeDecodeError, KeyError, OSError) as e:
        raise CorruptionError(
            f"{path}: corrupt or truncated index archive ({e})") from e
    return header.get("kind", "")


def _params_to_aux(params):
    """Family IndexParams → JSON-safe dict (enums → ints)."""
    if params is None:
        return None
    d = dataclasses.asdict(params)
    return {k: (int(v) if isinstance(v, enum.IntEnum) else v)
            for k, v in d.items()}


def _params_from_aux(kind: str, d):
    if d is None:
        return None
    d = dict(d)
    d["metric"] = DistanceType(d["metric"])
    if kind == "ivf_pq":
        d["codebook_kind"] = ivf_pq.CodebookKind(d["codebook_kind"])
        return ivf_pq.IndexParams(**d)
    return ivf_flat.IndexParams(**d)


def save_mutable(path, mut) -> None:
    """Write a :class:`raft_tpu.neighbors.mutable.MutableIndex` to *path*
    (``.npz``; atomic + CRC-manifested — module docstring): ONE
    write-ordered snapshot of the (main, delta, tombstone) triple, taken
    under the write lock so a save racing live upserts/deletes is still a
    consistent state some prefix of the writes produced.

    The MAIN segment is stored verbatim (single-device family leaves, or
    the sharded ``rep{j}``/``st{j}`` blocks — the :func:`save_sharded`
    layout, fleet-consistent: every process of a serving fleet loads the
    same partition).  The delta and tombstones are stored as their
    SOURCE-OF-TRUTH host books (delta rows + insertion order, dead-id
    sets): load replays them through the normal ``upsert``/``delete``
    write path — O(delta), delta small by the compaction invariant — so
    the loaded triple is live-row identical and serves through the exact
    same warmed programs, without freezing the delta's physical packing
    into the archive format."""
    from raft_tpu.neighbors import mutable as _mutable

    expects(isinstance(mut, _mutable.MutableIndex),
            "save_mutable needs a MutableIndex")
    with mut._lock:
        core = mut._mut_core
        fam_kind = core.kind
        if core.sharded:
            for leaf in tuple(core.main.replicated) + tuple(core.main.stacked):
                expects(getattr(leaf, "is_fully_addressable", True)
                        or getattr(leaf, "is_fully_replicated", False),
                        "save_mutable: sharded leaves span non-addressable "
                        "devices — save from the building process")
            arrays = {f"main_rep{j}": np.asarray(leaf)
                      for j, leaf in enumerate(core.main.replicated)}
            arrays.update({f"main_st{j}": np.asarray(leaf)
                           for j, leaf in enumerate(core.main.stacked)})
            fam = {"aux": dict(core.main.aux)}
        else:
            index = core.main
            if fam_kind == "ivf_flat":
                fam = {"metric": int(index.metric),
                       "adaptive_centers": bool(index.adaptive_centers)}
            else:
                fam = {"metric": int(index.metric),
                       "codebook_kind": int(index.codebook_kind),
                       "pq_bits": int(index.pq_bits),
                       "dataset_dtype": index.dataset_dtype}
            arrays = {f"main_{f.name}": np.asarray(getattr(index, f.name))
                      for f in dataclasses.fields(index)
                      if f.name not in fam}
        arrays["mut_main_ids"] = np.asarray(core.main_ids, np.int64)
        arrays["mut_main_dead"] = np.asarray(sorted(core.main_dead),
                                             np.int64)
        # live main vectors re-seed the host row store (compaction's and
        # the delta dedup-rebuild's input); dead mains replay as pure
        # tombstones, no vector required
        live_main = np.asarray(
            [j for j in core.main_ids.tolist() if j not in core.main_dead],
            np.int64)
        arrays["mut_main_live_ids"] = live_main
        if live_main.size:
            arrays["mut_main_live_rows"] = np.stack(
                [core.store[int(j)] for j in live_main])
        delta_ids = np.asarray(list(core.delta_live), np.int64)
        arrays["mut_delta_ids"] = delta_ids
        if delta_ids.size:
            arrays["mut_delta_rows"] = np.stack(
                [core.store[int(j)] for j in delta_ids])
        aux = {"kind": fam_kind, "sharded": bool(core.sharded),
               "family": fam,
               "build_params": _params_to_aux(mut.build_params)}
    _atomic_savez(path, _finish("mutable", arrays, aux))


def load_mutable(path, comms=None):
    """Load a mutable index: restore the main segment verbatim (onto
    *comms*' mesh when the archive is sharded), then REPLAY the archived
    delta/tombstone books through the normal ``upsert``/``delete`` write
    path — the loaded triple serves the same live rows through the same
    warmed fixed-shape programs as the saved one."""
    from raft_tpu.neighbors import ann_mnmg
    from raft_tpu.neighbors import mutable as _mutable

    aux, a = _unpack(path, "mutable")
    fam_kind, fam = aux["kind"], aux["family"]
    main_ids = a["mut_main_ids"].astype(np.int64)
    main_dead = a["mut_main_dead"].astype(np.int64)
    delta_ids = a["mut_delta_ids"].astype(np.int64)
    delta_rows = a.get("mut_delta_rows")
    if aux["sharded"]:
        from jax.sharding import PartitionSpec as P

        from raft_tpu.comms.comms import as_comms

        expects(comms is not None,
                "load_mutable: archive holds a sharded main — pass comms")
        comms = as_comms(comms)
        sh_aux = dict(fam["aux"])
        world = int(sh_aux["world"])
        expects(world == comms.get_size(),
                f"archive was sharded for world={world}, communicator has "
                f"{comms.get_size()} — re-shard the base index instead")
        n_rep = sum(1 for k in a if k.startswith("main_rep"))
        n_st = sum(1 for k in a if k.startswith("main_st"))
        replicated = tuple(
            comms.globalize(jnp.asarray(a[f"main_rep{j}"]), P())
            for j in range(n_rep))
        stacked = tuple(
            comms.globalize(jnp.asarray(a[f"main_st{j}"]),
                            P(comms.axis_name))
            for j in range(n_st))
        main = ann_mnmg.ShardedIndex(fam_kind, comms, replicated, stacked,
                                     sh_aux)
        dim = int(main.dim)
    else:
        arrays = {k[len("main_"):]: jnp.asarray(v) for k, v in a.items()
                  if k.startswith("main_")}
        if fam_kind == "ivf_flat":
            main = ivf_flat.Index(
                **arrays, metric=DistanceType(fam["metric"]),
                adaptive_centers=fam["adaptive_centers"])
        else:
            main = ivf_pq.Index(
                **arrays, metric=DistanceType(fam["metric"]),
                codebook_kind=ivf_pq.CodebookKind(fam["codebook_kind"]),
                pq_bits=fam["pq_bits"],
                dataset_dtype=fam.get("dataset_dtype", "float32"))
        dim = int(main.dim)
    live_main = a["mut_main_live_ids"].astype(np.int64)
    live_rows = a.get("mut_main_live_rows",
                      np.zeros((0, dim), np.float32))
    mut = _mutable.MutableIndex(
        main, live_rows, live_main,
        build_params=_params_from_aux(fam_kind, aux["build_params"]),
        comms=comms)
    core = mut._mut_core
    # the constructor only saw LIVE ids; restore the full main roster
    # (dead mains replay as tombstones below) and make sure the bitmap
    # ladder covers the highest archived id before the tombstone replay
    # exempt(mutation-discipline): load-time roster restore pre-serving
    core.main_ids = main_ids
    max_id = max([int(main_ids.max()) if main_ids.size else 0,
                  int(delta_ids.max()) if delta_ids.size else 0])
    words = _mutable._tomb_words(max_id)
    if words > core.n_words:
        mut._grow_tombstones(core, words)
    if delta_ids.size:
        mut.upsert(delta_rows, delta_ids)
    dead = np.setdiff1d(main_dead, delta_ids)
    if dead.size:
        mut.delete(dead)
    return mut


def save_tiered(path, tiered) -> None:
    """Write a :class:`raft_tpu.neighbors.tiering.TieredIndex` to *path*
    (``.npz``; atomic + checksummed — module docstring): the reassembled
    family leaves plus the residency POLICY (hot-list mask, tile size) and
    the host refine store.  The split blocks themselves are not stored —
    load recuts them from the mask, bit-identically (the split is a pure
    row permutation of the packed leaves)."""
    from raft_tpu.neighbors import tiering

    index = tiering.to_index(tiered)
    if tiered.kind == "ivf_flat":
        fam = {"metric": int(index.metric),
               "adaptive_centers": bool(index.adaptive_centers)}
    else:
        fam = {"metric": int(index.metric),
               "codebook_kind": int(index.codebook_kind),
               "pq_bits": int(index.pq_bits),
               "dataset_dtype": index.dataset_dtype}
    aux = {"kind": tiered.kind, "tile_phys": int(tiered.tile_phys),
           "family": fam}
    arrays = {f.name: np.asarray(getattr(index, f.name))
              for f in dataclasses.fields(index) if f.name not in fam}
    arrays["tiered_hot_lists"] = np.asarray(tiered.hot_lists)
    if tiered.refine_store is not None:
        arrays["tiered_refine_store"] = np.asarray(tiered.refine_store)
    _atomic_savez(path, _finish("tiered", arrays, aux))


def load_tiered(path):
    """Load a tiered index: rebuild the family Index from the archived
    leaves, then re-tier under the ARCHIVED residency mask — the loaded
    split (hot block, cold tiles, probe budgets) is bit-identical to the
    saved one."""
    from raft_tpu.neighbors import tiering

    aux, a = _unpack(path, "tiered")
    mask = a.pop("tiered_hot_lists").astype(bool)
    store = a.pop("tiered_refine_store", None)
    fam = aux["family"]
    arrays = {k: jnp.asarray(v) for k, v in a.items()}
    if aux["kind"] == "ivf_flat":
        index = ivf_flat.Index(
            **arrays, metric=DistanceType(fam["metric"]),
            adaptive_centers=fam["adaptive_centers"])
    else:
        index = ivf_pq.Index(
            **arrays, metric=DistanceType(fam["metric"]),
            codebook_kind=ivf_pq.CodebookKind(fam["codebook_kind"]),
            pq_bits=fam["pq_bits"],
            dataset_dtype=fam.get("dataset_dtype", "float32"))
    return tiering.tier(index, hot_lists=mask,
                        tile_phys=int(aux["tile_phys"]), dataset=store)


def load_ivf_pq(path) -> ivf_pq.Index:
    aux, a = _unpack(path, "ivf_pq")
    arrays = {k: jnp.asarray(v) for k, v in a.items()}
    per_cluster = (ivf_pq.CodebookKind(aux["codebook_kind"])
                   == ivf_pq.CodebookKind.PER_CLUSTER)
    if "list_adc" not in arrays:
        # v1 archive (pre hoisted-ADC): recompute the build-time list-side
        # table from the trained model — exact, since it is a pure f32
        # function of centers/rotation/codebooks
        arrays["list_adc"] = ivf_pq._build_list_adc(
            arrays["centers"], arrays["rotation"], arrays["codebooks"],
            per_cluster)
    if "list_csum" not in arrays:
        # likewise its per-candidate contraction, re-derived by unpacking
        # the stored codes — TILED over physical rows (r7): the compat
        # load of a large v1 archive must honor the same O(tile) transient
        # contract as the tiled build, not materialize the index-wide
        # unpacked codes (docs/index_build.md)
        arrays["list_csum"] = ivf_pq._csum_for_packed(
            arrays["list_codes"], arrays["owner"], arrays["centers"],
            arrays["rotation"], arrays["codebooks"], per_cluster,
            aux["pq_bits"], tile_phys=1024)
    return ivf_pq.Index(
        **arrays,
        metric=DistanceType(aux["metric"]),
        codebook_kind=ivf_pq.CodebookKind(aux["codebook_kind"]),
        pq_bits=aux["pq_bits"],
        # pre-r4 archives predate the dtype tag; they were all f32-built
        dataset_dtype=aux.get("dataset_dtype", "float32"))
