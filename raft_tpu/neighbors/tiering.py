"""Host/device memory tiering for IVF serving (SURVEY §2.1 memory spaces).

The fully-resident serving spine caps corpus size at device memory.  This
layer splits a packed IVF-Flat/IVF-PQ index into two residency tiers by a
telemetry-fed hotness policy (per-list probe counters accumulated ON
DEVICE by the serve path):

* **hot tier** — the most-probed lists' physical chunk rows, compacted
  into a device-resident block whose chunk table keeps the ORIGINAL
  (n_lists, max_chunks) shape with cold lists remapped to the reserved
  dummy row (``_common.remap_chunk_table``);
* **cold tier** — the remaining rows, cut into fixed-shape host tiles of
  ``tile_phys`` physical rows (ragged tail padded with the source dummy
  row) and streamed through O(tile) staging buffers, double-buffered on
  the ``Handle`` stream-pool lanes (``Stream.stage``: prefetch tile i+1
  while tile i scores).

The probe scan becomes a fixed-shape TWO-PHASE program.  The hot phase is
ONE aot-cached executable (coarse ranking + top-n_probes + hot-block scan
+ device-side probe-counter accumulate); each cold tile is one aot-cached
``tiering.cold_scan`` dispatch.  Both phases score through the families'
UNCHANGED scan programs (``ivf_flat._probe_search_impl``,
``ivf_pq._search_batch_impl``) over doctored leaves, so per-candidate
distances are bit-identical to the fully-resident scan; the per-phase
sorted runs fold through the ``merge_sorted_parts`` semantics (hot run
first, tiles in storage order, run *a* wins ties — the eager fold
dispatches the same ``merge_sorted_runs`` primitive the part fold scans),
so the final f32 top-k matches the fully-resident search bit for bit on
tie-free data.

**Exact re-rank** (``SearchParams.refine_ratio``): the two-phase scan runs
at ``k·ratio`` candidates; the survivors' ORIGINAL vectors are gathered
from the host refine store (ONE amortized id fetch + ONE staged upload per
super-batch) and re-scored with exact distance in one aot-cached
``tiering.refine`` program — the recall safety net for compressed list
storage (the reference IVF-PQ + refine() recipe; PR-3 triage: 0.53 recall,
information-limited ceiling 0.62).

Zero-retrace serving: ``TieredSearcher.warm`` pre-lowers the hot-phase,
cold-phase, refine and run-merge signatures per (bucket, dtype);
re-tiering (:func:`retier` from a :meth:`TieredSearcher.hotness`
snapshot) swaps residency through ``ServeEngine.refresh`` — compiles
happen off the request path, the swap is atomic.

Residency/transfer contract (the ``tier-staging`` analysis form): per-row
data crosses the host/device boundary ONLY at the single marked staging
call site (:meth:`TieredSearcher._stage`); device residency is the hot
set + the model tables + at most two staging tiles.  docs/index_tiering.md
has the full design note.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import telemetry
from raft_tpu.analysis.registry import hlo_program
from raft_tpu.core.aot import _bucket_dim, aot, dispatch_device
from raft_tpu.core.error import expects
from raft_tpu.core.handle import Handle
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.distance.pairwise import _l2_expanded, accum_dtype
from raft_tpu.matrix.select_k import (_merge_aot, merge_sorted_runs,
                                      select_k)
from raft_tpu.neighbors import ivf_flat, ivf_pq
from raft_tpu.neighbors._common import empty_result, remap_chunk_table

#: tiered-serving residency events and bytes — the serve bench's per-tier
#: traffic report reads these keys (hot_dispatches, cold_tiles,
#: prefetch_bytes, refine_gather_bytes, retiers)
tier_counters = telemetry.legacy_counter(
    "raft_tpu_tier_events_total",
    "Tiered-serving residency events and bytes moved (hot dispatches, "
    "cold tiles scanned, staged prefetch bytes, refine gather bytes)")

#: staging-enqueue latency: how long the host spends handing one cold tile
#: (or one refine gather) to the async device copy — the prefetch overlap
#: the double-buffered lanes exist to hide
prefetch_seconds = telemetry.histogram(
    "raft_tpu_tier_prefetch_seconds",
    "Cold-tile / refine-gather staging enqueue latency (seconds)")

_DEFAULT_TILE_PHYS = 512


# ---------------------------------------------------------------------------
# the two-phase programs


def _select_probes(q, centers, kind: str, metric_val: int, n_probes: int,
                   engine: str):
    """Coarse ranking + top-n_probes, mirroring each family's serving
    coarse EXACTLY (``ivf_flat._coarse_distances`` /
    ``ivf_pq._full_search_impl``) so the tiered probe selection is
    bit-identical to the fully-resident program's."""
    if kind == "ivf_flat":
        cd = ivf_flat._coarse_distances(q, centers,
                                        DistanceType(metric_val))
    elif metric_val == int(DistanceType.InnerProduct):
        cd = -(q @ centers.T)
    else:
        cd = _l2_expanded(q, centers, sqrt=False, precision=None)
    _, sel = select_k(cd, n_probes, select_min=True, engine=engine)
    return sel.astype(jnp.int32)


def _scan_block(q, probes, model, blk, kind: str, metric_val: int, k: int,
                probe_extra: int, per_cluster: bool, lut_dtype_name: str,
                int_dtype_name: str, pq_bits: int, hoisted: bool,
                engine: str):
    """Score one physical block (the hot set, or one staged cold tile)
    against *probes* through the family's unchanged scan program.  *model*
    holds the residency-independent tables (device-resident for both
    phases); *blk* the per-row arrays of this block."""
    if kind == "ivf_flat":
        sqrt = metric_val == int(DistanceType.L2SqrtExpanded)
        return ivf_flat._probe_search_impl(q, probes, blk, metric_val, k,
                                           sqrt, probe_extra, engine)
    centers, rotation, codebooks, list_adc = model
    codes, indices, sizes, table, owner, csum = blk
    leaves = (centers, rotation, codebooks, codes, indices, sizes, table,
              owner, list_adc, csum)
    return ivf_pq._search_batch_impl(q, probes, leaves, metric_val, k,
                                     per_cluster, lut_dtype_name,
                                     int_dtype_name, pq_bits, hoisted,
                                     probe_extra, engine)


def _hot_phase_impl(q, acc, model, blk, kind: str, metric_val: int, k: int,
                    n_probes: int, probe_extra: int, per_cluster: bool,
                    lut_dtype_name: str, int_dtype_name: str, pq_bits: int,
                    hoisted: bool, engine: str):
    """The hot phase as ONE program: coarse ranking → top-n_probes →
    hot-block scan → probe-counter accumulate.  Returns (probe_ids,
    run_d, run_i, acc') — the probe ids feed every cold-tile dispatch of
    the same batch, and *acc* is the device-resident (n_lists,) hotness
    counter the re-tiering policy snapshots off the request path."""
    probes = _select_probes(q, model[0], kind, metric_val, n_probes, engine)
    d, i = _scan_block(q, probes, model, blk, kind, metric_val, k,
                       probe_extra, per_cluster, lut_dtype_name,
                       int_dtype_name, pq_bits, hoisted, engine)
    acc = acc.at[probes.reshape(-1)].add(1)
    return probes, d, i, acc


def _cold_scan_impl(q, probes, model, blk, kind: str, metric_val: int,
                    k: int, probe_extra: int, per_cluster: bool,
                    lut_dtype_name: str, int_dtype_name: str, pq_bits: int,
                    hoisted: bool, engine: str):
    """One staged cold tile scored as ONE program — the O(tile) search
    residency analogue of the tiled build's ``run_tiles`` shape: every
    tile shares one (bucket, dtype) signature, so the whole cold sweep
    dispatches one warmed executable per tile."""
    return _scan_block(q, probes, model, blk, kind, metric_val, k,
                       probe_extra, per_cluster, lut_dtype_name,
                       int_dtype_name, pq_bits, hoisted, engine)


def _refine_impl(q, cand_vecs, cand_ids, metric_val: int, k: int,
                 engine: str = "xla"):
    """Exact re-rank: re-score the top k·ratio candidates' ORIGINAL
    vectors (gathered from the host tier) with exact distance and keep the
    best k.  Padding slots (id −1) score sentinel; cosine expects
    pre-normalized queries (the family ingest contract)."""
    qf = q.astype(jnp.float32)
    v = cand_vecs.astype(jnp.float32)
    is_ip = metric_val == int(DistanceType.InnerProduct)
    is_cos = metric_val == int(DistanceType.CosineExpanded)
    dots = jnp.einsum("qd,qrd->qr", qf, v,
                      preferred_element_type=jnp.float32)
    if is_ip:
        d = dots
    elif is_cos:
        vn = jnp.sqrt(jnp.maximum(jnp.sum(v * v, axis=-1), 1e-30))
        d = 1.0 - dots / vn
    else:
        q_sq = jnp.sum(qf * qf, axis=-1)[:, None]
        d = q_sq + jnp.sum(v * v, axis=-1) - 2.0 * dots
    sentinel = jnp.float32(-jnp.inf if is_ip else jnp.inf)
    d = jnp.where(cand_ids >= 0, d, sentinel)
    d, i = select_k(d, k, select_min=not is_ip, indices=cand_ids,
                    engine=engine)
    if metric_val == int(DistanceType.L2SqrtExpanded):
        d = jnp.sqrt(jnp.maximum(d, 0))
    return d, i


_HOT_STATICS = tuple(range(4, 15))
_hot_phase_aot = aot(_hot_phase_impl, static_argnums=_HOT_STATICS)
_COLD_STATICS = tuple(range(4, 14))
_cold_scan_aot = aot(_cold_scan_impl, static_argnums=_COLD_STATICS)
_REFINE_STATICS = (3, 4, 5)
_refine_aot = aot(_refine_impl, static_argnums=_REFINE_STATICS)


# ---------------------------------------------------------------------------
# the tiered container


@dataclasses.dataclass
class TieredIndex:
    """Two-tier residency split of one packed IVF index (module
    docstring).  NOT a pytree: the device-resident members (``model``,
    ``hot_scan``) are jax arrays, the cold tiles and the full per-row
    source blocks stay host numpy.

    ``model``    residency-independent device tables — flat: (centers,);
                 pq: (centers, rotation, codebooks, list_adc)
    ``hot_scan`` the hot block's scan leaves (device) — flat:
                 (data, indices, sizes, table); pq: (codes, indices,
                 sizes, table, owner, csum)
    ``cold_tiles`` per-tile host tuples in the same per-kind leaf order,
                 every tile exactly (tile_phys + 1) rows (tail padded
                 with the source dummy row)
    ``host``     the FULL per-row blocks (numpy source of truth) —
                 re-tiering and serialization slice from here, never from
                 device
    """

    kind: str
    metric: DistanceType
    n_lists: int
    dim: int
    tile_phys: int
    hot_lists: np.ndarray
    chunk_table: np.ndarray
    list_sizes: np.ndarray
    model: Tuple[jnp.ndarray, ...]
    hot_scan: Tuple[jnp.ndarray, ...]
    cold_tiles: Tuple[Tuple[np.ndarray, ...], ...]
    host: dict
    probe_extra_hot: int
    probe_extra_cold: int
    aux: dict
    refine_store: Optional[np.ndarray] = None
    hotness: Optional[np.ndarray] = None
    _searchers: dict = dataclasses.field(default_factory=dict, repr=False)

    @property
    def n_hot_lists(self) -> int:
        return int(np.sum(self.hot_lists))

    @property
    def hot_rows(self) -> int:
        """Real physical rows resident on device (excl. the dummy)."""
        return int(self.hot_scan[0].shape[0]) - 1

    @property
    def n_phys(self) -> int:
        """Total real physical rows across both tiers."""
        return int(self.host["sizes"].shape[0]) - 1

    def device_bytes(self) -> int:
        """Hot-tier residency: the model tables + the hot block."""
        return int(sum(a.nbytes for a in self.model)
                   + sum(a.nbytes for a in self.hot_scan))

    def tile_bytes(self) -> int:
        """Bytes of ONE staging tile (0 with no cold tier)."""
        if not self.cold_tiles:
            return 0
        return int(sum(a.nbytes for a in self.cold_tiles[0]))

    def searcher(self, k: int, params=None) -> "TieredSearcher":
        """Get-or-create the serving searcher for (k, params) — shared by
        the serve backend and the eager :func:`search` path so both
        dispatch the same warmed executables and probe counters."""
        key = (int(k), repr(params))
        s = self._searchers.get(key)
        if s is None:
            s = self._searchers[key] = TieredSearcher(self, int(k), params)
        return s


def _host_parts(index) -> dict:
    """Pull an index's per-row blocks to host numpy (tier/serialize path —
    off the dispatch path by construction)."""
    if isinstance(index, ivf_flat.Index):
        return {"kind": "ivf_flat",
                "data": np.asarray(index.list_data),
                "indices": np.asarray(index.list_indices),
                "sizes": np.asarray(index.phys_sizes)}
    expects(isinstance(index, ivf_pq.Index),
            f"tier(): expected an ivf_flat/ivf_pq Index, got {type(index)}")
    return {"kind": "ivf_pq",
            "codes": np.asarray(index.list_codes),
            "indices": np.asarray(index.list_indices),
            "sizes": np.asarray(index.phys_sizes),
            "owner": np.asarray(index.owner),
            "csum": np.asarray(index.list_csum)}


def _owners_from_table(chunk_table: np.ndarray, n_phys: int) -> np.ndarray:
    """(n_phys + 1,) owner list ids from the chunk table (host) — ivf_flat
    carries no owner leaf; every real row appears exactly once."""
    n_lists, max_chunks = chunk_table.shape
    owner = np.zeros(n_phys + 1, np.int64)
    flat = chunk_table.reshape(-1).astype(np.int64)
    ids = np.repeat(np.arange(n_lists, dtype=np.int64), max_chunks)
    real = flat < n_phys
    owner[flat[real]] = ids[real]
    owner[n_phys] = 0
    return owner


def _select_hot(hotness: Optional[np.ndarray], counts: np.ndarray,
                cap: int, hot_fraction: float) -> np.ndarray:
    """Greedy hotness policy: lists in (probe count desc, id asc) order
    until their physical rows reach ``hot_fraction`` of the total.  With
    no counters yet (a fresh tier), list size is the proxy — the biggest
    lists are the likeliest probe targets and the costliest to stream."""
    n_lists = counts.shape[0]
    n_chunks = np.maximum(-(-counts.astype(np.int64) // cap), 1)
    n_phys = int(n_chunks.sum())
    # exempt(dtype-drift): host-numpy policy score, never enters jax
    score = (np.asarray(hotness, np.float64) if hotness is not None
             # exempt(dtype-drift): host-numpy policy score, never enters jax
             else counts.astype(np.float64))
    expects(score.shape == (n_lists,),
            f"hotness must be (n_lists,) = ({n_lists},), got {score.shape}")
    order = np.lexsort((np.arange(n_lists), -score))
    target = int(np.ceil(float(hot_fraction) * n_phys))
    mask = np.zeros(n_lists, bool)
    taken = 0
    for l in order:
        if taken >= target:
            break
        mask[l] = True
        taken += int(n_chunks[l])
    return mask


def _tier_from_parts(host: dict, chunk_table: np.ndarray,
                     list_sizes: np.ndarray, model_host: dict,
                     metric: DistanceType, aux: dict, *,
                     hot_fraction: float, hotness, hot_lists, tile_phys,
                     refine_store) -> TieredIndex:
    kind = host["kind"]
    chunk_table = np.asarray(chunk_table).astype(np.int32)
    list_sizes = np.asarray(list_sizes).astype(np.int32)
    n_lists = list_sizes.shape[0]
    sizes = host["sizes"]
    n_phys = sizes.shape[0] - 1
    cap = host["indices"].shape[1]
    if kind == "ivf_pq":
        owner = host["owner"].astype(np.int64)
    else:
        owner = _owners_from_table(chunk_table, n_phys)

    if hot_lists is not None:
        mask = np.asarray(hot_lists).astype(bool)
        expects(mask.shape == (n_lists,),
                f"hot_lists must be (n_lists,) bool, got {mask.shape}")
    else:
        mask = _select_hot(hotness, list_sizes, cap, hot_fraction)

    blocks = [k for k in host if k != "kind"]
    dev = dispatch_device()

    # --- hot tier: compact the hot rows (original order) + fresh dummy
    hot_sel = np.where(mask[owner[:n_phys]])[0]
    hot_dummy = hot_sel.shape[0]
    rows = np.concatenate([hot_sel, [n_phys]]).astype(np.int64)
    row_map = np.full(n_phys + 1, -1, np.int64)
    row_map[hot_sel] = np.arange(hot_dummy)
    row_map[n_phys] = hot_dummy
    hot_table = remap_chunk_table(chunk_table, row_map, hot_dummy)
    hot_blk = {k: host[k][rows] for k in blocks}
    hot_blk["table"] = hot_table
    probe_extra_hot = max(0, hot_dummy - int(mask.sum()))

    # --- cold tier: fixed tile_phys tiles, tail padded with the source
    # dummy row (zero data, −1 indices, size 0 — never scored)
    cold = np.where(~mask[owner[:n_phys]])[0]
    t_phys = int(tile_phys or _DEFAULT_TILE_PHYS)
    expects(t_phys >= 1, "tile_phys must be >= 1")
    tiles = []
    for t0 in range(0, cold.shape[0], t_phys):
        rows_t = cold[t0:t0 + t_phys]
        pad = t_phys - rows_t.shape[0]
        rows_full = np.concatenate(
            [rows_t, np.full(pad + 1, n_phys)]).astype(np.int64)
        map_t = np.full(n_phys + 1, -1, np.int64)
        map_t[rows_t] = np.arange(rows_t.shape[0])
        map_t[n_phys] = t_phys
        blk = {k: np.ascontiguousarray(host[k][rows_full]) for k in blocks}
        blk["table"] = remap_chunk_table(chunk_table, map_t, t_phys)
        tiles.append(blk)

    def _leaves(blk, device=None):
        if kind == "ivf_flat":
            order = ("data", "indices", "sizes", "table")
        else:
            order = ("codes", "indices", "sizes", "table", "owner", "csum")
        out = tuple(blk[k] for k in order)
        if device is not None:
            out = tuple(jax.device_put(a, device) for a in out)
        return out

    model = tuple(jax.device_put(model_host[k], dev)
                  for k in _model_keys(kind))
    tiered = TieredIndex(
        kind=kind, metric=metric, n_lists=n_lists,
        dim=int(model_host["centers"].shape[1]), tile_phys=t_phys,
        hot_lists=mask, chunk_table=chunk_table, list_sizes=list_sizes,
        model=model, hot_scan=_leaves(hot_blk, device=dev),
        cold_tiles=tuple(_leaves(b) for b in tiles), host=host,
        probe_extra_hot=probe_extra_hot, probe_extra_cold=t_phys,
        aux=dict(aux),
        refine_store=refine_store,
        hotness=None if hotness is None else np.asarray(hotness))
    return tiered


def _model_keys(kind: str) -> Tuple[str, ...]:
    return (("centers",) if kind == "ivf_flat"
            else ("centers", "rotation", "codebooks", "list_adc"))


def tier(index, *, hot_fraction: float = 0.25, hotness=None, hot_lists=None,
         tile_phys: Optional[int] = None, dataset=None) -> TieredIndex:
    """Split *index* (ivf_flat/ivf_pq) into a :class:`TieredIndex`.

    *hot_fraction* targets the device-resident share of physical rows;
    *hotness* is an optional (n_lists,) probe-count vector (a
    :meth:`TieredSearcher.hotness` snapshot — list size is the cold-start
    proxy without one); *hot_lists* overrides the policy with an explicit
    (n_lists,) bool residency mask.  *dataset* supplies the original
    vectors for the host refine store (``SearchParams.refine_ratio``);
    IVF-Flat reconstructs the store from its own stored vectors when the
    dataset is omitted, IVF-PQ (lossy codes) requires it for refine.
    """
    expects(0.0 <= float(hot_fraction) <= 1.0,
            "hot_fraction must be in [0, 1]")
    host = _host_parts(index)
    kind = host["kind"]
    if kind == "ivf_flat":
        model_host = {"centers": np.asarray(index.centers)}
        aux = {"adaptive_centers": bool(index.adaptive_centers)}
    else:
        model_host = {"centers": np.asarray(index.centers),
                      "rotation": np.asarray(index.rotation),
                      "codebooks": np.asarray(index.codebooks),
                      "list_adc": np.asarray(index.list_adc)}
        aux = {"codebook_kind": int(index.codebook_kind),
               "pq_bits": int(index.pq_bits),
               "pq_dim": int(index.pq_dim),
               "dataset_dtype": index.dataset_dtype}
    store = None
    if dataset is not None:
        store = np.ascontiguousarray(np.asarray(dataset, np.float32))
        expects(store.ndim == 2 and store.shape[1] == int(index.dim),
                "refine dataset must be (n, dim) with the index's dim")
    elif kind == "ivf_flat":
        store = _reconstruct_store(host, int(index.dim))
    return _tier_from_parts(
        host, np.asarray(index.chunk_table), np.asarray(index.list_sizes),
        model_host, index.metric, aux, hot_fraction=hot_fraction,
        hotness=hotness, hot_lists=hot_lists, tile_phys=tile_phys,
        refine_store=store)


def _reconstruct_store(host: dict, dim: int) -> np.ndarray:
    """IVF-Flat refine store from the packed lists themselves: scatter the
    live slots back to their source positions (exact — flat stores the
    vectors, possibly in a widening-exact half dtype)."""
    data, indices, sizes = host["data"], host["indices"], host["sizes"]
    n_phys, cap = indices.shape[0] - 1, indices.shape[1]
    live = np.arange(cap)[None, :] < sizes[:n_phys, None]
    ids = indices[:n_phys][live].astype(np.int64)
    if ids.size == 0:
        return np.zeros((0, dim), np.float32)
    store = np.zeros((int(ids.max()) + 1, dim), np.float32)
    store[ids] = data[:n_phys][live].astype(np.float32)
    return store


def retier(tiered: TieredIndex, hotness=None, *,
           hot_fraction: Optional[float] = None,
           tile_phys: Optional[int] = None) -> TieredIndex:
    """Recut a :class:`TieredIndex`'s residency from fresh hotness
    counters (promotion/demotion) WITHOUT the source index: the full
    per-row blocks live host-side on the tiered container.  Swap the
    result in through ``ServeEngine.refresh`` — warmup happens there, off
    the request path, and the swap is atomic."""
    frac = (float(hot_fraction) if hot_fraction is not None
            else tiered.hot_rows / max(tiered.n_phys, 1))
    model_host = {k: np.asarray(a)
                  for k, a in zip(_model_keys(tiered.kind), tiered.model)}
    out = _tier_from_parts(
        tiered.host, tiered.chunk_table, tiered.list_sizes, model_host,
        tiered.metric, tiered.aux, hot_fraction=frac, hotness=hotness,
        hot_lists=None, tile_phys=tile_phys or tiered.tile_phys,
        refine_store=tiered.refine_store)
    tier_counters.inc("retiers")
    return out


def to_index(tiered: TieredIndex):
    """Reassemble the fully-resident family Index from the host source
    blocks (serialization compat + the bit-identity reference in tests)."""
    h = tiered.host
    model = {k: np.asarray(a)
             for k, a in zip(_model_keys(tiered.kind), tiered.model)}
    if tiered.kind == "ivf_flat":
        return ivf_flat.Index(
            centers=jnp.asarray(model["centers"]),
            list_data=jnp.asarray(h["data"]),
            list_indices=jnp.asarray(h["indices"]),
            list_sizes=jnp.asarray(tiered.list_sizes),
            phys_sizes=jnp.asarray(h["sizes"]),
            chunk_table=jnp.asarray(tiered.chunk_table),
            metric=tiered.metric,
            adaptive_centers=bool(tiered.aux.get("adaptive_centers",
                                                 False)))
    return ivf_pq.Index(
        centers=jnp.asarray(model["centers"]),
        rotation=jnp.asarray(model["rotation"]),
        codebooks=jnp.asarray(model["codebooks"]),
        list_codes=jnp.asarray(h["codes"]),
        list_indices=jnp.asarray(h["indices"]),
        list_sizes=jnp.asarray(tiered.list_sizes),
        phys_sizes=jnp.asarray(h["sizes"]),
        chunk_table=jnp.asarray(tiered.chunk_table),
        owner=jnp.asarray(h["owner"]),
        list_adc=jnp.asarray(model["list_adc"]),
        list_csum=jnp.asarray(h["csum"]),
        metric=tiered.metric,
        codebook_kind=ivf_pq.CodebookKind(tiered.aux["codebook_kind"]),
        pq_bits=int(tiered.aux["pq_bits"]),
        dataset_dtype=tiered.aux.get("dataset_dtype", "float32"))


# ---------------------------------------------------------------------------
# the serving searcher


class TieredSearcher:
    """Two-phase tiered dispatch for one (TieredIndex, k, params) serving
    key — the ``_TieredBackend`` delegate (``serve.engine``), holding the
    warmed executable signatures, the double-buffer staging lanes and the
    device-resident hotness counters."""

    def __init__(self, tiered: TieredIndex, k: int, params=None):
        expects(k >= 1, "k must be >= 1")
        self.tiered = tiered
        self.kind = tiered.kind
        self.k = int(k)
        self.dim = int(tiered.dim)
        self.name = f"tiered_{tiered.kind}"
        self.metric = tiered.metric
        if self.kind == "ivf_flat":
            self.params = params or ivf_flat.SearchParams()
            self.per_cluster = False
            self.lut_dtype = "float32"
            self.int_dtype = "float32"
            self.pq_bits = 0
            self.hoisted = False
            from raft_tpu.kernels.engine import resolve_engine

            self.engine = resolve_engine("select_k", dtype=jnp.float32)
        else:
            self.params = params or ivf_pq.SearchParams()
            expects(self.params.lut_dtype in ivf_pq._LUT_DTYPES,
                    f"lut_dtype must be one of {list(ivf_pq._LUT_DTYPES)}")
            self.per_cluster = (
                ivf_pq.CodebookKind(tiered.aux["codebook_kind"])
                == ivf_pq.CodebookKind.PER_CLUSTER)
            self.lut_dtype = self.params.lut_dtype
            self.int_dtype = self.params.internal_distance_dtype
            self.pq_bits = int(tiered.aux["pq_bits"])
            self.hoisted = (ivf_pq.hoisted_lut_enabled()
                            if self.params.hoisted_lut is None
                            else bool(self.params.hoisted_lut))
            self.engine = ivf_pq._resolve_scan_engine(
                int(tiered.aux["pq_dim"]), self.pq_bits)
        self.n_probes = int(min(self.params.n_probes, tiered.n_lists))
        ratio = getattr(self.params, "refine_ratio", None)
        self.refine_ratio = max(1, int(ratio)) if ratio else 1
        if self.refine_ratio > 1:
            expects(tiered.refine_store is not None,
                    "refine_ratio needs the host refine store — "
                    "tier(..., dataset=original_vectors)")
        self.search_k = self.k * self.refine_ratio
        self.select_min = tiered.metric != DistanceType.InnerProduct
        self._handle = Handle(n_streams=2)
        self._acc = jax.device_put(
            np.zeros((tiered.n_lists,), np.int32), dispatch_device())
        # _backend_fn cost attribution reads the dispatched fn here
        self.fn = _hot_phase_aot

    # -- argument assembly (ONE place, shared by warm and dispatch) --------
    def _hot_args(self, qb, acc):
        t = self.tiered
        return (qb, acc, t.model, t.hot_scan, self.kind, int(t.metric),
                self.search_k, self.n_probes, t.probe_extra_hot,
                self.per_cluster, self.lut_dtype, self.int_dtype,
                self.pq_bits, self.hoisted, self.engine)

    def _cold_args(self, qb, probes, blk):
        t = self.tiered
        return (qb, probes, t.model, blk, self.kind, int(t.metric),
                self.search_k, t.probe_extra_cold, self.per_cluster,
                self.lut_dtype, self.int_dtype, self.pq_bits, self.hoisted,
                self.engine)

    def _run_dtype(self, dtype):
        """The phase runs' distance dtype for *dtype* queries (both
        families accumulate half inputs in f32)."""
        return (accum_dtype(jnp.dtype(dtype)) if self.kind == "ivf_flat"
                else jnp.float32)

    def warm(self, bucket: int, dtype) -> None:
        """Pre-lower EVERY executable one warmed dispatch touches: the
        hot phase, the cold-tile program, the run merge, and the refine
        program — the ServeEngine zero-compile contract extended to the
        tiered path."""
        t = self.tiered
        qspec = jax.ShapeDtypeStruct((bucket, self.dim), dtype)
        aspec = jax.ShapeDtypeStruct((t.n_lists,), jnp.int32)
        _hot_phase_aot.compiled(*self._hot_args(qspec, aspec))
        run_dt = self._run_dtype(dtype)
        dspec = jax.ShapeDtypeStruct((bucket, self.search_k), run_dt)
        ispec = jax.ShapeDtypeStruct((bucket, self.search_k), jnp.int32)
        if t.cold_tiles:
            pspec = jax.ShapeDtypeStruct((bucket, self.n_probes), jnp.int32)
            blk = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                        for a in t.cold_tiles[0])
            _cold_scan_aot.compiled(*self._cold_args(qspec, pspec, blk))
            _merge_aot.compiled(dspec, ispec, dspec, ispec,
                                self.search_k, self.select_min)
        if self.refine_ratio > 1:
            vspec = jax.ShapeDtypeStruct(
                (bucket, self.search_k, self.dim), jnp.float32)
            _refine_aot.compiled(qspec, vspec, ispec, int(t.metric),
                                 self.k, self.engine)

    def batch_cap(self) -> Optional[int]:
        """The hoisted compressed-LUT transient clamp, sized by the FULL
        layout (conservative over both phases' probe budgets) — the ONE
        shared ``ivf_pq.hoisted_batch_cap_dims`` formula."""
        if self.kind != "ivf_pq":
            return None
        t = self.tiered
        return ivf_pq.hoisted_batch_cap_dims(
            t.metric, self.per_cluster, t.n_phys, t.chunk_table.shape[1],
            t.n_lists, int(t.aux["pq_dim"]), self.pq_bits, self.n_probes,
            self.lut_dtype, self.hoisted)

    def ingest(self, q):
        """HOST-side compute-form conversion, mirroring the family
        backends bit for bit (exact widenings stay numpy; only cosine's
        inexact row normalize round-trips the device)."""
        # exempt(hot-path-host-transfer): request ingest of host numpy
        q = np.asarray(q)
        expects(q.ndim == 2 and q.shape[1] == self.dim,
                "query dim mismatch")
        if self.kind == "ivf_pq":
            if q.dtype in (np.int8, np.uint8):
                q_dtype = str(q.dtype)
            else:
                expects(jnp.issubdtype(q.dtype, jnp.floating),
                        f"ivf_pq: unsupported query dtype {q.dtype}")
                q_dtype = "float32"
            expects(q_dtype in (self.tiered.aux["dataset_dtype"],
                                "float32"),
                    f"query dtype {q_dtype} != index dataset dtype "
                    f"{self.tiered.aux['dataset_dtype']}")
            return q.astype(np.float32)
        if q.dtype in (np.int8, np.uint8):
            q = q.astype(np.float32)  # exact widening: matches device cast
        if self.metric == DistanceType.CosineExpanded:
            # exempt(hot-path-host-transfer): cosine solo-numerics
            return np.asarray(ivf_flat._normalize_rows(jnp.asarray(q)))
        return q

    def _stage(self, tile, lane: int, key: str):
        """The ONE sanctioned host→device transfer site: hand one cold
        tile (or one refine gather) to the async copy on a pool lane."""
        t0 = telemetry.now()
        stream = self._handle.get_next_usable_stream(lane)
        # the designed cold-tier transfer — O(tile) host arrays to the
        # dispatch device, double-buffered across pool lanes:
        # tier-staging(hot-path-host-transfer): docs/index_tiering.md
        staged = stream.stage(tile)
        prefetch_seconds.observe(telemetry.now() - t0)
        tier_counters.inc(key, sum(int(a.nbytes) for a in tile))
        return staged

    def dispatch(self, qb):
        """One super-batch through the two-phase program: hot phase (ONE
        executable, probe ids + hot run + counter accumulate), then each
        cold tile staged ahead one lane and folded into the running top-k
        (run *a* = earlier parts, the merge_sorted_parts order), then the
        optional exact re-rank.  Every device call here dispatches a
        warmed executable — zero compiles in the warmed steady state."""
        t = self.tiered
        probes, d, i, self._acc = _hot_phase_aot(
            *self._hot_args(qb, self._acc))
        tier_counters.inc("hot_dispatches")
        if t.cold_tiles:
            d, i = self._run_cold(qb, probes, d, i)
        if self.refine_ratio > 1:
            d, i = self._refine(qb, i)
        return d, i

    def _run_cold(self, qb, probes, d, i):
        """The cold sweep: stage tile n+1 on the alternate lane while tile
        n scores (double-buffered prefetch), fold each tile's sorted run
        into the running top-k in storage order (run *a* = earlier parts —
        the ``merge_sorted_parts`` fold order, so the final top-k is the
        stable full sort's)."""
        tiles = self.tiered.cold_tiles
        lane = 0
        cur = self._stage(tiles[0], lane, "prefetch_bytes")
        for n in range(len(tiles)):
            nxt = (self._stage(tiles[n + 1], 1 - lane, "prefetch_bytes")
                   if n + 1 < len(tiles) else None)
            td, ti = _cold_scan_aot(*self._cold_args(qb, probes, cur))
            d, i = merge_sorted_runs(d, i, td, ti, k=self.search_k,
                                     select_min=self.select_min)
            tier_counters.inc("cold_tiles")
            cur, lane = nxt, 1 - lane
        return d, i

    def _refine(self, qb, ids):
        """Exact re-rank: ONE amortized candidate-id fetch per
        super-batch, host gather from the refine store, ONE staged upload,
        one warmed re-score program."""
        t = self.tiered
        # the designed refine gather, once per super-batch:
        # exempt(hot-path-host-transfer): (nq, k·ratio) candidate-id fetch
        ids_host = np.asarray(ids)
        rows = np.clip(ids_host, 0, t.refine_store.shape[0] - 1)
        vecs = np.ascontiguousarray(t.refine_store[rows])
        vecs_d, ids_d = self._stage((vecs, ids_host), 0,
                                    "refine_gather_bytes")
        return _refine_aot(qb, vecs_d, ids_d, int(t.metric), self.k,
                           self.engine)

    def solo(self, q):
        """Uncoalesced fallback (compiles allowed — off the warmed path)."""
        return search(self.tiered, q, self.k, params=self.params)

    def hotness(self) -> np.ndarray:
        """Snapshot the device-resident per-list probe counters — the
        re-tiering policy input.  Off the dispatch path (refresh loop) —
        hotness() is outside the declared hot-path scope, so the
        (n_lists,) fetch needs no marker (the _build.py precedent)."""
        return np.asarray(self._acc)

    def reset_hotness(self) -> None:
        self._acc = jax.device_put(
            np.zeros((self.tiered.n_lists,), np.int32), dispatch_device())

    def tier_stats(self) -> dict:
        """Residency summary for /healthz and the bench report."""
        t = self.tiered
        return {"kind": t.kind, "n_lists": t.n_lists,
                "hot_lists": t.n_hot_lists, "hot_rows": t.hot_rows,
                "total_rows": t.n_phys, "cold_tiles": len(t.cold_tiles),
                "tile_phys": t.tile_phys,
                "device_bytes": t.device_bytes(),
                "tile_bytes": t.tile_bytes(),
                "refine_ratio": self.refine_ratio}


def search(tiered: TieredIndex, queries, k: int, params=None
           ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Eager tiered search (the solo/convenience entry — serving goes
    through ``serve.ServeEngine`` with the tiered backend).  Returns
    (distances [nq, k], indices [nq, k]), bit-identical to the
    fully-resident family search on tie-free data."""
    s = tiered.searcher(int(k), params)
    q = s.ingest(queries)
    nq = q.shape[0]
    if nq == 0:
        dt = jnp.float32 if s.refine_ratio > 1 else s._run_dtype(q.dtype)
        return empty_result(0, s.k, dt)
    bucket = _bucket_dim(nq)
    block = np.zeros((bucket, tiered.dim), q.dtype)
    block[:nq] = q
    d, i = s.dispatch(jnp.asarray(block))
    return d[:nq], i[:nq]


# ---------------------------------------------------------------------------
# audit programs (analysis catalog: fingerprint goldens + transient
# ceilings proving O(tile) cold-tier search residency)


@hlo_program(
    "tiering.cold_scan",
    collectives=0, collective_bytes=0,
    # ONE staged tile's scan: the gathered (nq, cap, …) probe step + the
    # per-batch LUT — O(tile_phys), NEVER an index-sized transient (the
    # whole point of the cold tier); the audit shape sits far below this
    transient_bytes=2 << 20,
    notes="one cold-tier tile scored as ONE program over staged O(tile) "
          "buffers — the tiered ServeEngine backend's cold phase "
          "(docs/index_tiering.md)")
def _audit_cold_scan():
    import numpy as np

    x = np.random.default_rng(0).standard_normal((2048, 32)
                                                 ).astype(np.float32)
    idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=16, pq_dim=8, pq_bits=8),
                       x)
    t = tier(idx, hot_fraction=0.5, tile_phys=8, dataset=x)
    q = jax.ShapeDtypeStruct((64, 32), jnp.float32)
    probes = jax.ShapeDtypeStruct((64, 4), jnp.int32)
    blk = tuple(jax.ShapeDtypeStruct(a.shape, jnp.dtype(a.dtype))
                for a in t.cold_tiles[0])
    return dict(fn=_cold_scan_impl,
                args=(q, probes, t.model, blk, "ivf_pq",
                      int(DistanceType.L2SqrtExpanded), 8,
                      t.probe_extra_cold, False, "float32", "float32", 8,
                      True, "xla"),
                static_argnums=_COLD_STATICS)


@hlo_program(
    "tiering.refine",
    collectives=0, collective_bytes=0,
    # exact re-score over the staged (nq, k·ratio, dim) gather + select
    # scratch — O(nq·k·ratio·dim), no index-sized term
    transient_bytes=2 << 20,
    notes="exact re-rank of the top k·ratio candidates' staged original "
          "vectors — the refine_ratio recall safety net "
          "(docs/index_tiering.md)")
def _audit_refine():
    q = jax.ShapeDtypeStruct((64, 32), jnp.float32)
    vecs = jax.ShapeDtypeStruct((64, 32, 32), jnp.float32)
    ids = jax.ShapeDtypeStruct((64, 32), jnp.int32)
    return dict(fn=_refine_impl,
                args=(q, vecs, ids, int(DistanceType.L2SqrtExpanded), 8,
                      "xla"),
                static_argnums=_REFINE_STATICS)
