"""RNG + data generators (reference raft/random/ — SURVEY.md §2.6)."""

from raft_tpu.random.rng import (  # noqa: F401
    GeneratorType,
    RngState,
    bernoulli,
    discrete,
    exponential,
    fill,
    gumbel,
    laplace,
    logistic,
    lognormal,
    normal,
    normal_int,
    normal_table,
    permute,
    rayleigh,
    sample_without_replacement,
    scaled_bernoulli,
    uniform,
    uniform_int,
)
from raft_tpu.random.generators import (  # noqa: F401
    make_blobs,
    make_regression,
    multi_variable_gaussian,
    rmat_rectangular_gen,
)
