"""Synthetic data generators.

Counterparts of reference raft/random/{make_blobs,make_regression,
multi_variable_gaussian,rmat_rectangular_generator}.cuh.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.handle import auto_sync_handle
from raft_tpu.random.rng import _key_of


def make_blobs(
    rng,
    n_samples: int,
    n_features: int,
    n_clusters: int = 3,
    cluster_std: float = 1.0,
    centers=None,
    center_box: Tuple[float, float] = (-10.0, 10.0),
    shuffle: bool = True,
    dtype=jnp.float32,
):
    """Isotropic Gaussian blobs (reference random/make_blobs.cuh:63).

    Returns (X[n_samples, n_features], labels[n_samples], centers).
    """
    key = _key_of(rng)
    k_centers, k_labels, k_noise, k_shuffle = jax.random.split(key, 4)
    if centers is None:
        lo, hi = center_box
        centers = jax.random.uniform(k_centers, (n_clusters, n_features),
                                     dtype=dtype, minval=lo, maxval=hi)
    else:
        centers = jnp.asarray(centers, dtype)
        n_clusters = centers.shape[0]
    # Balanced labels like the reference's default (proportions=None).
    labels = jnp.arange(n_samples) % n_clusters
    if shuffle:
        labels = jax.random.permutation(k_shuffle, labels)
    noise = jax.random.normal(k_noise, (n_samples, n_features), dtype=dtype)
    x = jnp.take(centers, labels, axis=0) + cluster_std * noise
    return x, labels.astype(jnp.int32), centers


def make_regression(
    rng,
    n_samples: int,
    n_features: int,
    n_informative: Optional[int] = None,
    n_targets: int = 1,
    bias: float = 0.0,
    noise: float = 0.0,
    effective_rank: Optional[int] = None,
    tail_strength: float = 0.5,
    shuffle: bool = True,
    coef: bool = False,
    dtype=jnp.float32,
):
    """Linear-model regression problem (reference random/make_regression.cuh).

    Returns (X, y[, w]) with y = X·w + bias + N(0, noise).
    """
    if n_informative is None:
        n_informative = n_features
    n_informative = min(n_informative, n_features)
    key = _key_of(rng)
    k_x, k_w, k_noise, k_shuf, k_lr = jax.random.split(key, 5)
    x = jax.random.normal(k_x, (n_samples, n_features), dtype=dtype)
    if effective_rank is not None:
        # Low-rank-plus-tail singular profile (reference uses the same
        # scheme borrowed from sklearn's make_low_rank_matrix).
        n = min(n_samples, n_features)
        sing = jnp.arange(n, dtype=dtype)
        low = jnp.exp(-(sing / effective_rank) ** 2)
        tail = jnp.exp(-0.1 * sing / effective_rank)
        s = (1 - tail_strength) * low + tail_strength * tail
        u, _, vt = jnp.linalg.svd(x, full_matrices=False)
        x = (u * s[None, :]) @ vt
    w = jnp.zeros((n_features, n_targets), dtype=dtype)
    w_inf = 100.0 * jax.random.uniform(k_w, (n_informative, n_targets), dtype=dtype)
    w = w.at[:n_informative].set(w_inf)
    y = x @ w + bias
    if noise > 0:
        y = y + noise * jax.random.normal(k_noise, y.shape, dtype=dtype)
    if shuffle:
        perm = jax.random.permutation(k_shuf, n_samples)
        x, y = x[perm], y[perm]
    y = y.squeeze(-1) if n_targets == 1 else y
    if coef:
        return x, y, w.squeeze(-1) if n_targets == 1 else w
    return x, y


def multi_variable_gaussian(rng, mean, cov, n_samples: int = 1,
                            method: str = "cholesky"):
    """Sample from N(mean, cov) (reference
    random/multi_variable_gaussian.cuh — cuSOLVER potrf/eig there, XLA
    cholesky/eigh here).  Returns [n_samples, dim]."""
    mean = jnp.asarray(mean)
    cov = jnp.asarray(cov)
    dim = mean.shape[0]
    expects(cov.shape == (dim, dim), "cov must be [dim, dim]")
    key = _key_of(rng)
    z = jax.random.normal(key, (n_samples, dim), dtype=cov.dtype)
    if method == "cholesky":
        l_factor = jnp.linalg.cholesky(cov)
        samples = z @ l_factor.T
    else:  # eigendecomposition path ("jacobi" in the reference)
        w, v = jnp.linalg.eigh(cov)
        samples = z @ (v * jnp.sqrt(jnp.maximum(w, 0))[None, :]).T
    return mean[None, :] + samples


@auto_sync_handle
def rmat_rectangular_gen(rng, theta, r_scale: int, c_scale: int, n_edges: int,
                         clip_and_flip: bool = False, handle=None):
    """Stochastic Kronecker (R-MAT) graph generator (reference
    random/rmat_rectangular_generator.cuh:75).

    *theta* is the per-level quadrant distribution, shape
    [max(r_scale, c_scale), 4] (a, b, c, d per level), or [4] to reuse one
    distribution for all levels.  Returns (out[n_edges, 2], src, dst) with
    src ∈ [0, 2^r_scale), dst ∈ [0, 2^c_scale).

    TPU-first design: instead of the reference's per-thread loop over levels,
    sample all (edge, level) quadrant choices in one [n_edges, max_scale]
    categorical draw and reduce with bit-shifts — one fused XLA program.
    """
    theta = jnp.asarray(theta, jnp.float32)
    max_scale = max(r_scale, c_scale)
    if theta.ndim == 1:
        theta = jnp.broadcast_to(theta[None, :], (max_scale, 4))
    expects(theta.shape[0] >= max_scale, "theta must cover max(r_scale, c_scale) levels")
    key = _key_of(rng)
    logits = jnp.log(jnp.maximum(theta[:max_scale], 1e-37))  # [L, 4]
    # quad[e, l] ∈ {0,1,2,3} = (row_bit<<1)|col_bit
    quad = jax.random.categorical(key, logits[None, :, :], axis=-1,
                                  shape=(n_edges, max_scale))
    row_bits = (quad >> 1) & 1
    col_bits = quad & 1
    # Level l contributes bit (scale-1-l); levels beyond a side's scale
    # contribute nothing to that side (rectangular adjustment).
    r_weights = jnp.where(jnp.arange(max_scale) < r_scale,
                          1 << (jnp.maximum(r_scale - 1 - jnp.arange(max_scale), 0)), 0)
    c_weights = jnp.where(jnp.arange(max_scale) < c_scale,
                          1 << (jnp.maximum(c_scale - 1 - jnp.arange(max_scale), 0)), 0)
    src = jnp.sum(row_bits * r_weights[None, :], axis=1).astype(jnp.int64)
    dst = jnp.sum(col_bits * c_weights[None, :], axis=1).astype(jnp.int64)
    if clip_and_flip:
        # Mirror edges above the diagonal into the lower triangle (square case).
        lo = jnp.minimum(src, dst)
        hi = jnp.maximum(src, dst)
        src, dst = hi, lo
    out = jnp.stack([src, dst], axis=1)
    return out, src, dst
