"""Device RNG: counter-based generators and the distribution surface.

Counterpart of reference raft/random/rng.cuh + rng_state.hpp:28-52 —
``RngState`` {seed, base_subsequence, GeneratorType} with device-side Philox/
PCG generators (random/detail/rng_device.cuh:438,536).  JAX's RNG is already
counter-based (threefry2x32 default, or rbg), so :class:`RngState` maps
directly: seed → PRNGKey, base_subsequence → fold_in counter.  Every call
advances the subsequence exactly like the reference's
``rng_state.advance(...)``, so results are reproducible per (seed, call #).
"""

from __future__ import annotations

import enum
from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects


class GeneratorType(enum.Enum):
    """reference random/rng_state.hpp:28 — GenPhilox / GenPC."""

    GenPhilox = "philox"  # → threefry (counter-based, same guarantees)
    GenPC = "pc"  # → rbg


class RngState:
    """Mutable RNG state (reference random/rng_state.hpp:37-52)."""

    def __init__(self, seed: int = 0, base_subsequence: int = 0,
                 type: GeneratorType = GeneratorType.GenPhilox):
        self.seed = int(seed)
        self.base_subsequence = int(base_subsequence)
        self.type = type

    def advance(self, subsequences: int = 1) -> None:
        """reference rng_state.hpp ``advance``."""
        self.base_subsequence += int(subsequences)

    def key(self) -> jax.Array:
        k = jax.random.PRNGKey(self.seed)
        return jax.random.fold_in(k, self.base_subsequence)

    def next_key(self) -> jax.Array:
        k = self.key()
        self.advance()
        return k


def _key_of(rng) -> jax.Array:
    if isinstance(rng, RngState):
        return rng.next_key()
    return rng  # raw PRNGKey


# -- distributions (reference random/rng.cuh) --------------------------------

def uniform(rng, shape, low=0.0, high=1.0, dtype=jnp.float32):
    return jax.random.uniform(_key_of(rng), shape, dtype=dtype, minval=low, maxval=high)


def uniform_int(rng, shape, low, high, dtype=jnp.int32):
    return jax.random.randint(_key_of(rng), shape, low, high, dtype=dtype)


def normal(rng, shape, mu=0.0, sigma=1.0, dtype=jnp.float32):
    return mu + sigma * jax.random.normal(_key_of(rng), shape, dtype=dtype)


def normal_int(rng, shape, mu, sigma, dtype=jnp.int32):
    return jnp.rint(mu + sigma * jax.random.normal(_key_of(rng), shape)).astype(dtype)


def normal_table(rng, n_rows, mu_vec, sigma_vec=None, sigma=1.0, dtype=jnp.float32):
    """Per-column mean/std normal table (reference ``normalTable``)."""
    mu_vec = jnp.asarray(mu_vec, dtype)
    n_cols = mu_vec.shape[0]
    sig = jnp.asarray(sigma_vec, dtype) if sigma_vec is not None else sigma
    z = jax.random.normal(_key_of(rng), (n_rows, n_cols), dtype=dtype)
    return mu_vec[None, :] + z * (sig[None, :] if sigma_vec is not None else sig)


def lognormal(rng, shape, mu=0.0, sigma=1.0, dtype=jnp.float32):
    return jnp.exp(normal(rng, shape, mu, sigma, dtype))


def gumbel(rng, shape, mu=0.0, beta=1.0, dtype=jnp.float32):
    return mu + beta * jax.random.gumbel(_key_of(rng), shape, dtype=dtype)


def logistic(rng, shape, mu=0.0, scale=1.0, dtype=jnp.float32):
    return mu + scale * jax.random.logistic(_key_of(rng), shape, dtype=dtype)


def exponential(rng, shape, lambda_=1.0, dtype=jnp.float32):
    return jax.random.exponential(_key_of(rng), shape, dtype=dtype) / lambda_


def rayleigh(rng, shape, sigma=1.0, dtype=jnp.float32):
    u = jax.random.uniform(_key_of(rng), shape, dtype=dtype, minval=1e-12, maxval=1.0)
    return sigma * jnp.sqrt(-2.0 * jnp.log(u))


def laplace(rng, shape, mu=0.0, scale=1.0, dtype=jnp.float32):
    return mu + scale * jax.random.laplace(_key_of(rng), shape, dtype=dtype)


def bernoulli(rng, shape, prob=0.5):
    return jax.random.bernoulli(_key_of(rng), prob, shape)


def scaled_bernoulli(rng, shape, prob=0.5, scale=1.0, dtype=jnp.float32):
    """±scale with P(+)=1-prob (reference ``scaled_bernoulli``)."""
    b = jax.random.bernoulli(_key_of(rng), prob, shape)
    return jnp.where(b, -scale, scale).astype(dtype)


def fill(rng, shape, value, dtype=jnp.float32):
    """reference ``fill`` (lives in rng.cuh for historical reasons)."""
    return jnp.full(shape, value, dtype=dtype)


def discrete(rng, shape, weights, dtype=jnp.int32):
    """Sample indices ∝ weights (reference ``discrete``)."""
    w = jnp.asarray(weights)
    logits = jnp.log(jnp.maximum(w, 1e-37))
    return jax.random.categorical(_key_of(rng), logits, shape=shape).astype(dtype)


def sample_without_replacement(rng, in_items, n_samples: int, weights=None,
                               return_indices: bool = False):
    """Weighted sampling without replacement (reference
    ``sampleWithoutReplacement``, rng.cuh) — Gumbel-top-k trick: one sort,
    no rejection loop (TPU-friendly; the reference uses per-thread rejection).
    """
    in_items = jnp.asarray(in_items)
    n = in_items.shape[0]
    expects(0 < n_samples <= n, "sampledLen must be in (0, len]")
    key = _key_of(rng)
    g = jax.random.gumbel(key, (n,))
    if weights is not None:
        g = g + jnp.log(jnp.maximum(jnp.asarray(weights), 1e-37))
    _, idx = jax.lax.top_k(g, n_samples)
    out = jnp.take(in_items, idx, axis=0)
    if return_indices:
        return out, idx
    return out


def permute(rng, in_array=None, n: Optional[int] = None, return_perm: bool = True):
    """Random permutation of rows (reference random/permute.cuh).  Returns
    (permuted_rows, perm) like the reference's (out, outPerms)."""
    if in_array is not None:
        n = in_array.shape[0]
    perm = jax.random.permutation(_key_of(rng), n)
    if in_array is None:
        return perm
    out = jnp.take(in_array, perm, axis=0)
    return (out, perm) if return_perm else out
