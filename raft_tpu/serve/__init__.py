"""Batched query serving — coalesced, bucket-compiled, zero-retrace ANN
dispatch over the neighbors backends (docs/serving.md).

Public surface:

- :class:`ServeEngine` — one engine per (index, k, params) serving key:
  request coalescing into bucket-padded super-batches, executable
  warmup/pinning through the ``core.aot`` cache, double-buffered dispatch
  over the handle's stream pool, solo fallback for out-of-range requests.
"""

from raft_tpu.serve.engine import ServeEngine  # noqa: F401

__all__ = ["ServeEngine"]
