"""Batched query serving — coalesced, bucket-compiled, zero-retrace ANN
dispatch over the neighbors backends (docs/serving.md).

Public surface:

- :class:`ServeEngine` — one engine per (index, k, params) serving key:
  request coalescing into bucket-padded super-batches, executable
  warmup/pinning through the ``core.aot`` cache, double-buffered dispatch
  over the handle's stream pool, solo fallback for out-of-range requests.
- The failure-handling layer (docs/serving.md §failure model):
  :class:`ServeRequest` (deadline/timeout envelope),
  :class:`AdmissionController` + :class:`RejectedError` (deadline-aware
  admission, load shedding, typed rejection),
  :class:`DispatchSupervisor` + :class:`WatchdogTimeout` /
  :class:`DispatchError` (watchdog, bounded retry/backoff,
  fail-fast classification).
- :class:`AutoTuner` + :class:`TunerConfig` / :class:`Candidate`
  (docs/serving.md §autotuning): online shadow-canary knob search over
  the certified warmed-signature ladder with atomic zero-compile
  promotion through ``refresh`` and a guarded rollback window.
"""

from raft_tpu.serve.admission import (  # noqa: F401
    AdmissionController,
    RejectedError,
    ServeRequest,
)
from raft_tpu.serve.autotune import (  # noqa: F401
    AutoTuner,
    Candidate,
    TunerConfig,
)
from raft_tpu.serve.engine import ServeEngine  # noqa: F401
from raft_tpu.serve.schedule import (  # noqa: F401
    CostModel,
    ReplicaRouter,
    SchedulerConfig,
)
from raft_tpu.serve.supervise import (  # noqa: F401
    DispatchError,
    DispatchSupervisor,
    WatchdogTimeout,
)

__all__ = ["ServeEngine", "ServeRequest", "AdmissionController",
           "RejectedError", "DispatchSupervisor", "DispatchError",
           "WatchdogTimeout", "SchedulerConfig", "CostModel",
           "ReplicaRouter", "AutoTuner", "TunerConfig", "Candidate"]
