"""Deadline-aware admission control and load shedding for the serving
engine (docs/serving.md §failure model).

An overloaded queue with no admission policy has unbounded latency: every
request is eventually served, and every request is eventually late.  The
production contract is the opposite — requests that cannot meet their
deadline are REJECTED at admission with a typed error (cheap, immediate,
actionable for the caller) so the requests that ARE admitted keep a
bounded p99.  Three pieces:

* :class:`ServeRequest` — the request envelope: a query batch plus an
  optional absolute ``deadline_s`` (on the ``telemetry.now()`` clock) or
  relative ``timeout_s`` (resolved against admission time).  Plain arrays
  remain valid requests (no deadline, never shed on deadline).
* :class:`RejectedError` — the typed rejection every shed request
  receives IN ITS RESULT SLOT (``reason`` ∈ {"deadline", "overload",
  "expired", "closed"}); other requests in the same call are unaffected.
* :class:`AdmissionController` — the policy object one engine owns.  The
  per-super-batch cost estimate is seeded from LIVE telemetry: the
  sampled true device seconds of the backend's program
  (``raft_tpu_device_seconds{fn}`` p50), falling back to the host-side
  dispatch-latency histogram (``raft_tpu_aot_dispatch_seconds{fn,sig}``
  rows merged across signatures), falling back to a static estimate when
  cold.  A request's projected completion is (batches ahead of it + its
  own) × that estimate; a deadline that cannot cover the projection sheds
  at admission.

Overload policy (``policy=``, the documented choice):

* ``"shed-newest"`` (default) — when the bounded queue
  (``max_queue`` queries per call) would overflow, the NEWEST arrival is
  shed (``reason="overload"``).  Admission is a promise: admitted
  requests are always dispatched, and ones that complete past their
  deadline are merely COUNTED expired.
* ``"shed-over-deadline"`` — additionally, an admitted request whose
  deadline has already passed when its super-batch assembles is dropped
  there (``reason="expired"``) instead of burning device time on an
  answer nobody is waiting for.

Counters (``telemetry``-registered, labeled per engine):
``raft_tpu_serve_admitted_total{engine}``,
``raft_tpu_serve_shed_total{engine,reason}``,
``raft_tpu_serve_expired_total{engine}`` — plus mirror keys in
``ServeEngine.stats``.  Recent shedding/expiry flips the engine's
``/healthz`` body to ``degraded: true`` (still HTTP 200 — the engine IS
serving; a load balancer that wants to route away can read the flag).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

from raft_tpu import telemetry
from raft_tpu.core.error import RaftError, expects

#: fallback per-super-batch service-time estimate before any telemetry
#: exists (cold start) — deliberately conservative for CPU-class hosts;
#: real deployments converge onto measured values after the first batches
DEFAULT_STATIC_BATCH_S = 0.05

#: /healthz reports ``degraded: true`` for this long after a shed/expiry
DEGRADED_WINDOW_S = 30.0

POLICIES = ("shed-newest", "shed-over-deadline")


class RejectedError(RaftError):
    """A request shed by admission control (or refused by a closed
    engine).  ``reason`` is machine-readable: ``"deadline"`` (projected
    completion past the deadline), ``"overload"`` (bounded queue full),
    ``"expired"`` (admitted, but the deadline passed before dispatch —
    shed-over-deadline policy), ``"closed"`` (engine shut down)."""

    def __init__(self, reason: str, message: str = ""):
        super().__init__(message or f"request rejected: {reason}")
        self.reason = reason


@dataclasses.dataclass
class ServeRequest:
    """The deadline-carrying request envelope.

    ``deadline_s`` is ABSOLUTE on the ``telemetry.now()`` clock (i.e.
    ``telemetry.now() + budget``); ``timeout_s`` is RELATIVE and resolves
    to ``now + timeout_s`` at admission.  Passing both takes the tighter
    one.  With neither, the request is never deadline-shed (it can still
    be overload-shed by the queue bound)."""

    q: Any
    deadline_s: Optional[float] = None
    timeout_s: Optional[float] = None

    def resolve_deadline(self, now: float) -> Optional[float]:
        cands = []
        if self.deadline_s is not None:
            cands.append(float(self.deadline_s))
        if self.timeout_s is not None:
            cands.append(now + float(self.timeout_s))
        return min(cands) if cands else None


def _batch_cost_from_telemetry(fn: Optional[str]) -> Optional[float]:
    """The live per-super-batch cost estimate for program *fn*: sampled
    device seconds p50 first (the truest number), host-side dispatch
    latency second (always populated once serving)."""
    if not fn:
        return None
    dev = telemetry.REGISTRY.get("raft_tpu_device_seconds")
    if dev is not None:
        q = dev.quantile(0.5, (fn,))
        if q is not None:
            return float(q)
    disp = telemetry.REGISTRY.get("raft_tpu_aot_dispatch_seconds")
    if disp is not None:
        # (fn, sig)-labeled: merge every signature row of this fn on the
        # shared bucket geometry (the aggregate.merge property) — ONE
        # implementation, shared with the scheduler's cost model
        from raft_tpu.telemetry.registry import merged_quantile

        est = merged_quantile(disp, 0.5, (fn,))
        if est is not None:
            return float(est)
    return None


class AdmissionController:
    """Deadline-aware admission + bounded-queue load shedding for ONE
    engine (the engine constructs a default controller; pass your own to
    tune policy/bounds, or ``admission=False`` to disable the layer)."""

    def __init__(self, policy: str = "shed-newest",
                 max_queue: Optional[int] = None,
                 static_batch_s: float = DEFAULT_STATIC_BATCH_S,
                 degraded_window_s: float = DEGRADED_WINDOW_S,
                 use_telemetry: bool = True):
        expects(policy in POLICIES,
                f"admission policy {policy!r} (want one of {POLICIES})")
        expects(max_queue is None or max_queue >= 1,
                "max_queue must be >= 1 (or None for unbounded)")
        self.policy = policy
        self.max_queue = max_queue
        self.static_batch_s = float(static_batch_s)
        self.degraded_window_s = float(degraded_window_s)
        #: False pins the cost model to static_batch_s (deterministic
        #: tests / bench scenarios); True (default) prefers live signals
        self.use_telemetry = bool(use_telemetry)
        #: EWMA of the OWNING engine's observed end-to-end per-batch wall
        #: time (engine feeds it after each call) — the most faithful
        #: planning number, since the registry's device/dispatch
        #: histograms see device or host-dispatch time but not the full
        #: assemble→deliver service time a queued request actually waits
        self._observed_batch_s: Optional[float] = None
        self._last_event = float("-inf")  # last shed/expiry, now() clock
        self._engine = "?"
        self._admitted = telemetry.counter(
            "raft_tpu_serve_admitted_total",
            "requests admitted by deadline-aware admission control",
            labelnames=("engine",))
        self._shed = telemetry.counter(
            "raft_tpu_serve_shed_total",
            "requests shed at admission (deadline/overload) or refused "
            "closed", labelnames=("engine", "reason"))
        self._expired = telemetry.counter(
            "raft_tpu_serve_expired_total",
            "admitted requests whose deadline passed before dispatch "
            "(dropped under shed-over-deadline, served late otherwise)",
            labelnames=("engine",))

    def bind(self, engine_label: str) -> "AdmissionController":
        """Pin the engine label the counters record under (called by the
        owning engine; one controller serves one engine)."""
        self._engine = str(engine_label)
        return self

    # -- cost model ---------------------------------------------------------
    def observe_batches(self, n_batches: int, wall_s: float) -> None:
        """Feed one serving call's observed (super-batches, wall seconds)
        back into the cost model (EWMA) — the engine calls this after
        every call that dispatched coalesced batches, so the estimate
        self-corrects from SERVED traffic instead of trusting the
        device-time histogram's lower bound forever."""
        if n_batches <= 0 or wall_s <= 0.0:
            return
        per = float(wall_s) / float(n_batches)
        if self._observed_batch_s is None:
            self._observed_batch_s = per
        else:
            self._observed_batch_s = (0.7 * self._observed_batch_s
                                      + 0.3 * per)

    def reset_observed(self) -> None:
        """Drop the observed per-batch EWMA (the autotuner's promotion
        hook): after a config swap the old observations describe the OLD
        config — the estimate re-converges from the telemetry seed under
        the new one instead of blending stale costs in."""
        self._observed_batch_s = None

    def batch_cost_s(self, fn: Optional[str]) -> float:
        """Estimated seconds to serve ONE coalesced super-batch of program
        *fn*: the engine's own observed end-to-end per-batch time first,
        then the registry telemetry (sampled device seconds p50 /
        dispatch-latency rows), then the static estimate when cold.
        ``use_telemetry=False`` pins to static (deterministic tests)."""
        if not self.use_telemetry:
            return self.static_batch_s
        if self._observed_batch_s is not None:
            return self._observed_batch_s
        est = _batch_cost_from_telemetry(fn)
        return self.static_batch_s if est is None else est

    # -- admission decisions (engine-driven; engine owns its stats mirror) --
    def admit(self, n_queries: int, deadline: Optional[float], now: float,
              queued_queries: int, batches_ahead: int,
              est_batch_s: float) -> Optional[RejectedError]:
        """One admission decision.  Returns None (admitted — counted) or
        the :class:`RejectedError` to place in the request's result slot
        (counted shed).  ``batches_ahead`` is how many super-batches are
        already planned ahead of this request in the call."""
        if self.max_queue is not None \
                and queued_queries + n_queries > self.max_queue:
            return self._reject(
                "overload", now,
                f"bounded queue full ({queued_queries} queries queued, "
                f"bound {self.max_queue}) — overload policy "
                f"{self.policy} sheds the newest arrival")
        if deadline is not None:
            projected = (batches_ahead + 1) * est_batch_s
            if now + projected > deadline:
                return self._reject(
                    "deadline", now,
                    f"remaining budget {max(0.0, deadline - now):.4f}s < "
                    f"projected completion {projected:.4f}s "
                    f"({batches_ahead} batch(es) ahead at "
                    f"~{est_batch_s:.4f}s each) — shed at admission")
        self._admitted.inc(1, (self._engine,))
        return None

    def expire(self, deadline: float, now: float) -> Optional[RejectedError]:
        """Dispatch-time deadline check for an ADMITTED request: count it
        expired; under ``shed-over-deadline`` also return the rejection to
        drop it from the super-batch (None = serve it anyway, late)."""
        self._expired.inc(1, (self._engine,))
        self._last_event = now
        if self.policy != "shed-over-deadline":
            return None
        return RejectedError(
            "expired",
            f"deadline passed {now - deadline:.4f}s before dispatch "
            "(admitted under estimate; dropped by shed-over-deadline)")

    def reject_closed(self) -> RejectedError:
        return RejectedError("closed", "engine is closed")

    def _reject(self, reason: str, now: float, msg: str) -> RejectedError:
        self._shed.inc(1, (self._engine, reason))
        self._last_event = now
        return RejectedError(reason, msg)

    # -- /healthz surface ---------------------------------------------------
    def degraded(self, now: float) -> bool:
        """True while the engine shed or expired a request within the
        degraded window — the non-503 overload flag /healthz exposes."""
        return (now - self._last_event) < self.degraded_window_s

    def health(self, now: float) -> dict:
        eng = (self._engine,)
        shed = sum(v for labels, v in self._shed.items()
                   if labels and labels[0] == self._engine)
        return {
            "policy": self.policy,
            "max_queue": self.max_queue,
            "degraded": self.degraded(now),
            "admitted_total": int(self._admitted.get(eng)),
            "shed_total": int(shed),
            "expired_total": int(self._expired.get(eng)),
        }
