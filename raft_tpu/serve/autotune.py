"""Online serving autotuner (docs/serving.md §autotuning): shadow-canary
knob search with atomic zero-compile promotion.

Every serving knob the PR arc accumulated — the bucket-ladder cap,
``n_probes``/``refine_ratio`` (and kernel-engine choice) inside the
backend's ``SearchParams``, the scheduler quantum — was hand-set, while
the runtime already measures everything needed to set them: per-bucket
cost EWMAs, per-request completion latencies, per-program device seconds.
This module closes the loop, under three hard constraints that make an
ONLINE tuner safe on a serving process:

* **Zero-compile exploration by construction.**  The candidate space is
  derived from the engine's certified warmed-signature ladder
  (:meth:`ServeEngine.warmed_signatures`): bucket-cap candidates are
  SUBSETS of the warmed set, and backend-params candidates (``n_probes``,
  ``refine_ratio``, engine choice) are pre-lowered once by
  :meth:`AutoTuner.warm_candidates` — off the request path, through the
  same shared ``aot()`` caches ``warmup()`` pins — before any shadow
  traffic flows.  After that, explore and promotion dispatch only warmed
  executables (the retrace certifier pins this statically:
  ``serve.tuner_closure.*`` obligations; the bench counter-asserts it at
  runtime).
* **Shadow evaluation off the serving path.**  Candidates replay shadow
  traffic — sampled live requests from the engine's bounded shadow ring
  plus (optionally) the bench traffic-plan DSL — against an off-path
  warmed lane: a param candidate's own pre-warmed backend, or (replica
  engines) a :meth:`~raft_tpu.serve.schedule.ReplicaRouter.drain`-ed
  replica lane.  Live requests are never shed for or failed by an
  evaluation; replays through the live backend serialize each
  super-batch dispatch under the engine lock (the :class:`ServeEngine`
  thread-safety contract), so a live call can at most wait behind one
  in-flight shadow dispatch.  Scores are measured qps / p99 under a
  recall-probe floor (exact re-rank spot checks: pass ``reference=`` an
  exact oracle, e.g. a boosted-``refine_ratio`` tiered searcher or
  :func:`exact_reference`).
* **Atomic promotion, guarded rollback.**  A winner is selected by
  successive halving and promoted ONLY on a statistically paired win
  (min-over-pairs objective ratio, the PR 14 paired best-of protocol):
  backend params swap atomically through the existing
  ``ServeEngine.refresh`` (all signatures already warm → the swap's
  re-lower is pure cache hits), host knobs through
  ``ServeEngine.apply_tuning``.  For ``rollback_window_s`` after a
  promotion, a live p99 regression beyond ``rollback_p99_rel`` × the
  pre-promotion p99 reverts the whole decision.  The guard needs a live
  pre-promotion p99 baseline to arm; promoting without one (no traffic
  yet, telemetry disabled) still applies the winner but counts
  ``raft_tpu_autotune_guard_disarmed_total`` and reports
  ``rollback_window_open=false`` rather than advertising a guard it
  cannot enforce.

Every decision (candidate, scores, promote/reject/rollback) exports
through ``raft_tpu_autotune_*`` registry counters/gauges (visible in
``/varz`` like every registry metric) and in the engine's ``/healthz``
body (``autotune`` sub-object).

Determinism: the candidate schedule and the shadow-traffic sampling
derive from one seed (``TunerConfig.seed``), exactly like
``testing/faults.py`` — same seed + same measurement stream ⇒ identical
candidate schedule and identical promote/reject decisions (tier-1 pins
this with an injected ``measure=``).

The serve hot-path rules apply module-wide (no ``jax.jit``/``jax.lax``,
``telemetry.now()`` for clocks, typed errors, marked host fetches).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from raft_tpu import telemetry
from raft_tpu.core.error import expects
from raft_tpu.serve.schedule import choose_batches

#: decision labels exported via raft_tpu_autotune_decisions_total
DECISIONS = ("promote", "reject", "rollback")


@dataclasses.dataclass(frozen=True)
class TunerConfig:
    """The autotuner's knobs (all decisions derive from ``seed``)."""

    #: candidate-schedule + shadow-sampling seed (testing/faults.py
    #: precedent: one seed, bit-identical schedule on replay)
    seed: int = 0
    #: shadow requests per evaluation in round 0 (grows ×eta per round)
    shadow_requests: int = 24
    #: successive-halving factor: keep len//eta candidates per round and
    #: multiply the shadow budget by eta
    eta: int = 2
    #: paired candidate/baseline replays per evaluation (the PR 14 paired
    #: best-of protocol: each pair replays the SAME request set through
    #: both configs back-to-back, so ambient drift hits both sides)
    pairs: int = 3
    #: paired win margin: the candidate must beat the baseline objective
    #: by this relative margin in EVERY pair to promote
    min_win_rel: float = 0.10
    #: "equal p99 / equal qps" tolerance for the win rule's held axis
    slack_rel: float = 0.10
    #: recall-probe floor: a candidate whose probe recall drops below this
    #: is rejected regardless of speed
    recall_floor: float = 0.95
    #: requests spot-checked against the recall reference per evaluation
    recall_probes: int = 4
    #: bound on the derived candidate set (seeded subsample above it)
    max_candidates: int = 16
    #: live-p99 guard window after a promotion
    rollback_window_s: float = 30.0
    #: rollback when live p99 exceeds this multiple of the pre-promotion
    #: p99 inside the window
    rollback_p99_rel: float = 1.5


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One point of the bounded knob space.

    ``params`` is a backend ``SearchParams`` variant (``n_probes``,
    ``refine_ratio``, kernel-engine choice — promoted via ``refresh``);
    ``max_batch`` caps the planner's bucket ladder at a WARMED bucket;
    ``quantum_s`` retunes the streaming scheduler.  ``None`` fields keep
    the serving value."""

    name: str
    params: Any = None
    max_batch: Optional[int] = None
    quantum_s: Optional[float] = None


@dataclasses.dataclass(frozen=True)
class Score:
    """One shadow evaluation's measurements.  ``served`` is the fraction
    of the request set the candidate could serve inside its warmed
    ladder — the coverage rule rejects any candidate that serves less
    than the baseline (qps over a shrunken request set is not a win)."""

    qps: float
    p99_s: float
    recall: float
    served: float = 1.0


#: the no-change candidate every pair measures against
BASELINE = Candidate("baseline")


def exact_reference(dataset: np.ndarray, k: int
                    ) -> Callable[[np.ndarray], np.ndarray]:
    """An exact brute-force recall oracle over *dataset* — the
    spot-check equivalent of a full-refine re-rank (tiered engines can
    instead pass a boosted-``refine_ratio`` searcher's ids)."""
    def _ref(q: np.ndarray) -> np.ndarray:
        from raft_tpu.neighbors import brute_force

        _d, i = brute_force.knn(dataset, q, k)
        # exempt(hot-path-host-transfer): recall-oracle result fetch
        return np.array(i)
    return _ref


class AutoTuner:
    """Online shadow-canary tuner for one :class:`ServeEngine`.

    Lifecycle: :meth:`warm_candidates` (pre-lowers param-variant
    backends; the ONLY stage allowed to compile) → :meth:`explore`
    (successive halving over shadow replays) → :meth:`promote` on a
    paired win → :meth:`maybe_rollback` while the guard window is open.
    :meth:`run` chains the first three.  Constructing the tuner attaches
    it to the engine's ``/healthz`` body (``autotune`` sub-object)."""

    def __init__(self, engine, config: Optional[TunerConfig] = None, *,
                 param_variants: Sequence[Any] = (),
                 extra_candidates: Sequence[Candidate] = (),
                 shadow_plan: Optional[Any] = None,
                 shadow_lane: Optional[int] = None,
                 reference: Optional[Callable[[np.ndarray],
                                              np.ndarray]] = None,
                 measure: Optional[Callable[[Candidate, List[np.ndarray]],
                                            Score]] = None):
        self.engine = engine
        self.cfg = config or TunerConfig()
        expects(self.cfg.eta >= 2, "TunerConfig.eta must be >= 2")
        expects(self.cfg.pairs >= 1, "TunerConfig.pairs must be >= 1")
        self._variants = tuple(param_variants)
        self._extra = tuple(extra_candidates)
        #: bench traffic-plan DSL spec (str → resolved through
        #: bench.common.traffic_requests, lazily) or a callable
        #: ``(seed, n, dim, dtype) -> [arrays]`` supplying synthetic fill
        self._plan = shadow_plan
        #: replica engines: the drained lane shadow replays dispatch to
        self._shadow_lane = shadow_lane
        self._reference = reference
        self._measure = measure or self._measure_real
        #: name -> pre-warmed off-path backend (param variants only)
        self._shadow: Dict[str, Any] = {}
        #: the evaluation order actually executed: (round, candidate)
        self.schedule: List[Tuple[int, str]] = []
        #: every decision taken: (candidate, decision, why)
        self.decisions: List[Tuple[str, str, str]] = []
        self._winner_scores: Optional[Tuple[List[Score], List[Score]]] = None
        self._promoted: Optional[Candidate] = None
        self._previous: Optional[Dict[str, Any]] = None
        self._promoted_at = 0.0
        self._pre_p99: Optional[float] = None
        #: True iff the open rollback window has a live pre-promotion
        #: p99 baseline to compare against (see :meth:`promote`)
        self._guard_armed = False
        self._label = (getattr(engine, "_engine_id", "?"),)
        self._evals = telemetry.counter(
            "raft_tpu_autotune_evals_total",
            "shadow evaluations executed per candidate",
            labelnames=("engine", "candidate"))
        self._decisions_c = telemetry.counter(
            "raft_tpu_autotune_decisions_total",
            "tuner decisions by kind (promote/reject/rollback)",
            labelnames=("engine", "decision"))
        self._rounds = telemetry.counter(
            "raft_tpu_autotune_rounds_total",
            "successive-halving rounds executed",
            labelnames=("engine",))
        self._skipped = telemetry.counter(
            "raft_tpu_autotune_shadow_skipped_total",
            "shadow requests skipped (rows above the warmed ladder cap)",
            labelnames=("engine",))
        self._guard_disarmed = telemetry.counter(
            "raft_tpu_autotune_guard_disarmed_total",
            "promotions with no live pre-promotion p99 baseline: the "
            "rollback guard could not arm",
            labelnames=("engine",))
        self._exploring = telemetry.gauge(
            "raft_tpu_autotune_exploring",
            "1 while a tune cycle's explore phase is running",
            labelnames=("engine",))
        self._qps_g = telemetry.gauge(
            "raft_tpu_autotune_qps",
            "best-pair shadow qps per candidate",
            labelnames=("engine", "candidate"))
        self._p99_g = telemetry.gauge(
            "raft_tpu_autotune_p99_seconds",
            "best-pair shadow p99 per candidate",
            labelnames=("engine", "candidate"))
        self._recall_g = telemetry.gauge(
            "raft_tpu_autotune_recall",
            "worst-pair probe recall per candidate",
            labelnames=("engine", "candidate"))
        engine.attach_tuner(self)

    # -- candidate space ----------------------------------------------------
    def candidates(self) -> List[Candidate]:
        """Derive the bounded candidate space from the engine's certified
        warmed-signature ladder.

        Bucket-cap candidates (one per warmed bucket ≠ the serving cap)
        are subsets of the warmed set — trivially zero-compile; operator-
        supplied ``param_variants`` become backend candidates that
        :meth:`warm_candidates` must pre-lower; ``extra_candidates`` pass
        through (e.g. quantum retunes).  The set is deterministic for a
        given engine state + seed: enumeration order is fixed and the
        over-bound subsample uses the config seed."""
        eng = self.engine
        sigs = eng.warmed_signatures()
        buckets = sorted({b for bs in sigs.values() for b in bs})
        expects(buckets, "candidates() before warmup(): the ladder is "
                         "empty, there is nothing certified to explore")
        out: List[Candidate] = [BASELINE]
        for b in buckets:
            if b != eng.max_batch:
                out.append(Candidate(f"cap{b}", max_batch=b))
        for i, p in enumerate(self._variants):
            out.append(Candidate(f"params{i}", params=p))
        out.extend(self._extra)
        if len(out) > self.cfg.max_candidates:
            rng = np.random.default_rng(self.cfg.seed)
            tail = out[1:]
            keep = rng.choice(len(tail), size=self.cfg.max_candidates - 1,
                              replace=False)
            out = [out[0]] + [tail[i] for i in sorted(keep)]
        return out

    # -- zero-compile pre-warm ----------------------------------------------
    def warm_candidates(self) -> int:
        """Pre-lower every params-variant candidate across the engine's
        warmed (bucket, dtype) ladder — the ONE tuner stage where
        compiles are sanctioned (exactly like ``warmup()``/``refresh()``,
        off the request path).  The shadow backends share the library's
        ``aot()`` caches, so a later promotion's ``refresh`` re-lower is
        pure cache hits.  Returns the number of signatures ensured."""
        from raft_tpu.serve.engine import _make_backend

        eng = self.engine
        sigs = eng.warmed_signatures()
        c = dict(eng._ctor)
        n = 0
        for cand in self.candidates():
            if cand.params is None or cand.name in self._shadow:
                continue
            be = _make_backend(eng.index, c["k"], cand.params, c["metric"],
                               c["metric_arg"], c["batch_size_index"])
            for dt, bs in sigs.items():
                for b in bs:
                    be.warm(b, jnp.dtype(dt))
                    n += 1
            self._shadow[cand.name] = be
        return n

    # -- shadow traffic -----------------------------------------------------
    def shadow_traffic(self, n: int, seed: int) -> List[np.ndarray]:
        """*n* shadow request arrays: a seeded sample of the engine's live
        shadow ring, topped up from the traffic-plan DSL (``shadow_plan``)
        when the ring cannot fill the budget.  Deterministic per seed for
        a fixed ring state + plan."""
        rng = np.random.default_rng(seed)
        live = self.engine.shadow_samples()
        reqs: List[np.ndarray] = []
        if live:
            # take <= len(live) always, so sample WITHOUT replacement: a
            # short ring contributes each live request exactly once (the
            # plan tops up the remainder) instead of duplicating some
            # and dropping others
            take = min(n, len(live))
            idx = rng.choice(len(live), size=take, replace=False)
            reqs = [live[i] for i in idx]
        fill = n - len(reqs)
        if fill > 0 and self._plan is not None:
            be = self.engine._backend
            if callable(self._plan):
                reqs.extend(self._plan(seed, fill, be.dim, "float32"))
            else:
                from bench.common import traffic_requests

                reqs.extend(traffic_requests(str(self._plan), seed, fill,
                                             be.dim, "float32"))
        return reqs

    # -- measurement --------------------------------------------------------
    @staticmethod
    def objective(s: Score) -> float:
        """The scalar ranking objective within a halving round: qps per
        unit p99 (the promote decision itself uses :meth:`paired_win`,
        which holds one axis and requires a win on the other)."""
        return s.qps / max(s.p99_s, 1e-9)

    def paired_win(self, cand: Sequence[Score],
                   base: Sequence[Score]) -> bool:
        """The statistically paired promotion rule: in EVERY pair the
        candidate must win qps by ``min_win_rel`` at no-worse p99 (within
        ``slack_rel``), or win p99 by ``min_win_rel`` at no-worse qps —
        min-over-pairs, so one lucky replay cannot promote."""
        cfg = self.cfg
        for cs, bs in zip(cand, base):
            qps_win = (cs.qps >= (1.0 + cfg.min_win_rel) * bs.qps
                       and cs.p99_s <= bs.p99_s * (1.0 + cfg.slack_rel))
            p99_win = (cs.p99_s * (1.0 + cfg.min_win_rel) <= bs.p99_s
                       and cs.qps >= bs.qps * (1.0 - cfg.slack_rel))
            if not (qps_win or p99_win):
                return False
        return True

    def _dispatch(self, be, block, lane: Optional[int]):
        """One shadow super-batch dispatch.  A params candidate's
        pre-warmed shadow backend owns its own searcher state and
        dispatches directly; anything routed through the LIVE backend
        serializes under the engine lock — the :class:`ServeEngine`
        thread-safety contract: planning/dispatch share the handle's
        stream pool, and a concurrent ``refresh()`` swaps ``_backend``
        under that lock — so an off-thread ``explore()`` can never
        interleave its dispatches with a live ``search()``'s.  A live
        call at most waits behind ONE in-flight shadow super-batch; it
        is never shed or failed."""
        eng = self.engine
        if be is not eng._backend:
            return be.dispatch(block)
        with eng._lock:
            if lane is None:
                return be.dispatch(block)
            return be.dispatch(block, lane)

    def _measure_real(self, cand: Candidate,
                      requests: List[np.ndarray]) -> Score:
        """Replay *requests* against the candidate's off-path lane and
        measure (qps, p99, probe recall).  Param candidates replay
        through their pre-warmed shadow backend; knob candidates through
        the live backend's warmed executables (on the drained
        ``shadow_lane`` for replica engines), each dispatch serialized
        under the engine lock (:meth:`_dispatch`) — never through
        admission or the router, so live requests are never shed or
        failed by an evaluation (they can at most wait behind one
        in-flight shadow super-batch)."""
        expects(requests, "no shadow traffic: serve some requests first "
                          "or pass shadow_plan=")
        eng = self.engine
        be = self._shadow.get(cand.name)
        lane = None
        if be is None:
            be = eng._backend
            lane = self._shadow_lane
        cap = cand.max_batch if cand.max_batch is not None \
            else eng.max_batch
        qps, p99, results, served = self._replay(be, requests, cap, lane)
        recall = self._recall_probe(requests, results, served)
        return Score(qps=qps, p99_s=p99, recall=recall,
                     served=len(served) / len(requests))

    def _replay(self, be, requests: List[np.ndarray], cap: int,
                lane: Optional[int]):
        """Coalesce + dispatch *requests* exactly like the engine's plan
        stage — buckets bound ONLY through the certified ``_bucket_for``
        ladder over the warmed set (capped at the candidate's ladder cap),
        so every dispatch hits a pre-lowered executable."""
        eng = self.engine
        sigs = eng.warmed_signatures()
        ingested = [be.ingest(q) for q in requests]
        results: List[Optional[Tuple[np.ndarray, np.ndarray]]] = \
            [None] * len(requests)
        lat = [0.0] * len(requests)
        by_dtype: Dict[str, List[int]] = {}
        skipped = 0
        for j, q in enumerate(ingested):
            dt = str(q.dtype)
            warmed = {b for b in sigs.get(dt, ()) if b <= cap}
            if not warmed or q.shape[0] > max(warmed) \
                    or q.shape[0] == 0:
                skipped += 1  # stays zero-compile: never solo off-path
                continue
            by_dtype.setdefault(dt, []).append(j)
        if skipped:
            self._skipped.inc(skipped, self._label)
        t_start = telemetry.now()
        n_served = 0
        for dt, idxs in by_dtype.items():
            warmed = {b for b in sigs.get(dt, ()) if b <= cap}
            max_bucket = max(warmed)
            sizes = [int(ingested[j].shape[0]) for j in idxs]
            batches, _solo = choose_batches(
                sizes, [None] * len(sizes),
                lambda total, w=warmed: eng._bucket_for(total, w),
                max_bucket, eng._cost, dt, telemetry.now())
            for batch in batches:
                members = [(idxs[jj], start, n) for jj, start, n in batch]
                total = members[-1][1] + members[-1][2]
                bucket = eng._bucket_for(total, warmed)
                block = np.zeros((bucket, be.dim),
                                 ingested[members[0][0]].dtype)
                for j, start, n in members:
                    block[start:start + n] = ingested[j]
                d, i = self._dispatch(be, jnp.asarray(block), lane)
                # exempt(hot-path-host-transfer): shadow result delivery
                d = np.asarray(d)
                # exempt(hot-path-host-transfer): shadow result delivery
                i = np.asarray(i)
                done = telemetry.now() - t_start
                for j, start, n in members:
                    results[j] = (d[start:start + n], i[start:start + n])
                    lat[j] = done
                    n_served += 1
        wall = max(telemetry.now() - t_start, 1e-9)
        served = [j for j in range(len(requests))
                  if results[j] is not None]
        expects(served, "shadow replay served nothing: every request "
                        "exceeded the warmed ladder cap")
        p99 = float(np.percentile([lat[j] for j in served], 99.0))
        return n_served / wall, p99, results, served

    def _recall_probe(self, requests, results, served) -> float:
        """Spot-check the first ``recall_probes`` served requests against
        the reference oracle (exact re-rank when ``reference=`` is an
        exact oracle; the live config's own results otherwise)."""
        probes = served[:self.cfg.recall_probes]
        if not probes:
            return 1.0
        hit = tot = 0
        for j in probes:
            ids = results[j][1]
            if self._reference is not None:
                ref_ids = self._reference(requests[j])
            else:
                ref_ids = self._live_ids(requests[j])
            # exempt(hot-path-host-transfer): recall-probe comparison
            ref_ids = np.asarray(ref_ids)
            for row in range(ids.shape[0]):
                hit += len(set(ids[row].tolist())
                           & set(ref_ids[row].tolist()))
                tot += ids.shape[1]
        return hit / max(tot, 1)

    def _live_ids(self, q: np.ndarray) -> np.ndarray:
        """The serving config's own ids for one request — the default
        recall reference (a candidate may not lose more than the floor of
        what the live config returns), via the live backend's warmed
        ladder (zero-compile, off-path)."""
        eng = self.engine
        be = eng._backend
        qi = be.ingest(q)
        dt = str(qi.dtype)
        warmed = set(eng.warmed_signatures().get(dt, ()))
        bucket = eng._bucket_for(int(qi.shape[0]), warmed)
        block = np.zeros((bucket, be.dim), qi.dtype)
        block[:qi.shape[0]] = qi
        out = self._dispatch(be, jnp.asarray(block), self._shadow_lane)
        # exempt(hot-path-host-transfer): recall-probe result fetch
        ids = np.asarray(out[1])
        return ids[:qi.shape[0]]

    # -- explore (successive halving) ---------------------------------------
    def explore(self) -> Optional[Candidate]:
        """Successive halving over the candidate set: evaluate every
        survivor on the round's shadow budget (paired against the
        baseline on the SAME request sets), drop candidates below the
        recall floor or the baseline's served coverage (the coverage
        rule), keep the top ``1/eta`` by min-over-pairs objective
        ratio, grow the budget ×eta, repeat to one winner.  Returns the
        winner iff it passes :meth:`paired_win` (else None; every
        non-winner's rejection is recorded + counted).  Zero-compile:
        requires :meth:`warm_candidates` for params variants."""
        eng = self.engine
        cands = [c for c in self.candidates() if c.name != BASELINE.name]
        for c in cands:
            expects(c.params is None or c.name in self._shadow,
                    "explore() before warm_candidates(): candidate "
                    f"{c.name} has no pre-warmed shadow backend")
        if not cands:
            return None
        router = eng._router
        drained = (self._shadow_lane is not None and router is not None
                   and self._shadow_lane not in router.degraded_lanes())
        if drained:
            router.drain(self._shadow_lane)
        self._exploring.set(1, self._label)
        try:
            return self._halve(cands)
        finally:
            self._exploring.set(0, self._label)
            if drained:
                router.restore(self._shadow_lane)

    def _halve(self, survivors: List[Candidate]) -> Optional[Candidate]:
        cfg = self.cfg
        budget = cfg.shadow_requests
        rnd = 0
        while survivors:
            self._rounds.inc(1, self._label)
            scored = []
            for ci, cand in enumerate(survivors):
                pc: List[Score] = []
                pb: List[Score] = []
                for p in range(cfg.pairs):
                    seed = (cfg.seed * 1000003 + rnd * 8191
                            + ci * 131 + p)
                    reqs = self.shadow_traffic(budget, seed)
                    pb.append(self._measure(BASELINE, reqs))
                    pc.append(self._measure(cand, reqs))
                self.schedule.append((rnd, cand.name))
                self._evals.inc(1, (self._label[0], cand.name))
                best = max(pc, key=self.objective)
                self._qps_g.set(best.qps, (self._label[0], cand.name))
                self._p99_g.set(best.p99_s, (self._label[0], cand.name))
                worst_recall = min(s.recall for s in pc)
                self._recall_g.set(worst_recall,
                                   (self._label[0], cand.name))
                ratio = min(self.objective(c)
                            / max(self.objective(b), 1e-12)
                            for c, b in zip(pc, pb))
                # the coverage rule: a candidate must serve at least the
                # baseline's fraction of every pair's request set — qps
                # measured over a shrunken (skip-heavy) set is not a win
                covers = all(c.served >= b.served - 1e-9
                             for c, b in zip(pc, pb))
                recall_ok = worst_recall >= cfg.recall_floor
                why = ("recall floor" if not recall_ok
                       else "coverage" if not covers else "")
                scored.append((cand, pc, pb, recall_ok and covers,
                               ratio, why))
            for cand, _pc, _pb, ok, _r, why in scored:
                if not ok:
                    self._decide("reject", cand.name, why)
            viable = [t for t in scored if t[3]]
            if not viable:
                return None
            viable.sort(key=lambda t: (-t[4], t[0].name))
            if len(viable) == 1:
                return self._final(viable[0])
            keep = max(1, len(viable) // cfg.eta)
            for cand, *_ in viable[keep:]:
                self._decide("reject", cand.name, "halved")
            survivors = [t[0] for t in viable[:keep]]
            if len(survivors) == 1:
                return self._final(viable[0])
            budget *= cfg.eta
            rnd += 1
        return None

    def _final(self, entry) -> Optional[Candidate]:
        cand, pc, pb, _ok, _ratio, _why = entry
        if not self.paired_win(pc, pb):
            self._decide("reject", cand.name, "no paired win")
            return None
        self._winner_scores = (pc, pb)
        return cand

    # -- promotion / rollback ------------------------------------------------
    def promote(self, cand: Candidate) -> Dict[str, Any]:
        """Atomically apply *cand*: backend params through the existing
        ``ServeEngine.refresh`` swap (every signature pre-warmed by
        :meth:`warm_candidates` → the re-lower is pure ``aot()`` cache
        hits, zero compiles), host knobs through
        ``ServeEngine.apply_tuning``.  Records the rollback token + live
        p99 baseline and opens the guard window; with NO baseline (no
        live traffic yet, or telemetry disabled) the promotion still
        applies but the guard cannot arm — counted in
        ``raft_tpu_autotune_guard_disarmed_total`` and reported as
        ``rollback_window_open=false`` in ``/healthz``.  The admission
        controller's observed-cost EWMA resets so its estimates
        re-converge under the new config.  Returns the previous config
        (the rollback token)."""
        eng = self.engine
        pre_p99 = eng.latency_quantiles((0.99,))[0]
        prev_params = eng._ctor["params"]
        pre_cap = eng.max_batch
        if cand.params is not None:
            eng.refresh(eng.index, params=cand.params)
        # refresh() re-derives max_batch from the construction bound: a
        # cap promoted by an EARLIER tune cycle must survive a params
        # promotion, so re-assert the pre-refresh cap whenever this
        # candidate leaves the ladder cap alone (a no-op when nothing
        # was refreshed)
        prev = eng.apply_tuning(
            quantum_s=cand.quantum_s,
            max_batch=(cand.max_batch if cand.max_batch is not None
                       else pre_cap))
        prev["max_batch"] = pre_cap  # the true pre-promotion cap
        adm = eng._admission
        if adm is not None:
            adm.reset_observed()
        self._promoted = cand
        self._previous = dict(prev, params=prev_params)
        self._promoted_at = telemetry.now()
        self._pre_p99 = pre_p99
        self._guard_armed = pre_p99 is not None and pre_p99 > 0.0
        if not self._guard_armed:
            self._guard_disarmed.inc(1, self._label)
        self._decide("promote", cand.name, "paired win")
        return dict(self._previous)

    def maybe_rollback(self, live_p99_s: Optional[float] = None) -> bool:
        """The guarded rollback window: within ``rollback_window_s`` of a
        promotion, a live p99 above ``rollback_p99_rel`` × the
        pre-promotion p99 reverts the promotion (params back through
        ``refresh`` — still zero-compile, the old signatures stayed warm
        — knobs back through ``apply_tuning``).  *live_p99_s* defaults to
        the p99 of the engine's most recent ``search()`` call.  Returns
        True iff a rollback happened; once the window closes the
        promotion is accepted and the guard disarms.  A promotion whose
        guard never armed (no pre-promotion baseline) is accepted
        immediately — :meth:`promote` already counted and reported the
        disarm."""
        cfg = self.cfg
        eng = self.engine
        if self._promoted is None:
            return False
        if not self._guard_armed:
            self._promoted = None  # unguarded promotion: accepted as-is
            return False
        now = telemetry.now()
        if now - self._promoted_at > cfg.rollback_window_s:
            self._promoted = None  # window closed: promotion accepted
            return False
        if live_p99_s is None:
            lats = eng.last_latencies
            if not lats:
                return False
            live_p99_s = float(np.percentile(lats, 99.0))
        pre = self._pre_p99
        if live_p99_s <= cfg.rollback_p99_rel * pre:
            return False
        prev = self._previous or {}
        name = self._promoted.name
        if self._promoted.params is not None:
            # the token's params apply VERBATIM (KEEP_PARAMS semantics):
            # a None here restores a params=None construction instead of
            # silently keeping the regressing candidate's params
            eng.refresh(eng.index, params=prev.get("params"))
        eng.apply_tuning(quantum_s=prev.get("quantum_s"),
                         max_batch=prev.get("max_batch"))
        adm = eng._admission
        if adm is not None:
            adm.reset_observed()
        self._promoted = None
        self._decide("rollback", name,
                     f"live p99 {live_p99_s:.4f}s > "
                     f"{cfg.rollback_p99_rel}x pre-promotion {pre:.4f}s")
        return True

    def run(self) -> Dict[str, Any]:
        """One full tune cycle: warm → explore → promote on a paired win.
        Returns a report (winner, schedule, decisions) — the bench/ops
        entry point."""
        self.warm_candidates()
        winner = self.explore()
        if winner is not None:
            self.promote(winner)
        return {"winner": winner.name if winner is not None else None,
                "schedule": list(self.schedule),
                "decisions": list(self.decisions)}

    # -- reporting ----------------------------------------------------------
    def _decide(self, decision: str, candidate: str, why: str = "") -> None:
        self.decisions.append((candidate, decision, why))
        self._decisions_c.inc(1, (self._label[0], decision))

    def health(self) -> Dict[str, Any]:
        """The engine ``/healthz`` ``autotune`` sub-object (JSON-safe)."""
        return {
            "seed": self.cfg.seed,
            "evaluations": len(self.schedule),
            "decisions": [list(d) for d in self.decisions[-8:]],
            "promoted": (self._promoted.name
                         if self._promoted is not None else None),
            # open means ARMED: an unguarded promotion (no pre-promotion
            # p99 baseline existed) must not advertise a guard window it
            # cannot enforce
            "rollback_window_open": (self._promoted is not None
                                     and self._guard_armed),
        }
