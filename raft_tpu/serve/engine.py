"""Batched query-serving engine over the ANN backends.

The reference ships precompiled instantiation libraries
(``libraft-nn``/``libraft-distance``, SURVEY.md §2.14) precisely so a
serving process never compiles on the request path; raft_tpu's kernels are
fast (fused kNN scan, hoisted ADC) but a naive serving loop still pays, per
request: a jit trace-check dispatch, one executable per ragged batch shape,
and zero cross-request amortization of the scan's fixed costs.  This module
closes that gap (docs/serving.md):

* **Request coalescing** — concurrent ragged query batches against one
  (index, k, params) engine are packed, in arrival order, into
  ``core.aot._bucket_dim``-padded super-batches and dispatched as ONE fused
  search each; results are sliced back per request.  Per-query rows of
  every backend's search program are independent of the other rows in the
  batch, so per-request results are bit-identical to solo dispatch (the
  property tests/test_serve.py pins across backends × dtypes × mixes).
* **Executable warmup/pinning** — :meth:`ServeEngine.warmup` pre-lowers
  every (bucket, dtype) signature through the backend's ``aot()`` cache at
  engine construction time, so steady-state serving never compiles or
  retraces: asserted via ``core.aot.aot_compile_counters``.
* **Double-buffered dispatch** — dispatch is async: while super-batch *i*
  executes on device, super-batch *i+1* is coalesced, padded (host-side
  numpy) and transferred.  In-flight outputs are recorded on the handle's
  stream pool (``Handle.get_next_usable_stream``), alternating lanes, so
  pool bookkeeping owns the overlap the way the reference's stream-pool
  batched launches do (handle.hpp:88-130).
* **Graceful degradation** — a request larger than the warmed bucket range
  (or the engine's ``max_batch``) is served solo through the backend's
  public entry point and counted in :attr:`ServeEngine.stats`, never
  crashed and never silently recompiled into the coalesced path.
* **Failure handling** (docs/serving.md §failure model) — requests may
  carry deadlines (:class:`raft_tpu.serve.admission.ServeRequest`);
  a request whose remaining budget cannot cover its projected completion
  is SHED at admission with a typed
  :class:`~raft_tpu.serve.admission.RejectedError` in its result slot
  instead of queued to die, super-batch collection runs under a
  :class:`~raft_tpu.serve.supervise.DispatchSupervisor` (wall-clock
  watchdog, bounded retry with backoff+jitter for transient failures,
  fail-fast for logic bugs), and a poisoned request fails ALONE: ingest
  errors land in that request's slot, and a failed multi-member
  super-batch is split and re-dispatched member-by-member through the
  warmed bucket ladder (still zero-compile).  ``refresh()`` is atomic
  under injected crashes (the old backend keeps serving), ``close()`` is
  bounded and idempotent, and ``/healthz`` reports a non-503
  ``degraded`` flag while shedding.
* **Telemetry** — the request lifecycle runs under nested
  ``raft_tpu.telemetry`` spans (``serve.request`` → ingest/coalesce/
  assemble/dispatch/deliver), per-request completion latency lands in a
  fixed-memory histogram + bounded reservoir
  (:meth:`ServeEngine.latency_quantiles`), and ``stats`` is a
  registry-backed atomic counter view — all host-side wall-time only
  (zero device syncs), no-ops under ``RAFT_TPU_TELEMETRY=0``, overhead
  gated < 3% qps in-bench (docs/observability.md).
  :meth:`ServeEngine.serve_http` adds the live scrape surface on top:
  ``/metrics``, ``/healthz`` (readiness: warmed buckets, refresh in
  flight), ``/varz`` and ``/debug/slow`` (bounded flight-recorder ring of
  slow-request span trees).

Hot-path rule (ci/lint.py): nothing in this package may call ``jax.jit``
or ``jax.lax`` — every device computation must route through the
backends' ``aot()``-cached entry points, otherwise the zero-retrace
guarantee silently erodes.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
from concurrent import futures
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import telemetry
from raft_tpu.core.aot import _bucket_dim
from raft_tpu.core.error import expects
from raft_tpu.core.handle import Handle
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.neighbors import (ann_mnmg, brute_force, ivf_flat, ivf_pq,
                                mutable, tiering)
from raft_tpu.serve.admission import (AdmissionController, RejectedError,
                                      ServeRequest)
from raft_tpu.serve.schedule import (CostModel, ReplicaRouter,
                                     SchedulerConfig, choose_batches,
                                     should_dispatch)
from raft_tpu.serve.supervise import DispatchSupervisor, retryable
from raft_tpu.testing import faults as _faults

#: Bound on the per-call latency list AND the cumulative latency reservoir:
#: the pre-telemetry ``last_latencies`` attribute kept one float per request
#: of the last call UNBOUNDED (a single huge ``search()`` call — or an
#: engine polled only via the attribute — grew it without limit); the
#: replacement keeps at most this many samples while the full distribution
#: lives in the fixed-memory latency histogram.
LATENCY_RESERVOIR = 4096

#: bounded live-request shadow ring size (serve.autotune shadow traffic):
#: enough for a representative mix, small enough that retaining the
#: ingested arrays costs at most a few MB
_SHADOW_RING = 64

#: per-instance ordinal labeling each engine's metrics in the registry
_ENGINE_IDS = itertools.count()


class _BruteForceBackend:
    """Adapter: dense (n, dim) matrix → ``brute_force._knn_scan_aot``."""

    name = "brute_force"

    def __init__(self, index, k: int, metric, metric_arg: float,
                 batch_size_index: int):
        self.index = jnp.asarray(index)  # device-resident serving state
        expects(self.index.ndim == 2, "brute-force index must be (n, dim)")
        expects(1 <= k <= self.index.shape[0],
                f"k={k} must be in [1, n_index={self.index.shape[0]}]")
        self.k = int(k)
        self.metric = brute_force._resolve_metric(metric)
        self.metric_arg = float(metric_arg)
        self.tile = int(min(batch_size_index, self.index.shape[0]))
        self.select_min = self.metric != DistanceType.InnerProduct
        self.dim = int(self.index.shape[1])
        self.fn = brute_force._knn_scan_aot

    def ingest(self, q):
        """Per-request compute-form conversion (must match what the solo
        path does BEFORE batching, so coalescing cannot change values)."""
        # exempt(hot-path-host-transfer): host-numpy request ingest
        q = np.asarray(q)
        expects(q.ndim == 2 and q.shape[1] == self.dim,
                "query must be (n, dim) with the index's dim")
        return q

    def _args(self, qb):
        return (self.index, qb, self.k, self.metric, self.metric_arg,
                self.tile, self.select_min)

    def warm(self, bucket: int, dtype) -> None:
        self.fn.compiled(*self._args(
            jax.ShapeDtypeStruct((bucket, self.dim), dtype)))

    def dispatch(self, qb):
        return self.fn(*self._args(qb))

    def solo(self, q):
        return brute_force.knn(self.index, q, self.k, self.metric,
                               self.metric_arg,
                               batch_size_index=self.tile)


class _IvfFlatBackend:
    """Adapter: ``ivf_flat.Index`` → ``ivf_flat._search_batch_aot``."""

    name = "ivf_flat"

    def __init__(self, index: ivf_flat.Index, k: int,
                 params: Optional[ivf_flat.SearchParams]):
        self.index = index
        self.params = params or ivf_flat.SearchParams()
        expects(k >= 1, "k must be >= 1")
        self.k = int(k)
        self.n_probes = int(min(self.params.n_probes, index.n_lists))
        self.sqrt = index.metric == DistanceType.L2SqrtExpanded
        self.dim = int(index.dim)
        self.leaves = (index.centers, index.list_data, index.list_indices,
                       index.phys_sizes, index.chunk_table)
        # kernel engine resolved at backend construction (kernels.engine
        # policy) and threaded as a static through _args, so warmup()
        # pre-lowers the SELECTED engine's executable per (bucket, dtype)
        # signature — the Pallas variant warms exactly like the XLA one
        from raft_tpu.kernels.engine import resolve_engine

        self.engine = resolve_engine("select_k", dtype=jnp.float32)
        self.fn = ivf_flat._search_batch_aot

    def ingest(self, q):
        """HOST-side compute-form conversion wherever the conversion is
        exact (int8/uint8 → f32 widening matches the device cast bit-for-
        bit), so the hot loop's per-request work stays numpy — no device
        bounce, no per-ragged-shape eager executables outside the
        zero-compile counter.  The one INEXACT prologue step, cosine's
        row normalize, must reproduce the solo path's device numerics
        exactly (reduction order differs between numpy and XLA), so only
        that metric pays a per-request device round-trip."""
        # exempt(hot-path-host-transfer): request ingest of host numpy
        q = np.asarray(q)
        expects(q.ndim == 2 and q.shape[1] == self.dim, "query dim mismatch")
        if q.dtype in (np.int8, np.uint8):
            q = q.astype(np.float32)  # exact widening: matches device cast
        if self.index.metric == DistanceType.CosineExpanded:
            # exempt(hot-path-host-transfer): cosine solo-numerics
            return np.asarray(ivf_flat._normalize_rows(jnp.asarray(q)))
        return q

    def _args(self, qb):
        return (qb, self.leaves, int(self.index.metric), self.k,
                self.n_probes, self.sqrt, -1, self.engine)

    def warm(self, bucket: int, dtype) -> None:
        self.fn.compiled(*self._args(
            jax.ShapeDtypeStruct((bucket, self.dim), dtype)))

    def dispatch(self, qb):
        return self.fn(*self._args(qb))

    def solo(self, q):
        return ivf_flat.search(self.params, self.index, q, self.k)


class _IvfPqBackend:
    """Adapter: ``ivf_pq.Index`` → ``ivf_pq._full_search_aot`` (coarse +
    select + probe scan as ONE pinned executable)."""

    name = "ivf_pq"

    def __init__(self, index: ivf_pq.Index, k: int,
                 params: Optional[ivf_pq.SearchParams]):
        self.index = index
        self.params = params or ivf_pq.SearchParams()
        expects(k >= 1, "k must be >= 1")
        expects(self.params.lut_dtype in ivf_pq._LUT_DTYPES,
                f"lut_dtype must be one of {list(ivf_pq._LUT_DTYPES)}")
        self.k = int(k)
        self.n_probes = int(min(self.params.n_probes, index.n_lists))
        self.hoisted = (ivf_pq.hoisted_lut_enabled()
                        if self.params.hoisted_lut is None
                        else bool(self.params.hoisted_lut))
        self.dim = int(index.dim)
        self.leaves = (index.centers, index.rotation, index.codebooks,
                       index.list_codes, index.list_indices,
                       index.phys_sizes, index.chunk_table, index.owner,
                       index.list_adc, index.list_csum)
        # kernel engine (LUT-in-VMEM scorer + blockwise select_k) resolved
        # at backend construction and threaded as a static through _args —
        # warmup() pre-lowers the selected engine's executable per
        # (bucket, dtype) signature (kernels.engine policy)
        self.engine = ivf_pq._resolve_scan_engine(index.pq_dim,
                                                  index.pq_bits)
        self.fn = ivf_pq._full_search_aot

    def ingest(self, q):
        """HOST-side f32 ingest: every dtype ivf_pq accepts converts to
        f32 EXACTLY (int8/uint8/bf16/f16 are all widenings, f32 is a
        no-op), so the numpy cast is bit-identical to the solo path's
        device cast — no device bounce per request (the dtype-acceptance
        checks mirror ``ivf_pq._ingest_dataset``)."""
        # exempt(hot-path-host-transfer): request ingest of host numpy
        q = np.asarray(q)
        if q.dtype in (np.int8, np.uint8):
            q_dtype = str(q.dtype)
        else:
            expects(jnp.issubdtype(q.dtype, jnp.floating),
                    f"ivf_pq: unsupported query dtype {q.dtype}")
            q_dtype = "float32"
        expects(q_dtype in (self.index.dataset_dtype, "float32"),
                f"query dtype {q_dtype} != index dataset dtype "
                f"{self.index.dataset_dtype}")
        expects(q.ndim == 2 and q.shape[1] == self.dim,
                "query dim mismatch")
        return q.astype(np.float32)

    def batch_cap(self) -> Optional[int]:
        """Hoisted compressed-LUT / PER_CLUSTER configs materialize
        per-(query, probe) combined ADC tables once per batch — the same
        ~128 MiB transient bound ``ivf_pq.search`` applies to its query
        batching must clamp the engine's super-batch; ONE shared formula
        (``ivf_pq.hoisted_batch_cap``) so a tuning there reaches the
        engine too."""
        return ivf_pq.hoisted_batch_cap(self.index, self.n_probes,
                                        self.params.lut_dtype, self.hoisted)

    def _args(self, qb):
        return (qb, self.leaves, int(self.index.metric), self.k,
                self.n_probes,
                self.index.codebook_kind == ivf_pq.CodebookKind.PER_CLUSTER,
                self.params.lut_dtype, self.params.internal_distance_dtype,
                self.index.pq_bits, self.hoisted, -1, self.engine)

    def warm(self, bucket: int, dtype) -> None:
        self.fn.compiled(*self._args(
            jax.ShapeDtypeStruct((bucket, self.dim), dtype)))

    def dispatch(self, qb):
        return self.fn(*self._args(qb))

    def solo(self, q):
        return ivf_pq.search(self.params, self.index, q, self.k)


class _ShardedBackend:
    """Adapter: ``ann_mnmg.ShardedIndex`` → one MeshAot shard_map
    executable whose super-batches dispatch across EVERY device of the
    index's communicator (coarse replicated, probe scan per shard, ONE
    allgather + on-device merge — docs/sharded_ann.md).  Warmup pre-lowers
    each (bucket, dtype, world) signature through the MeshAot cache, so
    the zero-compile steady state holds for sharded serving too."""

    def __init__(self, sharded, k: int, params):
        expects(k >= 1, "k must be >= 1")
        self.sharded = sharded
        # brute-force sharded indexes carry their metric themselves —
        # reject params loudly (ShardedSearcher's contract) instead of
        # silently serving with them ignored
        expects(sharded.kind != "brute_force" or params is None,
                "sharded brute-force serving takes no SearchParams "
                "(metric/metric_arg ride the ShardedIndex)")
        self.params = params
        self.name = f"sharded_{sharded.kind}"
        self.searcher = sharded.searcher(int(k), self.params)
        self.k = int(k)
        self.dim = int(sharded.dim)

    def ingest(self, q):
        """Per-request compute-form conversion mirroring
        ``ann_mnmg._ingest`` (itself mirroring each kind's solo prologue):
        exact host-side widenings stay numpy; only cosine's inexact row
        normalize round-trips the device (the _IvfFlatBackend contract)."""
        return _sharded_ingest(self.sharded, q, self.dim)

    def batch_cap(self) -> Optional[int]:
        """Per-SHARD transient bound: the hoisted compressed-LUT configs
        materialize their combined tables on every shard, so the clamp
        sizes by the shard-local physical block (the ONE formula,
        ``ivf_pq.hoisted_batch_cap_dims``)."""
        return _sharded_batch_cap(self.sharded, self.searcher)

    def warm(self, bucket: int, dtype) -> None:
        self.searcher.warm(bucket, dtype)

    def dispatch(self, qb):
        return self.searcher.dispatch(qb)

    def solo(self, q):
        return ann_mnmg.search(self.sharded, q, self.k, self.params)


def _sharded_ingest(container, q, dim: int):
    """The sharded kinds' HOST-side ingest (shared by the sharded and
    replica backends — *container* is a ``ShardedIndex`` or a
    ``ReplicaSet``, both expose ``kind``/``aux``/``metric``): exact
    widenings stay numpy; only cosine's inexact row normalize
    round-trips the device (the _IvfFlatBackend contract)."""
    # exempt(hot-path-host-transfer): request ingest of host numpy
    q = np.asarray(q)
    expects(q.ndim == 2 and q.shape[1] == dim,
            "query must be (n, dim) with the index's dim")
    kind = container.kind
    if kind == "brute_force":
        return q
    if kind == "ivf_pq":
        # dataset-dtype consistency BEFORE the widening (the
        # _IvfPqBackend/ann_mnmg._ingest contract — widening first
        # would silently admit traffic the solo fallback rejects)
        if q.dtype in (np.int8, np.uint8):
            q_dtype = str(q.dtype)
        else:
            expects(jnp.issubdtype(q.dtype, jnp.floating),
                    f"ivf_pq: unsupported query dtype {q.dtype}")
            q_dtype = "float32"
        expects(q_dtype in (container.aux["dataset_dtype"], "float32"),
                f"query dtype {q_dtype} != index dataset dtype "
                f"{container.aux['dataset_dtype']}")
        return q.astype(np.float32)
    if q.dtype in (np.int8, np.uint8):
        q = q.astype(np.float32)  # exact widening: matches device cast
    if container.metric == DistanceType.CosineExpanded:
        # exempt(hot-path-host-transfer): cosine solo-numerics bounce
        return np.asarray(ivf_flat._normalize_rows(jnp.asarray(q)))
    return q


def _sharded_batch_cap(container, searcher) -> Optional[int]:
    """The per-shard ivf_pq transient clamp (the ONE formula,
    ``ivf_pq.hoisted_batch_cap_dims``) — shared by the sharded and
    replica backends."""
    if container.kind != "ivf_pq" or not getattr(searcher, "hoisted",
                                                 False):
        return None
    aux = container.aux
    return ivf_pq.hoisted_batch_cap_dims(
        container.metric,
        aux["codebook_kind"] == int(ivf_pq.CodebookKind.PER_CLUSTER),
        aux["cap_n_phys"], aux["cap_max_chunks"], aux["n_lists"],
        aux["pq_dim"], aux["pq_bits"], searcher.n_probes,
        searcher.lut_dtype, searcher.hoisted)


class _ReplicaBackend:
    """Adapter: ``ann_mnmg.ReplicaSet`` → R per-group ``ShardedSearcher``s
    on the 2D (shard × replica) carve (docs/sharded_ann.md §replica
    groups).  ``warm()`` fans the (bucket, dtype) signature out across
    EVERY replica lane's MeshAot cache (the caches are per-group-
    communicator, so signatures never alias across lanes and any lane
    can serve any warmed batch — that is what makes fault re-routing
    zero-compile); ``dispatch(qb, lane)`` runs one pre-bucketed batch on
    ONE lane's sub-mesh, occupying only that group's devices.  The
    engine's :class:`~raft_tpu.serve.schedule.ReplicaRouter` owns lane
    choice, draining and re-routing."""

    def __init__(self, rep, k: int, params):
        expects(k >= 1, "k must be >= 1")
        # brute-force replica sets carry their metric themselves — the
        # _ShardedBackend contract
        expects(rep.kind != "brute_force" or params is None,
                "replicated brute-force serving takes no SearchParams "
                "(metric/metric_arg ride the ReplicaSet)")
        self.rep = rep
        self.params = params
        self.name = f"replica_{rep.kind}"
        self.k = int(k)
        self.dim = int(rep.dim)
        self.searchers = tuple(s.searcher(int(k), params)
                               for s in rep.replicas)
        self.n_replicas = len(self.searchers)

    def ingest(self, q):
        return _sharded_ingest(self.rep, q, self.dim)

    def batch_cap(self) -> Optional[int]:
        return _sharded_batch_cap(self.rep, self.searchers[0])

    def warm(self, bucket: int, dtype) -> None:
        for s in self.searchers:
            s.warm(bucket, dtype)

    def dispatch(self, qb, lane: int = 0):
        # the PR-14 fault plane's `comms` site, per replica lane: a plan
        # like `comms:op=replica_dispatch:rank=1:raise` deterministically
        # faults lane 1 — the provable degrade path the battery drives
        _faults.check("comms", op="replica_dispatch", rank=int(lane))
        return self.searchers[lane].dispatch(qb)

    def solo(self, q, lane: int = 0):
        return ann_mnmg.search(self.rep.replicas[lane], q, self.k,
                               self.params)


class _TieredBackend:
    """Adapter: ``tiering.TieredIndex`` → the two-phase tiered searcher
    (hot-set scan + staged cold-tile sweep + optional exact re-rank,
    ``neighbors.tiering``).  Pure delegation, the ``_ShardedBackend``
    precedent: the searcher owns the warmed hot/cold/refine/merge
    signatures, the double-buffer staging lanes and the device-resident
    hotness counters ``refresh(tiering.retier(...))`` re-tiers from."""

    def __init__(self, tiered, k: int, params):
        expects(k >= 1, "k must be >= 1")
        self.tiered = tiered
        self.params = params
        self.name = f"tiered_{tiered.kind}"
        self.searcher = tiered.searcher(int(k), params)
        self.k = int(k)
        self.dim = int(tiered.dim)

    def ingest(self, q):
        return self.searcher.ingest(q)

    def batch_cap(self) -> Optional[int]:
        return self.searcher.batch_cap()

    def warm(self, bucket: int, dtype) -> None:
        self.searcher.warm(bucket, dtype)

    def dispatch(self, qb):
        return self.searcher.dispatch(qb)

    def solo(self, q):
        return tiering.search(self.tiered, q, self.k, params=self.params)


class _MutableBackend:
    """Adapter: ``mutable.MutableIndex`` → the delta-merged tombstone-
    masked searcher (``neighbors.mutable``).  Pure delegation, the
    ``_TieredBackend`` precedent: the searcher owns the warmed
    main/delta/merge signatures and the write-ordered core snapshots;
    writes (``upsert``/``delete``) land on the SAME MutableIndex object
    concurrently with serving, and compaction promotes its rebuilt core
    through ``engine.refresh(mutable_index)`` — the one sanctioned swap
    door (the ``mutation-discipline`` analysis rule)."""

    def __init__(self, mut, k: int, params):
        expects(k >= 1, "k must be >= 1")
        self.mutable = mut
        self.params = params
        self.name = f"mutable_{mut.kind}"
        self.searcher = mut.searcher(int(k), params)
        self.k = int(k)
        self.dim = int(mut.dim)

    def ingest(self, q):
        return self.searcher.ingest(q)

    def batch_cap(self) -> Optional[int]:
        return self.searcher.batch_cap()

    def warm(self, bucket: int, dtype) -> None:
        self.searcher.warm(bucket, dtype)

    def dispatch(self, qb):
        return self.searcher.dispatch(qb)

    def solo(self, q):
        return mutable.search(self.mutable, q, self.k, params=self.params)


class _KeepParams:
    """Sentinel type — :data:`KEEP_PARAMS` is its only instance."""

    def __repr__(self) -> str:  # deterministic api-doc rendering
        return "KEEP_PARAMS"


#: :meth:`ServeEngine.refresh`'s ``params`` default: keep the current
#: serving params.  Any OTHER value — including ``None`` — is applied
#: verbatim (``None`` rebuilds the backend with its library-default
#: params).  The distinction matters to the autotuner's guarded
#: rollback: reverting a params promotion on an engine constructed with
#: ``params=None`` must restore that ``None``, not silently keep the
#: regressing candidate's params.
KEEP_PARAMS = _KeepParams()


def _make_backend(index, k, params, metric, metric_arg, batch_size_index):
    if isinstance(index, ann_mnmg.ReplicaSet):
        return _ReplicaBackend(index, k, params)
    if isinstance(index, ann_mnmg.ShardedIndex):
        return _ShardedBackend(index, k, params)
    if isinstance(index, tiering.TieredIndex):
        return _TieredBackend(index, k, params)
    if isinstance(index, mutable.MutableIndex):
        return _MutableBackend(index, k, params)
    if isinstance(index, ivf_flat.Index):
        return _IvfFlatBackend(index, k, params)
    if isinstance(index, ivf_pq.Index):
        return _IvfPqBackend(index, k, params)
    return _BruteForceBackend(index, k, metric, metric_arg,
                              batch_size_index)


class ServeEngine:
    """Coalescing, bucket-compiled, zero-retrace query server for one
    (index, k, params) serving key.

    Construct one engine per serving key; concurrent requests against the
    same key are what coalescing amortizes (the reference's analogue: one
    precompiled kernel instantiation serving every caller of that
    signature).  ``index`` selects the backend by type:

    * a dense (n, dim) array → brute-force kNN (``metric``/``metric_arg``/
      ``batch_size_index`` apply),
    * :class:`raft_tpu.neighbors.ivf_flat.Index` → IVF-Flat
      (*params* is an ``ivf_flat.SearchParams``),
    * :class:`raft_tpu.neighbors.ivf_pq.Index` → IVF-PQ
      (*params* is an ``ivf_pq.SearchParams``),
    * :class:`raft_tpu.neighbors.ann_mnmg.ShardedIndex` → the sharded
      multi-device backend: super-batches dispatch as ONE shard_map
      program across every device of the index's communicator (*params*
      is the underlying kind's SearchParams; brute-force sharded indexes
      carry their metric themselves),
    * :class:`raft_tpu.neighbors.tiering.TieredIndex` → the two-phase
      host/device tiered backend (hot-set scan + double-buffered cold-tile
      staging + optional ``refine_ratio`` exact re-rank, still
      zero-compile warm; *params* is the underlying kind's SearchParams).
      Re-tier off the request path via
      ``engine.refresh(tiering.retier(tiered, hotness))`` with the
      backend's ``searcher.hotness()`` counters.
    * :class:`raft_tpu.neighbors.mutable.MutableIndex` → the mutable
      (delta segment + tombstones) backend: serving stays zero-compile
      while ``upsert()``/``delete()`` land concurrently, and background
      compaction promotes its rebuilt core via ``engine.refresh``
      (*params* is the underlying kind's SearchParams; see
      docs/mutable_index.md).

    ``max_batch`` bounds one coalesced super-batch (and is the largest
    bucket :meth:`warmup` pins by default).  ``handle`` supplies the stream
    pool used for double-buffered dispatch; the default builds a 2-lane
    pool (double buffering proper).

    Thread-safety: :meth:`search` may be called concurrently; the engine
    serializes planning/dispatch under a lock (the coalescing win comes
    from batching WITHIN a call — an async front-end should gather its
    in-flight requests and pass them as one ``search([...])``).
    """

    def __init__(self, index, k: int, params=None, *,
                 metric=DistanceType.L2SqrtExpanded, metric_arg: float = 2.0,
                 max_batch: int = 1024, batch_size_index: int = 16384,
                 handle: Optional[Handle] = None,
                 admission=None, watchdog_s: Optional[float] = None,
                 max_retries: int = 2, retry_backoff_s: float = 0.05,
                 retry_backoff_cap_s: float = 1.0, retry_seed: int = 0,
                 scheduler=None):
        expects(max_batch >= 8, "max_batch must be >= 8")
        self._backend = _make_backend(index, k, params, metric, metric_arg,
                                      batch_size_index)
        # the served container itself (refresh() re-points it): the
        # autotuner's promotion path re-refreshes the SAME index under
        # candidate params, so the engine must be able to hand it back
        self._index = index
        # refresh() rebuilds a backend of the (possibly) same kind with the
        # same serving knobs — keep them (and the UNCLAMPED batch bound:
        # the transient cap depends on the index and is re-derived then)
        self._ctor = dict(k=int(k), params=params, metric=metric,
                          metric_arg=metric_arg,
                          batch_size_index=batch_size_index)
        self._requested_max_batch = int(max_batch)
        self.max_batch = int(max_batch)
        cap = getattr(self._backend, "batch_cap", lambda: None)()
        if cap is not None:
            self.max_batch = max(8, min(self.max_batch, cap))
        # double-buffering wants >= 2 pool lanes to alternate; a caller-
        # supplied handle is used AS-IS (its get_next_usable_stream falls
        # back to the main stream when it carries no pool — correct, just
        # single-lane bookkeeping), and the caller owns its sync
        self._handle = handle if handle is not None else Handle(n_streams=2)
        self._warmed: Dict[Any, set] = {}  # dtype(str) -> {buckets}
        self._lock = threading.Lock()
        # guards in-place _warmed mutation against the LOCKLESS /healthz
        # reader (_health must not queue behind an in-flight search() on
        # self._lock, and must never iterate a set mid-add); writers
        # already hold self._lock, so ordering is always _lock → this
        self._warmed_mut = threading.Lock()
        self._refreshing = False  # /healthz: refresh in flight
        self._closed = False      # close(): new requests reject typed
        self._recorder = None     # slow-request flight recorder (serve_http)
        self._http = None         # the live scrape server, if started
        #: bounded live-traffic shadow ring (docs/serving.md §autotuning):
        #: the autotuner's shadow replays sample REAL recent requests from
        #: here — round-robin overwrite, at most _SHADOW_RING ingested
        #: request arrays retained (fixed memory, one list store per
        #: request on the hot path)
        self._shadow_ring: List[Optional[np.ndarray]] = \
            [None] * _SHADOW_RING
        self._shadow_pos = 0
        self._tuner = None        # attached AutoTuner (/healthz autotune)
        #: Serving statistics — the same keys and read surface as the
        #: pre-telemetry plain dict, now a Counter-shaped view over the
        #: registry (``raft_tpu_serve_engine_stats{engine,key}``): reads
        #: (``stats["requests"]``, ``dict(stats)``, iteration) are
        #: unchanged, mutation is atomic, and every engine's stats export
        #: via ``telemetry.snapshot()`` / ``prometheus_text()``.
        self._engine_id = str(next(_ENGINE_IDS))
        self.stats: telemetry.LegacyCounterView = telemetry.legacy_counter(
            "raft_tpu_serve_engine_stats", "ServeEngine serving statistics",
            labelnames=("engine", "key"), fixed=(self._engine_id,))
        for key in ("requests", "queries", "super_batches",
                    "solo_fallbacks", "coalesced_requests", "refreshes",
                    "admitted", "sheds", "expired", "retries",
                    "watchdog_timeouts", "isolation_splits",
                    "ingest_errors", "dispatch_errors",
                    "sched_dispatches", "sched_waits",
                    "replica_faults", "replica_reroutes"):
            self.stats[key] = 0
        #: continuous-batching scheduler (docs/serving.md §scheduler) —
        #: ON by default: the telemetry-steered chooser replaces the
        #: drain-all coalescer (cold it reproduces the drain-all packing
        #: exactly, so default behavior only changes once measured
        #: per-bucket costs say a different packing is cheaper);
        #: ``scheduler=False`` pins the legacy drain-all planner (the
        #: bench A/B baseline), a SchedulerConfig tunes quantum/model
        if scheduler is False:
            self._sched_cfg: Optional[SchedulerConfig] = None
        else:
            self._sched_cfg = (scheduler if isinstance(
                scheduler, SchedulerConfig) else SchedulerConfig())
        #: the scheduler/router cost model: per-(dtype, bucket) EWMA fed
        #: after every collected super-batch, registry-seeded
        self._cost = CostModel(
            fn=self._backend_fn(),
            static_batch_s=(self._sched_cfg.static_batch_s
                            if self._sched_cfg is not None else 0.05),
            use_telemetry=(self._sched_cfg.use_telemetry
                           if self._sched_cfg is not None else True))
        # cold-start cost seeding (docs/serving.md §cold start): when an
        # AOT executable store is installed, the previous process's
        # persisted per-(dtype, bucket) cost rows (written by close())
        # seed the model, so the FIRST scheduler decisions after a 0.15s
        # store-warm restart use real costs, not the static fallback
        self._seed_cost_from_store()
        #: replica-lane router (2D shard × replica backends only):
        #: least-estimated-completion-time pick + fault draining
        self._router: Optional[ReplicaRouter] = None
        if getattr(self._backend, "n_replicas", 0) > 1:
            self._router = ReplicaRouter(self._backend.n_replicas,
                                         self._engine_id)
        #: streaming continuous batching (submit()): pending envelope
        #: queue + the quantum-paced scheduler thread, started lazily
        self._pending: List[Any] = []
        self._pending_cv = threading.Condition()
        self._sched_thread: Optional[threading.Thread] = None
        #: deadline-aware admission (docs/serving.md §failure model):
        #: default controller unless the caller passes its own or opts
        #: out with ``admission=False`` — with no deadlines and no queue
        #: bound the default never sheds, so the layer is free until used
        if admission is False:
            self._admission: Optional[AdmissionController] = None
        else:
            self._admission = (admission if admission is not None
                               else AdmissionController())
            self._admission.bind(self._engine_id)
        #: supervised collection: watchdog + bounded retry/backoff; the
        #: supervisor mirrors its events into stats via _sup_event
        self._supervisor = DispatchSupervisor(
            watchdog_s=watchdog_s, max_retries=max_retries,
            backoff_s=retry_backoff_s, backoff_cap_s=retry_backoff_cap_s,
            seed=retry_seed, on_event=self._sup_event)
        #: Fixed-memory per-request completion-latency distribution
        #: (request j completes when its super-batch's results land on the
        #: host, measured from ``search()`` entry) + a bounded
        #: LATENCY_RESERVOIR-sample uniform reservoir for exact-sample
        #: percentiles — the bounded replacement of the old unbounded
        #: ``last_latencies`` list (see :meth:`latency_quantiles`).
        self.latency_hist: telemetry.Histogram = telemetry.histogram(
            "raft_tpu_serve_request_latency_seconds",
            "per-request completion latency within one search() call",
            labelnames=("engine",), reservoir=LATENCY_RESERVOIR)
        self._last_latencies: List[float] = []

    @property
    def backend(self) -> str:
        return self._backend.name

    @property
    def k(self) -> int:
        return self._backend.k

    @property
    def index(self):
        """The served container (as last constructed/refreshed) — what the
        autotuner re-refreshes under candidate params."""
        return self._index

    def _sup_event(self, kind: str) -> None:
        # supervisor events → the engine's stats mirror
        self.stats.inc({"retry": "retries",
                        "watchdog_timeout": "watchdog_timeouts"}[kind])

    def _backend_fn(self) -> Optional[str]:
        """The backend program's telemetry label (``raft_tpu_device_seconds
        {fn}`` / dispatch-latency rows) — the admission cost estimator's
        key; None when unknown (estimator falls back to static)."""
        be = self._backend
        fn = getattr(be, "fn", None)
        if fn is None:
            fn = getattr(getattr(be, "searcher", None), "fn", None)
        if fn is None:
            searchers = getattr(be, "searchers", None)
            if searchers:  # replica lanes share one program identity
                fn = getattr(searchers[0], "fn", None)
        return getattr(fn, "__qualname__", None)

    # -- latency telemetry --------------------------------------------------
    @property
    def last_latencies(self) -> List[float]:
        """Per-request completion latencies (seconds) of the LAST
        ``search()`` call — the legacy read surface, now BOUNDED: at most
        :data:`LATENCY_RESERVOIR` samples are retained per call (the full
        distribution is in :attr:`latency_hist`; long-running engines no
        longer accumulate one float per request forever)."""
        return list(self._last_latencies)

    def latency_quantiles(self, qs: Sequence[float] = (0.5, 0.99)
                          ) -> List[Optional[float]]:
        """Completion-latency quantile estimates over the engine's WHOLE
        serving history, from the fixed-memory log-bucketed histogram
        (within ~one bucket ratio of exact; the serve bench reports its
        p50/p99 from here).  ``None`` entries when nothing was recorded
        (e.g. telemetry disabled)."""
        return [self.latency_hist.quantile(q, (self._engine_id,))
                for q in qs]

    # -- warmup / pinning ---------------------------------------------------
    def warmup(self, buckets: Optional[Sequence[int]] = None,
               dtypes: Sequence[Any] = (jnp.float32,)) -> int:
        """Pre-lower+compile every (bucket, dtype) search signature through
        the backend's ``aot()`` cache (the ship-precompiled-libs moment).

        *buckets* defaults to every power-of-two bucket from 8 up to
        ``max_batch`` — after that, ANY coalesced super-batch the planner
        can emit hits a pinned executable and steady-state serving performs
        zero compiles (assert with ``core.aot.aot_compile_counters``).
        Explicit *buckets* narrow the range: requests that cannot fit the
        largest warmed bucket are served solo (counted, not compiled).
        Returns the number of (bucket, dtype) signatures ensured."""
        expects(not self._closed, "warmup() on a closed engine")
        if buckets is None:
            buckets = []
            b = 8
            while b < self.max_batch:
                buckets.append(b)
                b <<= 1
            buckets.append(self.max_batch)
        n = 0
        with self._lock:
            for dt in dtypes:
                dt = jnp.dtype(dt)
                for b in sorted(set(int(x) for x in buckets)):
                    expects(8 <= b <= self.max_batch,
                            f"bucket {b} outside [8, max_batch="
                            f"{self.max_batch}]")
                    self._backend.warm(b, dt)
                    with self._warmed_mut:
                        self._warmed.setdefault(str(dt), set()).add(b)
                    n += 1
        return n

    def warmed_buckets(self, dtype) -> List[int]:
        return sorted(self._warmed.get(str(jnp.dtype(dtype)), ()))

    def warmed_signatures(self) -> Dict[str, List[int]]:
        """The certified warmed-signature ladder as a plain mapping
        (dtype string → sorted buckets) — the autotuner's candidate-space
        source: every knob it explores is drawn from this set, which is
        what makes exploration zero-compile by construction."""
        with self._warmed_mut:
            return {dt: sorted(bs) for dt, bs in self._warmed.items()}

    # -- autotuning hooks (docs/serving.md §autotuning) ---------------------
    def shadow_samples(self) -> List[np.ndarray]:
        """A snapshot of the live-traffic shadow ring: up to _SHADOW_RING
        recently ingested request arrays, the autotuner's sampled-live
        shadow traffic source."""
        return [q for q in list(self._shadow_ring) if q is not None]

    def attach_tuner(self, tuner) -> None:
        """Attach (or detach with None) an AutoTuner: its state shows in
        the ``/healthz`` body as the ``autotune`` sub-object."""
        self._tuner = tuner

    def apply_tuning(self, *, quantum_s: Optional[float] = None,
                     max_batch: Optional[int] = None) -> Dict[str, Any]:
        """Atomically apply host-side tuner knobs; returns the PREVIOUS
        values (the tuner's rollback token).  ``max_batch`` must be a
        warmed bucket (or the construction-time cap): the planner's
        ladder cap stays inside the certified warmed signature space, so
        a promoted cap can never make dispatch compile."""
        expects(not self._closed, "apply_tuning() on a closed engine")
        with self._lock:
            prev: Dict[str, Any] = {
                "quantum_s": (self._sched_cfg.quantum_s
                              if self._sched_cfg is not None else None),
                "max_batch": self.max_batch}
            if quantum_s is not None:
                expects(self._sched_cfg is not None,
                        "quantum tuning needs the scheduler enabled")
                expects(quantum_s > 0.0, "quantum_s must be positive")
                self._sched_cfg = dataclasses.replace(
                    self._sched_cfg, quantum_s=float(quantum_s))
            if max_batch is not None:
                b = int(max_batch)
                with self._warmed_mut:
                    warmed_any = {x for bs in self._warmed.values()
                                  for x in bs}
                cap = getattr(self._backend, "batch_cap", lambda: None)()
                base = (self._requested_max_batch if cap is None else
                        max(8, min(self._requested_max_batch, cap)))
                expects(b in warmed_any or b == base,
                        f"max_batch={b} is neither a warmed bucket nor "
                        "the construction cap — tuning must stay inside "
                        "the certified ladder")
                self.max_batch = b
            return prev

    def _seed_cost_from_store(self) -> None:
        """Seed the scheduler cost model from the AOT store's persisted
        per-signature cost rows (written by close()); a no-op without an
        installed store or persisted rows for this backend program."""
        from raft_tpu.core import aotstore

        store = aotstore.installed()
        fn = self._backend_fn()
        if store is None or not fn:
            return
        self._cost.seed_rows(store.load_costs(fn))

    def _persist_cost_rows(self) -> None:
        """Persist the cost model's observed rows next to the executables
        (close()-time): the next process's construction seeds from them."""
        from raft_tpu.core import aotstore

        store = aotstore.installed()
        fn = self._backend_fn()
        if store is None or not fn:
            return
        rows = self._cost.rows()
        if rows:
            store.save_costs(fn, rows)

    # -- index refresh ------------------------------------------------------
    def refresh(self, index, params=KEEP_PARAMS) -> None:
        """Swap the served index for *index* without cold-serving a single
        request — the serving half of the tiled-build refresh loop
        (docs/index_build.md): rebuild or ``extend()`` the index off the
        request path (``ivf_pq.build_sharded`` for multi-device serving),
        then ``refresh()`` it in.

        The replacement backend (same k; *params* defaults to
        :data:`KEEP_PARAMS` — the current serving params; any OTHER
        value, including ``None`` for the backend's library defaults, is
        applied verbatim) is constructed and EVERY previously-warmed
        (bucket, dtype) signature is pre-lowered through its ``aot()``
        cache BEFORE the swap, so compiles happen here — off the request
        path — and steady-state traffic after the swap stays
        zero-compile (counter-assertable exactly like first warmup).  The
        swap itself is atomic under the engine lock; in-flight results of
        earlier ``search()`` calls were already collected and are
        unaffected.  ``max_batch`` re-derives from the requested bound and
        the NEW index's transient cap; warmed buckets above it are
        dropped (requests that needed them fall back to solo, counted)."""
        expects(not self._closed, "refresh() on a closed engine")
        self._refreshing = True  # /healthz reports the swap in flight
        try:
            with telemetry.span("serve.refresh"):
                self._refresh(index, params)
        finally:
            self._refreshing = False

    def _refresh(self, index, params):
        # fault-plane crash window 1: nothing built yet — a crash here
        # must leave the old backend untouched trivially
        _faults.check("refresh", stage="pre_warm")
        with self._lock:  # snapshot under the lock: warmup() mutates it
            c = dict(self._ctor)
            snapshot = {dt: set(bs) for dt, bs in self._warmed.items()}
        if params is KEEP_PARAMS:
            params = c["params"]
        backend = _make_backend(index, c["k"], params, c["metric"],
                                c["metric_arg"], c["batch_size_index"])
        max_batch = self._requested_max_batch
        cap = getattr(backend, "batch_cap", lambda: None)()
        if cap is not None:
            max_batch = max(8, min(max_batch, cap))
        warmed = {dt: {b for b in bs if b <= max_batch}
                  for dt, bs in snapshot.items()}
        for dt, buckets in warmed.items():
            for b in sorted(buckets):
                backend.warm(b, jnp.dtype(dt))
        # fault-plane crash window 2: BETWEEN re-lower and swap — the
        # atomicity the battery proves: a crash raised here discards the
        # fully-warmed replacement and the OLD backend keeps serving
        # (tests/test_serve_faults.py injects it; nothing below this line
        # but the locked swap may fail partially)
        _faults.check("refresh", stage="pre_swap")
        with self._lock:
            # signatures warmed by a concurrent warmup() since the
            # snapshot must not be silently dropped — warm them under the
            # lock (rare; blocks briefly) so the zero-retrace contract
            # survives the swap
            for dt, bs in self._warmed.items():
                late = {b for b in bs if b <= max_batch} - warmed.get(
                    dt, set())
                for b in sorted(late):
                    backend.warm(b, jnp.dtype(dt))
                warmed.setdefault(dt, set()).update(late)
            self._backend = backend
            self._index = index
            self._ctor = dict(c, params=params)
            self.max_batch = max_batch
            self._warmed = warmed
            # the scheduler's cost seed re-points at the new backend
            # program, and a replica backend gets a FRESH router (the new
            # ReplicaSet's lanes are new replicas — drained state does
            # not carry over a swap)
            self._cost.bind_fn(self._backend_fn())
            self._seed_cost_from_store()
            if getattr(backend, "n_replicas", 0) > 1:
                self._router = ReplicaRouter(backend.n_replicas,
                                             self._engine_id)
            else:
                self._router = None
            self.stats.inc("refreshes")

    # -- live scrape surface ------------------------------------------------
    def _health(self) -> Dict[str, Any]:
        """The /healthz body: ready iff at least one (bucket, dtype)
        signature is warmed (steady-state serving cannot compile) and no
        index refresh is mid-swap.  Deliberately does NOT take the engine
        lock (a probe must not queue behind an in-flight search); the
        warmed map is copied under its mutation lock so a scrape racing
        warmup() never iterates a set mid-add."""
        with self._warmed_mut:
            warmed = {dt: sorted(bs) for dt, bs in self._warmed.items()}
        ready = (any(warmed.values()) and not self._refreshing
                 and not self._closed)
        body = {"ready": bool(ready), "backend": self.backend, "k": self.k,
                "max_batch": self.max_batch, "warmed": warmed,
                "refresh_in_flight": bool(self._refreshing),
                "closed": bool(self._closed),
                "stats": dict(self.stats)}
        # overload is DEGRADED, not down: recent shedding/expiry flags the
        # body (load balancers can read it) while the probe stays 200 —
        # a shedding engine is still the best place to send traffic that
        # fits its deadline budget (docs/serving.md §failure model)
        adm = self._admission
        body["degraded"] = (adm.degraded(telemetry.now())
                            if adm is not None else False)
        if adm is not None:
            body["admission"] = adm.health(telemetry.now())
        # replica routing: a drained (faulted) lane marks the body
        # DEGRADED — the engine still serves on survivors (200, not 503),
        # and a balancer can see which lanes died
        router = self._router
        if router is not None:
            rh = router.health()
            body["replicas"] = rh
            if rh["degraded"]:
                body["degraded"] = True
        if self._sched_cfg is not None:
            body["scheduler"] = {
                "quantum_s": self._sched_cfg.quantum_s,
                "pending": len(self._pending)}
        # autotuner visibility: candidate decisions, promotion state and
        # the rollback guard window (docs/serving.md §autotuning)
        tuner = self._tuner
        if tuner is not None:
            body["autotune"] = tuner.health()
        # tiered residency: hot/cold split + staging-tile footprint, so a
        # scrape can see what re-tiering (refresh + tiering.retier) did
        stats_fn = getattr(self._backend, "searcher", None)
        stats_fn = getattr(stats_fn, "tier_stats", None)
        if stats_fn is not None:
            body["tiering"] = stats_fn()
        return body

    def serve_http(self, port: int = 0, host: str = "127.0.0.1", *,
                   slow_threshold_s: Optional[float] = None,
                   slow_cap: Optional[int] = None):
        """Start the live scrape surface for this engine
        (docs/observability.md §scrape endpoints): ``/metrics`` (Prometheus
        text over the whole process registry), ``/healthz`` (engine
        readiness: warmed buckets present, no refresh in flight — 503 until
        :meth:`warmup` ran), ``/varz`` (snapshot JSON) and ``/debug/slow``
        (a bounded flight-recorder ring of span trees for ``search()``
        calls slower than *slow_threshold_s*; recording costs one
        thread-local list per request and only while telemetry is
        enabled).  ``port=0`` binds an ephemeral port — read it from the
        returned server's ``.port``.  Idempotent: a second call returns
        the running server; ``close()`` (or the server's own ``close()``)
        stops it."""
        from raft_tpu.telemetry import http as telemetry_http

        expects(not self._closed, "serve_http() on a closed engine")
        with self._lock:
            if self._http is None:
                self._recorder = telemetry_http.FlightRecorder(
                    telemetry_http.DEFAULT_SLOW_THRESHOLD_S
                    if slow_threshold_s is None else slow_threshold_s,
                    telemetry_http.DEFAULT_SLOW_CAP
                    if slow_cap is None else slow_cap)
                self._http = telemetry_http.TelemetryServer(
                    port, host, health=self._health,
                    recorder=self._recorder).start()
            return self._http

    def close(self, timeout_s: float = 5.0) -> None:
        """Bounded, idempotent shutdown (docs/serving.md §failure model):

        * requests arriving AFTER close() reject immediately with a typed
          ``RejectedError(reason="closed")`` — never a hang, never an
          undefined half-closed dispatch;
        * requests already in flight DRAIN: close() waits up to
          *timeout_s* for the engine lock (an in-flight ``search()``
          completes and delivers its results) and proceeds regardless
          after the bound — shutdown latency is bounded either way;
        * the scrape server (if :meth:`serve_http` started one) stops and
          joins with its own bounded timeout, the flight recorder drops;
        * double-close is a no-op (pinned by the fault battery).

        ``/healthz`` reports ``ready: false`` (503) once closed."""
        if self._closed:
            return  # idempotent
        self._closed = True  # reject new requests from this point on
        # persist the observed per-(dtype, bucket) cost rows next to the
        # store's executables, so the next process's cold restore starts
        # its scheduler on real costs (see _seed_cost_from_store)
        self._persist_cost_rows()
        # stop the submit() scheduler thread and reject its queue typed
        # (never leave a Future dangling)
        with self._pending_cv:
            pending, self._pending = list(self._pending), []
            self._pending_cv.notify_all()
        for _r, f, _t in pending:
            if not f.done():
                f.set_exception(RejectedError(
                    "closed", "engine closed with the request still "
                    "queued in the scheduler"))
        t = self._sched_thread
        if t is not None:
            t.join(timeout=min(1.0, timeout_s))
        acquired = self._lock.acquire(timeout=timeout_s)  # drain in-flight
        try:
            http, self._http, self._recorder = self._http, None, None
        finally:
            if acquired:
                self._lock.release()
        if http is not None:
            http.close()

    # -- the request path ---------------------------------------------------
    def _plan(self, sizes: List[int], max_bucket: int
              ) -> Tuple[List[List[Tuple[int, int, int]]], List[int]]:
        """Greedy in-order packing: returns (super_batches, solo) where each
        super-batch is [(request_idx, start_row, n_rows), ...] with total
        rows ≤ *max_bucket*, and *solo* lists requests too large for it."""
        batches: List[List[Tuple[int, int, int]]] = []
        solo: List[int] = []
        cur: List[Tuple[int, int, int]] = []
        cur_n = 0
        for j, n in enumerate(sizes):
            if n > max_bucket:
                solo.append(j)
                continue
            if cur_n + n > max_bucket:
                batches.append(cur)
                cur, cur_n = [], 0
            cur.append((j, cur_n, n))
            cur_n += n
        if cur:
            batches.append(cur)
        return batches, solo

    def _bucket_for(self, total: int, warmed: set) -> int:
        """Smallest usable padded size: the power-of-two bucket, clamped to
        max_batch; if warmup pinned an explicit set, the smallest warmed
        bucket ≥ total (warmup guarantees one exists for totals the planner
        emits — max_bucket below is min(max(warmed), max_batch))."""
        b = min(_bucket_dim(total), self.max_batch)
        if warmed and b not in warmed:
            bigger = [w for w in warmed if w >= total]
            if bigger:
                b = min(bigger)
        return b

    def search(self, requests: Sequence[Any]
               ) -> List[Tuple[np.ndarray, np.ndarray]]:
        """Serve a batch of concurrent requests.

        *requests*: sequence of (n_j, dim) query matrices (ragged n_j ≥ 0),
        each optionally wrapped in a
        :class:`~raft_tpu.serve.admission.ServeRequest` to carry a
        deadline/timeout.  Returns one ``(distances (n_j, k), indices
        (n_j, k))`` numpy pair per request, in request order — each
        bit-identical to what the backend's public solo entry point
        returns for that request.

        Failure model (docs/serving.md §failure model): a request that is
        shed (deadline/overload), fails ingest, or whose dispatch fails
        after supervision receives ITS EXCEPTION in its result slot — a
        typed ``RejectedError`` / the ingest/dispatch error — while every
        other request in the call is served normally.  ``search()`` itself
        raises only for engine-level misuse (closed engine).

        Pipeline: ingest → group by compute dtype → greedy in-order packing
        into ≤ max_batch super-batches → per batch: host-side numpy
        assembly + pad to the warmed bucket, ONE device transfer, ONE fused
        async dispatch recorded on the next pool stream (assembly of batch
        i+1 overlaps execution of batch i) → collect host results → slice
        per request.

        Each phase runs under a nested telemetry span
        (``serve.request`` → ``serve.ingest`` / ``serve.coalesce`` /
        ``serve.assemble`` / ``serve.dispatch`` / ``serve.deliver``) — wall
        time only, no device syncs, no-ops under ``RAFT_TPU_TELEMETRY=0``
        (docs/observability.md has the span taxonomy).  With
        :meth:`serve_http` running, a call slower than the flight
        recorder's threshold leaves its span tree in the bounded
        ``/debug/slow`` ring."""
        if self._closed:
            raise RejectedError("closed", "ServeEngine is closed — new "
                                "requests reject; see close()")
        rec = self._recorder
        if rec is None or not telemetry.enabled():
            with self._lock:
                with telemetry.span("serve.request"):
                    return self._search_locked(requests)
        with self._lock:
            t0 = telemetry.now()
            with telemetry.collect_spans() as col:
                with telemetry.span("serve.request"):
                    out = self._search_locked(requests)
            dur = telemetry.now() - t0
            if dur >= rec.threshold_s:
                rec.record(col.events, dur_s=round(dur, 6),
                           requests=len(requests),
                           queries=sum(
                               int(np.shape(q.q if isinstance(
                                   q, ServeRequest) else q)[0])
                               for q in requests))
            return out

    # -- streaming continuous batching (submit/flush) -----------------------
    def submit(self, request) -> "futures.Future":
        """Enqueue ONE request for continuous batching; returns a
        ``concurrent.futures.Future`` resolving to the same ``(distances,
        indices)`` pair ``search()`` would produce for it (or raising its
        typed rejection/ingest error).

        The quantum-paced scheduler thread coalesces submissions across
        callers: a pending partial batch dispatches when it fills the
        largest warmed bucket, when its oldest member has waited one
        quantum, or when an admitted deadline would be jeopardized by
        waiting longer — otherwise it waits one quantum to fill a larger
        bucket (:func:`raft_tpu.serve.schedule.should_dispatch`; the
        decision counters land in ``stats["sched_dispatches"]`` /
        ``stats["sched_waits"]``).  Dispatch itself runs through the
        exact ``search()`` pipeline (admission, chooser, supervision,
        replica routing), so every contract — bit-identity, zero-compile,
        per-request isolation — carries over unchanged."""
        expects(self._sched_cfg is not None,
                "submit() requires the continuous-batching scheduler "
                "(engine constructed with scheduler=False)")
        if self._closed:
            raise RejectedError("closed", "ServeEngine is closed — new "
                                "requests reject; see close()")
        fut: futures.Future = futures.Future()
        with self._pending_cv:
            self._pending.append((request, fut, telemetry.now()))
            if self._sched_thread is None \
                    or not self._sched_thread.is_alive():
                self._sched_thread = threading.Thread(
                    target=self._sched_loop, daemon=True,
                    name=f"raft-tpu-serve-sched-{self._engine_id}")
                self._sched_thread.start()
            self._pending_cv.notify_all()
        return fut

    def flush(self) -> None:
        """Force-dispatch everything pending in the submit() queue NOW
        (in the caller's thread), without waiting out the quantum."""
        with self._pending_cv:
            batch, self._pending = list(self._pending), []
        if batch:
            self._serve_pending(batch)

    def _serve_pending(self, batch) -> None:
        try:
            outs = self.search([r for r, _f, _t in batch])
        except Exception as e:  # engine-level misuse (e.g. closed)
            for _r, f, _t in batch:
                if not f.done():
                    f.set_exception(e)
            return
        for (_r, f, _t), out in zip(batch, outs):
            if f.done():
                continue
            if isinstance(out, BaseException):
                f.set_exception(out)
            else:
                f.set_result(out)

    def _sched_loop(self) -> None:
        """The quantum-paced scheduler thread behind :meth:`submit`."""
        cfg = self._sched_cfg
        while True:
            with self._pending_cv:
                if not self._pending:
                    if self._closed:
                        return
                    self._pending_cv.wait(timeout=cfg.quantum_s)
                    if not self._pending:
                        if self._closed:
                            return
                        continue
                now = telemetry.now()
                rows = 0
                dls: List[float] = []
                for r, _f, _t in self._pending:
                    q = r.q if isinstance(r, ServeRequest) else r
                    rows += int(np.shape(q)[0])
                    if isinstance(r, ServeRequest):
                        dl = r.resolve_deadline(now)
                        if dl is not None:
                            dls.append(dl)
                oldest = now - self._pending[0][2]
                with self._warmed_mut:
                    largest = max((max(bs) for bs in self._warmed.values()
                                   if bs), default=self.max_batch)
                est = self._cost.batch_cost_s("float32", largest)
                if self._closed or should_dispatch(
                        rows, largest, oldest, cfg.quantum_s, dls, now,
                        est):
                    batch, self._pending = list(self._pending), []
                    self.stats.inc("sched_dispatches")
                else:
                    # wait one quantum to fill a larger bucket
                    self.stats.inc("sched_waits")
                    self._pending_cv.wait(timeout=cfg.quantum_s)
                    continue
            self._serve_pending(batch)

    def _search_locked(self, requests):
        t_entry = telemetry.now()
        be = self._backend
        sup = self._supervisor
        adm = self._admission
        raw = [r.q if isinstance(r, ServeRequest) else r for r in requests]
        results: List[Any] = [None] * len(raw)
        latencies = [0.0] * len(raw)
        ingested: List[Any] = [None] * len(raw)
        with telemetry.span("serve.ingest"):
            for j, q in enumerate(raw):
                try:
                    ingested[j] = be.ingest(q)
                except Exception as e:
                    # per-request isolation: a poisoned request (bad
                    # dim/dtype, NaN-shaped ingest failure) fails ALONE —
                    # its typed error lands in its slot, the call goes on
                    results[j] = e
                    self.stats.inc("ingest_errors")
        self.stats.inc("requests", len(raw))
        self.stats.inc("queries", sum(int(q.shape[0]) for q in ingested
                                      if q is not None))
        # feed the bounded shadow ring (autotune shadow traffic source):
        # round-robin overwrite of fixed slots — one list store per
        # request, no allocation, no growth
        for q in ingested:
            if q is not None and q.shape[0]:
                self._shadow_ring[self._shadow_pos % _SHADOW_RING] = q
                self._shadow_pos += 1

        # deadline-aware admission in arrival order, BEFORE planning: a
        # request whose remaining budget cannot cover its projected
        # completion (batches queued ahead × the live per-batch cost
        # estimate) is shed here with a typed error, not queued to die
        deadlines: List[Optional[float]] = [None] * len(raw)
        if adm is not None:
            with telemetry.span("serve.admit"):
                est = adm.batch_cost_s(self._backend_fn())
                queued = 0
                for j, r in enumerate(requests):
                    if results[j] is not None or ingested[j] is None:
                        continue
                    n = int(ingested[j].shape[0])
                    if n == 0:
                        continue
                    now = telemetry.now()
                    if isinstance(r, ServeRequest):
                        deadlines[j] = r.resolve_deadline(now)
                    rej = adm.admit(n, deadlines[j], now, queued,
                                    queued // self.max_batch, est)
                    if rej is not None:
                        results[j] = rej
                        self.stats.inc("sheds")
                    else:
                        self.stats.inc("admitted")
                        queued += n

        # group by compute dtype (the engine IS the (index, k, params) key;
        # dtype is the one per-request signature dimension left)
        with telemetry.span("serve.coalesce"):
            by_dtype: Dict[str, List[int]] = {}
            for j, q in enumerate(ingested):
                if results[j] is not None or q is None:
                    continue
                if q.shape[0] == 0:
                    results[j] = (np.zeros((0, be.k), np.float32),
                                  np.full((0, be.k), -1, np.int32))
                    continue
                by_dtype.setdefault(str(q.dtype), []).append(j)
            plans = []
            for dt, idxs in by_dtype.items():
                warmed = self._warmed.get(dt, set())
                max_bucket = (min(max(warmed), self.max_batch) if warmed
                              else self.max_batch)
                sizes = [int(ingested[j].shape[0]) for j in idxs]
                if self._sched_cfg is not None:
                    # the continuous-batching chooser: telemetry-steered
                    # cut points, deadlines breaking ties; buckets come
                    # ONLY from the certified _bucket_for ladder, so the
                    # chooser stays inside the warmed signature space
                    # (retrace obligation serve.scheduler_closure)
                    dls = [deadlines[j] for j in idxs]
                    batches, solo = choose_batches(
                        sizes, dls,
                        lambda total, w=warmed: self._bucket_for(total, w),
                        max_bucket, self._cost, dt, telemetry.now())
                else:
                    batches, solo = self._plan(sizes, max_bucket)
                plans.append((dt, idxs, warmed, batches, solo))

        # (kind, members, out, redo, warmed, dt, bucket, block, lane_r, t0)
        inflight = []
        lane = 0
        for dt, idxs, warmed, batches, solo in plans:
            for batch in batches:
                members = [(idxs[jj], start, n) for jj, start, n in batch]
                members = self._drop_expired(members, deadlines, results)
                if not members:
                    continue
                total = members[-1][1] + members[-1][2]
                bucket = self._bucket_for(total, warmed)
                # host-side assembly: one contiguous padded block, ONE
                # transfer — deliberately numpy, so coalescing+padding is
                # pure host work the double-buffering can overlap with the
                # previous batch's device execution (and dispatches no
                # per-shape concat/pad programs on device)
                with telemetry.span("serve.assemble"):
                    block = np.zeros((bucket, be.dim),
                                     ingested[members[0][0]].dtype)
                    for j, start, n in members:
                        block[start:start + n] = ingested[j]
                est = self._cost.batch_cost_s(dt, bucket)
                t0 = telemetry.now()
                with telemetry.span("serve.dispatch"):
                    if self._router is None:
                        out = be.dispatch(jnp.asarray(block))  # async
                        lane_r = None
                    else:
                        # replica routing: least-estimated-completion
                        # lane; a dispatch-time lane fault drains the
                        # lane and re-routes (zero failed requests while
                        # any lane lives)
                        try:
                            out, lane_r = self._dispatch_routed(block, est)
                        except Exception as e:
                            done = telemetry.now() - t_entry
                            self.stats.inc("dispatch_errors")
                            for j, _s, _n in members:
                                results[j] = e
                                latencies[j] = done
                            continue
                    self._handle.get_next_usable_stream(lane).record(out)
                lane += 1
                # the retry path re-dispatches the SAME block through the
                # SAME warmed executable — zero-compile by construction
                if lane_r is None:
                    redo = (lambda blk=block: be.dispatch(jnp.asarray(blk)))
                else:
                    redo = (lambda blk=block, ln=lane_r:
                            be.dispatch(jnp.asarray(blk), ln))
                inflight.append(("coalesced", members, out, redo, warmed,
                                 dt, bucket, block, lane_r, t0))
                self.stats.inc("super_batches")
                self.stats.inc("coalesced_requests", len(members))
            for jj in solo:
                j = idxs[jj]
                if not self._drop_expired([(j, 0, 0)], deadlines, results):
                    continue
                # the RAW request, not the ingested form: the public entry
                # point applies its own ingest prologue, and re-ingesting
                # (e.g. normalizing an already-normalized cosine query)
                # would break the identical-to-solo contract at ulp level
                with telemetry.span("serve.dispatch"):
                    try:
                        out = be.solo(raw[j])  # public: compiles allowed
                    except Exception as e:
                        # an eager solo failure fails alone, like ingest
                        results[j] = e
                        self.stats.inc("dispatch_errors")
                        continue
                    self._handle.get_next_usable_stream(lane).record(out)
                lane += 1
                redo = (lambda jj_=j: be.solo(raw[jj_]))
                inflight.append(("solo", [(j, 0, ingested[j].shape[0])],
                                 out, redo, None, dt, None, None, None,
                                 telemetry.now()))
                self.stats.inc("solo_fallbacks")

        # collect: blocks per batch; later batches keep executing
        # meanwhile.  Collection is SUPERVISED (watchdog + bounded retry);
        # a replica-lane failure drains the lane and re-routes the SAME
        # block through a surviving lane's warmed executable; a
        # super-batch that still fails is split and re-dispatched
        # member-by-member so one poisoned request fails alone.
        with telemetry.span("serve.deliver"):
            for (kind, members, out, redo, warmed, dt, bucket, block,
                 lane_r, t0) in inflight:
                try:
                    d, i = sup.collect(out, redo=redo, label=kind)
                except Exception as e:
                    collected = None
                    if lane_r is not None:
                        collected = self._reroute(block, lane_r, e)
                    if collected is None:
                        self.stats.inc("dispatch_errors")
                        if kind == "coalesced" and len(members) > 1:
                            self.stats.inc("isolation_splits")
                            self._isolate(members, ingested, warmed,
                                          results, latencies, t_entry)
                        else:
                            done = telemetry.now() - t_entry
                            for j, _start, _n in members:
                                results[j] = e
                                latencies[j] = done
                        continue
                    d, i = collected
                done = telemetry.now() - t_entry
                now = telemetry.now()
                if kind == "coalesced" and bucket is not None:
                    # per-(dtype, bucket) service time → the scheduler's
                    # cost model (EWMA), the signal the chooser steers by
                    self._cost.observe(dt, bucket, now - t0)
                if lane_r is not None:
                    # per-lane observed service time → the router's cost
                    # EWMA: a SLOW (not failed) lane sheds load gradually
                    self._router.note_done(lane_r, now, now - t0)
                for j, start, n in members:
                    results[j] = (d[start:start + n], i[start:start + n])
                    latencies[j] = done
        # feed the observed end-to-end per-batch service time back into
        # the admission cost model (EWMA; see AdmissionController)
        n_batches = sum(1 for kind, *_ in inflight if kind == "coalesced")
        if adm is not None and n_batches:
            adm.observe_batches(n_batches, telemetry.now() - t_entry)
        eng = (self._engine_id,)
        for j, v in enumerate(latencies):
            if isinstance(results[j], tuple):  # served: record latency
                self.latency_hist.observe(v, eng)
        # the legacy per-call read surface, BOUNDED (see last_latencies)
        self._last_latencies = latencies[:LATENCY_RESERVOIR]
        return results

    def _drop_expired(self, members, deadlines, results):
        """Dispatch-time deadline pass over one planned batch: admitted
        requests whose deadline already passed are counted expired (and,
        under shed-over-deadline, dropped — their slots get the typed
        rejection and the survivors re-pack contiguously)."""
        adm = self._admission
        if adm is None:
            return members
        live, start = [], 0
        for j, _start, n in members:
            dl = deadlines[j]
            now = telemetry.now()
            if dl is not None and now > dl:
                self.stats.inc("expired")
                rej = adm.expire(dl, now)
                if rej is not None:
                    results[j] = rej
                    continue
            live.append((j, start, n))
            start += n
        return live

    def _dispatch_routed(self, block, est_s):
        """Replica-lane dispatch with dispatch-time fault draining: pick
        the least-loaded live lane, dispatch; a retryable lane failure
        (the comms fault site, a transient runtime error) DRAINS that
        lane and the same block re-routes to the next live lane — zero
        failed requests while any lane survives.  Raises only when every
        lane is drained or the failure is a logic bug (fail fast)."""
        be = self._backend
        tried: List[int] = []
        last: Optional[Exception] = None
        while True:
            lane = self._router.pick(telemetry.now(), est_s, exclude=tried)
            if lane is None:
                raise last if last is not None else RejectedError(
                    "overload", "no live replica lane to dispatch to")
            try:
                out = be.dispatch(jnp.asarray(block), lane)
                if tried:  # a drained lane's traffic landed elsewhere
                    self.stats.inc("replica_reroutes")
                return out, lane
            except Exception as e:
                if not retryable(e):
                    raise
                self._router.fault(lane)
                self.stats.inc("replica_faults")
                tried.append(lane)
                last = e

    def _reroute(self, block, lane, exc):
        """Collect-time replica failure: drain *lane* and re-dispatch the
        SAME assembled block through a surviving lane's warmed executable
        (zero-compile — every lane warmed every signature).  Returns the
        collected (d, i) or None when no lane can serve it (the caller
        falls back to isolation/per-request errors)."""
        if not retryable(exc):
            return None
        be = self._backend
        self._router.fault(lane)
        self.stats.inc("replica_faults")
        tried = [lane]
        while True:
            alt = self._router.pick(telemetry.now(), 0.0, exclude=tried)
            if alt is None:
                return None
            try:
                out = be.dispatch(jnp.asarray(block), alt)
                redo = (lambda blk=block, ln=alt:
                        be.dispatch(jnp.asarray(blk), ln))
                d, i = self._supervisor.collect(out, redo=redo,
                                                label="rerouted")
                self.stats.inc("replica_reroutes")
                return d, i
            except Exception:
                self._router.fault(alt)
                self.stats.inc("replica_faults")
                tried.append(alt)

    def _isolate(self, members, ingested, warmed, results, latencies,
                 t_entry):
        """Per-request isolation: re-dispatch each member of a failed
        super-batch ALONE through the existing bucket ladder (the ladder
        is warmed, so the re-dispatches are zero-compile — the fault
        battery counter-asserts this).  Members that fail alone get their
        error; the rest are served."""
        be = self._backend
        sup = self._supervisor
        for j, _start, n in members:
            bucket = self._bucket_for(n, warmed)
            block = np.zeros((bucket, be.dim), ingested[j].dtype)
            block[:n] = ingested[j]
            if self._router is not None:
                lanes = self._router.alive_lanes() or [0]
                ln0 = lanes[0]
                redo = (lambda blk=block, ln=ln0:
                        be.dispatch(jnp.asarray(blk), ln))
            else:
                redo = (lambda blk=block: be.dispatch(jnp.asarray(blk)))
            try:
                d, i = sup.collect(redo(), redo=redo, label="isolated")
                results[j] = (d[:n], i[:n])
            except Exception as e:
                self.stats.inc("dispatch_errors")
                results[j] = e
            latencies[j] = telemetry.now() - t_entry

    def sync(self) -> None:
        """Wait for every recorded in-flight dispatch (delegates to the
        handle; ``search`` already collected its own results)."""
        self._handle.sync()

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return (f"ServeEngine(backend={self.backend}, k={self.k}, "
                f"max_batch={self.max_batch}, "
                f"warmed={ {d: sorted(b) for d, b in self._warmed.items()} },"
                f" stats={dict(self.stats)})")
