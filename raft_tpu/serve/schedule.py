"""Telemetry-steered continuous batching + replica routing policy
(docs/serving.md §scheduler).

The PR-4 coalescer drained whatever was queued into maximal super-batches
— a fixed heuristic that is optimal only when per-batch cost is flat in
the bucket size.  The live telemetry the runtime already exports
(``raft_tpu_device_seconds{fn}`` p50, per-signature dispatch-latency
rows, the admission layer's end-to-end per-batch EWMA) says otherwise:
per-bucket cost has a fixed dispatch overhead plus a rows term, so
sometimes one padded 1024-bucket beats two 512s (overhead dominates) and
sometimes a 512 + an 8 beats a padded 1024 (padding waste dominates).
This module makes that choice explicitly, per dispatch, from measured
costs.  Three policy objects, all host-side arithmetic (no jax, no
device work — the serve hot-path rules apply module-wide):

* :class:`CostModel` — per-(dtype, bucket) service-time estimates:
  an EWMA fed by the engine after every collected super-batch, seeded
  from the registry (device-seconds p50 / merged dispatch-latency rows —
  ``telemetry.registry.merged_quantile``) and falling back to the
  admission layer's static estimate when cold.  Unobserved buckets
  interpolate from the nearest observed bucket's fixed+per-row split.
* :func:`choose_batches` — the chooser: a dynamic program over arrival-
  order cut points that minimizes the estimated total service time of
  the call's queue, with DEADLINE PRESSURE breaking ties (packings
  within one cost epsilon prefer fewer estimated deadline overruns,
  then earlier completion of deadline-carrying requests).  Buckets are
  chosen ONLY through the engine-supplied ``bucket_for`` callable (the
  certified ``_bucket_for`` ladder), so the chooser can never emit a
  signature ``warmup()`` did not pre-lower — the retrace certifier pins
  this statically (``serve.scheduler_closure``).
* :func:`should_dispatch` — the streaming quantum rule for
  ``ServeEngine.submit()``: dispatch the pending partial batch NOW when
  it fills the largest warmed bucket, when the oldest request has waited
  a full quantum, or when one more quantum of waiting would jeopardize
  an admitted deadline; otherwise wait one quantum to fill a larger
  bucket.
* :class:`ReplicaRouter` — least-estimated-completion-time routing
  across replica groups (the 2D shard × replica carve,
  docs/sharded_ann.md §replica groups): each lane tracks an estimated
  busy-until horizon; a faulted lane is DRAINED (marked degraded,
  removed from routing, visible in ``/healthz``) and its traffic
  re-routes to surviving lanes.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from raft_tpu import telemetry
from raft_tpu.core.error import expects

#: default scheduler quantum: how long a partial batch may wait for more
#: arrivals before it dispatches anyway (streaming ``submit()`` path)
DEFAULT_QUANTUM_S = 0.002

#: EWMA blend for per-bucket cost observations (matches the admission
#: controller's per-batch EWMA so the two models converge alike)
EWMA_KEEP = 0.7

#: two packings within this relative cost of each other are "tied" —
#: deadline pressure (overruns, then completion of deadline-carrying
#: requests) breaks the tie, per the scheduler contract
COST_TIE_REL = 0.05


@dataclasses.dataclass(frozen=True)
class SchedulerConfig:
    """Continuous-batching knobs (``ServeEngine(scheduler=...)``).

    ``use_telemetry=False`` pins the cost model to *static_batch_s* for
    every bucket — the chooser then degenerates to the drain-all packing
    (fewest batches), which is what makes deterministic tests and A/B
    baselines possible."""

    quantum_s: float = DEFAULT_QUANTUM_S
    static_batch_s: float = 0.05
    use_telemetry: bool = True


class CostModel:
    """Per-(dtype, bucket) super-batch service-time estimates for ONE
    engine's backend program.

    Estimate precedence per bucket: the bucket's own observed EWMA →
    interpolation from observed buckets (fixed + per-row decomposition
    when two buckets are observed, proportional scaling from one) → the
    registry seed (``raft_tpu_device_seconds{fn}`` p50, then the merged
    per-signature dispatch-latency rows) → the static fallback.  The
    engine feeds :meth:`observe` after every collected super-batch, so
    the model self-corrects from served traffic exactly like the
    admission EWMA does."""

    def __init__(self, fn: Optional[str] = None,
                 static_batch_s: float = 0.05,
                 use_telemetry: bool = True):
        self._fn = fn
        self.static_batch_s = float(static_batch_s)
        self.use_telemetry = bool(use_telemetry)
        self._ewma: Dict[Tuple[str, int], float] = {}

    def bind_fn(self, fn: Optional[str]) -> None:
        """Re-point the registry seed at a new backend program (refresh)."""
        self._fn = fn

    def rows(self) -> Dict[Tuple[str, int], float]:
        """The observed per-(dtype, bucket) EWMA rows — what the engine
        persists into the AOT executable store at close() so a cold
        restore's first scheduler decisions use real costs."""
        return dict(self._ewma)

    def seed_rows(self, rows: Dict[Tuple[str, int], float]) -> int:
        """Seed ABSENT per-(dtype, bucket) rows from a persisted snapshot
        (the AOT store's cost manifest); live observations already made
        take precedence.  Returns the number of rows seeded."""
        n = 0
        for (dt, b), v in rows.items():
            key = (str(dt), int(b))
            if key not in self._ewma and float(v) > 0.0:
                self._ewma[key] = float(v)
                n += 1
        return n

    def observe(self, dtype: str, bucket: int, wall_s: float) -> None:
        """One collected super-batch's end-to-end wall time."""
        if wall_s <= 0.0:
            return
        key = (str(dtype), int(bucket))
        prev = self._ewma.get(key)
        self._ewma[key] = (wall_s if prev is None
                           else EWMA_KEEP * prev + (1 - EWMA_KEEP) * wall_s)

    def _seed(self) -> Optional[float]:
        """The registry's per-batch estimate for the backend program —
        sampled device seconds p50 first, merged host dispatch-latency
        rows second (the admission controller's precedence)."""
        if not self._fn:
            return None
        dev = telemetry.REGISTRY.get("raft_tpu_device_seconds")
        if dev is not None:
            q = dev.quantile(0.5, (self._fn,))
            if q is not None:
                return float(q)
        disp = telemetry.REGISTRY.get("raft_tpu_aot_dispatch_seconds")
        if disp is not None:
            from raft_tpu.telemetry.registry import merged_quantile

            q = merged_quantile(disp, 0.5, (self._fn,))
            if q is not None:
                return float(q)
        return None

    def batch_cost_s(self, dtype: str, bucket: int) -> float:
        """Estimated seconds to serve one *bucket*-shaped super-batch."""
        if not self.use_telemetry:
            return self.static_batch_s
        dtype = str(dtype)
        bucket = int(bucket)
        exact = self._ewma.get((dtype, bucket))
        if exact is not None:
            return exact
        observed = sorted((b, v) for (dt, b), v in self._ewma.items()
                          if dt == dtype)
        if len(observed) >= 2:
            # fixed + per-row decomposition from the two nearest buckets
            (b0, c0), (b1, c1) = observed[0], observed[-1]
            per_row = max(0.0, (c1 - c0) / float(b1 - b0))
            fixed = max(0.0, c0 - per_row * b0)
            return fixed + per_row * bucket
        if len(observed) == 1:
            b0, c0 = observed[0]
            # one observation: scale the rows term, keep half as overhead
            return c0 * (0.5 + 0.5 * bucket / float(b0))
        seed = self._seed()
        return self.static_batch_s if seed is None else seed


def choose_batches(sizes: Sequence[int],
                   deadlines: Sequence[Optional[float]],
                   bucket_for: Callable[[int], int],
                   max_bucket: int,
                   cost: CostModel,
                   dtype: str,
                   now: float,
                   ) -> Tuple[List[List[Tuple[int, int, int]]], List[int]]:
    """The continuous-batching chooser: partition the arrival-order queue
    into super-batches minimizing estimated total service time under the
    live cost model, deadlines breaking ties.

    Same contract as the drain-all planner it replaces: returns
    ``(batches, solo)`` where each batch is ``[(request_idx, start_row,
    n_rows), ...]`` with total rows ≤ *max_bucket* and ``solo`` lists
    requests too large for any warmed bucket.  Requests stay in arrival
    order and batches are contiguous cuts of it, so per-request results
    remain bit-identical to solo dispatch regardless of where the cuts
    land (the PR-4 row-independence property).  Every batch's bucket is
    chosen through *bucket_for* — the engine's certified ladder — never
    computed here, which is what keeps the chooser inside the warmed
    signature space (retrace obligation ``serve.scheduler_closure``).

    The DP is over cut points: ``best[i]`` is the cheapest dispatch plan
    for the first *i* packable requests, compared by (total cost, then —
    within ``COST_TIE_REL`` — deadline overrun, then deadline-weighted
    completion).  With a flat cost model (cold start, or
    ``use_telemetry=False``) minimizing total cost minimizes the number
    of batches, which is exactly the drain-all packing.
    """
    expects(len(sizes) == len(deadlines),
            "choose_batches: one deadline slot per request")
    items: List[Tuple[int, int]] = []   # (request_idx, rows), packable
    solo: List[int] = []
    for j, n in enumerate(sizes):
        if n > max_bucket:
            solo.append(j)
        else:
            items.append((j, int(n)))
    if not items:
        return [], solo

    n_items = len(items)
    bucket_cost: Dict[int, float] = {}  # per-plan memo of the ladder costs

    def cost_of(total: int) -> Tuple[int, float]:
        bucket = bucket_for(total)
        c = bucket_cost.get(bucket)
        if c is None:
            c = cost.batch_cost_s(dtype, bucket)
            bucket_cost[bucket] = c
        return bucket, c

    # best[i] = (cost_s, overrun_s, weighted_s, cut_index)
    best: List[Tuple[float, float, float, int]] = [(0.0, 0.0, 0.0, -1)]
    for i in range(1, n_items + 1):
        cand: Optional[Tuple[float, float, float, int]] = None
        total = 0
        window_dls: List[float] = []  # deadlines within items[cut:i]
        for cut in range(i - 1, -1, -1):
            j, rows = items[cut]
            total += rows
            if total > max_bucket:
                break
            dl = deadlines[j]
            if dl is not None:
                window_dls.append(dl)
            _bucket, batch_cost = cost_of(total)
            prev = best[cut]
            cost_s = prev[0] + batch_cost
            overrun = 0.0
            weighted = 0.0
            for dl in window_dls:  # empty for deadline-less traffic
                weighted += cost_s
                late = (now + cost_s) - dl
                if late > 0.0:
                    overrun += late
            entry = (cost_s, prev[1] + overrun, prev[2] + weighted, cut)
            if cand is None:
                cand = entry
            else:
                # primary: total cost; within the tie epsilon the
                # deadline terms decide (pressure breaks ties)
                if entry[0] < cand[0] * (1.0 - COST_TIE_REL):
                    cand = entry
                elif entry[0] <= cand[0] * (1.0 + COST_TIE_REL):
                    if (entry[1], entry[2], entry[0]) < (cand[1], cand[2],
                                                         cand[0]):
                        cand = entry
        best.append(cand)

    # reconstruct the cuts back-to-front
    cuts: List[Tuple[int, int]] = []
    i = n_items
    while i > 0:
        cut = best[i][3]
        cuts.append((cut, i))
        i = cut
    cuts.reverse()
    batches: List[List[Tuple[int, int, int]]] = []
    for lo, hi in cuts:
        start = 0
        members = []
        for j, rows in items[lo:hi]:
            members.append((j, start, rows))
            start += rows
        batches.append(members)
    return batches, solo


def should_dispatch(pending_rows: int, largest_bucket: int,
                    oldest_age_s: float, quantum_s: float,
                    deadlines: Sequence[Optional[float]], now: float,
                    est_batch_s: float) -> bool:
    """The streaming quantum decision (``ServeEngine.submit()`` loop):
    dispatch the pending partial batch NOW, or wait one more quantum to
    fill a larger bucket?

    Dispatch now when (a) the queue already fills the largest warmed
    bucket (waiting cannot improve the packing), (b) the oldest pending
    request has waited a full quantum (bounded added latency — the
    continuous-batching contract), or (c) one more quantum of waiting
    plus the estimated batch service time would push any admitted
    deadline past its budget (deadline pressure overrides batching
    greed).  Otherwise wait."""
    if pending_rows <= 0:
        return False
    if pending_rows >= largest_bucket:
        return True
    if oldest_age_s >= quantum_s:
        return True
    for dl in deadlines:
        if dl is not None and now + quantum_s + est_batch_s > dl:
            return True
    return False


class ReplicaRouter:
    """Least-estimated-completion-time routing over the replica lanes of
    a 2D (shard × replica) backend, with fault draining.

    Each lane tracks a host-clock ``busy_until`` horizon: picking a lane
    for a batch of estimated cost *est_s* extends its horizon, so
    concurrent super-batches spread across groups instead of convoying
    on one (the in-call analogue of least-outstanding-requests LB).  A
    lane marked :meth:`fault`-ed is DRAINED: it stops receiving traffic,
    ``/healthz`` lists it degraded, and :meth:`pick` routes only over
    survivors — zero failed requests as long as one lane lives.
    :meth:`drain` marks a lane degraded WITHOUT counting a fault (the
    operator/autotuner canary action).

    Between those extremes, each lane also keeps an observed service-time
    EWMA (fed by the engine's collect via :meth:`note_done`/
    :meth:`observe`): a SLOW-but-alive lane (a stalled host, a noisy
    neighbor) books its batches at ``est_s × slowness`` — its relative
    EWMA against the fastest live lane — so it sheds load GRADUALLY as it
    degrades and wins it back as it recovers, instead of flapping between
    the binary live/drained states.  Counters export per-lane
    dispatch/fault totals
    (``raft_tpu_serve_replica_{dispatch,faults}_total{engine,replica}``),
    the per-lane cost EWMA
    (``raft_tpu_serve_replica_cost_seconds{engine,replica}``) and a
    live-lane gauge (``raft_tpu_serve_replicas_live{engine}``)."""

    def __init__(self, n_lanes: int, engine_label: str = "?"):
        expects(n_lanes >= 1, "ReplicaRouter needs at least one lane")
        self.n_lanes = int(n_lanes)
        self._engine = str(engine_label)
        self._busy_until = [0.0] * self.n_lanes
        self._degraded = [False] * self.n_lanes
        #: per-lane observed service-time EWMA (None until first observed)
        self._cost_ewma: List[Optional[float]] = [None] * self.n_lanes
        self._cost_g = telemetry.gauge(
            "raft_tpu_serve_replica_cost_seconds",
            "per-lane observed super-batch service-time EWMA",
            labelnames=("engine", "replica"))
        self._dispatches = telemetry.counter(
            "raft_tpu_serve_replica_dispatch_total",
            "super-batches routed to each replica lane",
            labelnames=("engine", "replica"))
        self._faults = telemetry.counter(
            "raft_tpu_serve_replica_faults_total",
            "replica-lane dispatch failures observed by the router",
            labelnames=("engine", "replica"))
        self._live = telemetry.gauge(
            "raft_tpu_serve_replicas_live",
            "replica lanes currently routable", labelnames=("engine",))
        self._live.set(self.n_lanes, (self._engine,))

    def alive_lanes(self) -> List[int]:
        return [i for i in range(self.n_lanes) if not self._degraded[i]]

    def pick(self, now: float, est_s: float,
             exclude: Sequence[int] = ()) -> Optional[int]:
        """The lane with the least estimated completion time for one more
        batch (None when every lane is drained/excluded).  Picking books
        the batch onto the lane's horizon."""
        best_lane, best_done = None, 0.0
        for i in self.alive_lanes():
            if i in exclude:
                continue
            done = max(self._busy_until[i], now) + est_s * self.slowness(i)
            if best_lane is None or done < best_done:
                best_lane, best_done = i, done
        if best_lane is not None:
            self._busy_until[best_lane] = best_done
            self._dispatches.inc(1, (self._engine, str(best_lane)))
        return best_lane

    def slowness(self, lane: int) -> float:
        """The lane's observed cost relative to the FASTEST live lane
        (≥ 1.0; 1.0 while unobserved) — the gradual-shedding weight
        :meth:`pick` books batches at."""
        mine = self._cost_ewma[lane]
        if mine is None:
            return 1.0
        floor = min((self._cost_ewma[i] for i in self.alive_lanes()
                     if self._cost_ewma[i] is not None),
                    default=None)
        if floor is None or floor <= 0.0:
            return 1.0
        return max(1.0, mine / floor)

    def observe(self, lane: int, wall_s: float) -> None:
        """One collected batch's observed service time on *lane* → the
        lane's cost EWMA (the gradual-shedding signal)."""
        if wall_s <= 0.0:
            return
        prev = self._cost_ewma[lane]
        self._cost_ewma[lane] = (
            wall_s if prev is None
            else EWMA_KEEP * prev + (1 - EWMA_KEEP) * wall_s)
        self._cost_g.set(self._cost_ewma[lane],
                         (self._engine, str(lane)))

    def note_done(self, lane: int, now: float,
                  wall_s: Optional[float] = None) -> None:
        """A lane's batch collected: clamp its horizon to the present so
        stale over-estimates do not starve it; *wall_s* (when the caller
        measured it) feeds the lane's cost EWMA."""
        if self._busy_until[lane] > now:
            self._busy_until[lane] = now
        if wall_s is not None:
            self.observe(lane, wall_s)

    def drain(self, lane: int) -> None:
        """Administratively drain *lane* (no fault counted): the
        autotuner's shadow-canary lane, an operator's maintenance drain.
        :meth:`restore` un-drains."""
        if not self._degraded[lane]:
            self._degraded[lane] = True
            self._live.set(len(self.alive_lanes()), (self._engine,))

    def fault(self, lane: int) -> None:
        """Drain *lane*: no further traffic routes to it; visible as
        degraded in the router's health view."""
        self._faults.inc(1, (self._engine, str(lane)))
        if not self._degraded[lane]:
            self._degraded[lane] = True
            self._live.set(len(self.alive_lanes()), (self._engine,))

    def restore(self, lane: int) -> None:
        """Un-drain *lane* (an operator action after replacing the
        replica; the engine never restores on its own)."""
        if self._degraded[lane]:
            self._degraded[lane] = False
            self._live.set(len(self.alive_lanes()), (self._engine,))

    def degraded_lanes(self) -> List[int]:
        return [i for i in range(self.n_lanes) if self._degraded[i]]

    def health(self) -> Dict[str, object]:
        """The ``/healthz`` replicas sub-object."""
        return {"total": self.n_lanes,
                "live": len(self.alive_lanes()),
                "degraded": self.degraded_lanes()}
