"""Dispatch supervision: watchdog, bounded retry, failure classification
(docs/serving.md §failure model).

A super-batch dispatch is async — its failure (or its hang) surfaces when
the engine COLLECTS the host copy of the results.  The supervisor owns
that collection:

* **Watchdog** — with ``watchdog_s`` set, the host fetch runs on a helper
  thread and the caller waits at most the wall-clock budget; a hung
  dispatch raises :class:`WatchdogTimeout` instead of blocking the engine
  forever (the abandoned daemon thread finishes — or never does —
  harmlessly; the next dispatch uses fresh buffers, so the engine stays
  serviceable).  ``watchdog_s=None`` (the default) fetches inline with
  zero per-batch thread cost.
* **Bounded retry with backoff + jitter** — RETRYABLE failures
  (transient ``RuntimeError`` from the runtime — XLA's runtime errors
  subclass it — injected :class:`~raft_tpu.testing.faults.InjectedFault`
  faults, and watchdog timeouts) are retried up to ``max_retries`` times:
  exponential backoff from ``backoff_s`` capped at ``backoff_cap_s``,
  multiplied by seeded jitter so a fleet of retrying engines does not
  re-dispatch in lockstep.  The re-dispatch goes back through the SAME
  warmed executable (the caller's ``redo`` closure), so the retry path is
  zero-compile — counter-asserted by the fault battery and the bench.
* **Fail-fast classification** — NON-retryable failures (``LogicError``
  — the shape/dtype-bug family — ``TypeError``/``ValueError``, anything
  that is not a ``RuntimeError``) are raised immediately: retrying a
  deterministic bug burns its whole backoff schedule to fail identically,
  and can mask the bug as flakiness.

The fault plane's ``dispatch`` site is consulted INSIDE the fetch (once
per collection attempt), so injected raises/stalls flow through exactly
the path real runtime failures take.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, Tuple

import numpy as np

from raft_tpu.core.error import LogicError, RaftError
from raft_tpu.testing import faults as _faults


class DispatchError(RaftError):
    """Base of the supervisor's own failure types."""


class WatchdogTimeout(DispatchError):
    """The wall-clock watchdog fired before the dispatch produced its
    results.  Classified RETRYABLE: a hang is indistinguishable from an
    arbitrarily slow transient, and the retry dispatches fresh buffers."""


def retryable(exc: BaseException) -> bool:
    """The documented classification: watchdog timeouts and transient
    ``RuntimeError``s retry; logic/shape/dtype bugs never do."""
    if isinstance(exc, WatchdogTimeout):
        return True
    if isinstance(exc, LogicError):  # InjectedLogicFault included
        return False
    return isinstance(exc, RuntimeError)


class DispatchSupervisor:
    """Supervised collection of in-flight dispatch results for one engine.

    ``on_event(kind)`` (kind ∈ {"retry", "watchdog_timeout"}) lets the
    owning engine mirror supervisor events into its ``stats`` without the
    supervisor knowing about engines."""

    def __init__(self, watchdog_s: Optional[float] = None,
                 max_retries: int = 2, backoff_s: float = 0.05,
                 backoff_cap_s: float = 1.0, jitter: float = 0.25,
                 seed: int = 0,
                 on_event: Optional[Callable[[str], None]] = None):
        if watchdog_s is not None and watchdog_s <= 0:
            raise LogicError("watchdog_s must be positive (or None)")
        self.watchdog_s = watchdog_s
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.jitter = float(jitter)
        self._rng = np.random.default_rng(seed)
        self._on_event = on_event or (lambda kind: None)

    # -- one attempt --------------------------------------------------------
    @staticmethod
    def _pull(out) -> Tuple[np.ndarray, np.ndarray]:
        # the injected-fault site: raises/stalls surface here, exactly
        # where a real async dispatch's failure does
        _faults.check("dispatch")
        # exempt(hot-path-host-transfer): supervised result-delivery fetch
        return np.asarray(out[0]), np.asarray(out[1])

    def fetch(self, out, label: str = "") -> Tuple[np.ndarray, np.ndarray]:
        """Collect one dispatch's results, under the watchdog if armed."""
        if self.watchdog_s is None:
            return self._pull(out)
        box: dict = {}

        def run():
            try:
                box["value"] = self._pull(out)
            except BaseException as e:  # relayed to the caller below
                box["error"] = e

        t = threading.Thread(target=run, daemon=True,
                             name=f"raft-tpu-serve-fetch-{label}")
        t.start()
        t.join(self.watchdog_s)
        if t.is_alive():
            self._on_event("watchdog_timeout")
            raise WatchdogTimeout(
                f"dispatch {label or '<super-batch>'} produced no results "
                f"within the {self.watchdog_s}s watchdog budget")
        if "error" in box:
            raise box["error"]
        return box["value"]

    def _backoff(self, attempt: int) -> float:
        base = min(self.backoff_s * (2.0 ** attempt), self.backoff_cap_s)
        return base * (1.0 + self.jitter * float(self._rng.random()))

    def collect(self, out, redo: Optional[Callable[[], object]] = None,
                label: str = "") -> Tuple[np.ndarray, np.ndarray]:
        """Collect with bounded retry: on a retryable failure, back off,
        re-dispatch via ``redo()`` (the caller's closure over the SAME
        warmed executable and block — zero-compile) and fetch again.
        Non-retryable failures and exhausted retries raise to the caller,
        which isolates them per request."""
        attempt = 0
        while True:
            try:
                return self.fetch(out, label)
            except Exception as e:
                if redo is None or attempt >= self.max_retries \
                        or not retryable(e):
                    raise
                time.sleep(self._backoff(attempt))
                attempt += 1
                self._on_event("retry")
                out = redo()
