"""raft_tpu.solver — combinatorial solvers.

Counterpart of reference ``raft/solver/`` (SURVEY.md §2.12):
``LinearAssignmentProblem`` (solver/linear_assignment.cuh:53).
"""

from raft_tpu.solver.linear_assignment import (
    LinearAssignmentProblem,
    solve_lap,
)

__all__ = ["LinearAssignmentProblem", "solve_lap"]
