"""Batched linear assignment (LAP) solver.

Counterpart of reference ``solver/linear_assignment.cuh:53``
(``LinearAssignmentProblem`` — the Date-Nagi GPU alternating-tree Hungarian
algorithm, kernels in ``solver/detail/lap_kernels.cuh``), which solves a
batch of n×n min-cost assignment problems and exposes row/col assignments,
row/col duals, and primal/dual objective values.

TPU-first redesign: the Hungarian alternating-tree search is a
frontier-expansion algorithm with data-dependent serial augmenting paths —
a poor fit for SPMD/XLA.  Instead this uses **Bertsekas' auction algorithm
with ε-scaling**: every phase is dense row-parallel work (per-row top-2
reduction over the cost matrix → bids → per-column argmax over bidders),
which vectorizes perfectly over the VPU/MXU and batches with ``vmap``.
ε-scaling from a coarse ε down to ``final_eps`` keeps the number of
bidding rounds near O(n) per phase; with integer-valued costs and
``final_eps < 1/n`` the result is provably optimal, and for float costs it
is ε-optimal (|primal − dual| ≤ n·ε), exactly the guarantee the reference's
``epsilon_`` tolerance encodes.

All control flow is ``lax.while_loop`` on device — one compiled
computation per (n, batch) shape, no host round-trips per round.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects


class LAPResult(NamedTuple):
    """Solution of a batch of assignment problems.

    ``converged``/``residual`` make the solver's two silent degradation
    modes OBSERVABLE (ADVICE r5): ``converged[b]`` is False when the final
    auction phase hit its round cap and the completion fallback had to
    assign leftover rows (the returned permutation is valid but the
    ``n·ε_eff`` optimality bound no longer certifies it), and
    ``residual[b] = primal − dual`` is the duality gap — the computable
    certificate, ≤ ``n·ε_eff`` whenever the bound holds (up to fp
    rounding)."""

    row_assignment: jnp.ndarray   # (batch, n) int32: col assigned to each row
    col_assignment: jnp.ndarray   # (batch, n) int32: row assigned to each col
    objective: jnp.ndarray        # (batch,) primal objective Σ cost[i, σ(i)]
    row_duals: jnp.ndarray        # (batch, n) dual u_i
    col_duals: jnp.ndarray        # (batch, n) dual v_j (auction prices)
    converged: jnp.ndarray        # (batch,) bool: final phase completed
    residual: jnp.ndarray         # (batch,) duality gap |primal − dual|


def _auction_phase(benefit, prices, eps, max_rounds):
    """One ε-phase of the forward auction on a single (n, n) benefit matrix.

    Jacobi parallel bidding: all unassigned persons bid simultaneously;
    each object goes to its highest bidder, evicting the previous owner.
    """
    n = benefit.shape[0]
    inf = jnp.asarray(jnp.finfo(benefit.dtype).max, benefit.dtype)

    def cond(state):
        row_to_col, _, _, rounds = state
        return jnp.any(row_to_col < 0) & (rounds < max_rounds)

    def body(state):
        row_to_col, col_to_row, prices, rounds = state
        unassigned = row_to_col < 0                       # (n,)
        value = benefit - prices[None, :]                  # (n, n)
        # per-row best and second-best values (n=1 has no second-best:
        # the bid is price + eps, and top_k(…, 2) would be ill-formed)
        if n >= 2:
            top2, top2_idx = jax.lax.top_k(value, 2)
            best_j = top2_idx[:, 0]
            gap = top2[:, 0] - top2[:, 1]
        else:
            best_j = jnp.zeros((n,), jnp.int32)
            gap = jnp.zeros((n,), benefit.dtype)
        bid_amount = prices[best_j] + gap + eps
        # Each column takes the highest bid among unassigned bidders.
        bid = jnp.where(unassigned[:, None] &
                        (jnp.arange(n)[None, :] == best_j[:, None]),
                        bid_amount[:, None], -inf)         # (n_rows, n_cols)
        best_bid = jnp.max(bid, axis=0)                    # (n_cols,)
        winner = jnp.argmax(bid, axis=0).astype(jnp.int32)
        got_bid = best_bid > -inf
        # Evict previous owners of re-auctioned columns, then award to the
        # winners.  Winners are unassigned rows and owners are assigned
        # rows, so the two scatters touch disjoint rows.
        prev_owner = jnp.where(got_bid & (col_to_row >= 0), col_to_row, n)
        row_to_col = row_to_col.at[prev_owner].set(-1, mode="drop")
        col_to_row = jnp.where(got_bid, winner, col_to_row)
        row_to_col = row_to_col.at[
            jnp.where(got_bid, winner, n)].set(
                jnp.arange(n, dtype=jnp.int32), mode="drop")
        prices = jnp.where(got_bid, best_bid, prices)
        return row_to_col, col_to_row, prices, rounds + 1

    init = (jnp.full((n,), -1, jnp.int32), jnp.full((n,), -1, jnp.int32),
            prices, jnp.zeros((), jnp.int32))
    row_to_col, col_to_row, prices, _ = jax.lax.while_loop(cond, body, init)
    return row_to_col, col_to_row, prices


def _solve_single(cost, final_eps: float, scaling_factor: float,
                  max_rounds_per_phase: int):
    """ε-scaled auction for one (n, n) cost matrix → LAP fields."""
    n = cost.shape[0]
    benefit = -cost                     # min-cost ↔ max-benefit
    spread = jnp.maximum(jnp.max(cost) - jnp.min(cost),
                         jnp.asarray(1.0, cost.dtype))
    # Effective ε is floored at a multiple of the price scale's ULP: with
    # exact cost ties the bid increment is exactly ε, and an ε below
    # ULP(price) leaves `price + ε == price` in f32 — the evicted duplicate
    # re-bids identically forever and the phase stalls at its round cap
    # (observed: duplicate-row costs at ε=1e-7, price scale ~10).  The
    # optimality guarantee degrades gracefully to |primal − dual| ≤ n·ε_eff.
    eps_eff = jnp.maximum(jnp.asarray(final_eps, cost.dtype),
                          spread * 8 * jnp.finfo(cost.dtype).eps)

    # phase schedule: eps_0 = spread/2, shrink by scaling_factor until
    # <= eps_eff.  The count must be static for while_loop-free scan.
    def phase(carry, _):
        prices, eps, done = carry
        _, _, new_prices = _auction_phase(benefit, prices, eps,
                                          max_rounds_per_phase)
        prices = jnp.where(done, prices, new_prices)
        next_eps = jnp.maximum(eps / scaling_factor, eps_eff)
        new_done = done | (eps <= eps_eff)
        return (prices, next_eps, new_done), None

    # number of phases needed: log_{sf}(spread/(2·eps_eff)) + 1.  The ULP
    # floor bounds eps0/eps_eff at 1/(16·eps_machine) — ~5e5 for f32 but
    # ~3e14 for f64 — so the static bound is derived from the cost dtype,
    # not a fixed constant.
    import math
    max_ratio = 1.0 / (16 * float(jnp.finfo(cost.dtype).eps))
    n_phases = 1 + max(1, int(math.ceil(math.log(max_ratio)
                                        / math.log(scaling_factor))))
    eps0 = spread / 2
    (prices, _, _), _ = jax.lax.scan(
        phase, (jnp.zeros((n,), cost.dtype), eps0,
                jnp.asarray(False)), None, length=n_phases)
    # Final phase at eps_eff with the settled prices — its assignment is
    # ε-optimal (|primal − dual| ≤ n·ε_eff).
    r2c, c2r, prices = _auction_phase(benefit, prices, eps_eff,
                                      max_rounds_per_phase)
    # Completion guarantee: the reference always returns a permutation.  If
    # the final phase hit its round cap with rows still unassigned (only
    # reachable on adversarial tie structures), assign each leftover row to
    # its best FREE column in row order — among sub-ε ties this loses
    # nothing, and it restores the permutation invariant every caller
    # relies on.  ``converged`` records whether the fallback fired at all
    # (False → the n·ε_eff bound is no longer certified; the returned
    # ``residual`` duality gap is then the only certificate).
    converged = jnp.all(r2c >= 0)
    inf = jnp.asarray(jnp.finfo(benefit.dtype).max, benefit.dtype)

    def complete(i, carry):
        r2c_, c2r_, free = carry
        need = r2c_[i] < 0
        v = jnp.where(free, benefit[i] - prices, -inf)
        j = jnp.argmax(v).astype(jnp.int32)
        r2c_ = jnp.where(need, r2c_.at[i].set(j), r2c_)
        c2r_ = jnp.where(need, c2r_.at[j].set(i), c2r_)
        free = jnp.where(need, free.at[j].set(False), free)
        return r2c_, c2r_, free

    r2c, c2r, _ = jax.lax.fori_loop(0, n, complete, (r2c, c2r, c2r < 0))
    safe = jnp.clip(r2c, 0, n - 1)
    objective = jnp.sum(jnp.take_along_axis(cost, safe[:, None], axis=1)[:, 0])
    # duals: v = prices, u_i = max_j (benefit_ij − v_j) (complementary
    # slackness in the max-benefit form; reference exposes row/col duals
    # via getRowDualVector/getColDualVector).
    u = jnp.max(benefit - prices[None, :], axis=1)
    # duality gap in min-cost form: primal − dual ∈ [0, n·ε_eff] when the
    # bound holds (tiny negative values are fp rounding of the two sums)
    residual = objective - (jnp.sum(-u) + jnp.sum(-prices))
    # negate duals back to min-cost form
    return r2c, c2r, objective, -u, -prices, converged, residual


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def _solve_batched(costs, final_eps, scaling_factor, max_rounds_per_phase):
    return jax.vmap(lambda c: _solve_single(
        c, final_eps, scaling_factor, max_rounds_per_phase))(costs)


def solve_lap(costs, epsilon: float = 1e-6, scaling_factor: float = 8.0,
              max_rounds_per_phase: int = 0) -> LAPResult:
    """Solve a batch of n×n min-cost assignment problems.

    *costs* is (batch, n, n) or (n, n).  *epsilon* is the optimality
    tolerance (reference ctor's ``epsilon``): the returned assignment's
    objective is within ``n·ε_eff`` of optimal, where
    ``ε_eff = max(epsilon, spread · 8 · eps_machine(dtype))`` — the floor
    keeps bid increments above the ULP of the price scale (below it the
    auction stalls on exact cost ties; f32 at spread 1e6 floors ε at ~1).
    For integer costs pass ``epsilon < 1/n`` to get the exact optimum,
    provided the floor itself stays below 1/n (true whenever
    ``spread · n ≲ 1e6`` in f32; use f64 costs beyond that).

    Observability (ADVICE r5): the result carries ``converged`` (False →
    the final phase round-capped and the completion fallback fired; the
    optimality bound is then uncertified) and ``residual`` (the duality
    gap, the computable certificate).  When the ULP floor EXCEEDS the
    requested *epsilon* for concrete (non-traced) inputs, integer costs
    are silently UPCAST to f64 under ``jax_enable_x64`` (restoring the
    documented integer-exactness guarantee instead of voiding it in the
    fine print); otherwise a warning is logged with the effective ε.
    """
    import jax as _jax

    from raft_tpu.core.aot import is_tracer
    from raft_tpu.core.logger import log_warn

    costs = jnp.asarray(costs)
    squeeze = costs.ndim == 2
    if squeeze:
        costs = costs[None]
    expects(costs.ndim == 3 and costs.shape[1] == costs.shape[2],
            "solve_lap: costs must be (batch, n, n) square")
    n = costs.shape[1]
    if max_rounds_per_phase <= 0:
        max_rounds_per_phase = 16 * n + 256
    compute_dtype = jnp.promote_types(costs.dtype, jnp.float32)
    if not is_tracer(costs) and costs.size:
        spread = max(float(jnp.max(costs) - jnp.min(costs)), 1.0)
        floor = spread * 8 * float(jnp.finfo(compute_dtype).eps)
        if floor > float(epsilon):
            integer = jnp.issubdtype(costs.dtype, jnp.integer)
            if integer and bool(_jax.config.jax_enable_x64) \
                    and compute_dtype != jnp.float64:
                # integer-cost callers asked for exactness (ε < 1/n): keep
                # the guarantee by computing in f64, whose ULP floor at
                # this spread sits ~2^29 lower (x64 checked above)
                compute_dtype = jnp.float64
            else:
                log_warn(
                    "solve_lap: requested epsilon=%g is below the f%d ULP "
                    "floor %g at cost spread %g — the optimality bound "
                    "degrades to n*%g%s", float(epsilon),
                    jnp.finfo(compute_dtype).bits, floor, spread, floor,
                    " (enable jax_enable_x64 or pass f64 costs to keep "
                    "integer exactness)" if integer else "")
    r2c, c2r, obj, u, v, conv, resid = _solve_batched(
        costs.astype(compute_dtype),
        float(epsilon), float(scaling_factor), int(max_rounds_per_phase))
    res = LAPResult(r2c, c2r, obj, u, v, conv, resid)
    if squeeze:
        res = LAPResult(*(a[0] for a in res))
    return res


class LinearAssignmentProblem:
    """Reference-parity class surface (solver/linear_assignment.cuh:53).

    ``solve(cost_matrices)`` → stores assignments/duals/objectives, exposed
    through the same getters the reference has.
    """

    def __init__(self, size: int, batchsize: int = 1, epsilon: float = 1e-6):
        self.size = int(size)
        self.batchsize = int(batchsize)
        self.epsilon = float(epsilon)
        self._result: LAPResult | None = None

    def solve(self, cost_matrices) -> LAPResult:
        costs = jnp.asarray(cost_matrices)
        if costs.ndim == 2:
            costs = costs[None]
        expects(costs.shape == (self.batchsize, self.size, self.size),
                f"expected ({self.batchsize}, {self.size}, {self.size}) costs")
        self._result = solve_lap(costs, self.epsilon)
        return self._result

    def _res(self) -> LAPResult:
        expects(self._result is not None, "call solve() first")
        return self._result

    # Reference getters (linear_assignment.cuh:118-170)
    def get_row_assignments(self):
        return self._res().row_assignment

    def get_col_assignments(self):
        return self._res().col_assignment

    def get_primal_objective_value(self, batch: int = 0):
        return self._res().objective[batch]

    def get_dual_objective_value(self, batch: int = 0):
        r = self._res()
        return jnp.sum(r.row_duals[batch]) + jnp.sum(r.col_duals[batch])

    def get_row_dual_vector(self, batch: int = 0):
        return self._res().row_duals[batch]

    def get_col_dual_vector(self, batch: int = 0):
        return self._res().col_duals[batch]
