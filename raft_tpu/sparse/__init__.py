"""Sparse primitives (reference raft/sparse/ — SURVEY.md §2.10).

COO/CSR fixed-capacity containers, conversions, structural ops, sparse
linear algebra, sparse pairwise distances, sparse neighbors, and the MST /
Lanczos solvers.

TPU-first design: XLA wants static shapes, so every container is a
fixed-capacity buffer + padding convention (reference pre-allocates outputs
for the same reason — SURVEY.md §7 "dynamic shapes").  Padded COO entries
carry ``row == n_rows, col == 0, val == 0``: segment reductions with
``num_segments == n_rows`` drop them, gathers stay in-bounds, and sums are
unaffected.  CSR keeps ``indptr[-1] == nnz`` with tail padding beyond nnz.
"""

from raft_tpu.sparse.types import COO, CSR  # noqa: F401
from raft_tpu.sparse import convert, linalg, op  # noqa: F401
from raft_tpu.sparse import distance, neighbors  # noqa: F401
from raft_tpu.sparse.convert import (  # noqa: F401
    adj_to_csr,
    coo_to_csr,
    coo_to_dense,
    csr_to_coo,
    csr_to_dense,
    dense_to_coo,
    dense_to_csr,
    from_triplets,
)
from raft_tpu.sparse.op import (  # noqa: F401
    coo_max_duplicates,
    coo_remove_scalar,
    coo_remove_zeros,
    coo_sort,
    coo_sum_duplicates,
    csr_row_slice,
    csr_row_op,
)
from raft_tpu.sparse.linalg import (  # noqa: F401
    coo_degree,
    csr_add,
    csr_degree,
    csr_transpose,
    fit_embedding,
    laplacian,
    row_normalize,
    EllHybrid,
    csr_to_ell,
    ell_spmv,
    spmm,
    spmv,
    symmetrize,
    weak_cc,
)
from raft_tpu.sparse.solver import (  # noqa: F401
    MSTResult,
    boruvka_mst,
    lanczos_largest,
    lanczos_smallest,
)
