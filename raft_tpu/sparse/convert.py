"""Format conversions: coo↔csr↔dense, adjacency→csr.

Counterpart of reference ``sparse/convert/`` (``coo.cuh``, ``csr.cuh``,
``dense.cuh``, ``detail/adj_to_csr.cuh``).  All conversions are jittable
with static capacities; the dense→sparse direction takes an explicit
``capacity`` (the reference preallocates the output and counts first —
here count-first is a host-side convenience, see :func:`dense_to_csr`).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.sparse.types import COO, CSR


def from_triplets(rows, cols, vals, shape) -> CSR:
    """Build a CSR from raw host (row, col, value) triplets: sort by
    (row, col), sum duplicates, drop explicit zeros, then convert.

    The canonicalization runs in the native C++ runtime when built
    (native/raft_runtime.cpp ``rt_coo_canonicalize``) — the host ingest
    path of the reference's ``sparse/op`` sort+dedupe — with a numpy
    fallback otherwise.
    """
    import numpy as np

    rows = np.asarray(rows)
    cols = np.asarray(cols)
    vals = np.asarray(vals)
    expects(rows.shape == cols.shape == vals.shape,
            "from_triplets: rows/cols/vals must be the same length")
    try:
        from raft_tpu import native

        if native.is_available():
            r, c, v = native.coo_canonicalize_host(rows, cols, vals)
            v = v.astype(vals.dtype if np.issubdtype(vals.dtype, np.floating)
                         # x64: int vals widen exactly; host-side numpy
                         else np.float64)
        else:
            raise RuntimeError
    except (ImportError, RuntimeError):
        order = np.lexsort((cols, rows))
        r, c, v0 = rows[order], cols[order], vals[order]
        key = r.astype(np.int64) * shape[1] + c
        uniq, inv = np.unique(key, return_inverse=True)
        v = np.zeros(len(uniq), vals.dtype)
        np.add.at(v, inv, v0)
        r = (uniq // shape[1]).astype(np.int32)
        c = (uniq % shape[1]).astype(np.int32)
        keep = v != 0
        r, c, v = r[keep], c[keep], v[keep]
    coo = COO(jnp.asarray(r, jnp.int32), jnp.asarray(c, jnp.int32),
              jnp.asarray(v), tuple(shape))
    return coo_to_csr(coo)


def coo_to_csr(coo: COO) -> CSR:
    """COO (row-sorted) → CSR.  Reference sparse/convert/csr.cuh
    ``sorted_coo_to_csr``: the input must be sorted by row (use
    :func:`raft_tpu.sparse.op.coo_sort` first)."""
    n_rows = coo.shape[0]
    live = coo.mask()
    # Padded rows are n_rows → fall outside [0, n_rows) bincount range.
    counts = jnp.bincount(
        jnp.where(live, coo.rows, n_rows), length=n_rows + 1
    )[:n_rows]
    indptr = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                              jnp.cumsum(counts).astype(jnp.int32)])
    indices = jnp.where(live, coo.cols, 0)
    data = jnp.where(live, coo.vals, jnp.zeros((), coo.vals.dtype))
    return CSR(indptr, indices, data, coo.shape)


def csr_to_coo(csr: CSR) -> COO:
    """CSR → COO.  Reference sparse/convert/coo.cuh ``csr_to_coo``."""
    rows = csr.row_ids()
    live = csr.mask()
    return COO(jnp.where(live, rows, csr.shape[0]),
               jnp.where(live, csr.indices, 0),
               jnp.where(live, csr.data, jnp.zeros((), csr.data.dtype)),
               csr.shape, nnz=csr.nnz)


def coo_to_dense(coo: COO) -> jnp.ndarray:
    """COO → dense.  Padding (row == n_rows) is dropped by the scatter."""
    out = jnp.zeros(coo.shape, coo.vals.dtype)
    return out.at[coo.rows, coo.cols].add(coo.vals, mode="drop")


def csr_to_dense(csr: CSR) -> jnp.ndarray:
    """CSR → dense (reference sparse/convert/dense.cuh ``csr_to_dense``)."""
    return coo_to_dense(csr_to_coo(csr))


def dense_to_coo(x, capacity: Optional[int] = None) -> COO:
    """Dense → COO.  ``capacity`` defaults to m*n (fully dense worst case);
    pass the known nnz bound to keep buffers small.  Entries are produced in
    row-major (row-sorted) order; zeros are compacted out."""
    x = jnp.asarray(x)
    m, n = x.shape
    cap = min(int(capacity), m * n) if capacity is not None else m * n
    flat = x.ravel()
    nonzero = flat != 0
    # Entries past the caller's capacity are truncated (matches the
    # reference's preallocated-output contract); nnz reports what survived.
    nnz = jnp.minimum(jnp.sum(nonzero, dtype=jnp.int32), cap)
    # Stable compaction: order live entries first, keeping row-major order.
    order = jnp.argsort(~nonzero, stable=True)[:cap]
    live = jnp.arange(cap) < nnz
    rows = jnp.where(live, (order // n).astype(jnp.int32), m)
    cols = jnp.where(live, (order % n).astype(jnp.int32), 0)
    vals = jnp.where(live, flat[order], jnp.zeros((), x.dtype))
    return COO(rows, cols, vals, (m, n), nnz=nnz)


def dense_to_csr(x, capacity: Optional[int] = None) -> CSR:
    """Dense → CSR (reference sparse/convert/csr.cuh ``dense_to_csr``)."""
    return coo_to_csr(dense_to_coo(x, capacity))


def adj_to_csr(adj, capacity: Optional[int] = None) -> CSR:
    """Boolean adjacency matrix → CSR with unit weights.

    Reference sparse/convert/detail/adj_to_csr.cuh (``adj_to_csr``).
    """
    adj = jnp.asarray(adj)
    expects(adj.dtype == jnp.bool_ or jnp.issubdtype(adj.dtype, jnp.integer),
            "adj_to_csr expects a boolean/integer adjacency matrix")
    return dense_to_csr(adj.astype(jnp.float32), capacity)
