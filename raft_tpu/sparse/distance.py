"""Sparse pairwise distances over CSR inputs.

Counterpart of reference ``sparse/distance/distance.cuh:37-68`` (18
supported metrics) with its engines — hash-table / dense-smem COO SpMV
strategies (``detail/coo_spmv.cuh``), L2/cosine-from-IP
(``detail/l2_distance.cuh``), generic LP loop (``detail/lp_distance.cuh``)
and binary metrics (``detail/bin_distance.cuh``).

TPU-first redesign: the strategy zoo collapses into one **block-densify**
engine.  CSR tiles are scattered into dense (block × dim) VMEM-resident
tiles and handed to the dense :mod:`raft_tpu.distance` engines, so inner-
product metrics ride the MXU and LP-loop metrics ride the fused VPU path.
On TPU, densified tiles + static shapes beat gather-heavy sparse inner
loops for the dimensionalities this library targets — the reference's own
"dense smem" COO SpMV strategy is the same idea constrained to shared
memory.  Batch sizes bound the densified footprint exactly like the
reference's ``batch_size_index/query`` knobs (SURVEY.md §5).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.distance import DistanceType
from raft_tpu.distance import pairwise as _dense
from raft_tpu.sparse.op import csr_row_slice
from raft_tpu.sparse.convert import csr_to_dense
from raft_tpu.sparse.types import CSR

# reference sparse/distance/distance.cuh:37-56
SUPPORTED_SPARSE_DISTANCES = (
    DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded,
    DistanceType.CosineExpanded,
    DistanceType.InnerProduct,
    DistanceType.L1,
    DistanceType.Canberra,
    DistanceType.Linf,
    DistanceType.LpUnexpanded,
    DistanceType.JaccardExpanded,
    DistanceType.HellingerExpanded,
    DistanceType.DiceExpanded,
    DistanceType.L2Unexpanded,
    DistanceType.L2SqrtUnexpanded,
    DistanceType.CorrelationExpanded,
    DistanceType.RusselRaoExpanded,
    DistanceType.HammingUnexpanded,
    DistanceType.JensenShannon,
    DistanceType.KLDivergence,
)


def pairwise_distance(x: CSR, y: CSR, metric: DistanceType = DistanceType.L2Expanded,
                      p: float = 2.0, batch_size_x: int = 4096,
                      batch_size_y: Optional[int] = None) -> jnp.ndarray:
    """All-pairs distances between rows of two CSR matrices.

    Mirrors reference ``sparse::distance::pairwiseDistance``
    (sparse/distance/distance.cuh:68); returns a dense (m, n) matrix like
    the reference.
    """
    expects(metric in SUPPORTED_SPARSE_DISTANCES,
            f"metric {metric} not supported for sparse inputs")
    expects(x.shape[1] == y.shape[1], "pairwise_distance: dim mismatch")
    m, n = x.shape[0], y.shape[0]
    bx = min(batch_size_x, m)
    by = min(batch_size_y or max(batch_size_x, 4096), n)

    out_rows = []
    for i0 in range(0, m, bx):
        i1 = min(i0 + bx, m)
        xd = csr_to_dense(csr_row_slice(x, i0, i1))
        row = []
        # Densify each y block inside the loop so at most one (bx, dim) and
        # one (by, dim) dense tile are live at a time — the batch knobs must
        # bound the densified footprint (reference batch_size_index/query).
        for j0 in range(0, n, by):
            j1 = min(j0 + by, n)
            yd = csr_to_dense(csr_row_slice(y, j0, j1))
            row.append(_dense.pairwise_distance(xd, yd, metric, p=p))
        out_rows.append(row[0] if len(row) == 1 else jnp.concatenate(row, axis=1))
    return out_rows[0] if len(out_rows) == 1 else jnp.concatenate(out_rows, axis=0)
