"""Sparse pairwise distances over CSR inputs.

Counterpart of reference ``sparse/distance/distance.cuh:37-68`` (18
supported metrics) with its engines — hash-table / dense-smem COO SpMV
strategies (``detail/coo_spmv.cuh``), L2/cosine-from-IP
(``detail/l2_distance.cuh``), generic LP loop (``detail/lp_distance.cuh``)
and binary metrics (``detail/bin_distance.cuh``).

TPU-first redesign: the strategy zoo collapses into two engines.

* **block-densify** (moderate dim): CSR tiles are scattered into dense
  (block × dim) VMEM-resident tiles and handed to the dense
  :mod:`raft_tpu.distance` engines, so inner-product metrics ride the MXU
  and LP-loop metrics ride the fused VPU path.  The reference's own
  "dense smem" COO SpMV strategy is the same idea constrained to shared
  memory.
* **feature-compressed** (high dim — the hash-table COO-SpMV role,
  ``detail/coo_spmv.cuh`` + ``coo_spmv_strategies/``): each x-block is
  densified onto its OWN sorted feature set ``u`` (≤ block-nnz columns —
  independent of ``dim``), y-entries are matched into that compressed axis
  by binary search, and the per-pair work runs on the compressed axis
  (matmul for IP-family, tiled elementwise for the LP family).  Features a
  y-row holds OUTSIDE ``u`` meet only zeros of x, so their contribution is
  a per-row sum/max correction computed straight from the y entries.
  Memory is O(block·block_nnz), never O(block·dim) — this is the engine
  for 10⁴⁺-dimensional TF-IDF-style inputs where densification is
  impossible (the inputs the reference's hash-table strategies exist for).

Batch sizes bound the footprint exactly like the reference's
``batch_size_index/query`` knobs (SURVEY.md §5).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from raft_tpu.linalg.reduce import segment_sum
import numpy as np

from raft_tpu.core.error import expects
from raft_tpu.core.handle import auto_sync_handle
from raft_tpu.distance import DistanceType
from raft_tpu.distance import pairwise as _dense
from raft_tpu.sparse.op import csr_row_slice
from raft_tpu.sparse.convert import csr_to_dense
from raft_tpu.sparse.types import CSR

# reference sparse/distance/distance.cuh:37-56
SUPPORTED_SPARSE_DISTANCES = (
    DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded,
    DistanceType.CosineExpanded,
    DistanceType.InnerProduct,
    DistanceType.L1,
    DistanceType.Canberra,
    DistanceType.Linf,
    DistanceType.LpUnexpanded,
    DistanceType.JaccardExpanded,
    DistanceType.HellingerExpanded,
    DistanceType.DiceExpanded,
    DistanceType.L2Unexpanded,
    DistanceType.L2SqrtUnexpanded,
    DistanceType.CorrelationExpanded,
    DistanceType.RusselRaoExpanded,
    DistanceType.HammingUnexpanded,
    DistanceType.JensenShannon,
    DistanceType.KLDivergence,
)


# metrics the block-densify engine cannot express through the dense
# dispatch (reference computes them only sparsely, bin_distance.cuh)
_COMPRESSED_ONLY = (DistanceType.JaccardExpanded, DistanceType.DiceExpanded)

# dim above which "auto" switches to the feature-compressed engine (the
# reference picks hash-table COO SpMV strategies by nnz/smem footprint;
# here the criterion is the densified-tile width)
HIGHDIM_THRESHOLD = 4096


@auto_sync_handle
def pairwise_distance(x: CSR, y: CSR, metric: DistanceType = DistanceType.L2Expanded,
                      p: float = 2.0, batch_size_x: int = 4096,
                      batch_size_y: Optional[int] = None,
                      engine: str = "auto", handle=None) -> jnp.ndarray:
    """All-pairs distances between rows of two CSR matrices.

    Mirrors reference ``sparse::distance::pairwiseDistance``
    (sparse/distance/distance.cuh:68); returns a dense (m, n) matrix like
    the reference.

    engine: "auto" (feature-compressed when dim > HIGHDIM_THRESHOLD or the
    metric is sparse-only), "densify", or "compressed".
    """
    expects(metric in SUPPORTED_SPARSE_DISTANCES,
            f"metric {metric} not supported for sparse inputs")
    expects(x.shape[1] == y.shape[1], "pairwise_distance: dim mismatch")
    expects(engine in ("auto", "densify", "compressed"),
            f"unknown engine {engine!r}")
    expects(not (engine == "densify" and metric in _COMPRESSED_ONLY),
            f"{metric.name} has no densify path (sparse-only in the "
            "reference, bin_distance.cuh) — use engine='compressed' or 'auto'")
    if engine == "auto":
        engine = ("compressed" if x.shape[1] > HIGHDIM_THRESHOLD
                  or metric in _COMPRESSED_ONLY else "densify")
    if engine == "compressed":
        return _pairwise_compressed(x, y, metric, p, batch_size_x,
                                    batch_size_y)
    m, n = x.shape[0], y.shape[0]
    bx = min(batch_size_x, m)
    by = min(batch_size_y or max(batch_size_x, 4096), n)

    out_rows = []
    for i0 in range(0, m, bx):
        i1 = min(i0 + bx, m)
        xd = csr_to_dense(csr_row_slice(x, i0, i1))
        row = []
        # Densify each y block inside the loop so at most one (bx, dim) and
        # one (by, dim) dense tile are live at a time — the batch knobs must
        # bound the densified footprint (reference batch_size_index/query).
        for j0 in range(0, n, by):
            j1 = min(j0 + by, n)
            yd = csr_to_dense(csr_row_slice(y, j0, j1))
            # undecorated dispatcher: no per-tile default-handle sync
            row.append(_dense.distance(xd, yd, metric, p))
        out_rows.append(row[0] if len(row) == 1 else jnp.concatenate(row, axis=1))
    return out_rows[0] if len(out_rows) == 1 else jnp.concatenate(out_rows, axis=0)


# ---------------------------------------------------------------------------
# feature-compressed engine (reference detail/coo_spmv.cuh hash-strategy role)
# ---------------------------------------------------------------------------

def _seg_sum(v, rows, nrows):
    # one extra segment collects padding rows; sliced off
    return segment_sum(v, rows, nrows + 1)[:nrows]


def _row_stats(rows, vals, nrows):
    """Exact per-row (Σv, Σv², nnz) from padded COO entries (padding rows
    carry v=0 and land in the dropped extra segment)."""
    s = _seg_sum(vals, rows, nrows)
    sq = _seg_sum(vals * vals, rows, nrows)
    nnz = _seg_sum((vals != 0).astype(vals.dtype), rows, nrows)
    return s, sq, nnz


# additive metrics: (pair_fn(x, y), zero_fn(y)) with Σ_f pair_fn and the
# outside-u y-features contributing Σ zero_fn — pair_fn(0, 0) == 0 and
# pair_fn(0, y) == zero_fn(y) by construction.  Final transforms applied
# after the correction (so roots see the complete sum).
_ADDITIVE = {
    DistanceType.L1: (lambda x, y: jnp.abs(x - y), jnp.abs),
    DistanceType.L2Unexpanded: (lambda x, y: (x - y) ** 2, lambda v: v * v),
    DistanceType.L2SqrtUnexpanded: (lambda x, y: (x - y) ** 2, lambda v: v * v),
    DistanceType.Canberra: (_dense.canberra_terms,
                            lambda v: (v != 0).astype(v.dtype)),
    DistanceType.HammingUnexpanded: (
        lambda x, y: (x != y).astype(x.dtype),
        lambda v: (v != 0).astype(v.dtype)),
    DistanceType.JensenShannon: (
        _dense.jensen_shannon_terms,
        lambda v: jnp.where(v > 0, v, 0.0) * jnp.asarray(np.log(2.0), v.dtype)),
}


def _additive_tile(fn):
    def tile(xi, yj):
        return jnp.sum(fn(xi, yj), axis=-1)

    return tile


@functools.partial(jax.jit, static_argnames=("metric", "p", "bx", "by",
                                             "ucap", "dim"))
def _compressed_tile(xr, xc, xv, yr, yc, yv, metric: DistanceType, p: float,
                     bx: int, by: int, ucap: int, dim: int):
    """One (bx × by) output tile from padded COO entries of an x-block and a
    y-block, via the compressed feature axis ``u`` of the x-block.

    Padding convention: x pads have (row=bx, col=dim, val=0); y pads
    (row=by, col=dim, val=0).  Pad scatters drop (mode='drop'); pad
    segments are the sliced-off extra row of :func:`_seg_sum`.
    """
    dt = xv.dtype
    u = jnp.unique(xc, size=ucap, fill_value=dim)  # sorted; fill sorts last
    xpos = jnp.searchsorted(u, xc).astype(jnp.int32)
    xd = jnp.zeros((bx, ucap), dt).at[xr, xpos].add(xv, mode="drop")
    ypos = jnp.searchsorted(u, yc).astype(jnp.int32)
    member = jnp.take(u, jnp.clip(ypos, 0, ucap - 1)) == yc
    yd = jnp.zeros((by, ucap), dt).at[
        yr, jnp.where(member, ypos, ucap)].add(yv, mode="drop")
    y_out = (yr < by) & ~member  # real y entries outside u

    def outside_sum(g0v):
        return _seg_sum(jnp.where(y_out, g0v, 0), yr, by)

    mm = functools.partial(jnp.matmul, precision="highest")

    if metric in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded):
        _, xsq, _ = _row_stats(xr, xv, bx)
        _, ysq, _ = _row_stats(yr, yv, by)
        d = jnp.maximum(xsq[:, None] + ysq[None, :] - 2.0 * mm(xd, yd.T), 0.0)
        return jnp.sqrt(d) if metric == DistanceType.L2SqrtExpanded else d
    if metric == DistanceType.InnerProduct:
        return mm(xd, yd.T)
    if metric == DistanceType.CosineExpanded:
        _, xsq, _ = _row_stats(xr, xv, bx)
        _, ysq, _ = _row_stats(yr, yv, by)
        denom = jnp.maximum(jnp.sqrt(xsq)[:, None] * jnp.sqrt(ysq)[None, :],
                            1e-30)
        return 1.0 - mm(xd, yd.T) / denom
    if metric == DistanceType.CorrelationExpanded:
        xs, xsq, _ = _row_stats(xr, xv, bx)
        ys, ysq, _ = _row_stats(yr, yv, by)
        k = dim
        numer = k * mm(xd, yd.T) - xs[:, None] * ys[None, :]
        q = k * xsq - xs * xs
        r = k * ysq - ys * ys
        denom = jnp.sqrt(jnp.maximum(q[:, None] * r[None, :], 1e-30))
        return 1.0 - numer / denom
    if metric == DistanceType.HellingerExpanded:
        # scatter √|v| instead of v: IP of square roots
        xs_ = jnp.zeros((bx, ucap), dt).at[xr, xpos].add(
            jnp.sqrt(jnp.abs(xv)), mode="drop")
        ys_ = jnp.zeros((by, ucap), dt).at[
            yr, jnp.where(member, ypos, ucap)].add(
            jnp.sqrt(jnp.abs(yv)), mode="drop")
        return jnp.sqrt(jnp.maximum(1.0 - mm(xs_, ys_.T), 0.0))
    if metric == DistanceType.RusselRaoExpanded:
        # raw-value IP, matching the dense engine (russell_rao.cuh assumes
        # boolean-valued inputs; the formula is applied to values as-is)
        return (dim - mm(xd, yd.T)) * (1.0 / dim)
    if metric == DistanceType.KLDivergence:
        # 0.5·(Σ x log x − Σ x log y): both terms live entirely on u
        # (x = 0 elsewhere; log y := 0 where y == 0, kl_divergence.cuh:27)
        xlx = _seg_sum(jnp.where(xv > 0, xv * jnp.log(
            jnp.where(xv > 0, xv, 1.0)), 0.0), xr, bx)
        ylog = jnp.where(yd > 0, jnp.log(jnp.where(yd > 0, yd, 1.0)), 0.0)
        return 0.5 * (xlx[:, None] - mm(xd, ylog.T))
    if metric in (DistanceType.JaccardExpanded, DistanceType.DiceExpanded):
        # reference bin_distance.cuh:114-157 / :168-213 on row SUMS + dot
        xs, _, _ = _row_stats(xr, xv, bx)
        ys, _, _ = _row_stats(yr, yv, by)
        dot = mm(xd, yd.T)
        union = xs[:, None] + ys[None, :]
        both_empty = union == 0
        if metric == DistanceType.JaccardExpanded:
            denom = union - dot
            sim = jnp.where(denom != 0, dot / jnp.where(denom != 0, denom, 1.0), 0.0)
        else:
            sim = jnp.where(union != 0, 2.0 * dot / jnp.where(union != 0, union, 1.0), 0.0)
        return jnp.where(both_empty, 0.0, 1.0 - sim)
    if metric == DistanceType.Linf:
        base = _dense._blocked_reduce(xd, yd, _dense._tile_linf)
        corr = jax.ops.segment_max(
            jnp.where(y_out, jnp.abs(yv), 0.0), yr, by + 1)[:by]
        return jnp.maximum(base, corr[None, :])
    if metric == DistanceType.LpUnexpanded:
        pair = lambda a, b: jnp.power(jnp.abs(a - b), p)  # noqa: E731
        base = _dense._blocked_reduce(xd, yd, _additive_tile(pair))
        corr = outside_sum(jnp.power(jnp.abs(yv), p))
        return jnp.power(base + corr[None, :], 1.0 / p)
    pair, zero = _ADDITIVE[metric]
    base = _dense._blocked_reduce(xd, yd, _additive_tile(pair))
    acc = base + outside_sum(zero(yv))[None, :]
    if metric == DistanceType.L2SqrtUnexpanded:
        return jnp.sqrt(jnp.maximum(acc, 0.0))
    if metric == DistanceType.HammingUnexpanded:
        return acc * (1.0 / dim)
    if metric == DistanceType.JensenShannon:
        return jnp.sqrt(jnp.maximum(0.5 * acc, 0.0))
    return acc


def _block_entries(indptr, indices, data, i0, i1, bsz, cap, dim):
    """Padded (rows_local, cols, vals) for CSR rows [i0, i1) — numpy."""
    s, e = int(indptr[i0]), int(indptr[i1])
    nz = e - s
    rows = np.repeat(np.arange(i1 - i0), np.diff(indptr[i0:i1 + 1]))
    r = np.full(cap, bsz, np.int32)
    c = np.full(cap, dim, np.int32)
    v = np.zeros(cap, data.dtype)
    r[:nz] = rows
    c[:nz] = indices[s:e]
    v[:nz] = data[s:e]
    return r, c, v


def _pairwise_compressed(x: CSR, y: CSR, metric: DistanceType, p: float,
                         batch_size_x: int, batch_size_y: Optional[int]):
    m, dim = x.shape
    n = y.shape[0]
    bx = min(batch_size_x, m, 512)  # compressed tiles want narrower x-blocks
    by = min(batch_size_y or 2048, n)
    xip = np.asarray(x.indptr)
    yip = np.asarray(y.indptr)
    xind, xdat = np.asarray(x.indices), np.asarray(x.data)
    yind, ydat = np.asarray(y.indices), np.asarray(y.data)

    def roundup(v, q=256):
        return max(q, -(-v // q) * q)

    cap_x = roundup(max(int(xip[min(i0 + bx, m)] - xip[i0])
                        for i0 in range(0, m, bx)))
    cap_y = roundup(max(int(yip[min(j0 + by, n)] - yip[j0])
                        for j0 in range(0, n, by)))
    # ucap must cover every distinct column value in a padded x-block:
    # distinct ≤ min(cap_x entries, dim features + the pad value dim)
    ucap = min(cap_x, roundup(dim + 1, 128))

    out = np.zeros((m, n), xdat.dtype)
    for i0 in range(0, m, bx):
        i1 = min(i0 + bx, m)
        xr, xc, xv = _block_entries(xip, xind, xdat, i0, i1, bx, cap_x, dim)
        for j0 in range(0, n, by):
            j1 = min(j0 + by, n)
            yr, yc, yv = _block_entries(yip, yind, ydat, j0, j1, by, cap_y, dim)
            tile = _compressed_tile(xr, xc, xv, yr, yc, yv, metric, float(p),
                                    bx, by, ucap, dim)
            out[i0:i1, j0:j1] = np.asarray(tile)[: i1 - i0, : j1 - j0]
    return jnp.asarray(out)
