"""Sparse linear algebra: SpMV/SpMM, add, transpose, symmetrize, norms.

Counterpart of reference ``sparse/linalg/`` (``add.cuh``, ``degree.cuh``,
``norm.cuh``, ``symmetrize.cuh``, ``transpose.cuh``) — the cusparse calls
become segment reductions + gathers that XLA lowers to TPU scatter/gather
HLOs; SpMM rides a gather + segment-sum which XLA fuses (the Pallas
alternative only pays off for very large nnz).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from raft_tpu.linalg.reduce import segment_sum

from raft_tpu.core.error import expects
from raft_tpu.sparse.types import COO, CSR
from raft_tpu.sparse.convert import coo_to_csr, csr_to_coo
from raft_tpu.sparse.op import _coo_combine_duplicates, coo_sort, coo_sum_duplicates


def spmv(csr: CSR, x) -> jnp.ndarray:
    """y = A @ x for CSR A, dense x (n_cols,).

    The reference uses cusparse SpMV (sparse/detail/cusparse_wrappers.h);
    here: gather x at column indices, multiply, segment-sum by row.  Padding
    rows (id n_rows) are dropped by ``num_segments``.

    NOTE: the segment-sum lowers to a scatter, which serializes on TPU.
    Iterative solvers that apply the same matrix many times should convert
    once with :func:`csr_to_ell` and use :func:`ell_spmv` (pure
    gather+reduce — no scatter in the hot loop).
    """
    x = jnp.asarray(x)
    expects(x.shape[0] == csr.shape[1], "spmv: dimension mismatch")
    prod = csr.data * x[csr.indices]
    return segment_sum(prod, csr.row_ids(), csr.shape[0])


@jax.tree_util.register_pytree_node_class
class EllHybrid:
    """Row-padded (ELL) sparse layout + COO overflow — the TPU SpMV format.

    ``cols``/``vals`` are (n_rows, r) with r ≈ the row-nnz quantile; rows
    longer than r spill their tail into the (small) COO overflow arrays.
    The matvec is then a dense gather + row reduction (VPU-friendly, no
    scatter) plus a scatter only over the overflow tail — the classic
    HYB format cusparse itself used, chosen here because XLA's scatter
    lowering on TPU serializes while gathers vectorize.
    """

    def __init__(self, cols, vals, ov_rows, ov_cols, ov_vals, shape):
        self.cols = cols
        self.vals = vals
        self.ov_rows = ov_rows
        self.ov_cols = ov_cols
        self.ov_vals = ov_vals
        self.shape = tuple(shape)

    def tree_flatten(self):
        return ((self.cols, self.vals, self.ov_rows, self.ov_cols,
                 self.ov_vals), self.shape)

    @classmethod
    def tree_unflatten(cls, shape, leaves):
        return cls(*leaves, shape)


def csr_to_ell(csr: CSR, quantile: float = 0.95) -> EllHybrid:
    """Host-side CSR → :class:`EllHybrid` conversion (one-time cost; do it
    outside the solver loop)."""
    import numpy as np

    indptr = np.asarray(csr.indptr)
    nnz = int(indptr[-1])
    n_rows = csr.shape[0]
    if nnz == 0:  # empty matrix: one all-zero column, no overflow
        zcols = np.zeros((n_rows, 1), np.int32)
        zvals = np.zeros((n_rows, 1), np.asarray(csr.data).dtype)
        empty = np.zeros(0, np.int32)
        return EllHybrid(jnp.asarray(zcols), jnp.asarray(zvals),
                         jnp.asarray(empty), jnp.asarray(empty),
                         jnp.asarray(zvals[:0, 0]), csr.shape)
    # static-capacity CSRs pad indices/data past indptr[-1] — drop padding
    indices = np.asarray(csr.indices)[:nnz]
    data = np.asarray(csr.data)[:nnz]
    nnz_row = np.diff(indptr)
    r = int(np.percentile(nnz_row, quantile * 100)) if n_rows else 0
    r = max(1, -(-max(r, 1) // 8) * 8)
    try:
        from raft_tpu.native import csr_to_ell_host

        cols, vals, ov_rows, ov_cols, ov_vals = csr_to_ell_host(
            indptr, indices, data, r)
    except RuntimeError:  # no toolchain: vectorized numpy fallback
        offs = np.arange(r)
        starts = indptr[:-1].astype(np.int64)
        valid = offs[None, :] < nnz_row[:, None]
        take = np.where(valid, starts[:, None] + offs[None, :], 0)
        cols = np.where(valid, indices[take], 0).astype(np.int32)
        vals = np.where(valid, data[take], 0)
        # entries at position >= r within their row spill to COO overflow
        pos = np.arange(len(indices)) - np.repeat(starts, nnz_row)
        ovm = pos >= r
        ov_rows = np.repeat(np.arange(n_rows, dtype=np.int32), nnz_row)[ovm]
        ov_cols = indices[ovm].astype(np.int32)
        ov_vals = data[ovm]
    return EllHybrid(jnp.asarray(cols), jnp.asarray(vals),
                     jnp.asarray(ov_rows), jnp.asarray(ov_cols),
                     jnp.asarray(ov_vals), csr.shape)


def ell_spmv(ell: EllHybrid, x) -> jnp.ndarray:
    """y = A @ x over :class:`EllHybrid` — gather + row-sum on the padded
    block (no scatter), scatter only over the overflow tail."""
    x = jnp.asarray(x)
    y = jnp.sum(ell.vals * x[ell.cols], axis=1)
    if ell.ov_rows.shape[0]:
        y = y + segment_sum(ell.ov_vals * x[ell.ov_cols], ell.ov_rows,
                                    ell.shape[0])
    return y


def matvec_operand(csr: CSR):
    """Best SpMV *operand* for :func:`apply_matvec` — a pytree that can be
    passed through jit boundaries (unlike a closure, whose identity breaks
    jit caching and whose captured buffers outlive the caller).

    Concrete CSR → one-time host-side ELL conversion (scatter-free hot
    loop).  Traced CSR (inside jit/vmap — the host conversion is
    impossible) → the CSR itself (plain :func:`spmv`).
    """
    import jax.core

    if isinstance(csr.indptr, jax.core.Tracer) \
            or isinstance(csr.indices, jax.core.Tracer):
        return csr
    return csr_to_ell(csr)


def apply_matvec(op, v) -> jnp.ndarray:
    """``A @ v`` for a :func:`matvec_operand` result (EllHybrid or CSR)."""
    if isinstance(op, CSR):
        return spmv(op, v)
    return ell_spmv(op, v)


def best_matvec(csr: CSR):
    """``A @ ·`` closure over :func:`matvec_operand` (prefer the operand +
    :func:`apply_matvec` pair when crossing jit boundaries)."""
    op = matvec_operand(csr)
    return lambda v: apply_matvec(op, v)


def spmm(csr: CSR, b) -> jnp.ndarray:
    """C = A @ B for CSR A (m×k), dense B (k×n)."""
    b = jnp.asarray(b)
    expects(b.shape[0] == csr.shape[1], "spmm: dimension mismatch")
    prod = csr.data[:, None] * b[csr.indices, :]
    return segment_sum(prod, csr.row_ids(), csr.shape[0])


def csr_degree(csr: CSR) -> jnp.ndarray:
    """Number of live entries per row (reference sparse/linalg/degree.cuh
    ``coo_degree``)."""
    return jnp.diff(csr.indptr)


def coo_degree(coo: COO) -> jnp.ndarray:
    ids = jnp.where(coo.mask(), coo.rows, coo.shape[0])
    return jnp.bincount(ids, length=coo.shape[0] + 1)[:coo.shape[0]]


def row_normalize(csr: CSR, norm: str = "l1") -> CSR:
    """Normalize each row by its L1 norm or max (reference
    sparse/linalg/norm.cuh ``csr_row_normalize_l1`` / ``_max``)."""
    rows = csr.row_ids()
    if norm == "l1":
        denom = segment_sum(jnp.abs(csr.data), rows,
                                    csr.shape[0])
    elif norm == "max":
        denom = jax.ops.segment_max(csr.data, rows,
                                    csr.shape[0])
    else:
        raise ValueError(f"unknown norm {norm!r}")
    denom = jnp.where(denom != 0, denom, 1)
    safe_rows = jnp.clip(rows, 0, csr.shape[0] - 1)
    data = csr.data / denom[safe_rows]
    data = jnp.where(csr.mask(), data, jnp.zeros((), data.dtype))
    return CSR(csr.indptr, csr.indices, data, csr.shape)


def csr_transpose(csr: CSR) -> CSR:
    """Aᵀ (reference sparse/linalg/transpose.h, cusparse csr2csc)."""
    coo = csr_to_coo(csr)
    live = coo.mask()
    t = COO(jnp.where(live, coo.cols, csr.shape[1]),
            jnp.where(live, coo.rows, 0),
            coo.vals, (csr.shape[1], csr.shape[0]), nnz=coo.nnz)
    return coo_to_csr(coo_sort(t))


def csr_add(a: CSR, b: CSR) -> CSR:
    """A + B with duplicate coalescing (reference sparse/linalg/add.cuh
    ``csr_add_calc_inds``/``csr_add_finalize``).  Output capacity is
    ``a.capacity + b.capacity`` (the exact union size is data-dependent)."""
    expects(a.shape == b.shape, "csr_add: shape mismatch")
    ca, cb = csr_to_coo(a), csr_to_coo(b)
    merged = COO(jnp.concatenate([ca.rows, cb.rows]),
                 jnp.concatenate([ca.cols, cb.cols]),
                 jnp.concatenate([ca.vals, jnp.asarray(cb.vals, ca.vals.dtype)]),
                 a.shape, nnz=ca.nnz + cb.nnz)
    return coo_to_csr(coo_sum_duplicates(merged))


def symmetrize(coo_or_csr, combine: str = "sum"):
    """A ← A + Aᵀ handling duplicates (reference sparse/linalg/symmetrize.cuh
    ``coo_symmetrize`` builds the union with a custom reduction; kNN-graph
    symmetrization uses max semantics).  Returns the same container kind."""
    is_csr = isinstance(coo_or_csr, CSR)
    coo = csr_to_coo(coo_or_csr) if is_csr else coo_or_csr
    expects(coo.shape[0] == coo.shape[1], "symmetrize: matrix must be square")
    live = coo.mask()
    n = coo.shape[0]
    both = COO(jnp.concatenate([coo.rows, jnp.where(live, coo.cols, n)]),
               jnp.concatenate([coo.cols, jnp.where(live, coo.rows, 0)]),
               jnp.concatenate([coo.vals,
                                jnp.where(live, coo.vals,
                                          jnp.zeros((), coo.vals.dtype))]),
               coo.shape, nnz=2 * coo.nnz)
    out = _coo_combine_duplicates(both, combine)
    return coo_to_csr(out) if is_csr else out


def weak_cc(g: CSR) -> jnp.ndarray:
    """Weakly-connected component labels via min-label propagation.

    Counterpart of reference ``sparse/csr.hpp`` ``weak_cc`` (per-vertex
    frontier kernel); here a ``lax.while_loop`` of whole-graph segment-min
    passes — each pass halves label diameter via a pointer-jumping step, so
    convergence is fast in practice.  Labels are the minimum vertex id
    reachable; relabel with :mod:`raft_tpu.label` if compaction is needed.
    """
    n = g.shape[0]
    expects(g.shape[0] == g.shape[1], "weak_cc: graph must be square")
    rows = g.row_ids()
    rows_safe = jnp.clip(rows, 0, n - 1)
    cols_safe = jnp.clip(g.indices, 0, n - 1)

    def cond(state):
        return state[1]

    def body(state):
        color, _ = state
        # Weak connectivity ignores direction: propagate the min label both
        # ways along every edge...
        pulled = jax.ops.segment_min(
            jnp.where(g.mask(), color[cols_safe], n), rows, n)
        pushed = jax.ops.segment_min(
            jnp.where(g.mask(), color[rows_safe], n),
            jnp.where(g.mask(), g.indices, n), n)
        new = jnp.minimum(color, jnp.minimum(pulled, pushed))
        # ...then pointer-jump through the current labels.
        new = new[jnp.clip(new, 0, n - 1)]
        return (new, jnp.any(new != color))

    color, _ = jax.lax.while_loop(
        cond, body, (jnp.arange(n, dtype=jnp.int32), jnp.asarray(True)))
    return color


def fit_embedding(adj: CSR, n_components: int, *, seed: int = 0,
                  tol: float = 1e-6) -> jnp.ndarray:
    """Spectral embedding of a graph: smallest non-trivial Laplacian
    eigenvectors, row-scaled.

    Counterpart of reference ``sparse/linalg/detail/spectral.cuh:34-80``
    (``fit_embedding``): Laplacian + Lanczos smallest n_components+1 +
    scaling, dropping the trivial constant eigenvector.
    Returns (n, n_components).
    """
    from raft_tpu.sparse.solver import lanczos_smallest

    lap = laplacian(adj)
    _, vecs = lanczos_smallest(lap, n_components + 1, seed=seed, tol=tol)
    emb = vecs[:, 1:]
    # Scale each component to unit std (the reference scales the embedding
    # before handing it to k-means).
    std = jnp.maximum(jnp.std(emb, axis=0), 1e-12)
    return emb / std


def laplacian(adj: CSR, normalized: bool = False) -> CSR:
    """Graph Laplacian L = D − A (or I − D^-1/2 A D^-1/2).

    Reference spectral/matrix_wrappers.hpp ``laplacian_matrix_t`` represents
    L implicitly (SpMV = D·x − A·x); this materializes it for reuse by the
    Lanczos solver, with capacity nnz + n for the diagonal.
    """
    n = adj.shape[0]
    expects(adj.shape[0] == adj.shape[1], "laplacian: matrix must be square")
    deg = segment_sum(adj.data, adj.row_ids(), n)
    ca = csr_to_coo(adj)
    live = ca.mask()
    if normalized:
        inv_sqrt = jnp.where(deg > 0, 1.0 / jnp.sqrt(jnp.maximum(deg, 1e-30)), 0.0)
        safe_r = jnp.clip(ca.rows, 0, n - 1)
        safe_c = jnp.clip(ca.cols, 0, n - 1)
        off = jnp.where(live, -ca.vals * inv_sqrt[safe_r] * inv_sqrt[safe_c],
                        jnp.zeros((), ca.vals.dtype))
        diag = jnp.where(deg > 0, 1.0, 0.0).astype(ca.vals.dtype)
    else:
        off = jnp.where(live, -ca.vals, jnp.zeros((), ca.vals.dtype))
        diag = deg.astype(ca.vals.dtype)
    merged = COO(
        jnp.concatenate([jnp.where(live, ca.rows, n), jnp.arange(n, dtype=jnp.int32)]),
        jnp.concatenate([jnp.where(live, ca.cols, 0), jnp.arange(n, dtype=jnp.int32)]),
        jnp.concatenate([off, diag]),
        adj.shape, nnz=ca.nnz + n)
    return coo_to_csr(coo_sum_duplicates(merged))
