"""Sparse neighbors: batched sparse brute-force kNN, kNN-graph builder,
connect_components MST fix-up.

Counterpart of reference ``sparse/neighbors/`` — ``detail/knn.cuh``
(batched sparse bf-kNN), ``knn_graph.cuh`` (dense input → COO kNN graph),
``detail/connect_components.cuh`` (cross-component 1-NN used to turn a
spanning forest into a spanning tree).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.distance import DistanceType, pairwise_distance as dense_pairwise
from raft_tpu.matrix import select_k
from raft_tpu.sparse.distance import pairwise_distance as sparse_pairwise
from raft_tpu.sparse.types import COO, CSR
from raft_tpu.sparse.op import csr_row_slice
from raft_tpu.sparse.solver import boruvka_mst
from raft_tpu.sparse.solver.mst import sorted_mst_edges


def brute_force_knn(index: CSR, query: CSR, k: int,
                    metric: DistanceType = DistanceType.L2Expanded,
                    batch_size_index: int = 16384,
                    batch_size_query: int = 4096
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Batched sparse brute-force kNN (reference
    sparse/neighbors/detail/knn.cuh ``brute_force_knn``): tiles over both
    index and query, merging per-tile top-k like ``knn_merge_parts``.

    Returns (distances [nq, k], indices [nq, k]).
    """
    nq, ni = query.shape[0], index.shape[0]
    expects(1 <= k <= ni, "brute_force_knn: need 1 <= k <= n_index")
    bq = min(batch_size_query, nq)
    bi = min(batch_size_index, ni)

    out_d, out_i = [], []
    for q0 in range(0, nq, bq):
        q1 = min(q0 + bq, nq)
        qs = csr_row_slice(query, q0, q1)
        best_d = best_i = None
        for i0 in range(0, ni, bi):
            i1 = min(i0 + bi, ni)
            d = sparse_pairwise(qs, csr_row_slice(index, i0, i1), metric)
            kk = min(k, i1 - i0)
            vals, idx = select_k(d, kk, select_min=True)
            idx = idx + i0
            if best_d is None:
                best_d, best_i = vals, idx
            else:
                # merge parts: top-k of the union of running + new candidates
                cat_d = jnp.concatenate([best_d, vals], axis=1)
                cat_i = jnp.concatenate([best_i, idx], axis=1)
                best_d, best_i = select_k(cat_d, min(k, cat_d.shape[1]),
                                          select_min=True, indices=cat_i)
        # pad if fewer than k candidates total (ni < k handled by expects)
        out_d.append(best_d)
        out_i.append(best_i)
    return (out_d[0] if len(out_d) == 1 else jnp.concatenate(out_d, axis=0),
            out_i[0] if len(out_i) == 1 else jnp.concatenate(out_i, axis=0))


def build_k(n_samples: int, c: int) -> int:
    """k heuristic for kNN-graph connectivity (reference
    sparse/neighbors/detail/knn_graph.cuh:56, from "kNN-MST-Agglomerative"):
    min(n, max(2, ⌊log2 n⌋ + c))."""
    return int(min(n_samples, max(2, math.floor(math.log2(max(n_samples, 2))) + c)))


def knn_graph(x, metric: DistanceType = DistanceType.L2SqrtExpanded,
              c: int = 15, k: Optional[int] = None,
              batch_size: int = 4096) -> COO:
    """Directed kNN graph of dense points as COO (reference
    sparse/neighbors/knn_graph.cuh:— dense input, sparse output).

    Self-edges are excluded; edge (i, j) carries the metric distance.
    """
    x = jnp.asarray(x)
    n = x.shape[0]
    kk = int(k) if k is not None else build_k(n, c)
    kk = min(kk, n - 1)
    rows_list, cols_list, vals_list = [], [], []
    for i0 in range(0, n, batch_size):
        i1 = min(i0 + batch_size, n)
        d = dense_pairwise(x[i0:i1], x, metric)
        # exclude self by +inf on the diagonal entries of this block
        r = jnp.arange(i0, i1)
        d = d.at[jnp.arange(i1 - i0), r].set(jnp.inf)
        vals, idx = select_k(d, kk, select_min=True)
        rows_list.append(jnp.repeat(r, kk).astype(jnp.int32))
        cols_list.append(idx.reshape(-1).astype(jnp.int32))
        vals_list.append(vals.reshape(-1))
    return COO(jnp.concatenate(rows_list), jnp.concatenate(cols_list),
               jnp.concatenate(vals_list), (n, n))


def connect_components(x, colors,
                       metric: DistanceType = DistanceType.L2SqrtExpanded,
                       batch_size: int = 4096) -> COO:
    """Cross-component nearest-neighbor edges (reference
    sparse/neighbors/detail/connect_components.cuh): for each point the
    nearest point in a *different* component, reduced to the minimum edge
    per (component) color pair endpoint, symmetrized.

    Returns a COO edge set (n, n) with one edge per source color minimum —
    enough to strictly reduce the number of components when merged with a
    spanning forest (``min_components_by_color`` in the reference).
    """
    x = jnp.asarray(x)
    colors = jnp.asarray(colors, jnp.int32)
    n = x.shape[0]
    nn_dist_list, nn_idx_list = [], []
    for i0 in range(0, n, batch_size):
        i1 = min(i0 + batch_size, n)
        d = dense_pairwise(x[i0:i1], x, metric)
        same = colors[i0:i1, None] == colors[None, :]
        d = jnp.where(same, jnp.inf, d)
        nn_idx_list.append(jnp.argmin(d, axis=1).astype(jnp.int32))
        nn_dist_list.append(jnp.min(d, axis=1))
    nn_idx = jnp.concatenate(nn_idx_list)
    nn_dist = jnp.concatenate(nn_dist_list)

    # Per-color minimum outgoing edge (min_components_by_color): the point
    # with the smallest cross-component distance within each color.
    best_dist = jax.ops.segment_min(nn_dist, colors, num_segments=n)
    is_best = (nn_dist == best_dist[jnp.clip(colors, 0, n - 1)]) & jnp.isfinite(nn_dist)
    # deterministic pick: smallest point index among equals per color
    cand = jnp.where(is_best, jnp.arange(n, dtype=jnp.int32), n)
    best_pt = jax.ops.segment_min(cand, colors, num_segments=n)
    has = best_pt < n
    src = jnp.where(has, best_pt, n).astype(jnp.int32)
    src_safe = jnp.clip(src, 0, n - 1)
    dst = jnp.where(has, nn_idx[src_safe], 0).astype(jnp.int32)
    w = jnp.where(has, nn_dist[src_safe], 0.0)
    # symmetrize: emit both directions
    rows = jnp.concatenate([src, jnp.where(has, dst, n)])
    cols = jnp.concatenate([dst, jnp.where(has, src_safe, 0).astype(jnp.int32)])
    vals = jnp.concatenate([w, jnp.where(has, w, 0.0)])
    # Compact live entries to the front so the COO honors the module's
    # padding convention (types.py: positions >= nnz hold row == n_rows).
    pad = rows >= n
    order = jnp.argsort(pad, stable=True)
    rows = rows[order]
    cols = jnp.where(pad, 0, cols)[order]
    vals = jnp.where(pad, 0.0, vals)[order]
    return COO(rows, cols, vals, (n, n), nnz=2 * jnp.sum(has, dtype=jnp.int32))


def mst_from_knn_graph(x, metric: DistanceType = DistanceType.L2SqrtExpanded,
                       c: int = 15, max_fixup_iter: int = 32):
    """Sorted MST edges of the kNN-graph connectivity (reference
    cluster/detail/connectivities.cuh + detail/mst.cuh ``build_sorted_mst``
    with ``connect_components`` fix-up for disconnected kNN graphs).

    Returns (src, dst, weight) sorted ascending by weight with exactly
    n−1 live edges.
    """
    x = jnp.asarray(x)
    n = x.shape[0]
    knn = knn_graph(x, metric, c)
    # symmetrize by emitting reverse edges (duplicates are harmless for MST)
    live = knn.mask()
    g = COO(jnp.concatenate([knn.rows, jnp.where(live, knn.cols, n)]),
            jnp.concatenate([knn.cols, jnp.where(live, knn.rows, 0)]),
            jnp.concatenate([knn.vals, knn.vals]), (n, n), nnz=2 * knn.nnz)
    res = boruvka_mst(g)
    for _ in range(max_fixup_iter):
        n_comp = len(jnp.unique(jax.device_get(res.color)))
        if n_comp == 1:
            break
        fix = connect_components(x, res.color, metric)
        # merge forest edges + fix-up edges and re-run Borůvka (reference
        # merges MST(msf) with MST(cross edges); rerunning on the union is
        # the same tree by cut optimality)
        fsrc, fdst, fw = res.src, res.dst, res.weight
        flive = jnp.arange(fsrc.shape[0]) < res.n_edges
        rows = jnp.concatenate([jnp.where(flive, fsrc, n),
                                jnp.where(flive, fdst, n), fix.rows])
        cols = jnp.concatenate([jnp.where(flive, fdst, 0),
                                jnp.where(flive, fsrc, 0), fix.cols])
        vals = jnp.concatenate([jnp.where(flive, fw, 0.0),
                                jnp.where(flive, fw, 0.0), fix.vals])
        g = COO(rows, cols, vals, (n, n),
                nnz=2 * res.n_edges + fix.nnz)
        res = boruvka_mst(g)
    expects(int(res.n_edges) == n - 1,
            "mst_from_knn_graph: could not connect the kNN graph")
    return sorted_mst_edges(res)
