"""Structural sparse ops: sort, filter, dedupe, slice, row op.

Counterpart of reference ``sparse/op/`` (``sort.h``, ``filter.hpp``,
``reduce.cuh``, ``slice.hpp``, ``row_op.cuh``).  Everything is jittable:
filters compact in place within the fixed capacity and update ``nnz``
instead of shrinking buffers (the reference similarly pre-counts and
allocates, SURVEY.md §7 "dynamic shapes").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from raft_tpu.linalg.reduce import segment_sum

from raft_tpu.sparse.types import COO, CSR


def _compact(coo: COO, keep) -> COO:
    """Stable-compact entries where ``keep`` holds; repad the tail."""
    keep = keep & coo.mask()
    nnz = jnp.sum(keep, dtype=jnp.int32)
    order = jnp.argsort(~keep, stable=True)
    live = jnp.arange(coo.capacity) < nnz
    return COO(jnp.where(live, coo.rows[order], coo.shape[0]),
               jnp.where(live, coo.cols[order], 0),
               jnp.where(live, coo.vals[order], jnp.zeros((), coo.vals.dtype)),
               coo.shape, nnz=nnz)


def coo_sort(coo: COO) -> COO:
    """Sort entries by (row, col).  Reference sparse/op/sort.h ``coo_sort``.
    Padding (row == n_rows) sorts to the tail automatically.

    Two-pass stable sort (cols then rows) instead of a fused int64 key —
    TPUs compute in int32 and a fused key overflows past 2³¹ entries.
    """
    order = jnp.argsort(coo.cols, stable=True)
    order = order[jnp.argsort(coo.rows[order], stable=True)]
    return COO(coo.rows[order], coo.cols[order], coo.vals[order],
               coo.shape, nnz=coo.nnz)


def coo_remove_scalar(coo: COO, scalar) -> COO:
    """Drop entries equal to *scalar* (reference sparse/op/filter.hpp
    ``coo_remove_scalar``)."""
    return _compact(coo, coo.vals != scalar)


def coo_remove_zeros(coo: COO) -> COO:
    """Drop explicit zeros (reference ``coo_remove_zeros``)."""
    return coo_remove_scalar(coo, 0)


def coo_sum_duplicates(coo: COO) -> COO:
    """Sum duplicate (row, col) entries; output is sorted by (row, col).

    Reference sparse/op/reduce.cuh ``max_duplicates``-family dedupe (the
    reference keeps max; RAFT's symmetrize uses sum semantics — both are
    exposed, see *combine*).
    """
    return _coo_combine_duplicates(coo, "sum")


def coo_max_duplicates(coo: COO) -> COO:
    """Keep the max over duplicate coordinates (reference
    sparse/op/reduce.cuh ``max_duplicates``)."""
    return _coo_combine_duplicates(coo, "max")


def _coo_combine_duplicates(coo: COO, combine: str) -> COO:
    s = coo_sort(coo)
    live = s.mask()
    is_new = jnp.concatenate([jnp.ones((1,), bool),
                              (s.rows[1:] != s.rows[:-1])
                              | (s.cols[1:] != s.cols[:-1])]) & live
    group = jnp.cumsum(is_new) - 1  # group id per entry; padding → last group
    group = jnp.where(live, group, s.capacity)
    n_groups = jnp.sum(is_new, dtype=jnp.int32)
    if combine == "sum":
        vals = segment_sum(s.vals, group, s.capacity)
    elif combine == "max":
        # segment_max's -inf fill in empty tail slots is cleared by the
        # out_live mask at the return site.
        vals = jax.ops.segment_max(s.vals, group, s.capacity)
    elif combine == "min":
        # min over DUPLICATES of the union (an edge present in only one
        # direction keeps its value) — the reference's coo_symmetrize
        # takes an arbitrary reduction functor (sparse/linalg/symmetrize.cuh)
        vals = jax.ops.segment_min(s.vals, group, s.capacity)
    else:  # pragma: no cover
        raise ValueError(combine)
    # First-occurrence coordinates per group (all duplicates share them).
    rows = jnp.full((s.capacity,), s.shape[0], jnp.int32).at[group].min(
        s.rows, mode="drop")
    cols = jax.ops.segment_min(s.cols, group, s.capacity)
    out_live = jnp.arange(s.capacity) < n_groups
    return COO(jnp.where(out_live, rows, s.shape[0]),
               jnp.where(out_live, cols, 0),
               jnp.where(out_live, vals, jnp.zeros((), s.vals.dtype)),
               s.shape, nnz=n_groups)


def csr_row_slice(csr: CSR, start: int, stop: int) -> CSR:
    """Extract rows [start, stop) as a new CSR (reference
    sparse/op/slice.hpp ``csr_row_slice_indptr``/``_populate``).

    *start*/*stop* must be static Python ints (the output row count is a
    shape).  Capacity is preserved; entries are shifted to the front.
    """
    start, stop = int(start), int(stop)
    lo, hi = csr.indptr[start], csr.indptr[stop]
    nnz = hi - lo
    idx = jnp.arange(csr.capacity)
    src = jnp.clip(idx + lo, 0, csr.capacity - 1)
    live = idx < nnz
    indptr = jnp.clip(csr.indptr[start:stop + 1] - lo, 0, nnz)
    return CSR(indptr,
               jnp.where(live, csr.indices[src], 0),
               jnp.where(live, csr.data[src], jnp.zeros((), csr.data.dtype)),
               (stop - start, csr.shape[1]))


def csr_row_op(csr: CSR, fn) -> CSR:
    """Apply ``fn(row_id, values) -> values`` elementwise with the row id
    available (reference sparse/op/row_op.cuh ``csr_row_op`` hands each row's
    extent to a device lambda)."""
    new = fn(csr.row_ids(), csr.data)
    new = jnp.where(csr.mask(), new, jnp.zeros((), new.dtype))
    return CSR(csr.indptr, csr.indices, new, csr.shape)
