"""Sparse solvers: restarted Lanczos eigensolver + Borůvka MST
(reference raft/sparse/solver/ — SURVEY.md §2.10)."""

from raft_tpu.sparse.solver.lanczos import (  # noqa: F401
    lanczos_largest,
    lanczos_smallest,
)
from raft_tpu.sparse.solver.mst import MSTResult, boruvka_mst  # noqa: F401
