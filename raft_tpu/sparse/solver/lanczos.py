"""Restarted Lanczos eigensolver.

Counterpart of reference ``sparse/solver/lanczos.cuh:68,132``
(``computeSmallestEigenvectors`` / ``computeLargestEigenvectors``, impl
``sparse/solver/detail/lanczos.cuh:746,990``): cusparse SpMV + cublas
dots/axpys with host LAPACK ``steqr`` on the tridiagonal problem.

TPU-first redesign:
- The Krylov build runs entirely on device inside ``lax.fori_loop`` — each
  host sync costs far more on TPU than on GPU (SURVEY.md §7 hard parts), so
  the whole m-step decomposition is one XLA computation.
- Full reorthogonalization instead of the reference's selective scheme:
  the extra work is two skinny matmuls per step (``Q @ w``, ``Qᵀ @ proj``)
  that ride the MXU, and it removes the ghost-eigenvalue bookkeeping.
- The projected (tridiagonal) eigenproblem is solved with ``jnp.linalg.eigh``
  on an m×m dense matrix — m is small (≤ a few hundred), the role of host
  LAPACK ``steqr`` in the reference.
- Smallest eigenpairs come from running on the spectral complement
  ``σI − A`` (σ = Gershgorin upper bound) — extremal convergence without
  shift-invert solves.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.sparse.types import CSR
from raft_tpu.sparse.linalg import best_matvec


def _gershgorin_upper(csr: CSR) -> jnp.ndarray:
    """Upper bound on eigenvalues: max_i (a_ii + Σ_{j≠i} |a_ij|)."""
    rows = csr.row_ids()
    n = csr.shape[0]
    absrow = jax.ops.segment_sum(jnp.abs(csr.data), rows, num_segments=n)
    is_diag = (csr.indices == jnp.clip(rows, 0, n - 1)) & csr.mask()
    diag = jax.ops.segment_sum(jnp.where(is_diag, csr.data, 0), rows,
                               num_segments=n)
    return jnp.max(diag + (absrow - jnp.abs(diag)))


def _lanczos_decomp(matvec, v0, m: int):
    """m-step Lanczos with full reorthogonalization.

    Returns (Q [m+1, n] row-major basis, alpha [m], beta [m]) with
    A qⱼ = βⱼ₋₁qⱼ₋₁ + αⱼqⱼ + βⱼqⱼ₊₁.
    """
    n = v0.shape[0]
    dtype = v0.dtype
    eps = jnp.asarray(jnp.finfo(dtype).tiny ** 0.5, dtype)
    q0 = v0 / jnp.maximum(jnp.linalg.norm(v0), eps)
    Q = jnp.zeros((m + 1, n), dtype).at[0].set(q0)
    alpha = jnp.zeros((m,), dtype)
    beta = jnp.zeros((m,), dtype)

    def body(j, state):
        Q, alpha, beta = state
        v = Q[j]
        w = matvec(v)
        a = jnp.dot(w, v)
        alpha = alpha.at[j].set(a)
        # Two-pass full reorthogonalization against every basis vector built
        # so far (rows > j of Q are zero and contribute nothing).
        w = w - Q.T @ (Q @ w)
        w = w - Q.T @ (Q @ w)
        b = jnp.linalg.norm(w)
        beta = beta.at[j].set(b)
        qn = jnp.where(b > eps, w / jnp.maximum(b, eps), jnp.zeros_like(w))
        Q = Q.at[j + 1].set(qn)
        return Q, alpha, beta

    return jax.lax.fori_loop(0, m, body, (Q, alpha, beta))


def _ritz(Q, alpha, beta, k: int, largest: bool):
    """Eigenpairs of the projected tridiagonal + Ritz vectors + residuals."""
    m = alpha.shape[0]
    T = (jnp.diag(alpha) + jnp.diag(beta[:m - 1], 1) + jnp.diag(beta[:m - 1], -1))
    evals, S = jnp.linalg.eigh(T)  # ascending
    if largest:
        sel = jnp.arange(m - k, m)[::-1]
    else:
        sel = jnp.arange(k)
    evals, S = evals[sel], S[:, sel]
    vecs = Q[:m].T @ S  # (n, k)
    resid = jnp.abs(beta[m - 1] * S[m - 1, :])
    return evals, vecs, resid


def _lanczos(matvec: Callable, n: int, k: int, *, largest: bool,
             ncv: Optional[int] = None, max_restarts: int = 15,
             tol: float = 1e-6, seed: int = 0, dtype=jnp.float32,
             v0=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    expects(1 <= k < n, "lanczos: need 1 <= k < n")
    # Subspace sizing: larger single rounds beat many small restarted ones
    # on dense bulk spectra (measured on a 3k random-graph Laplacian:
    # ncv=96 was 4.7× faster AND 30× more accurate than ncv=48).
    m = int(ncv) if ncv is not None else min(n - 1, max(4 * k + 32, 64))
    expects(k < m <= n, "lanczos: need k < ncv <= n")
    # f32 residuals bottom out near eps·scale — an unreachable tol would
    # disable convergence detection (and locking) entirely
    tol = max(float(tol), float(jnp.finfo(dtype).eps) * 10)

    if v0 is None:
        v0 = jax.random.normal(jax.random.PRNGKey(seed), (n,), dtype)
    v0 = jnp.asarray(v0, dtype)

    @jax.jit
    def one_round(v0, locked):
        # Deflated operator P·A·P with P = I − UᵀU over the locked Ritz
        # vectors: converged directions are projected out so restarts hunt
        # the REMAINING spectrum — a single weighted restart vector cannot
        # separate clustered eigenvalues (observed: near-degenerate pairs
        # skipped at default ncv).  Valid for the largest-side searches this
        # module performs (deflated directions collapse to eigenvalue 0, at
        # the bottom of the shifted non-negative spectra used here).
        def mv(v):
            v = v - locked.T @ (locked @ v)
            w = matvec(v)
            return w - locked.T @ (locked @ w)

        Q, alpha, beta = _lanczos_decomp(mv, v0, m)
        evals, vecs, resid = _ritz(Q, alpha, beta, k, largest)
        return evals, vecs, resid

    # Restart loop on host (bounded, few iterations); the reference's
    # restarted Lanczos plays the same role (detail/lanczos.cuh:746).
    locked = jnp.zeros((k, n), dtype)
    locked_vals = []
    eps = float(jnp.finfo(dtype).tiny) ** 0.5
    evals, vecs, resid = one_round(v0, locked)
    for _ in range(max_restarts):
        scale = max(float(jnp.max(jnp.abs(evals))),
                    max((abs(v) for v in locked_vals), default=0.0), 1e-30)
        conv = resid <= tol * scale
        # lock converged Ritz pairs (extremal-first order from _ritz)
        for i in range(k):
            if len(locked_vals) >= k:
                break
            if bool(conv[i]):
                u = vecs[:, i]
                u = u - locked.T @ (locked @ u)
                nrm = float(jnp.linalg.norm(u))
                if nrm <= eps:
                    continue  # duplicate of an already-locked vector
                locked = locked.at[len(locked_vals)].set(u / nrm)
                locked_vals.append(float(evals[i]))
        if len(locked_vals) >= k:
            break
        # restart toward the unconverged directions; a collapsed restart
        # vector (rank-deficient remainder) means there is nothing further
        # to extract — stop instead of burning rounds on zero Krylov spaces
        w = jnp.where(conv, 0.0, resid + tol)
        v0 = jnp.sum(vecs * w[None, :], axis=1)
        if float(jnp.linalg.norm(v0)) <= eps:
            break
        evals, vecs, resid = one_round(v0, locked)

    if not locked_vals:
        return evals, vecs
    n_locked = len(locked_vals)
    if n_locked < k:
        # fill with the best unconverged Ritz pairs; if the operator's
        # effective rank ran out (degenerate directions), complete the
        # basis with random orthonormal vectors and their Rayleigh
        # quotients so callers ALWAYS get k columns
        extra_vals, extra_vecs = [], []

        def free_part(u):
            u = u - locked.T @ (locked @ u)
            for v in extra_vecs:
                u = u - v * jnp.dot(v, u)
            return u

        for i in range(k):
            if n_locked + len(extra_vals) >= k:
                break
            u = free_part(vecs[:, i])
            nrm = float(jnp.linalg.norm(u))
            if nrm <= eps:
                continue
            extra_vals.append(float(evals[i]))
            extra_vecs.append(u / nrm)
        key = jax.random.PRNGKey(seed + 1)
        while n_locked + len(extra_vals) < k:
            key, sub = jax.random.split(key)
            u = free_part(jax.random.normal(sub, (n,), dtype))
            nrm = float(jnp.linalg.norm(u))
            if nrm <= eps:
                continue
            u = u / nrm
            extra_vals.append(float(jnp.dot(u, matvec(u))))
            extra_vecs.append(u)
        all_vals = jnp.asarray(locked_vals + extra_vals, dtype)
        all_vecs = jnp.concatenate(
            [locked[:n_locked].T] + [v[:, None] for v in extra_vecs], axis=1)
    else:
        all_vals = jnp.asarray(locked_vals[:k], dtype)
        all_vecs = locked[:k].T
    order = jnp.argsort(-all_vals) if largest else jnp.argsort(all_vals)
    order = order[:k]
    return all_vals[order], all_vecs[:, order]


def lanczos_smallest(a: Union[CSR, Callable], n_components: int, *,
                     n: Optional[int] = None, ncv: Optional[int] = None,
                     max_restarts: int = 15, tol: float = 1e-6,
                     seed: int = 0, v0=None, dtype=jnp.float32):
    """Smallest eigenpairs of a symmetric operator.

    Reference ``computeSmallestEigenvectors`` (sparse/solver/lanczos.cuh:68).
    *a* is a :class:`CSR` or a ``matvec`` callable (pass *n* then).
    Returns (eigenvalues [k] ascending, eigenvectors [n, k]).
    """
    if isinstance(a, CSR):
        n = a.shape[0]
        expects(a.shape[0] == a.shape[1], "lanczos: matrix must be square")
        sigma = _gershgorin_upper(a)
        # one-time ELL conversion (best_matvec): the Krylov loop applies A
        # m x restarts times; scatters must stay out of it on TPU
        mv = best_matvec(a)
        matvec = lambda v: sigma * v - mv(v)  # noqa: E731
        dtype = a.data.dtype
        evals, vecs = _lanczos(matvec, n, n_components, largest=True, ncv=ncv,
                               max_restarts=max_restarts, tol=tol, seed=seed,
                               dtype=dtype, v0=v0)
        return (sigma - evals), vecs
    expects(n is not None, "lanczos with a matvec callable needs n")
    # For a bare operator run on -A and negate.
    neg = lambda v: -a(v)  # noqa: E731
    evals, vecs = _lanczos(neg, n, n_components, largest=True, ncv=ncv,
                           max_restarts=max_restarts, tol=tol, seed=seed,
                           dtype=dtype, v0=v0)
    return -evals, vecs


def lanczos_largest(a: Union[CSR, Callable], n_components: int, *,
                    n: Optional[int] = None, ncv: Optional[int] = None,
                    max_restarts: int = 15, tol: float = 1e-6,
                    seed: int = 0, v0=None, dtype=jnp.float32):
    """Largest eigenpairs (reference ``computeLargestEigenvectors``,
    sparse/solver/lanczos.cuh:132).  Returns (eigenvalues [k] descending,
    eigenvectors [n, k])."""
    if isinstance(a, CSR):
        expects(a.shape[0] == a.shape[1], "lanczos: matrix must be square")
        n = a.shape[0]
        matvec = best_matvec(a)
        dtype = a.data.dtype
    else:
        expects(n is not None, "lanczos with a matvec callable needs n")
        matvec = a
    return _lanczos(matvec, n, n_components, largest=True, ncv=ncv,
                    max_restarts=max_restarts, tol=tol, seed=seed,
                    dtype=dtype, v0=v0)
