"""Restarted Lanczos eigensolver.

Counterpart of reference ``sparse/solver/lanczos.cuh:68,132``
(``computeSmallestEigenvectors`` / ``computeLargestEigenvectors``, impl
``sparse/solver/detail/lanczos.cuh:746,990``): cusparse SpMV + cublas
dots/axpys with host LAPACK ``steqr`` on the tridiagonal problem.

TPU-first redesign:
- The Krylov build runs entirely on device inside ``lax.fori_loop`` — each
  host sync costs far more on TPU than on GPU (SURVEY.md §7 hard parts), so
  the whole m-step decomposition is one XLA computation.
- Full reorthogonalization instead of the reference's selective scheme:
  the extra work is two skinny matmuls per step (``Q @ w``, ``Qᵀ @ proj``)
  that ride the MXU, and it removes the ghost-eigenvalue bookkeeping.
- The projected (tridiagonal) eigenproblem is solved with ``jnp.linalg.eigh``
  on an m×m dense matrix — m is small (≤ a few hundred), the role of host
  LAPACK ``steqr`` in the reference.
- Smallest eigenpairs come from running on the spectral complement
  ``σI − A`` (σ = Gershgorin upper bound) — extremal convergence without
  shift-invert solves.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.sparse.types import CSR
from raft_tpu.sparse.linalg import spmv


def _gershgorin_upper(csr: CSR) -> jnp.ndarray:
    """Upper bound on eigenvalues: max_i (a_ii + Σ_{j≠i} |a_ij|)."""
    rows = csr.row_ids()
    n = csr.shape[0]
    absrow = jax.ops.segment_sum(jnp.abs(csr.data), rows, num_segments=n)
    is_diag = (csr.indices == jnp.clip(rows, 0, n - 1)) & csr.mask()
    diag = jax.ops.segment_sum(jnp.where(is_diag, csr.data, 0), rows,
                               num_segments=n)
    return jnp.max(diag + (absrow - jnp.abs(diag)))


def _lanczos_decomp(matvec, v0, m: int):
    """m-step Lanczos with full reorthogonalization.

    Returns (Q [m+1, n] row-major basis, alpha [m], beta [m]) with
    A qⱼ = βⱼ₋₁qⱼ₋₁ + αⱼqⱼ + βⱼqⱼ₊₁.
    """
    n = v0.shape[0]
    dtype = v0.dtype
    eps = jnp.asarray(jnp.finfo(dtype).tiny ** 0.5, dtype)
    q0 = v0 / jnp.maximum(jnp.linalg.norm(v0), eps)
    Q = jnp.zeros((m + 1, n), dtype).at[0].set(q0)
    alpha = jnp.zeros((m,), dtype)
    beta = jnp.zeros((m,), dtype)

    def body(j, state):
        Q, alpha, beta = state
        v = Q[j]
        w = matvec(v)
        a = jnp.dot(w, v)
        alpha = alpha.at[j].set(a)
        # Two-pass full reorthogonalization against every basis vector built
        # so far (rows > j of Q are zero and contribute nothing).
        w = w - Q.T @ (Q @ w)
        w = w - Q.T @ (Q @ w)
        b = jnp.linalg.norm(w)
        beta = beta.at[j].set(b)
        qn = jnp.where(b > eps, w / jnp.maximum(b, eps), jnp.zeros_like(w))
        Q = Q.at[j + 1].set(qn)
        return Q, alpha, beta

    return jax.lax.fori_loop(0, m, body, (Q, alpha, beta))


def _ritz(Q, alpha, beta, k: int, largest: bool):
    """Eigenpairs of the projected tridiagonal + Ritz vectors + residuals."""
    m = alpha.shape[0]
    T = (jnp.diag(alpha) + jnp.diag(beta[:m - 1], 1) + jnp.diag(beta[:m - 1], -1))
    evals, S = jnp.linalg.eigh(T)  # ascending
    if largest:
        sel = jnp.arange(m - k, m)[::-1]
    else:
        sel = jnp.arange(k)
    evals, S = evals[sel], S[:, sel]
    vecs = Q[:m].T @ S  # (n, k)
    resid = jnp.abs(beta[m - 1] * S[m - 1, :])
    return evals, vecs, resid


def _lanczos(matvec_or_csr, n: int, k: int, *, largest: bool,
             ncv: Optional[int] = None, max_restarts: int = 15,
             tol: float = 1e-6, seed: int = 0, dtype=jnp.float32,
             v0=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    expects(1 <= k < n, "lanczos: need 1 <= k < n")
    m = int(ncv) if ncv is not None else min(n - 1, max(2 * k + 16, 32))
    expects(k < m <= n, "lanczos: need k < ncv <= n")

    if isinstance(matvec_or_csr, CSR):
        csr = matvec_or_csr
        matvec = lambda v: spmv(csr, v)  # noqa: E731
    else:
        matvec = matvec_or_csr

    if v0 is None:
        v0 = jax.random.normal(jax.random.PRNGKey(seed), (n,), dtype)
    v0 = jnp.asarray(v0, dtype)

    @jax.jit
    def one_round(v0):
        Q, alpha, beta = _lanczos_decomp(matvec, v0, m)
        evals, vecs, resid = _ritz(Q, alpha, beta, k, largest)
        return evals, vecs, resid

    # Restart loop on host (bounded, few iterations): restart vector is the
    # sum of current Ritz vectors weighted by residual — the reference's
    # restarted Lanczos plays the same role (detail/lanczos.cuh:746).
    for _ in range(max_restarts):
        evals, vecs, resid = one_round(v0)
        scale = jnp.maximum(jnp.max(jnp.abs(evals)), 1e-30)
        if bool(jnp.max(resid) <= tol * scale):
            break
        v0 = jnp.sum(vecs * (resid + tol)[None, :], axis=1)
    return evals, vecs


def lanczos_smallest(a: Union[CSR, Callable], n_components: int, *,
                     n: Optional[int] = None, ncv: Optional[int] = None,
                     max_restarts: int = 15, tol: float = 1e-6,
                     seed: int = 0, v0=None, dtype=jnp.float32):
    """Smallest eigenpairs of a symmetric operator.

    Reference ``computeSmallestEigenvectors`` (sparse/solver/lanczos.cuh:68).
    *a* is a :class:`CSR` or a ``matvec`` callable (pass *n* then).
    Returns (eigenvalues [k] ascending, eigenvectors [n, k]).
    """
    if isinstance(a, CSR):
        n = a.shape[0]
        expects(a.shape[0] == a.shape[1], "lanczos: matrix must be square")
        sigma = _gershgorin_upper(a)
        matvec = lambda v: sigma * v - spmv(a, v)  # noqa: E731
        dtype = a.data.dtype
        evals, vecs = _lanczos(matvec, n, n_components, largest=True, ncv=ncv,
                               max_restarts=max_restarts, tol=tol, seed=seed,
                               dtype=dtype, v0=v0)
        return (sigma - evals), vecs
    expects(n is not None, "lanczos with a matvec callable needs n")
    # For a bare operator run on -A and negate.
    neg = lambda v: -a(v)  # noqa: E731
    evals, vecs = _lanczos(neg, n, n_components, largest=True, ncv=ncv,
                           max_restarts=max_restarts, tol=tol, seed=seed,
                           dtype=dtype, v0=v0)
    return -evals, vecs


def lanczos_largest(a: Union[CSR, Callable], n_components: int, *,
                    n: Optional[int] = None, ncv: Optional[int] = None,
                    max_restarts: int = 15, tol: float = 1e-6,
                    seed: int = 0, v0=None, dtype=jnp.float32):
    """Largest eigenpairs (reference ``computeLargestEigenvectors``,
    sparse/solver/lanczos.cuh:132).  Returns (eigenvalues [k] descending,
    eigenvectors [n, k])."""
    if isinstance(a, CSR):
        expects(a.shape[0] == a.shape[1], "lanczos: matrix must be square")
        n = a.shape[0]
        matvec = lambda v: spmv(a, v)  # noqa: E731
        dtype = a.data.dtype
    else:
        expects(n is not None, "lanczos with a matvec callable needs n")
        matvec = a
    return _lanczos(matvec, n, n_components, largest=True, ncv=ncv,
                    max_restarts=max_restarts, tol=tol, seed=seed,
                    dtype=dtype, v0=v0)
