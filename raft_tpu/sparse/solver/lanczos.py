"""Restarted Lanczos eigensolver.

Counterpart of reference ``sparse/solver/lanczos.cuh:68,132``
(``computeSmallestEigenvectors`` / ``computeLargestEigenvectors``, impl
``sparse/solver/detail/lanczos.cuh:746,990``): cusparse SpMV + cublas
dots/axpys with host LAPACK ``steqr`` on the tridiagonal problem.

TPU-first redesign:
- The Krylov build runs entirely on device inside ``lax.fori_loop`` — each
  host sync costs far more on TPU than on GPU (SURVEY.md §7 hard parts), so
  the whole m-step decomposition is one XLA computation.
- Full reorthogonalization instead of the reference's selective scheme:
  the extra work is two skinny matmuls per step (``Q @ w``, ``Qᵀ @ proj``)
  that ride the MXU, and it removes the ghost-eigenvalue bookkeeping.
- The projected (tridiagonal) eigenproblem is solved with ``jnp.linalg.eigh``
  on an m×m dense matrix — m is small (≤ a few hundred), the role of host
  LAPACK ``steqr`` in the reference.
- Smallest eigenpairs come from running on the spectral complement
  ``σI − A`` (σ = Gershgorin upper bound) — extremal convergence without
  shift-invert solves.
"""

from __future__ import annotations

import functools
import inspect
import weakref
from typing import Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from raft_tpu.linalg.reduce import segment_sum
import numpy as np

from raft_tpu.core.error import expects
from raft_tpu.core.logger import traced
from raft_tpu.sparse.types import CSR
from raft_tpu.sparse.linalg import apply_matvec, matvec_operand


# --- static operator appliers -----------------------------------------------
# Module-level (stable-identity) so _solve_program's jit cache is reused
# across solves; a per-call closure would retrace/recompile every call.
# CSR operators use sparse.linalg's (matvec_operand, apply_matvec) pair:
# one-time host-side ELL conversion, scatter-free in the Krylov loop.

def _apply_shifted_neg(op, v):
    """(σ, A) → σ·v − A·v: the spectral complement used for smallest-side
    searches (extremal convergence without shift-invert solves)."""
    sigma, inner = op
    return sigma * v - apply_matvec(inner, v)


def _gershgorin_upper(csr: CSR) -> jnp.ndarray:
    """Upper bound on eigenvalues: max_i (a_ii + Σ_{j≠i} |a_ij|)."""
    rows = csr.row_ids()
    n = csr.shape[0]
    absrow = segment_sum(jnp.abs(csr.data), rows, n)
    is_diag = (csr.indices == jnp.clip(rows, 0, n - 1)) & csr.mask()
    diag = segment_sum(jnp.where(is_diag, csr.data, 0), rows,
                               n)
    return jnp.max(diag + (absrow - jnp.abs(diag)))


def _lanczos_decomp(matvec, v0, m: int):
    """m-step Lanczos with full reorthogonalization.

    Returns (Q [m+1, n] row-major basis, alpha [m], beta [m]) with
    A qⱼ = βⱼ₋₁qⱼ₋₁ + αⱼqⱼ + βⱼqⱼ₊₁.
    """
    n = v0.shape[0]
    dtype = v0.dtype
    tiny = jnp.asarray(jnp.finfo(dtype).tiny ** 0.5, dtype)
    ulp = jnp.asarray(jnp.finfo(dtype).eps, dtype)
    q0 = v0 / jnp.maximum(jnp.linalg.norm(v0), tiny)
    Q = jnp.zeros((m + 1, n), dtype).at[0].set(q0)
    alpha = jnp.zeros((m,), dtype)
    beta = jnp.zeros((m,), dtype)

    def body(j, state):
        Q, alpha, beta = state
        v = Q[j]
        w = matvec(v)
        a = jnp.dot(w, v)
        alpha = alpha.at[j].set(a)
        # Two-pass full reorthogonalization against every basis vector built
        # so far (rows > j of Q are zero and contribute nothing).
        w = w - Q.T @ (Q @ w)
        w = w - Q.T @ (Q @ w)
        b = jnp.linalg.norm(w)
        # Breakdown test must be RELATIVE to the recurrence scale: comparing
        # against tiny**0.5 lets reorthogonalization noise (~ulp·scale, i.e.
        # ~1e-13 after an exact invariant-subspace breakdown) be normalized
        # into a garbage basis vector, after which the recurrence explodes
        # (observed: beta growing to ~1e3 on a rank-1 operator of norm 5).
        # A spurious-zero qn is harmless: the remaining steps stay zero and
        # T decouples.
        scale = jnp.maximum(jnp.max(jnp.abs(alpha)), jnp.max(beta))
        good = b > 128.0 * ulp * jnp.maximum(scale, tiny)
        beta = beta.at[j].set(jnp.where(good, b, jnp.asarray(0, dtype)))
        qn = jnp.where(good, w / jnp.maximum(b, tiny), jnp.zeros_like(w))
        Q = Q.at[j + 1].set(qn)
        return Q, alpha, beta

    return jax.lax.fori_loop(0, m, body, (Q, alpha, beta))


def _ritz(Q, alpha, beta, k: int, largest: bool):
    """Eigenpairs of the projected tridiagonal + Ritz vectors + residuals."""
    m = alpha.shape[0]
    T = (jnp.diag(alpha) + jnp.diag(beta[:m - 1], 1) + jnp.diag(beta[:m - 1], -1))
    evals, S = jnp.linalg.eigh(T)  # ascending
    if largest:
        sel = jnp.arange(m - k, m)[::-1]
    else:
        sel = jnp.arange(k)
    evals, S = evals[sel], S[:, sel]
    vecs = Q[:m].T @ S  # (n, k)
    resid = jnp.abs(beta[m - 1] * S[m - 1, :])
    return evals, vecs, resid


# Incremented each time _solve_impl is TRACED (its Python body runs only at
# trace time) — lets tests assert jit-cache reuse without private JAX APIs.
_trace_count = 0


def _solve_impl(operator, v0, tol, max_restarts, *, apply_fn: Callable,
                k: int, m: int, largest: bool):
    """The ENTIRE restarted solve as one compiled program.

    The reference drives restarts from the host (detail/lanczos.cuh:746);
    here the restart+locking loop is a ``lax.while_loop`` so a solve costs
    one dispatch and zero per-restart host syncs — on a remote-attached TPU
    the old host loop's ~15 scalar pulls per restart dominated solve time.

    ``apply_fn(operator, v)`` applies A; it is a STATIC module-level
    function so repeated solves (same shapes) reuse the jit cache — a
    per-call closure would retrace every time.  ``tol``/``max_restarts``
    are dynamic scalar operands for the same reason: sweeping tolerances
    must not recompile the program.
    """
    global _trace_count
    _trace_count += 1
    n = v0.shape[0]
    dtype = v0.dtype
    eps = jnp.asarray(jnp.finfo(dtype).tiny ** 0.5, dtype)
    ulp = jnp.asarray(jnp.finfo(dtype).eps, dtype)

    # Warm the operator ONCE at this (outer) trace level: a user callable
    # that lazily memoizes state on first use (e.g. building a converted
    # layout in a closure cell) must not capture that state inside one
    # sub-trace (the first one_round's fori_loop) and replay it in a
    # sibling sub-trace (the restart loop's lax.cond branch) — that is a
    # tracer leak.  The result is unused and DCE'd; only the trace-time
    # side effect matters.
    apply_fn(operator, jnp.zeros_like(v0))

    def one_round(v0, locked):
        # Deflated operator P·A·P with P = I − UᵀU over the locked Ritz
        # vectors: converged directions are projected out so restarts hunt
        # the REMAINING spectrum — a single weighted restart vector cannot
        # separate clustered eigenvalues (observed: near-degenerate pairs
        # skipped at default ncv).  Valid for the largest-side searches this
        # module performs (deflated directions collapse to eigenvalue 0, at
        # the bottom of the shifted non-negative spectra used here).
        def mv(v):
            v = v - locked.T @ (locked @ v)
            w = apply_fn(operator, v)
            return w - locked.T @ (locked @ w)

        Q, alpha, beta = _lanczos_decomp(mv, v0, m)
        return _ritz(Q, alpha, beta, k, largest)

    locked0 = jnp.zeros((k, n), dtype)
    lvals0 = jnp.zeros((k,), dtype)
    evals0, vecs0, resid0 = one_round(v0, locked0)
    state0 = (jnp.asarray(0), v0, locked0, lvals0, jnp.asarray(0),
              evals0, vecs0, resid0, jnp.asarray(False))

    def cond(state):
        it, *_, done = state
        return (it < max_restarts) & ~done

    def body(state):
        it, v0, locked, lvals, nl, evals, vecs, resid, _ = state
        slot = jnp.arange(k)
        scale = jnp.maximum(jnp.max(jnp.abs(evals)),
                            jnp.max(jnp.where(slot < nl, jnp.abs(lvals), 0.0)))
        scale = jnp.maximum(scale, 1e-30)
        conv = resid <= tol * scale

        # lock converged Ritz pairs (extremal-first order from _ritz);
        # re-orthogonalize against already-locked vectors, skip duplicates
        def lock_one(carry, i):
            locked, lvals, nl = carry
            u = vecs[:, i]
            u = u - locked.T @ (locked @ u)
            nrm = jnp.linalg.norm(u)
            # Duplicate test must be RELATIVE, like the breakdown test in
            # _lanczos_decomp: a Ritz vector duplicating a locked one leaves
            # a projected remainder of ~ulp (u is unit norm), far above the
            # absolute tiny**0.5 (~1e-19 f32) — which would normalize that
            # noise and lock it as a spurious eigenvector.
            take = conv[i] & (nl < k) & (nrm > 128.0 * ulp)
            cand = locked.at[nl].set(u / jnp.maximum(nrm, eps))
            locked = jnp.where(take, cand, locked)
            lvals = jnp.where(take, lvals.at[nl].set(evals[i]), lvals)
            return (locked, lvals, nl + take.astype(nl.dtype)), None

        (locked, lvals, nl), _ = jax.lax.scan(lock_one, (locked, lvals, nl),
                                              jnp.arange(k))
        # restart toward the unconverged directions; a collapsed restart
        # vector (rank-deficient remainder) means there is nothing further
        # to extract — stop instead of burning rounds on zero Krylov spaces
        w = jnp.where(conv, jnp.asarray(0, dtype), resid + tol)
        v0n = vecs @ w
        done = (nl >= k) | (jnp.linalg.norm(v0n) <= eps)
        evals, vecs, resid = jax.lax.cond(
            done, lambda a, b: (evals, vecs, resid), one_round, v0n, locked)
        return (it + 1, v0n, locked, lvals, nl, evals, vecs, resid, done)

    (_, _, locked, lvals, nl, evals, vecs, resid, _) = jax.lax.while_loop(
        cond, body, state0)
    return evals, vecs, resid, locked, lvals, nl


# Module-level program for the static appliers: every solve with the same
# shape signature reuses one compiled executable.
_solve_program = jax.jit(_solve_impl,
                         static_argnames=("apply_fn", "k", "m", "largest"))


@functools.partial(jax.jit, static_argnames=("apply_fn", "iters"))
def _power_repair(operator, basis, u0, shift, eps, *, apply_fn: Callable,
                  iters: int = 64):
    """64 rounds of deflated, spectrum-shifted power iteration — the
    multiplicity-repair engine of :func:`_lanczos`'s host tail.  *basis* is
    a fixed-capacity (cap, n) projector (zero rows are no-ops) so every
    repair attempt of a solve reuses ONE compiled program."""
    def body(_, u):
        w = apply_fn(operator, u) + shift * u
        w = w - basis.T @ (basis @ w)
        nrm = jnp.linalg.norm(w)
        return jnp.where(nrm > eps, w / jnp.maximum(nrm, eps), u)

    return jax.lax.fori_loop(0, iters, body, u0)


def _apply_partial(op, v):
    """op is a ``jax.tree_util.Partial`` riding through jit as a DYNAMIC
    operand: its captured arrays are traced leaves and its wrapped function
    is part of the (stable) treedef — so Partial-based operators (e.g.
    spectral.laplacian_matvec) share one compiled solve across graphs."""
    return op(v)


def _apply_partial_neg(op, v):
    return -op(v)


_STATIC_APPLIERS = (apply_matvec, _apply_shifted_neg, _apply_partial,
                    _apply_partial_neg)

# Per-user-callable programs, keyed by the callable's IDENTITY (id()) —
# __eq__-based keying would let two equal-but-distinct callables share one
# program whose trace baked the FIRST one's data in as constants.  A
# weakref finalizer evicts the entry when the callable dies, releasing the
# compiled program (and the operand buffers embedded in it); the entry
# itself references the callable only weakly.
_CALLABLE_PROGS: dict = {}


def _callable_entry(a: Callable, negate: bool):
    """(apply_fn, program) for a plain user matvec callable.

    Bound methods get special keying: ``obj.method`` creates a FRESH
    bound-method object on every attribute access, so an ``id(a)`` key
    would be evicted the moment the call returns and every solve with the
    "same" method would silently retrace.  Key on (owner id, underlying
    function) and weakref the owner instead.
    """
    bound = inspect.ismethod(a)
    anchor = a.__self__ if bound else a
    key = (id(anchor), a.__func__) if bound else id(anchor)
    entry = _CALLABLE_PROGS.get(key)
    if entry is None:
        recordable = True
        try:
            ref = weakref.ref(anchor)
            weakref.finalize(anchor, _CALLABLE_PROGS.pop, key, None)
        except TypeError:  # unweakrefable: per-call entry, dies with frame
            recordable = False
            ref = lambda anchor=anchor: anchor  # noqa: E731

        if bound:
            func = a.__func__

            def apply_pos(op, v):
                return func(ref(), v)

            def apply_neg(op, v):
                return -func(ref(), v)
        else:

            def apply_pos(op, v):
                return ref()(v)

            def apply_neg(op, v):
                return -ref()(v)

        entry = {}
        for neg, fn in ((False, apply_pos), (True, apply_neg)):
            entry[neg] = (fn, jax.jit(
                functools.partial(_solve_impl, apply_fn=fn),
                static_argnames=("k", "m", "largest")))
        if recordable:
            _CALLABLE_PROGS[key] = entry
    return entry[negate]


def _solve(apply_fn, operator, v0, tol, max_restarts, *, program=None, **kw):
    if program is not None:
        return program(operator, v0, tol, max_restarts, **kw)
    if apply_fn in _STATIC_APPLIERS:
        return _solve_program(operator, v0, tol, max_restarts,
                              apply_fn=apply_fn, **kw)
    # Anonymous applier (e.g. internal tests): per-call jit, released with
    # this frame.
    prog = jax.jit(functools.partial(_solve_impl, apply_fn=apply_fn),
                   static_argnames=("k", "m", "largest"))
    return prog(operator, v0, tol, max_restarts, **kw)


def _lanczos(apply_fn: Callable, operator, n: int, k: int, *, largest: bool,
             ncv: Optional[int] = None, max_restarts: int = 15,
             tol: float = 1e-6, seed: int = 0, dtype=jnp.float32,
             v0=None, program=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Driver: one :func:`_solve_program` dispatch + host-side tail repair.

    ``apply_fn(operator, v)`` applies A.  Compiled-program reuse: the
    appliers in ``_STATIC_APPLIERS`` share the module-level jit; a
    ``program`` from :func:`_callable_entry` is reused per callable; any
    other apply_fn retraces per call.
    """
    expects(1 <= k < n, "lanczos: need 1 <= k < n")
    # Subspace sizing: larger single rounds beat many small restarted ones
    # on dense bulk spectra (measured on a 3k random-graph Laplacian:
    # ncv=96 was 4.7× faster AND 30× more accurate than ncv=48).
    m = int(ncv) if ncv is not None else min(n - 1, max(4 * k + 32, 64))
    expects(k < m <= n, "lanczos: need k < ncv <= n")
    # f32 residuals bottom out near eps·scale — an unreachable tol would
    # disable convergence detection (and locking) entirely
    tol = max(float(tol), float(jnp.finfo(dtype).eps) * 10)

    if v0 is None:
        v0 = jax.random.normal(jax.random.PRNGKey(seed), (n,), dtype)
    v0 = jnp.asarray(v0, dtype)

    evals, vecs, resid, locked, lvals, nl = _solve(
        apply_fn, operator, v0, jnp.asarray(tol, dtype), max_restarts,
        program=program, k=k, m=m, largest=largest)

    eps = float(jnp.finfo(dtype).tiny) ** 0.5
    ulp = float(jnp.finfo(dtype).eps)
    n_locked = int(nl)  # the solve's single host sync
    if n_locked == 0:
        return evals, vecs
    if n_locked >= k:  # success path: stay on device, no further sync
        order = jnp.argsort(-lvals[:k]) if largest else jnp.argsort(lvals[:k])
        return lvals[:k][order], locked[:k].T[:, order]
    locked_vals = [float(v) for v in np.asarray(lvals)[:n_locked]]

    # Partial convergence (rare): fill with the best unconverged Ritz pairs;
    # if the operator's effective rank ran out (degenerate directions),
    # complete via deflated power iteration from random restarts so callers
    # ALWAYS get k columns of actual eigenvector quality.
    extra_vals, extra_vecs = [], []

    def free_part(u):
        u = u - locked.T @ (locked @ u)
        for v in extra_vecs:
            u = u - v * jnp.dot(v, u)
        return u

    for i in range(k):
        if n_locked + len(extra_vals) >= k:
            break
        u = free_part(vecs[:, i])
        nrm = float(jnp.linalg.norm(u))
        # RELATIVE duplicate test (Ritz vectors are unit norm): a Ritz pair
        # duplicating a locked one leaves ~ulp projected remainder, far
        # above the absolute tiny**0.5 — normalizing that noise would
        # report a spurious eigenvector under a converged eigenvalue.
        if nrm <= 128.0 * ulp:
            continue
        extra_vals.append(float(evals[i]))
        extra_vecs.append(u / nrm)

    # Eigenvalue multiplicity repair: a direction degenerate with a locked
    # eigenvalue is UNREACHABLE from the original Krylov sequence (invariant
    # subspace — restarts stay inside it up to rounding noise), so the solve
    # can exhaust restarts with nl < k.  Power-iterate random restarts on
    # the deflated, spectrum-shifted operator: each converges to the
    # DOMINANT remaining eigendirection, with its honest Rayleigh quotient
    # as the value.  Keep repairing while the newly found direction beats
    # the current k-th best — an inferior pair locked early (e.g. a
    # 0-eigenvector of a low-rank operator) must not displace a
    # still-missing degenerate extremal copy; the final top-k sort below
    # drops the loser.
    shift_mag = max(
        float(np.max(np.abs(np.asarray(lvals)[:max(n_locked, 1)]))),
        float(np.max(np.abs(np.asarray(evals)))), 1.0)
    # largest: shift up so the largest algebraic eigenvalue dominates in
    # magnitude; plain `largest=False` solves shift down symmetrically
    # (smallest-eigenpair callers already negate via apply_fn).
    shift = jnp.asarray(shift_mag if largest else -shift_mag, dtype)
    sign = 1.0 if largest else -1.0

    key = jax.random.PRNGKey(seed + 1)
    margin = float(tol) * shift_mag
    attempts = 2 * k + 4  # bound on repair attempts
    cap = k + attempts    # fixed deflation-basis capacity: ONE compile of
    #                       the repair program per solve signature (a
    #                       per-attempt basis shape would retrace each time)
    eps_arr = jnp.asarray(eps, dtype)
    for _ in range(attempts):
        # Deflate against everything found so far INCLUDING previous repairs:
        # without the extras in the projector, iteration re-converges onto an
        # already-repaired direction and its final free_part leaves noise.
        basis = (locked if not extra_vecs
                 else jnp.concatenate([locked, jnp.stack(extra_vecs)], axis=0))
        basis = jnp.pad(basis, ((0, cap - basis.shape[0]), (0, 0)))

        key, sub = jax.random.split(key)
        u = free_part(jax.random.normal(sub, (n,), dtype))
        nrm = float(jnp.linalg.norm(u))
        if nrm <= eps:
            break  # deflated space exhausted
        u = _power_repair(operator, basis, u / nrm, shift, eps_arr,
                          apply_fn=apply_fn)
        u = free_part(u)
        nrm = float(jnp.linalg.norm(u))
        if nrm <= eps:
            break
        u = u / nrm
        lam = float(jnp.dot(u, apply_fn(operator, u)))
        if n_locked + len(extra_vals) >= k:
            # basis already full: keep hunting only while each new dominant
            # remaining direction still beats the current k-th best value
            cur = sorted(locked_vals + extra_vals, key=lambda v: -sign * v)
            if sign * lam <= sign * cur[k - 1] + margin:
                break  # no better than what we already return
        extra_vals.append(lam)
        extra_vecs.append(u)
    all_vals = jnp.asarray(locked_vals + extra_vals, dtype)
    all_vecs = jnp.concatenate(
        [locked[:n_locked].T] + [v[:, None] for v in extra_vecs], axis=1)
    order = jnp.argsort(-all_vals) if largest else jnp.argsort(all_vals)
    order = order[:k]
    return all_vals[order], all_vecs[:, order]


@traced("raft_tpu.sparse.lanczos_smallest")
def lanczos_smallest(a: Union[CSR, Callable], n_components: int, *,
                     n: Optional[int] = None, ncv: Optional[int] = None,
                     max_restarts: int = 15, tol: float = 1e-6,
                     seed: int = 0, v0=None, dtype=jnp.float32):
    """Smallest eigenpairs of a symmetric operator.

    Reference ``computeSmallestEigenvectors`` (sparse/solver/lanczos.cuh:68).
    *a* is a :class:`CSR` or a ``matvec`` callable (pass *n* then).
    Returns (eigenvalues [k] ascending, eigenvectors [n, k]).

    A plain callable must be PURE over immutable captured state: its solve
    program is cached per callable, with captured arrays baked in as
    constants — mutating them between solves returns stale results.  For
    operator data that varies between solves, pass a
    ``jax.tree_util.Partial`` (its arrays are dynamic operands).
    """
    if isinstance(a, CSR):
        n = a.shape[0]
        expects(a.shape[0] == a.shape[1], "lanczos: matrix must be square")
        sigma = _gershgorin_upper(a)
        dtype = a.data.dtype
        evals, vecs = _lanczos(_apply_shifted_neg, (sigma, matvec_operand(a)),
                               n, n_components, largest=True, ncv=ncv,
                               max_restarts=max_restarts, tol=tol, seed=seed,
                               dtype=dtype, v0=v0)
        return (sigma - evals), vecs
    expects(n is not None, "lanczos with a matvec callable needs n")
    # For a bare operator run on -A and negate.  A jax.tree_util.Partial
    # rides through jit as a dynamic operand (one compiled program across
    # operators); other callables get a weak-cached per-callable program.
    if isinstance(a, jax.tree_util.Partial):
        apply_fn, op, program = _apply_partial_neg, a, None
    else:
        (apply_fn, program), op = _callable_entry(a, negate=True), ()
    evals, vecs = _lanczos(apply_fn, op, n, n_components, largest=True,
                           ncv=ncv, max_restarts=max_restarts, tol=tol,
                           seed=seed, dtype=dtype, v0=v0, program=program)
    return -evals, vecs


@traced("raft_tpu.sparse.lanczos_largest")
def lanczos_largest(a: Union[CSR, Callable], n_components: int, *,
                    n: Optional[int] = None, ncv: Optional[int] = None,
                    max_restarts: int = 15, tol: float = 1e-6,
                    seed: int = 0, v0=None, dtype=jnp.float32):
    """Largest eigenpairs (reference ``computeLargestEigenvectors``,
    sparse/solver/lanczos.cuh:132).  Returns (eigenvalues [k] descending,
    eigenvectors [n, k]).  Same callable contract as
    :func:`lanczos_smallest`: plain callables must be pure over immutable
    captured state; use ``jax.tree_util.Partial`` for varying data."""
    if isinstance(a, CSR):
        expects(a.shape[0] == a.shape[1], "lanczos: matrix must be square")
        n = a.shape[0]
        return _lanczos(apply_matvec, matvec_operand(a), n, n_components,
                        largest=True, ncv=ncv, max_restarts=max_restarts,
                        tol=tol, seed=seed, dtype=a.data.dtype, v0=v0)
    expects(n is not None, "lanczos with a matvec callable needs n")
    if isinstance(a, jax.tree_util.Partial):  # shared compiled program
        return _lanczos(_apply_partial, a, n, n_components, largest=True,
                        ncv=ncv, max_restarts=max_restarts, tol=tol,
                        seed=seed, dtype=dtype, v0=v0)
    apply_fn, program = _callable_entry(a, negate=False)
    return _lanczos(apply_fn, (), n, n_components, largest=True, ncv=ncv,
                    max_restarts=max_restarts, tol=tol, seed=seed,
                    dtype=dtype, v0=v0, program=program)
