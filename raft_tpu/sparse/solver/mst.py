"""Minimum spanning tree/forest — parallel Borůvka.

Counterpart of reference ``sparse/solver/mst_solver.cuh:40`` (``MST_solver``,
kernels ``solver/detail/mst_kernels.cuh``, alterated-weight tie-breaking
``mst_utils.cuh``).

TPU-first redesign: the reference's per-vertex CUDA kernels (min-edge-
per-supervertex, cycle removal, pointer-jumping label merge) become
whole-array XLA ops inside one ``lax.while_loop`` — segment reductions via
stable sorts, scatter for per-color winners, and pointer jumping as an
inner ``while_loop``.  Tie-breaking uses lexicographic (weight, min(u,v),
max(u,v)) via chained stable argsorts instead of the reference's epsilon
"alteration" of weights — a strict total order on undirected edges, so the
per-color minimum-edge choice is consistent across both directed copies
and the selected edge set is a forest (plus 2-cycles, removed explicitly,
same as the reference's cycle-elimination kernel).

Everything is static-shape: edge capacity E, MST capacity n−1 with a live
count, colors as an (n,) labeling — a spanning *forest* falls out naturally
for disconnected graphs (reference returns n−1−n_components edges likewise).
"""

from __future__ import annotations

from typing import NamedTuple, Union

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.sparse.types import COO, CSR
from raft_tpu.sparse.convert import csr_to_coo


class MSTResult(NamedTuple):
    """Spanning forest edges (capacity n−1, live entries first) + labels."""

    src: jnp.ndarray      # (n-1,) int32; padding = n
    dst: jnp.ndarray      # (n-1,) int32; padding = n
    weight: jnp.ndarray   # (n-1,); padding = 0
    n_edges: jnp.ndarray  # int32 scalar — live edge count
    color: jnp.ndarray    # (n,) int32 component label per vertex


def boruvka_mst(g: Union[COO, CSR]) -> MSTResult:
    """MST/MSF of a symmetric weighted graph (both directed copies present,
    as the reference requires — mst_solver.cuh:40 takes a symmetrized CSR).
    """
    coo = csr_to_coo(g) if isinstance(g, CSR) else g
    expects(coo.shape[0] == coo.shape[1], "boruvka_mst: graph must be square")
    n = coo.shape[0]
    e = coo.capacity
    u, v, w = coo.rows, coo.cols, coo.vals
    # Robust to non-compacted inputs (merged edge lists): an entry is live
    # iff its endpoints are in range — padding carries the row==n sentinel.
    live = (u >= 0) & (u < n) & (v >= 0) & (v < n)
    # Canonical undirected identity for tie-breaking.
    minuv = jnp.minimum(u, v)
    maxuv = jnp.maximum(u, v)
    inf = jnp.asarray(jnp.inf, w.dtype)

    def round_body(state):
        color, msrc, mdst, mw, count, _changed = state
        cu = color[jnp.clip(u, 0, n - 1)]
        cv = color[jnp.clip(v, 0, n - 1)]
        cross = live & (cu != cv)

        # Sort edges by (color; weight; canonical id) — least-significant
        # keys first, each pass stable.
        order = jnp.argsort(maxuv, stable=True)
        order = order[jnp.argsort(minuv[order], stable=True)]
        wk = jnp.where(cross, w, inf)
        order = order[jnp.argsort(wk[order], stable=True)]
        ck = jnp.where(cross, cu, n)
        order = order[jnp.argsort(ck[order], stable=True)]

        ck_s = ck[order]
        first = jnp.concatenate([jnp.ones((1,), bool), ck_s[1:] != ck_s[:-1]])
        first &= ck_s < n
        # Per-color winning edge (original index); colors without a cross
        # edge keep sentinel E.
        sel = jnp.full((n,), e, jnp.int32).at[
            jnp.where(first, ck_s, n)].set(order.astype(jnp.int32), mode="drop")
        any_cross = jnp.any(sel < e)

        # parent[c] = color at the other end of c's winning edge.
        has = sel < e
        sel_safe = jnp.clip(sel, 0, e - 1)
        other = jnp.where(has, cv[sel_safe], jnp.arange(n, dtype=jnp.int32))
        parent = other
        # Remove 2-cycles (mutual minimum pairs): smaller color becomes root
        # (reference mst_kernels.cuh cycle elimination).
        gp = parent[jnp.clip(parent, 0, n - 1)]
        iota = jnp.arange(n, dtype=jnp.int32)
        is_cycle = (gp == iota) & (iota < parent)
        parent = jnp.where(is_cycle, iota, parent)

        # Pointer-jump to roots.
        def pj_cond(p):
            return jnp.any(p[jnp.clip(p, 0, n - 1)] != p)

        def pj_body(p):
            return p[jnp.clip(p, 0, n - 1)]

        roots = jax.lax.while_loop(pj_cond, pj_body, parent)

        # Accepted edges: the distinct winners.  With a strict total order a
        # mutual (2-cycle) pair necessarily picks the same undirected edge
        # through its two directed copies — dropping the root side's mark
        # adds it exactly once.
        mark = has & ~is_cycle
        # Scatter True only at winning edges (index e for non-winners →
        # dropped); writing `mark` at clipped indices would let a False from
        # a cross-edge-less color clobber a real winner at buffer slot e-1.
        chosen = jnp.zeros((e,), bool).at[
            jnp.where(mark, sel, e)].set(True, mode="drop")
        chosen &= live
        # Compact accepted edges to positions count..count+k-1 of the MST.
        pos = count + jnp.cumsum(chosen.astype(jnp.int32)) - 1
        pos = jnp.where(chosen, pos, n)  # out-of-range → dropped by scatter
        msrc = msrc.at[pos].set(u.astype(jnp.int32), mode="drop")
        mdst = mdst.at[pos].set(v.astype(jnp.int32), mode="drop")
        mw = mw.at[pos].set(w, mode="drop")
        count = count + jnp.sum(chosen, dtype=jnp.int32)

        new_color = roots[jnp.clip(color, 0, n - 1)]
        return new_color, msrc, mdst, mw, count, any_cross

    def cond(state):
        return state[5]

    init = (jnp.arange(n, dtype=jnp.int32),
            jnp.full((n - 1,), n, jnp.int32),
            jnp.full((n - 1,), n, jnp.int32),
            jnp.zeros((n - 1,), w.dtype),
            jnp.zeros((), jnp.int32),
            jnp.asarray(True))
    color, msrc, mdst, mw, count, _ = jax.lax.while_loop(cond, round_body, init)
    return MSTResult(msrc, mdst, mw, count, color)


def sorted_mst_edges(result: MSTResult):
    """MST edges sorted ascending by weight (reference
    cluster/detail/mst.cuh ``build_sorted_mst`` sorts before the dendrogram
    stage).  Padding (weight 0 at src == n) is pushed to the tail."""
    wk = jnp.where(jnp.arange(result.src.shape[0]) < result.n_edges,
                   result.weight, jnp.inf)
    order = jnp.argsort(wk, stable=True)
    return result.src[order], result.dst[order], result.weight[order]
