"""Sparse containers: fixed-capacity COO and CSR.

Counterpart of reference ``sparse/coo.hpp`` (``COO`` with preallocated
device buffers + ``setSize``) and ``sparse/csr.hpp``.  Registered as JAX
pytrees so they flow through ``jit``/``vmap``/``shard_map``; the matrix
shape is static aux data, the buffers are leaves.

Padding convention (module doc of :mod:`raft_tpu.sparse`): entries at
positions ``>= nnz`` hold ``row == n_rows, col == 0, val == 0``.  ``nnz``
is carried as a traced scalar so structural ops (filter, dedupe) stay
jittable; capacity (buffer length) is static.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects


@jax.tree_util.register_pytree_node_class
class COO:
    """Coordinate-format sparse matrix with fixed capacity.

    Attributes:
      rows, cols: int32 (capacity,) coordinate buffers.
      vals: (capacity,) values.
      nnz: traced int32 scalar — number of live entries (<= capacity).
      shape: static (n_rows, n_cols).
    """

    def __init__(self, rows, cols, vals, shape: Tuple[int, int], nnz=None):
        self.rows = jnp.asarray(rows, jnp.int32)
        self.cols = jnp.asarray(cols, jnp.int32)
        self.vals = jnp.asarray(vals)
        self.shape = (int(shape[0]), int(shape[1]))
        self.nnz = jnp.asarray(self.rows.shape[0] if nnz is None else nnz, jnp.int32)

    @property
    def capacity(self) -> int:
        return self.rows.shape[0]

    @property
    def dtype(self):
        return self.vals.dtype

    def mask(self):
        """Boolean (capacity,) mask of live entries."""
        return jnp.arange(self.capacity) < self.nnz

    def tree_flatten(self):
        return (self.rows, self.cols, self.vals, self.nnz), self.shape

    @classmethod
    def tree_unflatten(cls, shape, leaves):
        rows, cols, vals, nnz = leaves
        obj = object.__new__(cls)
        obj.rows, obj.cols, obj.vals, obj.nnz = rows, cols, vals, nnz
        obj.shape = shape
        return obj

    def __repr__(self):
        return (f"COO(shape={self.shape}, capacity={self.capacity}, "
                f"dtype={self.vals.dtype})")


@jax.tree_util.register_pytree_node_class
class CSR:
    """Compressed-sparse-row matrix with fixed capacity.

    ``indptr`` is (n_rows+1,) with ``indptr[-1] == nnz``; ``indices``/
    ``data`` have static length ``capacity >= nnz`` with zero tail padding.
    """

    def __init__(self, indptr, indices, data, shape: Tuple[int, int]):
        self.indptr = jnp.asarray(indptr, jnp.int32)
        self.indices = jnp.asarray(indices, jnp.int32)
        self.data = jnp.asarray(data)
        self.shape = (int(shape[0]), int(shape[1]))
        expects(self.indptr.shape[0] == self.shape[0] + 1,
                "CSR indptr must have n_rows+1 entries")

    @property
    def capacity(self) -> int:
        return self.indices.shape[0]

    @property
    def nnz(self):
        return self.indptr[-1]

    @property
    def dtype(self):
        return self.data.dtype

    def row_ids(self):
        """int32 (capacity,) row index per entry; padding maps to n_rows
        (dropped by segment ops with num_segments == n_rows)."""
        return jnp.searchsorted(
            self.indptr, jnp.arange(self.capacity, dtype=jnp.int32), side="right"
        ).astype(jnp.int32) - 1

    def mask(self):
        return jnp.arange(self.capacity) < self.nnz

    def tree_flatten(self):
        return (self.indptr, self.indices, self.data), self.shape

    @classmethod
    def tree_unflatten(cls, shape, leaves):
        obj = object.__new__(cls)
        obj.indptr, obj.indices, obj.data = leaves
        obj.shape = shape
        return obj

    def __repr__(self):
        return (f"CSR(shape={self.shape}, capacity={self.capacity}, "
                f"dtype={self.data.dtype})")
