"""raft_tpu.spectral — spectral graph partitioning and modularity clustering.

Counterpart of reference ``raft/spectral/`` (SURVEY.md §2.11):
pluggable eigen/cluster solvers (``spectral/eigen_solvers.cuh:45``,
``cluster_solvers.cuh:43``), ``partition()``
(``spectral/detail/partition.hpp:65-107``), ``modularity_maximization()``
(``spectral/modularity_maximization.cuh:47-77``) and the partition quality
metrics ``analyze_partition`` / ``analyze_modularity``.
"""

from raft_tpu.spectral.matrix import (
    degrees,
    laplacian_matvec,
    modularity_matvec,
)
from raft_tpu.spectral.solvers import (
    EigenSolverConfig,
    LanczosEigenSolver,
    ClusterSolverConfig,
    KMeansClusterSolver,
)
from raft_tpu.spectral.partition import (
    partition,
    modularity_maximization,
    analyze_partition,
    analyze_modularity,
)

__all__ = [
    "degrees",
    "laplacian_matvec",
    "modularity_matvec",
    "EigenSolverConfig",
    "LanczosEigenSolver",
    "ClusterSolverConfig",
    "KMeansClusterSolver",
    "partition",
    "modularity_maximization",
    "analyze_partition",
    "analyze_modularity",
]
