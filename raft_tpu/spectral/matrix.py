"""Implicit spectral operators over a CSR adjacency matrix.

Counterpart of reference ``spectral/matrix_wrappers.hpp:41-45``
(``sparse_matrix_t`` / ``laplacian_matrix_t`` / ``modularity_matrix_t``):
the reference wraps cusparse SpMV and overrides ``mv`` so the Lanczos solver
sees ``L·x`` or ``B·x`` without materializing L or B.  TPU-first the same
idea is a closure over :func:`raft_tpu.sparse.linalg.spmv` — XLA fuses the
rank-1/diagonal corrections into the surrounding computation, and the
Lanczos solver already accepts a bare ``matvec``.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.sparse.types import CSR
from raft_tpu.sparse.linalg import best_matvec


def _degrees(adj: CSR) -> jnp.ndarray:
    """Weighted degree vector d_i = Σ_j a_ij."""
    return jax.ops.segment_sum(adj.data, adj.row_ids(),
                               num_segments=adj.shape[0])


def laplacian_matvec(adj: CSR) -> Tuple[Callable, jnp.ndarray]:
    """Implicit Laplacian operator: ``L·x = D·x − A·x``.

    Returns (matvec, degrees).  Reference ``laplacian_matrix_t::mv``
    computes the same two-term SpMV (spectral/matrix_wrappers.hpp).
    """
    expects(adj.shape[0] == adj.shape[1], "laplacian: matrix must be square")
    deg = _degrees(adj)
    # lazy: deg-only callers (analyze_partition) must not pay the host-side
    # ELL conversion; first mv call builds the scatter-free operator
    box = []

    def mv(x):
        if not box:
            box.append(best_matvec(adj))
        return deg * x - box[0](x)

    return mv, deg


def modularity_matvec(adj: CSR) -> Tuple[Callable, jnp.ndarray, jnp.ndarray]:
    """Implicit modularity operator ``B·x = A·x − d (dᵀx) / (2m)``.

    Returns (matvec, degrees, edge_sum) where ``edge_sum = Σ_ij a_ij = 2m``.
    Reference ``modularity_matrix_t::mv`` (spectral/matrix_wrappers.hpp).
    """
    expects(adj.shape[0] == adj.shape[1], "modularity: matrix must be square")
    deg = _degrees(adj)
    edge_sum = jnp.sum(deg)  # 2m for an undirected (symmetric) graph

    box = []

    def mv(x):
        if not box:
            box.append(best_matvec(adj))
        scale = jnp.dot(deg, x) / jnp.maximum(edge_sum, 1e-30)
        return box[0](x) - deg * scale

    return mv, deg, edge_sum
