"""Implicit spectral operators over a CSR adjacency matrix.

Counterpart of reference ``spectral/matrix_wrappers.hpp:41-45``
(``sparse_matrix_t`` / ``laplacian_matrix_t`` / ``modularity_matrix_t``):
the reference wraps cusparse SpMV and overrides ``mv`` so the Lanczos solver
sees ``L·x`` or ``B·x`` without materializing L or B.  TPU-first the same
idea is a closure over :func:`raft_tpu.sparse.linalg.spmv` — XLA fuses the
rank-1/diagonal corrections into the surrounding computation, and the
Lanczos solver already accepts a bare ``matvec``.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.sparse.types import CSR
from raft_tpu.sparse.linalg import apply_matvec, matvec_operand


def degrees(adj: CSR) -> jnp.ndarray:
    """Weighted degree vector d_i = Σ_j a_ij.

    Use this directly when only degrees are needed — the operator builders
    below also pay the one-time ELL conversion."""
    from raft_tpu.linalg.reduce import segment_sum

    return segment_sum(adj.data, adj.row_ids(), adj.shape[0])


def _laplacian_apply(deg, op, x):
    return deg * x - apply_matvec(op, x)


def _modularity_apply(deg, edge_sum, op, x):
    scale = jnp.dot(deg, x) / jnp.maximum(edge_sum, 1e-30)
    return apply_matvec(op, x) - deg * scale


def laplacian_matvec(adj: CSR) -> Tuple[Callable, jnp.ndarray]:
    """Implicit Laplacian operator: ``L·x = D·x − A·x``.

    Returns (matvec, degrees).  Reference ``laplacian_matrix_t::mv``
    computes the same two-term SpMV (spectral/matrix_wrappers.hpp).

    The matvec is a ``jax.tree_util.Partial`` of a module-level applier:
    its state (degrees + ELL operand) rides through jit boundaries as
    dynamic operands, so consumers like the Lanczos solver reuse ONE
    compiled program across graphs instead of retracing per closure — and
    nothing pins the graph's buffers beyond the Partial's own lifetime.
    """
    expects(adj.shape[0] == adj.shape[1], "laplacian: matrix must be square")
    deg = degrees(adj)
    return jax.tree_util.Partial(_laplacian_apply, deg,
                                 matvec_operand(adj)), deg


def modularity_matvec(adj: CSR) -> Tuple[Callable, jnp.ndarray, jnp.ndarray]:
    """Implicit modularity operator ``B·x = A·x − d (dᵀx) / (2m)``.

    Returns (matvec, degrees, edge_sum) where ``edge_sum = Σ_ij a_ij = 2m``.
    Reference ``modularity_matrix_t::mv`` (spectral/matrix_wrappers.hpp).
    Same ``Partial`` design as :func:`laplacian_matvec`.
    """
    expects(adj.shape[0] == adj.shape[1], "modularity: matrix must be square")
    deg = degrees(adj)
    edge_sum = jnp.sum(deg)  # 2m for an undirected (symmetric) graph
    return jax.tree_util.Partial(_modularity_apply, deg, edge_sum,
                                 matvec_operand(adj)), deg, edge_sum
