"""Spectral partition / modularity maximization pipelines.

Counterparts of reference ``spectral/detail/partition.hpp:65-107``
(``partition`` + ``analyzePartition``) and
``spectral/detail/modularity_maximization.hpp`` (``modularity_maximization``
+ ``analyzeModularity``).

TPU-first notes:
- The Laplacian/modularity operators stay implicit (closures over spmv);
  Lanczos runs them inside one jitted ``fori_loop`` (no per-SpMV host sync,
  unlike the reference's cusparse-call-per-iteration loop).
- The eigenvector "whitening" (``transform_eigen_matrix``: mean-center +
  unit-normalize each eigenvector) is two fused XLA reductions.
- ``analyze_partition`` evaluates all clusters at once with a one-hot
  (n, k) indicator matrix — the k indicator SpMVs become one SpMM riding
  the MXU, instead of the reference's per-cluster loop.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.logger import traced
from raft_tpu.sparse.types import CSR
from raft_tpu.sparse.linalg import spmm
from raft_tpu.spectral.matrix import degrees, laplacian_matvec, modularity_matvec
from raft_tpu.spectral.solvers import LanczosEigenSolver, KMeansClusterSolver


def _transform_eigen_matrix(vecs: jnp.ndarray) -> jnp.ndarray:
    """Whiten the eigenvector matrix: mean-center + scale each eigenvector
    to unit norm (reference ``transform_eigen_matrix``,
    spectral/detail/spectral_util.cuh)."""
    v = vecs - jnp.mean(vecs, axis=0, keepdims=True)
    nrm = jnp.maximum(jnp.linalg.norm(v, axis=0, keepdims=True), 1e-30)
    return v / nrm


@traced("raft_tpu.spectral.partition")
def partition(adj: CSR, eigen_solver: LanczosEigenSolver,
              cluster_solver: KMeansClusterSolver
              ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Spectral min-balanced-cut partition.

    Laplacian smallest eigenvectors → whiten → k-means (reference
    ``spectral/detail/partition.hpp:65``).

    Returns (clusters [n] int32, eig_vals [k], eig_vecs [n, k], inertia).
    """
    expects(adj.shape[0] == adj.shape[1], "partition: adjacency must be square")
    n = adj.shape[0]
    mv, _ = laplacian_matvec(adj)
    eig_vals, eig_vecs = eigen_solver.solve_smallest_eigenvectors(
        mv, n=n, dtype=adj.data.dtype)
    emb = _transform_eigen_matrix(eig_vecs)
    labels, inertia = cluster_solver.solve(emb)
    return labels, eig_vals, eig_vecs, inertia


@traced("raft_tpu.spectral.modularity_maximization")
def modularity_maximization(adj: CSR, eigen_solver: LanczosEigenSolver,
                            cluster_solver: KMeansClusterSolver
                            ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                                       jnp.ndarray]:
    """Community detection by modularity-matrix spectral clustering.

    Modularity matrix largest eigenvectors → whiten → row-scale → k-means
    (reference ``spectral/detail/modularity_maximization.hpp``).

    Returns (clusters [n] int32, eig_vals [k], eig_vecs [n, k], inertia).
    """
    expects(adj.shape[0] == adj.shape[1],
            "modularity_maximization: adjacency must be square")
    n = adj.shape[0]
    mv, _, _ = modularity_matvec(adj)
    eig_vals, eig_vecs = eigen_solver.solve_largest_eigenvectors(
        mv, n=n, dtype=adj.data.dtype)
    emb = _transform_eigen_matrix(eig_vecs)
    # scale_obs: normalize each observation (row) to unit norm before
    # k-means (reference modularity_maximization.hpp ``scale_obs``).
    rnorm = jnp.maximum(jnp.linalg.norm(emb, axis=1, keepdims=True), 1e-30)
    emb = emb / rnorm
    labels, inertia = cluster_solver.solve(emb)
    return labels, eig_vals, eig_vecs, inertia


def _one_hot(labels: jnp.ndarray, k: int, dtype) -> jnp.ndarray:
    return (labels[:, None] == jnp.arange(k, dtype=labels.dtype)[None, :]
            ).astype(dtype)


def analyze_partition(adj: CSR, n_clusters: int, labels
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Edge cut + balanced-cut cost of a partition.

    ``cost = Σ_i cut(i)/|V_i|``, ``edge_cut = Σ_i cut(i)/2`` where
    ``cut(i) = u_iᵀ L u_i`` for the indicator vector of cluster i
    (reference ``analyzePartition``, spectral/detail/partition.hpp).
    Empty clusters contribute nothing (reference warns + skips).

    Returns (edge_cut, cost).
    """
    labels = jnp.asarray(labels)
    n = adj.shape[0]
    expects(labels.shape[0] == n, "labels must have one entry per vertex")
    deg = degrees(adj)  # deg-only: skip the operator build
    U = _one_hot(labels, n_clusters, adj.data.dtype)        # (n, k)
    LU = deg[:, None] * U - spmm(adj, U)                    # one SpMM, not k SpMVs
    cut = jnp.sum(U * LU, axis=0)                            # (k,) uᵀLu
    size = jnp.sum(U, axis=0)
    nonempty = size > 0
    cost = jnp.sum(jnp.where(nonempty, cut / jnp.maximum(size, 1), 0.0))
    edge_cut = jnp.sum(jnp.where(nonempty, cut, 0.0)) / 2
    return edge_cut, cost


def analyze_modularity(adj: CSR, n_clusters: int, labels) -> jnp.ndarray:
    """Modularity Q = (1/2m) Σ_i u_iᵀ B u_i of a clustering
    (reference ``analyzeModularity``,
    spectral/detail/modularity_maximization.hpp)."""
    labels = jnp.asarray(labels)
    n = adj.shape[0]
    expects(labels.shape[0] == n, "labels must have one entry per vertex")
    deg = degrees(adj)
    edge_sum = jnp.sum(deg)
    U = _one_hot(labels, n_clusters, adj.data.dtype)
    BU = spmm(adj, U) - deg[:, None] * (deg @ U)[None, :] / jnp.maximum(edge_sum, 1e-30)
    q = jnp.sum(U * BU)
    return q / jnp.maximum(edge_sum, 1e-30)
