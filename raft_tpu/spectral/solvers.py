"""Pluggable eigen/cluster solvers for the spectral pipelines.

Counterparts of reference ``spectral/eigen_solvers.cuh:45``
(``lanczos_solver_t`` + ``eigen_solver_config_t``) and
``spectral/cluster_solvers.cuh:43`` (``kmeans_solver_t`` +
``cluster_solver_config_t``).  The configs keep the reference's field names
so downstream callers translate one-to-one.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax.numpy as jnp

from raft_tpu.cluster import KMeansParams, InitMethod, fit_predict


@dataclasses.dataclass
class EigenSolverConfig:
    """Reference ``eigen_solver_config_t`` (spectral/eigen_solvers.cuh:28)."""

    n_eigVecs: int
    maxIter: int = 15          # restart rounds (reference: maxIter_lanczos)
    restartIter: int = 0       # Krylov size m (0 → auto, like reference's 2k+16)
    tol: float = 1e-6
    reorthogonalize: bool = True  # always on in the TPU build (MXU-cheap)
    seed: int = 1234567


class LanczosEigenSolver:
    """Reference ``lanczos_solver_t`` (spectral/eigen_solvers.cuh:45).

    ``solve_smallest_eigenvectors`` / ``solve_largest_eigenvectors`` accept
    either a :class:`~raft_tpu.sparse.types.CSR` or a bare ``matvec``
    callable (the implicit Laplacian/modularity operators).
    """

    def __init__(self, config: EigenSolverConfig):
        self.config = config

    def _kwargs(self):
        c = self.config
        return dict(
            ncv=(c.restartIter or None),
            max_restarts=c.maxIter,
            tol=c.tol,
            seed=c.seed,
        )

    def solve_smallest_eigenvectors(self, a, n: Optional[int] = None,
                                    dtype=jnp.float32
                                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        from raft_tpu.sparse.solver import lanczos_smallest

        return lanczos_smallest(a, self.config.n_eigVecs, n=n, dtype=dtype,
                                **self._kwargs())

    def solve_largest_eigenvectors(self, a, n: Optional[int] = None,
                                   dtype=jnp.float32
                                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        from raft_tpu.sparse.solver import lanczos_largest

        return lanczos_largest(a, self.config.n_eigVecs, n=n, dtype=dtype,
                               **self._kwargs())


@dataclasses.dataclass
class ClusterSolverConfig:
    """Reference ``cluster_solver_config_t`` (spectral/cluster_solvers.cuh:30)."""

    n_clusters: int
    maxIter: int = 100
    tol: float = 1e-4
    seed: int = 123456


class KMeansClusterSolver:
    """Reference ``kmeans_solver_t`` (spectral/cluster_solvers.cuh:43):
    k-means on the (n, n_eigVecs) spectral embedding."""

    def __init__(self, config: ClusterSolverConfig):
        self.config = config

    def solve(self, embedding) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Returns (labels [n], inertia scalar)."""
        c = self.config
        params = KMeansParams(
            n_clusters=c.n_clusters,
            max_iter=c.maxIter,
            tol=c.tol,
            seed=c.seed,
            init=InitMethod.KMeansPlusPlus,
        )
        out = fit_predict(params, jnp.asarray(embedding))
        return out.labels, out.inertia
