"""Model-evaluation and clustering metrics.

Counterparts of reference raft/stats/{accuracy,r2_score,regression_metrics,
silhouette_score,trustworthiness_score,adjusted_rand_index,rand_index,
completeness_score,homogeneity_score,v_measure,mutual_info_score,entropy,
kl_divergence,contingency_matrix,dispersion,information_criterion}.cuh.
"""

from __future__ import annotations

import enum
from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.distance import DistanceType
# the undecorated dispatcher: these call sites sit inside batch loops,
# where the public @auto_sync_handle wrapper would force a blocking
# default-handle sync per tile
from raft_tpu.distance.pairwise import distance as pairwise_distance
from raft_tpu.linalg.reduce import reduce_cols_by_key


# -- classification / regression ---------------------------------------------

def accuracy(predictions, ref_predictions):
    """Fraction of exact matches (reference stats/accuracy.cuh)."""
    predictions = jnp.asarray(predictions)
    ref_predictions = jnp.asarray(ref_predictions)
    return jnp.mean((predictions == ref_predictions).astype(jnp.float32))


def r2_score(y, y_hat):
    """Coefficient of determination (reference stats/r2_score.cuh)."""
    y = jnp.asarray(y)
    y_hat = jnp.asarray(y_hat)
    mu = jnp.mean(y)
    ss_tot = jnp.sum((y - mu) ** 2)
    ss_res = jnp.sum((y - y_hat) ** 2)
    return 1.0 - ss_res / ss_tot


def regression_metrics(predictions, ref_predictions):
    """(mean_abs_error, mean_squared_error, median_abs_error)
    (reference stats/regression_metrics.cuh)."""
    predictions = jnp.asarray(predictions)
    ref_predictions = jnp.asarray(ref_predictions)
    diff = predictions - ref_predictions
    return (jnp.mean(jnp.abs(diff)), jnp.mean(diff * diff),
            jnp.median(jnp.abs(diff)))


# -- contingency-table family ------------------------------------------------

def contingency_matrix(y_true, y_pred, n_classes: Optional[int] = None):
    """Dense contingency matrix [n_true_classes, n_pred_classes]
    (reference stats/contingency_matrix.cuh; CUB histograms there, a one-hot
    segment-sum here)."""
    y_true = jnp.asarray(y_true).astype(jnp.int32)
    y_pred = jnp.asarray(y_pred).astype(jnp.int32)
    if n_classes is None:
        n_classes = int(jnp.maximum(jnp.max(y_true), jnp.max(y_pred))) + 1
    flat = y_true * n_classes + y_pred
    counts = jnp.zeros((n_classes * n_classes,), jnp.int32).at[flat].add(1)
    return counts.reshape(n_classes, n_classes)


def entropy(labels, n_classes: Optional[int] = None):
    """Shannon entropy (nats) of a label vector (reference stats/entropy.cuh)."""
    labels = jnp.asarray(labels).astype(jnp.int32)
    if n_classes is None:
        n_classes = int(jnp.max(labels)) + 1
    # x64: exact counts under jax_enable_x64; demotes harmlessly to f32
    counts = jnp.zeros((n_classes,), jnp.float64).at[labels].add(1.0)
    p = counts / labels.shape[0]
    return -jnp.sum(jnp.where(p > 0, p * jnp.log(jnp.where(p > 0, p, 1.0)), 0.0))


def mutual_info_score(y_true, y_pred, n_classes: Optional[int] = None):
    """Mutual information (nats) between two labelings
    (reference stats/mutual_info_score.cuh)."""
    # x64: exact pair counts under jax_enable_x64 (f32 loses ints > 2^24)
    cm = contingency_matrix(y_true, y_pred, n_classes).astype(jnp.float64)
    n = jnp.sum(cm)
    pij = cm / n
    pi = jnp.sum(pij, axis=1, keepdims=True)
    pj = jnp.sum(pij, axis=0, keepdims=True)
    denom = pi * pj
    ok = pij > 0
    return jnp.sum(jnp.where(ok, pij * (jnp.log(jnp.where(ok, pij, 1.0))
                                        - jnp.log(jnp.where(ok, denom, 1.0))), 0.0))


def homogeneity_score(y_true, y_pred, n_classes: Optional[int] = None):
    """reference stats/homogeneity_score.cuh: MI / H(true)."""
    h = entropy(y_true, n_classes)
    mi = mutual_info_score(y_true, y_pred, n_classes)
    return jnp.where(h > 0, mi / jnp.maximum(h, 1e-300), 1.0)


def completeness_score(y_true, y_pred, n_classes: Optional[int] = None):
    """reference stats/completeness_score.cuh: MI / H(pred)."""
    h = entropy(y_pred, n_classes)
    mi = mutual_info_score(y_true, y_pred, n_classes)
    return jnp.where(h > 0, mi / jnp.maximum(h, 1e-300), 1.0)


def v_measure(y_true, y_pred, n_classes: Optional[int] = None, beta: float = 1.0):
    """reference stats/v_measure.cuh: weighted harmonic mean of
    homogeneity and completeness."""
    h = homogeneity_score(y_true, y_pred, n_classes)
    c = completeness_score(y_true, y_pred, n_classes)
    denom = beta * h + c
    return jnp.where(denom > 0, (1 + beta) * h * c / jnp.maximum(denom, 1e-300), 0.0)


def rand_index(y_true, y_pred):
    """Unadjusted Rand index (reference stats/rand_index.cuh)."""
    # x64: exact pair counts under jax_enable_x64 (f32 loses ints > 2^24)
    cm = contingency_matrix(y_true, y_pred).astype(jnp.float64)
    n = jnp.sum(cm)
    sum_sq = jnp.sum(cm * cm)
    a_sq = jnp.sum(jnp.sum(cm, axis=1) ** 2)
    b_sq = jnp.sum(jnp.sum(cm, axis=0) ** 2)
    # pairs agreeing: same-same + diff-diff
    tp_fp = (a_sq - n) / 2
    tp_fn = (b_sq - n) / 2
    tp = (sum_sq - n) / 2
    total = n * (n - 1) / 2
    return (total + 2 * tp - tp_fp - tp_fn) / total


def adjusted_rand_index(y_true, y_pred):
    """ARI (reference stats/adjusted_rand_index.cuh)."""
    # x64: exact pair counts under jax_enable_x64 (f32 loses ints > 2^24)
    cm = contingency_matrix(y_true, y_pred).astype(jnp.float64)
    n = jnp.sum(cm)

    def comb2(x):
        return x * (x - 1) / 2

    sum_comb = jnp.sum(comb2(cm))
    sum_a = jnp.sum(comb2(jnp.sum(cm, axis=1)))
    sum_b = jnp.sum(comb2(jnp.sum(cm, axis=0)))
    total = comb2(n)
    expected = sum_a * sum_b / total
    max_index = 0.5 * (sum_a + sum_b)
    denom = max_index - expected
    return jnp.where(jnp.abs(denom) > 1e-300, (sum_comb - expected) / denom, 1.0)


def kl_divergence(p, q):
    """Scalar KL divergence between two distributions
    (reference stats/kl_divergence.cuh: Σ p·log(p/q), 0 where p==0)."""
    p = jnp.asarray(p)
    q = jnp.asarray(q)
    ok = p > 0
    return jnp.sum(jnp.where(ok, p * (jnp.log(jnp.where(ok, p, 1.0))
                                      - jnp.log(jnp.where(q > 0, q, 1.0))), 0.0))


# -- embedding-quality metrics -----------------------------------------------

def silhouette_score(x, labels, n_clusters: Optional[int] = None,
                     metric: DistanceType = DistanceType.L2Expanded,
                     return_samples: bool = False):
    """Mean silhouette coefficient (reference stats/silhouette_score.cuh:46).

    a(i) = mean intra-cluster distance, b(i) = min mean distance to another
    cluster; s = (b−a)/max(a,b).  Computed from one pairwise-distance matrix
    plus a segment-sum over columns by label — no per-pair loop.
    """
    x = jnp.asarray(x)
    labels = jnp.asarray(labels).astype(jnp.int32)
    n = x.shape[0]
    if n_clusters is None:
        n_clusters = int(jnp.max(labels)) + 1
    d = pairwise_distance(x, x, metric)
    # per-row sums of distances to each cluster: (n, n_clusters)
    cluster_sums = reduce_cols_by_key(d, labels, n_clusters)
    counts = jnp.zeros((n_clusters,), d.dtype).at[labels].add(1.0)
    own = labels
    own_count = counts[own]
    a = jnp.where(own_count > 1,
                  jnp.take_along_axis(cluster_sums, own[:, None], axis=1)[:, 0]
                  / jnp.maximum(own_count - 1, 1.0),
                  0.0)
    mean_other = cluster_sums / jnp.maximum(counts[None, :], 1.0)
    mean_other = jnp.where(
        (jnp.arange(n_clusters)[None, :] == own[:, None]) | (counts[None, :] == 0),
        jnp.inf, mean_other)
    b = jnp.min(mean_other, axis=1)
    s = jnp.where(own_count > 1, (b - a) / jnp.maximum(jnp.maximum(a, b), 1e-300), 0.0)
    if return_samples:
        return jnp.mean(s), s
    return jnp.mean(s)


def silhouette_score_batched(x, labels, n_clusters: Optional[int] = None,
                             metric: DistanceType = DistanceType.L2Expanded,
                             batch_size: int = 4096, return_samples: bool = False):
    """Batched silhouette (reference stats/silhouette_score.cuh:62
    ``silhouette_score_batched``): tiles the pairwise matrix over row chunks
    so only batch_size×n distances are live."""
    x = jnp.asarray(x)
    labels = jnp.asarray(labels).astype(jnp.int32)
    n = x.shape[0]
    if n_clusters is None:
        n_clusters = int(jnp.max(labels)) + 1
    counts = jnp.zeros((n_clusters,), x.dtype).at[labels].add(1.0)
    samples = []
    for start in range(0, n, batch_size):
        xb = x[start:start + batch_size]
        lb = labels[start:start + batch_size]
        d = pairwise_distance(xb, x, metric)
        cluster_sums = reduce_cols_by_key(d, labels, n_clusters)
        own_count = counts[lb]
        a = jnp.where(own_count > 1,
                      jnp.take_along_axis(cluster_sums, lb[:, None], axis=1)[:, 0]
                      / jnp.maximum(own_count - 1, 1.0), 0.0)
        mean_other = cluster_sums / jnp.maximum(counts[None, :], 1.0)
        mean_other = jnp.where(
            (jnp.arange(n_clusters)[None, :] == lb[:, None]) | (counts[None, :] == 0),
            jnp.inf, mean_other)
        b = jnp.min(mean_other, axis=1)
        samples.append(jnp.where(own_count > 1,
                                 (b - a) / jnp.maximum(jnp.maximum(a, b), 1e-300), 0.0))
    s = jnp.concatenate(samples)
    if return_samples:
        return jnp.mean(s), s
    return jnp.mean(s)


def trustworthiness_score(x, x_embedded, n_neighbors: int = 5,
                          metric: DistanceType = DistanceType.L2SqrtExpanded):
    """Trustworthiness of a low-dimensional embedding
    (reference stats/trustworthiness_score.cuh — brute-force kNN there;
    full argsorted distance ranks here)."""
    x = jnp.asarray(x)
    x_embedded = jnp.asarray(x_embedded)
    n = x.shape[0]
    expects(n_neighbors < n // 2, "n_neighbors must be < n/2")
    d_orig = pairwise_distance(x, x, metric)
    d_emb = pairwise_distance(x_embedded, x_embedded, metric)
    big = jnp.asarray(jnp.inf, d_orig.dtype)
    eye = jnp.eye(n, dtype=bool)
    d_orig = jnp.where(eye, big, d_orig)
    d_emb = jnp.where(eye, big, d_emb)
    # rank of j in i's original-space ordering
    order_orig = jnp.argsort(d_orig, axis=1)
    ranks = jnp.zeros((n, n), jnp.int32)
    ranks = jax.vmap(lambda r, o: r.at[o].set(jnp.arange(n, dtype=jnp.int32)))(
        ranks, order_orig)
    # k nearest in embedded space
    _, emb_nn = jax.lax.top_k(-d_emb, n_neighbors)
    r = jnp.take_along_axis(ranks, emb_nn, axis=1)  # original ranks of embedded nns
    # x64: exact rank sums under jax_enable_x64
    penalty = jnp.maximum(r - n_neighbors + 1, 0).astype(jnp.float64)
    t = 1.0 - (2.0 / (n * n_neighbors * (2 * n - 3 * n_neighbors - 1))) * jnp.sum(penalty)
    return t


# -- cluster dispersion / information criterion ------------------------------

def dispersion(centroids, cluster_sizes, global_centroid=None, n_points: Optional[int] = None):
    """Cluster dispersion Σᵢ sizeᵢ·‖cᵢ − μ‖² (reference
    stats/detail/dispersion.cuh:31-32; returns sqrt like the reference's
    final host step)."""
    centroids = jnp.asarray(centroids)
    sizes = jnp.asarray(cluster_sizes)
    if n_points is None:
        n_points = jnp.sum(sizes)
    if global_centroid is None:
        global_centroid = jnp.sum(centroids * sizes[:, None], axis=0) / n_points
    diff = centroids - global_centroid[None, :]
    return jnp.sqrt(jnp.sum(diff * diff * sizes[:, None]))


class IC_Type(enum.Enum):
    """reference stats/stats_types.hpp:60 ``IC_Type``."""

    AIC = "aic"
    AICc = "aicc"
    BIC = "bic"


def information_criterion_batched(loglikelihood, ic_type: IC_Type,
                                  n_params: int, n_samples: int):
    """AIC/AICc/BIC per batch element from log-likelihoods
    (reference stats/detail/batched/information_criterion.cuh:44-69:
    ic = ic_base − 2·loglike)."""
    ll = jnp.asarray(loglikelihood)
    n = float(n_params)
    t = float(n_samples)
    if ic_type == IC_Type.AIC:
        base = 2.0 * n
    elif ic_type == IC_Type.AICc:
        base = 2.0 * (n + (n * (n + 1.0)) / (t - n - 1.0))
    else:
        base = float(jnp.log(t)) * n
    return base - 2.0 * ll
