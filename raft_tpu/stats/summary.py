"""Summary statistics.

Counterparts of reference raft/stats/{mean,mean_center,meanvar,stddev,sum,
cov,minmax,weighted_mean,histogram}.cuh.  RAFT's convention: statistics are
per-*column* (the reduction runs down the rows of the n_samples × n_features
matrix); ``sample=True`` uses the n−1 denominator.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp



def mean(data, sample: bool = False):
    """Column means (reference stats/mean.cuh).  *sample* matches the
    reference flag (divides by N−1 instead of N — kept for parity although
    it only matters when composing with stddev)."""
    n = data.shape[0]
    denom = (n - 1) if sample else n
    return jnp.sum(data, axis=0) / denom


def mean_center(data, mu=None):
    """Subtract column means (reference stats/mean_center.cuh ``meanCenter``)."""
    if mu is None:
        mu = mean(data)
    return data - mu[None, :]


def mean_add(data, mu):
    """Inverse of mean_center (reference ``meanAdd``)."""
    return data + mu[None, :]


def meanvar(data, sample: bool = True) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Column means and variances in one pass (reference stats/meanvar.cuh)."""
    n = data.shape[0]
    mu = jnp.mean(data, axis=0)
    centered = data - mu[None, :]
    denom = (n - 1) if sample else n
    var = jnp.sum(centered * centered, axis=0) / denom
    return mu, var


def stddev(data, mu=None, sample: bool = True):
    """Column standard deviations (reference stats/stddev.cuh)."""
    if mu is None:
        mu = jnp.mean(data, axis=0)
    n = data.shape[0]
    denom = (n - 1) if sample else n
    centered = data - mu[None, :]
    return jnp.sqrt(jnp.sum(centered * centered, axis=0) / denom)


def vars_(data, mu=None, sample: bool = True):
    """Column variances (reference ``vars``)."""
    s = stddev(data, mu, sample)
    return s * s


def sum_(data):
    """Column sums (reference stats/sum.cuh)."""
    return jnp.sum(data, axis=0)


def cov(data, mu=None, sample: bool = True, stable: bool = True):
    """Covariance matrix of the columns (reference stats/cov.cuh — cublas
    gemm over mean-centered data; here one MXU matmul)."""
    if mu is None:
        mu = jnp.mean(data, axis=0)
    centered = data - mu[None, :]
    n = data.shape[0]
    denom = (n - 1) if sample else n
    return jnp.matmul(centered.T, centered, precision="highest") / denom


def minmax(data) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-column (min, max) (reference stats/minmax.cuh)."""
    return jnp.min(data, axis=0), jnp.max(data, axis=0)


def row_weighted_mean(data, weights):
    """Weighted mean of each row (reference stats/weighted_mean.cuh
    ``rowWeightedMean``: weights along columns)."""
    w = jnp.asarray(weights)
    return jnp.sum(data * w[None, :], axis=1) / jnp.sum(w)


def col_weighted_mean(data, weights):
    """Weighted mean of each column (reference ``colWeightedMean``)."""
    w = jnp.asarray(weights)
    return jnp.sum(data * w[:, None], axis=0) / jnp.sum(w)


def weighted_mean(data, weights, along_rows: bool = True):
    """reference ``weightedMean`` dispatcher."""
    return row_weighted_mean(data, weights) if along_rows else col_weighted_mean(data, weights)


def histogram(data, n_bins: int, lower: Optional[float] = None,
              upper: Optional[float] = None):
    """Per-column histogram (reference stats/histogram.cuh — the reference
    ships 8+ CUDA binning strategies (smem/gmem atomics); XLA lowers one
    one-hot segment-sum instead).

    Values are binned into [lower, upper) with n_bins uniform bins; out-of-
    range values are clamped into the edge bins (reference binner semantics).
    Returns int32 [n_bins, n_features].
    """
    data = jnp.asarray(data)
    if data.ndim == 1:
        data = data[:, None]
    lo = jnp.min(data) if lower is None else lower
    hi = jnp.max(data) if upper is None else upper
    width = (hi - lo) / n_bins
    idx = jnp.clip(((data - lo) / width).astype(jnp.int32), 0, n_bins - 1)
    one_hot = jax.nn.one_hot(idx, n_bins, dtype=jnp.int32, axis=0)  # (bins, n, f)
    return jnp.sum(one_hot, axis=1)
