"""Unified runtime telemetry: metrics registry, span tracing, exporters.

The operational-telemetry layer the serving story demands
(docs/observability.md): ONE process-wide registry of labeled counters,
gauges and fixed-memory log-bucketed histograms (:mod:`.registry`), nested
host-side span tracing that also feeds the TPU profiler
(:mod:`.spans`), and exporters — plain-dict :func:`snapshot`, Prometheus
text exposition :func:`prometheus_text`, and an opt-in JSONL span event
sink (:func:`set_jsonl_sink`).

The five pre-registry telemetry fragments (``Comms.collective_calls``,
``core.aot.aot_compile_counters``, ``ivf_pq.lut_trace_counters``,
``neighbors._build.build_trace_counters``, ``ServeEngine.stats``) are all
registry-backed now, behind their exact legacy read surfaces
(:class:`LegacyCounterView`), with mutation made atomic
(``view.inc``) so concurrent ``ServeEngine.search()`` callers stop racing
plain Counters.

Beyond the host-process layer, three fleet-grade pieces
(docs/observability.md):

* **Device-cost attribution** (:mod:`.device`) — compile-time
  ``cost_analysis``/``memory_analysis`` harvest into per-program
  ``raft_tpu_program_{flops,bytes_accessed,temp_bytes}{fn,sig}`` gauges,
  plus sampled true device execution time (every Nth warm dispatch,
  ``RAFT_TPU_DEVICE_SAMPLE``) into ``raft_tpu_device_seconds{fn}`` with
  derived achieved FLOP/s / bytes/s gauges.
* **Fleet aggregation** (:mod:`.aggregate`) — :func:`merge` folds
  snapshots (histograms bucket-wise EXACT on the shared log-bucket
  geometry) and :func:`gather` collects per-host snapshots over a
  communicator's host p2p plane into one fleet view.
* **Live scrape surface** (:mod:`.http`, lazy import) — stdlib
  ``ThreadingHTTPServer`` serving ``/metrics`` (Prometheus), ``/healthz``,
  ``/varz`` and ``/debug/slow`` (a bounded flight-recorder ring of slow-
  request span trees); ``ServeEngine.serve_http(port)`` wires it to a
  serving engine.

Global off switch: ``RAFT_TPU_TELEMETRY=0`` (or :func:`set_enabled`) turns
spans, histograms, gauges, reservoirs, device sampling and the JSONL sink
into no-ops; counters stay live because they are contract instruments
(zero-compile serve gates, collective-call budgets), not just telemetry —
see :mod:`.registry` for the rationale.  The serve bench A/B gates the
telemetry-on overhead — device sampling at the default rate included — at
< 3% qps (bench.py ``serve``).

Quick tour::

    from raft_tpu import telemetry

    with telemetry.span("serve.dispatch"):
        ...                                   # timed, nested, profiled

    telemetry.counter("my_events", labelnames=("kind",)).inc(
        1, ("cache_miss",))

    telemetry.snapshot()                      # plain dict, JSON-safe
    print(telemetry.prometheus_text())        # Prometheus scrape body
    telemetry.set_jsonl_sink("/tmp/spans.jsonl")   # span event stream
"""

from __future__ import annotations

from raft_tpu.telemetry.aggregate import gather, merge  # noqa: F401
from raft_tpu.telemetry.device import (  # noqa: F401
    sample_every,
    set_sample_every,
)
from raft_tpu.telemetry.device import program_costs  # noqa: F401
from raft_tpu.telemetry import device as _device
from raft_tpu.telemetry.export import prometheus_text, snapshot  # noqa: F401
from raft_tpu.telemetry.registry import (  # noqa: F401
    HIST_BUCKETS,
    HIST_MAX,
    HIST_MIN,
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    LegacyCounterView,
    Registry,
    Reservoir,
    bucket_index,
    bucket_upper,
    enabled,
    set_enabled,
)
from raft_tpu.telemetry.spans import (  # noqa: F401
    Span,
    collect_spans,
    current_span,
    now,
    set_jsonl_sink,
    span,
)


def __getattr__(name):
    # the scrape-surface module pulls in stdlib http.server (socketserver
    # and friends) — loaded lazily so `import raft_tpu.telemetry`, which
    # core.aot (and therefore everything) pays, stays cheap
    if name == "http":
        import importlib

        return importlib.import_module("raft_tpu.telemetry.http")
    raise AttributeError(f"module 'raft_tpu.telemetry' has no "
                         f"attribute {name!r}")


def counter(name: str, help: str = "", labelnames=()) -> Counter:
    """Get-or-create a labeled counter on the default registry."""
    return REGISTRY.counter(name, help, labelnames)


def gauge(name: str, help: str = "", labelnames=()) -> Gauge:
    """Get-or-create a labeled gauge on the default registry."""
    return REGISTRY.gauge(name, help, labelnames)


def histogram(name: str, help: str = "", labelnames=(),
              reservoir: int = 0) -> Histogram:
    """Get-or-create a labeled log-bucketed histogram on the default
    registry (optional bounded uniform *reservoir* per label set)."""
    return REGISTRY.histogram(name, help, labelnames, reservoir=reservoir)


def legacy_counter(name: str, help: str = "", labelnames=("key",),
                   fixed=()) -> LegacyCounterView:
    """A :class:`LegacyCounterView` over ``name{*labelnames}`` — the
    migration shim the five legacy fragments sit behind.  *labelnames*
    must end in ``"key"`` (the view's mapping key); *fixed* pins every
    label before it (e.g. a per-instance ordinal), so per-instance views
    like ``Comms.collective_calls`` read privately while the registry and
    every exporter see all instances."""
    metric = REGISTRY.counter(name, help, tuple(labelnames))
    return LegacyCounterView(metric, tuple(str(v) for v in fixed))


# ---------------------------------------------------------------------------
# instrument helpers for the aot dispatch path (kept here so core/aot.py —
# imported by everything — adds exactly one cheap call per dispatch)

_dispatch_total = None
_dispatch_seconds = None


def _dispatch_metrics():
    global _dispatch_total, _dispatch_seconds
    if _dispatch_total is None:
        _dispatch_total = REGISTRY.counter(
            "raft_tpu_aot_dispatch_total",
            "AOT executable dispatches by function and warm/cold state",
            labelnames=("fn", "temp"))
        _dispatch_seconds = REGISTRY.histogram(
            "raft_tpu_aot_dispatch_seconds",
            "host-side dispatch latency per AOT function and signature",
            labelnames=("fn", "sig"))
    return _dispatch_total, _dispatch_seconds


def record_dispatch(fn: str, sig: str, cold: bool, seconds: float) -> None:
    """One AOT executable dispatch: bump the per-function warm/cold count
    and record the host-side dispatch latency under the (fn, sig) pair.

    The COUNTER stays live under ``RAFT_TPU_TELEMETRY=0`` — the module
    contract is that counters are contract instruments (warm/cold dispatch
    totals back the zero-compile serve gates exactly like
    ``aot_compile_counters``), so only the latency-histogram observation
    is gated (``Histogram.observe`` no-ops itself when disabled).  This is
    per-dispatch (per super-batch/tile), not per query."""
    total, hist = _dispatch_metrics()
    total.inc(1, (fn, "cold" if cold else "warm"))
    hist.observe(seconds, (fn, sig))


def record_program_costs(fn: str, sig: str, compiled):
    """Compile-time device-cost attribution hook (see
    :mod:`raft_tpu.telemetry.device`): harvest *compiled*'s
    ``cost_analysis``/``memory_analysis`` into the per-(fn, sig)
    ``raft_tpu_program_*`` gauges.  Called by ``core.aot`` on every
    compile miss — never on the dispatch path."""
    return _device.record_program_costs(fn, sig, compiled)


def device_sample_due(fn: str) -> bool:
    """Dispatch-time gate: True when this warm dispatch of *fn* should
    block on its output for a device-time sample (every
    ``RAFT_TPU_DEVICE_SAMPLE``-th; default 1/64, first warm dispatch
    always).  Always False with telemetry disabled."""
    return _device.sample_due(fn)


def record_device_sample(fn: str, sig: str, seconds: float) -> None:
    """Record one blocked-dispatch device-time sample into
    ``raft_tpu_device_seconds{fn}`` and refresh the achieved FLOP/s and
    bytes/s gauges from the program's static costs."""
    _device.record_sample(fn, sig, seconds)
