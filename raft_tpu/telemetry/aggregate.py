"""Fleet-wide telemetry aggregation: merge snapshots, gather a fleet view.

A sharded deployment is one logical system serving one traffic stream —
operators need ONE snapshot for it, not a per-process Python dict apiece
(docs/observability.md §fleet aggregation).  Two pieces:

* :func:`merge` folds any number of :func:`raft_tpu.telemetry.snapshot`
  dicts into one, in the snapshot schema.  Counters sum.  Histograms merge
  EXACTLY: every histogram in the process shares the one fixed log-bucket
  geometry (:data:`~raft_tpu.telemetry.registry.HIST_BUCKETS` bins over
  [HIST_MIN, HIST_MAX]), so merging is bucket-wise integer addition —
  bit-equal to having observed the union stream into one histogram, by
  construction (the property tests pin this).  ``count`` adds, ``sum``
  adds, ``min``/``max`` fold, and the convenience ``p50``/``p99`` are
  re-estimated from the merged buckets through the SAME
  :func:`~raft_tpu.telemetry.registry.quantile_from_counts` implementation
  :meth:`~raft_tpu.telemetry.registry.Histogram.quantile` calls.
  Gauges fold with ``max`` — the shipped gauges are static per-program
  costs (identical on every host, max = identity) and latest achieved
  rates (max = best-achieved across the fleet); a per-host read is always
  available in the ``hosts`` section of a gathered view.

* :func:`gather` collects per-host snapshots over a communicator's host
  p2p plane (the tagged isend/irecv mailbox every :class:`Comms` carries)
  and returns ``{"world", "hosts": {rank: snapshot}, "rollup": merged}``
  — per-host views preserved, plus the summed rollup, on EVERY host
  (symmetric all-to-all exchange, so any host can serve the fleet view
  from its scrape endpoint).  Single-host processes — including a
  single-controller process driving a whole 8-device mesh — gather
  trivially: the local snapshot already covers every device the process
  drives.
"""

from __future__ import annotations

import math
from typing import Dict, List, Sequence

from raft_tpu.telemetry.export import snapshot
from raft_tpu.telemetry.registry import (
    HIST_BUCKETS,
    bucket_upper,
    quantile_from_counts,
)

#: snapshot() rounds bucket upper bounds to 9 decimals; the same rounding
#: here makes the upper-bound → bucket-index lookup exact (float equality
#: on identical round() outputs), which is what keeps the merge bucket-wise
#: exact instead of nearest-match fuzzy.
_BUCKET_INDEX = {round(bucket_upper(i), 9): i for i in range(HIST_BUCKETS)}


def _counts_from_cell(cell: dict) -> List[int]:
    counts = [0] * HIST_BUCKETS
    for upper, n in cell["buckets"]:
        i = _BUCKET_INDEX.get(upper)
        if i is None:
            raise ValueError(
                f"histogram bucket upper bound {upper!r} is not on the "
                "shared log-bucket grid — snapshots from a build with a "
                "different HIST geometry cannot merge exactly")
        counts[i] += int(n)
    return counts


def _merge_hist_cells(cells: Sequence[dict]) -> dict:
    counts = [0] * HIST_BUCKETS
    total, vsum = 0, 0.0
    lo, hi = math.inf, -math.inf
    for cell in cells:
        for i, n in enumerate(_counts_from_cell(cell)):
            counts[i] += n
        total += int(cell["count"])
        vsum += float(cell["sum"])
        lo = min(lo, float(cell["min"]))
        hi = max(hi, float(cell["max"]))
    return {
        "count": total, "sum": vsum, "min": lo, "max": hi,
        "buckets": [[round(bucket_upper(i), 9), n]
                    for i, n in enumerate(counts) if n],
        "p50": quantile_from_counts(counts, total, lo, hi, 0.5),
        "p99": quantile_from_counts(counts, total, lo, hi, 0.99),
    }


def merge(snapshots: Sequence[Dict[str, dict]]) -> Dict[str, dict]:
    """Fold snapshot dicts into one (same schema as
    :func:`raft_tpu.telemetry.snapshot`).  Counters sum, gauges fold with
    max, histograms merge bucket-wise exactly (see module docstring).  A
    metric name appearing with conflicting type/labelnames across inputs
    raises — that is a deployment mixing incompatible builds, not
    something to paper over."""
    out: Dict[str, dict] = {}
    for snap in snapshots:
        for name, entry in snap.items():
            prior = out.get(name)
            if prior is None:
                out[name] = {
                    "type": entry["type"], "help": entry["help"],
                    "labelnames": list(entry["labelnames"]),
                    "values": {k: (dict(v) if isinstance(v, dict) else v)
                               for k, v in entry["values"].items()},
                }
                continue
            if (prior["type"] != entry["type"]
                    or list(prior["labelnames"]) != list(entry["labelnames"])):
                raise ValueError(
                    f"metric {name!r} disagrees across snapshots: "
                    f"{prior['type']}{prior['labelnames']} vs "
                    f"{entry['type']}{entry['labelnames']}")
            values = prior["values"]
            for key, v in entry["values"].items():
                cur = values.get(key)
                if cur is None:
                    values[key] = dict(v) if isinstance(v, dict) else v
                elif entry["type"] == "histogram":
                    values[key] = _merge_hist_cells([cur, v])
                elif entry["type"] == "gauge":
                    values[key] = max(cur, v)
                else:  # counter (and untyped): additive
                    values[key] = cur + v
    return out


#: host p2p tag reserved for the snapshot exchange (outside the small-int
#: tag space library algorithms use)
_GATHER_TAG = 0x7E1E


def gather(comms, timeout: float = 60.0, *,
           strict: bool = False) -> Dict[str, object]:
    """Collect every host process's :func:`snapshot` over *comms*' host
    p2p plane and return the fleet view on EVERY host::

        {"world": n_host_processes,
         "hosts": {"0": snapshot, "1": snapshot, ...},   # rank-keyed
         "rollup": merge(all collected host snapshots),
         "partial": False, "missing_ranks": []}

    Should be called collectively by every host process of the
    communicator (a symmetric all-to-all exchange of JSON-safe dicts;
    *timeout* bounds each pending receive).  On a single-process
    communicator — including one driving a whole multi-device mesh — this
    returns immediately with the local snapshot as both the only host
    view and the rollup.

    **Degradation contract**: a dead or slow host must not turn the fleet
    rollup into a timeout for every OTHER rank — an unreachable peer is
    recorded in ``missing_ranks`` (and ``partial: true``), its row is
    absent from ``hosts``, and the rollup merges whatever arrived.  A
    failed telemetry exchange is deliberately NOT treated as a broken
    data-plane clique: the communicator's aborted flag is restored to its
    prior value (the observability plane must never poison the compute
    plane).  ``strict=True`` restores the raise-on-first-failure
    behavior for callers that prefer a loud error to a partial view."""
    local = snapshot()
    world = int(getattr(comms, "_host_world", 1) or 1)
    rank = int(getattr(comms, "_host_rank", 0) or 0)
    hosts: Dict[str, dict] = {str(rank): local}
    missing: List[int] = []
    if world > 1:
        peers = [r for r in range(world) if r != rank]
        prior_aborted = bool(getattr(comms, "_aborted", False))
        for r in peers:
            try:
                comms.isend(local, dst=r, tag=_GATHER_TAG)
            except Exception:
                if strict:
                    raise
                # the peer will learn of us (or not) on its own recv; our
                # collection below decides whether IT is reachable
                comms._aborted = prior_aborted
        for r in peers:
            try:
                hosts[str(r)] = comms.waitall(
                    [comms.irecv(src=r, tag=_GATHER_TAG)],
                    timeout=timeout)[0]
            except Exception:
                if strict:
                    raise
                missing.append(r)
                comms._aborted = prior_aborted
    rollup = merge([hosts[k] for k in sorted(hosts, key=int)])
    return {"world": world, "hosts": hosts, "rollup": rollup,
            "partial": bool(missing), "missing_ranks": missing}
