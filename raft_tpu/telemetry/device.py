"""Device-cost attribution: what the accelerator did, per program.

Host spans (:mod:`.spans`) time the REQUEST path; this module attributes
cost to the DEVICE programs behind it, in two halves (docs/observability.md
§device attribution):

* **Static, at compile time** — every AOT cache miss harvests the compiled
  executable's ``cost_analysis()`` / ``memory_analysis()`` into
  per-(fn, sig) gauges:

  - ``raft_tpu_program_flops{fn,sig}``
  - ``raft_tpu_program_bytes_accessed{fn,sig}``
  - ``raft_tpu_program_temp_bytes{fn,sig}``

  This is the same static analysis the HLO auditor proves transient/budget
  ceilings against (:mod:`raft_tpu.analysis.hlo_audit` feeds its audit
  shapes through :func:`record_program_costs` too, under ``sig="audit"``),
  now exported live so an operator can read each serving program's cost
  model off ``/metrics`` instead of re-deriving it.

* **Sampled, at dispatch time** — compiled executables dispatch
  asynchronously, so host-side dispatch latency says nothing about device
  time.  Every Nth WARM dispatch of each function
  (``RAFT_TPU_DEVICE_SAMPLE``, default 1/64; the FIRST warm dispatch is
  always sampled so every program reports promptly) blocks on its output
  and records true submit→complete wall time into
  ``raft_tpu_device_seconds{fn}``.  Combining the sample with the static
  half yields roofline-style achieved rates:

  - ``raft_tpu_device_flops_per_second{fn}``
  - ``raft_tpu_device_bytes_per_second{fn}``

Hot-path discipline: the per-dispatch cost when a dispatch is NOT sampled
is one enabled() check + one lock-guarded counter bump + a modulo; a
sampled dispatch additionally blocks on an output the caller was about to
consume anyway (the serve engine fetches results host-side right after
dispatch).  ``RAFT_TPU_TELEMETRY=0`` turns sampling off entirely, and the
serve bench's telemetry-on A/B gates the whole instrumented path —
device sampling at the default rate included — at < 3% qps overhead.

Sampling measures from just before the executable call to output
readiness, so a sample includes submit overhead; at the >= millisecond
program scale this attributes, that bias is noise.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Tuple

from raft_tpu.telemetry import registry as _registry

#: default sampling period: one blocked (device-timed) dispatch per this
#: many warm dispatches of each function
DEFAULT_SAMPLE_EVERY = 64

_sample_every: Optional[int] = None

#: guards the per-fn dispatch counters and the static-cost table (NOT the
#: metrics — those take the registry lock themselves)
_LOCK = threading.Lock()
_dispatch_counts: Dict[str, int] = {}
#: (fn, sig) → (flops, bytes_accessed) harvested at compile time, read at
#: sample time to derive achieved rates
_static_costs: Dict[Tuple[str, str], Tuple[Optional[float],
                                           Optional[float]]] = {}

_program_flops = None
_program_bytes = None
_program_temp = None
_device_seconds = None
_device_flops_rate = None
_device_bytes_rate = None


def sample_every() -> int:
    """The device-sampling period N (one blocked dispatch per N warm
    dispatches per function).  ``RAFT_TPU_DEVICE_SAMPLE`` at first use, or
    :func:`set_sample_every`; ``0`` disables sampling."""
    global _sample_every
    if _sample_every is None:
        try:
            _sample_every = int(os.environ.get(
                "RAFT_TPU_DEVICE_SAMPLE", str(DEFAULT_SAMPLE_EVERY)))
        except ValueError:
            _sample_every = DEFAULT_SAMPLE_EVERY
    return _sample_every


def set_sample_every(n: int) -> int:
    """Set the sampling period at runtime (0 disables).  Returns the
    previous value — tests and the bench A/B save/restore with it."""
    global _sample_every
    prev = sample_every()
    _sample_every = max(0, int(n))
    return prev


def _metrics():
    global _program_flops, _program_bytes, _program_temp
    global _device_seconds, _device_flops_rate, _device_bytes_rate
    if _program_flops is None:
        reg = _registry
        _program_flops = reg.REGISTRY.gauge(
            "raft_tpu_program_flops",
            "XLA cost_analysis flops per compiled program (fn, signature)",
            labelnames=("fn", "sig"))
        _program_bytes = reg.REGISTRY.gauge(
            "raft_tpu_program_bytes_accessed",
            "XLA cost_analysis bytes accessed per compiled program",
            labelnames=("fn", "sig"))
        _program_temp = reg.REGISTRY.gauge(
            "raft_tpu_program_temp_bytes",
            "memory_analysis transient (temp) bytes per compiled program",
            labelnames=("fn", "sig"))
        _device_seconds = reg.REGISTRY.histogram(
            "raft_tpu_device_seconds",
            "sampled device execution wall time per AOT function",
            labelnames=("fn",))
        _device_flops_rate = reg.REGISTRY.gauge(
            "raft_tpu_device_flops_per_second",
            "achieved FLOP/s of the latest device sample (static flops / "
            "sampled device seconds)",
            labelnames=("fn",))
        _device_bytes_rate = reg.REGISTRY.gauge(
            "raft_tpu_device_bytes_per_second",
            "achieved bytes/s of the latest device sample (static bytes "
            "accessed / sampled device seconds)",
            labelnames=("fn",))
    return (_program_flops, _program_bytes, _program_temp,
            _device_seconds, _device_flops_rate, _device_bytes_rate)


def program_costs(compiled) -> Dict[str, Optional[float]]:
    """Harvest ``{"flops", "bytes_accessed", "temp_bytes"}`` from one
    compiled executable — robust to backends where either analysis is
    unavailable (a missing number is None, never an exception).  jax
    returns ``cost_analysis()`` as a per-device list on some versions and
    a plain dict on others; both shapes are accepted."""
    flops = nbytes = temp = None
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if isinstance(ca, dict):
            if ca.get("flops") is not None:
                flops = float(ca["flops"])
            if ca.get("bytes accessed") is not None:
                nbytes = float(ca["bytes accessed"])
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            temp = float(ma.temp_size_in_bytes)
    except Exception:
        pass
    return {"flops": flops, "bytes_accessed": nbytes, "temp_bytes": temp}


def record_program_costs(fn: str, sig: str,
                         compiled) -> Dict[str, Optional[float]]:
    """Compile-time half of the attribution: harvest *compiled*'s static
    costs into the per-(fn, sig) gauges and cache the (flops, bytes) pair
    for dispatch-time rate derivation.  Called once per AOT cache miss
    (and by the HLO auditor under ``sig="audit"``) — never on the dispatch
    path.  Returns the harvested dict."""
    costs = program_costs(compiled)
    with _LOCK:
        _static_costs[(fn, sig)] = (costs["flops"], costs["bytes_accessed"])
    g_flops, g_bytes, g_temp = _metrics()[:3]
    labels = (fn, sig)
    if costs["flops"] is not None:
        g_flops.set(costs["flops"], labels)
    if costs["bytes_accessed"] is not None:
        g_bytes.set(costs["bytes_accessed"], labels)
    if costs["temp_bytes"] is not None:
        g_temp.set(costs["temp_bytes"], labels)
    return costs


def sample_due(fn: str) -> bool:
    """Per-WARM-dispatch gate: bump *fn*'s dispatch count and return True
    when this dispatch should block for a device-time sample (count 0,
    then every Nth).  False whenever telemetry is disabled or sampling is
    off — the not-sampled cost is this check plus one locked add."""
    if not _registry.enabled():
        return False
    n = sample_every()
    if n <= 0:
        return False
    with _LOCK:
        c = _dispatch_counts.get(fn, 0)
        _dispatch_counts[fn] = c + 1
    return c % n == 0


def record_sample(fn: str, sig: str, seconds: float) -> None:
    """Record one blocked-dispatch device-time sample and refresh the
    achieved-rate gauges from the (fn, sig) static costs."""
    _, _, _, hist, g_fr, g_br = _metrics()
    hist.observe(seconds, (fn,))
    if seconds <= 0.0:
        return
    with _LOCK:
        flops, nbytes = _static_costs.get((fn, sig), (None, None))
    if flops is not None:
        g_fr.set(flops / seconds, (fn,))
    if nbytes is not None:
        g_br.set(nbytes / seconds, (fn,))
