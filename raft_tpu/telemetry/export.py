"""Exporters: plain-dict snapshot and Prometheus text exposition.

Both walk the default registry read-only; value lists are copied under
the registry lock per metric (a scrape racing live traffic may observe a
histogram mid-observation — counts torn by at most the in-flight sample,
never a crash).  Both work with telemetry disabled — they render whatever
the live counters accumulated (recording gates live at the instrument,
not the exporter)."""

from __future__ import annotations

from typing import Dict, List, Tuple

from raft_tpu.telemetry.registry import (
    HIST_BUCKETS,
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    bucket_upper,
)


def _label_key(labelnames: Tuple[str, ...], labels: Tuple[str, ...]) -> str:
    """One flat, JSON-safe key per label-value tuple (`k=v,k2=v2`, or ""
    for the unlabeled cell) — keeps :func:`snapshot` round-trippable
    through ``json.dumps``/``loads`` (dict keys must be strings)."""
    return ",".join(f"{k}={v}" for k, v in zip(labelnames, labels))


def snapshot(registry=None) -> Dict[str, dict]:
    """The whole registry as one plain, JSON-serializable dict.

    ``{metric_name: {"type", "help", "labelnames", "values"}}`` where
    ``values`` maps the flat label key (:func:`_label_key`) to either a
    number (counter/gauge) or, for histograms, a dict with ``count``,
    ``sum``, ``min``, ``max``, the non-empty ``buckets`` as
    ``[[upper_bound_s, count], ...]`` and convenience ``p50``/``p99``
    estimates.  ``json.loads(json.dumps(snapshot()))`` reproduces it
    exactly (tests/test_telemetry.py pins the round trip).  *registry*
    defaults to the process-wide one; passing another
    :class:`~raft_tpu.telemetry.Registry` snapshots that instead (the
    fleet merge property tests build per-shard registries this way)."""
    out: Dict[str, dict] = {}
    for m in (REGISTRY if registry is None else registry).metrics():
        entry = {"type": m.kind, "help": m.help,
                 "labelnames": list(m.labelnames)}
        values: Dict[str, object] = {}
        if isinstance(m, (Counter, Gauge)):
            for labels, v in m.items():
                values[_label_key(m.labelnames, labels)] = v
        elif isinstance(m, Histogram):
            for labels, cell in m.items():
                buckets = [[round(bucket_upper(i), 9), n]
                           for i, n in enumerate(cell.counts) if n]
                values[_label_key(m.labelnames, labels)] = {
                    "count": cell.count, "sum": cell.sum,
                    "min": cell.min, "max": cell.max,
                    "buckets": buckets,
                    "p50": m.quantile(0.5, labels),
                    "p99": m.quantile(0.99, labels),
                }
        entry["values"] = values
        out[m.name] = entry
    return out


def _prom_name(name: str) -> str:
    """Prometheus metric names: [a-zA-Z_:][a-zA-Z0-9_:]*."""
    return "".join(c if c.isalnum() or c in "_:" else "_" for c in name)


def _prom_label_str(labelnames: Tuple[str, ...], labels: Tuple[str, ...],
                    extra: Tuple[Tuple[str, str], ...] = ()) -> str:
    pairs = list(zip(labelnames, labels)) + list(extra)
    if not pairs:
        return ""
    def esc(v: str) -> str:
        return str(v).replace("\\", "\\\\").replace('"', '\\"').replace(
            "\n", "\\n")
    return "{" + ",".join(f'{k}="{esc(v)}"' for k, v in pairs) + "}"


def _fmt(v: float) -> str:
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def prometheus_text() -> str:
    """The registry in Prometheus text exposition format (one scrape body).

    Counters/gauges render as single samples; histograms render the
    standard triplet — cumulative ``_bucket{le=...}`` series ending at
    ``le="+Inf"``, plus ``_sum`` and ``_count``.  Serve this from any HTTP
    handler (or dump it periodically) to plug raft_tpu into an existing
    Prometheus/Grafana stack without a client-library dependency."""
    lines: List[str] = []
    for m in REGISTRY.metrics():
        name = _prom_name(m.name)
        if m.help:
            lines.append(f"# HELP {name} {m.help}")
        lines.append(f"# TYPE {name} {m.kind}")
        if isinstance(m, (Counter, Gauge)):
            for labels, v in sorted(m.items()):
                lines.append(
                    f"{name}{_prom_label_str(m.labelnames, labels)} "
                    f"{_fmt(v)}")
        elif isinstance(m, Histogram):
            for labels, cell in sorted(m.items()):
                cum = 0
                for i in range(HIST_BUCKETS):
                    cum += cell.counts[i]
                    if cell.counts[i]:  # sparse: emit buckets that moved
                        le = f"{bucket_upper(i):.9g}"
                        lines.append(
                            f"{name}_bucket"
                            f"{_prom_label_str(m.labelnames, labels, (('le', le),))}"
                            f" {cum}")
                lines.append(
                    f"{name}_bucket"
                    f"{_prom_label_str(m.labelnames, labels, (('le', '+Inf'),))}"
                    f" {cell.count}")
                lines.append(
                    f"{name}_sum{_prom_label_str(m.labelnames, labels)} "
                    f"{repr(float(cell.sum))}")
                lines.append(
                    f"{name}_count{_prom_label_str(m.labelnames, labels)} "
                    f"{cell.count}")
    return "\n".join(lines) + "\n"
