"""Live scrape surface: /metrics, /healthz, /varz, /debug/slow.

Stdlib-only (``http.server.ThreadingHTTPServer``) so a serving process
plugs into an existing Prometheus/Grafana stack with zero dependencies
(docs/observability.md §scrape endpoints).  Endpoints:

* ``/metrics``    — :func:`raft_tpu.telemetry.prometheus_text` (text
  exposition, content type ``text/plain; version=0.0.4``).
* ``/healthz``    — JSON readiness from the installed health callback
  (``ServeEngine.serve_http`` wires engine readiness: warmed buckets
  present, no refresh in flight).  HTTP 200 when ``ready``, 503 when not
  — the shape load balancers and k8s probes consume.
* ``/varz``       — the full :func:`raft_tpu.telemetry.snapshot` as JSON
  (or a caller-supplied provider, e.g. a fleet
  :func:`raft_tpu.telemetry.gather` view).
* ``/debug/slow`` — the flight recorder: a BOUNDED ring of span trees for
  requests that breached a latency threshold, newest last.

Every handler renders under the registry's own read locks (snapshots copy
per metric), so a scrape racing live traffic is torn by at most the
in-flight observation — never a crash, never a request-path stall.  This
module is the ONE sanctioned home for metric endpoints: the
``telemetry-discipline`` analysis rule flags raw ``http.server`` use
elsewhere in the library.
"""

from __future__ import annotations

import collections
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional

from raft_tpu.telemetry import export as _export

#: default /debug/slow latency threshold (seconds) and ring capacity
DEFAULT_SLOW_THRESHOLD_S = 0.25
DEFAULT_SLOW_CAP = 64


def _span_tree(events: List[dict]) -> List[dict]:
    """Nest a completion-ordered event list (children complete before
    parents — the collector/JSONL order) into trees.  Events are grouped
    per thread first (each thread's span stack nests independently); a
    parent at depth d adopts every pending subtree at depth d+1."""
    roots: List[dict] = []
    by_thread: Dict[int, List[dict]] = {}
    for e in events:
        by_thread.setdefault(e.get("thread", 0), []).append(e)
    for tevents in by_thread.values():
        pending: Dict[int, List[dict]] = {}
        for e in tevents:
            d = int(e.get("depth", 0))
            node = dict(e)
            node["children"] = pending.pop(d + 1, [])
            pending.setdefault(d, []).append(node)
        # depth-0 spans are proper roots; anything left at a deeper depth
        # means the collector opened mid-nesting — surface it, don't drop
        for d in sorted(pending):
            roots.extend(pending[d])
    return roots


class FlightRecorder:
    """Bounded ring of slow-request span trees (the /debug/slow body).

    ``record(events, **meta)`` nests the collected span events
    (:class:`raft_tpu.telemetry.collect_spans` order) into a tree and
    appends one entry; the deque drops the oldest beyond *cap*, so a
    pathological traffic pattern costs a constant ~cap trees of memory no
    matter how long it lasts.  ``seen`` counts every recorded entry
    (including since-evicted ones), so "how often are we slow" survives
    the ring wrapping."""

    def __init__(self, threshold_s: float = DEFAULT_SLOW_THRESHOLD_S,
                 cap: int = DEFAULT_SLOW_CAP):
        self.threshold_s = float(threshold_s)
        self.cap = int(cap)
        self.seen = 0
        self._ring = collections.deque(maxlen=self.cap)
        self._lock = threading.Lock()

    def record(self, events: List[dict], **meta) -> None:
        entry = dict(meta)
        entry["spans"] = _span_tree(events)
        with self._lock:
            self.seen += 1
            entry["seq"] = self.seen
            self._ring.append(entry)

    def entries(self) -> List[dict]:
        """Ring contents, oldest first (each entry JSON-safe)."""
        with self._lock:
            return list(self._ring)

    def view(self) -> dict:
        """The /debug/slow JSON body."""
        with self._lock:
            return {"threshold_s": self.threshold_s, "cap": self.cap,
                    "recorded": self.seen, "entries": list(self._ring)}


class TelemetryServer:
    """The scrape server.  ``port=0`` binds an ephemeral port (read it
    back from ``.port``); ``start()`` serves on a daemon thread and
    returns self; ``close()`` shuts down and joins.  Also a context
    manager.  *health* and *varz* are zero-arg callables returning
    JSON-safe dicts; *recorder* supplies /debug/slow."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1", *,
                 health: Optional[Callable[[], dict]] = None,
                 varz: Optional[Callable[[], dict]] = None,
                 recorder: Optional[FlightRecorder] = None):
        self._health = health
        self._varz = varz
        self.recorder = recorder
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # no stderr line per scrape
                pass

            def do_GET(self):
                try:
                    body, ctype, code = outer._route(self.path)
                except Exception as e:  # a handler bug must not kill serving
                    body = json.dumps({"error": repr(e)}).encode()
                    ctype, code = "application/json", 500
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((host, int(port)), _Handler)
        self._server.daemon_threads = True
        self.host = host
        self.port = int(self._server.server_address[1])
        self._thread: Optional[threading.Thread] = None

    def _route(self, path: str):
        path = path.split("?", 1)[0]
        if path == "/metrics":
            return (_export.prometheus_text().encode(),
                    "text/plain; version=0.0.4; charset=utf-8", 200)
        if path == "/healthz":
            health = self._health() if self._health is not None else {
                "ready": True}
            code = 200 if health.get("ready", True) else 503
            return json.dumps(health).encode(), "application/json", code
        if path == "/varz":
            varz = (self._varz() if self._varz is not None
                    else _export.snapshot())
            return json.dumps(varz).encode(), "application/json", 200
        if path == "/debug/slow":
            view = (self.recorder.view() if self.recorder is not None
                    else {"threshold_s": None, "cap": 0, "recorded": 0,
                          "entries": []})
            return json.dumps(view).encode(), "application/json", 200
        return (json.dumps({
            "error": "not found",
            "endpoints": ["/metrics", "/healthz", "/varz", "/debug/slow"],
        }).encode(), "application/json", 404)

    def start(self) -> "TelemetryServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._server.serve_forever,
                name=f"raft-tpu-telemetry-http-{self.port}", daemon=True)
            self._thread.start()
        return self

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def __enter__(self) -> "TelemetryServer":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


def serve(port: int = 0, host: str = "127.0.0.1", *,
          health: Optional[Callable[[], dict]] = None,
          varz: Optional[Callable[[], dict]] = None,
          recorder: Optional[FlightRecorder] = None) -> TelemetryServer:
    """Start a standalone scrape server over the process-wide registry
    (``ServeEngine.serve_http`` is the engine-wired form).  Returns the
    started :class:`TelemetryServer`; caller owns ``close()``."""
    return TelemetryServer(port, host, health=health, varz=varz,
                           recorder=recorder).start()
