"""The metrics registry: labeled counters, gauges, and fixed-memory
log-bucketed histograms behind ONE process-wide lock.

Before this module, runtime telemetry was five disconnected fragments —
``Comms.collective_calls``, ``core.aot.aot_compile_counters``,
``ivf_pq.lut_trace_counters``, ``ServeEngine.stats`` and the unbounded
``ServeEngine.last_latencies`` list — plain dicts/Counters whose
``c[k] += 1`` read-modify-write races under concurrent
``ServeEngine.search()`` callers, with no export path and no bounded-memory
latency distributions.  The registry replaces the storage while the legacy
read surfaces stay byte-for-byte valid (:class:`LegacyCounterView`).

Design points (docs/observability.md):

* **One lock.**  Every mutation takes the single module lock
  (:data:`_LOCK`).  An uncontended ``threading.Lock`` acquire is ~100 ns —
  far below the serve hot path's per-dispatch budget — and one lock keeps
  snapshot/export trivially consistent.  Reads of individual values take
  the same lock; :func:`snapshot`-style bulk reads copy under it.
* **Fixed-memory histograms.**  :class:`Histogram` buckets observations
  into ``HIST_BUCKETS`` (64) log-spaced bins spanning 1 µs – 100 s
  (under/overflow clamp into the edge bins), so a latency distribution
  costs a constant ~64 ints no matter how long the process serves.
  Quantiles interpolate within the hit bucket and are clamped to the
  observed min/max, so the estimate is never off by more than one bucket
  ratio (~×1.33) from the exact sample quantile.
* **Bounded reservoirs.**  :class:`Reservoir` keeps a uniform sample of at
  most ``cap`` observations (Vitter's algorithm R with a deterministic
  LCG), for exact-sample percentiles over a bounded window.
* **Disable gate.**  ``RAFT_TPU_TELEMETRY=0`` turns histogram/gauge/
  reservoir recording and span tracing into no-ops
  (:func:`raft_tpu.telemetry.enabled`).  COUNTERS STAY LIVE: the legacy
  counters are load-bearing contract instruments (the zero-compile serve
  gates, one-allreduce-per-iteration MNMG asserts, LUT trace asserts) and
  a counter bump is already "a few arithmetic ops" — the disable gate
  exists to shed timing/recording work, not correctness bookkeeping.
"""

from __future__ import annotations

import math
import os
import threading
from collections.abc import Mapping
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# the global enable gate

_ENABLED = os.environ.get("RAFT_TPU_TELEMETRY", "1") != "0"


def enabled() -> bool:
    """True unless telemetry is globally disabled (``RAFT_TPU_TELEMETRY=0``
    at import, or :func:`set_enabled`).  Gates spans, histogram/gauge/
    reservoir recording and the JSONL sink; counters stay live (see module
    docstring)."""
    return _ENABLED


def set_enabled(flag: bool) -> bool:
    """Flip the global gate at runtime (the bench's telemetry-off A/B side
    and the disabled-mode identity tests use this).  Returns the previous
    value."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = bool(flag)
    return prev


#: THE registry lock — one per process, shared by every metric, so
#: concurrent ``ServeEngine.search()`` callers can no longer lose
#: increments to the Counter read-modify-write race.
_LOCK = threading.Lock()


# ---------------------------------------------------------------------------
# histograms: fixed-memory log-bucketed latency distributions

#: bucket geometry: HIST_BUCKETS log-spaced bins spanning [HIST_MIN, HIST_MAX]
#: seconds; values outside clamp into the edge bins.
HIST_MIN = 1e-6
HIST_MAX = 100.0
HIST_BUCKETS = 64
_LOG_MIN = math.log(HIST_MIN)
_LOG_STEP = (math.log(HIST_MAX) - _LOG_MIN) / HIST_BUCKETS


def bucket_index(value: float) -> int:
    """The bucket a (seconds) observation lands in — pure arithmetic, no
    allocation (the hot-path cost of one histogram observation is this plus
    three adds under the lock)."""
    if value <= HIST_MIN:
        return 0
    if value >= HIST_MAX:
        return HIST_BUCKETS - 1
    return int((math.log(value) - _LOG_MIN) / _LOG_STEP)


def bucket_upper(i: int) -> float:
    """Upper edge (seconds) of bucket *i*."""
    return math.exp(_LOG_MIN + (i + 1) * _LOG_STEP)


def quantile_from_counts(counts: Sequence[int], total: int, lo: float,
                         hi: float, q: float) -> Optional[float]:
    """THE bucket-quantile rule — interpolate within the hit bucket, clamp
    to the observed [lo, hi] — over a raw bucket-count vector.  The ONE
    implementation behind both :meth:`Histogram.quantile` and the fleet
    merge's re-estimated p50/p99 (:mod:`raft_tpu.telemetry.aggregate`), so
    a rollup's quantiles can never silently diverge from per-host ones.
    None when *total* is zero."""
    if total <= 0:
        return None
    target = q * total
    acc = 0.0
    for i, n in enumerate(counts):
        if n == 0:
            continue
        if acc + n >= target:
            # linear interpolation within the (log-spaced) bucket
            lower = HIST_MIN if i == 0 else bucket_upper(i - 1)
            frac = (target - acc) / n
            est = lower + frac * (bucket_upper(i) - lower)
            return min(max(est, lo), hi)
        acc += n
    return hi


class Reservoir:
    """Bounded uniform sample (Vitter's algorithm R) — the exact-sample
    companion of a histogram: at most *cap* floats no matter how many
    observations arrive.  Deterministic LCG replacement stream, so tests
    are reproducible without the global ``random`` state."""

    __slots__ = ("cap", "samples", "seen", "_lcg")

    def __init__(self, cap: int = 4096):
        self.cap = int(cap)
        self.samples: List[float] = []
        self.seen = 0
        self._lcg = 0x9E3779B9

    def add(self, value: float) -> None:
        # caller holds _LOCK (metric-internal) or owns the instance
        self.seen += 1
        if len(self.samples) < self.cap:
            self.samples.append(value)
            return
        # LCG step (numerical recipes constants); uniform slot in [0, seen)
        self._lcg = (self._lcg * 1664525 + 1013904223) & 0xFFFFFFFF
        slot = self._lcg % self.seen
        if slot < self.cap:
            self.samples[slot] = value


class _HistState:
    """Per-label-tuple histogram cell: 64 bucket counts + count/sum/min/max."""

    __slots__ = ("counts", "count", "sum", "min", "max")

    def __init__(self):
        self.counts = [0] * HIST_BUCKETS
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf


class Metric:
    """Base: a named metric with a fixed label-name tuple.  Values are
    keyed by label-VALUE tuples (strings), matching prometheus's model."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Tuple[str, ...]):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)

    def _key(self, labels: Tuple[str, ...]) -> Tuple[str, ...]:
        if len(labels) != len(self.labelnames):
            raise ValueError(
                f"metric {self.name}: got {len(labels)} label values for "
                f"labelnames {self.labelnames}")
        return tuple(str(v) for v in labels)


class Counter(Metric):
    """Monotonic labeled counter.  ``inc`` is atomic under the registry
    lock — the thread-safe replacement for ``Counter[k] += 1``."""

    kind = "counter"

    def __init__(self, name: str, help: str, labelnames: Tuple[str, ...]):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1, labels: Tuple[str, ...] = ()) -> None:
        key = self._key(labels)
        with _LOCK:
            self._values[key] = self._values.get(key, 0) + amount

    def set(self, value: float, labels: Tuple[str, ...] = ()) -> None:
        """Absolute set — exists for the legacy Counter views' item
        assignment compat (``view[k] = 0`` snapshots); not part of the
        prometheus counter contract."""
        with _LOCK:
            self._values[self._key(labels)] = value

    def get(self, labels: Tuple[str, ...] = ()) -> float:
        with _LOCK:
            return self._values.get(self._key(labels), 0)

    def remove(self, labels: Tuple[str, ...]) -> None:
        with _LOCK:
            self._values.pop(self._key(labels), None)

    def items(self) -> List[Tuple[Tuple[str, ...], float]]:
        with _LOCK:
            return list(self._values.items())


class Gauge(Metric):
    """Labeled point-in-time value.  Recording is gated by
    :func:`enabled` (a gauge is telemetry, not contract bookkeeping)."""

    kind = "gauge"

    def __init__(self, name: str, help: str, labelnames: Tuple[str, ...]):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def set(self, value: float, labels: Tuple[str, ...] = ()) -> None:
        if not _ENABLED:
            return
        with _LOCK:
            self._values[self._key(labels)] = value

    def get(self, labels: Tuple[str, ...] = ()) -> float:
        with _LOCK:
            return self._values.get(self._key(labels), 0)

    def items(self) -> List[Tuple[Tuple[str, ...], float]]:
        with _LOCK:
            return list(self._values.items())


class Histogram(Metric):
    """Labeled log-bucketed histogram (fixed memory per label set; see
    module docstring for the bucket geometry).  ``observe`` is gated by
    :func:`enabled`."""

    kind = "histogram"

    def __init__(self, name: str, help: str, labelnames: Tuple[str, ...],
                 reservoir: int = 0):
        super().__init__(name, help, labelnames)
        self._cells: Dict[Tuple[str, ...], _HistState] = {}
        self._reservoir_cap = int(reservoir)
        self._reservoirs: Dict[Tuple[str, ...], Reservoir] = {}

    def observe(self, value: float, labels: Tuple[str, ...] = ()) -> None:
        if not _ENABLED:
            return
        value = float(value)
        i = bucket_index(value)
        key = self._key(labels)
        with _LOCK:
            cell = self._cells.get(key)
            if cell is None:
                cell = self._cells[key] = _HistState()
            cell.counts[i] += 1
            cell.count += 1
            cell.sum += value
            if value < cell.min:
                cell.min = value
            if value > cell.max:
                cell.max = value
            if self._reservoir_cap:
                r = self._reservoirs.get(key)
                if r is None:
                    r = self._reservoirs[key] = Reservoir(self._reservoir_cap)
                r.add(value)

    def cell(self, labels: Tuple[str, ...] = ()) -> Optional[_HistState]:
        with _LOCK:
            return self._cells.get(self._key(labels))

    def reservoir(self, labels: Tuple[str, ...] = ()) -> List[float]:
        with _LOCK:
            r = self._reservoirs.get(self._key(labels))
            return list(r.samples) if r is not None else []

    def count(self, labels: Tuple[str, ...] = ()) -> int:
        c = self.cell(labels)
        return c.count if c is not None else 0

    def quantile(self, q: float, labels: Tuple[str, ...] = ()
                 ) -> Optional[float]:
        """Bucket-interpolated quantile estimate, clamped to the observed
        [min, max] — within one bucket ratio (~×1.33) of the exact sample
        quantile (tests/test_telemetry.py pins this against
        ``np.percentile``).  None when the cell is empty."""
        with _LOCK:
            cell = self._cells.get(self._key(labels))
            if cell is None or cell.count == 0:
                return None
            counts = list(cell.counts)
            total, lo, hi = cell.count, cell.min, cell.max
        return quantile_from_counts(counts, total, lo, hi, q)

    def items(self) -> List[Tuple[Tuple[str, ...], _HistState]]:
        with _LOCK:
            return list(self._cells.items())


def merged_quantile(hist: "Histogram", q: float,
                    prefix: Tuple[str, ...]) -> Optional[float]:
    """Quantile estimate over the UNION of every cell whose label tuple
    starts with *prefix* — folded bucket-wise on the shared fixed log
    geometry (the :mod:`raft_tpu.telemetry.aggregate` merge property),
    then interpolated by the ONE :func:`quantile_from_counts` rule.

    This is how a per-(fn, sig) histogram (e.g.
    ``raft_tpu_aot_dispatch_seconds``) answers a per-fn question: merge
    all of *fn*'s signature rows rather than privileging one.  Both the
    serve admission cost model and the continuous-batching scheduler
    seed their estimates through here.  None when nothing matched."""
    counts: Optional[List[int]] = None
    total, lo, hi = 0, float("inf"), float("-inf")
    for labels, cell in hist.items():
        if labels[:len(prefix)] != tuple(prefix) or cell.count == 0:
            continue
        if counts is None:
            counts = [0] * len(cell.counts)
        for i, n in enumerate(cell.counts):
            counts[i] += n
        total += cell.count
        lo, hi = min(lo, cell.min), max(hi, cell.max)
    if counts is None or not total:
        return None
    return quantile_from_counts(counts, total, lo, hi, q)


# ---------------------------------------------------------------------------
# the registry


class Registry:
    """Name → metric.  ``counter``/``gauge``/``histogram`` are get-or-create
    (idempotent re-registration with the same kind/labelnames returns the
    existing metric, so module reloads don't crash); a kind or labelname
    mismatch raises."""

    def __init__(self):
        self._metrics: Dict[str, Metric] = {}

    def _get_or_create(self, cls, name: str, help: str,
                       labelnames: Sequence[str], **kw) -> Any:
        with _LOCK:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls or m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind}{m.labelnames}")
                return m
            m = cls(name, help, tuple(labelnames), **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  reservoir: int = 0) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   reservoir=reservoir)

    def metrics(self) -> List[Metric]:
        with _LOCK:
            return [m for _, m in sorted(self._metrics.items())]

    def get(self, name: str) -> Optional[Metric]:
        with _LOCK:
            return self._metrics.get(name)

    def reset(self) -> None:
        """Drop every metric — test-isolation helper for metrics created
        IN the test.  Library code never calls this, and callers must not
        reset the default registry under a live library: existing
        :class:`LegacyCounterView` instances (``aot_compile_counters``,
        ``Comms.collective_calls``, engine ``stats``) pin their backing
        metric at construction, so after a reset they keep mutating
        orphaned Counters that exporters no longer see."""
        with _LOCK:
            self._metrics.clear()


#: the process-wide default registry (the exporters and the module-level
#: convenience constructors in :mod:`raft_tpu.telemetry` all use it)
REGISTRY = Registry()


# ---------------------------------------------------------------------------
# legacy Counter-shaped views


class LegacyCounterView(Mapping):
    """``collections.Counter``-shaped READ surface over one labeled
    registry counter — how the five pre-registry fragments keep their
    exact public API while the registry becomes the store.

    The view fixes every label except the last (``key``): e.g. each
    ``Comms`` instance holds a view with ``fixed=("3",)`` over
    ``comms_collective_calls{comm,key}``, so ``comms.collective_calls``
    still reads as a private per-instance mapping while the global
    registry (and every exporter) sees all instances.

    Reads: ``view[k]`` (missing → 0, the Counter contract), iteration,
    ``len``, ``.get``, ``.items``, ``dict(view)`` — everything the tests
    and benches do with the old Counters.  Writes: ``view.inc(k, n)`` is
    the ATOMIC increment library code migrated to; ``view[k] = v`` still
    works (absolute set under the lock) so ``view[k] += 1`` remains legal
    for external code, with the documented caveat that only ``inc`` is
    atomic across threads."""

    def __init__(self, metric: Counter, fixed: Tuple[str, ...] = ()):
        self._metric = metric
        self._fixed = tuple(str(v) for v in fixed)
        if len(self._fixed) + 1 != len(metric.labelnames):
            raise ValueError(
                f"view over {metric.name}{metric.labelnames} needs "
                f"{len(metric.labelnames) - 1} fixed label(s)")

    @property
    def fixed_labels(self) -> Tuple[str, ...]:
        """The pinned label prefix (e.g. this instance's ordinal) — lets a
        holder locate its own rows in a snapshot/fleet rollup, where keys
        render as ``"label=value,...,key=<k>"``."""
        return self._fixed

    # -- writes ----------------------------------------------------------
    def inc(self, key: str, amount: float = 1) -> None:
        """Atomic increment (the thread-safe ``c[k] += 1``)."""
        self._metric.inc(amount, self._fixed + (key,))

    def __setitem__(self, key: str, value: float) -> None:
        self._metric.set(value, self._fixed + (key,))

    def __delitem__(self, key: str) -> None:
        self._metric.remove(self._fixed + (key,))

    # -- Counter-shaped reads -------------------------------------------
    def __getitem__(self, key: str) -> float:
        v = self._metric.get(self._fixed + (key,))
        return int(v) if float(v).is_integer() else v

    def get(self, key: str, default: float = 0) -> float:
        v = self[key]
        return v if key in self else default

    def _keys(self) -> List[str]:
        n = len(self._fixed)
        return sorted(labels[n] for labels, _ in self._metric.items()
                      if labels[:n] == self._fixed)

    def __iter__(self) -> Iterator[str]:
        return iter(self._keys())

    def __len__(self) -> int:
        return len(self._keys())

    def __contains__(self, key: object) -> bool:
        return key in self._keys()

    def __repr__(self) -> str:
        return f"LegacyCounterView({dict(self)})"
