"""Host-side span tracing.

The reference instruments every algorithm entry point with NVTX ranges
(core/nvtx.hpp:95); raft_tpu's production analogue is a nested host span
that does three things at once:

* records wall time into the registry histogram
  ``raft_tpu_span_seconds{span=<name>}`` (fixed memory, exportable), and
  bumps ``raft_tpu_span_total{span=<name>}``;
* emits a ``jax.profiler.TraceAnnotation`` so the span shows up in TPU
  profiler traces exactly like the old ``core.logger.time_range`` (which
  is now a thin wrapper over this);
* optionally appends one JSON line per completed span to the opt-in JSONL
  sink (:func:`set_jsonl_sink`), carrying the span's name, parent chain,
  depth, thread, wall-clock start, duration and error flag — the event
  stream a trace viewer or log pipeline ingests.

Spans nest per thread (a thread-local stack carries the context across the
serve request lifecycle: ingest → coalesce → assemble → dispatch →
deliver) and are exception-safe: the exit path records the histogram and
pops the stack whether or not the body raised, and never swallows the
exception.

Hot-path discipline: entering a span is two perf_counter reads, a list
push/pop and one histogram observation — no device work, no syncs, no
allocation beyond the span object.  With telemetry disabled
(``RAFT_TPU_TELEMETRY=0``) :func:`span` returns a shared no-op context
manager: zero work, no profiler import, no timing.

``jax.profiler`` is imported ONCE at first use and cached module-level
(the old ``time_range`` paid an import-machinery lookup on every
``__enter__`` — inside the serve hot path that lookup is real per-request
work).
"""

from __future__ import annotations

import json
import threading
import time
from typing import IO, List, Optional, Union

from raft_tpu.telemetry import registry as _registry

#: the monotonic clock every raft_tpu timing site routes through (the
#: ``telemetry-discipline`` analysis rule bans raw ``time.perf_counter`` /
#: ``time.monotonic`` in hot-path-registry modules so timing stays
#: swappable and accounted here).
now = time.perf_counter

# -- cached profiler import (satellite: hoisted out of time_range.__enter__)
_PROFILER_TRACE = None
_PROFILER_TRIED = False


def _trace_annotation_cls():
    """``jax.profiler.TraceAnnotation`` or None, resolved once per process
    — a cached module-level try-import instead of a per-``__enter__``
    ``import jax.profiler`` (import machinery is a dict-lookup cascade that
    the serve hot path would pay per request)."""
    global _PROFILER_TRACE, _PROFILER_TRIED
    if not _PROFILER_TRIED:
        _PROFILER_TRIED = True
        try:
            from jax.profiler import TraceAnnotation

            _PROFILER_TRACE = TraceAnnotation
        except Exception:  # pragma: no cover - profiler unavailable
            _PROFILER_TRACE = None
    return _PROFILER_TRACE


# -- the per-thread span stack ----------------------------------------------

_TLS = threading.local()


def _stack() -> List[str]:
    s = getattr(_TLS, "stack", None)
    if s is None:
        s = _TLS.stack = []
    return s


def current_span() -> Optional[str]:
    """Name of the innermost open span on this thread, or None."""
    s = _stack()
    return s[-1] if s else None


class collect_spans:
    """Capture completed span EVENTS on this thread (context manager) —
    the same dicts the JSONL sink receives, appended to ``self.events`` in
    completion order (children before parents).  The serve engine's slow-
    request flight recorder wraps each request in one of these and keeps
    the event list only when the request breaches its latency threshold
    (:class:`raft_tpu.telemetry.http.FlightRecorder`).  Nests: an inner
    collector shadows the outer one for its duration."""

    __slots__ = ("events", "_prev")

    def __enter__(self) -> "collect_spans":
        self.events: List[dict] = []
        self._prev = getattr(_TLS, "collect", None)
        _TLS.collect = self.events
        return self

    def __exit__(self, *exc) -> bool:
        _TLS.collect = self._prev
        return False


# -- the JSONL event sink ----------------------------------------------------

_SINK_LOCK = threading.Lock()
_SINK: Optional[IO[str]] = None
_SINK_OWNED = False


def set_jsonl_sink(sink: Union[None, str, IO[str]]) -> None:
    """Install (or with None, remove) the opt-in span event sink.

    *sink* is a path (opened append, line-buffered writes, closed on
    replacement) or an open text file-like.  Each completed span appends
    one JSON object::

        {"span": "serve.dispatch", "parent": "serve.request", "depth": 1,
         "thread": 140211, "start": 1722772800.123, "dur_s": 0.0042,
         "error": false}

    Span completion order is exit order (children before parents), the
    natural order for rebuilding the tree from parent back-pointers."""
    global _SINK, _SINK_OWNED
    with _SINK_LOCK:
        if _SINK is not None and _SINK_OWNED:
            try:
                _SINK.close()
            except Exception:  # pragma: no cover - best-effort close
                pass
        if sink is None:
            _SINK, _SINK_OWNED = None, False
        elif isinstance(sink, str):
            _SINK, _SINK_OWNED = open(sink, "a"), True
        else:
            _SINK, _SINK_OWNED = sink, False


def _emit_event(event: dict) -> None:
    with _SINK_LOCK:
        if _SINK is None:
            return
        _SINK.write(json.dumps(event) + "\n")
        _SINK.flush()


# -- the span metrics (created lazily so import stays cheap) -----------------

_span_seconds = None
_span_total = None


def _metrics():
    global _span_seconds, _span_total
    if _span_seconds is None:
        _span_seconds = _registry.REGISTRY.histogram(
            "raft_tpu_span_seconds", "wall time of host-side spans",
            labelnames=("span",))
        _span_total = _registry.REGISTRY.counter(
            "raft_tpu_span_total", "completed host-side spans",
            labelnames=("span",))
    return _span_seconds, _span_total


class Span:
    """One live span — returned by :func:`span`; use as a context manager.

    Re-entrant use of a single instance is not supported (make a new span);
    the object is deliberately tiny (``__slots__``) because the serve path
    creates a handful per request batch."""

    __slots__ = ("name", "_t0", "_start_wall", "_ann", "_parent", "_depth")

    def __init__(self, name: str):
        self.name = name
        self._t0 = 0.0
        self._start_wall = 0.0
        self._ann = None
        self._parent: Optional[str] = None
        self._depth = 0

    def __enter__(self) -> "Span":
        stack = _stack()
        self._parent = stack[-1] if stack else None
        self._depth = len(stack)
        stack.append(self.name)
        cls = _trace_annotation_cls()
        if cls is not None:
            try:
                self._ann = cls(self.name)
                self._ann.__enter__()
            except Exception:  # pragma: no cover - profiler unavailable
                self._ann = None
        # wall-clock start is only consumed by the event path (JSONL sink
        # / span collector) — skip the third clock read otherwise
        self._start_wall = (
            time.time()
            if _SINK is not None or getattr(_TLS, "collect", None) is not None
            else 0.0)
        self._t0 = now()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        # EXCEPTION SAFETY: every recording step runs regardless of exc and
        # none may raise past this frame; the stack pop is unconditional.
        dur = now() - self._t0
        stack = _stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        elif self.name in stack:  # pragma: no cover - misnested defensive
            stack.remove(self.name)
        if self._ann is not None:
            try:
                self._ann.__exit__(exc_type, exc, tb)
            except Exception:  # pragma: no cover - profiler teardown
                pass
        hist, total = _metrics()
        hist.observe(dur, (self.name,))
        total.inc(1, (self.name,))
        collect = getattr(_TLS, "collect", None)
        if _SINK is not None or collect is not None:
            event = {
                "span": self.name, "parent": self._parent,
                "depth": self._depth,
                "thread": threading.get_ident(),
                "start": round(self._start_wall, 6),
                "dur_s": round(dur, 9),
                "error": exc_type is not None,
            }
            if collect is not None:
                collect.append(event)
            if _SINK is not None:
                _emit_event(event)
        return False  # never swallow


class _NoopSpan:
    """Shared do-nothing span for the disabled mode — one instance, zero
    per-call work."""

    __slots__ = ()
    name = "<disabled>"

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


def span(name: str) -> Union[Span, _NoopSpan]:
    """Open a nested host-side span (context manager) — see the module
    docstring for what a span records.  With telemetry disabled this is a
    shared no-op object."""
    if not _registry.enabled():
        return _NOOP
    return Span(name)
