"""Test machinery that ships WITH the library — currently the
deterministic fault-injection plane (:mod:`raft_tpu.testing.faults`).

It lives inside ``raft_tpu`` (not under ``tests/``) because the serving
engine, the communicator and the refresh path carry the injection hooks:
the hooks must import the plane from library code, and operators may
enable it in a staging process via ``RAFT_TPU_FAULT_PLAN`` without a
checkout of the test tree.
"""

from raft_tpu.testing.faults import (  # noqa: F401
    FaultPlan,
    InjectedFault,
    InjectedLogicFault,
    active_plan,
    check,
    clear_plan,
    install_plan,
    plan,
)

__all__ = ["FaultPlan", "InjectedFault", "InjectedLogicFault",
           "active_plan", "check", "clear_plan", "install_plan", "plan"]
