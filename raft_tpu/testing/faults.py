"""Deterministic fault-injection plane (docs/serving.md §failure model).

The failure-handling layer of ``raft_tpu/serve`` (admission, dispatch
supervision, atomic refresh) is only as trustworthy as the tests that
drive it through real failures — and "real" failures on a healthy CI host
have to be injected.  This module is the ONE injection surface: a seeded,
declarative plan of fault directives that library hooks consult at
well-defined sites.  OFF by default; when no plan is installed every hook
is a single ``is None`` check.

Sites (each hook names its site; directives select by site):

* ``dispatch`` — consulted by the serve supervisor once per super-batch
  COLLECTION attempt (where an async dispatch's failure actually
  surfaces), so ``raise`` models a failed device dispatch and ``stall``
  models a hung one.  Retries and isolation re-dispatches are attempts
  too: a directive with ``times=1`` (the default) injects exactly one
  failure and the retry then succeeds.
* ``comms`` — consulted by :class:`raft_tpu.comms.comms.Comms` on the
  host p2p plane (``isend``/``waitall``, at runtime) and in
  ``_count_collective`` (at TRACE time — collectives are staged into
  compiled programs, so a collective fault fires when the program traces,
  mirroring the trace-time nature of ``collective_calls`` itself).
  ``rank=R`` filters to one host rank; ``op=NAME`` to one operation.
* ``refresh`` — consulted by ``ServeEngine._refresh`` at two stages:
  ``pre_warm`` (before the replacement backend re-lowers anything) and
  ``pre_swap`` (after every warmed signature re-lowered, immediately
  before the atomic swap) — the crash window that proves swap atomicity.

Plan grammar (``RAFT_TPU_FAULT_PLAN`` or :func:`install_plan` /
:func:`plan`): directives separated by ``;``, fields by ``:``; the first
field is the site, the rest are ``key=value`` matchers and ONE action::

    dispatch:n=2:raise              # 2nd collection attempt raises (transient)
    dispatch:n=1:raise=logic        # non-retryable (LogicError) injected
    dispatch:n=1:stall=3.0          # 1st attempt hangs 3 s (watchdog fodder)
    dispatch:p=0.1:seed=7:raise     # seeded Bernoulli faults, deterministic
    comms:rank=1:op=isend:fail      # host-plane sends fail on rank 1
    refresh:stage=pre_swap:raise    # crash between re-lower and swap

Matchers: ``n=K`` fires on the K-th MATCHING event (1-based; ``times=T``
extends it to events K..K+T-1, ``times=0`` = every event from K on),
``p=F``/``seed=S`` fires per-event with seeded probability (deterministic
sequence), ``rank=R``/``op=O``/``stage=G`` filter events by attribute
before counting.  A directive with neither ``n`` nor ``p`` fires on EVERY
matching event.  Actions: ``raise[=transient|logic]`` (``fail`` and
``crash`` are aliases of ``raise``) and ``stall=SECONDS``.

Trace-time guarantee: hooks are host-side Python — they stage NOTHING
into jitted programs, so with the plane off (and even with a dispatch/
refresh plan installed) every lowered program is byte-identical to an
uninjected build.  tests/test_serve_faults.py pins this against the
committed golden HLO fingerprints.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from raft_tpu.core.error import LogicError


class InjectedFault(RuntimeError):
    """A TRANSIENT injected failure — deliberately a ``RuntimeError`` so
    the serve supervisor's retryable classification treats it exactly like
    a transient XLA runtime error."""


class InjectedLogicFault(LogicError):
    """A NON-RETRYABLE injected failure — a ``LogicError`` (the shape/
    dtype-bug family), which the supervisor must fail fast on, never
    retry."""


_ACTIONS = ("raise", "stall")
_KINDS = ("transient", "logic")


@dataclasses.dataclass(frozen=True)
class Directive:
    """One parsed fault directive (see the module grammar)."""

    site: str
    action: str = "raise"            # "raise" | "stall"
    kind: str = "transient"          # raise flavor: transient | logic
    stall_s: float = 0.0
    n: Optional[int] = None          # fire on the n-th matching event
    times: int = 1                   # ... for this many events (0 = forever)
    p: float = 0.0                   # seeded per-event probability
    seed: int = 0
    rank: Optional[int] = None       # comms: host-rank filter
    op: Optional[str] = None         # comms: operation filter
    stage: Optional[str] = None      # refresh: stage filter

    def matches_attrs(self, attrs: Dict[str, object]) -> bool:
        for field in ("rank", "op", "stage"):
            want = getattr(self, field)
            if want is not None and attrs.get(field) != want:
                return False
        return True


def _parse_directive(text: str) -> Directive:
    parts = [p.strip() for p in text.strip().split(":") if p.strip()]
    if not parts:
        raise ValueError(f"empty fault directive in {text!r}")
    site = parts[0]
    if site not in ("dispatch", "comms", "refresh"):
        raise ValueError(
            f"unknown fault site {site!r} (want dispatch|comms|refresh)")
    kw: Dict[str, object] = {"site": site}
    action_seen = False
    for field in parts[1:]:
        key, eq, value = field.partition("=")
        if key in ("raise", "fail", "crash"):
            action_seen = True
            kw["action"] = "raise"
            if eq:
                if value not in _KINDS:
                    raise ValueError(
                        f"raise kind {value!r} (want transient|logic)")
                kw["kind"] = value
        elif key == "stall":
            action_seen = True
            kw["action"] = "stall"
            kw["stall_s"] = float(value)
        elif key in ("n", "times", "seed", "rank"):
            kw[key] = int(value)
        elif key == "p":
            kw[key] = float(value)
        elif key in ("op", "stage"):
            kw[key] = value
        else:
            raise ValueError(f"unknown fault directive field {key!r} "
                             f"in {text!r}")
    if not action_seen:
        raise ValueError(f"fault directive {text!r} declares no action "
                         "(raise/fail/crash/stall=T)")
    return Directive(**kw)


class FaultPlan:
    """A parsed, stateful fault plan: per-directive event counters and a
    seeded RNG stream, so a given plan string injects the SAME fault
    sequence on every run (the determinism the bit-identity tests need)."""

    def __init__(self, directives: List[Directive]):
        self.directives = list(directives)
        self._lock = threading.Lock()
        self._counts = [0] * len(self.directives)
        self._rngs = [np.random.default_rng(d.seed) for d in self.directives]
        self.fired: List[Tuple[str, str]] = []  # (site, action) log

    @classmethod
    def parse(cls, text: str) -> "FaultPlan":
        directives = [_parse_directive(t)
                      for t in str(text).split(";") if t.strip()]
        if not directives:
            raise ValueError(f"fault plan {text!r} holds no directives")
        return cls(directives)

    def _due(self, i: int, d: Directive) -> bool:
        # caller holds the lock; the event already matched site + attrs
        self._counts[i] += 1
        c = self._counts[i]
        if d.n is not None:
            if c < d.n:
                return False
            return d.times == 0 or c < d.n + d.times
        if d.p > 0.0:
            return bool(self._rngs[i].random() < d.p)
        return True  # no n, no p: every matching event

    def check(self, site: str, **attrs) -> None:
        """Consult the plan at *site*; stalls sleep, raises raise."""
        fire: Optional[Directive] = None
        with self._lock:
            for i, d in enumerate(self.directives):
                if d.site != site or not d.matches_attrs(attrs):
                    continue
                if self._due(i, d):
                    fire = d
                    self.fired.append((site, d.action))
                    break
        if fire is None:
            return
        if fire.action == "stall":
            time.sleep(fire.stall_s)
            return
        detail = ", ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
        msg = (f"injected fault at site {site!r}"
               + (f" ({detail})" if detail else ""))
        if fire.kind == "logic":
            raise InjectedLogicFault(msg)
        raise InjectedFault(msg)


#: the installed plan — None means OFF, and every hook is one attr read
_PLAN: Optional[FaultPlan] = None


def active_plan() -> Optional[FaultPlan]:
    return _PLAN


def install_plan(plan_or_text) -> Optional[FaultPlan]:
    """Install a plan (string or :class:`FaultPlan`); returns the previous
    one so callers can restore it.  ``None`` clears."""
    global _PLAN
    prev = _PLAN
    if plan_or_text is None:
        _PLAN = None
    elif isinstance(plan_or_text, FaultPlan):
        _PLAN = plan_or_text
    else:
        _PLAN = FaultPlan.parse(plan_or_text)
    return prev


def clear_plan() -> None:
    install_plan(None)


@contextlib.contextmanager
def plan(text):
    """Context-manager install: the plan is active inside the block and the
    previous plan (usually None) is restored on exit — the test battery's
    entry point."""
    prev = install_plan(text)
    try:
        yield _PLAN
    finally:
        install_plan(prev)


def check(site: str, **attrs) -> None:
    """The hook the library calls: free when no plan is installed."""
    p = _PLAN
    if p is None:
        return
    p.check(site, **attrs)


_env = os.environ.get("RAFT_TPU_FAULT_PLAN")
if _env:
    install_plan(_env)
del _env
