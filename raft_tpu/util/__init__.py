"""Utility layer: shape/tile math and host helpers.

Counterpart of reference ``raft/util/`` (SURVEY.md §2.2).  Most of the
reference's device utilities (warp shuffles, vectorized IO, atomics) are
subsumed by the XLA/Pallas programming model; what survives is integer/tile
math (``ceildiv``, ``Pow2`` — reference util/pow2_utils.cuh,
util/integer_utils.hpp), TPU tiling helpers, and small host-side tools
(``itertools``-style parameter products for tests/bench, a prime sieve).
"""

from raft_tpu.util.math import (  # noqa: F401
    Pow2,
    alignTo,
    alignDown,
    ceildiv,
    is_pow2,
    next_pow2,
    round_up_safe,
)
from raft_tpu.util.tiling import (  # noqa: F401
    LANE,
    SUBLANE,
    min_tile,
    pad_dim,
    pad_to_tile,
    unpad,
)
from raft_tpu.util.itertools import product_of  # noqa: F401
from raft_tpu.util.seive import Seive  # noqa: F401
