"""Host-side test/bench parameter helpers (reference util/itertools.hpp)."""

from __future__ import annotations

import itertools
from typing import Any, Dict, Iterable, List


def product_of(**axes: Iterable[Any]) -> List[Dict[str, Any]]:
    """Cartesian product of named axes → list of dicts, like the reference's
    ``raft::util::itertools::product`` used to build test input grids."""
    keys = list(axes)
    return [dict(zip(keys, vals)) for vals in itertools.product(*axes.values())]
