"""Integer and power-of-two math (reference util/integer_utils.hpp,
util/pow2_utils.cuh)."""

from __future__ import annotations


def ceildiv(a: int, b: int) -> int:
    """Reference ``raft::ceildiv`` (util/cuda_utils.cuh)."""
    return -(-a // b)


def round_up_safe(a: int, b: int) -> int:
    """Smallest multiple of *b* >= *a* (reference util/integer_utils.hpp)."""
    return ceildiv(a, b) * b


def is_pow2(v: int) -> bool:
    return v > 0 and (v & (v - 1)) == 0


def next_pow2(v: int) -> int:
    if v <= 1:
        return 1
    return 1 << (v - 1).bit_length()


def alignTo(v: int, align: int) -> int:
    return round_up_safe(v, align)


def alignDown(v: int, align: int) -> int:
    return (v // align) * align


class Pow2:
    """Power-of-two alignment helper (reference util/pow2_utils.cuh ``Pow2``)."""

    def __init__(self, value: int):
        if not is_pow2(value):
            raise ValueError(f"Pow2: {value} is not a power of two")
        self.value = value
        self.mask = value - 1
        self.log2 = value.bit_length() - 1

    def round_down(self, x: int) -> int:
        return x & ~self.mask

    def round_up(self, x: int) -> int:
        return (x + self.mask) & ~self.mask

    def div(self, x: int) -> int:
        return x >> self.log2

    def mod(self, x: int) -> int:
        return x & self.mask

    def is_aligned(self, x: int) -> bool:
        return (x & self.mask) == 0
