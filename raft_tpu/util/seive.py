"""Sieve of Eratosthenes (reference util/seive.hpp — spelling kept)."""

from __future__ import annotations

import numpy as np


class Seive:
    def __init__(self, n: int):
        self.n = n
        sieve = np.ones(n + 1, dtype=bool)
        sieve[:2] = False
        for p in range(2, int(n**0.5) + 1):
            if sieve[p]:
                sieve[p * p :: p] = False
        self._sieve = sieve

    def is_prime(self, k: int) -> bool:
        return bool(self._sieve[k])

    def primes(self) -> np.ndarray:
        return np.nonzero(self._sieve)[0]
