"""TPU tile-shape helpers.

The VPU is 8x128 and the MXU 128x128; minimum tile shapes depend on dtype
(see /opt/skills/guides/pallas_guide.md).  Pallas kernels and padded-layout
data structures (IVF lists, top-k buffers) use these helpers to pick
hardware-friendly shapes — the role the reference's ``Pow2``/veclen machinery
plays for CUDA (e.g. neighbors/ivf_flat_types.hpp:30 kIndexGroupSize).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from raft_tpu.util.math import round_up_safe

LANE = 128  # last-dim tile width, all dtypes
SUBLANE = 8  # second-to-last for f32

_SUBLANES = {
    4: 8,  # f32/i32
    2: 16,  # bf16/f16
    1: 32,  # i8/fp8
}


def min_tile(dtype) -> Tuple[int, int]:
    """Minimum (sublane, lane) tile for *dtype*."""
    itemsize = np.dtype(dtype).itemsize
    return (_SUBLANES.get(itemsize, 8), LANE)


def pad_dim(n: int, multiple: int) -> int:
    return round_up_safe(max(n, 1), multiple)


def pad_to_tile(x, row_mult: int = SUBLANE, col_mult: int = LANE, fill=0):
    """Pad the trailing two dims of *x* up to multiples of (row_mult,
    col_mult) with *fill*; returns (padded, original_shape)."""
    import jax.numpy as jnp

    shape = x.shape
    if x.ndim == 1:
        n = pad_dim(shape[0], col_mult)
        if n != shape[0]:
            x = jnp.pad(x, (0, n - shape[0]), constant_values=fill)
        return x, shape
    r, c = shape[-2], shape[-1]
    rp, cp = pad_dim(r, row_mult), pad_dim(c, col_mult)
    if (rp, cp) != (r, c):
        pad = [(0, 0)] * (x.ndim - 2) + [(0, rp - r), (0, cp - c)]
        x = jnp.pad(x, pad, constant_values=fill)
    return x, shape


def unpad(x, orig_shape):
    """Slice a padded array back to *orig_shape*."""
    idx = tuple(slice(0, s) for s in orig_shape)
    return x[idx]
