"""Test configuration: run on CPU with 8 virtual devices.

This is the TPU-land analogue of the reference's LocalCUDACluster-based
distributed tests (SURVEY.md §4): a multi-device single-host environment
available in CI without real chips, via
``--xla_force_host_platform_device_count``.
"""

import os

# Must run before jax is imported anywhere.  Force CPU (the environment may
# preset JAX_PLATFORMS to a TPU platform; tests always run on the virtual
# 8-device CPU mesh — bench.py and __graft_entry__.py use the real chip).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
# f64 paths are part of the API surface (reference supports double everywhere).
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax  # noqa: E402

# The environment's sitecustomize may have registered/selected a TPU PJRT
# plugin already; force the platform choice at the config level too.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# Persistent compilation cache: the suite is dominated by recompiles of the
# same programs across test processes (VERDICT r1 weak #7); warm runs reuse
# on-disk executables.
from raft_tpu.core.aot import try_enable_persistent_cache  # noqa: E402

try_enable_persistent_cache()  # skips silently on unwritable HOME (CI)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def mesh8(devices):
    from jax.sharding import Mesh
    import numpy as np

    return Mesh(np.array(devices[:8]), ("x",))


@pytest.fixture
def handle():
    from raft_tpu.core import Handle

    return Handle()


# ---------------------------------------------------------------------------
# Fast smoke tier (VERDICT r3 weak #5): the full grid takes 20+ min serial on
# a 1-vCPU host; this curated subset — one or two tests per family (with all
# their parametrizations, ~80 collected) plus the comms bringup battery —
# bounds the gate everywhere (~2 min warm / ~5 min cold).  Select it with
# ``-m fast`` or ``RAFT_TPU_FAST=1`` (ci/checks.sh does so automatically on
# small hosts).  The reference splits per-family gtest binaries for the same
# reason (ci/gpu/build.sh:106-121).
_FAST_TESTS = {
    "test_aot.py::test_public_entry_points_consume_aot",
    "test_bench_protocol.py::TestRooflineGuard::test_flags_impossible_reading",
    "test_bench_protocol.py::TestSessionResume::test_stage_markers_and_reset",
    "test_distance.py::TestHalfPrecisionInputs::test_accumulates_f32",
    "test_cluster.py::test_kmeans_fit_bf16_data",
    "test_ball_cover.py::test_ball_cover_knn_exact",
    "test_cluster.py::TestKMeansFit::test_fit_blobs_ari",
    "test_cluster.py::TestSingleLinkage::test_labels_match_scipy",
    "test_comms.py::TestCollectives::test_allreduce_ops",
    "test_comms.py::TestSelfTests::test",
    "test_core.py::TestHandle::test_default",
    "test_core.py::TestMdarray::test_device_matrix",
    "test_distance.py::test_vs_scipy",
    "test_handle_threading.py::test_handle_through_cluster_and_neighbors",
    "test_ivf_flat.py::test_ivf_flat_recall",
    "test_ivf_flat.py::test_extend_lists_chunked_matches_full_repack",
    "test_ivf_build.py::test_search_identity_tiled_vs_monolithic",
    "test_ivf_build.py::test_serve_engine_refresh_zero_compile",
    "test_lowering_locks.py::TestRetraceCertifier::"
    "test_head_closure_certified",
    "test_lowering_locks.py::TestShippedGoldens::"
    "test_every_registered_program_has_a_committed_golden",
    "test_serve.py::test_zero_compiles_after_warmup",
    "test_serve.py::test_out_of_bucket_range_request_served_solo",
    "test_serve_schedule.py::TestChooser::"
    "test_flat_cost_reproduces_drain_all",
    "test_serve_schedule.py::TestEngineScheduler::"
    "test_scheduler_on_off_bit_identical_zero_compile",
    "test_serve_replica.py::TestReplicaServe::"
    "test_routed_identical_zero_compile_per_group_allgather",
    "test_serve_replica.py::TestReplicaServe::"
    "test_degrade_reroutes_zero_failures_healthz",
    "test_serve_autotune.py::TestDeterminism::"
    "test_same_seed_same_schedule_and_decisions",
    "test_serve_autotune.py::TestZeroCompile::"
    "test_explore_and_promote_are_zero_compile",
    "test_ivf_pq.py::test_ivf_pq_recall_pq_bits",
    "test_mutable.py::TestWritePath::test_warm_write_path_zero_compiles",
    "test_mutable.py::TestCompactor::test_tick_deterministic_and_contained",
    "test_kmeans_mnmg.py::test_distributed_matches_single_device",
    "test_kmeans_mnmg.py::test_fori_loop_matches_device_loop",
    "test_pallas_kernels.py::test_pallas_is_enabled_requires_experimental_flag",
    "test_pallas_engines.py::TestSelectKBlockwise::test_tie_stability_contract",
    "test_pallas_engines.py::TestFusedL2nnPartials::"
    "test_fused_em_step_pallas_engine_single_pass",
    "test_pallas_engines.py::TestEngineResolution::"
    "test_env_1_requires_tpu_and_experimental",
    "test_label.py::test_make_monotonic",
    "test_label.py::test_select_k",
    "test_linalg.py::TestDecompositions::test_svd",
    "test_linalg.py::TestReduce::test_reduce_ops",
    "test_matrix.py::test_argmax_argmin",
    "test_matrix.py::TestOpsOracleSweep::test_gather_if_matches_masked_gather",
    "test_native.py::test_dendrogram_matches_scipy",
    "test_neighbors.py::test_knn_matches_scipy",
    "test_pallas_kernels.py::test_fused_l2_nn_pallas_matches_jnp",
    "test_random.py::test_make_blobs",
    "test_random.py::test_rng_state_reproducible",
    "test_solver.py::test_lap_vs_scipy_oracle",
    "test_sparse.py::test_spmv_spmm",
    "test_sparse_neighbors.py::test_sparse_pairwise_vs_scipy",
    "test_sparse_solver.py::test_boruvka_mst_matches_scipy",
    "test_sparse_solver.py::test_lanczos_smallest_vs_numpy",
    "test_spectral.py::test_partition_recovers_planted_blocks",
    "test_stats.py::TestContingency::test_rand_indices",
    "test_stats.py::TestSummary::test_meanvar_stddev",
    "test_telemetry.py::TestHistogram::test_quantile_oracle_vs_np_percentile",
    "test_telemetry.py::test_disabled_mode_identity",
    "test_telemetry_fleet.py::TestMerge::test_merge_equals_union_stream",
    "test_telemetry_fleet.py::TestScrapeServer::test_metrics_round_trip",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        base = item.nodeid.split("[")[0]
        if base.startswith("tests/"):
            base = base[len("tests/"):]
        if base in _FAST_TESTS:
            item.add_marker(pytest.mark.fast)
    if os.environ.get("RAFT_TPU_FAST", "") == "1":
        kept = [i for i in items if i.get_closest_marker("fast")]
        deselected = [i for i in items if not i.get_closest_marker("fast")]
        if deselected:
            config.hook.pytest_deselected(items=deselected)
            items[:] = kept


_last_module = [None]


@pytest.fixture(autouse=True)
def _bounded_jax_state(request):
    """Clear jax's internal trace/executable caches at every MODULE
    boundary.  The full serial suite accumulates thousands of compiled
    programs in one process; on this container class that accumulation
    ends in a deterministic XLA:CPU segfault inside ``backend_compile``
    late in the run (reproduced at clean HEAD too — the crash point
    tracks the cumulative compile count, landing in whatever file runs
    ~700 tests in).  Bounding the live compile state per module keeps the
    process inside whatever native resource the compiler is exhausting;
    the on-disk persistent cache (conftest above) absorbs most of the
    recompile cost for programs shared across modules."""
    mod = request.node.nodeid.split("::")[0]
    if _last_module[0] is not None and mod != _last_module[0]:
        jax.clear_caches()
    _last_module[0] = mod
    yield


_family_durations: dict = {}


def pytest_runtest_logreport(report):
    if report.when == "call":
        fam = report.nodeid.split("::")[0]
        _family_durations[fam] = (_family_durations.get(fam, 0.0)
                                  + report.duration)


def pytest_terminal_summary(terminalreporter):
    """Per-family wall-time table (the knob for curating the fast tier and
    for balancing xdist's per-file sharding)."""
    if not _family_durations:
        return
    terminalreporter.write_sep("-", "per-family durations")
    for fam, secs in sorted(_family_durations.items(), key=lambda kv: -kv[1]):
        terminalreporter.write_line(f"{secs:8.1f}s  {fam}")
