"""Test configuration: run on CPU with 8 virtual devices.

This is the TPU-land analogue of the reference's LocalCUDACluster-based
distributed tests (SURVEY.md §4): a multi-device single-host environment
available in CI without real chips, via
``--xla_force_host_platform_device_count``.
"""

import os

# Must run before jax is imported anywhere.  Force CPU (the environment may
# preset JAX_PLATFORMS to a TPU platform; tests always run on the virtual
# 8-device CPU mesh — bench.py and __graft_entry__.py use the real chip).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
# f64 paths are part of the API surface (reference supports double everywhere).
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax  # noqa: E402

# The environment's sitecustomize may have registered/selected a TPU PJRT
# plugin already; force the platform choice at the config level too.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# Persistent compilation cache: the suite is dominated by recompiles of the
# same programs across test processes (VERDICT r1 weak #7); warm runs reuse
# on-disk executables.
from raft_tpu.core.aot import try_enable_persistent_cache  # noqa: E402

try_enable_persistent_cache()  # skips silently on unwritable HOME (CI)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def mesh8(devices):
    from jax.sharding import Mesh
    import numpy as np

    return Mesh(np.array(devices[:8]), ("x",))


@pytest.fixture
def handle():
    from raft_tpu.core import Handle

    return Handle()
