"""raft_tpu.analysis (ISSUE 8): quarantine tests per AST rule — each rule
fires on a violating snippet, passes on the fixed form, and respects the
unified exemption marker — plus HLO-auditor tests on toy programs with a
deliberate budget violation and a deliberate dead donation, and a smoke
audit of the shipped program registry."""

import pathlib
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from raft_tpu.analysis import engine, hlo_audit, registry  # noqa: E402

REPO = pathlib.Path(__file__).resolve().parent.parent


def findings(posix, src, rule=None):
    out = engine.check_source(posix, src)
    if rule is not None:
        out = [f for f in out if f.rule == rule]
    return out


# ---------------------------------------------------------------------------
# the unified exemption marker


class TestUnifiedMarker:
    _SRC = ("import jax\n\n\ndef f(v, i):\n"
            "    return jax.ops.segment_sum(v, i, num_segments=4){}\n")

    def test_fires_bare(self):
        assert findings("raft_tpu/x/mod.py", self._SRC.format(""),
                        "raw-segment-sum")

    def test_unified_marker_with_rationale_exempts(self):
        src = self._SRC.format(
            "  # exempt(raw-segment-sum): engine A/B baseline")
        assert not findings("raft_tpu/x/mod.py", src, "raw-segment-sum")

    def test_marker_without_rationale_does_not_exempt(self):
        src = self._SRC.format("  # exempt(raw-segment-sum):")
        assert findings("raft_tpu/x/mod.py", src, "raw-segment-sum")
        # ... and the bare marker is itself flagged (no blanket allowlists)
        assert findings("raft_tpu/x/mod.py", src, "exemption-hygiene")

    def test_marker_for_other_rule_does_not_exempt(self):
        src = self._SRC.format("  # exempt(dtype-drift): wrong rule")
        assert findings("raft_tpu/x/mod.py", src, "raw-segment-sum")

    def test_marker_on_line_above(self):
        src = ("import jax\n\n\ndef f(v, i):\n"
               "    # exempt(raw-segment-sum): sanctioned here\n"
               "    return jax.ops.segment_sum(v, i, num_segments=4)\n")
        assert not findings("raft_tpu/x/mod.py", src, "raw-segment-sum")

    def test_comma_list_of_rules(self):
        src = self._SRC.format(
            "  # exempt(raw-segment-sum, dtype-drift): shared rationale")
        assert not findings("raft_tpu/x/mod.py", src, "raw-segment-sum")

    def test_legacy_spellings_still_parse(self):
        # each legacy marker maps onto its rule id (back-compat contract)
        assert engine.LEGACY_MARKERS == {
            "adc-exempt": "probe-scan-closure",
            "serve-exempt": "serve-dispatch",
            "host-ok": "hot-path-host-transfer"}

    def test_rule_catalog_registered(self):
        ids = {r.id for r in engine.iter_rules()}
        assert {"raw-segment-sum", "probe-scan-closure", "serve-dispatch",
                "hot-path-host-transfer", "collective-discipline",
                "trace-impurity", "static-arg-hashability",
                "dtype-drift", "telemetry-discipline",
                "pallas-discipline", "mutation-discipline"} <= ids


# ---------------------------------------------------------------------------
# pallas-discipline (ISSUE 13): kernels live in raft_tpu/kernels/ with
# registered VMEM ceilings and static block shapes


class TestPallasDiscipline:
    _OUTSIDE = ("from jax.experimental import pallas as pl\n\n\n"
                "def f(x):\n"
                "    return pl.pallas_call(lambda i, o: None,\n"
                "                          out_shape=x){}\n")

    def test_fires_outside_kernels_home(self):
        f = findings("raft_tpu/neighbors/mod.py", self._OUTSIDE.format(""),
                     "pallas-discipline")
        assert f and "kernels" in f[0].message

    def test_marker_exempts(self):
        src = self._OUTSIDE.format(
            "  # exempt(pallas-discipline): measurement scaffold")
        # marker sits on the call line (continuation): place it on the
        # pallas_call line instead
        src = ("from jax.experimental import pallas as pl\n\n\n"
               "def f(x):\n"
               "    # exempt(pallas-discipline): measurement scaffold\n"
               "    return pl.pallas_call(lambda i, o: None, out_shape=x)\n")
        assert not findings("raft_tpu/neighbors/mod.py", src,
                            "pallas-discipline")

    def test_home_without_ceiling_fires(self):
        src = ("from jax.experimental import pallas as pl\n\n\n"
               "def _kernel(i, o):\n    pass\n\n\n"
               "def f(x):\n"
               "    return pl.pallas_call(_kernel, out_shape=x)\n")
        f = findings("raft_tpu/kernels/mod.py", src, "pallas-discipline")
        assert f and "VMEM ceiling" in f[0].message

    def test_home_with_ceiling_passes(self):
        src = ("from jax.experimental import pallas as pl\n\n"
               "VMEM_CEILINGS = {\"_kernel\": 1024}\n\n\n"
               "def _kernel(i, o):\n    pass\n\n\n"
               "def f(x):\n"
               "    return pl.pallas_call(_kernel, out_shape=x)\n")
        assert not findings("raft_tpu/kernels/mod.py", src,
                            "pallas-discipline")

    def test_inline_runtime_shape_in_blockspec_fires(self):
        src = ("from jax.experimental import pallas as pl\n\n"
               "VMEM_CEILINGS = {\"_kernel\": 1024}\n\n\n"
               "def _kernel(i, o):\n    pass\n\n\n"
               "def f(x):\n"
               "    return pl.pallas_call(\n"
               "        _kernel, out_shape=x,\n"
               "        in_specs=[pl.BlockSpec((8, x.shape[1]),\n"
               "                               lambda i: (i, 0))])\n")
        f = findings("raft_tpu/kernels/mod.py", src, "pallas-discipline")
        assert f and "static" in f[0].message

    def test_shipped_kernels_home_is_clean(self):
        for mod in sorted((REPO / "raft_tpu" / "kernels").glob("*.py")):
            assert not findings(mod.as_posix(), mod.read_text(),
                                "pallas-discipline"), mod

    def test_shipped_tree_has_no_stray_pallas_calls(self):
        # the graduated layout: every pl.pallas_call in raft_tpu/ lives
        # under raft_tpu/kernels/ (the old distance/ scaffolds are shims)
        # — the RULE itself must find nothing to flag outside the home
        for mod in sorted((REPO / "raft_tpu").rglob("*.py")):
            if "__pycache__" in mod.parts:
                continue
            assert not findings(mod.as_posix(), mod.read_text(),
                                "pallas-discipline"), mod


# ---------------------------------------------------------------------------
# collective-discipline


class TestCollectiveDiscipline:
    _SRC = ("import jax\n\n\ndef prog(x, axis):\n"
            "    return jax.lax.psum(x, axis){}\n")

    def test_fires_outside_comms(self):
        f = findings("raft_tpu/neighbors/mod.py", self._SRC.format(""),
                     "collective-discipline")
        assert f and "psum" in f[0].message

    def test_comms_package_is_the_blessed_home(self):
        assert not findings("raft_tpu/comms/mod.py", self._SRC.format(""),
                            "collective-discipline")

    def test_from_import_fires(self):
        src = ("from jax.lax import all_gather\n\n\ndef prog(x, a):\n"
               "    return all_gather(x, a)\n")
        f = findings("raft_tpu/cluster/mod.py", src,
                     "collective-discipline")
        # both the import and the laundered bare call are flagged
        assert len(f) == 2

    def test_lax_alias_fires(self):
        src = ("import jax.lax as L\n\n\ndef prog(x, a):\n"
               "    return L.ppermute(x, a, [(0, 1)])\n")
        assert findings("raft_tpu/cluster/mod.py", src,
                        "collective-discipline")

    def test_axis_index_is_not_banned(self):
        src = ("import jax\n\n\ndef prog(x, axis):\n"
               "    return x + jax.lax.axis_index(axis)\n")
        assert not findings("raft_tpu/neighbors/mod.py", src,
                            "collective-discipline")

    def test_comms_wrapper_calls_pass(self):
        src = ("def prog(comms, x):\n"
               "    return comms.allreduce(x)\n")
        assert not findings("raft_tpu/cluster/mod.py", src,
                            "collective-discipline")

    def test_marker_exempts(self):
        src = self._SRC.format(
            "  # exempt(collective-discipline): counted by hand here")
        assert not findings("raft_tpu/neighbors/mod.py", src,
                            "collective-discipline")

    def test_shipped_tree_clean(self):
        for f in sorted((REPO / "raft_tpu").rglob("*.py")):
            src = f.read_text()
            assert not [x for x in engine.check_source(
                f.as_posix(), src) if x.rule == "collective-discipline"], f


# ---------------------------------------------------------------------------
# trace-impurity


class TestTraceImpurity:
    def test_time_in_impl_fires(self):
        src = ("import time\n\n\ndef _search_impl(q):\n"
               "    t0 = time.perf_counter()\n    return q, t0\n")
        f = findings("raft_tpu/neighbors/mod.py", src, "trace-impurity")
        assert f and "time.perf_counter" in f[0].message

    def test_np_random_in_program_fires(self):
        src = ("import numpy as np\n\n\ndef _em_program(x):\n"
               "    return x + np.random.rand()\n")
        assert findings("raft_tpu/cluster/mod.py", src, "trace-impurity")

    def test_print_in_impl_fires(self):
        src = ("def _scan_impl(x):\n    print(x)\n    return x\n")
        assert findings("raft_tpu/neighbors/mod.py", src, "trace-impurity")

    def test_scan_probe_lists_callback_covered(self):
        src = ("def search(probes, idxs, sizes):\n"
               "    def score_tile(rows):\n"
               "        print(rows)\n        return rows\n"
               "    return scan_probe_lists(probes, score_tile, idxs, "
               "sizes, 5)\n")
        assert findings("raft_tpu/neighbors/mod.py", src, "trace-impurity")

    def test_host_side_function_passes(self):
        # impurities OUTSIDE program bodies are not this rule's business
        src = ("import time\n\n\ndef bench(q):\n"
               "    return time.perf_counter()\n")
        assert not findings("raft_tpu/neighbors/mod.py", src,
                            "trace-impurity")

    def test_marker_exempts(self):
        src = ("def _scan_impl(x):\n"
               "    print(x)  # exempt(trace-impurity): debug scaffold\n"
               "    return x\n")
        assert not findings("raft_tpu/neighbors/mod.py", src,
                            "trace-impurity")


# ---------------------------------------------------------------------------
# telemetry-discipline


class TestErrorDiscipline:
    """The ISSUE-14 swallowed-error guard: bare ``except:`` and
    ``except Exception: pass`` are forbidden in raft_tpu/serve/,
    raft_tpu/comms/ and hot-path-registry modules (typed failure
    contracts — docs/serving.md §failure model)."""

    _BARE = ("def f(x):\n    try:\n        return x + 1\n"
             "    except:{}\n        return None\n")
    _SWALLOW = ("def f(x):\n    try:\n        return x + 1\n"
                "    except Exception:{}\n        pass\n")

    def test_bare_except_fires_in_serve(self):
        f = findings("raft_tpu/serve/engine.py", self._BARE.format(""),
                     "error-discipline")
        assert f and "bare `except:`" in f[0].message

    def test_swallowed_exception_fires_in_comms(self):
        f = findings("raft_tpu/comms/comms.py", self._SWALLOW.format(""),
                     "error-discipline")
        assert f and "swallows" in f[0].message

    def test_fires_in_hot_path_registry_module(self):
        assert findings("raft_tpu/neighbors/ann_mnmg.py",
                        self._SWALLOW.format(""), "error-discipline")

    def test_base_exception_and_tuple_fire(self):
        src = ("def f(x):\n    try:\n        return x\n"
               "    except (ValueError, BaseException):\n        ...\n")
        assert findings("raft_tpu/serve/mod.py", src, "error-discipline")

    def test_return_none_swallow_fires(self):
        src = ("def f(x):\n    try:\n        return x\n"
               "    except Exception:\n        return None\n")
        assert findings("raft_tpu/comms/mod.py", src, "error-discipline")

    def test_handled_broad_catch_passes(self):
        # logging / wrapping / recording IS handling, not swallowing
        src = ("def f(x, log, results):\n    try:\n        return x\n"
               "    except Exception as e:\n"
               "        results.append(e)\n        return None\n")
        assert not findings("raft_tpu/serve/mod.py", src,
                            "error-discipline")

    def test_typed_catch_passes(self):
        src = ("def f(x):\n    try:\n        return x\n"
               "    except (ValueError, KeyError):\n        pass\n")
        assert not findings("raft_tpu/serve/mod.py", src,
                            "error-discipline")

    def test_out_of_scope_module_passes(self):
        assert not findings("raft_tpu/stats/mod.py",
                            self._SWALLOW.format(""), "error-discipline")

    def test_marker_exempts(self):
        f = findings(
            "raft_tpu/serve/mod.py",
            self._SWALLOW.format(
                "  # exempt(error-discipline): third-party teardown"),
            "error-discipline")
        assert not f

    def test_shipped_surfaces_clean(self):
        from raft_tpu.analysis import hotpaths

        for f in sorted((REPO / "raft_tpu").rglob("*.py")):
            posix = f.as_posix()
            if not ("raft_tpu/serve/" in posix or "raft_tpu/comms/" in posix
                    or hotpaths.match(posix)):
                continue
            assert not [x for x in engine.check_source(posix, f.read_text())
                        if x.rule == "error-discipline"], f


class TestMutationDiscipline:
    """ISSUE 20: mutable-index core state is written only inside
    neighbors/mutable.py — raw writes elsewhere skip the device-push /
    rewarm / warm-before-swap protocol the retrace mutate_closure
    certifies inside the module."""

    _RAW = ("def hack(core, j):\n"
            "    core.words_main[j >> 5] |= 1 << (j & 31){}\n")

    def test_raw_bitmap_write_fires(self):
        f = findings("raft_tpu/serve/patch.py", self._RAW.format(""),
                     "mutation-discipline")
        assert f and "words_main" in f[0].message

    def test_core_swap_fires(self):
        src = ("def swap(m, core):\n"
               "    m._mut_core = core\n")
        assert findings("raft_tpu/serve/patch.py", src,
                        "mutation-discipline")

    def test_fixed_form_passes(self):
        src = ("def remove(m, ids):\n"
               "    return m.delete(ids)\n")
        assert not findings("raft_tpu/serve/patch.py", src,
                            "mutation-discipline")

    def test_home_module_is_the_blessed_door(self):
        assert not findings("raft_tpu/neighbors/mutable.py",
                            self._RAW.format(""), "mutation-discipline")

    def test_marker_exempts(self):
        src = self._RAW.format(
            "  # exempt(mutation-discipline): load-time replay")
        assert not findings("raft_tpu/serve/patch.py", src,
                            "mutation-discipline")

    def test_shipped_tree_clean(self):
        for f in sorted((REPO / "raft_tpu").rglob("*.py")):
            assert not [x for x in engine.check_source(
                f.as_posix(), f.read_text())
                if x.rule == "mutation-discipline"], f


class TestTelemetryDiscipline:
    _CLOCK = ("import time\n\n\ndef plan(reqs):\n"
              "    t0 = time.perf_counter(){}\n    return t0\n")

    def test_clock_in_hot_path_module_fires(self):
        f = findings("raft_tpu/serve/engine.py", self._CLOCK.format(""),
                     "telemetry-discipline")
        assert f and "time.perf_counter" in f[0].message

    def test_monotonic_fires(self):
        src = self._CLOCK.replace("perf_counter", "monotonic")
        assert findings("raft_tpu/neighbors/ann_mnmg.py", src.format(""),
                        "telemetry-discipline")

    def test_from_import_laundering_fires(self):
        src = ("from time import perf_counter\n\n\ndef plan():\n"
               "    return perf_counter()\n")
        assert findings("raft_tpu/neighbors/_build.py", src,
                        "telemetry-discipline")

    def test_module_level_counter_fires(self):
        src = "import collections\n\nstats = collections.Counter()\n"
        f = findings("raft_tpu/serve/engine.py", src,
                     "telemetry-discipline")
        assert f and "Counter" in f[0].message

    def test_bare_counter_name_fires(self):
        src = "from collections import Counter\n\nstats = Counter()\n"
        assert findings("raft_tpu/neighbors/knn_mnmg.py", src,
                        "telemetry-discipline")

    def test_telemetry_package_is_the_blessed_home(self):
        assert not findings("raft_tpu/telemetry/spans.py",
                            self._CLOCK.format(""), "telemetry-discipline")

    def test_non_hot_path_module_passes(self):
        # timing in a training prologue module off the registry is fine
        assert not findings("raft_tpu/stats/mod.py", self._CLOCK.format(""),
                            "telemetry-discipline")

    def test_telemetry_now_and_span_pass(self):
        src = ("from raft_tpu import telemetry\n\n\ndef plan(reqs):\n"
               "    t0 = telemetry.now()\n"
               "    with telemetry.span('serve.plan'):\n"
               "        return t0\n")
        assert not findings("raft_tpu/serve/engine.py", src,
                            "telemetry-discipline")

    def test_marker_exempts(self):
        src = self._CLOCK.format(
            "  # exempt(telemetry-discipline): bench-only scaffold")
        assert not findings("raft_tpu/serve/engine.py", src,
                            "telemetry-discipline")

    def test_shipped_tree_clean(self):
        for f in sorted((REPO / "raft_tpu").rglob("*.py")):
            assert not [x for x in engine.check_source(
                f.as_posix(), f.read_text())
                if x.rule == "telemetry-discipline"], f

    # -- raw http.server endpoints outside raft_tpu/telemetry/ (ISSUE 10)
    _HTTP = ("from http.server import ThreadingHTTPServer{}\n\n\n"
             "def serve_metrics(port):\n"
             "    return ThreadingHTTPServer(('', port), None)\n")

    def test_http_server_outside_telemetry_fires(self):
        f = findings("raft_tpu/serve/engine.py", self._HTTP.format(""),
                     "telemetry-discipline")
        assert f and "http.server" in f[0].message

    def test_http_server_fires_off_the_hot_path_registry_too(self):
        # the endpoint check covers the WHOLE library, not just hot paths
        src = "import http.server\n"
        assert findings("raft_tpu/stats/mod.py", src,
                        "telemetry-discipline")
        # ...including the `from http import server` spelling
        assert findings("raft_tpu/stats/mod.py", "from http import server\n",
                        "telemetry-discipline")

    def test_http_client_does_not_fire(self):
        # http.client (outbound) is not an endpoint; only the server half
        # forks the scrape surface
        assert not findings("raft_tpu/stats/mod.py",
                            "import http.client\n",
                            "telemetry-discipline")

    def test_http_server_in_telemetry_package_passes(self):
        assert not findings("raft_tpu/telemetry/http.py",
                            self._HTTP.format(""), "telemetry-discipline")

    def test_http_server_marker_exempts(self):
        src = self._HTTP.format(
            "  # exempt(telemetry-discipline): debug-only local tool")
        assert not findings("raft_tpu/serve/engine.py", src,
                            "telemetry-discipline")


# ---------------------------------------------------------------------------
# static-arg-hashability


class TestStaticArgHashability:
    def test_list_in_static_position_fires(self):
        src = ("F = aot(fn, static_argnums=(1,))\n\n\ndef go(x):\n"
               "    return F(x, [1, 2])\n")
        f = findings("raft_tpu/x/mod.py", src, "static-arg-hashability")
        assert f and "list" in f[0].message

    def test_tuple_in_static_position_passes(self):
        src = ("F = aot(fn, static_argnums=(1,))\n\n\ndef go(x):\n"
               "    return F(x, (1, 2))\n")
        assert not findings("raft_tpu/x/mod.py", src,
                            "static-arg-hashability")

    def test_module_const_statics_resolve(self):
        src = ("_S = (2,)\nF = aot(fn, static_argnums=_S)\n\n\n"
               "def go(x, y):\n    return F(x, y, {'a': 1})\n")
        f = findings("raft_tpu/x/mod.py", src, "static-arg-hashability")
        assert f and "dict" in f[0].message

    def test_ndarray_ctor_fires(self):
        src = ("import jax\nimport jax.numpy as jnp\n"
               "F = jax.jit(fn, static_argnums=(0,))\n\n\ndef go():\n"
               "    return F(jnp.zeros((3,)))\n")
        f = findings("raft_tpu/x/mod.py", src, "static-arg-hashability")
        assert f and "ndarray" in f[0].message

    def test_partial_jit_form_resolves(self):
        src = ("import functools\nimport jax\n"
               "F = functools.partial(jax.jit, static_argnums=(1,))(fn)\n"
               "\n\ndef go(x):\n    return F(x, [3])\n")
        assert findings("raft_tpu/x/mod.py", src, "static-arg-hashability")

    def test_dynamic_positions_unchecked(self):
        src = ("F = aot(fn, static_argnums=(1,))\n\n\ndef go(x):\n"
               "    return F([1, 2], 7)\n")  # pos 0 is dynamic
        assert not findings("raft_tpu/x/mod.py", src,
                            "static-arg-hashability")

    def test_marker_exempts(self):
        src = ("F = aot(fn, static_argnums=(1,))\n\n\ndef go(x):\n"
               "    return F(x, [1, 2])  "
               "# exempt(static-arg-hashability): test fixture\n")
        assert not findings("raft_tpu/x/mod.py", src,
                            "static-arg-hashability")


# ---------------------------------------------------------------------------
# dtype-drift


class TestDtypeDrift:
    def test_jnp_float64_fires(self):
        src = ("import jax.numpy as jnp\n\n\ndef f(x):\n"
               "    return x.astype(jnp.float64)\n")
        assert findings("raft_tpu/stats/mod.py", src, "dtype-drift")

    def test_np_float64_fires(self):
        src = ("import numpy as np\n\n\ndef f(x):\n"
               "    return np.zeros((3,), np.float64)\n")
        assert findings("raft_tpu/cluster/mod.py", src, "dtype-drift")

    def test_x64_comment_sanctions(self):
        src = ("import jax.numpy as jnp\n\n\ndef f(x):\n"
               "    # x64: exact widening under jax_enable_x64\n"
               "    return x.astype(jnp.float64)\n")
        assert not findings("raft_tpu/stats/mod.py", src, "dtype-drift")

    def test_exempt_marker_sanctions(self):
        src = ("import numpy as np\n\n\ndef f(x):\n"
               "    return np.float64(x)  "
               "# exempt(dtype-drift): host-side numpy\n")
        assert not findings("raft_tpu/cluster/mod.py", src, "dtype-drift")

    def test_native_out_of_scope(self):
        src = ("import numpy as np\n\n\ndef f(x):\n"
               "    return np.zeros((3,), np.float64)\n")
        assert not findings("raft_tpu/native/mod.py", src, "dtype-drift")

    def test_float32_passes(self):
        src = ("import jax.numpy as jnp\n\n\ndef f(x):\n"
               "    return x.astype(jnp.float32)\n")
        assert not findings("raft_tpu/stats/mod.py", src, "dtype-drift")


# ---------------------------------------------------------------------------
# hot-path-host-transfer generalization (beyond the two historical modules)


class TestHostTransferRegistry:
    def test_kmeans_fused_em_scope_fires(self):
        src = ("import numpy as np\n\n\ndef _fused_em_scan(x):\n"
               "    return np.asarray(x)\n")
        assert findings("raft_tpu/cluster/kmeans.py", src,
                        "hot-path-host-transfer")

    def test_kmeans_outside_hot_functions_passes(self):
        # the training prologue may touch host numpy — only the declared
        # fused-EM loop functions are hot
        src = ("import numpy as np\n\n\ndef _train_prologue(x):\n"
               "    return np.asarray(x)\n")
        assert not findings("raft_tpu/cluster/kmeans.py", src,
                            "hot-path-host-transfer")

    def test_serve_module_wide(self):
        src = ("import numpy as np\n\n\ndef dispatch(x):\n"
               "    return np.asarray(x)\n")
        assert findings("raft_tpu/serve/engine.py", src,
                        "hot-path-host-transfer")

    def test_knn_mnmg_covered(self):
        src = ("import jax\n\n\ndef merge(x):\n"
               "    return jax.device_get(x)\n")
        assert findings("raft_tpu/neighbors/knn_mnmg.py", src,
                        "hot-path-host-transfer")

    def test_unregistered_module_passes(self):
        src = ("import numpy as np\n\n\ndef f(x):\n"
               "    return np.asarray(x)\n")
        assert not findings("raft_tpu/stats/mod.py", src,
                            "hot-path-host-transfer")

    def test_unified_marker_exempts(self):
        src = ("import numpy as np\n\n\ndef _fused_em_scan(x):\n"
               "    return np.asarray(x)  "
               "# exempt(hot-path-host-transfer): (k,) table fetch\n")
        assert not findings("raft_tpu/cluster/kmeans.py", src,
                            "hot-path-host-transfer")

    def test_legacy_host_ok_still_exempts(self):
        src = ("import numpy as np\n\n\ndef _fused_em_scan(x):\n"
               "    return np.asarray(x)  # host-ok: (k,) table fetch\n")
        assert not findings("raft_tpu/cluster/kmeans.py", src,
                            "hot-path-host-transfer")

    # -- the tier-staging quarantine trio (ISSUE 18): staging=True entries
    # track device_put/stage as BUDGETED transfers that must carry the
    # tier-staging marker; the marker is not a general waiver

    def test_unmarked_staging_transfer_fires(self):
        src = ("import jax\n\n\ndef _stage(tile):\n"
               "    return jax.device_put(tile)\n")
        f = findings("raft_tpu/neighbors/tiering.py", src,
                     "hot-path-host-transfer")
        assert f and "device_put" in f[0].message

    def test_tier_staging_marker_sanctions_in_staging_scope(self):
        src = ("import jax\n\n\ndef _stage(tile):\n"
               "    # tier-staging(hot-path-host-transfer): O(tile) lane\n"
               "    return jax.device_put(tile)\n")
        assert not findings("raft_tpu/neighbors/tiering.py", src,
                            "hot-path-host-transfer")

    def test_tier_staging_marker_sanctions_nothing_elsewhere(self):
        # in a NON-staging hot path (serve/engine.py) the marker is inert:
        # banned fetches still fire — staging budgets don't leak out of
        # the residency layer
        src = ("import numpy as np\n\n\ndef dispatch(x):\n"
               "    # tier-staging(hot-path-host-transfer): not a budget\n"
               "    return np.asarray(x)\n")
        assert findings("raft_tpu/serve/engine.py", src,
                        "hot-path-host-transfer")


# ---------------------------------------------------------------------------
# the engine over the shipped tree


class TestEngineAtHead:
    @pytest.mark.slow  # tier-1 budget (ISSUE-20 rebalance): this IS the
    # ci/checks.sh `--ast` gate, re-run on every CI (PR-19 stale-marker
    # precedent)
    def test_repo_surface_clean(self):
        # the acceptance contract: level 1 exits 0 at HEAD
        import io

        bad = engine.run(out=io.StringIO())
        assert bad == 0


# ---------------------------------------------------------------------------
# HLO auditor — toy programs with deliberate violations


def _entry(name, fn, args, **kw):
    return registry.ProgramEntry(name=name, builder=lambda: dict(
        fn=fn, args=args, **{k: kw.pop(k) for k in ("donate_argnums",)
                             if k in kw}), **kw)


class TestHloAuditToys:
    def test_budget_violation_is_a_finding(self):
        def hog(x):
            # forces a real (n, n) temp the tiny ceiling cannot hold
            return (x @ x.T).sum(axis=0)

        e = registry.ProgramEntry(
            name="toy.budget_violation",
            builder=lambda: dict(fn=hog, args=(
                jax.ShapeDtypeStruct((256, 256), jnp.float32),)),
            transient_bytes=64)
        r = hlo_audit.audit_program(e)
        assert r.status == "fail"
        assert any("ceiling" in f for f in r.findings), r.findings

    def test_budget_holds_when_ceiling_sane(self):
        def hog(x):
            return (x @ x.T).sum(axis=0)

        e = registry.ProgramEntry(
            name="toy.budget_ok",
            builder=lambda: dict(fn=hog, args=(
                jax.ShapeDtypeStruct((256, 256), jnp.float32),)),
            transient_bytes=8 << 20)
        assert hlo_audit.audit_program(e).status == "ok"

    @pytest.mark.filterwarnings(
        "ignore:Some donated buffers were not usable")
    def test_dead_donation_is_a_finding(self):
        def drops_donation(a, b):
            return b * 2.0   # a is donated but unusable: no alias emitted

        e = registry.ProgramEntry(
            name="toy.dead_donation",
            builder=lambda: dict(
                fn=drops_donation,
                args=(jax.ShapeDtypeStruct((128,), jnp.float32),
                      jax.ShapeDtypeStruct((128,), jnp.float32)),
                donate_argnums=(0,)),
            donate_argnums=(0,),
            donation_policy={"cpu": "must-alias"})
        r = hlo_audit.audit_program(e)
        assert r.status == "fail"
        assert any("dropped" in f or "input_output_alias" in f
                   for f in r.findings), r.findings

    @pytest.mark.filterwarnings(
        "ignore:Some donated buffers were not usable")
    def test_dead_donation_recorded_under_may_alias_policy(self):
        def drops_donation(a, b):
            return b * 2.0

        e = registry.ProgramEntry(
            name="toy.dead_donation_recorded",
            builder=lambda: dict(
                fn=drops_donation,
                args=(jax.ShapeDtypeStruct((128,), jnp.float32),
                      jax.ShapeDtypeStruct((128,), jnp.float32)),
                donate_argnums=(0,)),
            donate_argnums=(0,),
            donation_policy={"cpu": "may-alias"})
        r = hlo_audit.audit_program(e)
        assert r.status == "ok"
        assert "dropped" in str(r.stats.get("donation_status", ""))

    @pytest.mark.filterwarnings(
        "ignore:Some donated buffers were not usable")
    def test_partial_donation_drop_is_a_finding(self):
        # b's only output is a scalar, so b's donation can never alias:
        # of 2 donated leaves at most 1 lands in input_output_alias —
        # a non-emptiness check would miss the dropped half
        def partial(a, b):
            return a.at[0].set(1.0), b.sum()

        e = registry.ProgramEntry(
            name="toy.partial_donation",
            builder=lambda: dict(
                fn=partial,
                args=(jax.ShapeDtypeStruct((128,), jnp.float32),
                      jax.ShapeDtypeStruct((64,), jnp.float32)),
                donate_argnums=(0, 1)),
            donate_argnums=(0, 1),
            donation_policy={"cpu": "must-alias"})
        r = hlo_audit.audit_program(e)
        assert r.status == "fail"
        assert any("dropped" in f or "input_output_alias" in f
                   for f in r.findings), r.findings

    def test_host_callback_is_a_finding(self):
        def impure(x):
            jax.debug.print("x sum {}", x.sum())
            return x * 2

        e = registry.ProgramEntry(
            name="toy.callback",
            builder=lambda: dict(fn=impure, args=(
                jax.ShapeDtypeStruct((8,), jnp.float32),)))
        r = hlo_audit.audit_program(e)
        assert r.status == "fail"
        assert any("callback" in f for f in r.findings), r.findings

    def test_device_requirement_skips(self):
        e = registry.ProgramEntry(
            name="toy.needs_mesh", builder=lambda: dict(),
            requires_devices=10**6)
        assert hlo_audit.audit_program(e).status == "skipped"

    def test_strict_counts_skips_as_failures(self, monkeypatch, capsys):
        # a preset XLA_FLAGS device count must not silently disable the
        # sharded audits while the CI gate still exits 0
        toy = registry.ProgramEntry(
            name="toy.skipper", builder=lambda: dict(),
            requires_devices=10**6)
        monkeypatch.setattr(registry, "iter_programs",
                            lambda fast_only=False: [toy])
        _, failed = hlo_audit.run(fast_only=True, strict=True)
        assert failed == 1
        _, failed = hlo_audit.run(fast_only=True, strict=False)
        assert failed == 0

    def test_full_run_enforces_min_verified_floor(self, monkeypatch,
                                                  capsys):
        # an emptied registry (or mass-skipping env) must fail the FULL
        # audit: the >= MIN_VERIFIED acceptance floor is enforced, not
        # just documented
        monkeypatch.setattr(registry, "iter_programs",
                            lambda fast_only=False: [])
        _, failed = hlo_audit.run()
        assert failed >= 1
        assert "floor" in capsys.readouterr().out

    def test_reregistration_same_module_overwrites(self):
        # module RELOADS re-execute @hlo_program decorators; same-module
        # re-registration must overwrite, not crash the reload
        from raft_tpu.analysis.registry import _PROGRAMS, hlo_program

        try:
            @hlo_program("toy.reload_me")
            def _b1():
                return {}

            @hlo_program("toy.reload_me")  # same module: a reload
            def _b2():
                return {}

            assert _PROGRAMS["toy.reload_me"].builder is _b2
        finally:
            _PROGRAMS.pop("toy.reload_me", None)


class TestHloTextParsers:
    _HLO = """
HloModule m, input_output_alias={ {0}: (1, {}, may-alias) }
  %x = f32[8,64]{1,0} parameter(0)
  %ag = f32[8,8,64]{2,1,0} all-gather(f32[8,1,64]{2,1,0} %x), dimensions={0}
  %ar = (f32[16]{0}, s32[16]{0}) all-reduce(f32[16]{0} %a, s32[16]{0} %b)
  %agr-start = f32[32]{0} all-gather-start(f32[4]{0} %x2)
  %agr-done = f32[32]{0} all-gather-done(f32[32]{0} %agr-start)
  %t-start = (f32[4]{0}, f32[32]{0}) all-gather-start(f32[4]{0} %x5)
  %t-done = f32[32]{0} all-gather-done((f32[4]{0}, f32[32]{0}) %t-start)
  %cc = f32[4]{0} custom-call(f32[4]{0} %x3), custom_call_target="xla_python_cpu_callback"
  %ok = f32[4]{0} custom-call(f32[4]{0} %x4), custom_call_target="TopK"
"""

    def test_collective_stats(self):
        count, nbytes, ops = hlo_audit.collective_stats(self._HLO)
        # all-gather + tuple all-reduce + 2 async starts (dones never
        # re-counted); the TUPLE async start counts only its result half
        # — (operand, result) would otherwise overcount vs the declared
        # result-payload budgets
        assert count == 4
        assert nbytes == ((8 * 8 * 64 * 4) + (16 * 4 + 16 * 4)
                          + 32 * 4 + 32 * 4)

    def test_host_calls_flag_callbacks_not_compute(self):
        f = hlo_audit.host_call_findings(self._HLO)
        assert any("xla_python_cpu_callback" in x for x in f)
        assert not any("TopK" in x for x in f)

    def test_aliased_params(self):
        assert hlo_audit.aliased_params(self._HLO) == [(1, "may-alias")]


# ---------------------------------------------------------------------------
# the shipped registry


class TestShippedRegistry:
    def test_catalog(self):
        entries = {e.name: e for e in registry.iter_programs()}
        # the ISSUE-20 floor: >= 17 hot-path programs declared — all three
        # serve backends in sharded one-allgather form (ISSUE 12), the
        # three graduated Pallas kernels (ISSUE 13), the replica-group
        # program on the 2D shard × replica carve (ISSUE 15), the tiered
        # cold-scan + exact-refine pair (ISSUE 18), and the mutable
        # delta-merged masked search (ISSUE 20)
        assert len(entries) >= 17, sorted(entries)
        for expected in ("brute_force.knn_scan", "ivf_flat.search_batch",
                         "ivf_pq.full_search", "ivf_pq.encode_tile",
                         "ivf_pq.csum_tile", "cluster.fused_em_step",
                         "build.scatter_append_in_place",
                         "ann_mnmg.ivf_flat_sharded",
                         "ann_mnmg.ivf_pq_sharded",
                         "ann_mnmg.brute_force_sharded",
                         "ann_mnmg.ivf_flat_replica_group",
                         "kernels.select_k", "kernels.fused_l2_nn",
                         "kernels.ivf_pq_lut",
                         "tiering.cold_scan", "tiering.refine",
                         "mutable.delta_merged_search"):
            assert expected in entries, expected
        # every single-device entry pins a zero-collective budget; the
        # sharded entries pin exactly one launch of the packed (nq, 2k)
        # merge payload — stacked over the FULL world for the full-mesh
        # programs, over the GROUP world for the replica-group program
        # (the fleet total is R × the group payload)
        sharded_bytes = set()
        for e in entries.values():
            if e.requires_devices == 1:
                assert e.collectives == 0, e.name
            else:
                assert e.collectives == 1, e.name
                sharded_bytes.add(e.collective_bytes)
        assert sharded_bytes == {8 * 64 * 2 * 8 * 4,
                                 (8 // 2) * 64 * 2 * 8 * 4}

    def test_ivf_pq_sharded_audit_one_allgather(self, devices):
        # satellite: the previously-missing third sharded backend entry
        r = hlo_audit.audit_program(registry.get_program(
            "ann_mnmg.ivf_pq_sharded"))
        assert r.status == "ok", r.findings
        assert r.stats["collectives"] == 1
        assert r.stats["collective_bytes"] == 8 * 64 * 2 * 8 * 4

    def test_hotpath_function_scopes_resolve(self):
        # a registry entry naming a function that does not exist guards
        # NOTHING — every declared function scope must resolve in its
        # module (the dead-entry regression class)
        import ast as ast_mod

        from raft_tpu.analysis import hotpaths

        for hp in hotpaths.HOT_PATHS:
            if not hp.functions:
                continue
            mod = REPO / hp.pattern
            assert mod.is_file(), hp.pattern
            defined = {n.name for n in ast_mod.walk(
                ast_mod.parse(mod.read_text()))
                if isinstance(n, (ast_mod.FunctionDef,
                                  ast_mod.AsyncFunctionDef))}
            missing = set(hp.functions) - defined
            assert not missing, (hp.pattern, sorted(missing))

    def test_donation_entry_documents_backends(self):
        e = registry.get_program("build.scatter_append_in_place")
        assert e.donate_argnums == (0, 1)
        assert e.donation_policy.get("cpu") == "may-alias"
        assert e.donation_policy.get("tpu") == "must-alias"

    def test_encode_tile_audit_passes(self):
        # the graduated PR-7 O(tile)-transient gate, spec-only (cheap)
        r = hlo_audit.audit_program(registry.get_program(
            "ivf_pq.encode_tile"))
        assert r.status == "ok", r.findings
        assert r.stats["transient_bytes"] <= 8 << 20

    def test_sharded_audit_one_allgather(self, devices):
        r = hlo_audit.audit_program(registry.get_program(
            "ann_mnmg.brute_force_sharded"))
        assert r.status == "ok", r.findings
        assert r.stats["collectives"] == 1


class TestExitCodes:
    """The CLI's exit-code contract (docs/static_analysis.md §exit
    codes): 0 clean, 1 findings, 2 when the ONLY failures are programs
    skipped under --strict.  Pinned here so documentation and behavior
    cannot drift apart again."""

    def test_clean_run_exits_zero(self, capsys):
        from raft_tpu.analysis.__main__ import main

        assert main(["--hlo", "--programs", "ivf_pq.csum_tile"]) == 0

    def test_findings_exit_one(self, tmp_path, capsys):
        bad = tmp_path / "raft_tpu" / "x"
        bad.mkdir(parents=True)
        f = bad / "mod.py"
        f.write_text("import jax\n\n\ndef g(v, i):\n"
                     "    return jax.ops.segment_sum(v, i, "
                     "num_segments=4)\n")
        from raft_tpu.analysis.__main__ import main

        assert main(["--ast", str(f)]) == 1

    def test_strict_skip_only_exits_two(self, monkeypatch, capsys):
        toy = registry.ProgramEntry(
            name="toy.skipper", builder=lambda: dict(),
            requires_devices=10 ** 6)
        monkeypatch.setattr(registry, "iter_programs",
                            lambda fast_only=False: [toy])
        from raft_tpu.analysis.__main__ import main

        # --fast: the toy registry would otherwise ALSO trip the full-run
        # MIN_VERIFIED floor (a finding → exit 1), masking the skip-only
        # path this test pins
        assert main(["--hlo", "--strict", "--fast"]) == 2
        # without strict the skip is free, but the emptied registry trips
        # the full-run MIN_VERIFIED floor — a FINDING, so exit 1 not 2
        assert main(["--hlo"]) == 1

    def test_strict_skip_plus_finding_exits_one(self, monkeypatch,
                                                tmp_path, capsys):
        toy = registry.ProgramEntry(
            name="toy.skipper", builder=lambda: dict(),
            requires_devices=10 ** 6)
        monkeypatch.setattr(registry, "iter_programs",
                            lambda fast_only=False: [toy])
        bad = tmp_path / "raft_tpu" / "x"
        bad.mkdir(parents=True)
        f = bad / "mod.py"
        f.write_text("import jax\n\n\ndef g(v, i):\n"
                     "    return jax.ops.segment_sum(v, i, "
                     "num_segments=4)\n")
        from raft_tpu.analysis.__main__ import main

        assert main(["--ast", "--hlo", "--strict", str(f)]) == 1

    def test_stale_exemptions_alone_always_exits_zero(self, tmp_path,
                                                      capsys):
        f = tmp_path / "mod.py"
        f.write_text("def f(x):\n"
                     "    return x  # exempt(raw-segment-sum): stale\n")
        from raft_tpu.analysis.__main__ import main

        assert main(["--stale-exemptions", str(f)]) == 0
        assert "stale" in capsys.readouterr().out


class TestCliArgs:
    def test_programs_filter_space_form(self, capsys):
        from raft_tpu.analysis.__main__ import main

        rc = main(["--hlo", "--programs", "ivf_pq.encode_tile"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "ivf_pq.encode_tile" in out
        assert "knn_scan" not in out

    def test_programs_filter_eq_form(self, capsys):
        from raft_tpu.analysis.__main__ import main

        rc = main(["--hlo", "--programs=ivf_pq.csum_tile"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "ivf_pq.csum_tile" in out
        assert "encode_tile" not in out


@pytest.mark.slow
class TestCli:
    def test_module_cli_exits_zero_at_head(self):
        # the full gate (AST + HLO audit + fingerprints + retrace), as CI
        # runs it — in CI's ENVIRONMENT: the conftest exports
        # JAX_ENABLE_X64=1 for the in-process suite, but the committed
        # goldens are recorded for the CI env (x64 off), and an
        # environment-mismatched golden is skipped, not compared
        import os

        env = {k: v for k, v in os.environ.items()
               if k != "JAX_ENABLE_X64"}
        p = subprocess.run([sys.executable, "-m", "raft_tpu.analysis"],
                           cwd=REPO, capture_output=True, text=True,
                           timeout=600, env=env)
        assert p.returncode == 0, p.stdout + p.stderr
        assert "verified" in p.stdout
        assert "obligation(s) certified" in p.stdout
