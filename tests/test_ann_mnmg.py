"""Sharded multi-device ANN search (raft_tpu/neighbors/ann_mnmg;
docs/sharded_ann.md): sharded ≡ single-device property grid across
{ivf_flat, ivf_pq, brute_force} × {f32, bf16} × world {1, 2, 8}, ragged
(multi-chunk) list partitions, empty-shard probe sets, ShardedIndex
serialize round-trip, the one-allgather collective contract (count AND
payload bytes), zero-compile warmed dispatch, the query-sharded
zero-collective knn_mnmg mode, and ServeEngine sharded coalescing."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.comms import build_comms
from raft_tpu.core.aot import aot_compile_counters
from raft_tpu.neighbors import ann_mnmg, ivf_flat, ivf_pq, knn
from raft_tpu.neighbors.knn_mnmg import knn_mnmg

_N, _DIM, _K = 600, 16, 5
_PROBES = 4

_COMMS = {}


def _comms(world):
    """One communicator per world size for the whole module (each carries
    its program/jit caches — rebuilding per test would retrace)."""
    if world not in _COMMS:
        from jax.sharding import Mesh

        _COMMS[world] = build_comms(
            Mesh(np.array(jax.devices()[:world]), ("world",)))
    return _COMMS[world]


def _data(seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(0, 1, (_N, _DIM)).astype(np.float32)
    q = rng.normal(0, 1, (33, _DIM)).astype(np.float32)
    return x, q


_STATE = {}


def _index(backend):
    """Build each base index once per module (builds dominate test time)."""
    if backend not in _STATE:
        x, _ = _data()
        if backend == "brute_force":
            _STATE[backend] = x
        elif backend == "ivf_flat":
            _STATE[backend] = ivf_flat.build(
                ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=4), x)
        else:
            _STATE[backend] = ivf_pq.build(
                ivf_pq.IndexParams(n_lists=16, pq_dim=8, pq_bits=8,
                                   kmeans_n_iters=4, seed=1), x)
    return _STATE[backend]


def _solo(backend, q, k=_K):
    idx = _index(backend)
    if backend == "brute_force":
        return knn(idx, q, k)
    if backend == "ivf_flat":
        return ivf_flat.search(ivf_flat.SearchParams(n_probes=_PROBES),
                               idx, q, k)
    return ivf_pq.search(ivf_pq.SearchParams(n_probes=_PROBES), idx, q, k)


def _sharded(backend, world):
    key = (backend, world)
    if key not in _STATE:
        comms = _comms(world)
        idx = _index(backend)
        if backend == "brute_force":
            _STATE[key] = ann_mnmg.shard_brute_force(idx, comms)
        else:
            _STATE[key] = idx.shard(comms)
    return _STATE[key]


def _params(backend):
    if backend == "brute_force":
        return None
    if backend == "ivf_flat":
        return ivf_flat.SearchParams(n_probes=_PROBES)
    return ivf_pq.SearchParams(n_probes=_PROBES)


@pytest.mark.parametrize("world", [1, 2, 8])
@pytest.mark.parametrize("backend", ["brute_force", "ivf_flat", "ivf_pq"])
def test_sharded_matches_single_device(backend, world):
    """The core contract: the sharded program's f32 top-k (ids AND
    distances) is IDENTICAL to single-device search of the same index —
    per-shard scans reproduce the solo scan's per-candidate scores
    exactly, and the shard-order part merge reproduces the sequential
    scan's stable tie order (deferred-sqrt merge on squared L2)."""
    _, q = _data()
    d0, i0 = _solo(backend, q)
    sh = _sharded(backend, world)
    d1, i1 = ann_mnmg.search(sh, q, _K, _params(backend))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))


@pytest.mark.parametrize("backend", ["brute_force", "ivf_flat", "ivf_pq"])
def test_sharded_matches_single_device_bf16(backend):
    """bf16 queries take the same accumulate-in-f32 path on both sides
    (ivf_pq ingests bf16 → f32 on both; ivf_flat/brute-force keep bf16
    MXU inputs with f32 scores), so sharded ≡ solo holds bit-for-bit for
    half-precision serving traffic too."""
    _, q = _data()
    qb = jnp.asarray(q, jnp.bfloat16)
    if backend == "brute_force":
        # a bf16 INDEX exercises the half-precision scan carry; build its
        # own shard (the f32 module index stays f32)
        x, _ = _data()
        xb = jnp.asarray(x, jnp.bfloat16)
        d0, i0 = knn(xb, qb, _K)
        sh = ann_mnmg.shard_brute_force(xb, _comms(8))
        d1, i1 = ann_mnmg.search(sh, qb, _K)
    else:
        d0, i0 = _solo(backend, qb)
        d1, i1 = ann_mnmg.search(_sharded(backend, 8), qb, _K,
                                 _params(backend))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))


def test_ragged_list_partitions():
    """Skewed cluster sizes force multi-chunk lists; the shard-local
    chunk tables then carry continuation chunks whose budget CANNOT be
    derived from the local table shape (expand_probes' extra override) —
    a truncated budget would silently drop real candidates here."""
    rng = np.random.default_rng(3)
    # one dominant tight blob (most rows land in few lists → multi-chunk)
    blob = rng.normal(0, 0.05, (400, _DIM)).astype(np.float32)
    rest = rng.normal(0, 1, (200, _DIM)).astype(np.float32)
    x = np.concatenate([blob, rest])
    idx = ivf_flat.build(
        ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=4), x)
    assert idx.chunk_table.shape[1] > 1, "data model failed to go ragged"
    q = rng.normal(0, 0.3, (17, _DIM)).astype(np.float32)
    sp = ivf_flat.SearchParams(n_probes=6)
    d0, i0 = ivf_flat.search(sp, idx, q, _K)
    for world in (2, 8):
        sh = idx.shard(_comms(world))
        assert sh.aux["probe_extra"] > 0, "ragged partition lost its chunks"
        d1, i1 = ann_mnmg.search(sh, q, _K, sp)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))


def test_empty_shard_probe_sets():
    """n_probes=1 on world=8 leaves 7 shards with an EMPTY probe
    intersection per query — their scans score only the masked dummy and
    contribute sentinel/-1 runs the merge must discard."""
    _, q = _data()
    sp = ivf_flat.SearchParams(n_probes=1)
    idx = _index("ivf_flat")
    d0, i0 = ivf_flat.search(sp, idx, q, 3)
    d1, i1 = ann_mnmg.search(_sharded("ivf_flat", 8), q, 3, sp)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))


def test_one_allgather_per_search_counter_and_bytes():
    """The collective contract (ISSUE 6 acceptance): one traced search
    program contains EXACTLY one allgather, and its payload is the packed
    (bucket, 2k) f32 merge payload — the bytes counter catches over-fat
    programs the launch count alone would miss."""
    comms = _comms(8)
    _, q = _data()
    q = q[:8]                      # bucket 8
    k = 7                          # fresh statics → fresh trace
    before = dict(comms.collective_calls)
    d1, i1 = ann_mnmg.search(_sharded("ivf_flat", 8), q, k,
                             ivf_flat.SearchParams(n_probes=_PROBES))
    delta = {key: comms.collective_calls[key] - before.get(key, 0)
             for key in comms.collective_calls
             if comms.collective_calls[key] != before.get(key, 0)}
    assert delta == {"allgather": 1,
                     "allgather_bytes": 8 * 2 * k * 4}, delta
    d0, i0 = _solo("ivf_flat", q, k)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))


def test_warmed_searcher_zero_compiles():
    """MeshAot pinning: after warm(bucket, dtype), dispatching that
    signature performs zero compiles/retraces (counter-asserted — the
    serving steady-state contract extended to shard_map programs)."""
    sh = _sharded("ivf_flat", 8)
    s = sh.searcher(_K, ivf_flat.SearchParams(n_probes=_PROBES))
    s.warm(8, jnp.float32)
    _, q = _data()
    c0 = aot_compile_counters["compiles"]
    d, i = ann_mnmg.search(sh, q[:6], _K,
                           ivf_flat.SearchParams(n_probes=_PROBES))
    assert aot_compile_counters["compiles"] == c0, \
        "warmed sharded search compiled at dispatch"
    assert np.asarray(d).shape == (6, _K)


def test_sharded_serialize_roundtrip(tmp_path):
    """ShardedIndex round-trip: the finished partition (replicated tables
    + per-shard blocks + aux) reloads onto a same-world communicator and
    searches identically; a world-mismatched load fails loudly."""
    from raft_tpu.core.error import LogicError
    from raft_tpu.neighbors import serialize

    sh = _sharded("ivf_pq", 8)
    p = str(tmp_path / "sharded.npz")
    serialize.save_sharded(p, sh)
    sh2 = serialize.load_sharded(p, _comms(8))
    _, q = _data()
    sp = ivf_pq.SearchParams(n_probes=_PROBES)
    d1, i1 = ann_mnmg.search(sh, q, _K, sp)
    d2, i2 = ann_mnmg.search(sh2, q, _K, sp)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))
    with pytest.raises(LogicError):
        serialize.load_sharded(p, _comms(2))  # partition is world-specific


def test_brute_force_pad_rows_never_surface():
    """501 rows over 8 shards pads with sentinel rows — they must never
    appear in the top-k for k <= n."""
    rng = np.random.default_rng(5)
    x = rng.normal(0, 1, (501, _DIM)).astype(np.float32)
    q = rng.normal(0, 1, (9, _DIM)).astype(np.float32)
    d0, i0 = knn(x, q, 7)
    sh = ann_mnmg.shard_brute_force(x, _comms(8))
    d1, i1 = ann_mnmg.search(sh, q, 7)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
    assert int(np.max(np.asarray(i1))) < 501


def test_brute_force_pad_rows_refused_outside_l2():
    """Sentinel row padding is only sound for float L2 metrics: no finite
    vector is guaranteed to LOSE under InnerProduct (dot grows with
    magnitude) or Cosine (scale-invariant), so a ragged split there must
    fail loudly instead of surfacing fabricated ids >= n."""
    from raft_tpu.core.error import LogicError
    from raft_tpu.distance.distance_types import DistanceType

    rng = np.random.default_rng(6)
    x = rng.normal(0, 1, (501, _DIM)).astype(np.float32)
    with pytest.raises(LogicError):
        ann_mnmg.shard_brute_force(x, _comms(8),
                                   metric=DistanceType.InnerProduct)
    with pytest.raises(LogicError):
        ann_mnmg.shard_brute_force(x.astype(np.int8), _comms(8))
    # an even split under IP is fine
    sh = ann_mnmg.shard_brute_force(x[:496], _comms(8),
                                    metric=DistanceType.InnerProduct)
    q = rng.normal(0, 1, (5, _DIM)).astype(np.float32)
    d0, i0 = knn(x[:496], q, 4, DistanceType.InnerProduct)
    d1, i1 = ann_mnmg.search(sh, q, 4)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))


def test_query_sharded_knn_zero_collectives():
    """partition="queries": disjoint per-rank results gathered by the
    output sharding alone — identical to single-device knn with ZERO
    collective launches in the traced program."""
    comms = _comms(8)
    rng = np.random.default_rng(7)
    x = rng.normal(0, 1, (256, _DIM)).astype(np.float32)
    q = rng.normal(0, 1, (41, _DIM)).astype(np.float32)
    d0, i0 = knn(x, q, 6)
    before = dict(comms.collective_calls)
    d1, i1 = knn_mnmg(comms, x, q, 6, partition="queries")
    assert dict(comms.collective_calls) == before, \
        "query-sharded program launched a collective"
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))
    # auto mode: nq >= n flips to query sharding
    d2, i2 = knn_mnmg(comms, x[:32], q, 6, partition="auto")
    d3, i3 = knn(x[:32], q, 6)
    np.testing.assert_array_equal(np.asarray(i2), np.asarray(i3))


def test_index_sharded_knn_one_allgather():
    """The default OPG topology now packs distances+ids into ONE
    allgather (was two in r1) — counter-asserted with payload bytes."""
    comms = _comms(8)
    rng = np.random.default_rng(9)
    x = rng.normal(0, 1, (256, _DIM)).astype(np.float32)
    q = rng.normal(0, 1, (16, _DIM)).astype(np.float32)
    k = 9                          # fresh statics → fresh trace
    before = dict(comms.collective_calls)
    d1, i1 = knn_mnmg(comms, x, q, k)
    delta = {key: comms.collective_calls[key] - before.get(key, 0)
             for key in comms.collective_calls
             if comms.collective_calls[key] != before.get(key, 0)}
    assert delta == {"allgather": 1,
                     "allgather_bytes": 16 * 2 * k * 4}, delta
    d0, i0 = knn(x, q, k)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))


@pytest.mark.parametrize("backend", ["brute_force", "ivf_flat", "ivf_pq"])
def test_serve_engine_sharded_coalescing(backend):
    """ServeEngine over the sharded backend: coalesced super-batches
    dispatch ONE shard_map program across all chips, per-request results
    identical to the solo sharded path, zero compiles after warmup."""
    from raft_tpu.serve import ServeEngine

    sh = _sharded(backend, 8)
    params = _params(backend)
    eng = ServeEngine(sh, _K, params, max_batch=64)
    assert eng.backend == f"sharded_{backend}"
    eng.warmup()
    rng = np.random.default_rng(11)
    mixes = [(3, 17, 1, 0, 9), (64,), (1, 1, 1)]
    eng.search([rng.normal(0, 1, (2, _DIM)).astype(np.float32)])
    c0 = aot_compile_counters["compiles"]
    for mix in mixes:
        reqs = [rng.normal(0, 1, (s, _DIM)).astype(np.float32)
                for s in mix]
        outs = eng.search(reqs)
        for qq, (d, i) in zip(reqs, outs):
            d0, i0 = ann_mnmg.search(sh, qq, _K, params)
            np.testing.assert_array_equal(i, np.asarray(i0))
            np.testing.assert_array_equal(d, np.asarray(d0))
    assert aot_compile_counters["compiles"] == c0, \
        "sharded serving compiled during steady state"
    assert eng.stats["super_batches"] >= len(mixes)
