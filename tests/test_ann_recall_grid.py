"""Reference-grade ANN recall grids (VERDICT r2 missing #6).

Shape of reference test/neighbors/ann_ivf_pq.cuh: parameterized input grids
(rows × dim × pq_bits × n_probes × dtype) with per-config ``min_recall``
thresholds.  The data model is clustered (make_blobs-like) — the regime the
reference's thresholds assume; on isotropic data PQ recall is information-
limited (see tests/test_ivf_pq.py ADC-oracle test).

CI economy: the default run covers a representative sub-grid (this round's
CI host has 1 vCPU); set ``RAFT_TPU_FULL_GRID=1`` for the full sweep
(n_rows 100k rows included), which is what a TPU CI runner should run.
"""

import os

import numpy as np
import pytest

# Tier-1 budget (ROADMAP.md): the grid builds 10k-row indexes per case and
# costs ~70s warm — slow-marked as a module; per-config recall gates stay
# covered in tier-1 by tests/test_ivf_pq.py (recall_pq_bits, bf16/int
# dataset recalls) and tests/test_ivf_flat.py.  Full-grid CI runs drop the
# marker filter (or set RAFT_TPU_FULL_GRID=1 for the 100k sweep).
pytestmark = pytest.mark.slow

from raft_tpu.distance import DistanceType
from raft_tpu.neighbors import ivf_flat, ivf_pq
from raft_tpu.neighbors.brute_force import knn

FULL = os.environ.get("RAFT_TPU_FULL_GRID", "") == "1"


def _clustered(n, dim, n_clusters, seed, scale=5.0):
    """Cluster centers + LOW-RANK residuals + small noise — the correlated-
    feature structure of real descriptor data (SIFT), which reference
    recall thresholds assume.  Isotropic residuals make PQ recall
    information-limited (see tests/test_ivf_pq.py ADC-oracle test) and
    would force uselessly low thresholds."""
    rng = np.random.default_rng(seed)
    rank = max(2, dim // 4)
    centers = rng.normal(0, scale, (n_clusters, dim))
    proj = rng.normal(0, 1, (rank, dim)) / np.sqrt(rank)

    def make(m):
        cid = rng.integers(0, n_clusters, m)
        return (centers[cid] + rng.normal(0, 1, (m, rank)) @ proj
                + rng.normal(0, 0.05, (m, dim))).astype(np.float32)

    return make(n), make(128)


def _recall(i, ti):
    i, ti = np.asarray(i), np.asarray(ti)
    return sum(len(set(a.tolist()) & set(b.tolist()))
               for a, b in zip(i, ti)) / ti.size


def _quantize(x, q, dtype: str):
    """Affine-map clustered f32 data into the integer dtype's range (the
    reference's per-dtype test instantiations feed integer-valued data the
    same way, cpp/test/neighbors/ann_ivf_pq/test_*.cu)."""
    if dtype == "int8":
        s = 127.0 / np.abs(x).max()
        return (np.clip(np.round(x * s), -127, 127).astype(np.int8),
                np.clip(np.round(q * s), -127, 127).astype(np.int8))
    if dtype == "uint8":
        off = -x.min()
        s = 255.0 / (x.max() + off)
        return (np.clip(np.round((x + off) * s), 0, 255).astype(np.uint8),
                np.clip(np.round((q + off) * s), 0, 255).astype(np.uint8))
    return x, q


# (n_rows, dim, pq_bits, n_probes, min_recall) — thresholds leave ~0.05
# headroom below values measured with the default (auto → pca_balanced)
# rotation on this data model (the reference's min_recall tables are
# calibrated the same way per config; measured: 0.97/0.95/0.78/0.95/0.88
# for the small grid rows in order).
_PQ_GRID_SMALL = [
    (10_000, 8, 8, 10, 0.90),
    (10_000, 64, 8, 10, 0.90),
    (10_000, 64, 4, 50, 0.70),
    (10_000, 128, 8, 50, 0.90),
    (10_000, 128, 5, 50, 0.80),
]
_PQ_GRID_FULL = _PQ_GRID_SMALL + [
    (10_000, 64, 6, 50, 0.80),   # measured 0.86
    (10_000, 128, 8, 200, 0.90),  # measured 0.95
    # 100k rows: gates calibrated from a FULL-grid CPU run (r3)
    (100_000, 64, 8, 10, 0.75),   # measured 0.81
    (100_000, 128, 8, 50, 0.82),  # measured 0.88
    (100_000, 128, 4, 200, 0.50),  # measured 0.59
]


@pytest.mark.parametrize("n_rows,dim,pq_bits,n_probes,min_recall",
                         _PQ_GRID_FULL if FULL else _PQ_GRID_SMALL)
def test_ivf_pq_recall_grid(n_rows, dim, pq_bits, n_probes, min_recall):
    n_lists = max(32, n_rows // 500)
    x, q = _clustered(n_rows, dim, n_clusters=max(20, n_lists), seed=dim + pq_bits)
    pq_dim = max(4, dim // 4)
    idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=n_lists, pq_dim=pq_dim,
                                          pq_bits=pq_bits, seed=1), x)
    _, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=min(n_probes, n_lists)),
                         idx, q, 10)
    _, ti = knn(x, q, 10, DistanceType.L2Expanded)
    r = _recall(i, ti)
    assert r >= min_recall, (
        f"ivf_pq recall {r:.3f} < {min_recall} at rows={n_rows} dim={dim} "
        f"pq_bits={pq_bits} n_probes={n_probes}")


# Per-dtype IVF-PQ rows (reference builds are templated on T ∈ {float,
# int8_t, uint8_t}, neighbors/ivf_pq.cuh:62, with per-dtype recall tests
# cpp/test/neighbors/ann_ivf_pq/test_*.cu).  Gates leave ~0.05 headroom
# below measured values (64-dim: int8 0.94 / uint8 0.947; 128-dim:
# int8 0.966 / uint8 0.949 on this data model, pq8 n_probes=50).
_PQ_DTYPE_GRID_SMALL = [
    (10_000, 64, "int8", 8, 50, 0.88),
    (10_000, 64, "uint8", 8, 50, 0.88),
]
_PQ_DTYPE_GRID_FULL = _PQ_DTYPE_GRID_SMALL + [
    (10_000, 128, "int8", 8, 50, 0.90),
    (10_000, 128, "uint8", 8, 50, 0.90),
    (100_000, 128, "int8", 8, 50, 0.80),
    (100_000, 128, "uint8", 8, 50, 0.80),
]


@pytest.mark.parametrize("n_rows,dim,dtype,pq_bits,n_probes,min_recall",
                         _PQ_DTYPE_GRID_FULL if FULL else _PQ_DTYPE_GRID_SMALL)
def test_ivf_pq_recall_grid_int_dtypes(n_rows, dim, dtype, pq_bits,
                                       n_probes, min_recall):
    n_lists = max(32, n_rows // 500)
    x, q = _clustered(n_rows, dim, n_clusters=max(20, n_lists),
                      seed=dim + pq_bits)
    xs, qs = _quantize(x, q, dtype)
    idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=n_lists,
                                          pq_dim=max(4, dim // 4),
                                          pq_bits=pq_bits, seed=1), xs)
    assert idx.dataset_dtype == dtype
    _, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=min(n_probes, n_lists)),
                         idx, qs, 10)
    _, ti = knn(xs.astype(np.float32), qs.astype(np.float32), 10,
                DistanceType.L2Expanded)
    r = _recall(i, ti)
    assert r >= min_recall, (
        f"ivf_pq recall {r:.3f} < {min_recall} at rows={n_rows} dim={dim} "
        f"dtype={dtype} pq_bits={pq_bits} n_probes={n_probes}")


# (n_rows, dim, dtype, n_probes, min_recall) — IVF-Flat stores exact
# vectors, so recall is limited only by probe coverage (reference
# ann_ivf_flat.cu thresholds are accordingly higher).
_FLAT_GRID_SMALL = [
    (10_000, 8, "float32", 10, 0.90),
    (10_000, 64, "float32", 50, 0.97),
    (10_000, 128, "int8", 50, 0.90),
]
_FLAT_GRID_FULL = _FLAT_GRID_SMALL + [
    (10_000, 128, "float32", 200, 0.99),
    (10_000, 64, "int8", 10, 0.70),
    (100_000, 64, "float32", 50, 0.95),
    (100_000, 128, "int8", 200, 0.95),
]


@pytest.mark.parametrize("n_rows,dim,dtype,n_probes,min_recall",
                         _FLAT_GRID_FULL if FULL else _FLAT_GRID_SMALL)
def test_ivf_flat_recall_grid(n_rows, dim, dtype, n_probes, min_recall):
    n_lists = max(32, n_rows // 500)
    x, q = _clustered(n_rows, dim, n_clusters=max(20, n_lists), seed=dim)
    xs, qs = _quantize(x, q, dtype)
    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=n_lists), xs)
    _, i = ivf_flat.search(
        ivf_flat.SearchParams(n_probes=min(n_probes, n_lists)), idx, qs, 10)
    _, ti = knn(xs.astype(np.float32), qs.astype(np.float32), 10,
                DistanceType.L2Expanded)
    r = _recall(i, ti)
    assert r >= min_recall, (
        f"ivf_flat recall {r:.3f} < {min_recall} at rows={n_rows} dim={dim} "
        f"dtype={dtype} n_probes={n_probes}")


@pytest.mark.parametrize("index_kind", ["ivf_flat", "ivf_pq"])
def test_incremental_extend_meets_build_recall_gate(index_kind):
    """r5 incremental extend: an index built on 90% of the rows and
    extended with the final 10% must clear the same min_recall gate as the
    all-at-once build on identical parameters — the reference holds
    extend-path indexes to the same recall thresholds
    (ann_ivf_pq.cuh build-then-extend instantiations)."""
    n = 20_000 if FULL else 6_000
    x, q = _clustered(n, 32, 40, seed=17)
    cut = int(n * 0.9)
    _, ti = knn(x, q, 10)
    if index_kind == "ivf_flat":
        params = ivf_flat.IndexParams(n_lists=64, seed=3)
        full = ivf_flat.build(params, x)
        part = ivf_flat.extend(ivf_flat.build(params, x[:cut]), x[cut:])
        sp = ivf_flat.SearchParams(n_probes=16)
        search = ivf_flat.search
    else:
        params = ivf_pq.IndexParams(n_lists=64, pq_dim=16, pq_bits=8,
                                    seed=3)
        full = ivf_pq.build(params, x)
        part = ivf_pq.extend(ivf_pq.build(params, x[:cut]), x[cut:])
        sp = ivf_pq.SearchParams(n_probes=16)
        search = ivf_pq.search
    r_full = _recall(search(sp, full, q, 10)[1], ti)
    r_part = _recall(search(sp, part, q, 10)[1], ti)
    # the extended index trains its quantizer on 90% of the data — allow a
    # small gap but hold it to the same regime
    assert r_part >= r_full - 0.03, (r_part, r_full)
    assert r_part >= (0.85 if index_kind == "ivf_flat" else 0.6), r_part
