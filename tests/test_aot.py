"""AOT executable cache (the precompiled-libs equivalent, SURVEY.md §2.14)."""

import jax.numpy as jnp
import numpy as np

from raft_tpu.core.aot import aot, enable_persistent_cache


def test_aot_caches_per_signature():
    calls = {"n": 0}

    @aot
    def f(x):
        calls["n"] += 1  # traced once per signature
        return x * 2.0

    a = np.ones((16, 4), np.float32)
    r1 = f(a)
    r2 = f(a + 1)
    np.testing.assert_allclose(np.array(r2), 4.0)
    assert calls["n"] == 1
    assert f.cache_size == 1
    f(np.ones((32, 4), np.float32))  # new shape → new executable
    assert f.cache_size == 2
    f(np.ones((16, 4), np.float64))  # new dtype → new executable
    assert f.cache_size == 3


def test_aot_bucketing_bounds_executables():
    @aot(bucket=True)
    def f(x):
        return jnp.sum(x, axis=1)

    for n in (9, 11, 13, 16):
        out = f(np.ones((n, 3), np.float32))
        assert out.shape[0] == 16  # bucketed to next pow2
        np.testing.assert_allclose(np.array(out)[:n], 3.0)
    assert f.cache_size == 1


def test_aot_static_args():
    @aot(static_argnums=(1,))
    def f(x, k):
        return x[:, :k]

    out = f(np.ones((4, 8), np.float32), 3)
    assert out.shape == (4, 3)
    assert f.cache_size == 1
    f(np.ones((4, 8), np.float32), 5)
    assert f.cache_size == 2


def test_persistent_cache_dir(tmp_path):
    d = enable_persistent_cache(str(tmp_path / "xla"))
    import os

    assert os.path.isdir(d)


def test_persistent_cache_scoped_by_machine_fingerprint(tmp_path, monkeypatch):
    """Every cache base gains the machine-fingerprint subdir (cross-host
    XLA:CPU AOT reuse can SIGILL — see _machine_fingerprint); the
    fingerprint is stable within a process."""
    from raft_tpu.core.aot import _machine_fingerprint

    fp = _machine_fingerprint()
    assert fp == _machine_fingerprint() and len(fp) == 12

    d = enable_persistent_cache(str(tmp_path / "base"))
    assert d.endswith(f"xla-{fp}")
    assert d.startswith(str(tmp_path / "base"))

    monkeypatch.setenv("RAFT_TPU_CACHE_DIR", str(tmp_path / "envbase"))
    d2 = enable_persistent_cache()
    assert d2.endswith(f"xla-{fp}") and str(tmp_path / "envbase") in d2
