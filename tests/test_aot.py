"""AOT executable cache (the precompiled-libs equivalent, SURVEY.md §2.14)."""

import jax.numpy as jnp
import numpy as np

from raft_tpu.core.aot import aot, enable_persistent_cache


def test_aot_caches_per_signature():
    calls = {"n": 0}

    @aot
    def f(x):
        calls["n"] += 1  # traced once per signature
        return x * 2.0

    a = np.ones((16, 4), np.float32)
    r1 = f(a)
    r2 = f(a + 1)
    np.testing.assert_allclose(np.array(r2), 4.0)
    assert calls["n"] == 1
    assert f.cache_size == 1
    f(np.ones((32, 4), np.float32))  # new shape → new executable
    assert f.cache_size == 2
    f(np.ones((16, 4), np.float64))  # new dtype → new executable
    assert f.cache_size == 3


def test_aot_bucketing_bounds_executables():
    @aot(bucket=True)
    def f(x):
        return jnp.sum(x, axis=1)

    for n in (9, 11, 13, 16):
        out = f(np.ones((n, 3), np.float32))
        assert out.shape[0] == 16  # bucketed to next pow2
        np.testing.assert_allclose(np.array(out)[:n], 3.0)
    assert f.cache_size == 1


def test_aot_static_args():
    @aot(static_argnums=(1,))
    def f(x, k):
        return x[:, :k]

    out = f(np.ones((4, 8), np.float32), 3)
    assert out.shape == (4, 3)
    assert f.cache_size == 1
    f(np.ones((4, 8), np.float32), 5)
    assert f.cache_size == 2


def test_persistent_cache_dir(tmp_path):
    d = enable_persistent_cache(str(tmp_path / "xla"))
    import os

    assert os.path.isdir(d)


def test_persistent_cache_scoped_by_machine_fingerprint(tmp_path, monkeypatch):
    """Every cache base gains the machine-fingerprint subdir (cross-host
    XLA:CPU AOT reuse can SIGILL — see _machine_fingerprint); the
    fingerprint is stable within a process."""
    from raft_tpu.core.aot import _machine_fingerprint

    fp = _machine_fingerprint()
    assert fp == _machine_fingerprint() and len(fp) == 12

    d = enable_persistent_cache(str(tmp_path / "base"))
    assert d.endswith(f"xla-{fp}")
    assert d.startswith(str(tmp_path / "base"))

    monkeypatch.setenv("RAFT_TPU_CACHE_DIR", str(tmp_path / "envbase"))
    d2 = enable_persistent_cache()
    assert d2.endswith(f"xla-{fp}") and str(tmp_path / "envbase") in d2


def test_aot_pytree_args():
    """Dynamic args may be pytrees of arrays (the IVF index-leaves pattern)."""
    from raft_tpu.core.aot import aot

    calls = []

    @aot(static_argnums=(1,))
    def f(tree, scale):
        calls.append(1)
        return tree[0] * scale + tree[1]["b"]

    t1 = (jnp.ones((4,)), {"b": jnp.full((4,), 2.0)})
    out = f(t1, 3.0)
    np.testing.assert_allclose(np.asarray(out), 5.0)
    f((jnp.zeros((4,)), {"b": jnp.ones((4,))}), 3.0)  # same signature: no retrace
    assert f.cache_size == 1
    f((jnp.zeros((8,)), {"b": jnp.ones((8,))}), 3.0)  # new shapes: new entry
    assert f.cache_size == 2


def test_aot_shape_dtype_struct_prewarm():
    """ShapeDtypeStruct specs compile without materializing data."""
    import jax

    from raft_tpu.core.aot import aot

    @aot(static_argnums=(1,))
    def g(x, k):
        return x * k

    g.compiled(jax.ShapeDtypeStruct((16,), np.float32), 2.0)
    assert g.cache_size == 1
    out = g(jnp.arange(16, dtype=jnp.float32), 2.0)  # hits the prewarmed exe
    assert g.cache_size == 1
    np.testing.assert_allclose(np.asarray(out), np.arange(16) * 2.0)


def test_public_entry_points_consume_aot():
    """VERDICT r2 #46: the public eager paths must dispatch through the AOT
    executable cache (real consumers), while traced calls inline."""
    import jax

    from raft_tpu.distance import pairwise_distance
    from raft_tpu.distance.pairwise import _distance_aot
    from raft_tpu.matrix.select_k import _select_k_aot, select_k

    rng = np.random.default_rng(0)
    x = rng.random((64, 16), dtype=np.float32)
    n0 = _distance_aot.cache_size
    d = pairwise_distance(x, x, "euclidean")
    assert _distance_aot.cache_size == n0 + 1
    pairwise_distance(x, x, "euclidean")
    assert _distance_aot.cache_size == n0 + 1  # cached executable reused

    k0 = _select_k_aot.cache_size
    select_k(np.asarray(d), 3)
    assert _select_k_aot.cache_size == k0 + 1

    # traced call inlines into the enclosing program (no new AOT entries)
    @jax.jit
    def inside(v):
        return select_k(v, 3)

    inside(jnp.asarray(np.asarray(d)))
    assert _select_k_aot.cache_size == k0 + 1


def test_prewarm_registry(tmp_path, monkeypatch):
    """prewarm() compiles the registered hot signatures into the caches."""
    import raft_tpu
    from raft_tpu.distance.pairwise import _distance_aot

    monkeypatch.setenv("RAFT_TPU_CACHE_DIR", str(tmp_path))
    n0 = _distance_aot.cache_size
    out = raft_tpu.prewarm(shapes=((96, 80, 8),),
                           metrics=("euclidean", "cityblock"),
                           select_k_shapes=((32, 64, 4),))
    assert out["n_signatures"] == 4  # 2 metrics + fused_l2_nn + select_k
    assert _distance_aot.cache_size >= n0 + 2
    # the prewarmed signature now serves real calls without compiling
    rng = np.random.default_rng(1)
    from raft_tpu.distance import pairwise_distance
    n1 = _distance_aot.cache_size
    pairwise_distance(rng.random((96, 8), dtype=np.float32),
                      rng.random((80, 8), dtype=np.float32), "euclidean")
    assert _distance_aot.cache_size == n1


def test_eager_call_off_default_device():
    """Code-review r3: AOT executables target the default device; inputs
    committed elsewhere must take the placement-specializing jit path, not
    crash with a sharding mismatch."""
    import jax

    from raft_tpu.core.aot import aot_dispatchable
    from raft_tpu.distance import pairwise_distance
    from raft_tpu.matrix.select_k import select_k

    if len(jax.devices()) < 2:
        import pytest
        pytest.skip("needs >= 2 devices")
    rng = np.random.default_rng(0)
    x = rng.random((32, 8), dtype=np.float32)
    x1 = jax.device_put(x, jax.devices()[1])
    assert not aot_dispatchable(x1)
    d = pairwise_distance(x1, x1, "euclidean")
    from scipy.spatial.distance import cdist

    np.testing.assert_allclose(np.asarray(d), cdist(x, x), atol=1e-4)
    v, i = select_k(jnp.asarray(np.asarray(d)), 3)
    v1, i1 = select_k(jax.device_put(np.asarray(d), jax.devices()[1]), 3)
    np.testing.assert_array_equal(np.asarray(i), np.asarray(i1))


def test_aot_cache_keys_distinguish_dtypes():
    """bf16 and f32 signatures must compile distinct AOT executables and
    each reuse its own (a dtype-blind key would silently serve the f32
    executable to bf16 inputs or vice versa)."""
    import jax.numpy as jnp

    from raft_tpu.distance import pairwise_distance
    from raft_tpu.distance.pairwise import _distance_aot

    rng = np.random.default_rng(1)
    x32 = rng.random((48, 8), dtype=np.float32)
    xbf = jnp.asarray(x32, jnp.bfloat16)
    n0 = _distance_aot.cache_size
    d32 = pairwise_distance(x32, x32, "euclidean")
    assert _distance_aot.cache_size == n0 + 1
    dbf = pairwise_distance(xbf, xbf, "euclidean")
    assert _distance_aot.cache_size == n0 + 2  # distinct executable
    pairwise_distance(xbf, xbf, "euclidean")
    assert _distance_aot.cache_size == n0 + 2  # ...reused
    assert d32.dtype == np.float32 and dbf.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(dbf), np.asarray(d32), atol=0.03)


def test_aot_dispatchable_fast_path_semantics():
    """PR 4's fast path (pointer-matched array type, flat-tuple walk, lazy
    default-device lookup) must preserve the gate's semantics exactly:
    True for host values and default-device arrays in any container shape,
    False for tracers and off-default placements wherever they hide."""
    import jax

    from raft_tpu.core.aot import aot_dispatchable

    x = jnp.ones((4, 3))
    assert aot_dispatchable()
    assert aot_dispatchable(x, (x, x), [x], {"a": x}, np.ones(3), 2, None)
    assert aot_dispatchable((x, {"b": (x,)}))  # nested pytree path

    @jax.jit
    def traced(v):
        assert not aot_dispatchable(v)
        assert not aot_dispatchable((v, v))     # tuple fast path
        assert not aot_dispatchable({"a": v})   # general path
        assert not aot_dispatchable(x, v)       # mixed concrete + tracer
        return v

    traced(x)

    if len(jax.devices()) >= 2:
        x1 = jax.device_put(np.ones((4, 3), np.float32), jax.devices()[1])
        assert not aot_dispatchable(x1)
        assert not aot_dispatchable((x, x1))    # tuple fast path
        assert not aot_dispatchable({"a": x1})  # general path
