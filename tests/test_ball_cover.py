"""Random ball cover: exactness tests vs brute force (the reference checks
ball cover against brute-force ground truth, test/neighbors/ball_cover.cu)."""

import numpy as np
import pytest
from scipy.spatial.distance import cdist

from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.neighbors.ball_cover import (
    all_knn_query,
    build_index,
    eps_nn,
    knn_query,
)


@pytest.mark.parametrize("n,dim,k", [(1500, 3, 7), (2000, 8, 11)])
def test_ball_cover_knn_exact(n, dim, k):
    rng = np.random.default_rng(n)
    x = rng.random((n, dim)).astype(np.float32)
    q = rng.random((100, dim)).astype(np.float32)
    index = build_index(x)
    d, i = knn_query(index, q, k)
    ref = cdist(q.astype(np.float64), x.astype(np.float64))
    ridx = np.argsort(ref, axis=1, kind="stable")[:, :k]
    rd = np.take_along_axis(ref, ridx, axis=1)
    np.testing.assert_allclose(np.sort(np.array(d), 1), rd, atol=1e-3)
    # exactness: distance multisets agree ⇒ same neighbor sets up to ties
    hits = sum(len(set(a.tolist()) & set(b.tolist()))
               for a, b in zip(np.array(i), ridx))
    assert hits / ridx.size > 0.999


def test_ball_cover_all_knn():
    rng = np.random.default_rng(0)
    x = rng.random((900, 4)).astype(np.float32)
    index = build_index(x)
    d, i = all_knn_query(index, 5)
    # each point's own nearest neighbor is itself at distance 0
    np.testing.assert_array_equal(np.array(i)[:, 0], np.arange(900))
    np.testing.assert_allclose(np.array(d)[:, 0], 0.0, atol=1e-4)


def test_ball_cover_haversine():
    rng = np.random.default_rng(1)
    lat = rng.uniform(-1.2, 1.2, 800)
    lon = rng.uniform(-3.0, 3.0, 800)
    x = np.stack([lat, lon], 1).astype(np.float32)
    q = x[:50] + 0.001
    index = build_index(x, DistanceType.Haversine)
    d, i = knn_query(index, q, 3)
    assert np.array_equal(np.array(i)[:, 0], np.arange(50))


def test_ball_cover_eps_nn():
    rng = np.random.default_rng(2)
    x = rng.random((600, 4)).astype(np.float32)
    q = rng.random((80, 4)).astype(np.float32)
    eps = 0.35
    index = build_index(x)
    adj, vd = eps_nn(index, q, eps)
    ref = cdist(q, x) <= eps
    np.testing.assert_array_equal(np.array(adj), ref)
    np.testing.assert_array_equal(np.array(vd), ref.sum(1))
