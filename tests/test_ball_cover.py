"""Random ball cover: exactness tests vs brute force (the reference checks
ball cover against brute-force ground truth, test/neighbors/ball_cover.cu)."""

import numpy as np
import pytest
from scipy.spatial.distance import cdist

from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.neighbors.ball_cover import (
    all_knn_query,
    build_index,
    eps_nn,
    knn_query,
)


@pytest.mark.parametrize("n,dim,k", [
    (1500, 3, 7),
    pytest.param(2000, 8, 11, marks=pytest.mark.slow),  # budget
])
def test_ball_cover_knn_exact(n, dim, k):
    rng = np.random.default_rng(n)
    x = rng.random((n, dim)).astype(np.float32)
    q = rng.random((100, dim)).astype(np.float32)
    index = build_index(x)
    d, i = knn_query(index, q, k)
    ref = cdist(q.astype(np.float64), x.astype(np.float64))
    ridx = np.argsort(ref, axis=1, kind="stable")[:, :k]
    rd = np.take_along_axis(ref, ridx, axis=1)
    np.testing.assert_allclose(np.sort(np.array(d), 1), rd, atol=1e-3)
    # exactness: distance multisets agree ⇒ same neighbor sets up to ties
    hits = sum(len(set(a.tolist()) & set(b.tolist()))
               for a, b in zip(np.array(i), ridx))
    assert hits / ridx.size > 0.999


def test_ball_cover_all_knn():
    rng = np.random.default_rng(0)
    x = rng.random((900, 4)).astype(np.float32)
    index = build_index(x)
    d, i = all_knn_query(index, 5)
    # each point's own nearest neighbor is itself at distance 0
    np.testing.assert_array_equal(np.array(i)[:, 0], np.arange(900))
    np.testing.assert_allclose(np.array(d)[:, 0], 0.0, atol=1e-4)


def test_ball_cover_haversine():
    rng = np.random.default_rng(1)
    lat = rng.uniform(-1.2, 1.2, 800)
    lon = rng.uniform(-3.0, 3.0, 800)
    x = np.stack([lat, lon], 1).astype(np.float32)
    q = x[:50] + 0.001
    index = build_index(x, DistanceType.Haversine)
    d, i = knn_query(index, q, 3)
    assert np.array_equal(np.array(i)[:, 0], np.arange(50))


def test_ball_cover_eps_nn():
    rng = np.random.default_rng(2)
    x = rng.random((600, 4)).astype(np.float32)
    q = rng.random((80, 4)).astype(np.float32)
    eps = 0.35
    index = build_index(x)
    adj, vd = eps_nn(index, q, eps)
    ref = cdist(q, x) <= eps
    np.testing.assert_array_equal(np.array(adj), ref)
    np.testing.assert_array_equal(np.array(vd), ref.sum(1))


# ---------------------------------------------------------------------------
# Certificate-path property tests (VERDICT r3 #8; sized against the
# reference's grid in cpp/test/neighbors/ball_cover.cu — uniform + clustered
# inputs, multiple dims/ks, haversine, all checked against brute force).


def _brute_knn(x, q, k):
    ref = cdist(q.astype(np.float64), x.astype(np.float64))
    ridx = np.argsort(ref, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(ref, ridx, axis=1), ridx


def _recall_vs(i, ridx):
    return sum(len(set(a.tolist()) & set(b.tolist()))
               for a, b in zip(np.asarray(i), ridx)) / ridx.size


@pytest.mark.slow  # forced low-budget probe-doubling stress (tier-1 budget, PR 4)
def test_ball_cover_forced_probe_doubling(monkeypatch):
    """initial_probes=1 starts below any reasonable coverage, so the
    exactness certificate MUST fail on the first pass and the host loop
    must double P (possibly to n_landmarks) before returning — and the
    result must still be exact.  Counts passes to prove the retry path
    actually executed (the static-shape stand-in for the reference's
    dynamic per-query pruning, detail/ball_cover.cuh:122)."""
    from raft_tpu.neighbors import ball_cover

    rng = np.random.default_rng(7)
    # two distant shells: a query near shell A has its kNN in A, but with
    # 1 probe the certificate can't clear shell B's landmarks
    a = rng.normal(0, 1, (800, 6)).astype(np.float32)
    b = rng.normal(8, 1, (800, 6)).astype(np.float32)
    x = np.concatenate([a, b])
    q = rng.normal(0, 1, (64, 6)).astype(np.float32)

    calls = []
    orig = ball_cover._probe_pass

    def counting(leaves, qb, k, p, metric):
        calls.append(p)
        return orig(leaves, qb, k, p, metric)

    monkeypatch.setattr(ball_cover, "_probe_pass", counting)
    index = build_index(x, seed=3)
    d, i = knn_query(index, q, 9, initial_probes=1)
    assert len(calls) >= 2 and calls[0] == 1 and calls[1] == 2, calls
    rd, ridx = _brute_knn(x, q, 9)
    np.testing.assert_allclose(np.sort(np.array(d), 1), rd, atol=1e-3)
    assert _recall_vs(i, ridx) > 0.999


def test_ball_cover_adversarial_landmark_skew():
    """99% of points in one tight blob (its landmark list is huge, radius
    tiny) + a sprinkling of far outliers (landmarks with 1-2 members and
    zero radius).  Exactness must survive the skew — the failure mode
    would be pruning an outlier list whose lower bound d(q,L)-r is
    misleadingly large."""
    rng = np.random.default_rng(11)
    blob = rng.normal(0, 0.05, (1980, 5)).astype(np.float32)
    outliers = rng.uniform(-20, 20, (20, 5)).astype(np.float32)
    x = np.concatenate([blob, outliers])
    # queries: half near the blob, half near outliers (their true kNN mixes
    # blob and outlier points at very different scales)
    q = np.concatenate([rng.normal(0, 0.05, (40, 5)),
                        outliers[:10] + 0.01]).astype(np.float32)
    index = build_index(x, seed=5)
    d, i = knn_query(index, q, 12)
    rd, ridx = _brute_knn(x, q, 12)
    # atol 5e-3: outlier coordinates ~20 put squared norms ~2000 through
    # the expanded-L2 cancellation in f32 (measured 3.2e-3 worst abs err
    # on a 0.022 distance) — the RANKING stays exact, which is the
    # property under test (recall gate below is strict).
    np.testing.assert_allclose(np.sort(np.array(d), 1), rd, atol=5e-3)
    assert _recall_vs(i, ridx) == 1.0


def test_ball_cover_duplicates_and_large_k():
    """Exact duplicates (distance ties) and k comparable to n/landmark-list
    sizes — the reference grid runs k up to 128 on small inputs."""
    rng = np.random.default_rng(13)
    base = rng.random((300, 4)).astype(np.float32)
    x = np.concatenate([base, base[:100]])       # 100 exact duplicates
    q = base[:60] + 1e-4
    index = build_index(x, seed=1)
    k = 96
    d, i = knn_query(index, q, k)
    rd, _ = _brute_knn(x, q, k)
    # distance multisets must agree even with ties (ids may permute)
    np.testing.assert_allclose(np.sort(np.array(d), 1), rd, atol=1e-3)


@pytest.mark.parametrize("n,k", [
    (700, 5),
    pytest.param(1200, 17, marks=pytest.mark.slow),  # budget
])
def test_ball_cover_haversine_vs_host_oracle(n, k):
    """Haversine kNN against a full numpy great-circle oracle (the
    reference has a dedicated haversine ball-cover test family,
    ball_cover.cu BallCoverHaversine) — not just self-query."""
    rng = np.random.default_rng(n)
    lat = rng.uniform(-1.4, 1.4, n)
    lon = rng.uniform(-np.pi, np.pi, n)
    x = np.stack([lat, lon], 1).astype(np.float32)
    qlat = rng.uniform(-1.4, 1.4, 80)
    qlon = rng.uniform(-np.pi, np.pi, 80)
    q = np.stack([qlat, qlon], 1).astype(np.float32)

    def hav(qq, xx):
        dlat = qq[:, None, 0] - xx[None, :, 0]
        dlon = qq[:, None, 1] - xx[None, :, 1]
        h = (np.sin(dlat / 2) ** 2 + np.cos(qq[:, None, 0])
             * np.cos(xx[None, :, 0]) * np.sin(dlon / 2) ** 2)
        return 2.0 * np.arcsin(np.sqrt(np.clip(h, 0, 1)))

    ref = hav(q.astype(np.float64), x.astype(np.float64))
    ridx = np.argsort(ref, axis=1, kind="stable")[:, :k]
    rd = np.take_along_axis(ref, ridx, axis=1)
    index = build_index(x, DistanceType.Haversine)
    d, i = knn_query(index, q, k)
    np.testing.assert_allclose(np.sort(np.array(d), 1), rd, atol=1e-3)
    assert _recall_vs(i, ridx) > 0.995


def test_ball_cover_all_knn_matches_bruteforce():
    """all_knn_query against the brute-force oracle on the full matrix (the
    existing test only checked the self-neighbor column)."""
    rng = np.random.default_rng(17)
    x = rng.random((800, 6)).astype(np.float32)
    index = build_index(x)
    k = 8
    d, i = all_knn_query(index, k)
    rd, ridx = _brute_knn(x, x, k)
    np.testing.assert_allclose(np.sort(np.array(d), 1), rd, atol=1e-3)
    assert _recall_vs(i, ridx) > 0.999


def test_ball_cover_eps_nn_clustered_pruning_scales():
    """eps_nn on strongly clustered data at eps below/above the cluster
    gap: adjacency must match the dense oracle in both regimes (the
    reference eps_nn tests sweep eps the same way, ball_cover.cu
    BallCoverEpsNN)."""
    rng = np.random.default_rng(19)
    c1 = rng.normal(0, 0.1, (400, 3)).astype(np.float32)
    c2 = rng.normal(3, 0.1, (400, 3)).astype(np.float32)
    x = np.concatenate([c1, c2])
    q = np.concatenate([c1[:30], c2[:30]])
    index = build_index(x)
    for eps in (0.3, 4.0):
        adj, deg = eps_nn(index, q, eps)
        ref = cdist(q.astype(np.float64), x.astype(np.float64)) <= eps
        np.testing.assert_array_equal(np.array(adj), ref)
        np.testing.assert_array_equal(np.array(deg), ref.sum(1))


def test_ball_cover_k_exceeding_smallest_list():
    """k larger than many landmark lists forces multi-list merges for
    every query; results must stay exact."""
    rng = np.random.default_rng(23)
    x = rng.random((500, 3)).astype(np.float32)
    q = rng.random((40, 3)).astype(np.float32)
    index = build_index(x, n_landmarks=100, seed=2)   # ~5 pts per list
    d, i = knn_query(index, q, 50)
    rd, ridx = _brute_knn(x, q, 50)
    np.testing.assert_allclose(np.sort(np.array(d), 1), rd, atol=1e-3)
    assert _recall_vs(i, ridx) > 0.999


def test_ball_cover_query_validation():
    rng = np.random.default_rng(29)
    x = rng.random((100, 4)).astype(np.float32)
    index = build_index(x)
    from raft_tpu.core import LogicError

    with pytest.raises(LogicError):
        knn_query(index, rng.random((5, 3)).astype(np.float32), 3)
    with pytest.raises(LogicError):
        build_index(x, DistanceType.InnerProduct)
    with pytest.raises(LogicError):
        build_index(x, DistanceType.Haversine)  # needs dim == 2
    d, i = knn_query(index, np.zeros((0, 4), np.float32), 3)
    assert d.shape == (0, 3) and i.shape == (0, 3)
