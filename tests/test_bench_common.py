"""Trace-driven load-generator DSL (ISSUE 18; bench/common.py): the named
HEAVY_TAIL_PLAN must replay the pre-DSL hardcoded request stream bit for
bit (every serve gate in bench.py was tuned on that traffic), plan
parsing must fail loudly, and the RNG-draw discipline (one random + one
integers + one payload draw per request, modifiers draw nothing) must
keep shared-prefix plans replay-compatible."""

import math

import numpy as np
import pytest

from bench.common import (
    BURST_PLAN,
    DIURNAL_PLAN,
    HEAVY_TAIL_PLAN,
    parse_traffic_plan,
    serve_request_stream,
    traffic_requests,
)


def _pre_dsl_stream(seed, n_requests, dim, dtype="float32"):
    """The hardcoded generator serve_request_stream shipped before the
    plan DSL — the replay-compatibility oracle, verbatim."""
    rng = np.random.default_rng(seed)
    reqs = []
    for _ in range(n_requests):
        u = rng.random()
        if u < 0.85:
            s = int(rng.integers(1, 17))
        elif u < 0.95:
            s = int(rng.integers(17, 129))
        else:
            s = int(rng.integers(129, 701))
        reqs.append(rng.random((s, dim)).astype(dtype))
    return reqs


class TestReplayCompatibility:
    @pytest.mark.parametrize("seed", [0, 1, 3])
    def test_heavy_tail_plan_is_bit_identical_to_pre_dsl(self, seed):
        new = serve_request_stream(seed=seed, n_requests=120, dim=16)
        old = _pre_dsl_stream(seed=seed, n_requests=120, dim=16)
        assert len(new) == len(old)
        for a, b in zip(new, old):
            np.testing.assert_array_equal(a, b)

    def test_shared_prefix_plans_replay_identically(self):
        # modifiers consume no EXTRA RNG draws, so BURST replays the plain
        # plan's traffic bit for bit up to the squall at request 100 (a
        # size change alters how many payload values the stream consumes,
        # so requests past the first modified one legitimately diverge)
        base = traffic_requests(HEAVY_TAIL_PLAN, 5, 120, 8)
        burst = traffic_requests(BURST_PLAN, 5, 120, 8)
        for a, b in zip(base[:100], burst[:100]):
            np.testing.assert_array_equal(a, b)
        assert any(r.shape[0] != s.shape[0]
                   for r, s in zip(base[100:116], burst[100:116]))

    def test_diurnal_envelope_is_index_deterministic(self):
        # a fixed-size band isolates the envelope: request j's size is
        # pure arithmetic on j, no extra draws
        day = traffic_requests(
            "band:p=1.0:lo=100:hi=101;diurnal:period=64:floor=0.25",
            2, 64, 4)
        for j, b in enumerate(day):
            scale = 0.25 + 0.75 * 0.5 * (1.0 + math.sin(2 * math.pi
                                                        * j / 64.0))
            assert b.shape[0] == max(1, int(round(100 * scale)))


class TestPlanParsing:
    def test_named_plans_parse(self):
        for plan in (HEAVY_TAIL_PLAN, DIURNAL_PLAN, BURST_PLAN):
            bands, mods = parse_traffic_plan(plan)
            assert bands

    def test_unknown_directive_fails_loudly(self):
        with pytest.raises(ValueError, match="directive"):
            parse_traffic_plan("band:p=1.0:lo=1:hi=2;lunar:phase=3")

    def test_malformed_field_fails_loudly(self):
        with pytest.raises(ValueError, match="k=v"):
            parse_traffic_plan("band:p=1.0:lo")

    def test_band_required(self):
        with pytest.raises(ValueError, match="band"):
            parse_traffic_plan("diurnal:period=64:floor=0.25")

    def test_burst_overrides_band_sizes(self):
        reqs = traffic_requests(BURST_PLAN, 9, 120, 4)
        assert all(r.shape[0] >= 129 for r in reqs[100:116])
