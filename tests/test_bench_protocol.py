"""Measurement-protocol regression tests (bench.common + bench.tpu_session
resume machinery) — the validity rules the perf evidence rests on:
roofline guarding, amortized timing, append-only JSONL, stage/metric
resume semantics.  These pin behaviors that were previously only proven
by inline rehearsals before each tunnel window."""

from bench.common import (apply_roofline_guard, jsonl_rows, make_emitter,
                          timed_amortized)


class TestRooflineGuard:
    def test_flags_impossible_reading(self):
        row = apply_roofline_guard({"value": 1000.0}, 1000.0, roofline=819.0)
        assert row["suspect"] is True and row["roofline_gbps"] == 819.0

    def test_passes_physical_reading(self):
        row = apply_roofline_guard({"value": 500.0}, 500.0, roofline=819.0)
        assert "suspect" not in row

    def test_unknown_roofline_never_flags(self):
        row = apply_roofline_guard({"value": 9e9}, 9e9, roofline=None)
        assert "suspect" not in row


class TestTimedAmortized:
    def test_per_iter_positive_and_chained(self):
        import jax.numpy as jnp

        calls = []

        def step(c):
            calls.append(1)
            return c * 1.0000001 + 1.0

        per_iter, info = timed_amortized(step, jnp.zeros(()), k_lo=2,
                                         k_hi=6, reps=2)
        assert per_iter > 0
        assert info["k_lo"] == 2 and info["k_hi"] == 6
        # step traces once per loop length (fori_loop body), not per trip
        assert len(calls) == 2

    def test_noise_floor_returns_conservative_bound(self):
        """If t_hi <= t_lo (measurement noise), the conservative t_hi/k_hi
        bound is returned and flagged delta_ok=False — never a negative
        or zero delta."""
        import jax.numpy as jnp

        per_iter, info = timed_amortized(lambda c: c + 1.0, jnp.zeros(()),
                                         k_lo=2, k_hi=4, reps=1)
        assert per_iter > 0
        assert isinstance(info["delta_ok"], bool)


class TestEmitterAndRows:
    def test_append_and_skip_bad_lines(self, tmp_path):
        p = str(tmp_path / "out.jsonl")
        emit = make_emitter(p)
        emit({"a": 1})
        with open(p, "a") as f:
            f.write("{not json\n")  # torn write mid-crash
        emit({"b": 2})
        rows = list(jsonl_rows(p))
        assert rows == [{"a": 1}, {"b": 2}]

    def test_missing_file_yields_nothing(self, tmp_path):
        assert list(jsonl_rows(str(tmp_path / "absent.jsonl"))) == []


class TestSessionResume:
    """Stage/metric resume semantics (bench.tpu_session) — a window that
    closes mid-session must resume where it left off, a completed session
    must reset, and pre-amortized-protocol rows must not satisfy resume."""

    def _session(self, tmp_path, rows):
        import bench.tpu_session as s

        path = str(tmp_path / "resume.jsonl")
        old_out, s.OUT = s.OUT, path
        emit = make_emitter(path)
        for r in rows:
            emit(r)
        return s, old_out

    def test_stage_markers_and_reset(self, tmp_path):
        s, old = self._session(tmp_path, [
            {"stage": "session", "schema": 3},
            {"stage": "stage_done", "name": "pairwise"},
            {"stage": "stage_done", "name": "rtt"},
        ])
        try:
            assert s._completed_stages() == {"pairwise", "rtt"}
            make_emitter(s.OUT)({"stage": "session", "done": True})
            assert s._completed_stages() == set()
            # done: False must NOT reset
            emit = make_emitter(s.OUT)
            emit({"stage": "stage_done", "name": "lanczos"})
            emit({"stage": "session", "done": False})
            assert s._completed_stages() == {"lanczos"}
        finally:
            s.OUT = old

    def test_headline_metric_resume_schema_gated(self, tmp_path):
        s, old = self._session(tmp_path, [
            {"stage": "session", "schema": 2},
            {"stage": "headline",
             "metric": "kmeans_mnmg_iter_100kx128_k1024_f32_1dev",
             "value": 3.03},
            {"stage": "session", "schema": 3},
            {"stage": "headline",
             "metric": "pairwise_distance_l2sqrt_5000x50_f32",
             "value": 400.0},
            {"stage": "headline", "error": "timeout", "metric": "lanczos"},
            {"stage": "headline",
             "metric": "ivf_pq_qps_200kx128_recall0.96", "value": 9000.0},
        ])
        try:
            # schema-2 row (pre-amortized protocols) does not count;
            # error rows do not count
            assert s._completed_headline_metrics() == {"pairwise", "ivf_pq"}
            make_emitter(s.OUT)({"stage": "session", "done": True})
            assert s._completed_headline_metrics() == set()
        finally:
            s.OUT = old

    def test_pallas_flags_restored_from_rows(self, tmp_path):
        s, old = self._session(tmp_path, [
            {"stage": "pallas_probe", "case": "trivial_add", "ok": True},
            {"stage": "pallas_probe", "case": "fused_l2nn_small",
             "ok": False, "error": "HTTP 500"},
        ])
        try:
            s._PALLAS_OK = s._PALLAS_FUSED_OK = None
            s._restore_pallas_flags()
            assert s._PALLAS_OK is True and s._PALLAS_FUSED_OK is False
        finally:
            s.OUT = old
            s._PALLAS_OK = s._PALLAS_FUSED_OK = None

    def test_dryrun_ignores_resume_state(self, tmp_path, monkeypatch):
        s, old = self._session(tmp_path, [
            {"stage": "stage_done", "name": "pairwise"},
        ])
        try:
            monkeypatch.setattr(s, "DRYRUN", True)
            assert s._completed_stages() == set()
        finally:
            s.OUT = old
