"""Measurement-protocol regression tests (bench.common + bench.tpu_session
resume machinery) — the validity rules the perf evidence rests on:
roofline guarding, amortized timing, append-only JSONL, stage/metric
resume semantics.  These pin behaviors that were previously only proven
by inline rehearsals before each tunnel window."""

from bench.common import (apply_roofline_guard, jsonl_rows, make_emitter,
                          timed_amortized)


class TestRooflineGuard:
    def test_flags_impossible_reading(self):
        row = apply_roofline_guard({"value": 1000.0}, 1000.0, roofline=819.0)
        assert row["suspect"] is True and row["roofline_gbps"] == 819.0

    def test_passes_physical_reading(self):
        row = apply_roofline_guard({"value": 500.0}, 500.0, roofline=819.0)
        assert "suspect" not in row

    def test_unknown_roofline_never_flags(self):
        row = apply_roofline_guard({"value": 9e9}, 9e9, roofline=None)
        assert "suspect" not in row


class TestTimedAmortized:
    def test_per_iter_positive_and_chained(self):
        import jax.numpy as jnp

        calls = []

        def step(c):
            calls.append(1)
            return c * 1.0000001 + 1.0

        per_iter, info = timed_amortized(step, jnp.zeros(()), k_lo=2,
                                         k_hi=6, reps=2)
        assert per_iter > 0
        assert info["k_lo"] == 2 and info["k_hi"] == 6
        # step traces once per loop length (fori_loop body), not per trip
        assert len(calls) == 2

    def test_noise_floor_returns_conservative_bound(self):
        """If t_hi <= t_lo (measurement noise), the conservative t_hi/k_hi
        bound is returned and flagged delta_ok=False — never a negative
        or zero delta."""
        import jax.numpy as jnp

        per_iter, info = timed_amortized(lambda c: c + 1.0, jnp.zeros(()),
                                         k_lo=2, k_hi=4, reps=1)
        assert per_iter > 0
        assert isinstance(info["delta_ok"], bool)


class TestEmitterAndRows:
    def test_append_and_skip_bad_lines(self, tmp_path):
        p = str(tmp_path / "out.jsonl")
        emit = make_emitter(p)
        emit({"a": 1})
        with open(p, "a") as f:
            f.write("{not json\n")  # torn write mid-crash
        emit({"b": 2})
        rows = list(jsonl_rows(p))
        assert rows == [{"a": 1}, {"b": 2}]

    def test_missing_file_yields_nothing(self, tmp_path):
        assert list(jsonl_rows(str(tmp_path / "absent.jsonl"))) == []


class TestSessionResume:
    """Stage/metric resume semantics (bench.tpu_session) — a window that
    closes mid-session must resume where it left off, a completed session
    must reset, and pre-amortized-protocol rows must not satisfy resume."""

    def _session(self, tmp_path, rows):
        import bench.tpu_session as s

        path = str(tmp_path / "resume.jsonl")
        old_out, s.OUT = s.OUT, path
        emit = make_emitter(path)
        for r in rows:
            emit(r)
        return s, old_out

    def test_failed_cases_blocks_partial_stage(self, tmp_path):
        """ADVICE r5 stage gate: a stage with one decisive failed CASE
        and one auxiliary success must not read as complete — any case
        whose every row errored blocks the stage_done marker; a case that
        errored then succeeded (retry) does not."""
        import bench.tpu_session as s

        rows = [
            {"stage": "mnmg_diag", "case": "B_jit_one_step", "iter_s": 5.0},
            {"stage": "mnmg_diag", "case": "E_full_fit", "error": "boom"},
        ]
        assert s._failed_cases(rows) == [str((("case", "E_full_fit"),))]
        # retried-and-succeeded case: not failed
        rows.append({"stage": "mnmg_diag", "case": "E_full_fit",
                     "iter_s": 3.0})
        assert s._failed_cases(rows) == []
        # all-errors single-row stage (the r4 gate) is subsumed
        assert s._failed_cases([{"stage": "lanczos", "error": "x"}]) \
            == [str(())]

    def test_stage_markers_and_reset(self, tmp_path):
        s, old = self._session(tmp_path, [
            {"stage": "session", "schema": 3},
            {"stage": "stage_done", "name": "pairwise"},
            {"stage": "stage_done", "name": "rtt"},
        ])
        try:
            assert s._completed_stages() == {"pairwise", "rtt"}
            make_emitter(s.OUT)({"stage": "session", "done": True})
            assert s._completed_stages() == set()
            # done: False must NOT reset
            emit = make_emitter(s.OUT)
            emit({"stage": "stage_done", "name": "lanczos"})
            emit({"stage": "session", "done": False})
            assert s._completed_stages() == {"lanczos"}
        finally:
            s.OUT = old

    def test_headline_metric_resume_schema_gated(self, tmp_path):
        s, old = self._session(tmp_path, [
            {"stage": "session", "schema": 2},
            {"stage": "headline",
             "metric": "kmeans_mnmg_iter_100kx128_k1024_f32_1dev",
             "value": 3.03},
            {"stage": "session", "schema": 3},
            {"stage": "headline",
             "metric": "pairwise_distance_l2sqrt_5000x50_f32",
             "value": 400.0},
            {"stage": "headline", "error": "timeout", "metric": "lanczos"},
            {"stage": "headline",
             "metric": "ivf_pq_qps_200kx128_recall0.96", "value": 9000.0},
        ])
        try:
            # schema-2 row (pre-amortized protocols) does not count;
            # error rows do not count
            assert s._completed_headline_metrics() == {"pairwise", "ivf_pq"}
            make_emitter(s.OUT)({"stage": "session", "done": True})
            assert s._completed_headline_metrics() == set()
        finally:
            s.OUT = old

    def test_pallas_flags_restored_from_rows(self, tmp_path):
        s, old = self._session(tmp_path, [
            {"stage": "pallas_probe", "case": "trivial_add", "ok": True},
            {"stage": "pallas_probe", "case": "fused_l2nn_small",
             "ok": False, "error": "HTTP 500"},
        ])
        try:
            s._PALLAS_OK = s._PALLAS_FUSED_OK = None
            s._restore_pallas_flags()
            assert s._PALLAS_OK is True and s._PALLAS_FUSED_OK is False
        finally:
            s.OUT = old
            s._PALLAS_OK = s._PALLAS_FUSED_OK = None

    def test_dryrun_ignores_resume_state(self, tmp_path, monkeypatch):
        s, old = self._session(tmp_path, [
            {"stage": "stage_done", "name": "pairwise"},
        ])
        try:
            monkeypatch.setattr(s, "DRYRUN", True)
            assert s._completed_stages() == set()
        finally:
            s.OUT = old


class TestEmitterErrorAccounting:
    """r5: the session main loop snapshots emit.rows/emit.errors around
    each inline stage and refuses to mark a stage done when every row it
    emitted was an error row (the per-config handlers swallow failures)."""

    def test_counters_track_rows_and_errors(self, tmp_path):
        from bench.common import make_emitter

        emit = make_emitter(str(tmp_path / "out.jsonl"))
        assert emit.rows == 0 and emit.errors == 0
        emit({"stage": "x", "value": 1})
        emit({"stage": "x", "error": "boom"})
        emit({"stage": "y", "error": "boom2"})
        assert emit.rows == 3
        assert emit.errors == 2

    def test_all_errors_detection_window(self, tmp_path):
        """The exact predicate the main loop applies: rows>0 and
        errors==rows within the stage's snapshot window."""
        from bench.common import make_emitter

        emit = make_emitter(str(tmp_path / "out.jsonl"))
        emit({"stage": "warmup", "value": 0})          # before the stage
        r0, e0 = emit.rows, emit.errors
        emit({"stage": "s", "error": "a"})
        emit({"stage": "s", "error": "b"})
        rows, errs = emit.rows - r0, emit.errors - e0
        assert rows == 2 and errs == rows              # -> stage NOT done
        r0, e0 = emit.rows, emit.errors
        emit({"stage": "t", "error": "a"})
        emit({"stage": "t", "ok": 1})
        rows, errs = emit.rows - r0, emit.errors - e0
        assert errs < rows                             # -> stage done


class TestRecordedRowsStayFlagged:
    """Repo-state regression for VERDICT r4 #4: every measurement-bearing
    row in the committed session results that the dispatch-RTT analysis
    invalidated must stay inline-flagged — a consumer reading rows without
    the schema-history comment must never see a clean invalid number."""

    def test_no_clean_rtt_bound_rows(self):
        import os

        from bench.common import jsonl_rows

        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tpu_session_results.jsonl")
        rows = list(jsonl_rows(path))
        assert rows, "committed session results missing"
        schema = 0
        for row in rows:
            if row.get("stage") == "session" and row.get("schema"):
                schema = row["schema"]
            # schema-2 era: any sub-10 ms per-dispatch measurement row is
            # RTT-bound (see bench/tpu_session.py schema history)
            if schema == 2 and row.get("stage") == "kmeans_sweep" \
                    and "iter_s" in row:
                assert row.get("suspect") is True, row
            if schema == 2 and row.get("stage") == "pairwise" \
                    and "value" in row:
                assert row.get("suspect") is True, row

    def test_wait_script_parses_done_row(self):
        """The waiter's completion check must be key-order/extra-field
        insensitive (r4 advisor finding: the old literal grep broke if any
        field preceded "done")."""
        import os
        import subprocess
        import sys

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = open(os.path.join(root, "bench",
                                   "tpu_wait_and_measure.sh")).read()
        # extract the embedded python parser between the quotes
        start = script.index("python -c '") + len("python -c '")
        end = script.index("'", start)
        parser = script[start:end]
        for line, ok in [
            ('{"stage": "session", "note": "x", "done": true}\n', True),
            ('{"done": true, "stage": "session"}\n', True),
            ('{"stage": "session", "done": false}\n', False),
            ('{"stage": "stage_done", "done": true}\n', False),
            ('not json\n{"stage": "session", "done": true}\n', True),
        ]:
            rc = subprocess.run([sys.executable, "-c", parser],
                                input=line, text=True).returncode
            assert (rc == 0) == ok, (line, rc)
