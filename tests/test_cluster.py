"""Cluster tests — counterpart of reference cpp/test/cluster/*: k-means is
validated by ARI == 1.0 against make_blobs ground truth
(reference test/cluster/kmeans.cu:362-369), linkage vs scipy."""

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu import cluster
from raft_tpu.cluster import InitMethod, KMeansParams
from raft_tpu.distance import DistanceType
from raft_tpu.random import RngState, make_blobs
from raft_tpu.stats import adjusted_rand_index


@pytest.fixture
def blobs():
    x, labels, centers = make_blobs(RngState(42), 1000, 16, n_clusters=5,
                                    cluster_std=0.4)
    return np.asarray(x), np.asarray(labels), np.asarray(centers)


class TestBuildingBlocks:
    def test_min_cluster_and_distance(self, blobs):
        x, labels, centers = blobs
        nn = cluster.min_cluster_and_distance(jnp.asarray(x), jnp.asarray(centers))
        import scipy.spatial.distance as sd

        d = sd.cdist(x, centers, "sqeuclidean")
        np.testing.assert_array_equal(np.asarray(nn.key), d.argmin(axis=1))
        np.testing.assert_allclose(np.asarray(nn.value), d.min(axis=1), rtol=1e-4,
                                   atol=1e-4)

    def test_update_centroids(self, blobs):
        x, labels, centers = blobs
        new, wsum = cluster.update_centroids(x, labels, 5)
        for k in range(5):
            np.testing.assert_allclose(np.asarray(new)[k], x[labels == k].mean(axis=0),
                                       rtol=1e-5)
        np.testing.assert_allclose(np.asarray(wsum), np.bincount(labels, minlength=5))

    def test_update_centroids_empty_cluster(self):
        x = np.random.default_rng(0).random((10, 3)).astype(np.float32)
        labels = np.zeros(10, np.int32)  # everything in cluster 0
        old = np.ones((3, 3), np.float32) * 7
        new, wsum = cluster.update_centroids(x, labels, 3, old_centroids=old)
        np.testing.assert_allclose(np.asarray(new)[1:], old[1:])  # kept
        np.testing.assert_allclose(np.asarray(new)[0], x.mean(axis=0), rtol=1e-5)

    def test_cluster_cost(self, blobs):
        x, _, centers = blobs
        nn = cluster.min_cluster_and_distance(jnp.asarray(x), jnp.asarray(centers))
        assert float(cluster.cluster_cost(nn)) > 0
        w = np.zeros(len(x), np.float32)
        assert float(cluster.cluster_cost(nn, w)) == 0.0


class TestKMeansFit:
    def test_fit_blobs_ari(self, blobs):
        x, true_labels, _ = blobs
        params = KMeansParams(n_clusters=5, init=InitMethod.KMeansPlusPlus,
                              seed=3, max_iter=100)
        out = cluster.fit_predict(params, x)
        ari = float(adjusted_rand_index(np.asarray(out.labels), true_labels))
        # reference gate: ARI == 1.0 on well-separated blobs (kmeans.cu:362)
        assert ari > 0.99, f"ARI {ari}"
        assert int(out.n_iter) <= 100

    def test_fit_random_init_best_of_n(self, blobs):
        x, true_labels, _ = blobs
        # Random init lands in local optima on well-separated blobs; n_init
        # best-of must pick the lowest-inertia run (reference n_init knob).
        p1 = KMeansParams(n_clusters=5, init=InitMethod.Random, seed=3, n_init=1)
        p5 = KMeansParams(n_clusters=5, init=InitMethod.Random, seed=3, n_init=5)
        out1 = cluster.fit(p1, x)
        out5 = cluster.fit(p5, x)
        assert float(out5.inertia) <= float(out1.inertia) + 1e-3
        assert int(out5.n_iter) < 100  # converged, didn't hit max_iter

    def test_fit_init_array(self, blobs):
        x, true_labels, centers = blobs
        params = KMeansParams(n_clusters=5, init=InitMethod.Array)
        out = cluster.fit_predict(params, x, centroids=centers)
        ari = float(adjusted_rand_index(np.asarray(out.labels), true_labels))
        assert ari > 0.99

    def test_sample_weights(self, blobs):
        x, _, _ = blobs
        w = np.ones(len(x), np.float32)
        params = KMeansParams(n_clusters=5, seed=1)
        out_w = cluster.fit(params, x, sample_weights=w)
        out = cluster.fit(params, x)
        np.testing.assert_allclose(np.asarray(out_w.centroids),
                                   np.asarray(out.centroids), rtol=1e-4, atol=1e-5)

    def test_transform(self, blobs):
        x, _, centers = blobs
        params = KMeansParams(n_clusters=5)
        t = cluster.transform(params, x, centers)
        assert t.shape == (len(x), 5)

    def test_predict_consistency(self, blobs):
        x, _, _ = blobs
        params = KMeansParams(n_clusters=5, seed=2)
        out = cluster.fit(params, x)
        labels, inertia = cluster.predict(params, x, out.centroids)
        np.testing.assert_allclose(float(inertia), float(out.inertia), rtol=1e-3)

    def test_estimator_wrapper(self, blobs):
        x, true_labels, _ = blobs
        km = cluster.KMeans(n_clusters=5, seed=5).fit(x)
        assert km.inertia_ > 0
        ari = float(adjusted_rand_index(np.asarray(km.labels_), true_labels))
        assert ari > 0.99
        assert km.predict(x).shape == (len(x),)


class TestBalanced:
    def test_build_clusters_balance(self):
        x, _, _ = make_blobs(RngState(7), 2000, 8, n_clusters=10, cluster_std=1.0)
        centers = cluster.build_clusters(RngState(0), x, 16, n_iters=10)
        assert centers.shape == (16, 8)
        nn = cluster.min_cluster_and_distance(jnp.asarray(x), centers)
        counts = np.bincount(np.asarray(nn.key), minlength=16)
        assert counts.min() > 0  # no empty clusters after balancing

    @pytest.mark.slow  # 5k-row hierarchical build (tier-1 budget)
    def test_build_hierarchical(self):
        x, _, _ = make_blobs(RngState(8), 5000, 8, n_clusters=20, cluster_std=1.0)
        centers = cluster.build_hierarchical(RngState(0), x, 64, n_iters=8)
        assert centers.shape == (64, 8)
        nn = cluster.min_cluster_and_distance(jnp.asarray(x), centers)
        counts = np.bincount(np.asarray(nn.key), minlength=64)
        assert (counts > 0).sum() >= 60  # nearly all lists populated


class TestSingleLinkage:
    def test_mst_weight_matches_scipy(self):
        rng = np.random.default_rng(0)
        x = rng.random((60, 4))
        src, dst, w = cluster.build_sorted_mst(x)
        import scipy.sparse.csgraph as csgraph
        import scipy.spatial.distance as sd

        d = sd.cdist(x, x)
        mst = csgraph.minimum_spanning_tree(d)
        np.testing.assert_allclose(float(np.sum(np.asarray(w))), mst.sum(), rtol=1e-5)

    def test_labels_match_scipy(self):
        from scipy.cluster.hierarchy import fcluster, linkage

        rng = np.random.default_rng(1)
        x = np.concatenate([
            rng.normal(0, 0.3, (40, 5)),
            rng.normal(5, 0.3, (30, 5)),
            rng.normal(-5, 0.3, (30, 5)),
        ]).astype(np.float64)
        out = cluster.single_linkage(x, n_clusters=3)
        sp = fcluster(linkage(x, "single"), 3, criterion="maxclust")
        ari = float(adjusted_rand_index(np.asarray(out.labels), sp - 1))
        assert ari == 1.0
        assert out.children.shape == (99, 2)
        assert out.sizes[-1] == 100

    def test_dendrogram_monotone(self):
        rng = np.random.default_rng(2)
        x = rng.random((50, 3))
        out = cluster.single_linkage(x, n_clusters=2)
        assert (np.diff(out.deltas) >= -1e-7).all()  # sorted merges


class TestReviewRegressions:
    def test_cosine_metric_threads_through(self):
        # cosine k-means: init + EM must both use cosine (review finding)
        rng = np.random.default_rng(0)
        x = rng.random((500, 16)).astype(np.float32) + 0.1
        params = KMeansParams(n_clusters=4, metric=DistanceType.CosineExpanded,
                              seed=0, max_iter=50)
        out = cluster.fit_predict(params, x)
        assert out.labels.shape == (500,)
        assert np.isfinite(float(out.inertia))

    def test_predict_normalize_weight(self, blobs):
        x, _, centers = blobs
        params = KMeansParams(n_clusters=5)
        w = np.full(len(x), 3.0, np.float32)
        _, i_norm = cluster.predict(params, x, centers, sample_weights=w)
        _, i_raw = cluster.predict(params, x, centers, sample_weights=w,
                                   normalize_weight=False)
        np.testing.assert_allclose(float(i_raw), 3 * float(i_norm), rtol=1e-5)

    def test_array_init_single_trial(self, blobs):
        x, _, centers = blobs
        params = KMeansParams(n_clusters=5, init=InitMethod.Array, n_init=10)
        out = cluster.fit(params, x, centroids=centers)  # must not do 10 fits
        assert float(out.inertia) > 0

    def test_hierarchical_with_empty_meso(self):
        # tiny duplicated dataset forces degenerate/empty mesoclusters
        x = np.tile(np.random.default_rng(0).random((40, 8)).astype(np.float32),
                    (20, 1))
        centers = cluster.build_hierarchical(RngState(0), x, 48, n_iters=4)
        assert centers.shape == (48, 8)

    def test_fine_stage_respects_center_mask(self):
        """Masked-out centers (quota padding) must neither attract points nor
        be re-seeded by balancing — they come back exactly as seeded."""
        from raft_tpu.cluster.kmeans_balanced import _fine_stage

        rng = np.random.default_rng(3)
        xs = jnp.asarray(rng.normal(0, 1, (2, 256, 8)).astype(np.float32))
        # seed masked centers FAR away; if they took part in EM they would
        # move (no point is near them, so balancing would re-seed them)
        c0 = np.concatenate([rng.normal(0, 1, (2, 4, 8)),
                             np.full((2, 4, 8), 1e6)], axis=1).astype(np.float32)
        cmask = jnp.asarray(np.repeat([[True] * 4 + [False] * 4], 2, axis=0))
        out = np.asarray(_fine_stage(jnp.asarray(xs), jnp.asarray(c0), cmask,
                                     n_iters=6))
        np.testing.assert_array_equal(out[:, 4:], c0[:, 4:])  # untouched
        assert np.all(np.abs(out[:, :4]) < 100)  # live centers moved to data

    def test_hierarchical_skewed_populations(self):
        """Quotas follow mesocluster populations; the concatenated centers
        must still total exactly n_clusters and cover the heavy region."""
        rng = np.random.default_rng(4)
        heavy = rng.normal(0, 0.5, (9000, 8))
        light = rng.normal(20, 0.5, (500, 8))
        x = np.concatenate([heavy, light]).astype(np.float32)
        centers = cluster.build_hierarchical(RngState(0), x, 100, n_iters=6)
        assert centers.shape == (100, 8)
        c = np.asarray(centers)
        assert np.isfinite(c).all()
        n_heavy = int((np.linalg.norm(c - 0.0, axis=1) < 10).sum())
        assert n_heavy > 60  # heavy region got the bulk of the quota


class TestEngineResolution:
    def test_pallas_engine_rejected_for_non_l2(self):
        x = np.random.default_rng(0).random((32, 8), dtype=np.float32)
        c = x[:4]
        with pytest.raises(ValueError, match="L2 metric family"):
            cluster.min_cluster_and_distance(
                x, c, metric=DistanceType.CosineExpanded, engine="pallas")

    def test_unknown_engine_rejected(self):
        x = np.random.default_rng(0).random((32, 8), dtype=np.float32)
        with pytest.raises(ValueError, match="unknown engine"):
            cluster.min_cluster_and_distance(x, x[:4], engine="cuda")

    def test_env_default_resolved_per_call(self, monkeypatch):
        """RAFT_TPU_PALLAS_NN is resolved OUTSIDE the jit cache: flipping it
        between same-shape calls must change the selected engine (ADVICE r2:
        an engine=None cache key silently kept the first compiled engine)."""
        from raft_tpu.cluster import kmeans as K

        seen = []
        orig = K._min_cluster_and_distance

        def spy(*a, **kw):
            seen.append(kw["engine"])
            return orig(*a, **kw)

        monkeypatch.setattr(K, "_min_cluster_and_distance", spy)
        x = np.random.default_rng(0).random((32, 8), dtype=np.float32)
        c = x[:4]
        from raft_tpu.distance import pallas_fused_l2nn

        monkeypatch.setattr(pallas_fused_l2nn, "is_enabled", lambda: False)
        cluster.min_cluster_and_distance(x, c)
        # flip the gate between same-shape calls (on TPU this is the
        # RAFT_TPU_PALLAS_NN env var; is_enabled() additionally requires a
        # real TPU backend, so patch the gate itself here on CPU)
        monkeypatch.setattr(pallas_fused_l2nn, "is_enabled", lambda: True)
        cluster.min_cluster_and_distance(x, c)
        assert seen == ["xla", "pallas"]


class TestLibraryOracles:
    """sklearn/scipy oracle grids (the reference validates against its own
    CPU naive kernels; an independent library is a stronger oracle)."""

    def test_kmeans_matches_sklearn_same_init(self):
        """Identical init array + Lloyd iterations → the same fixed point
        as sklearn KMeans (algorithm='lloyd', n_init=1)."""
        from sklearn.cluster import KMeans as SkKMeans

        x, _, centers = make_blobs(RngState(50), 800, 10, n_clusters=6,
                                   cluster_std=0.8)
        x, centers = np.asarray(x, np.float64), np.asarray(centers, np.float64)
        params = KMeansParams(n_clusters=6, init=InitMethod.Array,
                              max_iter=100, tol=1e-10)
        ours = cluster.fit(params, x, centroids=centers)
        sk = SkKMeans(n_clusters=6, init=centers, n_init=1, max_iter=100,
                      tol=1e-10, algorithm="lloyd").fit(x)
        np.testing.assert_allclose(float(ours.inertia), sk.inertia_,
                                   rtol=1e-6)
        # same partition (up to label permutation)
        labels, _ = cluster.predict(params, x, ours.centroids)
        assert float(adjusted_rand_index(np.asarray(labels),
                                         sk.labels_)) == pytest.approx(1.0)

    def test_plus_plus_init_beats_random(self):
        """k-means|| seeding lands a materially better starting inertia
        than uniform-random points on well-separated blobs (the seeding
        quality property the reference's initKMeansPlusPlus exists for)."""
        x, _, _ = make_blobs(RngState(51), 2000, 8, n_clusters=16,
                             cluster_std=0.2)
        x = np.asarray(x)
        pp = np.asarray(cluster.init_plus_plus(RngState(1), x, 16, 2.0))
        r = np.random.default_rng(1)
        rand_init = x[r.choice(len(x), 16, replace=False)]

        def inertia(c):
            nn = cluster.min_cluster_and_distance(jnp.asarray(x),
                                                  jnp.asarray(c))
            return float(cluster.cluster_cost(nn))

        # ++ seeding should be several times better pre-EM on this data
        assert inertia(pp) < 0.5 * inertia(rand_init)

    @pytest.mark.parametrize("n,d,seed", [(60, 3, 0), (200, 8, 1),
                                          (128, 2, 2)])
    def test_single_linkage_grid_vs_scipy(self, n, d, seed):
        """Full dendrogram parity with scipy single linkage across a
        size/dim grid (reference test/cluster/linkage.cu cases)."""
        import scipy.cluster.hierarchy as sch
        from scipy.spatial.distance import pdist

        r = np.random.default_rng(seed)
        x = r.normal(0, 1, (n, d)).astype(np.float64)
        for n_clusters in (2, 5):
            out = cluster.single_linkage(x, n_clusters=n_clusters)
            want = sch.fcluster(sch.linkage(pdist(x), method="single"),
                                n_clusters, criterion="maxclust")
            ari = float(adjusted_rand_index(np.asarray(out.labels), want))
            assert ari == pytest.approx(1.0), f"n_clusters={n_clusters}"

    @pytest.mark.slow  # full fits across a k sweep (tier-1 budget)
    def test_kmeans_inertia_monotone_in_k(self):
        """Optimal inertia is non-increasing in k (sanity property the
        reference checks via its elbow-style test grids)."""
        x, _, _ = make_blobs(RngState(52), 500, 6, n_clusters=8,
                             cluster_std=1.0)
        x = np.asarray(x)
        prev = np.inf
        for k in (2, 4, 8, 16):
            params = KMeansParams(n_clusters=k, max_iter=50, seed=3,
                                  n_init=3)
            out = cluster.fit(params, x)
            assert float(out.inertia) <= prev * 1.001, f"k={k}"
            prev = float(out.inertia)


def test_kmeans_fit_bf16_data():
    """bf16 datasets (the TPU-native dtype) fit end-to-end: distances
    accumulate in f32 (pairwise._mxu_dot), the while_loop carries use the
    matching dtypes, and the result lands near the f32 fit."""
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    x64, c64 = rng.random((200, 128)), rng.random((8, 128))
    params = KMeansParams(n_clusters=8, init=InitMethod.Array, max_iter=20)
    out_bf = cluster.fit(params, jnp.asarray(x64, jnp.bfloat16),
                         centroids=jnp.asarray(c64, jnp.bfloat16))
    out_f32 = cluster.fit(params, x64.astype(np.float32),
                          centroids=c64.astype(np.float32))
    assert out_bf.centroids.dtype == jnp.bfloat16
    assert float(out_bf.inertia) == pytest.approx(float(out_f32.inertia),
                                                  rel=0.02)


def test_kmeans_bf16_tol_convergence_uses_f32_delta():
    """The tol check's centroid-movement delta accumulates in f32 even for
    bf16 centroids (r4 advisor finding: a bf16 sum over k*dim tiny squared
    terms drops everything below sum*2^-8, so the loop could run to
    max_iter or stop early unpredictably).  On well-separated clusters the
    bf16 fit must early-stop like the f32 fit does."""
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    centers = 10.0 * rng.random((8, 64))
    x64 = centers[rng.integers(0, 8, 400)] + 0.01 * rng.random((400, 64))
    c0 = centers + 0.05 * rng.random((8, 64))
    params = KMeansParams(n_clusters=8, init=InitMethod.Array, max_iter=50,
                          tol=1e-3)
    out_f32 = cluster.fit(params, x64.astype(np.float32),
                          centroids=c0.astype(np.float32))
    out_bf = cluster.fit(params, jnp.asarray(x64, jnp.bfloat16),
                         centroids=jnp.asarray(c0, jnp.bfloat16))
    assert int(out_f32.n_iter) < 50
    # early convergence within a couple of iterations of the f32 fit
    assert int(out_bf.n_iter) <= int(out_f32.n_iter) + 3


def test_kmeans_fit_fori_matches_while():
    """fit(loop="fori") (r5: static-trip masked-update program — the
    config[1] while_loop A/B candidate) is semantically identical to the
    default while_loop fit: same centroids, inertia, and n_iter."""
    rng = np.random.default_rng(12)
    centers = 8.0 * rng.random((6, 24))
    x = (centers[rng.integers(0, 6, 600)]
         + 0.05 * rng.random((600, 24))).astype(np.float32)
    params = KMeansParams(n_clusters=6, init=InitMethod.Array, max_iter=40,
                          tol=1e-4)
    w = cluster.fit(params, x, centroids=centers.astype(np.float32))
    f = cluster.fit(params, x, centroids=centers.astype(np.float32),
                    loop="fori")
    assert int(f.n_iter) == int(w.n_iter) < 40
    np.testing.assert_allclose(np.asarray(f.centroids),
                               np.asarray(w.centroids), rtol=1e-6)
    np.testing.assert_allclose(float(f.inertia), float(w.inertia),
                               rtol=1e-6)


def test_build_hierarchical_bf16_matches_f32_structure():
    """Balanced hierarchical build on bf16 data: fine-stage E/M accumulate
    in f32 (accum_dtype policy), so cluster sizes stay balanced and
    centers land near the f32 build's."""
    import jax.numpy as jnp

    x, _, _ = make_blobs(RngState(13), 3000, 16, n_clusters=12,
                         cluster_std=0.3)
    x = np.asarray(x)
    out_f32 = cluster.build_hierarchical(RngState(0), x.astype(np.float32),
                                         24)
    out_bf = cluster.build_hierarchical(RngState(0),
                                        jnp.asarray(x, jnp.bfloat16), 24)

    def centers_sizes(out):
        if isinstance(out, tuple):
            return np.asarray(out[0], np.float64), np.asarray(out[1])
        return np.asarray(out, np.float64), None

    c32, s32 = centers_sizes(out_f32)
    cbf, sbf = centers_sizes(out_bf)
    assert cbf.shape == c32.shape
    # each bf16 center has a nearby f32 center (same partition structure)
    from scipy.spatial.distance import cdist

    d = cdist(cbf, c32)
    scale = np.abs(c32).max()
    assert np.median(d.min(axis=1)) < 0.25 * scale, (
        np.median(d.min(axis=1)), scale)
    if s32 is not None:
        assert int(sbf.sum()) == int(s32.sum()) == 3000
