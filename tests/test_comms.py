"""Comms tests over the 8-device virtual CPU mesh — the TPU-land analogue of
the reference's LocalCUDACluster-driven pytest suite
(python/raft-dask/raft_dask/test/test_comms.py:44-88)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.comms import CommsSession, ReduceOp, Status, build_comms
from raft_tpu.comms import self_tests
from raft_tpu.comms.session import local_handle


@pytest.fixture(scope="module")
def comms():
    return build_comms()


class TestSelfTests:
    """Drive every reference comms_test.hpp check."""

    @pytest.mark.parametrize("test_fn", self_tests.ALL_TESTS,
                             ids=[t.__name__ for t in self_tests.ALL_TESTS])
    def test(self, comms, test_fn):
        assert test_fn(comms)


class TestCollectives:
    def test_allreduce_ops(self, comms):
        n = comms.get_size()

        def fn(x):
            r = comms.get_global_rank().astype(jnp.float32)
            return (comms.allreduce(r, ReduceOp.SUM),
                    comms.allreduce(r, ReduceOp.MIN),
                    comms.allreduce(r, ReduceOp.MAX),
                    comms.allreduce(r + 1, ReduceOp.PROD))

        s, mn, mx, pr = comms.run(fn, jnp.zeros((n,)))
        assert float(s) == n * (n - 1) / 2
        assert float(mn) == 0 and float(mx) == n - 1
        assert float(pr) == float(np.prod(np.arange(1, n + 1)))

    def test_collective_calls_record_payload_bytes(self, comms):
        """Every collective launch also records its per-rank payload
        bytes under "<name>_bytes" at trace time (the sharded-ANN layer
        asserts bytes, not just counts — an over-chatty program that
        splits or fattens its payload is caught either way)."""
        before = dict(comms.collective_calls)

        def fn(x):
            return (comms.allreduce(x),              # (4, 8) f32
                    comms.allgather(x[0]))           # (8,) f32

        comms.run(fn, np.zeros((comms.get_size() * 4, 8), np.float32))
        delta = {k: comms.collective_calls[k] - before.get(k, 0)
                 for k in comms.collective_calls
                 if comms.collective_calls[k] != before.get(k, 0)}
        assert delta == {"allreduce": 1, "allreduce_bytes": 4 * 8 * 4,
                         "allgather": 1, "allgather_bytes": 8 * 4}, delta

    def test_allgatherv(self, comms):
        n = comms.get_size()
        counts = [(r % 3) + 1 for r in range(n)]

        def fn(x):
            rank = comms.get_global_rank()
            data = jnp.full((3,), rank, jnp.float32)  # padded shard
            g, _ = comms.allgatherv(data, counts, pad_to=3)
            return g

        g = comms.run(fn, jnp.zeros((n,)))
        g = np.asarray(g)
        for r in range(n):
            np.testing.assert_allclose(g[r, : counts[r]], r)

    def test_ring_permute_sums_to_identity(self, comms):
        n = comms.get_size()
        perm = [(i, (i + 1) % n) for i in range(n)]

        def fn(x):
            v = comms.get_global_rank().astype(jnp.float32)
            for _ in range(n):  # n hops around the ring returns home
                v = comms.device_sendrecv(v, perm)
            ok = v == comms.get_global_rank().astype(jnp.float32)
            return comms.allreduce(ok.astype(jnp.int32), ReduceOp.MIN)

        assert int(comms.run(fn, jnp.zeros((n,)))) == 1


class TestSplit:
    def test_split_four_groups(self, comms):
        n = comms.get_size()
        colors = [r % 4 for r in range(n)]
        sub = comms.comm_split(colors)
        assert sub.get_size() == n // 4

        def fn(x):
            return sub.allreduce(jnp.ones(()))

        assert float(comms.run(fn, jnp.zeros((n,)))) == n // 4

    def test_split_with_keys_reorders(self, comms):
        n = comms.get_size()
        colors = [0] * n
        keys = list(reversed(range(n)))  # reverse rank order
        sub = comms.comm_split(colors, keys)

        def fn(x):
            # my rank within the group must be n-1-global_rank
            r = sub.get_rank()
            expected = (n - 1) - comms.get_global_rank()
            ok = r == expected
            return comms.allreduce(ok.astype(jnp.int32), ReduceOp.MIN)

        assert int(comms.run(fn, jnp.zeros((n,)))) == 1

    def _check_grouped(self, comms, sub, groups):
        """Exercise every grouped collective and compare against numpy
        per-group reference results."""
        n = comms.get_size()
        g = len(groups[0])
        vals = np.arange(1.0, n + 1)  # rank r contributes r+1

        def fn(x):
            r = comms.get_global_rank().astype(jnp.float32) + 1
            s = sub.allreduce(r, ReduceOp.SUM)[None]
            mn = sub.allreduce(r, ReduceOp.MIN)[None]
            mx = sub.allreduce(r, ReduceOp.MAX)[None]
            pr = sub.allreduce(r, ReduceOp.PROD)[None]
            bc = sub.bcast(r, root=1)[None]
            ag = sub.allgather(r[None])[None]
            rs = sub.reducescatter(jnp.full((g,), r))[None]
            return s, mn, mx, pr, bc, ag, rs

        out_specs = tuple(jax.sharding.PartitionSpec("world") for _ in range(7))
        s, mn, mx, pr, bc, ag, rs = comms.run(
            fn, jnp.zeros((n,)), out_specs=out_specs)
        s, mn, mx, pr, bc = map(np.asarray, (s, mn, mx, pr, bc))
        ag, rs = np.asarray(ag)[:, :, 0], np.asarray(rs)[:, 0]
        for grp in groups:
            gv = vals[grp]
            for r_pos, r in enumerate(grp):
                assert s[r] == gv.sum()
                assert mn[r] == gv.min() and mx[r] == gv.max()
                assert pr[r] == gv.prod()
                assert bc[r] == vals[grp[1]]  # root=1 within group
                np.testing.assert_allclose(ag[r], gv)
                # reducescatter of a constant-per-rank vector: chunk r_pos
                # of the sum == sum of the group's contributions
                assert rs[r] == gv.sum()

    def test_grouped_butterfly_2x4(self, comms):
        """Power-of-two groups → recursive-doubling path."""
        n = comms.get_size()
        colors = [r // 4 for r in range(n)]
        sub = comms.comm_split(colors)
        groups = [list(range(4)), list(range(4, 8))]
        self._check_grouped(comms, sub, groups)

    def test_grouped_butterfly_interleaved(self, comms):
        """Non-contiguous power-of-two groups (even/odd ranks)."""
        n = comms.get_size()
        sub = comms.comm_split([r % 2 for r in range(n)])
        groups = [list(range(0, n, 2)), list(range(1, n, 2))]
        self._check_grouped(comms, sub, groups)

    def test_grouped_ring_3s(self):
        """Group size 3 (not a power of two) → rotation-ring path, on a
        6-device sub-mesh."""
        from jax.sharding import Mesh

        devs = jax.devices()[:6]
        mesh = Mesh(np.array(devs), ("world",))
        comms = build_comms(mesh, session_id="ring3")
        sub = comms.comm_split([0, 0, 0, 1, 1, 1])
        groups = [[0, 1, 2], [3, 4, 5]]
        self._check_grouped(comms, sub, groups)

    def test_grouped_keys_order(self, comms):
        """allgather must follow key order within each group."""
        n = comms.get_size()
        sub = comms.comm_split([0] * n, keys=list(reversed(range(n))))

        def fn(x):
            r = comms.get_global_rank().astype(jnp.float32)
            return sub.allgather(r[None])[None]

        ag = np.asarray(comms.run(
            fn, jnp.zeros((n,)),
            out_specs=jax.sharding.PartitionSpec("world")))[:, :, 0]
        for r in range(n):
            np.testing.assert_allclose(ag[r], np.arange(n - 1.0, -1.0, -1.0))

    def test_barrier_gates(self, comms):
        # outside shard_map, single process: local drain, returns None
        assert comms.barrier() is None

        def fn(x):
            return comms.barrier()

        assert float(comms.run(fn, jnp.zeros((comms.get_size(),)))) > 0

    def test_split_validates(self, comms):
        from raft_tpu.core import LogicError

        with pytest.raises(LogicError):
            comms.comm_split([0])  # wrong length

    def test_unequal_groups_allreduce(self, comms):
        """NCCL comm_split allows any color partition; shape-preserving
        collectives must work on unequal groups (3+5 split): within-group
        sums of global ranks."""
        import jax.numpy as jnp

        n = comms.mesh.shape[comms.axis_name]
        if n != 8:
            pytest.skip("shaped for the 8-device mesh")
        sub = comms.comm_split([0] * 3 + [1] * 5)

        def fn(x):
            s = sub.allreduce(comms.get_global_rank().astype(jnp.float32))
            r = comms.get_global_rank()
            exp = jnp.where(r < 3, 3.0, float(sum(range(3, 8))))
            from raft_tpu.comms.comms_types import ReduceOp

            ok = (s == exp) & (sub.get_group_size() == jnp.where(r < 3, 3, 5))
            return comms.allreduce(ok.astype(jnp.int32), ReduceOp.MIN)

        assert int(comms.run(fn, np.zeros(n, np.float32))) == 1

    def test_unequal_groups_reject_shape_changing(self, comms):
        """allgather/reducescatter outputs are group-size-shaped: one SPMD
        program cannot express them over unequal groups — explicit error."""
        from raft_tpu.core import LogicError

        n = comms.mesh.shape[comms.axis_name]
        if n != 8:
            pytest.skip("shaped for the 8-device mesh")
        sub = comms.comm_split([0] * 3 + [1] * 5)
        with pytest.raises(LogicError):
            sub.get_size()

        def ag(x):
            return sub.allgather(x)

        with pytest.raises(LogicError):
            comms.run(ag, np.zeros(n, np.float32))


class TestHostP2P:
    def test_tagged_roundtrip(self, comms):
        req_s = comms.isend([1, 2, 3], dst=0, tag=42)
        req_r = comms.irecv(src=0, tag=42)
        (got,) = comms.waitall([req_s, req_r])
        assert got == [1, 2, 3]

    def test_tags_do_not_cross(self, comms):
        comms.isend("a", dst=0, tag=1)
        comms.isend("b", dst=0, tag=2)
        r2 = comms.irecv(src=0, tag=2)
        r1 = comms.irecv(src=0, tag=1)
        got2, got1 = comms.waitall([r2, r1])
        assert (got1, got2) == ("a", "b")


_WORKER_SRC = r"""
import sys
rank, world, coord = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
from raft_tpu.comms import build_comms
comms = build_comms(session_id="xproc", coordinator=coord,
                    host_rank=rank, host_world=world)
peer = 1 - rank
# tagged payload exchange across real OS processes (ucp_helper.hpp role)
comms.isend({"from": rank, "data": list(range(rank + 3))}, dst=peer, tag=7)
(got,) = comms.waitall([comms.irecv(src=peer, tag=7)], timeout=60)
assert got["from"] == peer, got
assert got["data"] == list(range(peer + 3)), got
# cross-process barrier (twice: epoch handling)
comms.barrier()
comms.barrier()
print(f"worker{rank}:ok", flush=True)
"""


class TestCrossProcessP2P:
    """Two spawned OS processes exchanging tagged messages + barriers
    through the TCP mailbox — the reference's UCX-plane test shape
    (comms_test.hpp:100 driven over a real local cluster)."""

    def test_two_process_roundtrip(self, tmp_path):
        import os
        import subprocess
        import sys

        from raft_tpu.comms.hostcomm import MailboxServer

        with MailboxServer() as server:
            coord = f"{server.address[0]}:{server.address[1]}"
            script = tmp_path / "xproc_worker.py"
            script.write_text(_WORKER_SRC)
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["PALLAS_AXON_POOL_IPS"] = ""
            env.setdefault("PYTHONPATH", "")
            env["PYTHONPATH"] = (os.getcwd() + os.pathsep + env["PYTHONPATH"])
            procs = [subprocess.Popen(
                [sys.executable, str(script), str(rank), "2", coord],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
                for rank in (0, 1)]
            outs = [p.communicate(timeout=180)[0].decode() for p in procs]
            for rank, (p, out) in enumerate(zip(procs, outs)):
                assert p.returncode == 0, f"worker{rank} failed:\n{out}"
                assert f"worker{rank}:ok" in out

    def test_mailbox_direct(self):
        from raft_tpu.comms.hostcomm import MailboxServer, TcpMailbox

        with MailboxServer() as server:
            coord = f"{server.address[0]}:{server.address[1]}"
            a = TcpMailbox(coord, "s", 0)
            b = TcpMailbox(coord, "s", 1)
            a.put(dst=1, tag=3, obj=np.arange(5))
            got = b.get(src=0, tag=3, timeout=10)
            np.testing.assert_array_equal(got, np.arange(5))
            with pytest.raises(TimeoutError):
                b.get(src=0, tag=99, timeout=0.2)


class TestMailboxStress:
    """Framing/liveness stress for the host p2p plane (UCX role): large
    payloads cross the framed protocol intact and message floods with
    interleaved tags neither drop nor cross-deliver."""

    def test_large_payload_roundtrip(self):
        from raft_tpu.comms.hostcomm import MailboxServer, TcpMailbox

        with MailboxServer() as server:
            coord = f"{server.address[0]}:{server.address[1]}"
            a = TcpMailbox(coord, "L", 0)
            b = TcpMailbox(coord, "L", 1)
            rng = np.random.default_rng(0)
            big = rng.random(2_000_000)            # ~16 MB framed payload
            a.put(dst=1, tag=1, obj=big)
            got = b.get(src=0, tag=1, timeout=60)
            np.testing.assert_array_equal(got, big)
            # and the channel still works for small messages afterwards
            b.put(dst=0, tag=2, obj="after-big")
            assert a.get(src=1, tag=2, timeout=10) == "after-big"

    def test_many_interleaved_tags_fifo_per_tag(self):
        from raft_tpu.comms.hostcomm import MailboxServer, TcpMailbox

        with MailboxServer() as server:
            coord = f"{server.address[0]}:{server.address[1]}"
            a = TcpMailbox(coord, "M", 0)
            b = TcpMailbox(coord, "M", 1)
            n_tags, n_msgs = 8, 20
            for i in range(n_msgs):               # round-robin the tags
                for t in range(n_tags):
                    a.put(dst=1, tag=t, obj=(t, i))
            # drain tags in a DIFFERENT order than sent; per-tag FIFO holds
            for t in reversed(range(n_tags)):
                for i in range(n_msgs):
                    got = b.get(src=0, tag=t, timeout=30)
                    assert got == (t, i), (t, i, got)


class TestSyncStream:
    def test_success(self, comms):
        x = jnp.ones((8, 8)) * 2
        assert comms.sync_stream(x) == Status.SUCCESS

    def test_abort_sticky(self):
        c = build_comms(session_id="abort-test")
        c.abort()
        assert c.sync_stream() == Status.ABORT


class TestSession:
    def test_lifecycle(self):
        with CommsSession(n_devices=8) as sess:
            assert sess.initialized
            h = local_handle(sess.session_id)
            assert h is not None and h.comms_initialized()
            info = sess.worker_info()
            assert len(info) == 8 and info[3]["rank"] == 3
            # run a collective through the injected handle
            comms = h.get_comms()

            def fn(x):
                return comms.allreduce(jnp.ones(()))

            assert float(comms.run(fn, jnp.zeros((8,)))) == 8.0
        assert local_handle(sess.session_id) is None


_MULTIHOST_WORKER = r"""
import os, sys
rank, port = int(sys.argv[1]), sys.argv[2]
import jax
import numpy as np, jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from raft_tpu.comms.session import CommsSession, local_handle

sess = CommsSession(multihost=dict(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2,
    process_id=rank)).init()
comms = sess.comms
assert comms.get_size() == 4, comms.get_size()
h = local_handle(sess.session_id)
assert h is not None and h.comms_initialized()
local = np.full(2, rank + 1.0, np.float32)
x = jax.make_array_from_process_local_data(
    NamedSharding(comms.mesh, P("world")), local, (4,))

def fn(xs):
    return comms.allreduce(jnp.sum(xs))[None]

out = comms.run(fn, x, in_specs=(P("world"),), out_specs=P())
assert float(out[0]) == 6.0, float(out[0])  # 1+1+2+2 across both hosts
sess.destroy()
print(f"worker{rank}:ok", flush=True)
"""


class TestMultihostSession:
    """CommsSession's jax.distributed branch over two real OS processes
    (2 CPU devices each -> a 4-device global mesh) — the raft-dask
    LocalCUDACluster-bringup test shape (raft_dask/test/test_comms.py:44)."""

    def test_two_process_session_allreduce(self, tmp_path):
        import os
        import socket
        import subprocess
        import sys

        with socket.socket() as s:  # free port for the coordinator
            s.bind(("127.0.0.1", 0))
            port = str(s.getsockname()[1])
        script = tmp_path / "mh_worker.py"
        script.write_text(_MULTIHOST_WORKER)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")
        procs = [subprocess.Popen(
            [sys.executable, str(script), str(rank), port],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
            for rank in (0, 1)]
        try:
            outs = [p.communicate(timeout=300)[0].decode() for p in procs]
        finally:
            for p in procs:  # no orphans if a worker hangs past the timeout
                if p.poll() is None:
                    p.kill()
        for rank, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"worker{rank} failed:\n{out}"
            assert f"worker{rank}:ok" in out


class TestMailboxStress:
    def test_concurrent_tagged_exchange(self):
        """Many threads × many tags through one server — exercises the
        waiter-tracked queue reaping under contention."""
        import threading

        from raft_tpu.comms.hostcomm import MailboxServer, TcpMailbox

        with MailboxServer() as server:
            coord = f"{server.address[0]}:{server.address[1]}"
            world = 4
            rounds = 25
            boxes = [TcpMailbox(coord, "stress", r) for r in range(world)]
            errs = []

            def worker(rank):
                try:
                    peer = (rank + 1) % world
                    src = (rank - 1) % world
                    for t in range(rounds):
                        boxes[rank].put(peer, t, (rank, t))
                        got = boxes[rank].get(src, t, timeout=30)
                        assert got == (src, t), got
                except Exception as e:  # noqa: BLE001 - collected for assert
                    errs.append((rank, repr(e)))

            threads = [threading.Thread(target=worker, args=(r,))
                       for r in range(world)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(120)
            assert not any(t.is_alive() for t in threads), "workers hung"
            assert not errs, errs

    def test_grouped_reducescatter_multichunk(self, comms):
        """reducescatter with 2 rows per rank within each split group."""
        n = comms.get_size()
        sub = comms.comm_split([r // 4 for r in range(n)])

        def fn(x):
            r = comms.get_global_rank().astype(jnp.float32)
            data = jnp.stack([r, r + 100.0]).reshape(2)[None, :].repeat(4, 0)
            # (8,) per rank: 2 chunks of 2 per group member
            return sub.reducescatter(data.reshape(8))[None]

        out = np.asarray(comms.run(
            fn, jnp.zeros((n,)), out_specs=jax.sharding.PartitionSpec("world")))
        # group {0..3}: per-rank vector tiles [r, r+100] * 4 → chunk p of the
        # sum lands on rank p
        for g0 in (0, 4):
            s = sum(range(g0, g0 + 4))
            for p in range(4):
                np.testing.assert_allclose(out[g0 + p], [s, s + 400.0])


class TestMailboxBackends:
    """Both MailboxServer backends speak one binary protocol; the native
    poll-loop server (native/hostcomm_server.cpp — the reference's
    native-UCX-role plane) is preferred, the threaded Python server is the
    fallback (RAFT_TPU_NATIVE_MAILBOX=0)."""

    def _drive(self, server):
        import time

        from raft_tpu.comms.hostcomm import TcpMailbox

        addr = f"127.0.0.1:{server.address[1]}"
        a = TcpMailbox(addr, "s", 0)
        b = TcpMailbox(addr, "s", 1)
        try:
            # boxed put -> get
            a.put(1, 3, ("hello", 42))
            assert b.get(0, 3) == ("hello", 42)
            # blocked GET woken by a later PUT (waiter path)
            import threading

            got = []
            t = threading.Thread(
                target=lambda: got.append(b.get(0, 9, timeout=10)))
            t.start()
            time.sleep(0.1)
            a.put(1, 9, "wake")
            t.join(timeout=10)
            assert got == ["wake"]
            # timeout propagates
            with pytest.raises(TimeoutError):
                a.get(1, 777, timeout=0.3)
            # sessions are isolated
            other = TcpMailbox(addr, "s2", 1)
            a.put(1, 3, "for-s")
            with pytest.raises(TimeoutError):
                other.get(0, 3, timeout=0.3)
            assert b.get(0, 3) == "for-s"
            other.close()
        finally:
            a.close()
            b.close()

    def test_native_backend(self):
        from raft_tpu import native
        from raft_tpu.comms.hostcomm import MailboxServer

        if not native.is_available():
            pytest.skip("native runtime not built")
        with MailboxServer() as s:
            assert s.backend == "native"
            self._drive(s)

    def test_python_backend(self, monkeypatch):
        from raft_tpu.comms.hostcomm import MailboxServer

        monkeypatch.setenv("RAFT_TPU_NATIVE_MAILBOX", "0")
        with MailboxServer() as s:
            assert s.backend == "python"
            self._drive(s)

    def test_native_stalled_reader_does_not_block_others(self):
        """A peer that requests a large payload and then stops draining its
        socket must not head-of-line-block the coordinator: its reply queues
        on ITS connection (served under POLLOUT) while other clients' RPCs
        proceed (code-review r3 finding on the poll-loop design)."""
        import time

        from raft_tpu import native
        from raft_tpu.comms.hostcomm import MailboxServer, TcpMailbox

        if not native.is_available():
            pytest.skip("native runtime not built")
        with MailboxServer() as s:
            assert s.backend == "native"
            addr = f"127.0.0.1:{s.address[1]}"
            slow = TcpMailbox(addr, "s", 0)
            fast = TcpMailbox(addr, "s", 1)
            try:
                big = b"x" * (8 << 20)
                slow.put(0, 1, big)        # 8 MB boxed for rank 0
                # issue the GET request bytes but do NOT read the reply:
                # the server's reply overflows the kernel buffer and must
                # queue server-side on slow's connection only
                from raft_tpu.comms import hostcomm as hc

                sock = slow._sock()
                sock.sendall(hc._encode_req(hc._OP_GET, b"s", 0, 0, 1, 30.0))
                time.sleep(0.2)            # let the server hit EAGAIN
                t0 = time.perf_counter()
                for i in range(100):
                    fast.put(1, 2, i)        # rank 1 → itself
                    assert fast.get(1, 2) == i
                assert time.perf_counter() - t0 < 5.0, "coordinator stalled"
                # the slow client can still drain its reply afterwards
                ok, payload = hc._recv_reply(sock)
                assert ok and len(payload) > (8 << 20)
            finally:
                slow.close()
                fast.close()


class TestMultiprocessDryrun:
    """RAFT_TPU_DRYRUN_PROCS=2 runs the full dryrun battery over a
    2-OS-process x 4-device jax.distributed mesh — the CI-feasible analogue
    of the reference's multi-node NCCL rendezvous driven end to end
    (std_comms.hpp:55-96; raft-dask comms.py:171-218)."""

    def test_two_process_device_mesh_battery(self):
        import os
        import subprocess
        import sys

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        env["RAFT_TPU_DRYRUN_PROCS"] = "2"
        proc = subprocess.run(
            [sys.executable, os.path.join(root, "__graft_entry__.py"), "8"],
            env=env, cwd=root, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, timeout=560)
        out = proc.stdout.decode()
        assert proc.returncode == 0, out
        assert "dryrun_multichip(8) x 2 processes: ok" in out, out
        assert "cross_process_host_barrier: ok" in out, out
