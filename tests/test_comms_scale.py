"""Pod-like-scale grouped-collective tests: 32 virtual devices.

The in-process suite runs on the 8-device mesh (conftest); a v5p-32 target
(BASELINE.md) implies group shapes the 8-device mesh cannot represent —
groups of 16, 2×16 splits, deep butterflies.  jax pins the device count at
first backend init, so the 32-device profile runs in ONE subprocess that
executes the whole battery and prints a verdict line per check (the
reference analogue: per-clique comm_split tests on real clusters,
std_comms.hpp:107-171).
"""

import os
import subprocess
import sys

_SCRIPT = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=32"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh

from raft_tpu.comms import build_comms, self_tests
from raft_tpu.comms.comms_types import ReduceOp

N = 32
mesh = Mesh(np.array(jax.devices()[:N]), ("world",))
comms = build_comms(mesh)
failures = []

def check(name, ok):
    print(("ok " if ok else "FAIL ") + name, flush=True)
    if not ok:
        failures.append(name)

# full-axis self-test battery at 32 devices
for t, ok in self_tests.run_all(comms).items():
    check("world32/" + t, ok)

# grouped collectives: pow2 sizes ride the butterfly, others the ring
for gsize in (4, 8, 16):
    ngroups = N // gsize
    sub = comms.comm_split([r // gsize for r in range(N)])

    def fn(x, sub=sub, gsize=gsize):
        r = comms.get_global_rank()
        grp = r // gsize
        # allreduce: within-group sum of global ranks
        s = sub.allreduce(r.astype(jnp.float32))
        base = grp * gsize
        exp_sum = (base * gsize + gsize * (gsize - 1) // 2).astype(jnp.float32)
        ok = s == exp_sum
        # allgather: group members in order
        g = sub.allgather(r.astype(jnp.float32)[None])
        exp_g = base.astype(jnp.float32) + jnp.arange(gsize, dtype=jnp.float32)
        ok &= jnp.all(g.ravel() == exp_g)
        # reducescatter: ones -> each member holds gsize
        rs = sub.reducescatter(jnp.ones((gsize,)))
        ok &= jnp.all(rs == float(gsize))
        return comms.allreduce(ok.astype(jnp.int32), ReduceOp.MIN)

    check(f"split{gsize}x{ngroups}/allreduce+allgather+reducescatter",
          int(comms.run(fn, np.zeros(N, np.float32))) == 1)

# odd, non-dividing sizes: 32 = 3*10 + 2 and 32 = 5*6 + 2 -> unequal last
# group; shape-preserving collectives must still be exact per group
for gsize in (3, 5):
    colors = [r // gsize for r in range(N)]
    sub = comms.comm_split(colors)
    sizes = np.bincount(colors)

    def fn(x, sub=sub, colors=colors, sizes=sizes):
        r = comms.get_global_rank()
        col = jnp.asarray(colors, jnp.int32)[r]
        s = sub.allreduce(r.astype(jnp.float32))
        grp_sums = np.zeros(len(sizes), np.float32)
        for rr, c in enumerate(colors):
            grp_sums[c] += rr
        ok = s == jnp.asarray(grp_sums)[col]
        ok &= sub.get_group_size() == jnp.asarray(sizes, jnp.int32)[col]
        mn = sub.allreduce(r.astype(jnp.float32), ReduceOp.MIN)
        grp_mins = np.asarray([min(rr for rr, c in enumerate(colors) if c == cc)
                               for cc in range(len(sizes))], np.float32)
        ok &= mn == jnp.asarray(grp_mins)[col]
        return comms.allreduce(ok.astype(jnp.int32), ReduceOp.MIN)

    check(f"split{gsize}(unequal)/allreduce sum+min",
          int(comms.run(fn, np.zeros(N, np.float32))) == 1)

# multicast over a small participant set: O(group) ring, world untouched
srcs = [3, 17, 30]
dsts = [3, 5, 17, 21, 30]

def fn_mc(x):
    r = comms.get_global_rank()
    got = comms.device_multicast_sendrecv(r.astype(jnp.float32),
                                          dsts=dsts, srcs=srcs)
    member = jnp.isin(r, jnp.asarray(sorted(set(dsts) | set(srcs))))
    exp = jnp.asarray([float(s) for s in srcs])
    ok = jnp.where(member, jnp.all(got == exp), jnp.all(got == 0.0))
    return comms.allreduce(ok.astype(jnp.int32), ReduceOp.MIN)

check("multicast/participant-ring", int(comms.run(fn_mc, np.zeros(N, np.float32))) == 1)

print("SCALE32 DONE failures=%d" % len(failures), flush=True)
raise SystemExit(1 if failures else 0)
"""


def test_comms_battery_at_32_devices():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    out = subprocess.run([sys.executable, "-c", _SCRIPT], env=env,
                         capture_output=True, text=True, timeout=900,
                         cwd=os.path.dirname(os.path.dirname(
                             os.path.abspath(__file__))))
    sys.stdout.write(out.stdout)
    assert "SCALE32 DONE failures=0" in out.stdout, out.stdout + out.stderr[-2000:]
    assert out.returncode == 0
