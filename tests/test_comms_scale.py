"""Pod-like-scale grouped-collective tests: 32 virtual devices.

The in-process suite runs on the 8-device mesh (conftest); a v5p-32 target
(BASELINE.md) implies group shapes the 8-device mesh cannot represent —
groups of 16, 2×16 splits, deep butterflies.  jax pins the device count at
first backend init, so the 32-device profile runs in ONE subprocess that
executes the whole battery and prints a verdict line per check (the
reference analogue: per-clique comm_split tests on real clusters,
std_comms.hpp:107-171).
"""

import os
import subprocess
import sys

_SCRIPT = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=32"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh

from raft_tpu.comms import build_comms, self_tests
from raft_tpu.comms.comms_types import ReduceOp

N = 32
mesh = Mesh(np.array(jax.devices()[:N]), ("world",))
comms = build_comms(mesh)
failures = []

def check(name, ok):
    print(("ok " if ok else "FAIL ") + name, flush=True)
    if not ok:
        failures.append(name)

# full-axis self-test battery at 32 devices
for t, ok in self_tests.run_all(comms).items():
    check("world32/" + t, ok)

# grouped collectives: pow2 sizes ride the butterfly, others the ring
for gsize in (4, 8, 16):
    ngroups = N // gsize
    sub = comms.comm_split([r // gsize for r in range(N)])

    def fn(x, sub=sub, gsize=gsize):
        r = comms.get_global_rank()
        grp = r // gsize
        # allreduce: within-group sum of global ranks
        s = sub.allreduce(r.astype(jnp.float32))
        base = grp * gsize
        exp_sum = (base * gsize + gsize * (gsize - 1) // 2).astype(jnp.float32)
        ok = s == exp_sum
        # allgather: group members in order
        g = sub.allgather(r.astype(jnp.float32)[None])
        exp_g = base.astype(jnp.float32) + jnp.arange(gsize, dtype=jnp.float32)
        ok &= jnp.all(g.ravel() == exp_g)
        # reducescatter: ones -> each member holds gsize
        rs = sub.reducescatter(jnp.ones((gsize,)))
        ok &= jnp.all(rs == float(gsize))
        return comms.allreduce(ok.astype(jnp.int32), ReduceOp.MIN)

    check(f"split{gsize}x{ngroups}/allreduce+allgather+reducescatter",
          int(comms.run(fn, np.zeros(N, np.float32))) == 1)

# odd, non-dividing sizes: 32 = 3*10 + 2 and 32 = 5*6 + 2 -> unequal last
# group; shape-preserving collectives must still be exact per group
for gsize in (3, 5):
    colors = [r // gsize for r in range(N)]
    sub = comms.comm_split(colors)
    sizes = np.bincount(colors)

    def fn(x, sub=sub, colors=colors, sizes=sizes):
        r = comms.get_global_rank()
        col = jnp.asarray(colors, jnp.int32)[r]
        s = sub.allreduce(r.astype(jnp.float32))
        grp_sums = np.zeros(len(sizes), np.float32)
        for rr, c in enumerate(colors):
            grp_sums[c] += rr
        ok = s == jnp.asarray(grp_sums)[col]
        ok &= sub.get_group_size() == jnp.asarray(sizes, jnp.int32)[col]
        mn = sub.allreduce(r.astype(jnp.float32), ReduceOp.MIN)
        grp_mins = np.asarray([min(rr for rr, c in enumerate(colors) if c == cc)
                               for cc in range(len(sizes))], np.float32)
        ok &= mn == jnp.asarray(grp_mins)[col]
        return comms.allreduce(ok.astype(jnp.int32), ReduceOp.MIN)

    check(f"split{gsize}(unequal)/allreduce sum+min",
          int(comms.run(fn, np.zeros(N, np.float32))) == 1)

# multicast over a small participant set: O(group) ring, world untouched
srcs = [3, 17, 30]
dsts = [3, 5, 17, 21, 30]

def fn_mc(x):
    r = comms.get_global_rank()
    got = comms.device_multicast_sendrecv(r.astype(jnp.float32),
                                          dsts=dsts, srcs=srcs)
    member = jnp.isin(r, jnp.asarray(sorted(set(dsts) | set(srcs))))
    exp = jnp.asarray([float(s) for s in srcs])
    ok = jnp.where(member, jnp.all(got == exp), jnp.all(got == 0.0))
    return comms.allreduce(ok.astype(jnp.int32), ReduceOp.MIN)

check("multicast/participant-ring", int(comms.run(fn_mc, np.zeros(N, np.float32))) == 1)

# deepest and shallowest pow2 splits: 16 groups of 2 (one ppermute round)
# and the 1x32 split (split == world; butterfly depth 5)
for gsize in (2, 32):
    sub = comms.comm_split([r // gsize for r in range(N)])

    def fn(x, sub=sub, gsize=gsize):
        r = comms.get_global_rank()
        base = (r // gsize) * gsize
        s = sub.allreduce(r.astype(jnp.float32))
        exp = (base * gsize + gsize * (gsize - 1) // 2).astype(jnp.float32)
        ok = s == exp
        g = sub.allgather(r.astype(jnp.float32)[None])
        ok &= jnp.all(g.ravel() == base.astype(jnp.float32)
                      + jnp.arange(gsize, dtype=jnp.float32))
        return comms.allreduce(ok.astype(jnp.int32), ReduceOp.MIN)

    check(f"split{gsize}x{N // gsize}/allreduce+allgather",
          int(comms.run(fn, np.zeros(N, np.float32))) == 1)

# ring sendrecv at 32: forward shift, reverse shift, disjoint pair swap
def fn_ring(x):
    r = comms.get_global_rank().astype(jnp.float32)
    fwd = comms.device_sendrecv(r, [(i, (i + 1) % N) for i in range(N)])
    rev = comms.device_sendrecv(r, [(i, (i - 1) % N) for i in range(N)])
    ok = fwd == (comms.get_global_rank() - 1) % N
    ok &= rev == (comms.get_global_rank() + 1) % N
    swap = comms.device_sendrecv(r, [(0, 31), (31, 0)])
    me = comms.get_global_rank()
    ok &= jnp.where(me == 0, swap == 31.0,
                    jnp.where(me == 31, swap == 0.0, swap == 0.0))
    return comms.allreduce(ok.astype(jnp.int32), ReduceOp.MIN)

check("sendrecv/ring+reverse+pairswap",
      int(comms.run(fn_ring, np.zeros(N, np.float32))) == 1)

# allgatherv at 32 with ragged counts: padded shards come back exact
counts = [(r % 5) for r in range(N)]

def fn_agv(x, counts=counts):
    r = comms.get_global_rank()
    cnt = jnp.asarray(counts, jnp.int32)[r]
    mine = jnp.where(jnp.arange(4) < cnt, r.astype(jnp.float32) + 1, 0.0)
    gathered, _ = comms.allgatherv(mine, counts, pad_to=4)
    exp = jnp.where(jnp.arange(4)[None, :] < jnp.asarray(counts)[:, None],
                    jnp.arange(N, dtype=jnp.float32)[:, None] + 1, 0.0)
    ok = jnp.all(gathered == exp)
    return comms.allreduce(ok.astype(jnp.int32), ReduceOp.MIN)

check("allgatherv/ragged-counts-pad4",
      int(comms.run(fn_agv, np.zeros(N, np.float32))) == 1)

# ---- failure / misuse paths (reference: API misuse asserts + ncclCommAbort
# propagation, std_comms.hpp) -------------------------------------------
from raft_tpu.core.error import LogicError

# unequal-group allgather must raise: output shape is group-size-dependent,
# unexpressible in one SPMD program
sub_uneq = comms.comm_split([r // 5 for r in range(N)])  # 6 groups of 5 + 2

def fn_bad_ag(x):
    return sub_uneq.allgather(comms.get_global_rank().astype(jnp.float32)[None])

try:
    comms.run(fn_bad_ag, np.zeros(N, np.float32))
    check("raise/unequal-group-allgather", False)
except LogicError as e:
    check("raise/unequal-group-allgather", "equal-sized groups" in str(e))

# unequal-group reducescatter: same static-shape constraint
def fn_bad_rs(x):
    return sub_uneq.reducescatter(jnp.ones((10,)))

try:
    comms.run(fn_bad_rs, np.zeros(N, np.float32))
    check("raise/unequal-group-reducescatter", False)
except LogicError:
    check("raise/unequal-group-reducescatter", True)

# reducescatter length not divisible by group size
sub8 = comms.comm_split([r // 8 for r in range(N)])

def fn_bad_rs2(x):
    return sub8.reducescatter(jnp.ones((9,)))

try:
    comms.run(fn_bad_rs2, np.zeros(N, np.float32))
    check("raise/reducescatter-indivisible", False)
except LogicError:
    check("raise/reducescatter-indivisible", True)

# allgatherv pad_to smaller than a shard
try:
    comms.run(lambda x: comms.allgatherv(jnp.ones((5,)), [5] * N, pad_to=4)[0],
              np.zeros(N, np.float32))
    check("raise/allgatherv-pad-too-small", False)
except LogicError:
    check("raise/allgatherv-pad-too-small", True)

# comm_split color vector of the wrong length / with coverage gaps
try:
    comms.comm_split([0] * (N - 1))
    check("raise/split-bad-length", False)
except LogicError:
    check("raise/split-bad-length", True)

# abort propagation: ABORT is sticky on the aborted communicator and
# isolated from the world communicator (per-clique, as ncclCommAbort)
from raft_tpu.comms.comms_types import Status

sub_ab = comms.comm_split([r // 4 for r in range(N)])
assert sub_ab.sync_stream() == Status.SUCCESS
sub_ab.abort()
check("abort/sticky-on-aborted-clique",
      sub_ab.sync_stream() == Status.ABORT
      and sub_ab.sync_stream() == Status.ABORT)
check("abort/world-unaffected", comms.sync_stream() == Status.SUCCESS)
# device work still syncs fine through the healthy communicator
arr = comms.run(lambda x: comms.allreduce(x), np.ones(N, np.float32))
check("abort/world-collectives-still-run",
      comms.sync_stream(arr) == Status.SUCCESS and float(arr[0]) == N)

print("SCALE32 DONE failures=%d" % len(failures), flush=True)
raise SystemExit(1 if failures else 0)
"""


def test_comms_battery_at_32_devices():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    out = subprocess.run([sys.executable, "-c", _SCRIPT], env=env,
                         capture_output=True, text=True, timeout=900,
                         cwd=os.path.dirname(os.path.dirname(
                             os.path.abspath(__file__))))
    sys.stdout.write(out.stdout)
    assert "SCALE32 DONE failures=0" in out.stdout, out.stdout + out.stderr[-2000:]
    assert out.returncode == 0
