"""Core runtime tests — counterpart of reference cpp/test/{handle.cpp,
interruptible.cu, mdarray.cu, span.cu, pow2_utils.cu, memory_type.cpp}."""

import threading
import time

import numpy as np
import pytest

import raft_tpu
from raft_tpu.core import (
    Handle,
    KeyValuePair,
    Layout,
    LogicError,
    MemoryType,
    as_device_array,
    expects,
    fail,
    interruptible,
    kvp_min,
    make_device_matrix,
    make_device_vector,
    make_host_matrix,
)
from raft_tpu.core.logger import Logger, INFO, DEBUG
from raft_tpu.util import Pow2, Seive, ceildiv, min_tile, pad_to_tile, unpad


class TestHandle:
    def test_default(self):
        h = Handle()
        assert h.get_device() is not None
        assert not h.is_stream_pool_initialized()
        with pytest.raises(LogicError):
            h.get_stream_from_stream_pool()

    def test_stream_pool(self):
        h = Handle(n_streams=4)
        assert h.stream_pool_size == 4
        assert h.get_stream_from_stream_pool(6).name == "pool2"
        assert h.get_next_usable_stream(1).name == "pool1"
        h.sync_stream_pool()
        h.wait_stream_pool_on_stream()

    def test_sync_records_work(self):
        import jax.numpy as jnp

        h = Handle()
        x = jnp.ones((128, 128)) @ jnp.ones((128, 128))
        h.get_stream().record(x)
        h.sync()
        assert h.get_stream().query()

    def test_comms_slots(self):
        h = Handle()
        assert not h.comms_initialized()
        with pytest.raises(LogicError):
            h.get_comms()
        h.set_comms("fake")
        assert h.get_comms() == "fake"
        h.set_subcomm("rows", "sub")
        assert h.get_subcomm("rows") == "sub"
        with pytest.raises(LogicError):
            h.get_subcomm("cols")


class TestErrors:
    def test_expects(self):
        expects(True, "ok")
        with pytest.raises(LogicError, match="bad thing"):
            expects(False, "bad thing")
        with pytest.raises(LogicError):
            fail("boom")

    def test_hierarchy(self):
        from raft_tpu.core import RaftError

        assert issubclass(LogicError, RaftError)


class TestMdarray:
    def test_device_matrix(self, handle):
        m = make_device_matrix(handle, 4, 5)
        assert m.shape == (4, 5)
        assert m.memory_type == MemoryType.DEVICE
        assert np.asarray(m).shape == (4, 5)

    def test_col_major(self, handle):
        m = make_device_matrix(handle, 4, 6, layout=Layout.F)
        assert m.shape == (4, 6)
        assert m.data.shape == (6, 4)  # stored transposed
        assert m.view().shape == (4, 6)

    def test_host(self):
        m = make_host_matrix(3, 3, dtype=np.float64)
        assert m.memory_type == MemoryType.HOST
        assert m.dtype == np.float64

    def test_vector(self, handle):
        v = make_device_vector(handle, 7)
        assert v.shape == (7,)
        assert v.size() == 7

    def test_as_device_array(self):
        x = as_device_array(np.arange(6).reshape(2, 3), dtype=np.float32)
        assert x.dtype == np.float32
        np.testing.assert_array_equal(np.asarray(x), [[0, 1, 2], [3, 4, 5]])


class TestInterruptible:
    def test_synchronize_completes(self):
        import jax.numpy as jnp

        x = jnp.arange(1024.0) * 2
        interruptible.synchronize(x)

    def test_cancel_from_other_thread(self):

        from raft_tpu.core.error import InterruptedError_

        main_tid = threading.get_ident()
        # Pre-create the token so the canceller never races token creation.
        interruptible.get_token(main_tid)
        raised = {}

        def canceller():
            time.sleep(0.05)
            interruptible.cancel(main_tid)

        t = threading.Thread(target=canceller)
        t.start()
        try:
            with pytest.raises(InterruptedError_):
                # Spin in yields until cancelled (no long device op needed).
                deadline = time.time() + 5
                while time.time() < deadline:
                    interruptible.yield_()
                    time.sleep(0.001)
                raised["timeout"] = True
        finally:
            t.join()
        assert "timeout" not in raised

    def test_context_manager(self):
        with interruptible.interruptible():
            pass  # no KeyboardInterrupt -> nothing happens
        interruptible.yield_()  # token is clean


class TestLogger:
    def test_levels_and_callback(self):
        logger = Logger.get()
        captured = []
        logger.set_callback(lambda lvl, msg: captured.append(msg))
        old = logger.get_level()
        try:
            logger.set_level(INFO)
            raft_tpu.core.log_info("hello %d", 42)
            raft_tpu.core.log_debug("invisible")
            logger.set_level(DEBUG)
            raft_tpu.core.log_debug("visible")
        finally:
            logger.set_callback(None)
            logger.set_level(old)
        assert any("hello 42" in m for m in captured)
        assert not any("invisible" in m for m in captured)
        assert any("visible" in m for m in captured)

    def test_time_range(self):
        from raft_tpu.core import time_range

        with time_range("unit-test-range"):
            pass


class TestKvp:
    def test_kvp_min(self):
        import jax.numpy as jnp

        a = KeyValuePair(jnp.array([0, 1, 2]), jnp.array([1.0, 5.0, 3.0]))
        b = KeyValuePair(jnp.array([3, 0, 2]), jnp.array([2.0, 4.0, 3.0]))
        m = kvp_min(a, b)
        np.testing.assert_array_equal(np.asarray(m.key), [0, 0, 2])
        np.testing.assert_allclose(np.asarray(m.value), [1.0, 4.0, 3.0])


class TestUtil:
    def test_ceildiv(self):
        assert ceildiv(10, 3) == 4
        assert ceildiv(9, 3) == 3

    def test_pow2(self):
        p = Pow2(128)
        assert p.round_up(129) == 256
        assert p.round_down(129) == 128
        assert p.div(256) == 2
        assert p.mod(130) == 2
        with pytest.raises(ValueError):
            Pow2(100)

    def test_tiling(self):
        import jax.numpy as jnp

        assert min_tile(np.float32) == (8, 128)
        assert min_tile(np.int8) == (32, 128)
        x = jnp.ones((5, 100))
        xp, orig = pad_to_tile(x)
        assert xp.shape == (8, 128)
        assert unpad(xp, orig).shape == (5, 100)

    def test_seive(self):
        s = Seive(50)
        assert s.is_prime(47)
        assert not s.is_prime(49)
        assert list(s.primes()[:5]) == [2, 3, 5, 7, 11]


def test_mesh_fixture(mesh8):
    assert mesh8.devices.size == 8


class TestReviewRegressions:
    """Regression tests for code-review findings."""

    def test_auto_sync_handle_positional(self):
        from raft_tpu.core import auto_sync_handle, Handle

        @auto_sync_handle
        def f(x, handle=None):
            assert handle is not None
            return x + 1

        assert f(1) == 2                      # default injected + synced
        assert f(1, Handle()) == 2            # positional handle
        assert f(1, handle=Handle()) == 2     # keyword handle

    def test_logger_no_duplicate_handlers(self):
        from raft_tpu.core.logger import Logger

        a, b = Logger(), Logger()
        assert a is b is Logger.get()
        assert len(a._logger.handlers) == 1


def test_traced_decorator_preserves_semantics():
    """@traced (the NVTX-range analogue at algorithm entries) must be
    transparent: same results, same metadata, range emitted via
    jax.profiler without error."""
    from raft_tpu.core import traced

    calls = []

    @traced("raft_tpu.test.op")
    def op(a, b=2):
        """docstring survives"""
        calls.append((a, b))
        return a + b

    assert op(1, b=3) == 4
    assert op.__name__ == "op" and "survives" in op.__doc__
    assert calls == [(1, 3)]


def test_util_product_of_cartesian_grid():
    """util.itertools.product_of: named cartesian grid used by the prewarm
    instantiation registry — order within each axis is preserved."""
    from raft_tpu.util.itertools import product_of

    grid = product_of(a=[1, 2], b=["x"], c=[True, False])
    assert len(grid) == 4
    assert {frozenset(d.items()) for d in grid} == {
        frozenset({("a", 1), ("b", "x"), ("c", True)}.__iter__()),
        frozenset({("a", 1), ("b", "x"), ("c", False)}),
        frozenset({("a", 2), ("b", "x"), ("c", True)}),
        frozenset({("a", 2), ("b", "x"), ("c", False)}),
    }
    assert product_of() in ([], [{}])  # degenerate grid is well-defined
