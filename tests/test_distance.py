"""Distance tests — counterpart of reference cpp/test/distance/* (naive
kernel oracles) and pylibraft test_distance.py (scipy.cdist oracle)."""

import numpy as np
import pytest
import scipy.spatial.distance as scipy_dist

from raft_tpu.core import LogicError
from raft_tpu.distance import (
    DistanceType,
    KernelParams,
    KernelType,
    distance,
    fused_l2_nn,
    fused_l2_nn_argmin,
    gram_matrix,
    pairwise_distance,
)


@pytest.fixture
def data():
    rng = np.random.default_rng(3)
    x = rng.random((37, 13)).astype(np.float32) + 0.01
    y = rng.random((53, 13)).astype(np.float32) + 0.01
    return x, y


# metric name → scipy cdist oracle name (same table pylibraft tests use)
SCIPY_METRICS = [
    ("euclidean", "euclidean", {}),
    ("sqeuclidean", "sqeuclidean", {}),
    ("cityblock", "cityblock", {}),
    ("l1", "cityblock", {}),
    ("chebyshev", "chebyshev", {}),
    ("canberra", "canberra", {}),
    ("cosine", "cosine", {}),
    ("correlation", "correlation", {}),
    ("minkowski", "minkowski", {"p": 3.0}),
    ("braycurtis", "braycurtis", {}),
    ("jensenshannon", "jensenshannon", {}),
    ("hamming", "hamming", {}),
]


@pytest.mark.parametrize("name,scipy_name,kwargs", SCIPY_METRICS)
def test_vs_scipy(data, name, scipy_name, kwargs):
    x, y = data
    if name == "jensenshannon":
        # RAFT semantics: inputs are probability rows (the reference pytest
        # normalizes before the scipy comparison, test_distance.py:44-46)
        x = x / x.sum(axis=1, keepdims=True)
        y = y / y.sum(axis=1, keepdims=True)
    expected = scipy_dist.cdist(x, y, scipy_name, **kwargs)
    if name == "minkowski":
        got = pairwise_distance(x, y, name, p=3.0)
    else:
        got = pairwise_distance(x, y, name)
    np.testing.assert_allclose(np.asarray(got), expected, rtol=2e-4, atol=2e-5)


def test_hamming_binary(data):
    rng = np.random.default_rng(0)
    x = (rng.random((20, 32)) > 0.5).astype(np.float32)
    y = (rng.random((15, 32)) > 0.5).astype(np.float32)
    expected = scipy_dist.cdist(x, y, "hamming")
    got = pairwise_distance(x, y, "hamming")
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5)


def test_inner_product(data):
    x, y = data
    got = pairwise_distance(x, y, "inner_product")
    np.testing.assert_allclose(np.asarray(got), x @ y.T, rtol=1e-4)


def test_l2_expanded_matches_unexpanded(data):
    x, y = data
    exp = np.asarray(distance(x, y, DistanceType.L2Expanded))
    unexp = np.asarray(distance(x, y, DistanceType.L2Unexpanded))
    np.testing.assert_allclose(exp, unexp, rtol=1e-3, atol=1e-4)
    sq_exp = np.asarray(distance(x, y, DistanceType.L2SqrtExpanded))
    np.testing.assert_allclose(sq_exp, np.sqrt(unexp), rtol=1e-3, atol=1e-4)


def test_hellinger():
    rng = np.random.default_rng(1)
    x = rng.random((10, 8)).astype(np.float64)
    y = rng.random((12, 8)).astype(np.float64)
    # normalize to probability vectors (hellinger's domain)
    x /= x.sum(axis=1, keepdims=True)
    y /= y.sum(axis=1, keepdims=True)
    got = np.asarray(pairwise_distance(x, y, "hellinger"))
    expected = np.sqrt(
        np.maximum(1 - np.sqrt(x)[:, None, :] @ np.sqrt(y)[None].transpose(0, 2, 1), 0)
    )[0] if False else None
    # direct naive oracle
    exp = np.zeros((10, 12))
    for i in range(10):
        for j in range(12):
            exp[i, j] = np.sqrt(max(1 - np.sum(np.sqrt(x[i] * y[j])), 0.0))
    np.testing.assert_allclose(got, exp, atol=1e-6)


def test_kl_divergence():
    rng = np.random.default_rng(2)
    x = rng.random((8, 16)).astype(np.float64)
    y = rng.random((9, 16)).astype(np.float64)
    x /= x.sum(axis=1, keepdims=True)
    y /= y.sum(axis=1, keepdims=True)
    got = np.asarray(pairwise_distance(x, y, "kl_divergence"))
    exp = np.zeros((8, 9))
    for i in range(8):
        for j in range(9):
            exp[i, j] = 0.5 * np.sum(x[i] * (np.log(x[i]) - np.log(y[j])))
    np.testing.assert_allclose(got, exp, atol=1e-10)


def test_russellrao():
    rng = np.random.default_rng(4)
    x = (rng.random((12, 40)) > 0.5).astype(np.float32)
    y = (rng.random((9, 40)) > 0.5).astype(np.float32)
    got = np.asarray(pairwise_distance(x, y, "russellrao"))
    expected = scipy_dist.cdist(x, y, "russellrao")
    np.testing.assert_allclose(got, expected, rtol=1e-5)


def test_haversine():
    rng = np.random.default_rng(5)
    lat = rng.uniform(-np.pi / 2, np.pi / 2, (6, 1))
    lon = rng.uniform(-np.pi, np.pi, (6, 1))
    pts = np.concatenate([lat, lon], axis=1).astype(np.float64)
    got = np.asarray(pairwise_distance(pts, pts, "haversine"))
    assert np.allclose(np.diag(got), 0, atol=1e-7)
    # oracle
    i, j = 2, 4
    sd = np.sin(0.5 * (pts[j, 0] - pts[i, 0])) ** 2 + np.cos(pts[i, 0]) * np.cos(
        pts[j, 0]
    ) * np.sin(0.5 * (pts[j, 1] - pts[i, 1])) ** 2
    np.testing.assert_allclose(got[i, j], 2 * np.arcsin(np.sqrt(sd)), rtol=1e-10)


def test_unsupported_metrics(data):
    x, y = data
    with pytest.raises(LogicError):
        pairwise_distance(x, y, "jaccard")
    with pytest.raises(LogicError):
        pairwise_distance(x, y, "not_a_metric")
    with pytest.raises(LogicError):
        distance(x, y[:, :5], DistanceType.L1)


def test_enum_metric_arg(data):
    x, y = data
    got = pairwise_distance(x, y, DistanceType.LpUnexpanded, metric_arg=1.0)
    np.testing.assert_allclose(
        np.asarray(got), scipy_dist.cdist(x, y, "cityblock"), rtol=2e-4
    )


def test_large_blocked_path():
    # exercises padding + multi-block tiling (m, n not multiples of blocks)
    rng = np.random.default_rng(6)
    x = rng.random((301, 24)).astype(np.float32)
    y = rng.random((1537, 24)).astype(np.float32)
    got = np.asarray(pairwise_distance(x, y, "cityblock"))
    expected = scipy_dist.cdist(x, y, "cityblock")
    np.testing.assert_allclose(got, expected, rtol=2e-4)


class TestFusedL2NN:
    def test_matches_bruteforce(self):
        rng = np.random.default_rng(7)
        x = rng.random((200, 32)).astype(np.float32)
        y = rng.random((77, 32)).astype(np.float32)
        out = fused_l2_nn(x, y)
        d = scipy_dist.cdist(x, y, "sqeuclidean")
        np.testing.assert_array_equal(np.asarray(out.key), d.argmin(axis=1))
        np.testing.assert_allclose(np.asarray(out.value), d.min(axis=1), rtol=1e-4, atol=1e-5)

    def test_sqrt(self):
        rng = np.random.default_rng(8)
        x = rng.random((50, 8)).astype(np.float32)
        y = rng.random((60, 8)).astype(np.float32)
        out = fused_l2_nn(x, y, sqrt=True)
        d = scipy_dist.cdist(x, y, "euclidean")
        np.testing.assert_allclose(np.asarray(out.value), d.min(axis=1), rtol=1e-4, atol=1e-5)

    def test_multi_block(self):
        rng = np.random.default_rng(9)
        x = rng.random((64, 16)).astype(np.float32)
        y = rng.random((3000, 16)).astype(np.float32)
        out = fused_l2_nn(x, y, block_n=512)
        d = scipy_dist.cdist(x, y, "sqeuclidean")
        np.testing.assert_array_equal(np.asarray(out.key), d.argmin(axis=1))

    def test_argmin_api(self):
        rng = np.random.default_rng(10)
        x = rng.random((30, 4)).astype(np.float32)
        y = rng.random((9, 4)).astype(np.float32)
        idx = fused_l2_nn_argmin(x, y)
        d = scipy_dist.cdist(x, y, "euclidean")
        np.testing.assert_array_equal(np.asarray(idx), d.argmin(axis=1))


class TestGram:
    def setup_method(self):
        rng = np.random.default_rng(11)
        self.x = rng.random((20, 6)).astype(np.float64)
        self.y = rng.random((15, 6)).astype(np.float64)

    def test_linear(self):
        k = gram_matrix(self.x, self.y, KernelParams(KernelType.LINEAR))
        np.testing.assert_allclose(np.asarray(k), self.x @ self.y.T, rtol=1e-10)

    def test_polynomial(self):
        p = KernelParams(KernelType.POLYNOMIAL, degree=3, gamma=0.5, coef0=1.0)
        k = gram_matrix(self.x, self.y, p)
        np.testing.assert_allclose(
            np.asarray(k), (0.5 * self.x @ self.y.T + 1.0) ** 3, rtol=1e-10
        )

    def test_tanh(self):
        p = KernelParams(KernelType.TANH, gamma=0.5, coef0=0.1)
        k = gram_matrix(self.x, self.y, p)
        np.testing.assert_allclose(
            np.asarray(k), np.tanh(0.5 * self.x @ self.y.T + 0.1), rtol=1e-10
        )

    def test_rbf(self):
        p = KernelParams(KernelType.RBF, gamma=0.7)
        k = gram_matrix(self.x, self.y, p)
        sq = scipy_dist.cdist(self.x, self.y, "sqeuclidean")
        np.testing.assert_allclose(np.asarray(k), np.exp(-0.7 * sq), rtol=1e-8)


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_pairwise_random_shapes_vs_scipy(dtype):
    """Property sweep: random shapes (incl. m/n/k == 1) across dtypes — the
    reference supports double everywhere (f64 paths are API surface)."""
    from scipy.spatial.distance import cdist

    names = {"euclidean": "euclidean", "sqeuclidean": "sqeuclidean",
             "cityblock": "cityblock", "chebyshev": "chebyshev",
             "canberra": "canberra", "cosine": "cosine",
             "braycurtis": "braycurtis", "hamming": "hamming"}
    rng = np.random.default_rng(7)
    for trial in range(12):
        m = int(rng.integers(1, 40))
        n = int(rng.integers(1, 40))
        k = int(rng.integers(1, 24))
        name = list(names)[trial % len(names)]
        x = rng.random((m, k)).astype(dtype)
        y = rng.random((n, k)).astype(dtype)
        got = np.asarray(pairwise_distance(x, y, name))
        ref = cdist(x.astype(np.float64), y.astype(np.float64), names[name])
        tol = 2e-3 if dtype == np.float32 else 1e-8
        np.testing.assert_allclose(got, ref, rtol=tol, atol=tol,
                                   err_msg=f"{name} m={m} n={n} k={k}")


class TestLayoutSweep:
    """Reference distance tests sweep isRowMajor for every metric
    (test/distance/distance_base.cuh): inputs in either memory order must
    produce identical results.  On TPU the XLA layout is internal — the
    parity obligation is that F-ordered (column-major) host arrays, strided
    views, and transposed views all round-trip through the public API."""

    METRICS = ["euclidean", "sqeuclidean", "cosine", "l1", "chebyshev",
               "canberra", "correlation", "hamming", "jensenshannon"]

    @pytest.mark.parametrize("metric", METRICS)
    def test_fortran_order_inputs(self, metric):
        from raft_tpu.distance import pairwise_distance

        rng = np.random.default_rng(3)
        x = rng.random((70, 24), dtype=np.float32) + 0.1
        y = rng.random((50, 24), dtype=np.float32) + 0.1
        ref = np.asarray(pairwise_distance(x, y, metric))
        xf = np.asfortranarray(x)
        yf = np.asfortranarray(y)
        assert not xf.flags.c_contiguous
        out = np.asarray(pairwise_distance(xf, yf, metric))
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("metric", ["euclidean", "cosine", "l1"])
    def test_strided_and_transposed_views(self, metric):
        from raft_tpu.distance import pairwise_distance

        rng = np.random.default_rng(4)
        big = rng.random((140, 48), dtype=np.float32) + 0.1
        x = big[::2, ::2]              # non-contiguous strided view
        yt = np.ascontiguousarray(big[:50, :24])
        ref = np.asarray(pairwise_distance(np.ascontiguousarray(x), yt, metric))
        out = np.asarray(pairwise_distance(x, yt, metric))
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
        xt = np.ascontiguousarray(x).T.copy().T   # transposed-storage view
        out_t = np.asarray(pairwise_distance(xt, yt, metric))
        np.testing.assert_allclose(out_t, ref, rtol=1e-5, atol=1e-5)

    def test_fused_l2_nn_fortran_inputs(self):
        from raft_tpu.distance import fused_l2_nn

        rng = np.random.default_rng(5)
        x = rng.random((90, 16), dtype=np.float32)
        y = rng.random((40, 16), dtype=np.float32)
        ref = fused_l2_nn(x, y)
        out = fused_l2_nn(np.asfortranarray(x), np.asfortranarray(y))
        np.testing.assert_array_equal(np.asarray(out.key), np.asarray(ref.key))


class TestHalfPrecisionInputs:
    """bf16/f16 datasets — the TPU-native dtypes: inputs stay half-width
    (MXU double-rate, half the HBM traffic) while accumulation and the
    returned distances are f32 (the systolic array's native accumulate
    mode via preferred_element_type; VPU tiles upcast in-register)."""

    @pytest.mark.parametrize("dtype_name", ["bfloat16", "float16"])
    @pytest.mark.parametrize("metric", ["sqeuclidean", "cosine", "l1",
                                        "chebyshev", "inner_product",
                                        "correlation"])
    def test_accumulates_f32(self, dtype_name, metric):
        import jax.numpy as jnp

        dtype = getattr(jnp, dtype_name)
        rng = np.random.default_rng(0)
        x64 = rng.random((60, 32))
        y64 = rng.random((45, 32))
        x = jnp.asarray(x64, dtype)
        y = jnp.asarray(y64, dtype)
        d = pairwise_distance(x, y, metric)
        assert d.dtype == jnp.float32, (metric, d.dtype)
        if metric == "inner_product":
            want = x64 @ y64.T
        else:
            want = scipy_dist.cdist(
                x64, y64, {"sqeuclidean": "sqeuclidean", "cosine": "cosine",
                           "l1": "cityblock", "chebyshev": "chebyshev",
                           "correlation": "correlation"}[metric])
        # error budget: input rounding only (bf16 ~ 8e-3 relative), not
        # accumulation drift over k — correlation's cancellation doubles it
        rel = np.max(np.abs(np.asarray(d, np.float64) - want)) / max(
            1.0, np.max(np.abs(want)))
        budget = 0.02 if dtype_name == "bfloat16" else 0.005
        if metric == "correlation":
            budget *= 4
        assert rel < budget, (metric, rel)

    def test_kl_divergence_bf16_probability_rows(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(2)
        x64 = rng.random((30, 64)) + 0.01
        y64 = rng.random((25, 64)) + 0.01
        x64 /= x64.sum(1, keepdims=True)
        y64 /= y64.sum(1, keepdims=True)
        d = pairwise_distance(jnp.asarray(x64, jnp.bfloat16),
                              jnp.asarray(y64, jnp.bfloat16),
                              "kl_divergence")
        assert d.dtype == jnp.float32
        want = 0.5 * np.array([[np.sum(a * (np.log(a) - np.log(b)))
                                for b in y64] for a in x64])
        np.testing.assert_allclose(np.asarray(d, np.float64), want,
                                   atol=5e-3)

    def test_fused_l2_nn_accepts_bf16(self):
        import jax.numpy as jnp

        from raft_tpu.distance import fused_l2_nn_argmin

        rng = np.random.default_rng(1)
        x64 = rng.random((128, 16))
        c64 = rng.random((8, 16))
        got = np.asarray(fused_l2_nn_argmin(jnp.asarray(x64, jnp.bfloat16),
                                            jnp.asarray(c64, jnp.bfloat16)))
        want = np.argmin(scipy_dist.cdist(x64, c64, "sqeuclidean"), axis=1)
        assert (got == want).mean() > 0.97  # bf16 rounding may flip ties


def test_gram_matrix_sklearn_oracles():
    """All four kernels vs sklearn.metrics.pairwise on the same params."""
    from sklearn.metrics.pairwise import (linear_kernel, polynomial_kernel,
                                          rbf_kernel, sigmoid_kernel)

    from raft_tpu.distance import KernelParams, KernelType, gram_matrix

    rng = np.random.default_rng(6)
    x = rng.normal(0, 1, (40, 9)).astype(np.float32)
    y = rng.normal(0, 1, (25, 9)).astype(np.float32)
    cases = [
        (KernelParams(KernelType.LINEAR), linear_kernel(x, y)),
        (KernelParams(KernelType.POLYNOMIAL, degree=3, gamma=0.5, coef0=1.0),
         polynomial_kernel(x, y, degree=3, gamma=0.5, coef0=1.0)),
        (KernelParams(KernelType.RBF, gamma=0.7), rbf_kernel(x, y, gamma=0.7)),
        (KernelParams(KernelType.TANH, gamma=0.2, coef0=0.4),
         sigmoid_kernel(x, y, gamma=0.2, coef0=0.4)),
    ]
    for params, ref in cases:
        got = np.asarray(gram_matrix(x, y, params))
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)
