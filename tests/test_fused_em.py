"""Fused single-pass EM tests (PR 2): fused_em_step ≡ the unfused two-pass
E+M across dtypes × weighting × loop forms, the empty-cluster fallback, the
ragged final tile, the MNMG packed wire format, the keyed-reduction engine
equivalence, and the segment-sum lint quarantine."""

import pathlib

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu import cluster
from raft_tpu.cluster import (EMPartials, InitMethod, KMeansParams,
                              centroids_from_sums, fused_em_step,
                              min_cluster_and_distance, pack_em_partials,
                              unpack_em_partials, update_centroids)
from raft_tpu.random import RngState, make_blobs


@pytest.fixture
def blobs():
    x, labels, centers = make_blobs(RngState(21), 900, 12, n_clusters=5,
                                    cluster_std=0.3)
    return np.asarray(x), np.asarray(labels), np.asarray(centers)


class TestFusedStepBuildingBlock:
    def test_matches_two_pass_oracle(self, blobs):
        """One fused pass == unfused E-step + M-step on the same centroids
        (sums, weights, inertia).  (Raggedness is NOT exercised here on the
        CPU backend — its tile growth swallows 900 rows into one tile; see
        test_ragged_tile_oracle.)"""
        x, _, c = blobs
        p = fused_em_step(x, c, batch_samples=256)
        nn = min_cluster_and_distance(jnp.asarray(x), jnp.asarray(c))
        new_exp, w_exp = update_centroids(x, nn.key, 5, old_centroids=c)
        got = centroids_from_sums(p.sums, p.weights, jnp.asarray(c),
                                  jnp.float32)
        np.testing.assert_allclose(np.asarray(got), np.asarray(new_exp),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(p.weights), np.asarray(w_exp),
                                   rtol=1e-6)
        np.testing.assert_allclose(float(p.inertia),
                                   float(cluster.cluster_cost(nn)), rtol=1e-5)

    def test_return_labels_same_pass(self, blobs):
        """return_labels=True emits the per-row (label, distance) pair from
        the SAME single pass — identical to the unfused E-step's."""
        x, _, c = blobs
        p = fused_em_step(x, c, batch_samples=256, return_labels=True)
        nn = min_cluster_and_distance(jnp.asarray(x), jnp.asarray(c))
        np.testing.assert_array_equal(np.asarray(p.labels), np.asarray(nn.key))
        # both forms carry ~1e-4 expanded-form error vs an f64 oracle; they
        # differ from each other by fp association (xn+(yn-2xy) vs
        # (xn+yn)-2xy) — same tolerance as the E-step-vs-scipy test
        np.testing.assert_allclose(np.asarray(p.distances),
                                   np.asarray(nn.value), rtol=1e-4, atol=1e-4)

    def test_weighted_partials(self, blobs):
        x, _, c = blobs
        w = np.random.default_rng(3).random(len(x)).astype(np.float32) + 0.5
        p = fused_em_step(x, c, sample_weights=w, batch_samples=256)
        nn = min_cluster_and_distance(jnp.asarray(x), jnp.asarray(c))
        from raft_tpu.cluster.kmeans import _weighted_cluster_sums

        sums_e, wsum_e = _weighted_cluster_sums(jnp.asarray(x), nn.key,
                                                jnp.asarray(w), 5)
        np.testing.assert_allclose(np.asarray(p.sums), np.asarray(sums_e),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(p.weights),
                                   np.asarray(wsum_e), rtol=1e-6)
        np.testing.assert_allclose(float(p.inertia),
                                   float(jnp.sum(nn.value * w)), rtol=1e-5)

    @pytest.mark.parametrize("weighted", [False, True])
    def test_ragged_tile_oracle(self, weighted):
        """The pad-masking branch MUST actually execute: on the CPU backend
        row tiles are grown to ≥16k rows, so n must exceed that for the
        final tile to be ragged (n=17001 → 2 tiles, 15767 padding rows).
        Covers both discard mechanisms: the ``n_clusters`` discard label +
        zeroed distance (unweighted) and the weight-0 padding (weighted)."""
        rng = np.random.default_rng(9)
        x = rng.random((17001, 8)).astype(np.float32)
        c = x[:5].copy()
        w = (rng.random(17001).astype(np.float32) + 0.5) if weighted else None
        p = fused_em_step(x, c, sample_weights=w)
        nn = min_cluster_and_distance(jnp.asarray(x), jnp.asarray(c))
        from raft_tpu.cluster.kmeans import _weighted_cluster_sums

        sums_e, wsum_e = _weighted_cluster_sums(
            jnp.asarray(x), nn.key, None if w is None else jnp.asarray(w), 5)
        np.testing.assert_allclose(np.asarray(p.sums), np.asarray(sums_e),
                                   rtol=1e-5, atol=1e-4)
        np.testing.assert_allclose(np.asarray(p.weights), np.asarray(wsum_e),
                                   rtol=1e-6)
        cost_e = float(cluster.cluster_cost(
            nn, None if w is None else jnp.asarray(w)))
        np.testing.assert_allclose(float(p.inertia), cost_e, rtol=1e-5)
        # labels from the same pass: padding rows must be sliced off
        q = fused_em_step(x, c, sample_weights=w, return_labels=True)
        assert q.labels.shape == (17001,)
        np.testing.assert_array_equal(np.asarray(q.labels), np.asarray(nn.key))

    def test_pack_unpack_roundtrip(self, blobs):
        """The MNMG wire format: ONE (k·d + k + 1) vector carries the whole
        per-iteration payload."""
        x, _, c = blobs
        p = fused_em_step(x, c)
        packed = pack_em_partials(p)
        assert packed.shape == (5 * 12 + 5 + 1,)
        q = unpack_em_partials(packed, 5, 12)
        np.testing.assert_array_equal(np.asarray(q.sums), np.asarray(p.sums))
        np.testing.assert_array_equal(np.asarray(q.weights),
                                      np.asarray(p.weights))
        np.testing.assert_array_equal(np.asarray(q.inertia),
                                      np.asarray(p.inertia))

    def test_bf16_accumulates_f32(self, blobs):
        x, _, c = blobs
        p = fused_em_step(jnp.asarray(x, jnp.bfloat16),
                          jnp.asarray(c, jnp.bfloat16), batch_samples=256)
        assert p.sums.dtype == jnp.float32
        assert p.weights.dtype == jnp.float32
        assert p.inertia.dtype == jnp.float32

    def test_engine_validation_shared_with_unfused(self, blobs):
        from raft_tpu.distance import DistanceType

        x, _, c = blobs
        with pytest.raises(ValueError, match="L2 metric family"):
            fused_em_step(x, c, metric=DistanceType.CosineExpanded,
                          engine="pallas")
        with pytest.raises(ValueError, match="unknown engine"):
            fused_em_step(x, c, engine="cuda")


class TestFusedEqualsUnfusedFit:
    """The property grid the satellite pins: fused EM ≡ unfused EM
    (centroids, inertia, n_iter) across {f32, bf16} × {weighted,
    unweighted} × both loop forms."""

    @pytest.mark.parametrize("dtype", ["f32", "bf16"])
    @pytest.mark.parametrize("weighted", [False, True])
    @pytest.mark.parametrize("loop", ["while", "fori"])
    def test_grid(self, blobs, dtype, weighted, loop):
        x, _, c = blobs
        if dtype == "bf16":
            x = jnp.asarray(x, jnp.bfloat16)
            c = jnp.asarray(c, jnp.bfloat16)
            rtol, atol = 2e-2, 2e-2
        else:
            rtol, atol = 1e-4, 1e-5
        w = (np.random.default_rng(5).random(900).astype(np.float32) + 0.5
             if weighted else None)
        params = KMeansParams(n_clusters=5, init=InitMethod.Array,
                              max_iter=40, tol=1e-4)
        a = cluster.fit(params, x, sample_weights=w, centroids=c, loop=loop,
                        fused=True)
        b = cluster.fit(params, x, sample_weights=w, centroids=c, loop=loop,
                        fused=False)
        assert int(a.n_iter) == int(b.n_iter) < 40
        np.testing.assert_allclose(
            np.asarray(a.centroids, np.float32),
            np.asarray(b.centroids, np.float32), rtol=rtol, atol=atol)
        np.testing.assert_allclose(float(a.inertia), float(b.inertia),
                                   rtol=max(rtol, 1e-5))

    def test_empty_cluster_keeps_previous_centroid(self):
        """A centroid that owns no points keeps its previous value through
        the fused fit (reference fallback), same as the unfused path."""
        rng = np.random.default_rng(0)
        x = rng.random((64, 4)).astype(np.float32)  # data in [0, 1)
        far = np.full((1, 4), 50.0, np.float32)     # never wins an argmin
        c0 = np.concatenate([x[:3], far]).astype(np.float32)
        params = KMeansParams(n_clusters=4, init=InitMethod.Array,
                              max_iter=10, tol=0.0)
        out_f = cluster.fit(params, x, centroids=c0, fused=True)
        out_u = cluster.fit(params, x, centroids=c0, fused=False)
        np.testing.assert_array_equal(np.asarray(out_f.centroids)[3], far[0])
        np.testing.assert_allclose(np.asarray(out_f.centroids),
                                   np.asarray(out_u.centroids), rtol=1e-5,
                                   atol=1e-6)

    def test_ragged_final_tile(self):
        """n deliberately not a multiple of the row tile: padding rows of
        the last tile must contribute to neither sums nor inertia.  n is
        kept above the CPU backend's ≥16k tile growth so the final tile is
        genuinely ragged there (16384·2 − 17001 padding rows)."""
        rng = np.random.default_rng(1)
        x = rng.random((17001, 8)).astype(np.float32)
        c = x[:6].copy()
        params = KMeansParams(n_clusters=6, init=InitMethod.Array,
                              max_iter=15, tol=1e-5, batch_samples=128)
        a = cluster.fit(params, x, centroids=c, fused=True)
        b = cluster.fit(params, x, centroids=c, fused=False)
        assert int(a.n_iter) == int(b.n_iter)
        np.testing.assert_allclose(np.asarray(a.centroids),
                                   np.asarray(b.centroids), rtol=1e-4,
                                   atol=1e-5)

    def test_env_toggle(self, blobs, monkeypatch):
        from raft_tpu.cluster.kmeans import fused_em_enabled

        monkeypatch.setenv("RAFT_TPU_FUSED_EM", "0")
        assert not fused_em_enabled()
        monkeypatch.delenv("RAFT_TPU_FUSED_EM")
        assert fused_em_enabled()

    def test_fused_partials_namedtuple_shape(self, blobs):
        x, _, c = blobs
        p = fused_em_step(x, c)
        assert isinstance(p, EMPartials)
        assert p.labels is None and p.distances is None


class TestKeyedReductionEngines:
    """reduce_rows_by_key / reduce_cols_by_key pick the one-hot matmul or
    the scatter per linalg.reduce.use_one_hot_engine — both engines must
    agree bit-for-tolerance."""

    def test_cols_by_key_engines_agree(self, monkeypatch):
        import importlib

        R = importlib.import_module("raft_tpu.linalg.reduce")

        rng = np.random.default_rng(2)
        d = jnp.asarray(rng.random((17, 33)).astype(np.float32))
        keys = jnp.asarray(rng.integers(0, 7, 33).astype(np.int32))
        monkeypatch.setattr(R, "use_one_hot_engine", lambda k: False)
        scatter = R.reduce_cols_by_key(d, keys, 7)
        monkeypatch.setattr(R, "use_one_hot_engine", lambda k: True)
        onehot = R.reduce_cols_by_key(d, keys, 7)
        np.testing.assert_allclose(np.asarray(scatter), np.asarray(onehot),
                                   rtol=1e-6)
        # oracle: explicit per-key column sums
        dn, kn = np.asarray(d), np.asarray(keys)
        want = np.stack([dn[:, kn == k].sum(axis=1) for k in range(7)], axis=1)
        np.testing.assert_allclose(np.asarray(scatter), want, rtol=1e-6)

    def test_rows_by_key_engines_agree(self, monkeypatch):
        import importlib

        R = importlib.import_module("raft_tpu.linalg.reduce")

        rng = np.random.default_rng(3)
        d = jnp.asarray(rng.random((40, 5)).astype(np.float32))
        keys = jnp.asarray(rng.integers(0, 6, 40).astype(np.int32))
        w = jnp.asarray(rng.random(40).astype(np.float32))
        monkeypatch.setattr(R, "use_one_hot_engine", lambda k: False)
        scatter = R.reduce_rows_by_key(d, keys, 6, weights=w)
        monkeypatch.setattr(R, "use_one_hot_engine", lambda k: True)
        onehot = R.reduce_rows_by_key(d, keys, 6, weights=w)
        np.testing.assert_allclose(np.asarray(scatter), np.asarray(onehot),
                                   rtol=1e-5)

    def test_discard_slot_semantics(self):
        """Key == n_keys is a discard slot (padding rows) on BOTH engines —
        the fused scan relies on it for the ragged tail."""
        from raft_tpu.cluster.kmeans import _mstep_tile_partials

        x = jnp.ones((4, 3), jnp.float32)
        labels = jnp.asarray([0, 1, 2, 2], jnp.int32)  # 2 == discard (k=2)
        for one_hot in (False, True):
            sums, wsum = _mstep_tile_partials(x, labels, None, 2, one_hot,
                                              jnp.float32)
            np.testing.assert_allclose(np.asarray(wsum), [1.0, 1.0])
            np.testing.assert_allclose(np.asarray(sums),
                                       np.ones((2, 3), np.float32))


class TestSegmentSumQuarantine:
    """ci/lint.py forbids raw jax.ops.segment_sum in raft_tpu/ outside
    linalg/reduce.py (the ivf_pq M-step silently missing the one-hot engine
    is the regression class this catches)."""

    def test_lint_flags_raw_segment_sum(self, tmp_path):
        import sys

        sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
        from ci.lint import check_file

        bad = tmp_path / "raft_tpu" / "somewhere" / "mod.py"
        bad.parent.mkdir(parents=True)
        bad.write_text("import jax\n\n\ndef f(v, i):\n"
                       "    return jax.ops.segment_sum(v, i, num_segments=4)\n")
        findings = check_file(bad)
        assert any("segment_sum" in msg for _, msg in findings), findings
        # noqa opts out
        bad.write_text("import jax\n\n\ndef f(v, i):\n"
                       "    return jax.ops.segment_sum(v, i, 4)  # noqa\n")
        assert not any("segment_sum" in m for _, m in check_file(bad))

    def test_lint_allows_reduce_py(self, tmp_path):
        import sys

        sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
        from ci.lint import check_file

        ok = tmp_path / "raft_tpu" / "linalg" / "reduce.py"
        ok.parent.mkdir(parents=True)
        ok.write_text("import jax\n\n\ndef f(v, i):\n"
                      "    return jax.ops.segment_sum(v, i, num_segments=4)\n")
        assert not any("segment_sum" in m for _, m in check_file(ok))

    def test_library_tree_is_clean(self):
        """No raw segment_sum outside the blessed module in the shipped
        tree (grep-level, independent of the lint runner)."""
        root = pathlib.Path(__file__).resolve().parent.parent / "raft_tpu"
        offenders = []
        for f in root.rglob("*.py"):
            if f.as_posix().endswith("linalg/reduce.py"):
                continue
            for i, line in enumerate(f.read_text().splitlines(), 1):
                if "jax.ops.segment_sum" in line and "noqa" not in line:
                    offenders.append(f"{f}:{i}")
        assert not offenders, offenders


def test_balanced_em_fused_matches_unfused():
    """kmeans_balanced._em_program rides the fused scan: same centers as
    the two-pass form (labels/distances for adjust_centers come out of the
    same single pass)."""
    from raft_tpu.cluster.kmeans_balanced import _em_program

    x, _, _ = make_blobs(RngState(31), 1200, 8, n_clusters=6,
                         cluster_std=0.4)
    x = jnp.asarray(np.asarray(x))
    c0 = x[:8]
    from raft_tpu.distance import DistanceType

    a = _em_program(x, c0, 8, 6, DistanceType.L2Expanded, 2, fused=True)
    b = _em_program(x, c0, 8, 6, DistanceType.L2Expanded, 2, fused=False)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4,
                               atol=1e-5)
