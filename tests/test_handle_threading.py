"""Handle threading through public APIs (reference calling convention,
DEVELOPER_GUIDE.md:11-25; pylibraft @auto_sync_handle wrappers)."""

import numpy as np
import pytest

from raft_tpu.cluster import kmeans, kmeans_mnmg
from raft_tpu.cluster.kmeans_types import InitMethod, KMeansParams
from raft_tpu.comms import build_comms
from raft_tpu.core import Handle, LogicError
from raft_tpu.distance import fused_l2_nn_argmin, pairwise_distance
from raft_tpu.neighbors import ivf_flat, knn


@pytest.fixture(scope="module")
def data():
    return np.random.default_rng(0).random((96, 12), dtype=np.float32)


def test_supplied_handle_records_outputs(data):
    h = Handle()
    d = pairwise_distance(data, data, "euclidean", handle=h)
    # the output must have been recorded on the handle's stream
    assert len(h.get_stream()._inflight) > 0
    h.sync()
    assert h.get_stream().query()
    assert d.shape == (96, 96)


def test_default_handle_syncs_eagerly(data):
    d = pairwise_distance(data, data, "cityblock")
    np.testing.assert_allclose(np.diag(np.asarray(d)), 0.0, atol=1e-5)


def test_handle_through_cluster_and_neighbors(data):
    h = Handle(n_streams=2)
    out = kmeans.fit(KMeansParams(n_clusters=4, max_iter=4), data, handle=h)
    h.sync()
    assert out.centroids.shape == (4, 12)
    labels, inertia = kmeans.predict(
        KMeansParams(n_clusters=4), data, out.centroids, handle=h)
    h.sync()
    assert labels.shape == (96,)
    _ = fused_l2_nn_argmin(data, out.centroids, handle=h)
    _, idx = knn(data, data[:8], 3, handle=h)
    h.sync()
    assert idx.shape == (8, 3)
    index = ivf_flat.build(ivf_flat.IndexParams(n_lists=4, seed=0), data,
                           handle=h)
    dd, ii = ivf_flat.search(ivf_flat.SearchParams(n_probes=2), index,
                             data[:5], 2, handle=h)
    h.sync()
    assert ii.shape == (5, 2)


def test_mnmg_accepts_handle(data):
    comms = build_comms()
    h = Handle(mesh=comms.mesh)
    h.set_comms(comms)
    n = comms.get_size() * 8
    params = KMeansParams(n_clusters=2, init=InitMethod.Array, max_iter=3)
    out = kmeans_mnmg.fit(params, h, data[:n], centroids=data[:2])
    assert out.centroids.shape == (2, 12)
    labels, _ = kmeans_mnmg.predict(params, h, data[:n], out.centroids)
    assert labels.shape == (n,)


def test_mnmg_handle_without_comms_raises(data):
    h = Handle()
    params = KMeansParams(n_clusters=2, init=InitMethod.Array, max_iter=2)
    with pytest.raises(LogicError):
        kmeans_mnmg.fit(params, h, data[:16], centroids=data[:2])


def test_stream_semantics_with_stub_work():
    """Deterministic Stream bookkeeping contract (no runtime races): strong
    refs held while in flight, pruned once complete (on record AND query),
    released by synchronize."""
    from raft_tpu.core.handle import Stream

    class FakeWork:
        def __init__(self):
            self.done = False

        def is_ready(self):
            return self.done

    s = Stream("t")
    a, b = FakeWork(), FakeWork()
    s.record(a)
    s.record(b)
    assert not s.query() and len(s._inflight) == 2
    a.done = True
    assert not s.query()            # b still pending...
    assert s._inflight == [b]       # ...but a was pruned/released
    b.done = True
    c = FakeWork()
    s.record(c)                     # record prunes completed entries too
    assert s._inflight == [c]
    c.done = True
    assert s.query() and s._inflight == []


@pytest.mark.slow  # ~20s sleep-based concurrency stress (tier-1 budget)
def test_stream_pool_batches_overlap_in_flight():
    """Dispatch/execute overlap evidence for the stream pool (VERDICT r3
    weak #6): batched IVF-PQ search dispatches each query batch onto the
    next pool stream WITHOUT blocking, so while work is still executing
    after the (async) search call returned, multiple batches are
    simultaneously in flight — the launch-ahead concurrency the reference
    pool exists for (core/handle.hpp:88-130).  A single TPU core executes
    one program at a time, so the overlap the pool models is
    host-dispatch-ahead-of-device (pipelining), not concurrent device
    programs — see the Handle module docstring.

    Robustness: the executable is prewarmed (no compile inside the timed
    window), and on hosts fast enough that the device keeps pace with
    dispatch (nothing left in flight AND a negligible sync tail) the
    overlap is unobservable — the test skips rather than asserting on a
    race it cannot see.  The bookkeeping contract itself is covered
    deterministically by test_stream_semantics_with_stub_work.
    """
    import time

    from raft_tpu.neighbors import ivf_pq

    rng = np.random.default_rng(3)
    x = rng.normal(0, 1, (20_000, 64)).astype(np.float32)
    q = rng.normal(0, 1, (4096, 64)).astype(np.float32)
    idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=128, pq_dim=16,
                                          pq_bits=8, seed=1), x)
    sp = ivf_pq.SearchParams(n_probes=32)
    # prewarm the per-batch executable so no compile lands in the window
    import jax

    jax.block_until_ready(ivf_pq.search(sp, idx, q[:1024], 10))

    h = Handle(n_streams=4)
    t0 = time.perf_counter()
    d, i = ivf_pq.search(sp, idx, q, 10, batch_size_query=1024, handle=h)
    t_dispatch = time.perf_counter() - t0
    pending = sum(not h.get_stream_from_stream_pool(b).query()
                  for b in range(4))
    t0 = time.perf_counter()
    h.sync()
    t_sync = time.perf_counter() - t0
    assert d.shape == (4096, 10) and i.shape == (4096, 10)
    assert all(h.get_stream_from_stream_pool(b).query() for b in range(4))
    if pending >= 2:
        return  # ≥2 batches were concurrently in flight: overlap measured
    if pending == 1:
        # expected steady state when the device roughly paces dispatch:
        # only the final batch is still in flight — tracking is correct,
        # deeper overlap just isn't observable at this host/device speed
        pytest.skip("device paced dispatch — one batch in flight at "
                    "return; deeper overlap unobservable here")
    if t_sync <= 0.2 * max(t_dispatch, 1e-9):
        pytest.skip("device kept pace with dispatch on this host — "
                    "overlap unobservable (bookkeeping covered by the "
                    "stub test)")
    raise AssertionError(
        f"substantial work outstanding after dispatch (sync {t_sync:.3f}s "
        f"vs dispatch {t_dispatch:.3f}s) but zero batches tracked in "
        "flight — the pool lost its work")


def test_concurrent_threads_distinct_handles(data):
    """Each thread owns a Handle (the reference's one-handle-per-thread
    convention, DEVELOPER_GUIDE.md:11): concurrent dispatch of different
    ops must produce exactly the single-threaded results."""
    import threading

    results = {}
    errors = []

    def worker(tid):
        try:
            h = Handle()
            d = pairwise_distance(data, data[: 8 * (tid + 1)], "euclidean",
                                  handle=h)
            h.sync()
            results[tid] = np.asarray(d)
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append((tid, repr(e)))

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    for tid, got in results.items():
        ref = pairwise_distance(data, data[: 8 * (tid + 1)], "euclidean")
        np.testing.assert_allclose(got, np.asarray(ref), rtol=1e-5,
                                   atol=1e-5)


def test_concurrent_threads_shared_handle_stream_pool(data):
    """A single Handle with a stream pool used from several threads: the
    per-stream in-flight records must not lose or corrupt work (the pool
    holds strong refs; sync drains everything)."""
    import threading

    h = Handle(n_streams=4)
    outs = [None] * 4

    def worker(tid):
        s = h.get_stream_from_stream_pool(tid)
        d = pairwise_distance(data[: 16 * (tid + 1)], data, "cityblock")
        s.record(d)                      # this lane owns the work
        outs[tid] = d

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    h.sync_stream_pool()
    for tid, d in enumerate(outs):
        assert d is not None
        ref = pairwise_distance(data[: 16 * (tid + 1)], data, "cityblock")
        np.testing.assert_allclose(np.asarray(d), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


def test_interruptible_registry_is_per_thread(data):
    """The cancellation token registry keys on thread id (reference
    interruptible.hpp's per-thread token store): tokens fetched on two
    threads are distinct objects."""
    import threading

    from raft_tpu.core import interruptible

    tokens = {}
    # both workers must be ALIVE at get_token() time: thread ids are reused
    # after a thread dies, which would hand worker 1 worker 0's cached token
    gate = threading.Barrier(2, timeout=30)

    def worker(tid):
        gate.wait()
        tokens[tid] = interruptible.get_token()
        gate.wait()

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert tokens[0] is not tokens[1]
    assert interruptible.get_token() not in (tokens[0], tokens[1])
