"""Handle threading through public APIs (reference calling convention,
DEVELOPER_GUIDE.md:11-25; pylibraft @auto_sync_handle wrappers)."""

import numpy as np
import pytest

from raft_tpu.cluster import kmeans, kmeans_mnmg
from raft_tpu.cluster.kmeans_types import InitMethod, KMeansParams
from raft_tpu.comms import build_comms
from raft_tpu.core import Handle, LogicError
from raft_tpu.distance import fused_l2_nn_argmin, pairwise_distance
from raft_tpu.neighbors import ivf_flat, knn


@pytest.fixture(scope="module")
def data():
    return np.random.default_rng(0).random((96, 12), dtype=np.float32)


def test_supplied_handle_records_outputs(data):
    h = Handle()
    d = pairwise_distance(data, data, "euclidean", handle=h)
    # the output must have been recorded on the handle's stream
    assert len(h.get_stream()._inflight) > 0
    h.sync()
    assert h.get_stream().query()
    assert d.shape == (96, 96)


def test_default_handle_syncs_eagerly(data):
    d = pairwise_distance(data, data, "cityblock")
    np.testing.assert_allclose(np.diag(np.asarray(d)), 0.0, atol=1e-5)


def test_handle_through_cluster_and_neighbors(data):
    h = Handle(n_streams=2)
    out = kmeans.fit(KMeansParams(n_clusters=4, max_iter=4), data, handle=h)
    h.sync()
    assert out.centroids.shape == (4, 12)
    labels, inertia = kmeans.predict(
        KMeansParams(n_clusters=4), data, out.centroids, handle=h)
    h.sync()
    assert labels.shape == (96,)
    _ = fused_l2_nn_argmin(data, out.centroids, handle=h)
    _, idx = knn(data, data[:8], 3, handle=h)
    h.sync()
    assert idx.shape == (8, 3)
    index = ivf_flat.build(ivf_flat.IndexParams(n_lists=4, seed=0), data,
                           handle=h)
    dd, ii = ivf_flat.search(ivf_flat.SearchParams(n_probes=2), index,
                             data[:5], 2, handle=h)
    h.sync()
    assert ii.shape == (5, 2)


def test_mnmg_accepts_handle(data):
    comms = build_comms()
    h = Handle(mesh=comms.mesh)
    h.set_comms(comms)
    n = comms.get_size() * 8
    params = KMeansParams(n_clusters=2, init=InitMethod.Array, max_iter=3)
    out = kmeans_mnmg.fit(params, h, data[:n], centroids=data[:2])
    assert out.centroids.shape == (2, 12)
    labels, _ = kmeans_mnmg.predict(params, h, data[:n], out.centroids)
    assert labels.shape == (n,)


def test_mnmg_handle_without_comms_raises(data):
    h = Handle()
    params = KMeansParams(n_clusters=2, init=InitMethod.Array, max_iter=2)
    with pytest.raises(LogicError):
        kmeans_mnmg.fit(params, h, data[:16], centroids=data[:2])
