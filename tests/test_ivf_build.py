"""Tiled, device-resident index construction (raft_tpu/neighbors/_build;
docs/index_build.md): tiled ≡ monolithic bit-identity across the build
grid, ``build_sharded ≡ build().shard()`` at world {1, 2, 8},
extend-in-place ≡ legacy-extend equivalence, warm-build zero-compile, the
O(tile) transient contract, the trainset cap, ServeEngine.refresh, and the
ci/lint.py host-transfer rule extension."""

import pathlib

import numpy as np
import pytest

import jax

from raft_tpu.comms import build_comms
from raft_tpu.core.aot import aot_compile_counters
from raft_tpu.neighbors import _build, ann_mnmg, ivf_flat, ivf_pq

_N, _DIM = 900, 16
_PQ_LEAVES = ("centers", "rotation", "codebooks", "list_codes",
              "list_indices", "list_sizes", "phys_sizes", "chunk_table",
              "owner", "list_adc", "list_csum")
_FLAT_LEAVES = ("centers", "list_data", "list_indices", "list_sizes",
                "phys_sizes", "chunk_table")

_COMMS = {}
_STATE = {}


def _comms(world):
    if world not in _COMMS:
        from jax.sharding import Mesh

        _COMMS[world] = build_comms(
            Mesh(np.array(jax.devices()[:world]), ("world",)))
    return _COMMS[world]


def _data(dtype="float32", n=_N, seed=0):
    rng = np.random.default_rng(seed)
    if dtype == "int8":
        return rng.integers(-100, 100, (n, _DIM)).astype(np.int8)
    return rng.normal(0, 1, (n, _DIM)).astype(np.float32)


def _pq_params(kind=ivf_pq.CodebookKind.PER_SUBSPACE, bits=8, **kw):
    return ivf_pq.IndexParams(n_lists=16, pq_dim=4, pq_bits=bits,
                              codebook_kind=kind, kmeans_n_iters=4, seed=1,
                              **kw)


def _flat_params(**kw):
    return ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=4, **kw)


def _pq_mono(kind, bits, dtype):
    """Monolithic reference builds, cached — every tile size in the grid
    compares against the same baseline index."""
    key = ("pq", int(kind), bits, dtype)
    if key not in _STATE:
        _STATE[key] = ivf_pq.build(_pq_params(kind, bits), _data(dtype),
                                   tiled=False)
    return _STATE[key]


def _assert_leaves_equal(a, b, leaves):
    for name in leaves:
        va, vb = np.asarray(getattr(a, name)), np.asarray(getattr(b, name))
        assert va.dtype == vb.dtype and va.shape == vb.shape, name
        assert np.array_equal(va, vb), f"leaf {name} differs"


# ---------------------------------------------------------------------------
# tiled ≡ monolithic bit-identity grid


@pytest.mark.parametrize("kind", [ivf_pq.CodebookKind.PER_SUBSPACE,
                                  ivf_pq.CodebookKind.PER_CLUSTER])
@pytest.mark.parametrize("dtype", ["float32", "int8"])
@pytest.mark.parametrize("tile", [
    123,  # ragged last tile — the cell that exercises real tiling
    # tier-1 budget (ISSUE-20 rebalance): tile > n collapses to one tile
    # == the monolithic path the oracle itself runs
    pytest.param(4096, marks=pytest.mark.slow),
])
def test_pq_tiled_matches_monolithic(kind, dtype, tile):
    a = ivf_pq.build(_pq_params(kind), _data(dtype), tiled=True,
                     tile_rows=tile)
    _assert_leaves_equal(a, _pq_mono(kind, 8, dtype), _PQ_LEAVES)


@pytest.mark.parametrize("bits", [5])
def test_pq_tiled_matches_monolithic_subbyte(bits):
    """pq_bits=5 exercises the real bit-packing inside the tile kernel
    (pq_bits=8 packs as the identity)."""
    kind = ivf_pq.CodebookKind.PER_SUBSPACE
    a = ivf_pq.build(_pq_params(kind, bits), _data(), tiled=True,
                     tile_rows=250)
    _assert_leaves_equal(a, _pq_mono(kind, bits, "float32"), _PQ_LEAVES)


@pytest.mark.parametrize("dtype", ["float32", "int8"])
@pytest.mark.parametrize("tile", [123, 4096])
def test_flat_tiled_matches_monolithic(dtype, tile):
    # ivf_flat's populate has no per-row encode: tile_rows only drives the
    # sharded transfer granularity, so the single-device grid covers the
    # device-side pack against the host-bookkept legacy pack
    del tile
    a = ivf_flat.build(_flat_params(), _data(dtype), tiled=True)
    b = ivf_flat.build(_flat_params(), _data(dtype), tiled=False)
    _assert_leaves_equal(a, b, _FLAT_LEAVES)


def test_search_identity_tiled_vs_monolithic():
    """The acceptance-level statement: f32 search top-k (ids AND
    distances) bit-identical between the two populates."""
    kind = ivf_pq.CodebookKind.PER_SUBSPACE
    a = ivf_pq.build(_pq_params(kind), _data(), tiled=True, tile_rows=123)
    b = _pq_mono(kind, 8, "float32")
    q = _data(seed=5, n=33)
    sp = ivf_pq.SearchParams(n_probes=4)
    da, ia = ivf_pq.search(sp, a, q, 5)
    db, ib = ivf_pq.search(sp, b, q, 5)
    assert np.array_equal(np.asarray(ia), np.asarray(ib))
    assert np.array_equal(np.asarray(da), np.asarray(db))


# ---------------------------------------------------------------------------
# build_sharded ≡ build().shard()


@pytest.mark.parametrize("world", [1, 2, 8])
def test_pq_build_sharded_matches_shard(world):
    comms = _comms(world)
    ref = _pq_mono(ivf_pq.CodebookKind.PER_SUBSPACE, 8,
                   "float32").shard(comms)
    got = ivf_pq.build_sharded(_pq_params(), _data(), comms, tile_rows=200)
    assert got.aux == ref.aux
    for j, (ga, ra) in enumerate(zip(got.replicated, ref.replicated)):
        assert np.array_equal(np.asarray(ga), np.asarray(ra)), f"rep[{j}]"
    for j, (ga, ra) in enumerate(zip(got.stacked, ref.stacked)):
        assert np.array_equal(np.asarray(ga), np.asarray(ra)), f"st[{j}]"
    q = _data(seed=5, n=21)
    sp = ivf_pq.SearchParams(n_probes=4)
    d1, i1 = ann_mnmg.search(got, q, 5, sp)
    d0, i0 = ann_mnmg.search(ref, q, 5, sp)
    assert np.array_equal(np.asarray(i1), np.asarray(i0))
    assert np.array_equal(np.asarray(d1), np.asarray(d0))


@pytest.mark.parametrize("world", [1, 2, 8])
def test_flat_build_sharded_matches_shard(world):
    comms = _comms(world)
    ref = ivf_flat.build(_flat_params(), _data()).shard(comms)
    got = ivf_flat.build_sharded(_flat_params(), _data(), comms,
                                 tile_rows=200)
    assert got.aux == ref.aux
    for j, (ga, ra) in enumerate(zip(got.stacked, ref.stacked)):
        assert np.array_equal(np.asarray(ga), np.asarray(ra)), f"st[{j}]"


@pytest.mark.slow
def test_pq_build_sharded_per_cluster_int8():
    comms = _comms(2)
    kind = ivf_pq.CodebookKind.PER_CLUSTER
    ref = ivf_pq.build(_pq_params(kind), _data("int8")).shard(comms)
    got = ivf_pq.build_sharded(_pq_params(kind), _data("int8"), comms,
                               tile_rows=123)
    for j, (ga, ra) in enumerate(zip(got.stacked, ref.stacked)):
        assert np.array_equal(np.asarray(ga), np.asarray(ra)), f"st[{j}]"


def test_build_sharded_rejects_deferred_ingest():
    from raft_tpu.core.error import LogicError

    with pytest.raises(LogicError):
        ivf_pq.build_sharded(_pq_params(add_data_on_build=False), _data(),
                             _comms(1))


# ---------------------------------------------------------------------------
# extend: tiled / in-place ≡ legacy


def _extend_grid(in_place):
    base = ivf_pq.build(_pq_params(), _data(), tiled=True)
    legacy = ivf_pq.build(_pq_params(), _data(), tiled=False)
    # small append (fits free tail slots: the in-place path) then a large
    # one (overflows chunks: the grow path) — both must equal the legacy
    # extend bit for bit
    for n_new in (8, 400):
        x2 = _data(seed=7, n=n_new)
        got = ivf_pq.extend(base, x2, tiled=True, in_place=in_place)
        ref = ivf_pq.extend(legacy, x2, tiled=False)
        _assert_leaves_equal(got, ref, _PQ_LEAVES)
        # base was consumed when the in-place fast path fired; rebuild
        if in_place:
            base = ivf_pq.build(_pq_params(), _data(), tiled=True)


def test_extend_tiled_matches_legacy():
    _extend_grid(in_place=False)


def test_extend_in_place_matches_legacy():
    _extend_grid(in_place=True)


def test_flat_extend_tiled_matches_legacy():
    base_t = ivf_flat.build(_flat_params(), _data(), tiled=True)
    base_m = ivf_flat.build(_flat_params(), _data(), tiled=False)
    for n_new in (8, 400):
        x2 = _data(seed=7, n=n_new)
        got = ivf_flat.extend(base_t, x2, tiled=True)
        ref = ivf_flat.extend(base_m, x2, tiled=False)
        _assert_leaves_equal(got, ref, _FLAT_LEAVES)


def test_extend_into_empty_model_matches_build():
    """extend() into a model-only index (add_data_on_build=False) must
    reproduce the one-shot build's packed state — the serving-refresh
    ingest path."""
    base = ivf_pq.build(_pq_params(add_data_on_build=False), _data())
    full = ivf_pq.build(_pq_params(), _data(), tiled=True)
    got = ivf_pq.extend(base, _data(), tiled=True)
    _assert_leaves_equal(got, full, _PQ_LEAVES)


# ---------------------------------------------------------------------------
# warm executables / counters / transients


def test_second_tiled_build_compiles_nothing():
    ivf_pq.build(_pq_params(), _data(), tiled=True, tile_rows=128)
    c0 = aot_compile_counters["compiles"]
    t0 = dict(_build.build_trace_counters)
    ivf_pq.build(_pq_params(), _data(), tiled=True, tile_rows=128)
    assert aot_compile_counters["compiles"] == c0
    # and the tile programs actually RAN through the counters at least once
    assert _build.build_trace_counters["list_slots"] >= 1
    assert _build.build_trace_counters["scatter_new"] >= 1
    # warm rebuild traces nothing either (AOT dispatch, not jit re-trace)
    assert dict(_build.build_trace_counters) == t0


def test_second_extend_compiles_nothing():
    base = ivf_pq.build(_pq_params(), _data(), tiled=True)
    x2 = _data(seed=9, n=64)
    ivf_pq.extend(base, x2, tiled=True)
    c0 = aot_compile_counters["compiles"]
    ivf_pq.extend(base, x2, tiled=True)
    assert aot_compile_counters["compiles"] == c0


def test_tile_program_transient_is_o_tile():
    """The per-tile encode executable's temp footprint must be a small
    multiple of the tile — independent of any dataset size (the in-bench
    assertion's unit-test twin)."""
    base = ivf_pq.build(_pq_params(add_data_on_build=False), _data())
    tile, pq_dim, kcb = 256, 4, 256
    exe = ivf_pq._encode_tile_aot.compiled(
        jax.ShapeDtypeStruct((tile, _DIM), np.float32),
        jax.ShapeDtypeStruct((tile,), np.int32), base.centers,
        base.rotation, base.codebooks, False, 8)
    try:
        temp = int(exe.memory_analysis().temp_size_in_bytes)
    except AttributeError:
        pytest.skip("backend exposes no memory_analysis")
    assert temp <= 6 * tile * pq_dim * kcb * 4


def test_trainset_cap_bounds_codebook_training():
    """Above the cap, codebooks train on a seeded sample: the build still
    stands, is deterministic, and the tiled/monolithic identity holds."""
    p = _pq_params()
    p.pq_trainset_cap = 256  # << n
    a = ivf_pq.build(p, _data(), tiled=True, tile_rows=300)
    b = ivf_pq.build(p, _data(), tiled=False)
    _assert_leaves_equal(a, b, _PQ_LEAVES)
    q = _data(seed=5, n=16)
    d, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=4), a, q, 3)
    assert int((np.asarray(i) >= 0).sum()) > 0


# ---------------------------------------------------------------------------
# ServeEngine.refresh


def test_serve_engine_refresh_zero_compile():
    from raft_tpu.serve import ServeEngine

    x = _data()
    idx = ivf_flat.build(_flat_params(), x)
    sp = ivf_flat.SearchParams(n_probes=4)
    eng = ServeEngine(idx, 5, sp, max_batch=64)
    eng.warmup()
    reqs = [_data(seed=11, n=3), _data(seed=12, n=9)]
    eng.search(reqs)  # plumbing warm call

    idx2 = ivf_flat.extend(idx, _data(seed=13, n=200))
    eng.refresh(idx2)  # pre-lowers every warmed signature off-path
    c0 = aot_compile_counters["compiles"]
    outs = eng.search(reqs)
    assert aot_compile_counters["compiles"] == c0, \
        "serving compiled after refresh (re-warm is broken)"
    assert eng.stats["refreshes"] == 1
    for q, (d, i) in zip(reqs, outs):
        d_ref, i_ref = ivf_flat.search(sp, idx2, q, 5)
        assert np.array_equal(i, np.asarray(i_ref))
        assert np.array_equal(d, np.asarray(d_ref))


# ---------------------------------------------------------------------------
# lint rule extension (quarantine-tested like the existing rules)


def test_lint_flags_host_transfer_in_build_module(tmp_path):
    """The ann_mnmg host-transfer rule now covers neighbors/_build.py."""
    from ci.lint import check_file

    bad = tmp_path / "raft_tpu" / "neighbors" / "_build.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "import numpy as np\n\n\n"
        "def leak(x):\n"
        "    return np.asarray(x)\n")
    assert any("device-resident" in msg or "host" in msg
               for _, msg in check_file(bad))
    ok = tmp_path / "raft_tpu" / "neighbors" / "_build2.py"
    ok.write_text(
        "import numpy as np\n\n\n"
        "def fine(x):\n"
        "    return np.asarray(x)  # host-ok: (n_lists,) table\n")
    # _build2.py is outside the scoped module name: rule must not fire
    assert not check_file(ok)


def test_lint_allows_marked_bookkeeping(tmp_path):
    from ci.lint import check_file

    f = tmp_path / "raft_tpu" / "neighbors" / "_build.py"
    f.parent.mkdir(parents=True)
    f.write_text(
        "import numpy as np\n\n\n"
        "def counts(c):\n"
        "    return np.asarray(c)  # host-ok: (n_lists,) bookkeeping\n")
    assert not check_file(f)


def test_real_build_module_passes_lint():
    from ci.lint import check_file

    assert not check_file(pathlib.Path("raft_tpu/neighbors/_build.py"))
