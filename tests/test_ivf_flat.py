"""IVF-Flat: recall-threshold tests vs brute force (the reference's ANN
test pattern — test/neighbors/ann_ivf_pq.cuh min_recall gates)."""

import numpy as np
import pytest

from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.neighbors import knn
from raft_tpu.neighbors.ivf_flat import (
    IndexParams,
    SearchParams,
    build,
    extend,
    search,
)


def make_data(n=3000, dim=24, n_queries=64, seed=0, clusters=40):
    rng = np.random.default_rng(seed)
    centers = rng.normal(0, 5, (clusters, dim))
    assign = rng.integers(0, clusters, n)
    x = (centers[assign] + rng.normal(0, 1, (n, dim))).astype(np.float32)
    q = (centers[rng.integers(0, clusters, n_queries)] +
         rng.normal(0, 1, (n_queries, dim))).astype(np.float32)
    return x, q


def recall(found, truth):
    hits = 0
    for f, t in zip(np.asarray(found), np.asarray(truth)):
        hits += len(set(f.tolist()) & set(t.tolist()))
    return hits / truth.size


@pytest.mark.parametrize("metric,min_recall", [
    (DistanceType.L2Expanded, 0.95),
    (DistanceType.L2SqrtExpanded, 0.95),
    (DistanceType.InnerProduct, 0.9),
    (DistanceType.CosineExpanded, 0.9),
])
def test_ivf_flat_recall(metric, min_recall):
    x, q = make_data()
    k = 10
    params = IndexParams(n_lists=64, metric=metric, seed=3)
    idx = build(params, x)
    assert idx.size == x.shape[0]
    d, i = search(SearchParams(n_probes=16), idx, q, k)
    if metric == DistanceType.InnerProduct:
        bf_metric = DistanceType.InnerProduct
    elif metric == DistanceType.CosineExpanded:
        bf_metric = DistanceType.CosineExpanded
    else:
        bf_metric = DistanceType.L2Expanded
    _, ti = knn(x, q, k, bf_metric)
    assert recall(i, np.array(ti)) >= min_recall


def test_ivf_flat_full_probes_is_exact():
    x, q = make_data(n=1200, dim=16, n_queries=32)
    k = 8
    idx = build(IndexParams(n_lists=32, metric=DistanceType.L2Expanded), x)
    d, i = search(SearchParams(n_probes=32), idx, q, k)  # probe everything
    td, ti = knn(x, q, k, DistanceType.L2Expanded)
    assert recall(i, np.array(ti)) == 1.0
    np.testing.assert_allclose(np.sort(np.array(d), 1),
                               np.sort(np.array(td), 1), rtol=1e-3, atol=1e-3)


def test_ivf_flat_extend():
    x, q = make_data(n=2000, dim=16)
    half = 1000
    params = IndexParams(n_lists=32, metric=DistanceType.L2Expanded,
                         add_data_on_build=False)
    idx = build(params, x)
    assert idx.size == 0
    idx = extend(idx, x[:half])
    assert idx.size == half
    idx = extend(idx, x[half:], new_ids=np.arange(half, 2000, dtype=np.int32))
    assert idx.size == 2000
    d, i = search(SearchParams(n_probes=32), idx, q, 5)
    _, ti = knn(x, q, 5, DistanceType.L2Expanded)
    assert recall(i, np.array(ti)) == 1.0


def test_ivf_flat_int8_storage():
    rng = np.random.default_rng(7)
    x = rng.integers(-100, 100, (800, 16)).astype(np.int8)
    q = x[:20]
    idx = build(IndexParams(n_lists=16, metric=DistanceType.L2Expanded), x)
    assert idx.list_data.dtype == np.int8
    d, i = search(SearchParams(n_probes=16), idx, q, 1)
    # each query is its own nearest neighbor at distance 0
    np.testing.assert_array_equal(np.array(i)[:, 0], np.arange(20))
    np.testing.assert_allclose(np.array(d)[:, 0], 0.0, atol=1e-3)


def test_ivf_flat_padding_metric():
    x, _ = make_data(n=1000, dim=8)
    idx = build(IndexParams(n_lists=16), x)
    assert 0.0 <= idx.padding_fraction < 0.95


def test_ivf_flat_skew_bounded_padding():
    """Heavily skewed cluster sizes: chunked lists must not pad every list
    to the largest list's size (the flat-packing failure mode VERDICT r1
    flagged; reference allocates per list, ivf_list.hpp)."""
    rng = np.random.default_rng(3)
    # one dense blob (~70% of points) + spread → one giant list, many tiny
    big = rng.normal(0, 0.05, (1400, 8)).astype(np.float32)
    rest = rng.normal(0, 8.0, (600, 8)).astype(np.float32)
    x = np.concatenate([big, rest])
    idx = build(IndexParams(n_lists=64, seed=0), x)
    n = x.shape[0]
    sizes = np.asarray(idx.list_sizes)
    assert sizes.sum() == n
    flat_alloc = 64 * max(8, -(-sizes.max() // 8) * 8)  # old flat packing
    chunk_alloc = idx.list_data.shape[0] * idx.capacity
    # chunked allocation stays near n; flat would blow up with the skew
    assert chunk_alloc <= n + (len(sizes) + 8) * idx.capacity + idx.capacity
    if sizes.max() > 4 * np.median(sizes[sizes > 0]):
        assert chunk_alloc < flat_alloc
    # recall must be unaffected by chunking
    q = x[::50]
    d, i = search(SearchParams(n_probes=64), idx, q, 1)
    np.testing.assert_array_equal(np.array(i)[:, 0], np.arange(0, n, 50))


def test_ivf_flat_serialize_roundtrip(tmp_path):
    from raft_tpu.neighbors.serialize import load_ivf_flat, save_ivf_flat

    x, q = make_data(n=600, dim=16)
    idx = build(IndexParams(n_lists=8, seed=2), x)
    p = tmp_path / "flat.npz"
    save_ivf_flat(p, idx)
    idx2 = load_ivf_flat(p)
    d1, i1 = search(SearchParams(n_probes=8), idx, q, 5)
    d2, i2 = search(SearchParams(n_probes=8), idx2, q, 5)
    np.testing.assert_array_equal(np.array(i1), np.array(i2))
    np.testing.assert_allclose(np.array(d1), np.array(d2), rtol=1e-6)


def test_serialize_kind_mismatch(tmp_path):
    from raft_tpu.core import LogicError
    from raft_tpu.neighbors.serialize import load_ivf_pq, save_ivf_flat
    import pytest as _pytest

    x, _ = make_data(n=200, dim=8)
    idx = build(IndexParams(n_lists=4, seed=2), x)
    p = tmp_path / "flat.npz"
    save_ivf_flat(p, idx)
    with _pytest.raises(LogicError):
        load_ivf_pq(p)


# multi-extend stress; the chunked-extend oracle + single-extend tests
# keep tier-1 coverage (tier-1 budget, PR 4)
@pytest.mark.slow
def test_ivf_flat_sequential_extends_with_ids():
    """Multiple extends with custom ids on chunked storage keep ids/recall."""
    rng = np.random.default_rng(9)
    dim = 12
    a = rng.normal(0, 1, (400, dim)).astype(np.float32)
    b = rng.normal(0, 1, (300, dim)).astype(np.float32)
    c = rng.normal(0, 1, (200, dim)).astype(np.float32)
    ids_a = np.arange(1000, 1400, dtype=np.int32)
    ids_b = np.arange(5000, 5300, dtype=np.int32)
    ids_c = np.arange(9000, 9200, dtype=np.int32)
    idx = build(IndexParams(n_lists=16, seed=1, add_data_on_build=False),
                np.concatenate([a, b, c]))
    idx = extend(idx, a, ids_a)
    idx = extend(idx, b, ids_b)
    idx = extend(idx, c, ids_c)
    assert idx.size == 900
    got_ids = np.asarray(idx.list_indices)
    got_ids = np.sort(got_ids[got_ids >= 0])
    np.testing.assert_array_equal(
        got_ids, np.sort(np.concatenate([ids_a, ids_b, ids_c])))
    # each point's own id is its 1-NN at full probes
    d, i = search(SearchParams(n_probes=16), idx, b[:25], 1)
    np.testing.assert_array_equal(np.asarray(i)[:, 0], ids_b[:25])
    np.testing.assert_allclose(np.asarray(d)[:, 0], 0.0, atol=1e-4)


def test_ivf_flat_search_tail_bucketing():
    """Ragged tail batches pad to a power of two and slice results — same
    serving-path compile-storm guard as ivf_pq.search."""
    from raft_tpu.neighbors import ivf_flat

    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (2000, 16)).astype(np.float32)
    q = rng.normal(0, 1, (80, 16)).astype(np.float32)
    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=16), x)
    ref_d, ref_i = ivf_flat.search(ivf_flat.SearchParams(n_probes=8),
                                   idx, q[:70], 5, batch_size_query=64)
    for nq in (69, 67, 66):
        d, i = ivf_flat.search(ivf_flat.SearchParams(n_probes=8),
                               idx, q[:nq], 5, batch_size_query=64)
        assert np.asarray(d).shape == (nq, 5)
        np.testing.assert_array_equal(np.asarray(i), np.asarray(ref_i)[:nq])


def test_ivf_flat_search_no_retrace_across_ragged_query_counts():
    """The eager ivf_flat search path routes every query batch through the
    bucketed AOT program (``_search_batch_aot``): once one bucket's
    executable is warm, ragged query counts inside that bucket must
    dispatch with ZERO further compiles (ISSUE 7 satellite — the serving
    no-retrace contract, counter-asserted like tests/test_serve.py)."""
    from raft_tpu.core.aot import aot_compile_counters
    from raft_tpu.neighbors import ivf_flat

    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (1500, 16)).astype(np.float32)
    q = rng.normal(0, 1, (64, 16)).astype(np.float32)
    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=16), x)
    sp = ivf_flat.SearchParams(n_probes=4)
    # warm the 8/16/32/64 buckets once
    for nq in (8, 16, 32, 64):
        ivf_flat.search(sp, idx, q[:nq], 5)
    c0 = aot_compile_counters["compiles"]
    for nq in (3, 5, 7, 9, 13, 17, 25, 31, 33, 47, 63):
        d, i = ivf_flat.search(sp, idx, q[:nq], 5)
        assert np.asarray(d).shape == (nq, 5)
    assert aot_compile_counters["compiles"] == c0, \
        "ragged query counts recompiled inside warm buckets"
    # liveness: the counter does move when a NEW bucket appears (65
    # queries pad to the un-warmed 128 bucket)
    ivf_flat.search(sp, idx, np.concatenate([q, q])[:65], 5)
    assert aot_compile_counters["compiles"] > c0


def test_ivf_flat_bf16_dataset_recall_near_f32():
    """bf16 datasets score with f32 accumulation — recall triage (PR 4).

    Two separate claims, asserted separately:

    1. SCORING is exact on the rounded data: with ALL lists probed
       (n_probes = n_lists, probe selection removed), the bf16 index
       recovers the bf16 brute-force top-k EXACTLY (measured 1.000 overlap
       on this config) — i.e. the f32-accumulated in-list scan introduces
       no error beyond the bf16 representation itself.  The representation
       bound (exact bf16 brute force vs f32 ground truth) is ~0.988 here.
    2. At partial probing the bf16 recall tracks f32 within partition
       noise.  The historical 0.02 gate was BELOW the noise floor of this
       estimator: 50 queries × k=5 = 250 candidates (one flipped candidate
       = 0.004), and the bf16-rounded dataset trains a DIFFERENT coarse
       partition whose probe-boundary losses are seed luck — measured
       across seeds 0-5 at this config the (f32 − bf16) gap spans −0.024
       … +0.044 (bf16 WINS on 3 of 6 seeds; mean +0.005).  Training the
       quantizer in f32 and storing bf16 does not close it (0.796 vs
       0.800 on seed 0), confirming there is no fixable scoring/training
       bug — the gate is widened to 0.05, just past the observed spread.
    """
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    x = rng.random((2000, 32)).astype(np.float32)
    q = rng.random((50, 32)).astype(np.float32)
    _, iref = knn(x, q, 5)
    xb, qb = jnp.asarray(x, jnp.bfloat16), jnp.asarray(q, jnp.bfloat16)
    _, ibf = knn(xb, qb, 5)  # exact search on the rounded data

    def overlap(i, ref):
        return np.mean([len(set(a.tolist()) & set(b.tolist())) / 5
                        for a, b in zip(np.asarray(i), np.asarray(ref))])

    idx32 = build(IndexParams(n_lists=20), x)
    idxb = build(IndexParams(n_lists=20), xb)

    # claim 1: probe ALL lists → pure scoring; must reproduce the bf16
    # brute-force top-k exactly (scores accumulate in f32)
    d_all, i_all = search(SearchParams(n_probes=20), idxb, qb, 5)
    assert d_all.dtype == jnp.float32  # scores accumulate in f32
    assert overlap(i_all, ibf) == 1.0, overlap(i_all, ibf)

    # claim 2: partial probing tracks f32 within the measured partition
    # noise (±0.05 across seeds; NOT a precision bug — see docstring)
    _, i32 = search(SearchParams(n_probes=8), idx32, q, 5)
    _, ib = search(SearchParams(n_probes=8), idxb, qb, 5)
    rec_f32, rec_bf = overlap(i32, iref), overlap(ib, iref)
    assert rec_bf >= rec_f32 - 0.05, (rec_bf, rec_f32)


def test_extend_lists_chunked_matches_full_repack():
    """Unit oracle for the r5 incremental extend: after any sequence of
    extends, the chunked state holds exactly the same per-list member sets
    as a fresh pack of all rows at the same cap, tail slots fill before new
    chunks, and the reserved dummy row stays empty."""
    from raft_tpu.neighbors._common import (extend_lists_chunked,
                                            pack_lists_chunked)

    rng = np.random.default_rng(3)
    n_lists, dim = 7, 4
    n0 = 60
    x0 = rng.normal(0, 1, (n0, dim)).astype(np.float32)
    lab0 = rng.integers(0, n_lists, n0).astype(np.int32)
    ids0 = np.arange(n0, dtype=np.int32)
    state = pack_lists_chunked(x0, ids0, lab0, n_lists, chunk_cap=8)
    all_x, all_lab, all_ids = [x0], [lab0], [ids0]
    nxt = n0
    for n_new in (5, 40, 1, 23):  # tail-fill only, multi-chunk growth, ...
        xn = rng.normal(0, 1, (n_new, dim)).astype(np.float32)
        # skew into few lists so single lists overflow across chunks
        labn = rng.integers(0, max(2, n_lists // 2), n_new).astype(np.int32)
        idsn = np.arange(nxt, nxt + n_new, dtype=np.int32)
        nxt += n_new
        data, idx, phys, sizes, table, owner, cap = state = \
            extend_lists_chunked(state[0], state[1], state[3], state[4],
                                 xn, idsn, labn)
        all_x.append(xn)
        all_lab.append(labn)
        all_ids.append(idsn)
        assert cap == 8
        catl = np.concatenate(all_lab)
        cati = np.concatenate(all_ids)
        catx = np.concatenate(all_x)
        # logical sizes and physical accounting agree
        np.testing.assert_array_equal(
            np.asarray(sizes), np.bincount(catl, minlength=n_lists))
        assert int(np.asarray(phys).sum()) == cati.size
        # dummy row (last) is empty and -1-padded
        assert int(np.asarray(phys)[-1]) == 0
        np.testing.assert_array_equal(np.asarray(idx)[-1], -1)
        # per-list member id sets match the labels oracle, and every stored
        # vector sits at the slot its id says it should
        idx_h, data_h = np.asarray(idx), np.asarray(data)
        table_h, owner_h = np.asarray(table), np.asarray(owner)
        dummy = data_h.shape[0] - 1
        by_id = {int(i): v for i, v in zip(cati, catx)}
        for l in range(n_lists):
            got = []
            for ci, p in enumerate(table_h[l]):
                if p == dummy:
                    continue
                assert owner_h[p] == l
                live = idx_h[p][: np.asarray(phys)[p]]
                assert (idx_h[p][np.asarray(phys)[p]:] == -1).all()
                got.extend(int(v) for v in live)
                for slot, rid in enumerate(live):
                    np.testing.assert_allclose(data_h[p, slot], by_id[rid],
                                               rtol=1e-6)
            assert sorted(got) == sorted(cati[catl == l].tolist())


def test_ivf_flat_extend_search_matches_rebuild():
    """Search on an incrementally extended index returns the same ids as a
    full rebuild over the union (full probes → both are exact)."""
    rng = np.random.default_rng(11)
    x = rng.normal(0, 1, (1200, 16)).astype(np.float32)
    q = rng.normal(0, 1, (40, 16)).astype(np.float32)
    params = IndexParams(n_lists=16, seed=5)
    idx = build(params, x[:800])
    idx = extend(idx, x[800:])
    assert idx.size == 1200
    d, i = search(SearchParams(n_probes=16), idx, q, 10)
    _, ti = knn(x, q, 10, DistanceType.L2Expanded)
    assert recall(i, np.array(ti)) == 1.0


def test_ivf_flat_extend_adaptive_centers():
    """adaptive_centers=True drifts a list's center toward appended members
    incrementally: new = (old·n_old + Σnew)/n_total (reference
    ivf_flat_build.cuh extend updates centers from accumulated sums);
    lists receiving nothing keep their center."""
    rng = np.random.default_rng(2)
    x = rng.normal(0, 1, (600, 8)).astype(np.float32)
    idx = build(IndexParams(n_lists=8, seed=3, adaptive_centers=True), x)
    c0 = np.asarray(idx.centers).copy()
    sizes0 = np.asarray(idx.list_sizes).copy()
    # extend with rows pinned near one existing center, shifted by +2
    target = int(np.argmax(sizes0))
    new = (c0[target] + 2.0
           + 0.01 * rng.normal(0, 1, (64, 8))).astype(np.float32)
    idx2 = extend(idx, new)
    c1 = np.asarray(idx2.centers)
    sizes1 = np.asarray(idx2.list_sizes)
    got_new = sizes1 - sizes0
    for l in range(8):
        if got_new[l] == 0:
            np.testing.assert_allclose(c1[l], c0[l], rtol=1e-6)
    # the receiving lists moved, in the direction of the appended mass
    moved = np.where(got_new > 0)[0]
    assert moved.size > 0
    for l in moved:
        assert np.linalg.norm(c1[l] - c0[l]) > 1e-4
    # exact incremental formula on the largest receiver
    l = moved[np.argmax(got_new[moved])]
    mask = np.asarray(
        np.argmin(((new[:, None, :] - c0[None]) ** 2).sum(-1), axis=1)) == l
    expect = (c0[l] * sizes0[l] + new[mask].sum(0)) / (sizes0[l] + mask.sum())
    np.testing.assert_allclose(c1[l], expect, rtol=1e-4, atol=1e-5)


def test_ivf_flat_int8_extend_incremental():
    """int8 storage + r5 incremental extend: appended rows keep the int8
    dtype in the lists and exact full-probe search."""
    rng = np.random.default_rng(21)
    x = rng.integers(-100, 100, (1200, 16)).astype(np.int8)
    idx = build(IndexParams(n_lists=16, seed=2), x[:900])
    assert idx.list_data.dtype == np.int8
    idx = extend(idx, x[900:])
    assert idx.list_data.dtype == np.int8 and idx.size == 1200
    # query BOTH the build rows and the appended rows (the incremental
    # append path is the thing under test)
    for lo in (40, 950):
        q = x[lo:lo + 20]
        d, i = search(SearchParams(n_probes=16), idx, q, 1)
        hit = np.mean(np.asarray(i)[:, 0] == np.arange(lo, lo + 20))
        assert hit >= 0.9, lo  # integer data can have exact duplicates


def test_ivf_flat_cosine_extend_assigns_by_direction():
    """CosineExpanded + extend: assignment normalizes the new rows, so a
    scaled copy of an indexed vector lands in the same list and is its own
    nearest neighbour by cosine distance."""
    rng = np.random.default_rng(22)
    x = rng.normal(0, 1, (800, 12)).astype(np.float32)
    idx = build(IndexParams(n_lists=8, seed=4,
                            metric=DistanceType.CosineExpanded), x)
    scaled = 7.5 * x[:30]  # same directions, different norms
    idx2 = extend(idx, scaled, new_ids=np.arange(800, 830, dtype=np.int32))
    # direct membership check: the scaled copy must land in the SAME list
    # as its original (extend normalizes before assignment) — asserted on
    # the stored ids, not through a search that probes every list
    ids = np.asarray(idx2.list_indices)
    # map physical row -> logical list via the chunk table
    table = np.asarray(idx2.chunk_table)
    phys_to_list = {}
    for l in range(table.shape[0]):
        for p in table[l]:
            phys_to_list[int(p)] = l
    id_to_list = {}
    for phys in range(ids.shape[0]):
        for v in ids[phys]:
            if v >= 0:
                # KeyError here = ids written to a physical row the chunk
                # table does not own — fail loudly, not vacuously
                id_to_list[int(v)] = phys_to_list[phys]
    for qi in range(30):
        assert id_to_list[800 + qi] == id_to_list[qi], qi
    # and with FEWER probes than lists, the scaled copy is still found
    d, i = search(SearchParams(n_probes=2), idx2, x[:30], 2)
    for row, qi in zip(np.asarray(i), range(30)):
        assert set(row.tolist()) == {qi, 800 + qi}, (qi, row)
